"""Host-side collective communication between tasks/actors.

API surface of the reference's ``ray.util.collective``
(``python/ray/util/collective/collective.py:120-615`` —
``init_collective_group / allreduce / allgather / reducescatter /
broadcast / send / recv``) with two backends:

- ``backend="shm"`` (default, gloo's role): host arrays rendezvous
  through an **async coordinator actor** — every rank's single
  ``collect`` call parks on the actor's event loop until the round
  completes, so a round costs one actor round-trip per rank (no
  polling), with array payloads moving through the shared-memory object
  store.
- ``backend="xla"`` (nccl's role, SURVEY §5.8): ops ride the jax
  runtime's own collectives — each rank must be a jax process in one
  initialized ``jax.distributed`` runtime (the Train worker-gang setup);
  cross-process movement lowers onto ICI/DCN, never through Python.
  In-jit code should use :mod:`ray_tpu.parallel.collective` directly.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import numpy as np

import ray_tpu

# Process-global: a worker joins a group once and may drive it from any
# thread (train loops run on their own thread inside the hosting actor).
_GROUPS: Dict[str, object] = {}

_COLLECTIVE_HIST = None


def _record_collective(group: str, op: str, rank: int, round_id: int,
                       dur_s: float) -> None:
    """Flight-recorder span + latency histogram for one collective round
    (the timeline merges these next to task slices)."""
    from ray_tpu._private import events as _events

    if not _events.ENABLED:
        return
    global _COLLECTIVE_HIST
    if _COLLECTIVE_HIST is None:
        from ray_tpu.util.metrics import Histogram

        _COLLECTIVE_HIST = Histogram(
            "ray_tpu_collective_latency_s",
            "host-collective round latency (s)",
            boundaries=[0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 30],
            tag_keys=("op",))
    _COLLECTIVE_HIST.observe(dur_s, tags={"op": op})
    _events.emit("collective", f"{op} ({group})", severity="DEBUG",
                 entity_id=f"rank-{rank}", span_dur=dur_s, round=round_id)


def _groups() -> Dict[str, object]:
    return _GROUPS


class _Coordinator:
    """Async rendezvous actor: one ``collect`` per rank per round.

    Analog of the NCCL communicator bootstrap store
    (``nccl_collective_group.py:127``), but it also executes the
    host-side reduction.  Async methods multiplex on the actor's event
    loop, so all ranks of a round park here concurrently and return the
    moment the last one arrives — no poll loops, no separate fetch."""

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.rounds: Dict[int, dict] = {}
        # (src, dst) -> fifo of in-flight point-to-point tensors
        self.mailbox: Dict[tuple, list] = {}
        self.mailbox_events: Dict[tuple, object] = {}

    async def collect(self, round_id: int, rank: int, value, op: str):
        import asyncio

        r = self.rounds.setdefault(
            round_id,
            {"parts": {}, "op": op, "result": None,
             "event": asyncio.Event(), "fetched": set()},
        )
        r["parts"][rank] = value
        if len(r["parts"]) == self.world_size:
            r["result"] = self._finish(r)
            r["event"].set()
        else:
            await r["event"].wait()
        out = r["result"]
        r["fetched"].add(rank)
        if len(r["fetched"]) == self.world_size:
            self.rounds.pop(round_id, None)
        if isinstance(out, dict):  # per-rank outputs (reducescatter)
            return out[rank]
        return out

    async def p2p_put(self, src: int, dst: int, value) -> None:
        import asyncio

        key = (src, dst)
        self.mailbox.setdefault(key, []).append(value)
        ev = self.mailbox_events.setdefault(key, asyncio.Event())
        ev.set()

    async def p2p_take(self, src: int, dst: int, timeout: float = 60.0):
        """Returns (ok, value).  The deadline lives SERVER-side so a
        timed-out receive leaves no orphaned waiter that would steal the
        next message for this (src, dst) pair."""
        import asyncio

        key = (src, dst)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            q = self.mailbox.get(key)
            if q:
                return True, q.pop(0)
            remaining = deadline - loop.time()
            if remaining <= 0:
                return False, None
            ev = self.mailbox_events.setdefault(key, asyncio.Event())
            ev.clear()
            try:
                await asyncio.wait_for(ev.wait(), timeout=remaining)
            except asyncio.TimeoutError:
                return False, None

    def _finish(self, r: dict):
        op = r["op"]
        parts = [r["parts"][i] for i in sorted(r["parts"])]
        if op == "barrier":
            return True
        if op in ("sum", "mean", "max", "min", "product"):
            acc = np.stack([np.asarray(p) for p in parts])
            fn = {"sum": np.sum, "mean": np.mean, "max": np.max,
                  "min": np.min, "product": np.prod}[op]
            return fn(acc, axis=0)
        if op == "allgather":
            return [np.asarray(p) for p in parts]
        if op == "broadcast":
            root, vals = parts[0][0], {i: v for i, (_, v) in enumerate(parts)}
            return vals[root]
        if op == "reducescatter":
            acc = np.sum(np.stack([np.asarray(p) for p in parts]), axis=0)
            chunks = np.array_split(acc, self.world_size, axis=0)
            return {i: chunks[i] for i in range(self.world_size)}
        raise ValueError(f"unknown op {op}")


class _GroupHandle:
    backend = "shm"

    def __init__(self, name: str, world_size: int, rank: int, coordinator):
        import threading

        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.coordinator = coordinator
        self.round_id = 0
        self._round_lock = threading.Lock()

    def _run(self, value, op: str, timeout: float = 120.0):
        with self._round_lock:
            rid = self.round_id
            self.round_id += 1
        t0 = time.perf_counter()
        out = ray_tpu.get(
            self.coordinator.collect.remote(rid, self.rank, value, op),
            timeout=timeout,
        )
        _record_collective(self.name, op, self.rank, rid,
                           time.perf_counter() - t0)
        return out

    def send(self, tensor, dst_rank: int) -> None:
        ray_tpu.get(self.coordinator.p2p_put.remote(self.rank, dst_rank, tensor))

    def recv(self, src_rank: int, timeout: float = 120.0):
        ok, val = ray_tpu.get(
            self.coordinator.p2p_take.remote(src_rank, self.rank, timeout),
            timeout=timeout + 30,  # server-side deadline fires first
        )
        if not ok:
            raise TimeoutError(
                f"recv from rank {src_rank} timed out after {timeout}s"
            )
        return val


class _XlaGroup:
    """Collectives over the jax runtime (the "nccl" slot on TPU).

    Every rank must be a jax process of one ``jax.distributed`` runtime
    (the JaxConfig Train backend arranges exactly this); world_size must
    equal ``jax.process_count()``.  Ops use cross-process gathers whose
    transfers XLA lowers onto ICI/DCN — the coordinator-actor data path
    is never touched."""

    backend = "xla"

    def __init__(self, name: str, world_size: int, rank: int):
        import jax

        self.name = name
        self.world_size = world_size
        self.rank = rank
        if world_size != jax.process_count():
            raise ValueError(
                f"xla backend groups span jax processes: world_size="
                f"{world_size} != jax.process_count()={jax.process_count()} "
                "(initialize the gang with jax.distributed / JaxConfig first)"
            )

    def _gather(self, tensor) -> np.ndarray:
        from jax.experimental import multihost_utils

        return np.asarray(
            multihost_utils.process_allgather(np.asarray(tensor), tiled=False)
        )

    def _run(self, value, op: str, timeout: float = 120.0):
        t0 = time.perf_counter()
        try:
            return self._run_inner(value, op)
        finally:
            _record_collective(self.name, op, self.rank, -1,
                               time.perf_counter() - t0)

    def _run_inner(self, value, op: str):
        from jax.experimental import multihost_utils

        if op == "barrier":
            multihost_utils.sync_global_devices(f"rtpu-collective-{self.name}")
            return True
        if op == "broadcast":
            root, tensor = value
            out = multihost_utils.broadcast_one_to_all(
                np.asarray(tensor), is_source=self.rank == root
            )
            return np.asarray(out)
        stacked = self._gather(value)  # [world, ...]
        if op in ("sum", "mean", "max", "min", "product"):
            fn = {"sum": np.sum, "mean": np.mean, "max": np.max,
                  "min": np.min, "product": np.prod}[op]
            return fn(stacked, axis=0)
        if op == "allgather":
            return [stacked[i] for i in range(self.world_size)]
        if op == "reducescatter":
            acc = stacked.sum(axis=0)
            return np.array_split(acc, self.world_size, axis=0)[self.rank]
        raise ValueError(f"unknown op {op}")

    def send(self, tensor, dst_rank: int) -> None:
        raise NotImplementedError(
            "xla backend has no host-level p2p; use ppermute/send_recv inside "
            "jit (ray_tpu.parallel.collective) or the shm backend"
        )

    recv = send


def init_collective_group(
    world_size: int, rank: int, backend: str = "shm", group_name: str = "default"
) -> None:
    """Join a collective group from inside a task/actor (collective.py:120).

    shm backend: rank 0 creates the coordinator; other ranks poll for it —
    a deterministic rendezvous with no named-actor creation race.
    xla backend: the jax runtime is the rendezvous."""
    if backend in ("xla", "nccl"):
        _groups()[group_name] = _XlaGroup(group_name, world_size, rank)
        return
    if rank == 0:
        coord = _get_or_create_coordinator(group_name, world_size)
    else:
        import time

        deadline = time.monotonic() + 30.0
        while True:
            try:
                coord = ray_tpu.get_actor(f"__collective_{group_name}")
                break
            except ValueError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"rank {rank} timed out waiting for collective group "
                        f"{group_name!r} to be created by rank 0"
                    )
                time.sleep(0.01)
    _groups()[group_name] = _GroupHandle(group_name, world_size, rank, coord)


def create_collective_group(
    actors: List, world_size: int, ranks: List[int],
    backend: str = "shm", group_name: str = "default",
) -> None:
    """Driver-side declarative setup (collective.py:151): tells each actor
    to join the group with its rank.  The actor class must expose a
    ``join_collective_group(world_size, rank, group_name)`` method that
    calls :func:`init_collective_group`."""
    _get_or_create_coordinator(group_name, world_size)
    ray_tpu.get([
        a.join_collective_group.remote(world_size, rank, group_name)
        for a, rank in zip(actors, ranks)
    ])


def destroy_collective_group(group_name: str = "default") -> None:
    _groups().pop(group_name, None)


def get_rank(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g.rank if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g.world_size if g else -1


def _group(group_name: str):
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this worker; "
            "call init_collective_group() first"
        )
    return g


def _get_or_create_coordinator(group_name: str, world_size: int):
    name = f"__collective_{group_name}"
    try:
        return ray_tpu.get_actor(name)
    except ValueError:
        pass
    Coord = ray_tpu.remote(num_cpus=0)(_Coordinator)
    try:
        return Coord.options(name=name).remote(world_size)
    except Exception:
        return ray_tpu.get_actor(name)


def allreduce(tensor: np.ndarray, group_name: str = "default", op: str = "sum") -> np.ndarray:
    return _group(group_name)._run(np.asarray(tensor), op)


def allgather(tensor: np.ndarray, group_name: str = "default") -> List[np.ndarray]:
    return _group(group_name)._run(np.asarray(tensor), "allgather")


def reducescatter(tensor: np.ndarray, group_name: str = "default") -> np.ndarray:
    return _group(group_name)._run(np.asarray(tensor), "reducescatter")


def broadcast(tensor: np.ndarray, src_rank: int = 0, group_name: str = "default") -> np.ndarray:
    return _group(group_name)._run((src_rank, np.asarray(tensor)), "broadcast")


def send(tensor: np.ndarray, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send via the coordinator mailbox — NOT a group round,
    so only the (src, dst) pair participates (collective.py:531)."""
    _group(group_name).send(np.asarray(tensor), dst_rank)


def recv(shape, dtype, src_rank: int, group_name: str = "default",
         timeout: float = 120.0) -> np.ndarray:
    """Blocking point-to-point receive from ``src_rank`` (collective.py:594)."""
    val = _group(group_name).recv(src_rank, timeout)
    return np.asarray(val, dtype=dtype).reshape(shape)


def barrier(group_name: str = "default") -> None:
    _group(group_name)._run(None, "barrier")
