"""Host-side collective communication between tasks/actors.

API surface of the reference's ``ray.util.collective``
(``python/ray/util/collective/collective.py:120-615`` —
``init_collective_group / allreduce / allgather / reducescatter /
broadcast / send / recv``), re-based for TPU clusters:

- **Device tensors never travel this path.**  On-TPU reductions belong in
  jit via :mod:`ray_tpu.parallel.collective` (XLA lowers them onto ICI).
- This module moves *host* arrays between workers — the role gloo plays in
  the reference (``gloo_collective_group.py:184``) — through the
  shared-memory object store, rendezvoused by a named coordinator actor.

Each group op is a barriered round: every rank contributes its array,
rank 0's coordinator computes the reduction once, and all ranks fetch the
result as a zero-copy object-store read.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

import ray_tpu

# Process-global: a worker joins a group once and may drive it from any
# thread (train loops run on their own thread inside the hosting actor).
_GROUPS: Dict[str, "_GroupHandle"] = {}


def _groups() -> Dict[str, "_GroupHandle"]:
    return _GROUPS


class _Coordinator:
    """Named actor performing the gather/reduce/scatter rendezvous.

    One instance per group; lives on the head node.  Analog of the NCCL
    communicator bootstrap store (``nccl_collective_group.py:127``), but it
    also executes the host-side reduction itself.
    """

    def __init__(self, world_size: int):
        self.world_size = world_size
        self.rounds: Dict[int, dict] = {}
        # (src, dst) -> fifo of in-flight point-to-point tensors
        self.mailbox: Dict[tuple, list] = {}

    def p2p_put(self, src: int, dst: int, value) -> None:
        self.mailbox.setdefault((src, dst), []).append(value)

    def p2p_take(self, src: int, dst: int):
        q = self.mailbox.get((src, dst))
        if not q:
            return False, None
        return True, q.pop(0)

    def contribute(self, round_id: int, rank: int, value, op: str):
        """Blocks (by repeated polling from caller) until all ranks arrive."""
        r = self.rounds.setdefault(round_id, {"parts": {}, "op": op, "result": None})
        r["parts"][rank] = value
        if len(r["parts"]) == self.world_size:
            r["result"] = self._finish(r)
        return r["result"] is not None

    def fetch(self, round_id: int, rank: int):
        r = self.rounds.get(round_id)
        if r is None or r["result"] is None:
            return False, None
        out = r["result"]
        r.setdefault("fetched", set()).add(rank)
        if len(r["fetched"]) == self.world_size:
            del self.rounds[round_id]
        if isinstance(out, dict):  # per-rank outputs (reducescatter / recv)
            return True, out[rank]
        return True, out

    def _finish(self, r: dict):
        op = r["op"]
        parts = [r["parts"][i] for i in sorted(r["parts"])]
        if op == "barrier":
            return True
        if op in ("sum", "mean", "max", "min", "product"):
            acc = np.stack([np.asarray(p) for p in parts])
            fn = {"sum": np.sum, "mean": np.mean, "max": np.max,
                  "min": np.min, "product": np.prod}[op]
            return fn(acc, axis=0)
        if op == "allgather":
            return [np.asarray(p) for p in parts]
        if op == "broadcast":
            root, vals = parts[0][0], {i: v for i, (_, v) in enumerate(parts)}
            return vals[root]
        if op == "reducescatter":
            acc = np.sum(np.stack([np.asarray(p) for p in parts]), axis=0)
            chunks = np.array_split(acc, self.world_size, axis=0)
            return {i: chunks[i] for i in range(self.world_size)}
        raise ValueError(f"unknown op {op}")


class _GroupHandle:
    def __init__(self, name: str, world_size: int, rank: int, coordinator):
        import threading

        self.name = name
        self.world_size = world_size
        self.rank = rank
        self.coordinator = coordinator
        self.round_id = 0
        self._round_lock = threading.Lock()

    def _run(self, value, op: str, timeout: float = 120.0):
        import time

        with self._round_lock:
            rid = self.round_id
            self.round_id += 1
        self.coordinator.contribute.remote(rid, self.rank, value, op)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            done, out = ray_tpu.get(self.coordinator.fetch.remote(rid, self.rank))
            if done:
                return out
            time.sleep(0.005)
        raise TimeoutError(f"collective {op} round {rid} timed out in group {self.name}")


def init_collective_group(
    world_size: int, rank: int, backend: str = "shm", group_name: str = "default"
) -> None:
    """Join a collective group from inside a task/actor (collective.py:120).

    Rank 0 creates the coordinator; other ranks poll for it — a
    deterministic rendezvous with no named-actor creation race.
    """
    if rank == 0:
        coord = _get_or_create_coordinator(group_name, world_size)
    else:
        import time

        deadline = time.monotonic() + 30.0
        while True:
            try:
                coord = ray_tpu.get_actor(f"__collective_{group_name}")
                break
            except ValueError:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"rank {rank} timed out waiting for collective group "
                        f"{group_name!r} to be created by rank 0"
                    )
                time.sleep(0.01)
    _groups()[group_name] = _GroupHandle(group_name, world_size, rank, coord)


def create_collective_group(
    actors: List, world_size: int, ranks: List[int],
    backend: str = "shm", group_name: str = "default",
) -> None:
    """Driver-side declarative setup (collective.py:151): tells each actor
    to join the group with its rank.  The actor class must expose a
    ``join_collective_group(world_size, rank, group_name)`` method that
    calls :func:`init_collective_group`."""
    _get_or_create_coordinator(group_name, world_size)
    ray_tpu.get([
        a.join_collective_group.remote(world_size, rank, group_name)
        for a, rank in zip(actors, ranks)
    ])


def destroy_collective_group(group_name: str = "default") -> None:
    _groups().pop(group_name, None)


def get_rank(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g.rank if g else -1


def get_collective_group_size(group_name: str = "default") -> int:
    g = _groups().get(group_name)
    return g.world_size if g else -1


def _group(group_name: str) -> _GroupHandle:
    g = _groups().get(group_name)
    if g is None:
        raise RuntimeError(
            f"collective group {group_name!r} not initialized in this worker; "
            "call init_collective_group() first"
        )
    return g


def _get_or_create_coordinator(group_name: str, world_size: int):
    name = f"__collective_{group_name}"
    try:
        return ray_tpu.get_actor(name)
    except ValueError:
        pass
    Coord = ray_tpu.remote(num_cpus=0)(_Coordinator)
    try:
        return Coord.options(name=name).remote(world_size)
    except Exception:
        return ray_tpu.get_actor(name)


def allreduce(tensor: np.ndarray, group_name: str = "default", op: str = "sum") -> np.ndarray:
    return _group(group_name)._run(np.asarray(tensor), op)


def allgather(tensor: np.ndarray, group_name: str = "default") -> List[np.ndarray]:
    return _group(group_name)._run(np.asarray(tensor), "allgather")


def reducescatter(tensor: np.ndarray, group_name: str = "default") -> np.ndarray:
    return _group(group_name)._run(np.asarray(tensor), "reducescatter")


def broadcast(tensor: np.ndarray, src_rank: int = 0, group_name: str = "default") -> np.ndarray:
    return _group(group_name)._run((src_rank, np.asarray(tensor)), "broadcast")


def send(tensor: np.ndarray, dst_rank: int, group_name: str = "default") -> None:
    """Point-to-point send via the coordinator mailbox — NOT a group round,
    so only the (src, dst) pair participates (collective.py:531)."""
    g = _group(group_name)
    ray_tpu.get(g.coordinator.p2p_put.remote(g.rank, dst_rank, np.asarray(tensor)))


def recv(shape, dtype, src_rank: int, group_name: str = "default",
         timeout: float = 120.0) -> np.ndarray:
    """Blocking point-to-point receive from ``src_rank`` (collective.py:594)."""
    import time

    g = _group(group_name)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        ok, val = ray_tpu.get(g.coordinator.p2p_take.remote(src_rank, g.rank))
        if ok:
            return np.asarray(val, dtype=dtype).reshape(shape)
        time.sleep(0.005)
    raise TimeoutError(f"recv from rank {src_rank} timed out after {timeout}s")


def barrier(group_name: str = "default") -> None:
    _group(group_name)._run(None, "barrier")
