"""Analytical FLOPs / roofline model shared by bench and the live profiler.

One home for the MFU arithmetic: ``bench.py`` computed
``flops_per_token``/``peak_flops`` privately and once per run, which made
a LIVE per-step MFU impossible to compare against the end-of-run number
(any drift between two copies of the formula would make an "MFU
regressed" doctor rule meaningless).  Everything here is pure host-side
arithmetic — no jax import unless the XLA cross-check is asked for.

Conventions (unchanged from bench.py's originals):

- ``transformer_flops_per_token`` counts MODEL FLOPs only — ``6N``
  matmul fwd+bwd plus the ``12·L·D·T`` attention term; remat
  recomputation is never credited.
- ``peak_flops`` is the bf16 peak of the chip generation, keyed by
  substring of ``device.device_kind``; unknown kinds (CPU dev boxes
  included) fall back to the v5e number so ratios stay comparable
  across environments.
"""

from __future__ import annotations

from typing import Any, Optional

# bf16 peak FLOP/s per chip generation (marketing peaks; MFU denominators)
PEAK_FLOPS_BF16 = {
    "v5 lite": 197e12, "v5litepod": 197e12, "v5e": 197e12,
    "v4": 275e12, "v5p": 459e12, "v6 lite": 918e12, "v6e": 918e12,
}

DEFAULT_PEAK_FLOPS = 197e12


def peak_flops(device_kind: str) -> float:
    """bf16 peak FLOP/s for a device kind string (``jax.devices()[0]
    .device_kind``); unknown kinds fall back to the v5e peak."""
    kind = (device_kind or "").lower()
    for k, v in PEAK_FLOPS_BF16.items():
        if k in kind:
            return v
    return DEFAULT_PEAK_FLOPS


def transformer_flops_per_token(n_params: int, n_layers: int,
                                d_model: int, seq_len: int) -> float:
    """Training FLOPs per token for a decoder transformer: ``6N`` matmul
    (fwd 2N + bwd 4N) + ``12·L·D·T`` attention score/value math, fwd+bwd
    folded into the constants.  Model FLOPs only (no remat credit)."""
    return 6.0 * n_params + 12.0 * n_layers * d_model * seq_len


def model_flops_per_token(cfg: Any, n_params: int) -> float:
    """``transformer_flops_per_token`` off a model config (anything with
    ``n_layers``/``d_model``/``max_seq_len`` — gpt2/llama/bert configs
    qualify).  ``n_params`` comes from the caller (``models.*.num_params``
    over an ``eval_shape`` pytree is free) so this agrees EXACTLY with
    the bench formula rather than re-estimating the count analytically."""
    return transformer_flops_per_token(
        int(n_params), int(cfg.n_layers), int(cfg.d_model),
        int(cfg.max_seq_len))


def decode_flops_per_token(n_params: int) -> float:
    """Inference decode FLOPs per generated token: the ``2N`` forward
    matmul cost (attention-over-cache is bandwidth-, not FLOP-bound at
    decode shapes, so the matmul term is the roofline numerator)."""
    return 2.0 * n_params


def mfu(tokens_per_sec: float, flops_per_token: float,
        device_kind: str = "", peak: Optional[float] = None) -> float:
    """Model FLOPs utilization: achieved model FLOP/s over the chip's
    bf16 peak.  ``peak`` overrides the device-kind lookup (tests, CPU
    dev boxes with a synthetic denominator)."""
    denom = peak if peak else peak_flops(device_kind)
    if denom <= 0:
        return 0.0
    return tokens_per_sec * flops_per_token / denom


def xla_cost_analysis_flops(jitted_fn, *args, **kwargs) -> Optional[float]:
    """XLA's own FLOP count for one call of a jitted function, via
    ``lower(...).compile().cost_analysis()`` — the cross-check that keeps
    the analytical model honest (the two should agree within the remat /
    non-matmul-op noise).  Returns None wherever the backend doesn't
    expose cost analysis (never raises: this is a diagnostic, and a
    backend quirk must not take down a bench or doctor run)."""
    try:
        lowered = jitted_fn.lower(*args, **kwargs)
        compiled = lowered.compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not ca:
            return None
        f = ca.get("flops")
        return float(f) if f else None
    except Exception:
        return None
