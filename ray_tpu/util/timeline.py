"""Chrome-trace timeline of task execution — ``ray timeline`` analog.

The reference batches per-task profile events to the GCS and dumps
chrome-trace JSON (``python/ray/_private/state.py:414``
``chrome_tracing_dump``, ``:829 timeline``; worker-side ``Profiler``
``src/ray/core_worker/profiling.h:30``).  Here the head's task table
carries begin/end/node for every task; this renders it in the trace-event
format that chrome://tracing / Perfetto load directly.
"""

from __future__ import annotations

import json
import time
from typing import List, Optional


def timeline_events() -> List[dict]:
    from ray_tpu._private.worker import global_worker

    tasks = global_worker.client.request(
        {"type": "list_state", "what": "tasks", "limit": 100_000}
    )["value"]
    events: List[dict] = []
    now = time.time()
    for t in tasks:
        start = t.get("start_time")
        if start is None:
            continue
        end = t.get("end_time") or now
        pid = t.get("node_id") or "pending"
        tid = t.get("worker_pid") or (t.get("task_id") or "")[:8]
        exec_start, exec_end = t.get("exec_start"), t.get("exec_end")
        if exec_start:
            # queue slice (submission -> worker pickup) + exec slice,
            # keyed to the actual worker pid like the reference timeline
            events.append({
                "name": f"{t.get('name', 'task')} (queued)", "cat": "queue",
                "ph": "X", "ts": start * 1e6,
                "dur": max(0.0, (exec_start - start) * 1e6),
                "pid": pid, "tid": tid,
                "args": {"task_id": t.get("task_id")},
            })
            start, end = exec_start, exec_end or now
        events.append({
            "name": t.get("name", "task"),
            "cat": "task",
            "ph": "X",  # complete event
            "ts": start * 1e6,
            "dur": max(0.0, (end - start) * 1e6),
            "pid": pid,
            "tid": tid,
            "args": {"state": t.get("state"), "task_id": t.get("task_id")},
        })
    return events


def timeline_dump(path: Optional[str] = None) -> str:
    path = path or f"/tmp/ray_tpu/timeline-{int(time.time())}.json"
    import os

    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        json.dump(timeline_events(), f)
    return path
