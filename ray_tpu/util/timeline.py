"""Chrome-trace timeline of task execution — ``ray timeline`` analog.

The reference batches per-task profile events to the GCS and dumps
chrome-trace JSON (``python/ray/_private/state.py:414``
``chrome_tracing_dump``, ``:829 timeline``; worker-side ``Profiler``
``src/ray/core_worker/profiling.h:30``).  Here the head's task table
carries begin/end/node for every task; this renders it in the trace-event
format that chrome://tracing / Perfetto load directly.
"""

from __future__ import annotations

import json
import time
from typing import List, Optional


def timeline_events() -> List[dict]:
    from ray_tpu._private.worker import global_worker

    tasks = global_worker.client.request(
        {"type": "list_state", "what": "tasks", "limit": 100_000}
    )["value"]
    try:
        recorder = global_worker.client.request(
            {"type": "list_state", "what": "events", "limit": 100_000}
        )["value"]
    except Exception:
        recorder = []  # older head without an event table
    return merged_timeline(tasks, recorder)


def merged_timeline(tasks: List[dict], recorder_rows: List[dict]) -> List[dict]:
    """One trace: task/queue slices (+ flow arrows) interleaved with
    flight-recorder spans — streaming-operator, collective, and
    serve-admission slices land on per-source rows next to the tasks
    that caused them.  Perfetto/chrome load the merged list directly."""
    events = events_from_task_rows(tasks)
    events.extend(events_from_recorder_rows(recorder_rows))
    events.extend(_trace_flow_events(recorder_rows))
    events.extend(_metadata_events(events))
    return events


def events_from_recorder_rows(rows: List[dict]) -> List[dict]:
    """Flight-recorder events as chrome-trace events: span events
    (``span_dur`` covers [ts - dur, ts]) become "X" slices; point events
    become instants.

    The ``compiled_dag`` source (``dag/compiled.py``) is keyed by
    entity_id (``<graph>:<node>``) rather than origin, so each graph node
    gets its own timeline row — the pipeline bubble structure (exec spans
    interleaved with channel-wait spans) reads directly off the trace,
    next to the task slices.  The ``trace`` source is keyed the same way
    (entity_id = trace_id): each request trace renders as one row whose
    spans are linked by flow arrows (:func:`_trace_flow_events`)."""
    out: List[dict] = []
    for r in rows:
        ts = r.get("ts")
        source = r.get("source")
        if ts is None or source is None:
            continue
        pid, tid = _recorder_row_key(r)
        args = {"severity": r.get("severity")}
        if r.get("entity_id"):
            args["entity_id"] = r["entity_id"]
        if r.get("data"):
            args.update(r["data"])
        dur = r.get("span_dur")
        if dur:
            out.append({
                "name": r.get("message", source), "cat": source, "ph": "X",
                "ts": (ts - dur) * 1e6, "dur": dur * 1e6,
                "pid": pid, "tid": tid, "args": args,
            })
        else:
            out.append({
                "name": r.get("message", source), "cat": source, "ph": "i",
                "s": "t", "ts": ts * 1e6, "pid": pid, "tid": tid,
                "args": args,
            })
    return out


def _recorder_row_key(r: dict):
    """(pid, tid) for a recorder event: per-source process rows, keyed by
    origin — except compiled_dag (per graph node) and trace (per trace
    id), whose slices AND flow arrows must land on the same row."""
    source = r.get("source")
    pid = f"recorder:{source}"
    if source in ("compiled_dag", "trace"):
        tid = str(r.get("entity_id") or r.get("origin") or "events")
    else:
        tid = str(r.get("origin") or r.get("entity_id") or "events")
    return pid, tid


def _trace_flow_events(rows: List[dict]) -> List[dict]:
    """Per-trace flow arrows: recorder span events carrying trace lineage
    (``data.span_id``/``parent_span_id`` — trace-source spans AND traced
    compiled-graph spans) get chrome flow "s"/"f" pairs parent -> child,
    so a request's causal chain reads as arrows across the merged rows."""
    spans: List[dict] = []
    by_id: dict = {}
    for r in rows:
        d = r.get("data") or {}
        if r.get("ts") is None or not d.get("span_id"):
            continue
        spans.append(r)
        by_id.setdefault(d["span_id"], r)

    out: List[dict] = []
    for r in spans:
        d = r["data"]
        parent = by_id.get(d.get("parent_span_id"))
        if parent is None or parent is r:
            continue
        p_pid, p_tid = _recorder_row_key(parent)
        c_pid, c_tid = _recorder_row_key(r)
        p_start = (parent["ts"] - (parent.get("span_dur") or 0.0)) * 1e6
        c_start = (r["ts"] - (r.get("span_dur") or 0.0)) * 1e6
        out.append({"name": r.get("message", "span"), "cat": "trace_flow",
                    "ph": "s", "id": d["span_id"], "ts": p_start,
                    "pid": p_pid, "tid": p_tid})
        out.append({"name": r.get("message", "span"), "cat": "trace_flow",
                    "ph": "f", "bp": "e", "id": d["span_id"],
                    "ts": max(c_start, p_start), "pid": c_pid,
                    "tid": c_tid})
    return out


def _metadata_events(events: List[dict]) -> List[dict]:
    """Chrome-trace ``M`` metadata so Perfetto labels rows with node ids
    and worker pids instead of raw hex/ints."""
    by_pid: dict = {}
    for e in events:
        pid = e.get("pid")
        if pid is None:
            continue
        tids = by_pid.setdefault(pid, set())
        if e.get("tid") is not None:
            tids.add(e["tid"])
    out: List[dict] = []
    for pid, tids in by_pid.items():
        if isinstance(pid, str) and pid.startswith("recorder:"):
            pname = f"flight recorder · {pid[len('recorder:'):]}"
        else:
            pname = f"node {pid}"
        out.append({"name": "process_name", "ph": "M", "ts": 0, "pid": pid,
                    "args": {"name": pname}})
        for tid in tids:
            tname = f"worker pid {tid}" if isinstance(tid, int) else str(tid)
            out.append({"name": "thread_name", "ph": "M", "ts": 0, "pid": pid,
                        "tid": tid, "args": {"name": tname}})
    return out


def events_from_task_rows(tasks: List[dict]) -> List[dict]:
    """Render task-table rows as chrome-trace events.  Shared by the
    driver CLI path above and the dashboard's ``/api/timeline`` (which
    reads the head's table directly — no driver client there)."""
    events: List[dict] = []
    now = time.time()
    for t in tasks:
        start = t.get("start_time")
        if start is None:
            continue
        end = t.get("end_time") or now
        pid = t.get("node_id") or "pending"
        tid = t.get("worker_pid") or (t.get("task_id") or "")[:8]
        exec_start, exec_end = t.get("exec_start"), t.get("exec_end")
        if exec_start:
            # queue slice (submission -> worker pickup) + exec slice,
            # keyed to the actual worker pid like the reference timeline
            events.append({
                "name": f"{t.get('name', 'task')} (queued)", "cat": "queue",
                "ph": "X", "ts": start * 1e6,
                "dur": max(0.0, (exec_start - start) * 1e6),
                "pid": pid, "tid": tid,
                "args": {"task_id": t.get("task_id")},
            })
            start, end = exec_start, exec_end or now
        args = {"state": t.get("state"), "task_id": t.get("task_id")}
        tc = t.get("trace_ctx")
        if tc:
            args.update(trace_id=tc.get("trace_id"), span_id=tc.get("span_id"),
                        parent_span_id=tc.get("parent_span_id"))
        events.append({
            "name": t.get("name", "task"),
            "cat": "task",
            "ph": "X",  # complete event
            "ts": start * 1e6,
            "dur": max(0.0, (end - start) * 1e6),
            "pid": pid,
            "tid": tid,
            "args": args,
        })
        if tc:
            # flow arrows: submitter span -> this task (chrome flow events
            # bind on matching id; the parent task emits the "s" below)
            events.append({
                "name": tc.get("name", "submit"), "cat": "trace", "ph": "f",
                "bp": "e", "id": tc.get("span_id"),
                "ts": start * 1e6, "pid": pid, "tid": tid,
            })
    # emit flow starts from each parent task's exec window
    by_span = {
        (t.get("trace_ctx") or {}).get("span_id"): t
        for t in tasks if t.get("trace_ctx")
    }
    for t in tasks:
        tc = t.get("trace_ctx")
        if not tc:
            continue
        parent = by_span.get(tc.get("parent_span_id"))
        if parent is None or parent.get("start_time") is None:
            continue
        ts = (parent.get("exec_start") or parent["start_time"]) * 1e6
        events.append({
            "name": tc.get("name", "submit"), "cat": "trace", "ph": "s",
            "id": tc.get("span_id"), "ts": ts,
            "pid": parent.get("node_id") or "pending",
            "tid": parent.get("worker_pid") or (parent.get("task_id") or "")[:8],
        })
    return events


def timeline_dump(path: Optional[str] = None) -> str:
    path = path or f"/tmp/ray_tpu/timeline-{int(time.time())}.json"
    import os

    parent = os.path.dirname(path)
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as f:
        # default=repr: recorder-event args can carry arbitrary app
        # payloads (numpy scalars) and the dump must still be valid JSON
        json.dump(timeline_events(), f, default=repr)
    return path
