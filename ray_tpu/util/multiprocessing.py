"""``multiprocessing.Pool`` API over cluster actors.

Reference: ``python/ray/util/multiprocessing/pool.py`` (a drop-in
``Pool`` whose workers are actors, so pool jobs ride the scheduler and
can span nodes).  Covers the surface programs actually use: ``apply``,
``apply_async``, ``map``, ``map_async``, ``starmap``, ``starmap_async``,
``imap``, ``imap_unordered``, ``close``/``terminate``/``join``, context
manager, chunking.

Chunks ship as single actor calls (one control-plane message per chunk,
not per item) and fan out round-robin across the pool's actors.
"""

from __future__ import annotations

import itertools
import threading
import warnings
import weakref
from multiprocessing import TimeoutError  # the Pool-API timeout type
from typing import Any, Callable, Iterable, List, Optional

import ray_tpu

_CHUNK_TARGET = 4  # chunks per worker per map, the stdlib heuristic


@ray_tpu.remote
class _PoolWorker:
    """One pool seat: runs pickled callables over item chunks."""

    def __init__(self, initializer=None, initargs=()):
        if initializer is not None:
            initializer(*initargs)

    def run_chunk(self, func, chunk, star: bool) -> List[Any]:
        if star:
            return [func(*args) for args in chunk]
        return [func(arg) for arg in chunk]

    def run_one(self, func, args, kwargs) -> Any:
        return func(*args, **(kwargs or {}))


class AsyncResult:
    """``multiprocessing.pool.AsyncResult`` semantics over ObjectRefs."""

    def __init__(self, refs: List, combine: Callable[[List[Any]], Any],
                 callback=None, error_callback=None):
        self._refs = refs
        self._combine = combine
        self._value = None
        self._error: Optional[BaseException] = None
        self._done = threading.Event()
        # resolve on a side thread so callbacks fire without the caller
        # blocking (the stdlib's result-handler thread)
        t = threading.Thread(target=self._resolve,
                             args=(callback, error_callback), daemon=True)
        t.start()

    def _resolve(self, callback, error_callback) -> None:
        try:
            self._value = self._combine(ray_tpu.get(self._refs))
        except BaseException as e:  # noqa: BLE001 — surfaced via .get()
            self._error = e
        self._done.set()
        try:
            if self._error is None and callback is not None:
                callback(self._value)
            elif self._error is not None and error_callback is not None:
                error_callback(self._error)
        except Exception:
            pass

    def wait(self, timeout: Optional[float] = None) -> None:
        self._done.wait(timeout)

    def ready(self) -> bool:
        return self._done.is_set()

    def successful(self) -> bool:
        if not self._done.is_set():
            raise ValueError("result is not ready")
        return self._error is None

    def get(self, timeout: Optional[float] = None):
        if not self._done.wait(timeout):
            # multiprocessing.TimeoutError, NOT the builtin: ported code
            # catches the Pool API's exception type
            raise TimeoutError("result not ready")
        if self._error is not None:
            raise self._error
        return self._value


class Pool:
    def __init__(self, processes: Optional[int] = None, initializer=None,
                 initargs: tuple = (), maxtasksperchild: Optional[int] = None,
                 ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(1, int(
                ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError(f"processes must be >= 1, got {processes}")
        if maxtasksperchild is not None:
            # actor seats are long-lived by design (no per-N-tasks worker
            # recycling); a silent no-op would hide that from code that
            # relies on recycling to bound leaks
            warnings.warn(
                "ray_tpu.util.multiprocessing.Pool ignores maxtasksperchild:"
                " pool workers are long-lived actors and are not recycled",
                UserWarning, stacklevel=2)
        self._size = processes
        cls = _PoolWorker
        if ray_remote_args:
            cls = cls.options(**ray_remote_args)
        self._actors = [cls.remote(initializer, initargs)
                        for _ in range(processes)]
        self._rr = itertools.count()
        self._closed = False
        # outstanding async results, so join() can actually wait
        self._inflight = weakref.WeakSet()

    # -- plumbing ------------------------------------------------------
    def _actor(self):
        return self._actors[next(self._rr) % self._size]

    def _check_running(self) -> None:
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]):
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._size * _CHUNK_TARGET) or 1)
        return [items[i:i + chunksize]
                for i in range(0, len(items), chunksize)], chunksize

    def _map_refs(self, func, iterable, chunksize, star: bool):
        chunks, _ = self._chunks(iterable, chunksize)
        return [self._actor().run_chunk.remote(func, c, star)
                for c in chunks]

    @staticmethod
    def _flatten(chunked: List[List[Any]]) -> List[Any]:
        return [x for chunk in chunked for x in chunk]

    # -- the Pool API --------------------------------------------------
    def apply(self, func, args: tuple = (), kwds: Optional[dict] = None):
        return self.apply_async(func, args, kwds).get()

    def apply_async(self, func, args: tuple = (), kwds: Optional[dict] = None,
                    callback=None, error_callback=None) -> AsyncResult:
        self._check_running()
        ref = self._actor().run_one.remote(func, args, kwds)
        r = AsyncResult([ref], lambda vs: vs[0], callback, error_callback)
        self._inflight.add(r)
        return r

    def map(self, func, iterable, chunksize: Optional[int] = None):
        return self.map_async(func, iterable, chunksize).get()

    def map_async(self, func, iterable, chunksize: Optional[int] = None,
                  callback=None, error_callback=None) -> AsyncResult:
        self._check_running()
        refs = self._map_refs(func, iterable, chunksize, star=False)
        r = AsyncResult(refs, self._flatten, callback, error_callback)
        self._inflight.add(r)
        return r

    def starmap(self, func, iterable, chunksize: Optional[int] = None):
        return self.starmap_async(func, iterable, chunksize).get()

    def starmap_async(self, func, iterable, chunksize: Optional[int] = None,
                      callback=None, error_callback=None) -> AsyncResult:
        self._check_running()
        refs = self._map_refs(func, iterable, chunksize, star=True)
        r = AsyncResult(refs, self._flatten, callback, error_callback)
        self._inflight.add(r)
        return r

    def imap(self, func, iterable, chunksize: Optional[int] = None):
        """Ordered lazy iteration; chunks resolve as they complete.
        chunksize defaults to 1 (the stdlib's), so the first item yields
        after ONE call — not after a map()-sized chunk.

        Submission is EAGER, like the stdlib: every chunk is in flight
        when ``imap`` returns — workers compute while the caller is not
        yet (or slowly) iterating.  Only result consumption is lazy."""
        self._check_running()
        refs = self._map_refs(func, iterable, chunksize or 1, star=False)

        def drain_ordered():
            for ref in refs:
                yield from ray_tpu.get(ref)

        return drain_ordered()

    def imap_unordered(self, func, iterable, chunksize: Optional[int] = None):
        self._check_running()
        # eager submission at call time (see imap)
        refs = self._map_refs(func, iterable, chunksize or 1, star=False)

        def drain_completed():
            pending = list(refs)
            while pending:
                ready, rest = ray_tpu.wait(pending, num_returns=1)
                pending = rest
                yield from ray_tpu.get(ready[0])

        return drain_completed()

    # -- lifecycle -----------------------------------------------------
    def close(self) -> None:
        self._closed = True

    def terminate(self) -> None:
        self._closed = True
        for a in self._actors:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
        self._actors = []

    def join(self) -> None:
        """Block until all outstanding async work has resolved (the
        stdlib contract: close(); join() means every submitted task
        finished)."""
        if not self._closed:
            raise ValueError("Pool is still running")
        for r in list(self._inflight):
            r.wait()

    def __enter__(self) -> "Pool":
        return self

    def __exit__(self, *exc) -> None:
        self.terminate()


__all__ = ["Pool", "AsyncResult"]
