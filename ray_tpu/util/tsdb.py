"""Head-side metrics TSDB: bounded in-memory time series with staged
downsampling.

The metric registry (``util/metrics.py``) is a point-in-time snapshot
store — it can answer "what is the queue depth" but not "has the queue
depth been climbing for ten minutes", which is the question every leak,
creeping RSS, and slowly saturating router poses.  This module keeps the
trend: the head folds every registry snapshot that arrives over the
``metrics_report`` path (workers, node agents, its own self-sample loop)
into per-series ring buffers, Monarch-style — bounded in-memory storage,
staged resolution decay instead of unbounded growth:

- **raw**   ~5 s samples, ring of ``raw_points`` (default 1 h of history)
- **1 min** downsampled buckets, ring of ``m1_points`` (default 6 h)
- **10 min** downsampled buckets, ring of ``m10_points`` (default 28 h)

Each downsample bucket keeps ``(last, max, sum, count)`` so a query can
pick the aggregation that matches the metric's semantics — ``last`` for
cumulative counters, ``last``/``max`` for gauges, ``sum`` for per-bucket
deltas — without re-reading raw data that no longer exists.  Histograms
ingest as two cumulative scalar series, ``<name>_count`` and
``<name>_sum`` (rates and means are derivable; full bucket vectors would
multiply storage by the bucket count for little trend value).

Bounded three ways: fixed ring lengths per series, a total byte cap that
evicts least-recently-updated series first, and per-origin expiry so a
dead node's or worker's series stop occupying the store (the registry
analog of this fix lives in ``_Registry.expire_origins``).
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.events import _int_env
from ray_tpu._private.locks import make_lock

LabelKey = Tuple[Tuple[str, str], ...]

# Kill switch for the whole resource-accounting layer (head ingest +
# sampling).  Initialized from the env but MUTABLE module state read per
# tick: the resource_accounting_overhead bench flips it at runtime.
ENABLED = os.environ.get("RAY_TPU_TSDB", "1") not in ("0", "false", "no")


# stage ring lengths: 1h raw @5s, 6h of 1-min buckets, 28h of 10-min
DEFAULT_RAW_POINTS = _int_env("RAY_TPU_TSDB_RAW_POINTS", 720)
DEFAULT_M1_POINTS = _int_env("RAY_TPU_TSDB_M1_POINTS", 360)
DEFAULT_M10_POINTS = _int_env("RAY_TPU_TSDB_M10_POINTS", 168)
# total-store byte cap; least-recently-updated series evict first
DEFAULT_MAX_BYTES = _int_env("RAY_TPU_TSDB_MAX_BYTES", 64 << 20)
# origins not refreshed within this many push intervals expire
ORIGIN_EXPIRY_INTERVALS = 3

# byte-cost model for the cap (measured: a (float, float) tuple in a
# deque costs ~120 B; a 4-float bucket tuple ~180 B; per-series dict +
# key overhead ~600 B).  An estimate is enough — the cap bounds the
# order of magnitude, not the malloc.
_RAW_POINT_COST = 120
_BUCKET_COST = 180
_SERIES_OVERHEAD = 600

_AGGS = ("last", "max", "min", "sum", "avg", "count")


class _Series:
    """One (metric, labelset) stream across the three stages."""

    __slots__ = ("mtype", "origin", "last_ts", "raw", "m1", "m10",
                 "_cur1", "_cur10")

    def __init__(self, mtype: str, origin: str,
                 raw_points: int, m1_points: int, m10_points: int):
        self.mtype = mtype
        self.origin = origin
        self.last_ts = 0.0
        self.raw: deque = deque(maxlen=raw_points)      # (ts, value)
        self.m1: deque = deque(maxlen=m1_points)        # (ts, last, mx, mn, sm, n)
        self.m10: deque = deque(maxlen=m10_points)
        self._cur1: Optional[list] = None   # [bucket_id, last, mx, mn, sm, n]
        self._cur10: Optional[list] = None

    def add(self, ts: float, value: float) -> None:
        self.last_ts = ts
        self.raw.append((ts, value))
        self._roll(ts, value, 60.0, "_cur1", self.m1)
        self._roll(ts, value, 600.0, "_cur10", self.m10)

    def _roll(self, ts: float, value: float, width: float,
              cur_attr: str, ring: deque) -> None:
        bucket = int(ts // width)
        cur = getattr(self, cur_attr)
        if cur is None or cur[0] != bucket:
            if cur is not None:
                # finalize the closed bucket, stamped at its end time
                ring.append(((cur[0] + 1) * width,
                             cur[1], cur[2], cur[3], cur[4], cur[5]))
            setattr(self, cur_attr, [bucket, value, value, value, value, 1])
        else:
            cur[1] = value
            cur[2] = max(cur[2], value)
            cur[3] = min(cur[3], value)
            cur[4] += value
            cur[5] += 1

    def bytes_estimate(self) -> int:
        return (_SERIES_OVERHEAD + len(self.raw) * _RAW_POINT_COST
                + (len(self.m1) + len(self.m10)) * _BUCKET_COST)

    def _stage_points(self, step_s: float, start: float):
        """Points as (ts, last, max, min, sum, count) from the finest
        stage that both resolves ``step_s`` AND reaches back to
        ``start``.  Resolution alone is not enough: the raw ring holds
        ~1 h, so a 24 h query at a 5 s step must escalate to the
        minute/10-minute rings (whose whole purpose is covering windows
        the raw ring can't) instead of silently returning the last hour
        as if it were the full window."""
        stages = []  # (points, ring ever evicted) fine -> coarse
        if step_s < 60.0:
            stages.append(([(ts, v, v, v, v, 1) for ts, v in self.raw],
                           len(self.raw) == self.raw.maxlen))
        if step_s < 600.0:
            pts = list(self.m1)
            if self._cur1 is not None:
                c = self._cur1
                pts.append((self.last_ts, c[1], c[2], c[3], c[4], c[5]))
            stages.append((pts, len(self.m1) == self.m1.maxlen))
        pts = list(self.m10)
        if self._cur10 is not None:
            c = self._cur10
            pts.append((self.last_ts, c[1], c[2], c[3], c[4], c[5]))
        stages.append((pts, len(self.m10) == self.m10.maxlen))
        for pts, evicted in stages:
            # a stage covers the window when its oldest retained point
            # predates the start, or its ring never evicted anything —
            # then nothing older ever existed and coarser stages know
            # no more
            if pts and (not evicted or pts[0][0] <= start):
                return pts
        for pts, _ in stages:  # nothing reaches start: finest non-empty
            if pts:
                return pts
        return []


def _default_agg(mtype: str) -> str:
    # counters are cumulative — the newest value in a bin carries the
    # whole story; gauges too (max/min stay available explicitly)
    return "last"


class TimeSeriesStore:
    """Bounded multi-stage time-series store for registry snapshots."""

    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES,
                 raw_points: int = DEFAULT_RAW_POINTS,
                 m1_points: int = DEFAULT_M1_POINTS,
                 m10_points: int = DEFAULT_M10_POINTS):
        self._lock = make_lock("tsdb.store")
        self._max_bytes = int(max_bytes)
        self._raw_points = int(raw_points)
        self._m1_points = int(m1_points)
        self._m10_points = int(m10_points)
        # (name, labelkey) -> _Series; ordered by last update (LRU evict)
        self._series: "OrderedDict[Tuple[str, LabelKey], _Series]" = OrderedDict()
        # name -> (type, help) directory (survives series eviction)
        self._meta: Dict[str, Tuple[str, str]] = {}
        self._origin_seen: Dict[str, float] = {}
        self._est_bytes = 0
        self._evicted_series = 0

    # -- ingest --------------------------------------------------------
    def ingest(self, origin: str, snap: Dict[str, dict],
               ts: Optional[float] = None) -> None:
        """Fold one registry snapshot in, tagging every series with its
        origin (worker id, node id, or "head") exactly like
        ``_Registry.merge`` does for the exposition path."""
        if ts is None:
            ts = time.time()
        with self._lock:
            self._origin_seen[origin] = ts
            for name, m in snap.items():
                mtype = m.get("type", "gauge")
                help_ = m.get("help", "")
                if m.get("values") and mtype != "histogram":
                    self._meta.setdefault(name, (mtype, help_))
                for key, value in m.get("values", {}).items():
                    key = tuple(key)
                    if not any(k == "origin" for k, _ in key):
                        key = key + (("origin", origin),)
                    if mtype == "histogram" and isinstance(value, dict):
                        self._meta.setdefault(
                            name + "_count", ("counter", help_))
                        self._meta.setdefault(
                            name + "_sum", ("counter", help_))
                        self._add_locked(name + "_count", key, "counter",
                                         origin, ts, float(value["count"]))
                        self._add_locked(name + "_sum", key, "counter",
                                         origin, ts, float(value["sum"]))
                    elif isinstance(value, (int, float)):
                        self._add_locked(name, key, mtype, origin, ts,
                                         float(value))
            self._enforce_cap_locked()

    def add_sample(self, name: str, value: float,
                   tags: Optional[Dict[str, str]] = None,
                   mtype: str = "gauge", origin: str = "head",
                   ts: Optional[float] = None) -> None:
        """Direct single-sample ingest (synthetic series in tests/bench)."""
        if ts is None:
            ts = time.time()
        key = tuple(sorted((tags or {}).items()))
        if not any(k == "origin" for k, _ in key):
            key = key + (("origin", origin),)
        with self._lock:
            self._origin_seen[origin] = max(
                self._origin_seen.get(origin, 0.0), ts)
            self._meta.setdefault(name, (mtype, ""))
            self._add_locked(name, key, mtype, origin, ts, float(value))
            self._enforce_cap_locked()

    def _add_locked(self, name: str, key: LabelKey, mtype: str,
                    origin: str, ts: float, value: float) -> None:
        sk = (name, key)
        s = self._series.get(sk)
        if s is None:
            s = self._series[sk] = _Series(
                mtype, origin, self._raw_points, self._m1_points,
                self._m10_points)
            self._est_bytes += _SERIES_OVERHEAD
        else:
            self._series.move_to_end(sk)
        raw_n, m1_n, m10_n = len(s.raw), len(s.m1), len(s.m10)
        s.add(ts, value)
        # rings at maxlen stay flat (append evicts); only growth costs
        self._est_bytes += (len(s.raw) - raw_n) * _RAW_POINT_COST \
            + (len(s.m1) - m1_n + len(s.m10) - m10_n) * _BUCKET_COST

    def _enforce_cap_locked(self) -> None:
        while self._est_bytes > self._max_bytes and len(self._series) > 1:
            _, s = self._series.popitem(last=False)  # least recently updated
            self._est_bytes -= s.bytes_estimate()
            self._evicted_series += 1

    # -- expiry --------------------------------------------------------
    def expire_stale(self, max_age_s: float,
                     now: Optional[float] = None) -> int:
        """Drop SERIES (and origins) not refreshed within ``max_age_s``.

        Series-granular on purpose: every push re-ingests all of an
        origin's current values, so a series whose ``last_ts`` stopped
        advancing means either its origin died OR its label set vanished
        from a still-live origin's pushes (a worker that died on an agent
        node whose agent keeps reporting).  Both must leave, or
        per-entity series accumulate forever with churn.  Returns the
        number of series dropped."""
        if now is None:
            now = time.time()
        dropped = 0
        with self._lock:
            for sk in [sk for sk, s in self._series.items()
                       if now - s.last_ts > max_age_s]:
                self._est_bytes -= self._series.pop(sk).bytes_estimate()
                dropped += 1
            for o in [o for o, ts in self._origin_seen.items()
                      if now - ts > max_age_s]:
                del self._origin_seen[o]
        return dropped

    # -- query ---------------------------------------------------------
    def list_metrics(self) -> List[dict]:
        with self._lock:
            by_name: Dict[str, dict] = {}
            for (name, key), s in self._series.items():
                row = by_name.get(name)
                if row is None:
                    mtype, help_ = self._meta.get(name, (s.mtype, ""))
                    row = by_name[name] = {
                        "name": name, "type": mtype, "help": help_,
                        "num_series": 0, "origins": set(), "last_ts": 0.0,
                    }
                row["num_series"] += 1
                row["origins"].add(s.origin)
                row["last_ts"] = max(row["last_ts"], s.last_ts)
        # sort OUTSIDE the lock: by_name is ours alone once built, and
        # the ingest path must never wait on a directory listing
        out = []
        for row in sorted(by_name.values(), key=lambda r: r["name"]):
            row["origins"] = sorted(row["origins"])
            out.append(row)
        return out

    def query(self, name: str, window_s: float = 3600.0,
              step_s: float = 0.0, tags: Optional[Dict[str, str]] = None,
              agg: Optional[str] = None,
              now: Optional[float] = None) -> dict:
        """Aligned time series for ``name`` over the trailing window.

        Every matching label series returns separately (callers sum/plot
        per-series).  ``step_s <= 0`` defaults to the cluster's actual
        push cadence (``metrics.push_interval_s`` — the one knob every
        sampling loop ticks on, so default-step bins line up with real
        samples); ``step_s > window_s`` degrades to a single bin; an
        empty/negative window returns no points — never raises on shape,
        only on an unknown aggregation."""
        from ray_tpu.util.metrics import push_interval_s

        if agg is not None and agg not in _AGGS:
            raise ValueError(f"unknown agg {agg!r} (one of {_AGGS})")
        if now is None:
            now = time.time()
        step_s = float(step_s) if step_s and step_s > 0 else push_interval_s()
        window_s = float(window_s)
        # step > window degrades to a single bin inside _bin; an empty or
        # negative window yields no points — both are shape, not errors
        start = now - window_s
        out_series: List[dict] = []
        want = tuple(sorted((tags or {}).items()))
        with self._lock:
            mtype, help_ = self._meta.get(name, ("gauge", ""))
            matches = [(key, s) for (n, key), s in self._series.items()
                       if n == name and all(kv in key for kv in want)]
            use = agg or _default_agg(mtype)
            for key, s in matches:
                pts = [p for p in s._stage_points(step_s, start)
                       if p[0] > start]
                out_series.append({
                    "tags": dict(key),
                    "points": _bin(pts, start, now, step_s, use),
                })
        return {"name": name, "type": mtype, "help": help_,
                "window_s": window_s, "step_s": step_s,
                "agg": agg or _default_agg(mtype), "series": out_series}

    # -- admin ---------------------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {
                "num_series": len(self._series),
                "num_metrics": len({n for n, _ in self._series}),
                "num_origins": len(self._origin_seen),
                "est_bytes": self._est_bytes,
                "max_bytes": self._max_bytes,
                "evicted_series": self._evicted_series,
            }

    def memory_bytes(self) -> int:
        with self._lock:
            return self._est_bytes

    def origins(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._origin_seen)


def _bin(points, start: float, end: float, step_s: float,
         agg: str) -> List[List[float]]:
    """Fold stage points into aligned [ts, value] bins.  Bins with no
    source point are skipped (gaps stay visible as gaps — interpolating
    would invent data a doctor rule could false-positive on)."""
    if end <= start or not points:
        return []
    n_bins = max(1, int(round((end - start) / step_s)))
    bins: Dict[int, list] = {}
    for ts, last, mx, mn, sm, cnt in points:
        i = min(n_bins - 1, max(0, int((ts - start) / step_s)))
        b = bins.get(i)
        if b is None:
            bins[i] = [ts, last, mx, mn, sm, cnt]
        else:
            # points arrive time-ordered within a series
            b[0], b[1] = ts, last
            b[2] = max(b[2], mx)
            b[3] = min(b[3], mn)
            b[4] += sm
            b[5] += cnt
    out = []
    for i in sorted(bins):
        ts, last, mx, mn, sm, cnt = bins[i]
        if agg == "last":
            v = last
        elif agg == "max":
            v = mx
        elif agg == "min":
            v = mn
        elif agg == "sum":
            v = sm
        elif agg == "count":
            v = float(cnt)
        else:  # avg
            v = sm / cnt if cnt else 0.0
        out.append([round(start + (i + 1) * step_s, 3), v])
    return out
