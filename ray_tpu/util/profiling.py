"""Profiling helpers: XLA device traces + optional OpenTelemetry spans.

The reference's tracing layer (``python/ray/util/tracing/tracing_helper.py``
— lazily imported opentelemetry, span contexts injected into task
metadata) and its on-demand profiling endpoints
(``dashboard/modules/reporter/profile_manager.py``).  TPU additions:
``profile_trace`` captures an XLA/jax device trace viewable in
TensorBoard or Perfetto — the device-side half the reference never had.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, Optional


@contextlib.contextmanager
def profile_trace(logdir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a jax/XLA profiler trace for the enclosed block.

    Run inside a Train worker loop (or any TPU-holding task)::

        with profiling.profile_trace("/tmp/trace"):
            train_step(...)

    Open with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    import jax

    jax.profiler.start_trace(logdir, create_perfetto_trace=False)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def span(name: str, attributes: Optional[dict] = None) -> Iterator[None]:
    """OpenTelemetry span when the SDK is importable, no-op otherwise
    (the reference's lazy-import pattern, ``tracing_helper.py:53-59``)."""
    try:
        from opentelemetry import trace  # type: ignore
    except ImportError:
        yield
        return
    tracer = trace.get_tracer("ray_tpu")
    with tracer.start_as_current_span(name, attributes=attributes or {}):
        yield


class timed:
    """Tiny wall-clock scope, recorded into ray_tpu.util.metrics::

        with profiling.timed("ingest_batch"):
            ...
    """

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        from ray_tpu.util.metrics import Histogram

        Histogram(f"ray_tpu_timed_{self.name}_seconds",
                  f"wall time of {self.name} scopes").observe(
            time.perf_counter() - self._t0)
        return False
