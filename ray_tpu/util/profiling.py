"""Profiling helpers: XLA device traces + optional OpenTelemetry spans.

The reference's tracing layer (``python/ray/util/tracing/tracing_helper.py``
— lazily imported opentelemetry, span contexts injected into task
metadata) and its on-demand profiling endpoints
(``dashboard/modules/reporter/profile_manager.py``).  TPU additions:
``profile_trace`` captures an XLA/jax device trace viewable in
TensorBoard or Perfetto — the device-side half the reference never had.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Iterator, Optional

# one device trace at a time per process: jax.profiler.start_trace raises
# out of XLA on a second concurrent start, and a nested profile scope
# (e.g. profile_step firing inside a user's own profile_trace block)
# must degrade to a no-op instead of killing the train loop
_trace_lock = threading.Lock()
_trace_active = False


@contextlib.contextmanager
def profile_trace(logdir: str, *, host_tracer_level: int = 2) -> Iterator[None]:
    """Capture a jax/XLA profiler trace for the enclosed block.

    Run inside a Train worker loop (or any TPU-holding task)::

        with profiling.profile_trace("/tmp/trace"):
            train_step(...)

    Open with TensorBoard's profile plugin or ui.perfetto.dev.
    Re-entrant by degrading: when a trace is already running in this
    process the inner scope is a no-op (the outer trace still covers it)
    rather than an XLA "profiler already started" crash.
    """
    global _trace_active
    import jax

    with _trace_lock:
        if _trace_active:
            started = False
        else:
            _trace_active = started = True
    if not started:
        yield
        return
    try:
        jax.profiler.start_trace(logdir, create_perfetto_trace=False)
    except Exception:
        # a start failure (e.g. a foreign profiler session already owns
        # the backend) must not take the step down with it
        with _trace_lock:
            _trace_active = False
        yield
        return
    try:
        yield
    finally:
        try:
            jax.profiler.stop_trace()
        finally:
            with _trace_lock:
                _trace_active = False


def profile_step(logdir: str) -> bool:
    """Arm a ONE-STEP device trace on the process's active
    :class:`~ray_tpu.util.perf.StepProfiler`: the next ``prof.step()``
    scope runs inside :func:`profile_trace` and the trace lands under
    ``logdir``.  This is the on-demand hook a doctor perf rule (or an
    operator staring at ``ray_tpu perf``) triggers to capture device
    detail for exactly one step without paying trace overhead steadily.
    Returns whether a profiler was armed (False: no active profiler in
    this process)."""
    from ray_tpu.util import perf as _perf

    prof = _perf.active_profiler()
    if prof is None:
        return False
    prof.arm_trace(logdir)
    return True


@contextlib.contextmanager
def span(name: str, attributes: Optional[dict] = None) -> Iterator[None]:
    """OpenTelemetry span when the SDK is importable, no-op otherwise
    (the reference's lazy-import pattern, ``tracing_helper.py:53-59``)."""
    try:
        from opentelemetry import trace  # type: ignore
    except ImportError:
        yield
        return
    tracer = trace.get_tracer("ray_tpu")
    with tracer.start_as_current_span(name, attributes=attributes or {}):
        yield


class timed:
    """Tiny wall-clock scope, recorded into ray_tpu.util.metrics::

        with profiling.timed("ingest_batch"):
            ...
    """

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        from ray_tpu.util.metrics import Histogram

        Histogram(f"ray_tpu_timed_{self.name}_seconds",
                  f"wall time of {self.name} scopes").observe(
            time.perf_counter() - self._t0)
        return False
