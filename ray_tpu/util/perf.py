"""Per-step performance attribution: phases, live MFU, compile cache, HBM.

The trace plane (PR 4) attributes REQUEST time; this module attributes
DEVICE time.  A :class:`StepProfiler` lives inside a train loop (or any
step-shaped device workload) and splits every step into phases —
ingest-wait / h2d / compile / compute / collective / other — that sum
EXACTLY to the measured step wall (``trace_analysis.py``-style: the
residual no explicit scope covers is billed to ``other``, never
dropped).  Each step also yields a live MFU (via the shared
``util/flops.py`` roofline model — the same arithmetic bench.py uses at
end of run) and an HBM sample, and jit functions wrapped with
:meth:`StepProfiler.wrap_jit` get per-shape-signature compile-cache
accounting, so a recompile storm is a visible counter instead of a
mystery slowdown.

Everything publishes through the existing surfaces:

- flight recorder: ``perf``-source span events (``step phases``,
  ``jit compile``) — timeline rows, crash dumps, and the doctor's
  recompile-storm / ingest-bound rules for free;
- metrics registry → head TSDB: phase histograms, a per-rank MFU gauge
  (the ``mfu_regression`` trend rule's input), jit hit/miss counters,
  HBM gauges (``ray_tpu top`` renders the watermark);
- ``summary()``: the in-process aggregate ``ray_tpu perf`` and
  ``BackendExecutor.perf_summaries()`` hand back.

Cost discipline matches the rest of the observability layer: the hot
half is a few ``perf_counter()`` reads and dict adds per step (steps are
ms-scale; the ``perf_observability_overhead`` bench row gates < 1%), and
every emission is gated on ``events.ENABLED``.
"""

from __future__ import annotations

import contextlib
import hashlib
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from ray_tpu._private import events as _events
from ray_tpu.util import flops as flops_mod

# phase names the step profiler bills; anything else the loop invents is
# carried through verbatim (the breakdown renders whatever it sees)
KNOWN_PHASES = ("ingest", "h2d", "compile", "compute", "collective", "other")

_PERF_METRICS = None
_METRICS_LOCK = threading.Lock()


def _perf_metrics():
    global _PERF_METRICS
    if _PERF_METRICS is None:
        # import BEFORE taking the lock: the first import pays the global
        # import lock + disk I/O, and holding our lock across it would
        # stall every concurrent profiler step on it (raylint R4)
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        with _METRICS_LOCK:
            if _PERF_METRICS is None:
                _PERF_METRICS = {
                    "phase": Histogram(
                        "ray_tpu_train_phase_seconds",
                        "per-step wall seconds billed to each phase "
                        "(ingest/h2d/compile/compute/collective/other)",
                        boundaries=[1e-4, 5e-4, 1e-3, 5e-3, 0.01, 0.05,
                                    0.1, 0.5, 1, 5, 30],
                        tag_keys=("phase", "rank")),
                    "step_wall": Histogram(
                        "ray_tpu_train_step_wall_seconds",
                        "profiled train-step wall time (s)",
                        boundaries=[1e-3, 5e-3, 0.01, 0.05, 0.1, 0.5,
                                    1, 5, 30, 120],
                        tag_keys=("rank",)),
                    "mfu": Gauge(
                        "ray_tpu_train_step_mfu",
                        "live per-step model-FLOPs utilization "
                        "(util/flops.py roofline)",
                        tag_keys=("rank",)),
                    "jit_hits": Counter(
                        "ray_tpu_jit_cache_hits_total",
                        "wrapped-jit calls served from the compile cache",
                        tag_keys=("fn",)),
                    "jit_misses": Counter(
                        "ray_tpu_jit_cache_misses_total",
                        "wrapped-jit calls that compiled (new shape "
                        "signature)",
                        tag_keys=("fn",)),
                    "jit_compile": Histogram(
                        "ray_tpu_jit_compile_seconds",
                        "wall time of compiling jit calls",
                        boundaries=[0.01, 0.05, 0.1, 0.5, 1, 5, 30, 120],
                        tag_keys=("fn",)),
                    "hbm_used": Gauge(
                        "ray_tpu_hbm_bytes_in_use",
                        "device memory in use (host RSS on CPU fallback)",
                        tag_keys=("device", "kind")),
                    "hbm_limit": Gauge(
                        "ray_tpu_hbm_bytes_limit",
                        "device memory capacity (absent on CPU fallback)",
                        tag_keys=("device", "kind")),
                    "hbm_peak": Gauge(
                        "ray_tpu_hbm_peak_bytes_in_use",
                        "high-water device memory since process start",
                        tag_keys=("device", "kind")),
                }
    return _PERF_METRICS


def sample_device_memory(device: Any = None) -> Optional[dict]:
    """One device-memory sample: ``device.memory_stats()`` where the
    backend exposes it (TPU/GPU), host RSS as the graceful CPU fallback
    (keyed ``kind=host_rss`` so dashboards never mistake it for HBM).
    Returns None only when both paths fail; never raises."""
    dev_label = "0"
    try:
        if device is None:
            import jax

            device = jax.devices()[0]
        dev_label = str(getattr(device, "id", 0))
        ms = device.memory_stats() if hasattr(device, "memory_stats") \
            else None
        if ms:
            return {
                "device": dev_label, "kind": "hbm",
                "bytes_in_use": int(ms.get("bytes_in_use", 0)),
                "bytes_limit": int(ms.get("bytes_limit", 0)) or None,
                "peak_bytes_in_use":
                    int(ms.get("peak_bytes_in_use", 0)) or None,
            }
    except Exception:
        pass
    try:
        import os

        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        return {
            "device": dev_label, "kind": "host_rss",
            "bytes_in_use": rss_pages * os.sysconf("SC_PAGE_SIZE"),
            "bytes_limit": None, "peak_bytes_in_use": None,
        }
    except Exception:
        return None


def publish_device_memory(device: Any = None) -> Optional[dict]:
    """Sample + set the HBM gauges (what the step profiler and the serve
    engine call; also usable standalone from any device-holding actor)."""
    sample = sample_device_memory(device)
    if sample is None:
        return None
    m = _perf_metrics()
    tags = {"device": sample["device"], "kind": sample["kind"]}
    m["hbm_used"].set(float(sample["bytes_in_use"]), tags=tags)
    if sample.get("bytes_limit"):
        m["hbm_limit"].set(float(sample["bytes_limit"]), tags=tags)
    if sample.get("peak_bytes_in_use"):
        m["hbm_peak"].set(float(sample["peak_bytes_in_use"]), tags=tags)
    return sample


def _signature(args, kwargs) -> str:
    """Short stable shape-signature for a call's abstract values: an
    md5 digest over every leaf's (shape, dtype) plus a human hint (the
    few distinct array shapes involved) — a train step carries a
    many-hundred-leaf param pytree, so the full shape list would be
    kilobytes per event."""
    try:
        import jax

        leaves = jax.tree.leaves((args, kwargs))
    except Exception:
        leaves = list(args) + sorted(
            kwargs.items(), key=lambda kv: kv[0])
    parts: List[str] = []
    hint: List[str] = []
    for leaf in leaves:
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            s = f"{tuple(shape)}:{getattr(leaf, 'dtype', '?')}"
        else:
            s = type(leaf).__name__
        parts.append(s)
        if shape is not None and s not in hint and len(hint) < 3:
            hint.append(s)
    # blake2b: in-interpreter implementation, so FIPS-enforcing OpenSSL
    # builds (where md5() raises) can't crash the compile path
    digest = hashlib.blake2b("|".join(parts).encode(),
                             digest_size=6).hexdigest()
    return f"{digest}[{','.join(hint)}]" if hint else digest


class CompileTracker:
    """Per-function jit compile-cache accounting (hit/miss counters per
    shape signature, compile wall time) — usable standalone; the step
    profiler embeds one.  Detection rides the jitted function's own
    ``_cache_size()``: a call that grows the cache compiled."""

    def __init__(self):
        self._lock = threading.Lock()
        # name -> {"sigs": [sig...], "hits": n, "misses": n, "compile_s": s}
        self.fns: Dict[str, dict] = {}

    def _entry(self, name: str) -> dict:
        with self._lock:
            e = self.fns.get(name)
            if e is None:
                e = self.fns[name] = {"sigs": [], "hits": 0, "misses": 0,
                                      "compile_s": 0.0}
            return e

    def record(self, name: str, miss: bool, wall_s: float,
               sig: Optional[str] = None) -> dict:
        """Fold one call in; returns the function's entry (callers read
        ``n_sigs`` off it for the event payload)."""
        e = self._entry(name)
        with self._lock:
            if miss:
                e["misses"] += 1
                e["compile_s"] += wall_s
                if sig is not None and sig not in e["sigs"]:
                    e["sigs"].append(sig)
            else:
                e["hits"] += 1
        if _events.ENABLED:
            m = _perf_metrics()
            if miss:
                m["jit_misses"].inc(tags={"fn": name})
                m["jit_compile"].observe(wall_s, tags={"fn": name})
                _events.emit(
                    "perf", "jit compile", severity="DEBUG",
                    entity_id=name, span_dur=wall_s, fn=name,
                    signature=sig, n_sigs=len(e["sigs"]),
                    misses=e["misses"], hits=e["hits"])
            else:
                m["jit_hits"].inc(tags={"fn": name})
        return e

    def wrap(self, fn, name: Optional[str] = None,
             profiler: Optional["StepProfiler"] = None):
        """Wrap a jitted callable: every call is classified hit/miss via
        ``_cache_size()`` growth, misses billed to the ``compile`` phase
        of the hosting profiler step (hits to ``compute``) and recorded
        per shape signature.  Non-jit callables (no ``_cache_size``)
        pass through with every call billed to ``compute``."""
        name = name or getattr(fn, "__name__", None) or "jit_fn"
        cache_size = getattr(fn, "_cache_size", None)
        tracker = self

        def wrapped(*args, **kwargs):
            before = cache_size() if cache_size is not None else None
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
            miss = before is not None and cache_size() > before
            sig = _signature(args, kwargs) if miss else None
            tracker.record(name, miss, dt, sig)
            if profiler is not None:
                profiler._bill("compile" if miss else "compute", dt)
            return out

        wrapped.__name__ = name
        return wrapped

    def table(self) -> List[dict]:
        with self._lock:  # snapshot only; sort after release
            items = [(name, dict(e, sigs=list(e["sigs"])))
                     for name, e in self.fns.items()]
        return [{
            "fn": name, "hits": e["hits"], "misses": e["misses"],
            "compile_s": round(e["compile_s"], 6),
            "n_sigs": len(e["sigs"]), "signatures": e["sigs"],
        } for name, e in sorted(items)]


# process-global active profiler: helpers that sit below the train loop
# (jax_utils.allreduce_grads billing the collective phase) reach it here
_ACTIVE: Optional["StepProfiler"] = None
_ACTIVE_LOCK = threading.Lock()


def active_profiler() -> Optional["StepProfiler"]:
    return _ACTIVE


def local_summary() -> Optional[dict]:
    """The installed profiler's summary, or None (what
    ``BackendExecutor.perf_summaries`` runs on each rank)."""
    p = _ACTIVE
    return p.summary() if p is not None else None


class StepProfiler:
    """Attribute every step of a device loop to phases + live MFU.

    ::

        prof = StepProfiler(flops_per_token=fpt, tokens_per_step=B * T,
                            rank=rank).install()
        step_fn = prof.wrap_jit(train_step, name="train_step")
        for batch in batches:
            with prof.step():
                with prof.phase("ingest"):
                    host = next(it)
                with prof.phase("h2d"):
                    dev = jax.device_put(host)
                state, metrics = step_fn(state, dev)   # compile | compute
                with prof.phase("compute"):
                    loss = float(metrics["loss"])      # device sync

    Phase scopes are sequential within a step (the loop IS sequential);
    the residual between their sum and the step wall is billed to
    ``other`` so ``sum(phases) == wall`` holds exactly per step and in
    aggregate.  A step that raises is not recorded (a partial phase set
    would skew every fraction)."""

    def __init__(self, *, flops_per_token: Optional[float] = None,
                 tokens_per_step: Optional[int] = None,
                 device: Any = None, device_kind: Optional[str] = None,
                 peak: Optional[float] = None, rank: int = 0,
                 hbm_every: int = 1, keep_steps: int = 512):
        self.flops_per_token = flops_per_token
        self.tokens_per_step = tokens_per_step
        self.rank = int(rank)
        self._device = device
        self._peak = peak
        self._device_kind = device_kind
        self.hbm_every = max(0, int(hbm_every))
        self.compiles = CompileTracker()
        self._lock = threading.Lock()
        self.steps: deque = deque(maxlen=max(1, int(keep_steps)))
        self._phase_totals: Dict[str, float] = {}
        self._wall_total = 0.0
        self._tokens_total = 0
        self._n_steps = 0
        self._last_mfu: Optional[float] = None
        self._last_hbm: Optional[dict] = None
        # per-open-step state (one step open at a time, loop-thread owned)
        self._open = False
        self._t0 = 0.0
        self._cur_phases: Dict[str, float] = {}
        self._cur_tokens: Optional[int] = None
        self._trace_dir: Optional[str] = None

    # -- wiring --------------------------------------------------------
    def install(self) -> "StepProfiler":
        """Publish as the process's active profiler (``active_profiler``
        / ``local_summary`` / collective-phase billing find it here)."""
        global _ACTIVE
        with _ACTIVE_LOCK:
            _ACTIVE = self
        return self

    def uninstall(self) -> None:
        global _ACTIVE
        with _ACTIVE_LOCK:
            if _ACTIVE is self:
                _ACTIVE = None

    def wrap_jit(self, fn, name: Optional[str] = None):
        return self.compiles.wrap(fn, name=name, profiler=self)

    def arm_trace(self, logdir: str) -> None:
        """Capture ONE XLA device trace around the next step (what a
        doctor perf rule triggers on-demand; see
        ``profiling.profile_step``)."""
        self._trace_dir = logdir

    # -- step/phase scopes ---------------------------------------------
    @contextlib.contextmanager
    def step(self, tokens: Optional[int] = None):
        trace_cm = None
        if self._trace_dir is not None:
            from ray_tpu.util import profiling

            trace_cm = profiling.profile_trace(self._trace_dir)
            self._trace_dir = None
            trace_cm.__enter__()
        self._open = True
        self._cur_phases = {}
        self._cur_tokens = tokens
        self._t0 = time.perf_counter()
        try:
            yield self
            self._finish_step(time.perf_counter() - self._t0)
        finally:
            self._open = False
            if trace_cm is not None:
                trace_cm.__exit__(None, None, None)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._bill(name, time.perf_counter() - t0)

    def _bill(self, name: str, dur_s: float) -> None:
        if not self._open:
            return  # helper ran outside a step: nothing to attribute to
        self._cur_phases[name] = self._cur_phases.get(name, 0.0) + dur_s

    # -- recording -----------------------------------------------------
    def _finish_step(self, raw_wall: float) -> None:
        phases = dict(self._cur_phases)
        covered = sum(phases.values())
        # exact-sum invariant: the residual is billed to "other"; if
        # float error puts covered a hair past the raw wall, the wall is
        # the covered sum (phases can never exceed the step they're in)
        wall = max(raw_wall, covered)
        phases["other"] = wall - covered
        tokens = self._cur_tokens if self._cur_tokens is not None \
            else self.tokens_per_step
        mfu = None
        if tokens and self.flops_per_token and wall > 0:
            mfu = flops_mod.mfu(
                tokens / wall, self.flops_per_token,
                self._resolve_device_kind(), peak=self._peak)
        with self._lock:
            self._n_steps += 1
            n = self._n_steps
            self._wall_total += wall
            self._tokens_total += int(tokens or 0)
            for k, v in phases.items():
                self._phase_totals[k] = self._phase_totals.get(k, 0.0) + v
            self._last_mfu = mfu if mfu is not None else self._last_mfu
            self.steps.append({"step": n, "wall_s": wall,
                               "phases": phases, "mfu": mfu,
                               "tokens": tokens})
        if not _events.ENABLED:
            return
        m = _perf_metrics()
        rank_tag = {"rank": str(self.rank)}
        m["step_wall"].observe(wall, tags=rank_tag)
        for k, v in phases.items():
            m["phase"].observe(v, tags={"phase": k, "rank": str(self.rank)})
        if mfu is not None:
            m["mfu"].set(mfu, tags=rank_tag)
        if self.hbm_every and n % self.hbm_every == 0:
            self._last_hbm = publish_device_memory(self._device) \
                or self._last_hbm
        _events.emit(
            "perf", "step phases", severity="DEBUG",
            entity_id=f"rank{self.rank}", span_dur=wall, step=n,
            phases={k: round(v, 6) for k, v in phases.items()},
            wall_s=round(wall, 6),
            **({"mfu": round(mfu, 5)} if mfu is not None else {}),
            **({"tokens": int(tokens)} if tokens else {}))

    def _resolve_device_kind(self) -> str:
        if self._device_kind is None:
            try:
                import jax

                dev = self._device or jax.devices()[0]
                self._device_kind = getattr(dev, "device_kind", "")
            except Exception:
                self._device_kind = ""
        return self._device_kind

    # -- aggregate -----------------------------------------------------
    def summary(self) -> dict:
        """The in-process aggregate: phase totals (summing exactly to
        the summed step walls), time-weighted mean + last MFU, the
        compile table, the last HBM sample."""
        with self._lock:  # snapshot only; sort/derive after release
            wall = self._wall_total
            phase_totals = dict(self._phase_totals)
            tokens_total = self._tokens_total
            n_steps = self._n_steps
            last_mfu = self._last_mfu
            last_hbm = self._last_hbm
        phases = {
            k: {"s": round(v, 9),
                "frac": round(v / wall, 4) if wall > 0 else 0.0}
            for k, v in sorted(phase_totals.items(),
                               key=lambda kv: -kv[1])}
        mean_mfu = None
        if tokens_total and self.flops_per_token and wall > 0:
            mean_mfu = flops_mod.mfu(
                tokens_total / wall, self.flops_per_token,
                self._resolve_device_kind(), peak=self._peak)
        return {
            "rank": self.rank,
            "steps": n_steps,
            "wall_s": round(wall, 9),
            "tokens": tokens_total,
            "phases": phases,
            "mfu": {
                "last": round(last_mfu, 5)
                if last_mfu is not None else None,
                "mean": round(mean_mfu, 5)
                if mean_mfu is not None else None,
            },
            "hbm": last_hbm,
            "compiles": self.compiles.table(),
        }
