"""Runtime context (analog of ``python/ray/runtime_context.py``)."""

from __future__ import annotations

import os
from typing import List, Optional

from ray_tpu._private.worker import global_worker


class RuntimeContext:
    @property
    def node_id(self) -> str:
        return global_worker.node_id

    @property
    def worker_id(self) -> Optional[bytes]:
        return global_worker.worker_id or None

    @property
    def task_id(self) -> Optional[bytes]:
        return global_worker.current_task_id

    @property
    def actor_id(self) -> Optional[bytes]:
        return global_worker.current_actor_id

    @property
    def namespace(self) -> str:
        """The tenant namespace this code runs under: the driver's own
        (assigned at ``init(namespace=...)``; proxied tenants default to
        an isolated per-job namespace), or — inside a task/actor method —
        the namespace of the job that submitted it."""
        return (global_worker.current_namespace
                or global_worker.namespace or "default")

    @property
    def job_id(self) -> Optional[str]:
        """The submitting job's id (``job-NNNN``), the unit the head
        attributes ownership/metrics to and reaps on driver death."""
        return global_worker.current_job_id or global_worker.job_id

    def get_tpu_ids(self) -> List[int]:
        """Chips assigned to the current task/actor (CUDA_VISIBLE_DEVICES analog:
        the raylet exports TPU_VISIBLE_CHIPS, see node.py actor spawn)."""
        raw = os.environ.get("RAY_TPU_ASSIGNED_TPUS", "")
        return [int(x) for x in raw.split(",") if x.strip()]


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext()
