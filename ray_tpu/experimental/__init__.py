"""Experimental APIs (reference ``python/ray/experimental``)."""

from __future__ import annotations

from typing import Dict


def broadcast_object(ref, timeout: float = 120.0) -> Dict:
    """Proactively replicate ``ref``'s payload onto every alive cluster
    node (the PushManager 1->N distribution,
    ``src/ray/object_manager/push_manager.h:29``, as a user-facing
    primitive).  Doubling fan-out: completed copies serve later waves.

    Returns ``{"replicas": n, "error": ...}``.  Subsequent consumers pull
    from the nearest/least-loaded copy via the head's location set, and the
    object survives the origin node's death without lineage reconstruction.
    """
    from ray_tpu._private.object_ref import ObjectRef
    from ray_tpu._private.worker import global_worker

    if not isinstance(ref, ObjectRef):
        raise TypeError(f"broadcast_object expects an ObjectRef, got {type(ref)}")
    return global_worker.client.broadcast(ref.binary(), timeout=timeout)
