"""Cluster state API — ``ray.experimental.state.api`` analog.

``list_actors``/``list_tasks``/``list_objects``/``summarize_*``
(reference ``python/ray/experimental/state/api.py:729,952,996,1269-1333``,
aggregated by ``dashboard/state_aggregator.py``): live introspection of
the control plane, served by the head's ``list_state`` RPC and also over
HTTP by the dashboard (``/api/...``).
"""

from __future__ import annotations

from collections import Counter
from typing import Dict, List, Optional


def _client():
    from ray_tpu._private.worker import global_worker

    if not global_worker.connected:
        raise RuntimeError("ray_tpu.init() must run before the state API")
    return global_worker.client


def _list(what: str, limit: int, filters: Optional[dict] = None) -> List[dict]:
    msg = {"type": "list_state", "what": what, "limit": limit}
    if filters:
        msg["filters"] = filters
    reply = _client().request(msg)
    return reply["value"]


def list_actors(limit: int = 1000) -> List[dict]:
    return _list("actors", limit)


def list_nodes(limit: int = 1000) -> List[dict]:
    return _list("nodes", limit)


def list_tasks(limit: int = 1000) -> List[dict]:
    return _list("tasks", limit)


def list_objects(limit: int = 1000) -> List[dict]:
    return _list("objects", limit)


def list_placement_groups(limit: int = 1000) -> List[dict]:
    return _list("placement_groups", limit)


def list_workers(limit: int = 1000) -> List[dict]:
    return _list("workers", limit)


def list_jobs(limit: int = 1000) -> List[dict]:
    return _list("jobs", limit)


def list_events(limit: int = 1000, source: Optional[str] = None,
                severity: Optional[str] = None) -> List[dict]:
    """Flight-recorder events from the head's cluster-wide event table
    (scheduler dispatches, spills, OOM kills, backpressure stalls, slot
    admissions...), oldest-first.  ``source``/``severity`` filter
    HEAD-SIDE, before the limit — a rare WARNING stays findable behind
    thousands of newer sampled DEBUG rows."""
    filters = {}
    if source is not None:
        filters["source"] = source
    if severity is not None:
        filters["severity"] = severity
    return _list("events", limit, filters or None)


def summarize_events() -> Dict[str, Dict[str, int]]:
    """Event counts grouped by source and severity."""
    by_source: Dict[str, Counter] = {}
    for e in list_events(limit=100_000):
        by_source.setdefault(e["source"], Counter())[e["severity"]] += 1
    return {src: dict(sev) for src, sev in by_source.items()}


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    """Task counts grouped by name and state (summarize_tasks analog)."""
    by_name: Dict[str, Counter] = {}
    for t in list_tasks(limit=100_000):
        by_name.setdefault(t["name"], Counter())[t["state"]] += 1
    return {name: dict(states) for name, states in by_name.items()}


def summarize_actors() -> Dict[str, Dict[str, int]]:
    by_cls: Dict[str, Counter] = {}
    for a in list_actors(limit=100_000):
        by_cls.setdefault(a["class_name"], Counter())[a["state"]] += 1
    return {cls: dict(states) for cls, states in by_cls.items()}
