"""Cluster state API — ``ray.experimental.state.api`` analog.

``list_actors``/``list_tasks``/``list_objects``/``summarize_*``
(reference ``python/ray/experimental/state/api.py:729,952,996,1269-1333``,
aggregated by ``dashboard/state_aggregator.py``): live introspection of
the control plane, served by the head's ``list_state`` RPC and also over
HTTP by the dashboard (``/api/...``).

``summarize_*`` aggregate HEAD-SIDE via the ``summarize_state`` RPC
(``state_aggregator.py`` summary path): the head counts over its full
tables and ships the counts, instead of this client pulling up to 100k
rows to count locally.  ``list_traces``/``get_trace``/``summarize_traces``
expose the request-trace plane (``util/tracing.py`` spans assembled by the
head's TraceTable).
"""

from __future__ import annotations

import warnings
from typing import Dict, List, Optional


def _client():
    from ray_tpu._private.worker import global_worker

    if not global_worker.connected:
        raise RuntimeError("ray_tpu.init() must run before the state API")
    return global_worker.client


def list_state_page(what: str, limit: int = 1000,
                    filters: Optional[dict] = None) -> dict:
    """One page of a state table WITH its truncation marker:
    ``{"rows", "total", "truncated"}``.  The plain ``list_*`` helpers
    return bare rows for compatibility — use this when completeness
    matters (the CLI prints the marker from it)."""
    msg = {"type": "list_state", "what": what, "limit": limit}
    if filters:
        msg["filters"] = filters
    reply = _client().request(msg)
    rows = reply["value"]
    total = reply.get("total", len(rows))
    return {"rows": rows, "total": total, "truncated": total > len(rows)}


def _list(what: str, limit: int, filters: Optional[dict] = None) -> List[dict]:
    page = list_state_page(what, limit, filters)
    if page["truncated"]:
        # a silent cap reads as "this is everything" on a large cluster —
        # make the partial view loud without changing the return shape
        warnings.warn(
            f"list_{what} truncated: showing {len(page['rows'])} of "
            f"{page['total']} rows (raise limit= to see the rest)",
            stacklevel=3)
    return page["rows"]


def list_actors(limit: int = 1000) -> List[dict]:
    return _list("actors", limit)


def list_nodes(limit: int = 1000) -> List[dict]:
    return _list("nodes", limit)


def list_slices(limit: int = 1000) -> List[dict]:
    """One row per TPU slice (failure domain): members, alive/dead
    counts, draining flag, and whether it is currently degraded (dead
    member, not draining — what doctor's ``slice_degraded`` watches)."""
    return _list("slices", limit)


def list_tasks(limit: int = 1000) -> List[dict]:
    return _list("tasks", limit)


def list_objects(limit: int = 1000) -> List[dict]:
    return _list("objects", limit)


def list_placement_groups(limit: int = 1000) -> List[dict]:
    return _list("placement_groups", limit)


def list_workers(limit: int = 1000) -> List[dict]:
    return _list("workers", limit)


def list_jobs(limit: int = 1000) -> List[dict]:
    return _list("jobs", limit)


def list_tenants(limit: int = 1000) -> List[dict]:
    """Driver jobs (tenants) with namespace, driver pid, proxied flag,
    liveness, and live actor counts — the multi-tenancy directory (what
    ``ray_tpu list tenants`` renders and the tenant-kill chaos op
    resolves pids from)."""
    return _list("tenants", limit)


def list_events(limit: int = 1000, source: Optional[str] = None,
                severity: Optional[str] = None) -> List[dict]:
    """Flight-recorder events from the head's cluster-wide event table
    (scheduler dispatches, spills, OOM kills, backpressure stalls, slot
    admissions...), oldest-first.  ``source``/``severity`` filter
    HEAD-SIDE, before the limit — a rare WARNING stays findable behind
    thousands of newer sampled DEBUG rows."""
    filters = {}
    if source is not None:
        filters["source"] = source
    if severity is not None:
        filters["severity"] = severity
    return _list("events", limit, filters or None)


def summarize_state(what: str) -> dict:
    """Head-side aggregation RPC: the head counts over its full tables
    and ships only the counts (the client never pulls row dumps)."""
    value = _client().request(
        {"type": "summarize_state", "what": what})["value"]
    if isinstance(value, dict) and "__state_error__" in value:
        raise ValueError(value["__state_error__"])
    return value


def summarize_events() -> Dict[str, Dict[str, int]]:
    """Event counts grouped by source and severity (head-side)."""
    return summarize_state("events")


def summarize_tasks() -> Dict[str, Dict[str, int]]:
    """Task counts grouped by name and state (summarize_tasks analog,
    aggregated head-side)."""
    return summarize_state("tasks")


def summarize_actors() -> Dict[str, Dict[str, int]]:
    return summarize_state("actors")


def summarize_traces() -> dict:
    """Trace counts + duration percentiles from the head's TraceTable."""
    return summarize_state("traces")


def list_traces(limit: int = 100) -> List[dict]:
    """Summaries of recently updated traces (id, root span name, span
    count, start, duration)."""
    return _list("traces", limit)


def get_trace(trace_id: str) -> Optional[dict]:
    """One assembled trace: recorder spans (router admission, channel
    waits, compiled-graph node executions, get waits...) merged with
    task-table spans (queue + execution attribution), sorted by start,
    plus ``logs`` — the trace's stamped log records joined onto the
    span tree.  None if the id is unknown."""
    return _client().request(
        {"type": "get_trace", "trace_id": trace_id})["value"]


# ---------------------------------------------------------------------------
# log plane (head LogStore — `ray_tpu logs` backend)
# ---------------------------------------------------------------------------

def list_logs(limit: int = 1000) -> List[dict]:
    """One row per captured log stream in the head's LogStore (worker /
    job-driver / tenant-driver / head files the per-node monitors tail):
    stream name, node, pid, retained lines/bytes, and whether the
    stream's process already died (``retired`` — its death tail stays
    queryable until the retirement horizon)."""
    return _list("logs", limit)


def get_log(stream: Optional[str] = None, job: Optional[str] = None,
            task: Optional[str] = None, actor: Optional[str] = None,
            node: Optional[str] = None, pid: Optional[int] = None,
            trace: Optional[str] = None, grep: Optional[str] = None,
            errors: bool = False, since_seq: int = 0,
            limit: int = 1000) -> dict:
    """Filtered log records from the head's store — the ``ray_tpu logs``
    backend.  Every filter matches the per-line context stamps (so
    ``task=``/``actor=``/``trace=`` find a plain ``print()`` from inside
    that execution).  Returns ``{"records", "cursor"}``; pass ``cursor``
    back as ``since_seq`` to follow the stream incrementally.
    ``stream="job-<id>"`` falls back to the job driver's complete
    on-disk log when the ring has nothing."""
    return _client().request(
        {"type": "get_log", "stream": stream, "job": job, "task": task,
         "actor": actor, "node": node, "pid": pid, "trace": trace,
         "grep": grep, "errors": errors, "since_seq": since_seq,
         "limit": limit})["value"]


def tail_log(stream: str, n: int = 100, errors: bool = False) -> List[str]:
    """The last ``n`` raw lines of one stream (``errors=True`` keeps only
    stderr/traceback lines) — works for retired streams too, which is how
    a SIGKILL'd worker's final stderr is read back after death."""
    return _client().request(
        {"type": "tail_log", "stream": stream, "n": n,
         "errors": errors})["value"]


# ---------------------------------------------------------------------------
# resource accounting over time (head TSDB + ownership audit)
# ---------------------------------------------------------------------------

def list_metrics() -> List[dict]:
    """Every metric with retained history in the head's TSDB: name, type,
    number of label series, origins, freshest sample time."""
    return _client().request({"type": "list_metrics"})["value"]


def query_metric(name: str, window_s: float = 3600.0, step_s: float = 0.0,
                 tags: Optional[Dict[str, str]] = None,
                 agg: Optional[str] = None) -> dict:
    """Aligned time series for one metric over the trailing window,
    served from the head's staged-downsampling TSDB — the data behind
    sparklines, trend doctor rules, and capacity questions a snapshot
    can't answer.  ``step_s <= 0`` uses the sample interval; ``agg`` is
    one of last/max/min/sum/avg/count (default: the metric's natural
    aggregation)."""
    msg = {"type": "query_metric", "name": name, "window_s": window_s,
           "step_s": step_s}
    if tags:
        msg["tags"] = tags
    if agg:
        msg["agg"] = agg
    value = _client().request(msg)["value"]
    if isinstance(value, dict) and "__state_error__" in value:
        raise ValueError(value["__state_error__"])
    return value


# ---------------------------------------------------------------------------
# continuous profiling (head ProfileStore)
# ---------------------------------------------------------------------------

def list_profiles() -> List[dict]:
    """One row per origin with retained continuous-profile history in
    the head's ProfileStore: bucket counts (fine + decayed coarse),
    bytes, total samples, GIL-pressure estimate, push age, and the
    origin's current sampling cadence."""
    return _client().request({"type": "list_profiles"})["value"]


def get_profile(window_s: float = 300.0,
                origin: Optional[str] = None) -> dict:
    """Merged folded stacks over the trailing window (cluster-wide, or
    one origin's) from the always-on profiler — ``folded`` maps
    ``|``-joined root→leaf stacks to sample counts, plus the duty-cycle
    denominators (``ticks``/``busy_ticks``) the cost ledger divides by."""
    return _client().request(
        {"type": "get_profile", "window_s": window_s,
         "origin": origin})["value"]


def profile_diff(window_a: float = 600.0, window_b: float = 60.0,
                 origin: Optional[str] = None) -> dict:
    """Differential profile: the trailing ``window_b`` seconds against
    the ``window_a``-long baseline before it, counts scaled to the same
    span.  ``collapsed`` holds flamegraph.pl ``difffolded`` lines
    (``stack countA countB``); ``delta`` the per-stack change."""
    return _client().request(
        {"type": "profile_diff", "window_a": window_a,
         "window_b": window_b, "origin": origin})["value"]


def profile_ledger(window_s: float = 300.0,
                   tasks: Optional[int] = None) -> dict:
    """The per-task CPU cost ledger: sampled stacks joined with the task
    lane into driver-submit / head-dispatch / worker-exec / serialize /
    lock-wait / GIL-wait microsecond columns that sum to the measured
    per-task wall (``tasks`` overrides the TSDB-derived task count when
    the caller counted exactly)."""
    msg = {"type": "profile_ledger", "window_s": window_s}
    if tasks is not None:
        msg["tasks"] = tasks
    return _client().request(msg)["value"]


def memory_summary(limit: int = 200) -> dict:
    """Object-ownership audit (``ray memory`` analog): sealed object-store
    bytes attributed per owner (driver/worker/actor), pin-reason
    breakdown, per-object rows sorted by size, and orphan flags for
    objects whose owner process is gone."""
    return _client().request(
        {"type": "memory_audit", "limit": limit})["value"]


def top_snapshot() -> dict:
    """One frame of ``ray_tpu top``: nodes with host stats, workers with
    sampled RSS/CPU/fds and pinned bytes, task-state and store summaries,
    and device-memory (HBM) watermark rows."""
    return _client().request({"type": "top_snapshot"})["value"]


# ---------------------------------------------------------------------------
# watchdog plane (incidents, SLOs, head-side doctor, debug dumps)
# ---------------------------------------------------------------------------

def list_incidents(limit: int = 1000) -> List[dict]:
    """The watchdog's tracked incident set — open, acked, and resolved
    rows with stable ids keyed on (rule, entity), severity, re-open
    counts, the transition history, and the post-mortem bundle path
    captured at open.  Empty when the watchdog is disabled."""
    return _list("incidents", limit)


def list_slos(limit: int = 1000) -> List[dict]:
    """Declared SLOs (defaults + ``slos.json`` + ``add_slo``) with the
    latest multi-window burn-rate evaluation folded in: per-window
    value/coverage/breach and the overall ``burning`` verdict."""
    return _list("slos", limit)


def get_incident(incident_id: str) -> dict:
    """One incident's full record, including its evidence rows and
    transition history; raises ValueError on an unknown id."""
    value = _client().request(
        {"type": "get_incident", "incident_id": incident_id})["value"]
    if isinstance(value, dict) and "__state_error__" in value:
        raise ValueError(value["__state_error__"])
    return value


def ack_incident(incident_id: str) -> dict:
    """Acknowledge an open incident (open → ack): it stops alerting on
    refresh but still auto-resolves once clear.  Returns the updated
    record; raises ValueError if the id is unknown or not open."""
    value = _client().request(
        {"type": "ack_incident", "incident_id": incident_id})["value"]
    if isinstance(value, dict) and "__state_error__" in value:
        raise ValueError(value["__state_error__"])
    return value


def doctor_report(trend_window_s: float = 1800.0) -> List[dict]:
    """Doctor findings computed HEAD-SIDE over the head's own event /
    task / TSDB tables — the ``ray_tpu doctor`` backend.  The client
    receives only the findings, never the 100k-row tables they were
    diagnosed from."""
    value = _client().request(
        {"type": "doctor_report",
         "trend_window_s": trend_window_s})["value"]
    if isinstance(value, dict) and "__state_error__" in value:
        raise ValueError(value["__state_error__"])
    return value


def debug_dump(label: Optional[str] = None) -> str:
    """One-shot whole-cluster post-mortem bundle written head-side under
    ``<session>/incidents/`` (log tails, event excerpt, TSDB slices,
    collapsed profile, memory audit); returns the bundle directory."""
    value = _client().request(
        {"type": "debug_dump", "label": label})["value"]
    if isinstance(value, dict) and "__state_error__" in value:
        raise ValueError(value["__state_error__"])
    return value["path"]


def perf_summary(window_s: float = 1800.0) -> dict:
    """Performance-observability aggregate (``ray_tpu perf`` backend):
    the step-phase breakdown (phases sum exactly to profiled step wall),
    per-rank live MFU + the TSDB MFU trend over the trailing window, the
    jit compile-cache table per shape signature, HBM watermarks, and the
    decode attribution block (TTFT/ITL histograms, per-engine
    prefill-interference meters)."""
    return _client().request(
        {"type": "perf_summary", "window_s": window_s})["value"]
