from ray_tpu.experimental.state.api import (
    get_profile,
    get_trace,
    list_actors,
    list_events,
    list_jobs,
    list_nodes,
    list_objects,
    list_placement_groups,
    list_profiles,
    list_tasks,
    list_traces,
    list_workers,
    profile_diff,
    profile_ledger,
    summarize_actors,
    summarize_events,
    summarize_state,
    summarize_tasks,
    summarize_traces,
)

__all__ = [
    "list_actors", "list_nodes", "list_tasks", "list_objects",
    "list_placement_groups", "list_workers", "list_jobs", "list_events",
    "list_traces", "get_trace", "summarize_tasks", "summarize_actors",
    "summarize_events", "summarize_traces", "summarize_state",
    "list_profiles", "get_profile", "profile_diff", "profile_ledger",
]
