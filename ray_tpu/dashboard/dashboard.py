"""Head-process dashboard: JSON state API + Prometheus metrics over HTTP.

The reference runs an aiohttp ``DashboardHead`` (``dashboard/head.py:69``)
with per-module routes (actor/node/job/metrics/state —
``dashboard/modules/*``) and a Prometheus exporter on the metrics agent
(``python/ray/_private/metrics_agent.py``).  This serves the same
surface from a stdlib ThreadingHTTPServer inside the head process:

- ``/``                    tiny HTML cluster summary
- ``/api/cluster_status``  resources, node/actor/task/object counts
- ``/api/nodes|actors|tasks|placement_groups|workers|objects``
- ``/api/jobs``            submitted jobs (job_submission)
- ``/metrics``             Prometheus text format (runtime + app metrics)
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ray_tpu.util import metrics as metrics_mod


def _jsonable(obj):
    if isinstance(obj, bytes):
        return obj.hex()
    if isinstance(obj, dict):
        return {str(_jsonable(k)): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "__dataclass_fields__"):
        return {k: _jsonable(getattr(obj, k)) for k in obj.__dataclass_fields__}
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return repr(obj)


# Single-page web UI over the JSON API (the reference ships a 22k-LoC
# TypeScript frontend, dashboard/client/src; this is the build-step-free
# equivalent: live tables for every state table, auto-refreshing).
_INDEX = """<!doctype html><html><head><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:0;background:#f6f7f9;color:#1a1a2e}
 header{background:#1a1a2e;color:#fff;padding:10px 18px;display:flex;
        align-items:baseline;gap:16px}
 header h1{font-size:17px;margin:0} header span{opacity:.7;font-size:12px}
 nav{display:flex;gap:4px;padding:8px 14px;flex-wrap:wrap}
 nav button{border:1px solid #ccd;border-radius:6px;background:#fff;
            padding:5px 12px;cursor:pointer;font-size:13px}
 nav button.on{background:#1a1a2e;color:#fff;border-color:#1a1a2e}
 #cards{display:flex;gap:10px;padding:4px 14px;flex-wrap:wrap}
 .card{background:#fff;border:1px solid #e3e5ea;border-radius:8px;
       padding:8px 14px;min-width:110px}
 .card b{display:block;font-size:20px} .card small{color:#667}
 main{padding:8px 14px} table{border-collapse:collapse;width:100%;
      background:#fff;border:1px solid #e3e5ea;border-radius:8px;font-size:12px}
 th,td{padding:5px 9px;text-align:left;border-bottom:1px solid #eef0f4;
       max-width:340px;overflow:hidden;text-overflow:ellipsis;white-space:nowrap}
 th{background:#eef0f4;position:sticky;top:0} tr:hover td{background:#f3f6ff}
 .ALIVE,.RUNNING,.FINISHED,.true{color:#0a7d38}.DEAD,.FAILED,.false{color:#c0222b}
 #foot{color:#889;font-size:11px;padding:10px 14px}
 #detail{position:fixed;top:0;right:0;width:46%;height:100%;background:#fff;
   border-left:2px solid #1a1a2e;box-shadow:-4px 0 14px rgba(0,0,0,.15);
   overflow:auto;padding:14px;display:none;z-index:5}
 #detail pre{font-size:11px;white-space:pre-wrap;word-break:break-all}
 #detail .x{float:right;cursor:pointer;border:none;background:#eee;
   border-radius:5px;padding:3px 9px}
 #logview{background:#10131c;color:#cfd6e4;font-family:monospace;
   font-size:11px;padding:10px;border-radius:8px;white-space:pre-wrap;
   max-height:70vh;overflow:auto}
 tr.click{cursor:pointer}
 #flamegraph{position:relative;background:#fff;border:1px solid #e3e5ea;
   border-radius:8px;overflow:hidden;margin-bottom:10px}
 #flamegraph .frame{position:absolute;height:16px;line-height:16px;
   font-size:10px;font-family:monospace;overflow:hidden;white-space:nowrap;
   border-right:1px solid rgba(255,255,255,.55);box-sizing:border-box;
   padding-left:2px;cursor:default}
</style></head><body>
<header><h1>ray_tpu</h1><span id="hdr"></span></header>
<div id="cards"></div>
<nav id="nav"></nav>
<main><table id="tbl"><thead></thead><tbody></tbody></table>
<div id="logpane" style="display:none"><div id="streams"></div>
<div id="logview"></div></div>
<div id="flamepane" style="display:none">
<div style="font-size:12px;color:#667;padding:4px 0">always-on profiler,
 trailing 10&nbsp;min, all origins merged &middot; hover a frame for counts
 &middot; <a href="/api/profile/continuous?window=600&amp;format=collapsed">
 folded stacks</a></div>
<div id="flamegraph"></div>
<table id="ftbl"><thead></thead><tbody></tbody></table></div></main>
<div id="detail"><button class="x" onclick="hideDetail()">close</button>
<h3 id="dtitle"></h3><pre id="dbody"></pre></div>
<div id="foot">auto-refresh 2s &middot; JSON API: /api/&lt;table&gt;[/&lt;id&gt;],
 /api/cluster_status, /api/serve/applications, /api/logs[/&lt;stream&gt;],
 <a href="/api/timeline">/api/timeline</a> (chrome://tracing),
 <a href="/api/events">/api/events</a> (flight recorder),
 <a href="/api/traces">/api/traces</a>[/&lt;id&gt;] (request traces),
 <a href="/api/metrics/list">/api/metrics/list</a>,
 /api/metrics/query?name=&amp;window=&amp;step=,
 <a href="/api/incidents">/api/incidents</a> (watchdog incidents),
 <a href="/api/slos">/api/slos</a> (declared SLOs + burn-rate),
 <a href="/api/memory">/api/memory</a> (ownership audit),
 <a href="/api/top">/api/top</a>,
 <a href="/api/perf">/api/perf</a> (step phases/MFU/compiles/HBM),
 /api/grafana_dashboard,
 /api/profile?duration=3[&amp;worker_id=][&amp;format=collapsed],
 /api/profile/continuous?window=300[&amp;origin=][&amp;diff_a=&amp;diff_b=],
 /metrics</div>
<script>
const TABS=["nodes","actors","tasks","workers","objects","placement_groups",
            "jobs","serve","events","traces","metrics","flame","logs",
            "incidents"];
const ID_FIELD={nodes:"node_id",actors:"actor_id",tasks:"task_id",
 workers:"worker_id",placement_groups:"pg_id",jobs:"job_id",
 traces:"trace_id"};
let tab="nodes",timer=null;
const nav=document.getElementById("nav");
TABS.forEach(t=>{const b=document.createElement("button");b.textContent=t;
 b.onclick=()=>{tab=t;hideDetail();render()};nav.appendChild(b);});
function cell(v){if(v===null)return"";if(typeof v==="object")
 return JSON.stringify(v);return String(v);}
function esc(v){return String(v).replace(/[&<>"']/g,c=>({"&":"&amp;","<":"&lt;",
 ">":"&gt;",'"':"&quot;","'":"&#39;"}[c]));}
function hideDetail(){document.getElementById("detail").style.display="none";}
async function showDetail(table,id){
 const r=await fetch(`/api/${table}/${encodeURIComponent(id)}`);
 if(!r.ok)return;
 const d=await r.json();
 document.getElementById("dtitle").textContent=`${table} ${id}`;
 let html=JSON.stringify(d,null,2);
 document.getElementById("dbody").textContent=html;
 const panel=document.getElementById("detail");
 panel.style.display="block";
 if(d.log_stream){
  const a=document.createElement("a");
  a.href=`/api/logs/${encodeURIComponent(d.log_stream)}`;
  a.textContent="view log: "+d.log_stream;a.target="_blank";
  document.getElementById("dtitle").appendChild(document.createElement("br"));
  document.getElementById("dtitle").appendChild(a);
 }
}
async function showLog(stream){
 const r=await fetch(`/api/logs/${encodeURIComponent(stream)}?tail=500`);
 document.getElementById("logview").textContent=
  r.ok?await r.text():"(stream unavailable)";
}
function spark(seriesList){
 // inline SVG sparkline: one polyline PER label series on shared scales
 // (concatenating per-worker series into one path renders a sawtooth
 // alternating between unrelated values, not a trend)
 const ns="http://www.w3.org/2000/svg";
 const svg=document.createElementNS(ns,"svg");
 svg.setAttribute("width","160");svg.setAttribute("height","28");
 const all=[];seriesList.forEach(s=>all.push(...s));
 if(all.length<2)return svg;
 const xs=all.map(p=>p[0]),ys=all.map(p=>p[1]);
 const x0=Math.min(...xs),x1=Math.max(...xs),
       y0=Math.min(...ys),y1=Math.max(...ys);
 const sx=x1>x0?156/(x1-x0):0,sy=y1>y0?24/(y1-y0):0;
 seriesList.slice(0,8).forEach((pts,si)=>{
  if(pts.length<2)return;
  const d=pts.map((p,i)=>(i?"L":"M")+(2+(p[0]-x0)*sx).toFixed(1)+","+
    (26-(p[1]-y0)*sy).toFixed(1)).join(" ");
  const path=document.createElementNS(ns,"path");
  path.setAttribute("d",d);path.setAttribute("fill","none");
  path.setAttribute("stroke",si?"#7a86b8":"#1a1a2e");
  path.setAttribute("stroke-width","1.2");
  svg.appendChild(path);
 });
 return svg;
}
async function renderMetrics(){
 // TSDB-backed trend view: one sparkline per retained metric
 document.getElementById("logpane").style.display="none";
 const tbl=document.getElementById("tbl");tbl.style.display="";
 const list=(await (await fetch("/api/metrics/list")).json()).slice(0,30);
 const qs=await Promise.all(list.map(m=>
  fetch(`/api/metrics/query?name=${encodeURIComponent(m.name)}`+
        "&window=1800&step=30").then(r=>r.json()).catch(()=>null)));
 const thead=document.querySelector("#tbl thead"),
       tbody=document.querySelector("#tbl tbody");
 thead.innerHTML="<tr><th>metric</th><th>type</th><th>series</th>"+
  "<th>last</th><th>trend (30m)</th></tr>";
 tbody.textContent="";
 list.forEach((m,i)=>{
  const q=qs[i];
  const seriesList=q?q.series.map(s=>s.points):[];
  // "last" is only meaningful for a single series; show the spread
  // across series otherwise
  let last="";
  const lasts=seriesList.filter(p=>p.length).map(p=>p[p.length-1][1]);
  if(lasts.length===1)last=String(lasts[0]);
  else if(lasts.length>1)
   last=`${Math.min(...lasts)}…${Math.max(...lasts)}`;
  const tr=document.createElement("tr");
  [m.name,m.type,String(m.num_series),last].forEach(t=>{
   const td=document.createElement("td");td.textContent=t;tr.appendChild(td);});
  const td=document.createElement("td");td.appendChild(spark(seriesList));
  tr.appendChild(td);tbody.appendChild(tr);
 });
 if(!list.length){thead.innerHTML="";
  tbody.innerHTML="<tr><td>(no series retained yet)</td></tr>";}
}
async function renderLogs(){
 document.getElementById("tbl").style.display="none";
 const pane=document.getElementById("logpane");pane.style.display="block";
 const streams=await (await fetch("/api/logs")).json();
 // built via createElement/textContent: a stream name (derived from a
 // user-chosen job_id) containing quotes/angle brackets must render as
 // text, never as markup or an onclick payload
 const box=document.getElementById("streams");box.textContent="";
 streams.forEach(s=>{
  const b=document.createElement("button");
  b.textContent=s.stream+" ";
  const sm=document.createElement("small");
  sm.textContent=`(${s.kind}, ${Math.round(s.bytes/1024)}K)`;
  b.appendChild(sm);
  b.onclick=()=>showLog(s.stream);
  box.appendChild(b);box.appendChild(document.createTextNode(" "));
 });
 if(!streams.length)box.textContent="(no log streams yet)";
}
function flameColor(s){let h=0;for(let i=0;i<s.length;i++)
 h=(h*31+s.charCodeAt(i))>>>0;
 return `hsl(${18+h%42},${55+h%30}%,${60+h%14}%)`;}
async function renderFlame(){
 // icicle flamegraph straight from the ProfileStore's folded stacks
 // (root at the top); every origin's history is already head-side, so
 // this costs one fetch — no sampling is triggered
 document.getElementById("tbl").style.display="none";
 document.getElementById("logpane").style.display="none";
 const pane=document.getElementById("flamepane");pane.style.display="block";
 const p=await (await fetch("/api/profile/continuous?window=600")).json();
 const root={n:0,kids:{}};
 for(const [stack,n] of Object.entries(p.folded||{})){
  root.n+=n;let cur=root;
  for(const f of stack.split("|"))
   {cur=cur.kids[f]??(cur.kids[f]={n:0,kids:{}});cur.n+=n;}}
 const g=document.getElementById("flamegraph");g.textContent="";
 let maxd=0;
 const place=(node,x0,x1,d)=>{
  maxd=Math.max(maxd,d);if(d>48)return;let x=x0;
  for(const [f,k] of Object.entries(node.kids).sort((a,b)=>b[1].n-a[1].n)){
   const w=(x1-x0)*k.n/node.n;
   if(w<0.15){x+=w;continue;}
   const el=document.createElement("div");
   el.className="frame";el.textContent=f;
   el.title=`${f}  ${k.n} samples (${(100*k.n/root.n).toFixed(1)}%)`;
   el.style.left=x+"%";el.style.width=w+"%";el.style.top=(d*17)+"px";
   el.style.background=flameColor(f);
   g.appendChild(el);
   place(k,x,x+w,d+1);x+=w;}};
 if(root.n)place(root,0,100,0);
 else g.textContent=" (no continuous-profile samples yet)";
 g.style.height=(Math.min(maxd+1,49)*17+4)+"px";
 const rows=p.stats||[];
 const thead=document.querySelector("#ftbl thead"),
       tbody=document.querySelector("#ftbl tbody");
 if(!rows.length){thead.innerHTML="";tbody.innerHTML=
  "<tr><td>(no origins reporting)</td></tr>";return;}
 const cols=Object.keys(rows[0]);
 thead.innerHTML="<tr>"+cols.map(c=>`<th>${esc(c)}</th>`).join("")+"</tr>";
 tbody.innerHTML=rows.map(r=>"<tr>"+cols.map(c=>
  `<td>${esc(cell(r[c]))}</td>`).join("")+"</tr>").join("");
}
async function render(){
 [...nav.children].forEach(b=>b.classList.toggle("on",b.textContent===tab));
 if(tab!=="flame")document.getElementById("flamepane").style.display="none";
 try{
  const s=await (await fetch("/api/cluster_status")).json();
  document.getElementById("hdr").textContent=
   Object.entries(s.cluster_resources).map(([n,r])=>
    n+": "+Object.entries(r).map(([k,v])=>k+"="+v).join(" ")).join(" | ");
  const cards=[["nodes",s.num_nodes],["actors",s.num_actors],
   ["tasks",s.num_tasks],["workers",s.num_workers],
   ["objects",s.object_store.num_objects??s.object_store.objects??"-"],
   ["store MB",Math.round((s.object_store.bytes_used??0)/1048576)]];
  document.getElementById("cards").innerHTML=cards.map(([k,v])=>
   `<div class=card><b>${v}</b><small>${k}</small></div>`).join("");
  if(tab==="logs"){await renderLogs();return;}
  if(tab==="metrics"){await renderMetrics();return;}
  if(tab==="flame"){await renderFlame();return;}
  document.getElementById("logpane").style.display="none";
  document.getElementById("tbl").style.display="";
  const url=tab==="serve"?"/api/serve/applications":"/api/"+tab+"?limit=200";
  let rows=await (await fetch(url)).json();
  if(!Array.isArray(rows)){rows=Object.entries(rows||{}).map(([k,v])=>
   Object.assign({name:k},typeof v==="object"?v:{value:v}));}
  const thead=document.querySelector("#tbl thead"),
        tbody=document.querySelector("#tbl tbody");
  if(!rows.length){thead.innerHTML="";tbody.innerHTML=
   "<tr><td>(empty)</td></tr>";return;}
  const cols=Object.keys(rows[0]);
  thead.innerHTML="<tr>"+cols.map(c=>`<th>${esc(c)}</th>`).join("")+"</tr>";
  const idf=ID_FIELD[tab];
  // every interpolated value is esc()'d: row ids (e.g. a user-chosen
  // job_id) and cell payloads must not be able to break out of the
  // attribute or inject elements
  tbody.innerHTML=rows.map(r=>{
   const id=idf?r[idf]:null;
   const attrs=id?` class=click data-id="${esc(id)}"`:"";
   return `<tr${attrs}>`+cols.map(c=>
    `<td class="${esc(cell(r[c]))}">${esc(cell(r[c]))}</td>`).join("")+"</tr>";
  }).join("");
  if(idf)[...tbody.querySelectorAll("tr.click")].forEach(tr=>
   tr.onclick=()=>showDetail(tab,tr.dataset.id));
 }catch(e){document.getElementById("hdr").textContent="error: "+e;}
}
render();timer=setInterval(render,2000);
</script></body></html>"""


class Dashboard:
    def __init__(self, node, host: str = "127.0.0.1", port: int = 0):
        self.node = node

        dash = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def do_GET(self):
                try:
                    dash._route(self)
                except BrokenPipeError:
                    pass
                except Exception as e:
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())

            def do_PUT(self):
                try:
                    dash._route_put(self)
                except BrokenPipeError:
                    pass
                except Exception as e:
                    self.send_response(500)
                    self.end_headers()
                    self.wfile.write(str(e).encode())

            do_POST = do_PUT

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.address = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="dashboard")
        self._thread.start()

    # -- routing -----------------------------------------------------------
    def _route(self, req: BaseHTTPRequestHandler) -> None:
        parsed = urlparse(req.path)
        path = parsed.path.rstrip("/")
        qs = parse_qs(parsed.query)
        limit = int(qs.get("limit", ["1000"])[0])
        if path == "":
            self._send(req, _INDEX, ctype="text/html")
            return
        if path == "/metrics":
            self._send(req, self._metrics_text(), ctype="text/plain; version=0.0.4")
            return
        if path == "/api/profile/continuous":
            # the always-on plane: merged history from the head's
            # ProfileStore (no new sampling — it is already there).
            # ?window=300[&origin=][&format=collapsed]; add
            # &diff_a=600&diff_b=60 for a differential profile
            store = self.node.profile_store
            origin = qs.get("origin", [None])[0]
            fmt = qs.get("format", ["json"])[0]
            if "diff_a" in qs or "diff_b" in qs:
                d = store.diff(
                    window_a=float(qs.get("diff_a", ["600"])[0]),
                    window_b=float(qs.get("diff_b", ["60"])[0]),
                    origin=origin)
                if fmt == "collapsed":
                    self._send(req, d["collapsed"],
                               ctype="text/plain; charset=utf-8")
                else:
                    self._send(req, json.dumps(_jsonable(d)))
                return
            window = float(qs.get("window", ["300"])[0])
            if fmt == "collapsed":
                self._send(req, store.collapsed(window, origin=origin),
                           ctype="text/plain; charset=utf-8")
                return
            prof = store.query(window, origin=origin)
            prof["stats"] = store.stats()
            self._send(req, json.dumps(_jsonable(prof)))
            return
        if path == "/api/profile":
            # on-demand sampling profile (py-spy/profile_manager.py analog):
            # ?duration=3 for the head; &worker_id=<hex> for a worker;
            # &format=collapsed for folded stacks (speedscope/flamegraph.pl)
            duration = min(30.0, float(qs.get("duration", ["3"])[0]))
            wid = qs.get("worker_id", [None])[0]
            fmt = qs.get("format", ["json"])[0]
            # collapsed consumers want the whole profile, not the top-40
            top = 10_000 if fmt == "collapsed" else 40
            result = self._profile(wid, duration, top)
            if fmt == "collapsed" and "report" in result:
                from ray_tpu._private.sampling_profiler import (
                    collapsed_from_report,
                )

                self._send(req, collapsed_from_report(result["report"]),
                           ctype="text/plain; charset=utf-8")
                return
            self._send(req, json.dumps(result))
            return
        if path == "/api/metrics/list":
            # TSDB directory: every metric with retained history
            self._send(req, json.dumps(self.node.tsdb.list_metrics()))
            return
        if path == "/api/metrics/query":
            # time-series query over the head TSDB (the sparkline/Grafana
            # backend): ?name=...&window=3600&step=60[&agg=max]
            name = qs.get("name", [""])[0]
            if not name:
                req.send_response(400)
                req.end_headers()
                req.wfile.write(b'{"error": "name required"}')
                return
            try:
                result = self.node.tsdb.query(
                    name,
                    window_s=float(qs.get("window", ["3600"])[0]),
                    step_s=float(qs.get("step", ["0"])[0]),
                    agg=qs.get("agg", [None])[0],
                )
            except ValueError as e:
                req.send_response(400)
                req.end_headers()
                req.wfile.write(json.dumps({"error": str(e)}).encode())
                return
            self._send(req, json.dumps(result))
            return
        if path == "/api/memory":
            # object-ownership audit (`ray memory` analog over HTTP)
            self._send(req, json.dumps(_jsonable(
                self.node._memory_audit(limit=limit))))
            return
        if path == "/api/top":
            self._send(req, json.dumps(_jsonable(self.node._top_snapshot())))
            return
        if path == "/api/perf":
            # performance observability aggregate (`ray_tpu perf` over
            # HTTP): step-phase breakdown, MFU trend, compile table,
            # HBM watermark, decode TTFT/ITL + prefill interference
            window = float(qs.get("window", ["1800"])[0])
            self._send(req, json.dumps(_jsonable(
                self.node._perf_summary(window_s=window))))
            return
        if path.startswith("/api/logs/"):
            # tail one log stream as plain text (reference log viewer:
            # dashboard/modules/log)
            tail = min(100_000, int(qs.get("tail", ["2000"])[0]))
            text = self._log_tail(path[len("/api/logs/"):], tail)
            if text is None:
                req.send_response(404)
                req.end_headers()
                return
            self._send(req, text, ctype="text/plain; charset=utf-8")
            return
        if path.startswith("/api/"):
            payload = self._api(path[len("/api/"):], limit)
            if payload is None:
                req.send_response(404)
                req.end_headers()
                return
            self._send(req, json.dumps(payload), ctype="application/json")
            return
        req.send_response(404)
        req.end_headers()

    @staticmethod
    def _send(req, body: str, ctype: str = "application/json") -> None:
        data = body.encode()
        req.send_response(200)
        req.send_header("Content-Type", ctype)
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    def _profile(self, worker_id_hex, duration: float, top: int = 40):
        """Sample the head process, or ask a worker to sample itself."""
        import os as _os
        import threading as _threading

        if worker_id_hex is None:
            from ray_tpu._private.sampling_profiler import profile_for

            return {"target": "head", "duration_s": duration,
                    "report": profile_for(duration, top=top)}
        node = self.node
        try:
            wid = bytes.fromhex(worker_id_hex)
        except ValueError:
            return {"error": f"bad worker_id {worker_id_hex!r}"}
        with node.lock:
            w = node.workers.get(wid)
        if w is None or w.conn is None or w.state == "dead":
            return {"error": "unknown or dead worker"}
        token = _os.urandom(8).hex()
        holder = {"event": _threading.Event(), "report": None}
        node._profile_acks[token] = holder
        try:
            w.send({"type": "profile", "token": token, "duration": duration,
                    "top": top})
        except (OSError, ValueError):
            node._profile_acks.pop(token, None)
            return {"error": "worker unreachable"}
        if not holder["event"].wait(duration + 30.0):
            node._profile_acks.pop(token, None)
            return {"error": "profile timed out"}
        return {"target": worker_id_hex, "duration_s": duration,
                "report": holder["report"]}

    # -- payloads ----------------------------------------------------------
    def _api(self, what: str, limit: int):
        node = self.node
        if what == "cluster_status":
            snap = node._state_snapshot()
            with node.lock:
                num_workers = len([w for w in node.workers.values()
                                   if w.state != "dead"])
            return _jsonable({
                "cluster_resources": snap["cluster_resources"],
                "available_resources": snap["available_resources"],
                "object_store": snap["object_store"],
                "num_nodes": len(snap["nodes"]),
                "num_actors": len(snap["actors"]),
                "num_tasks": len(snap["tasks"]),
                "num_workers": num_workers,
            })
        if what == "serve/applications":
            return self._serve_status()
        if what == "timeline":
            # chrome-trace of task events merged with streaming/collective/
            # serve spans from the flight recorder (``ray_tpu timeline``
            # over HTTP; open in chrome://tracing / perfetto)
            from ray_tpu.util.timeline import merged_timeline

            # _jsonable: recorder-event args may carry arbitrary app
            # payloads (numpy scalars) that plain json.dumps rejects
            return _jsonable(merged_timeline(
                node._list_state("tasks", 100_000),
                node._list_state("events", 100_000)))
        if what == "grafana_dashboard":
            # dashboard-as-code from the live registry (the reference's
            # metrics/grafana_dashboard_factory.py analog)
            from ray_tpu.dashboard.grafana_dashboard_factory import (
                generate_grafana_dashboard,
            )

            return generate_grafana_dashboard(
                self._merged_snapshot(), tsdb=node.tsdb,
                slos=node.watchdog.slos() if node.watchdog else None)
        if what == "logs":
            return self._log_streams()
        if what == "serve/config":
            # the declarative goal config last applied over PUT (empty if
            # serve is down or nothing was config-deployed)
            import ray_tpu
            from ray_tpu.serve._private.controller import (
                CONTROLLER_NAME, SERVE_NAMESPACE)

            try:
                controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                               namespace=SERVE_NAMESPACE)
                return _jsonable(ray_tpu.get(
                    controller.get_deploy_config.remote(), timeout=10) or {})
            except Exception:
                return {}
        if what.startswith("traces/"):
            # one assembled trace + critical-path analysis (the JSON the
            # `ray_tpu trace <id>` CLI renders)
            trace = node._get_trace(what[len("traces/"):])
            if trace is None:
                return None
            from ray_tpu.util.trace_analysis import analyze

            trace["analysis"] = analyze(trace)
            return _jsonable(trace)
        if "/" in what:
            # drill-down: /api/<table>/<id> -> full detail for one row
            # (after every named serve/... route — must not shadow them)
            table, _, key = what.partition("/")
            return self._detail(table, key)
        try:
            # the state-API backend takes the right locks and strips blobs
            rows = node._list_state(what, limit)
        except ValueError:
            return None
        if what == "nodes":
            self._merge_node_stats(rows)
        return _jsonable(rows)

    def _merge_node_stats(self, rows) -> None:
        """Attach each node's live utilization (agent pongs carry remote
        stats; head-local nodes read /proc here) plus resource load —
        the reference dashboard-agent's per-node metrics surface."""
        from ray_tpu._private.resource_spec import host_stats

        node = self.node
        with node.lock:
            # only ALIVE nodes get stats: a dead remote's row must not
            # inherit the head host's /proc numbers (agent_conn is
            # cleared on death) or show stale pre-death stats as live
            live = {
                nid: (ns.host_stats, ns.utilization(),
                      ns.agent_conn is not None)
                for nid, ns in node.nodes.items() if ns.alive
            }
        local_stats = None
        for r in rows:
            nid = r.get("node_id")
            if nid not in live:
                continue
            stats, util, remote = live[nid]
            if stats is None and not remote:
                # emulated/head-local nodes genuinely share this host
                if local_stats is None:
                    local_stats = host_stats()
                stats = local_stats
            r["host_stats"] = stats
            r["resource_utilization"] = round(util, 3)

    # -- logs (reference dashboard/modules/log: per-worker files + job
    # driver logs under the session dir) -----------------------------------
    def _log_streams(self):
        import os

        node = self.node
        streams = []
        logs_dir = os.path.join(node.session_dir, "logs")
        try:
            for f in sorted(os.listdir(logs_dir)):
                if f.endswith(".log"):
                    full = os.path.join(logs_dir, f)
                    streams.append({
                        "stream": f[:-len(".log")], "kind": "worker",
                        "bytes": os.path.getsize(full),
                        "mtime": os.path.getmtime(full),
                    })
        except OSError:
            pass
        mgr = getattr(node, "job_manager", None)
        if mgr is not None:
            for info in mgr.list_jobs():
                lp = info.get("log_path")
                if lp and os.path.exists(lp):
                    streams.append({
                        "stream": f"job-{info['job_id']}", "kind": "job",
                        "bytes": os.path.getsize(lp),
                        "mtime": os.path.getmtime(lp),
                    })
        # union in the head LogStore's streams: remote-node workers have no
        # file under THIS session dir, but their shipped rings (and the
        # death tails of retired streams) are servable all the same
        store = getattr(node, "log_store", None)
        if store is not None:
            seen = {s["stream"] for s in streams}
            for r in store.stats():
                if r["stream"] in seen:
                    continue
                streams.append({
                    "stream": r["stream"],
                    "kind": "retired" if r.get("retired") else "remote",
                    "bytes": r["bytes"], "mtime": r.get("last_ts") or 0,
                })
        return streams

    def _log_path(self, stream: str):
        import os

        node = self.node
        if "/" in stream or ".." in stream:
            return None  # path traversal
        if stream.startswith("job-"):
            mgr = getattr(node, "job_manager", None)
            if mgr is not None:
                for info in mgr.list_jobs():
                    if f"job-{info['job_id']}" == stream:
                        return info.get("log_path")
            return None
        path = os.path.join(node.session_dir, "logs", f"{stream}.log")
        return path if os.path.exists(path) else None

    def _log_tail(self, stream: str, tail_lines: int):
        import os

        from ray_tpu._private import log_plane

        path = self._log_path(stream)
        if path is None:
            # not a local file: serve from the head LogStore ring (a
            # remote node's worker, or a retired stream's death tail) —
            # cross-node logs in the same viewer, zero JS changes
            store = getattr(self.node, "log_store", None)
            if store is None or stream not in store:
                return None
            return "\n".join(store.tail_text(stream, n=tail_lines))
        try:
            size = os.path.getsize(path)
            with open(path, "rb") as f:
                # read at most ~200 bytes/line from the end, then trim
                f.seek(max(0, size - tail_lines * 200))
                data = f.read()
        except OSError:
            return None
        lines = data.decode("utf-8", "replace").splitlines()
        # strip the machine context stamps for human eyes
        return "\n".join(log_plane.parse_line(ln)[5]
                         for ln in lines[-tail_lines:])

    # -- drill-down --------------------------------------------------------
    def _detail(self, table: str, key: str):
        """Everything about one task/actor/node/worker/pg/job — the row's
        full record plus cross-references (its worker's log stream, an
        actor's pending/running tasks) for the reference's detail pages
        (dashboard/client src TaskDetail/ActorDetail)."""
        node = self.node
        try:
            rows = node._list_state(table, 100_000)
        except ValueError:
            return None
        id_fields = ("task_id", "actor_id", "node_id", "worker_id",
                     "pg_id", "group_id", "job_id", "oid", "object_id")
        match = None
        for r in rows:
            if any(str(r.get(f)) == key for f in id_fields if f in r):
                match = dict(r)
                break
        if match is None:
            return None
        if table == "tasks":
            wid = match.get("worker_id")
            if wid:
                match["log_stream"] = f"worker-{wid}"
        elif table == "actors":
            # the actor's tasks, newest first
            aid = match.get("actor_id")
            match["recent_tasks"] = [
                t for t in node._list_state("tasks", 100_000)
                if t.get("actor_id") == aid][-20:]
        elif table == "workers":
            match["log_stream"] = f"worker-{key}"
        return _jsonable(match)

    def _route_put(self, req: BaseHTTPRequestHandler) -> None:
        path = urlparse(req.path).path.rstrip("/")
        if path != "/api/serve/applications":
            req.send_response(404)
            req.end_headers()
            return
        length = int(req.headers.get("Content-Length") or 0)
        body = req.rfile.read(length) if length else b""
        code, payload = self._serve_deploy(body)
        data = json.dumps(payload).encode()
        req.send_response(code)
        req.send_header("Content-Type", "application/json")
        req.send_header("Content-Length", str(len(data)))
        req.end_headers()
        req.wfile.write(data)

    def _serve_deploy(self, body: bytes):
        """PUT /api/serve/applications: validate a declarative config and
        hand it to the controller to reconcile (the reference's
        ``dashboard/modules/serve/serve_head.py`` deploy path)."""
        import ray_tpu
        from ray_tpu.serve.schema import SchemaError, parse_deploy_config

        try:
            parsed = parse_deploy_config(json.loads(body or b"{}"))
        except (ValueError, SchemaError) as e:  # includes JSONDecodeError
            return 400, {"error": str(e)}
        try:
            from ray_tpu.serve import api as serve_api

            serve_api.start()  # idempotent: connect-or-boot controller+proxy
            controller = serve_api._get_client().controller
        except Exception as e:  # noqa: BLE001
            return 503, {"error": f"cannot start serve: {type(e).__name__}: {e}"}
        try:
            out = ray_tpu.get(
                controller.apply_deploy_config.remote(parsed.to_dict()),
                timeout=180)
        except Exception as e:  # noqa: BLE001
            return 500, {"error": f"deploy failed: {type(e).__name__}: {e}"}
        return 200, out

    def _serve_status(self):
        """Serve REST module (``dashboard/modules/serve`` analog): live
        deployment + autoscaling state pulled from the controller actor.
        No controller -> {}; a broken/slow controller -> explicit error
        payload (an operator must be able to tell the two apart)."""
        import ray_tpu
        from ray_tpu.serve._private.controller import (
            CONTROLLER_NAME, SERVE_NAMESPACE)

        try:
            controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                           namespace=SERVE_NAMESPACE)
        except Exception:
            return {}  # serve not running
        try:
            status = ray_tpu.get(controller.get_status.remote(), timeout=10)
            # independent per-deployment calls: submit all, one shared get
            refs = {
                name: controller.get_autoscaling_metrics.remote(name)
                for name in status
            }
            metrics = ray_tpu.get(list(refs.values()), timeout=10)
            for (name, _), m in zip(refs.items(), metrics):
                status[name]["autoscaling_metrics"] = m
            return _jsonable(status)
        except Exception as e:  # noqa: BLE001
            return {"error": f"serve controller unavailable: {type(e).__name__}: {e}"}

    def _merged_snapshot(self) -> dict:
        """Head registry + worker-reported metrics, with runtime gauges
        refreshed at scrape time (metric_defs.cc analog).  The gauge
        refresh lives on the Node so the TSDB sample loop and this scrape
        path can never disagree about what the runtime gauges mean; the
        merge itself is the Node's too (`_merged_metrics_snapshot` — one
        merge path for /metrics, perf_summary, and top)."""
        self.node.refresh_runtime_gauges()
        return self.node._merged_metrics_snapshot()

    def _metrics_text(self) -> str:
        return metrics_mod.prometheus_text(self._merged_snapshot())

    def close(self) -> None:
        try:
            self._server.shutdown()
            self._server.server_close()
        except Exception:
            pass
