"""Grafana dashboard-as-code from the live metrics registry.

Analog of the reference's ``dashboard/modules/metrics/
grafana_dashboard_factory.py``: instead of hand-maintaining dashboard
JSON, the panel list is generated from what the registry actually
exports — every Counter becomes a rate panel, every Gauge a value panel,
every Histogram a p50/p99 quantile panel — so a metric added anywhere in
the codebase shows up on the next generation with zero dashboard work.

Serve it from the head (``GET /api/grafana_dashboard``) or write it to a
file and import it into Grafana against a Prometheus scraping the head's
``/metrics``.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional

# Metrics the dashboard always charts, even before anything observes them
# (name -> (type, help)).  Keeps the core cluster row stable across
# restarts when the registry is still cold.
CORE_METRICS: Dict[str, tuple] = {
    "ray_tpu_tasks": ("gauge", "tasks by state"),
    "ray_tpu_num_workers": ("gauge", "live workers"),
    "ray_tpu_num_nodes": ("gauge", "alive nodes"),
    "ray_tpu_sched_queue_depth": ("gauge", "tasks pending cluster-wide"),
    "ray_tpu_sched_dispatch_latency_s": ("histogram", "submit -> dispatch latency"),
    "ray_tpu_object_store_bytes": ("gauge", "head-local shm bytes"),
    "ray_tpu_object_put_latency_s": ("histogram", "object put latency"),
    "ray_tpu_object_get_latency_s": ("histogram", "object get latency"),
    "ray_tpu_streaming_blocks_total": ("counter", "blocks submitted per operator"),
    "ray_tpu_streaming_stall_s_total": ("counter", "pump backpressure stall seconds"),
    "ray_tpu_serve_admission_latency_s": ("histogram", "serve admission latency"),
    "ray_tpu_serve_router_queue_len": ("gauge", "router queue length"),
    "ray_tpu_llm_generated_tokens_total": ("counter", "LLM tokens generated"),
    "ray_tpu_llm_slot_admission_latency_s": ("histogram", "decode-slot admission latency"),
    "ray_tpu_train_step_time_s": ("histogram", "train step time"),
    "ray_tpu_data_ingest_wait_s_total": ("counter", "train ingest-wait seconds"),
    # perf observability (util/perf.py + serve/llm.py decode attribution)
    "ray_tpu_train_phase_seconds": ("histogram", "step-phase wall seconds"),
    "ray_tpu_train_step_mfu": ("gauge", "live per-step MFU"),
    "ray_tpu_jit_cache_misses_total": ("counter", "jit compiles (cache misses)"),
    "ray_tpu_hbm_bytes_in_use": ("gauge", "device memory in use"),
    "ray_tpu_llm_ttft_s": ("histogram", "LLM time-to-first-token"),
    "ray_tpu_llm_itl_s": ("histogram", "LLM inter-token latency"),
    "ray_tpu_llm_prefill_interference_s_total":
        ("counter", "decode-tick seconds billed to prefill"),
    # continuous-profiling plane (PR 17: sampling_profiler + locks)
    "ray_tpu_profiler_duty_frac": ("gauge", "profiler duty cycle fraction"),
    "ray_tpu_gil_lateness_frac": ("gauge", "GIL pressure (tick lateness)"),
    "ray_tpu_lock_wait_s": ("gauge", "named-lock wait seconds (ewma)"),
    "ray_tpu_lock_hold_s": ("gauge", "named-lock hold seconds (ewma)"),
    "ray_tpu_profile_serialization_frac":
        ("gauge", "profiled time in serialization"),
    # cluster log plane (PR 19: log ship / suppression pressure)
    "ray_tpu_log_records_total": ("counter", "log records ingested"),
    "ray_tpu_log_suppressed_total":
        ("counter", "log records dropped by rate suppression"),
    # serve SLO taps (watchdog plane)
    "ray_tpu_serve_http_p99_s": ("gauge", "serve HTTP p99 (trailing window)"),
    "ray_tpu_serve_http_requests_total":
        ("counter", "serve HTTP requests by status class"),
}

_PANEL_W = 12  # two panels per 24-unit grafana row
_PANEL_H = 8


def _target(expr: str, legend: str) -> dict:
    return {"expr": expr, "legendFormat": legend, "refId": "A"}


def _targets_for(name: str, mtype: str) -> List[dict]:
    if mtype == "counter":
        return [_target(f"sum(rate({name}[5m]))", f"{name}/s")]
    if mtype == "histogram":
        return [
            {"expr": (f"histogram_quantile(0.5, "
                      f"sum(rate({name}_bucket[5m])) by (le))"),
             "legendFormat": "p50", "refId": "A"},
            {"expr": (f"histogram_quantile(0.99, "
                      f"sum(rate({name}_bucket[5m])) by (le))"),
             "legendFormat": "p99", "refId": "B"},
        ]
    return [_target(name, name)]  # gauge


def _panel(panel_id: int, name: str, mtype: str, help_: str,
           x: int, y: int) -> dict:
    return {
        "id": panel_id,
        "title": help_ or name,
        "description": f"{name} ({mtype})",
        "type": "timeseries",
        "datasource": {"type": "prometheus", "uid": "${datasource}"},
        "gridPos": {"x": x, "y": y, "w": _PANEL_W, "h": _PANEL_H},
        "targets": _targets_for(name, mtype),
        "fieldConfig": {"defaults": {"custom": {"fillOpacity": 10}},
                        "overrides": []},
    }


def _apply_slo_threshold(panel: dict, slo: dict) -> None:
    """Render a declared SLO as a Grafana threshold line on its metric's
    panel — the same objective the watchdog alerts on, drawn where the
    operator looks."""
    # ">=" objectives (floors) alarm BELOW the threshold; "<=" above
    floor = slo.get("op") == ">="
    steps = [{"color": "red" if floor else "green", "value": None},
             {"color": "green" if floor else "red",
              "value": slo["threshold"]}]
    defaults = panel["fieldConfig"]["defaults"]
    defaults["thresholds"] = {"mode": "absolute", "steps": steps}
    defaults.setdefault("custom", {})["thresholdsStyle"] = {"mode": "line"}
    panel["description"] = (panel.get("description", "") +
                            f" | SLO {slo['name']}: {slo.get('op', '<=')} "
                            f"{slo['threshold']}")


def generate_grafana_dashboard(snapshot: Optional[Dict[str, dict]] = None,
                               tsdb=None,
                               slos: Optional[List[dict]] = None) -> dict:
    """Build the dashboard dict from a registry snapshot (defaults to this
    process's registry).  Deterministic layout: core metrics first in
    their declared order, then any extra registered metric sorted by name.

    ``tsdb`` (a ``util.tsdb.TimeSeriesStore``) widens the panel set to
    every metric with retained HISTORY — including series whose origin
    (a dead worker, a drained node) already expired from the live
    registry, which is exactly when an operator builds the dashboard to
    investigate.

    ``slos`` (rows shaped like ``watchdog.Watchdog.slos()``) draw each
    declared objective as a threshold line on its metric's panel, so the
    alerting objective and the dashboard can never disagree."""
    if snapshot is None:
        from ray_tpu.util import metrics as metrics_mod

        snapshot = metrics_mod.registry().snapshot()
    metrics: Dict[str, tuple] = dict(CORE_METRICS)
    extra: Dict[str, tuple] = {}
    for name, m in snapshot.items():
        extra[name] = (m["type"], m.get("help", ""))
    if tsdb is not None:
        for row in tsdb.list_metrics():
            extra.setdefault(row["name"], (row["type"], row.get("help", "")))
    for name in sorted(extra):
        metrics[name] = extra[name]
    # threshold-kind SLOs attach to their metric's panel (ratio SLOs
    # have no single-series threshold to draw)
    slo_by_metric = {s["metric"]: s for s in (slos or [])
                     if s.get("kind", "threshold") == "threshold"}
    panels = []
    for i, (name, (mtype, help_)) in enumerate(metrics.items()):
        x = (i % 2) * _PANEL_W
        y = (i // 2) * _PANEL_H
        panel = _panel(i + 1, name, mtype, help_, x, y)
        if name in slo_by_metric:
            _apply_slo_threshold(panel, slo_by_metric[name])
        panels.append(panel)
    return {
        "uid": "ray-tpu-default",
        "title": "ray_tpu cluster",
        "description": "generated by ray_tpu.dashboard.grafana_dashboard_factory",
        "tags": ["ray_tpu", "generated"],
        "timezone": "browser",
        "refresh": "10s",
        "time": {"from": "now-30m", "to": "now"},
        "templating": {"list": [{
            "name": "datasource", "type": "datasource", "query": "prometheus",
            "label": "Data source",
        }]},
        "panels": panels,
        "schemaVersion": 39,
        "version": 1,
    }


def write_grafana_dashboard(path: str,
                            snapshot: Optional[Dict[str, dict]] = None) -> str:
    with open(path, "w") as f:
        json.dump(generate_grafana_dashboard(snapshot), f, indent=2)
    return path
