"""ray_tpu.dashboard — cluster observability HTTP server.

Analog of the reference dashboard head (``dashboard/head.py:69``): JSON
state endpoints + Prometheus ``/metrics``, served from the head process.
"""

from ray_tpu.dashboard.dashboard import Dashboard

__all__ = ["Dashboard"]
