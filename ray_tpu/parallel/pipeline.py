"""Microbatched pipeline parallelism over the ``pp`` mesh axis.

The reference has no pipeline engine — SURVEY §2.5 marks PP "Absent as an
engine; primitives only" (``DatasetPipeline`` is *data* pipelining,
``python/ray/data/dataset_pipeline.py``).  This is the TPU-native engine
built the way the scaling-book prescribes: a GPipe schedule expressed as a
``lax.scan`` over pipeline ticks inside a **partial-manual**
``jax.shard_map`` — only ``pp`` is manual; every other mesh axis
(dp/fsdp/tp/ep/sp) stays under GSPMD so the stage body keeps its sharding
annotations and XLA keeps inserting those collectives.

Mechanics:

- Layer-stacked params (leading ``[L, ...]`` axis) are sharded over ``pp``,
  so each stage owns ``L / pp`` contiguous layers and runs one compiled
  stage body regardless of depth.
- Activations hop stage-to-stage with ``lax.ppermute`` — a single ICI
  neighbour transfer per tick on a TPU torus.
- The batch is split into ``M`` microbatches; the schedule runs
  ``M + pp - 1`` ticks (the GPipe bubble).  Backward is jax autodiff
  through the scan + ppermute, i.e. the reverse schedule, no hand-written
  backward needed.
- Every stage computes every tick (bubble ticks process don't-care data);
  per-tick validity masks keep aux losses exact.

Cost model: bubble fraction = (pp-1)/(M+pp-1); pick M >= 4*pp for <20%
overhead.  Activation memory per device is O(M/pp) microbatches thanks to
remat inside the stage body.
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def pp_size(mesh: Optional[Mesh]) -> int:
    """Size of the pipeline axis (1 when absent)."""
    if mesh is None or "pp" not in mesh.axis_names:
        return 1
    return mesh.shape["pp"]


def gpipe(
    stage_body: Callable[[Any, jax.Array], Tuple[jax.Array, jax.Array]],
    blocks: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    n_microbatches: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    """Run ``x`` through a pipeline of stages over the ``pp`` mesh axis.

    Args:
        stage_body: ``(local_blocks, h) -> (h, aux)`` applying this stage's
            layer slice to one microbatch; ``aux`` is a scalar auxiliary
            loss (0.0 when unused).  Runs under GSPMD for non-pp axes.
        blocks: layer-stacked param pytree; every leaf's leading axis is
            the layer axis, sharded over ``pp`` (``L % pp == 0``).
        x: ``[B, ...]`` activations; ``B % n_microbatches == 0``.
        mesh: mesh containing a ``pp`` axis.
        n_microbatches: microbatch count ``M`` (default: ``pp``).

    Returns:
        ``(y, aux)`` — same-shaped activations and the summed aux loss
        (mean over microbatches, summed over all layers).
    """
    npp = pp_size(mesh)
    if npp == 1:
        y, aux = stage_body(blocks, x)
        return y, aux

    M = n_microbatches or npp
    B = x.shape[0]
    if B % M:
        raise ValueError(f"batch {B} not divisible by {M} microbatches")
    # Schedule plumbing (select/ppermute/psum of activations) runs in f32:
    # XLA's partial-manual partitioner miscompiles ("invalid binary
    # instruction opcode copy") when a non-f32 dtype crosses the
    # manual/auto boundary; stage compute still runs in x.dtype.
    dtype = x.dtype
    xm = x.reshape(M, B // M, *x.shape[1:]).astype(jnp.float32)
    perm = [(i, (i + 1) % npp) for i in range(npp)]

    def program(blocks, xm):
        stage = lax.axis_index("pp")
        xm = lax.pcast(xm, ("pp",), to="varying")
        state = jnp.zeros_like(xm[0])
        outputs = jnp.zeros_like(xm)
        aux0 = lax.pcast(jnp.zeros((), jnp.float32), ("pp",), to="varying")

        def tick(carry, t):
            state, outputs, aux_sum = carry
            inp = jnp.where(stage == 0, xm[jnp.minimum(t, M - 1)], state)
            y, aux = stage_body(blocks, inp.astype(dtype))
            y = y.astype(jnp.float32)
            # microbatch (t - stage) is in flight here iff 0 <= t-stage < M
            valid = (t >= stage) & (t < stage + M)
            aux_sum = aux_sum + jnp.where(valid, aux.astype(jnp.float32), 0.0)
            idx = jnp.clip(t - (npp - 1), 0, M - 1)
            write = (stage == npp - 1) & (t >= npp - 1)
            outputs = outputs.at[idx].set(jnp.where(write, y, outputs[idx]))
            state = lax.ppermute(y, "pp", perm)
            return (state, outputs, aux_sum), None

        (_, outputs, aux_sum), _ = lax.scan(
            tick, (state, outputs, aux0), jnp.arange(M + npp - 1)
        )
        # the finished microbatches live on the last stage; mask-psum
        # replicates them (and sums per-stage aux) across the pp axis
        outputs = lax.psum(jnp.where(stage == npp - 1, outputs, 0.0), "pp")
        aux = lax.psum(aux_sum, "pp") / M
        return outputs, aux

    blk_specs = jax.tree.map(lambda _: P("pp"), blocks)
    y, aux = jax.shard_map(
        program,
        mesh=mesh,
        in_specs=(blk_specs, P()),
        out_specs=(P(), P()),
        axis_names={"pp"},
    )(blocks, xm)
    return y.reshape(B, *x.shape[1:]).astype(dtype), aux


# ---------------------------------------------------------------------------
# Actor-level microbatch pipelining over a compiled execution graph
# ---------------------------------------------------------------------------


class MicrobatchPipeline:
    """The GPipe microbatch schedule at ACTOR granularity, driven by a
    compiled execution graph (``dag/compiled.py``).

    :func:`gpipe` above pipelines *inside* one pjit program over the
    ``pp`` mesh axis; this class pipelines *between* stage actors — the
    shape used when stages are whole hosts (one model shard per TPU pod
    slice) rather than mesh slices.  The stage chain compiles once into
    per-actor execution loops connected by pre-allocated SPSC channels,
    so streaming ``M`` microbatches keeps every stage busy: stage ``k``
    processes microbatch ``i`` while stage ``k+1`` processes ``i-1`` —
    the classic ``(S-1)/(M+S-1)`` bubble, with per-hop cost a channel
    write instead of a scheduler round trip (the property that makes the
    schedule viable at sub-millisecond stage times).

    ``stages`` are bound actor constructors (``Actor.bind(...)`` class
    nodes); each stage's ``method`` takes the previous stage's output.
    """

    def __init__(self, stages: Sequence[Any], *, method: str = "run",
                 n_microbatches: int = 0, **compile_kwargs):
        from ray_tpu.dag import InputNode

        if not stages:
            raise ValueError("MicrobatchPipeline needs at least one stage")
        self.n_stages = len(stages)
        self.n_microbatches = n_microbatches or 2 * len(stages)
        with InputNode() as inp:
            h = inp
            for s in stages:
                h = getattr(s, method).bind(h)
        compile_kwargs.setdefault(
            "max_inflight", self.n_microbatches + len(stages))
        self._dag = h.experimental_compile(**compile_kwargs)

    @property
    def actors(self) -> List[Any]:
        return self._dag.actors

    def run(self, microbatches: Sequence[Any],
            timeout: Optional[float] = None) -> List[Any]:
        """Stream the microbatches through the stage chain; returns the
        last stage's outputs in order.  All microbatches are in flight
        together (channel slots bound the depth), which is the entire
        point — submit-then-drain would serialize the stages."""
        refs = [self._dag.execute(mb) for mb in microbatches]
        return [r.get(timeout=timeout) for r in refs]

    def teardown(self) -> None:
        self._dag.teardown()
