"""XLA collective primitives over mesh axes.

The tensor plane of the framework (SURVEY §5.8): where the reference calls
NCCL (``util/collective/collective_group/nccl_collective_group.py:127``),
TPU code expresses the same collectives *inside* jit/shard_map and XLA
lowers them onto ICI.  These are thin, named wrappers so library code
(train backends, ring attention, MoE dispatch) reads like the reference's
collective API while remaining fully traceable.

All functions must be called inside ``shard_map`` (or a ``pjit`` body with
manual axes) where ``axis`` is a bound mesh axis name.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
from jax import lax

Axis = Union[str, Sequence[str]]


def allreduce(x: jax.Array, axis: Axis, op: str = "sum") -> jax.Array:
    """All-reduce over a mesh axis (NCCL allreduce analog)."""
    if op == "sum":
        return lax.psum(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    raise ValueError(f"unsupported reduce op {op!r}")


def allgather(x: jax.Array, axis: str, *, tiled: bool = True, gather_axis: int = 0) -> jax.Array:
    """All-gather shards over a mesh axis (concatenates along gather_axis)."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reducescatter(x: jax.Array, axis: str, *, scatter_axis: int = 0, op: str = "sum") -> jax.Array:
    """Reduce-scatter over a mesh axis (psum_scatter)."""
    if op not in ("sum", "mean"):
        raise ValueError(f"unsupported reduce op {op!r}")
    out = lax.psum_scatter(x, axis, scatter_dimension=scatter_axis, tiled=True)
    if op == "mean":
        out = out / lax.psum(1, axis)
    return out


def broadcast(x: jax.Array, axis: str, root: int = 0) -> jax.Array:
    """Broadcast the root shard to every member of the axis."""
    idx = lax.axis_index(axis)
    masked = jnp.where(idx == root, x, jnp.zeros_like(x))
    return lax.psum(masked, axis)


def all_to_all(
    x: jax.Array, axis: str, *, split_axis: int, concat_axis: int, tiled: bool = True
) -> jax.Array:
    """All-to-all — the Ulysses / MoE-dispatch primitive."""
    return lax.all_to_all(x, axis, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def ppermute_next(x: jax.Array, axis: str, *, shift: int = 1) -> jax.Array:
    """Rotate shards around the axis ring (ring-attention step).

    Device ``i`` receives the shard of device ``(i - shift) % n``; a ring
    send/recv pair over ICI neighbours.
    """
    n = lax.psum(1, axis)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def send_recv(x: jax.Array, axis: str, pairs: Sequence[tuple]) -> jax.Array:
    """Explicit point-to-point permutation (collective send/recv analog)."""
    return lax.ppermute(x, axis, list(pairs))


def axis_index(axis: str) -> jax.Array:
    return lax.axis_index(axis)


def axis_size(axis: Axis) -> int:
    return lax.psum(1, axis)


def barrier(x: jax.Array, axis: Axis) -> jax.Array:
    """Order ``x`` after a cross-device sync point.  Returns ``x`` fused
    with an all-reduced token — the caller MUST use the return value, or
    XLA dead-code-eliminates the collective."""
    token = lax.psum(jnp.zeros((), x.dtype), axis)
    return x + token


def grad_sync(grads, axis: Axis, *, mean: bool = True):
    """Synchronize a gradient pytree across the data axes (DDP allreduce)."""
    op = partial(lax.pmean if mean else lax.psum, axis_name=axis)
    return jax.tree.map(op, grads)
