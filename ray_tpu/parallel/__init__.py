"""TPU-native parallelism layer.

This package replaces the reference's NCCL/MPI tensor plane
(``python/ray/util/collective/collective.py``; NCCL group
``nccl_collective_group.py:127``) with XLA collectives over a device mesh:
ICI axes inside a slice, DCN axes across slices (SURVEY §5.8).

- :mod:`ray_tpu.parallel.mesh` — ``MeshSpec`` / mesh construction with
  named axes (``dp``/``fsdp``/``tp``/``sp``/``ep``/``pp``).
- :mod:`ray_tpu.parallel.sharding` — sharding-rule tables mapping pytree
  paths to ``PartitionSpec``s (the ``prepare_model`` analog for jax).
- :mod:`ray_tpu.parallel.collective` — group-based collective API with the
  surface of ``ray.util.collective`` backed by ``jax.lax`` collectives.
"""

from ray_tpu.parallel.mesh import (
    MeshSpec,
    create_mesh,
    get_abstract_mesh,
    local_mesh,
)
from ray_tpu.parallel.pipeline import gpipe, pp_size
from ray_tpu.parallel.sharding import (
    ShardingRules,
    infer_sharding,
    logical_to_sharding,
    with_sharding_constraint,
)

__all__ = [
    "gpipe",
    "pp_size",
    "MeshSpec",
    "create_mesh",
    "local_mesh",
    "get_abstract_mesh",
    "ShardingRules",
    "infer_sharding",
    "logical_to_sharding",
    "with_sharding_constraint",
]
