"""Logical-axis sharding rules: the jax analog of ``prepare_model``.

The reference wraps a torch module in DDP/FSDP for the user
(``python/ray/train/torch/train_loop_utils.py:51,71-74`` ``prepare_model``).
The TPU-native equivalent is declarative: parameters carry *logical* axis
names (e.g. ``("embed", "mlp")``) and a rule table maps logical axes to
mesh axes, producing ``NamedSharding``s that pjit consumes.  This is the
GSPMD recipe — annotate, let XLA insert collectives.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxis = Union[None, str, Tuple[str, ...]]


class ShardingRules:
    """Maps logical axis names to mesh axes (or None = replicate).

    Example::

        rules = ShardingRules(
            batch=("dp", "fsdp"), seq="sp",
            embed="fsdp", mlp="tp", heads="tp", vocab="tp",
        )
        sharding = rules.spec(("embed", "mlp"))   # P("fsdp", "tp")
    """

    def __init__(self, **rules: MeshAxis):
        self.rules: Dict[str, MeshAxis] = dict(rules)

    def update(self, **rules: MeshAxis) -> "ShardingRules":
        new = dict(self.rules)
        new.update(rules)
        return ShardingRules(**new)

    def spec(self, logical_axes: Sequence[Optional[str]]) -> P:
        return P(*(self.rules.get(a) if a is not None else None for a in logical_axes))

    def sharding(self, mesh: Mesh, logical_axes: Sequence[Optional[str]]) -> NamedSharding:
        return NamedSharding(mesh, self.spec(logical_axes))


# Default rule tables for the canonical modes.  ``None`` replicates.
DP_RULES = ShardingRules(batch="dp", seq=None, embed=None, mlp=None, heads=None,
                         kv=None, vocab=None, expert=None)
FSDP_RULES = ShardingRules(batch=("dp", "fsdp"), seq=None, embed="fsdp", mlp=None,
                           heads=None, kv=None, vocab=None, expert=None)
TP_RULES = ShardingRules(batch="dp", seq=None, embed=None, mlp="tp", heads="tp",
                         kv="tp", vocab="tp", expert=None)
FSDP_TP_RULES = ShardingRules(batch=("dp", "fsdp"), seq=None, embed="fsdp",
                              mlp="tp", heads="tp", kv="tp", vocab="tp", expert=None)
# Long-context: sequence axis sharded over sp (ring attention), params fsdp+tp.
SP_RULES = ShardingRules(batch=("dp", "fsdp"), seq="sp", embed="fsdp", mlp="tp",
                         heads="tp", kv="tp", vocab="tp", expert=None)
# MoE: experts sharded over ep.
EP_RULES = ShardingRules(batch=("dp", "fsdp"), seq=None, embed="fsdp", mlp="tp",
                         heads="tp", kv="tp", vocab="tp", expert="ep")


def rules_for_mesh(mesh: Mesh) -> ShardingRules:
    """Pick a sensible default rule table from the mesh's axes."""
    axes = set(mesh.axis_names)
    batch = tuple(a for a in ("dp", "fsdp") if a in axes) or None
    if batch is not None and len(batch) == 1:
        batch = batch[0]
    return ShardingRules(
        batch=batch,
        seq="sp" if "sp" in axes else None,
        embed="fsdp" if "fsdp" in axes else None,
        mlp="tp" if "tp" in axes else None,
        heads="tp" if "tp" in axes else None,
        kv="tp" if "tp" in axes else None,
        vocab="tp" if "tp" in axes else None,
        expert="ep" if "ep" in axes else None,
        # the stacked layer axis becomes the pipeline-stage axis
        layers="pp" if "pp" in axes else None,
    )


def logical_to_sharding(
    logical_tree: Any, mesh: Mesh, rules: ShardingRules
) -> Any:
    """Map a pytree of logical-axis tuples to a pytree of NamedShardings."""
    return jax.tree.map(
        lambda axes: rules.sharding(mesh, axes),
        logical_tree,
        is_leaf=lambda x: isinstance(x, tuple),
    )


def infer_sharding(params: Any, mesh: Mesh, rules: ShardingRules) -> Any:
    """Heuristic sharding for an unannotated param pytree.

    FSDP-style: shard the largest divisible axis of each array over the
    param axes (``fsdp`` then ``tp`` if present), replicate small arrays.
    Good enough when a model doesn't carry logical axis metadata.
    """
    axes = [a for a in ("fsdp", "tp") if a in mesh.axis_names]
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))

    def _spec(x) -> NamedSharding:
        if not hasattr(x, "shape") or not axes or x.ndim == 0 or x.size < 1024:
            return NamedSharding(mesh, P())
        ax = axes[0]
        n = sizes[ax]
        # shard the largest dim divisible by the axis size
        order = sorted(range(x.ndim), key=lambda i: -x.shape[i])
        for i in order:
            if x.shape[i] % n == 0:
                parts: list = [None] * x.ndim
                parts[i] = ax
                return NamedSharding(mesh, P(*parts))
        return NamedSharding(mesh, P())

    return jax.tree.map(_spec, params)


def with_sharding_constraint(x: Any, mesh: Mesh, spec: P) -> Any:
    """``lax.with_sharding_constraint`` under an explicit mesh."""
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))
