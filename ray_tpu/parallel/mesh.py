"""Device-mesh construction with named parallelism axes.

The TPU-native replacement for the reference's process-group bootstrap
(``python/ray/train/torch/config.py:69`` ``_setup_torch_process_group`` /
``dist.init_process_group``): instead of a rank rendezvous, every process
builds the same ``jax.sharding.Mesh`` over the slice's devices and XLA
inserts the collectives.  Axis vocabulary follows the scaling-book recipe:

- ``dp``   — pure data parallelism (params replicated)
- ``fsdp`` — data parallelism with ZeRO-style parameter sharding
- ``tp``   — tensor (model) parallelism, Megatron-style
- ``sp``   — sequence/context parallelism (ring attention axis)
- ``ep``   — expert parallelism for MoE
- ``pp``   — pipeline stages

On real hardware the mesh should be built so that ``tp``/``sp`` ride ICI
(innermost, contiguous devices) and ``dp`` can span DCN across slices —
`create_mesh` orders axes accordingly.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical axis order: outermost (slowest, OK on DCN) to innermost
# (fastest, must be ICI).  dp/fsdp across slices is fine; tp/sp never is.
AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Declarative mesh shape: axis name -> size.

    ``-1`` for at most one axis means "all remaining devices".

    Example::

        MeshSpec(dp=-1, tp=4).build()   # 2D mesh over all devices
    """

    pp: int = 1
    dp: int = 1
    fsdp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    def axis_sizes(self) -> Dict[str, int]:
        return {a: getattr(self, a) for a in AXIS_ORDER}

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = self.axis_sizes()
        wild = [a for a, s in sizes.items() if s == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        fixed = math.prod(s for s in sizes.values() if s != -1)
        if wild:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes product {fixed}"
                )
            sizes[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh spec {sizes} needs {fixed} devices, have {n_devices}"
            )
        return sizes

    def build(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        *,
        keep_unit_axes: bool = False,
    ) -> Mesh:
        return create_mesh(self, devices, keep_unit_axes=keep_unit_axes)


def create_mesh(
    spec: MeshSpec | Dict[str, int],
    devices: Optional[Sequence[jax.Device]] = None,
    *,
    keep_unit_axes: bool = False,
) -> Mesh:
    """Build a ``Mesh`` from a spec over ``devices`` (default: all).

    Axes are laid out in ``AXIS_ORDER`` so the innermost (``tp``, then
    ``sp``) map to physically adjacent devices — on a TPU slice that means
    ICI neighbours; ``dp``/``pp`` get the outermost stride and may cross
    DCN.  Unit axes are dropped unless ``keep_unit_axes``.
    """
    if isinstance(spec, dict):
        spec = MeshSpec(**spec)
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    sizes = spec.resolve(len(devices))
    names = [a for a in AXIS_ORDER if keep_unit_axes or sizes[a] > 1]
    if not names:  # single-device mesh still needs one axis for pjit
        names = ["dp"]
    shape = tuple(sizes[a] for a in names)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(names))


def local_mesh(axis: str = "dp") -> Mesh:
    """1-D mesh over this process's addressable devices."""
    devs = jax.local_devices()
    return Mesh(np.asarray(devs), (axis,))


def get_abstract_mesh(mesh: Mesh) -> Dict[str, int]:
    """axis name -> size view of a mesh (for logging / bundle policies)."""
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def ici_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Axes that must stay inside one slice (collectives ride ICI)."""
    return tuple(a for a in mesh.axis_names if a in ("tp", "sp", "ep"))


def dcn_axes(mesh: Mesh) -> Tuple[str, ...]:
    """Axes that may span slices over DCN (gradient-sync only)."""
    return tuple(a for a in mesh.axis_names if a in ("pp", "dp", "fsdp"))
