"""Job submission SDK — ``ray.job_submission`` analog.

``JobSubmissionClient`` (reference ``dashboard/modules/job/sdk.py``, REST
head ``job_head.py``) drives the head's JobManager: submit an entrypoint
shell command as a cluster driver, poll status, fetch logs, stop.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = {SUCCEEDED, FAILED, STOPPED}


class JobSubmissionClient:
    """Talks to the head over the existing control connection.  With no
    argument, uses the current driver session; pass ``address`` (a
    ``tcp://host:port`` from `ray_tpu start --head`) to attach from
    outside."""

    def __init__(self, address: Optional[str] = None, authkey: Optional[bytes] = None):
        if address is None:
            from ray_tpu._private.worker import global_worker

            if not global_worker.connected:
                raise RuntimeError("no ray_tpu session; init() first or pass address")
            self._client = global_worker.client
            self._owned = False
        else:
            import os

            from ray_tpu._private.client import CoreClient

            authkey = authkey or bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
            self._client = CoreClient(address, authkey)
            self._client.register_client()
            self._owned = True

    def submit_job(self, *, entrypoint: str, runtime_env: Optional[dict] = None,
                   job_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        if runtime_env and (runtime_env.get("working_dir")
                            or runtime_env.get("py_modules")):
            # ship local code as content-addressed packages so the job
            # driver runs inside it on the HEAD host (reference
            # sdk.py upload_working_dir_if_needed)
            from ray_tpu._private.runtime_env_packaging import (
                prepare_runtime_env,
            )

            runtime_env = prepare_runtime_env(runtime_env, self._client)
        reply = self._client.request({
            "type": "submit_job", "entrypoint": entrypoint,
            "runtime_env": runtime_env, "job_id": job_id, "metadata": metadata,
        })
        return reply["value"]

    def get_job_info(self, job_id: str) -> Optional[dict]:
        return self._client.request({"type": "job_info", "job_id": job_id})["value"]

    def get_job_status(self, job_id: str) -> Optional[str]:
        info = self.get_job_info(job_id)
        return info["status"] if info else None

    def get_job_logs(self, job_id: str) -> str:
        return self._client.request({"type": "job_logs", "job_id": job_id})["value"]

    def stop_job(self, job_id: str) -> bool:
        return self._client.request({"type": "stop_job", "job_id": job_id})["value"]

    def list_jobs(self) -> List[dict]:
        return self._client.request({"type": "list_state", "what": "jobs",
                                     "limit": 10_000})["value"]

    def wait_until_finish(self, job_id: str, timeout: float = 300.0,
                          poll_s: float = 0.5) -> str:
        deadline = time.time() + timeout
        while time.time() < deadline:
            status = self.get_job_status(job_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(poll_s)
        raise TimeoutError(f"job {job_id} still {status} after {timeout}s")

    def close(self) -> None:
        if self._owned:
            self._client.close()


__all__ = ["JobSubmissionClient", "JobStatus"]
