"""ray_tpu — a TPU-native distributed computing framework.

Public core API with the surface of the reference's ``python/ray``
(``ray.init/remote/get/put/wait/kill`` — ``python/ray/_private/worker.py:1031,
2222,2335,2391``) over a head runtime that fuses GCS + raylet + object
directory, with **TPU as a first-class resource** (``num_tpus=``), and an
AIR-style toolkit (``ray_tpu.train/tune/data/serve/rllib``) rebuilt
TPU-first on jax/XLA/pjit/pallas.

Subpackages are imported lazily so that the core never drags in jax — a
worker process only pays for what its tasks use.
"""

from __future__ import annotations

import atexit
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

from ray_tpu import exceptions
from ray_tpu._private import log_plane as _log_plane
from ray_tpu._private.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu._private.worker import global_worker
from ray_tpu.actor import ActorClass, ActorHandle, get_actor
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.runtime_context import get_runtime_context

__version__ = "0.1.0"

_init_lock = threading.Lock()


def init(
    address: Optional[str] = None,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[Dict[str, float]] = None,
    ignore_reinit_error: bool = True,
    namespace: Optional[str] = None,
    _authkey: Optional[bytes] = None,
    _gcs_persistence_path: Optional[str] = None,
    **_kwargs,
) -> None:
    """Start (or join) a cluster and connect as the driver.

    With no ``address``, boots the head runtime in this process — the
    ``ray.init`` head-node path (reference ``worker.py:1031`` →
    ``node.py:1083 start_ray_processes``): GCS/raylet/object directory run
    as threads of the driver process, workers spawn on demand.

    With ``address="tcp://host:port"`` (or ``"auto"`` to read the session
    file a running head wrote), joins an existing cluster as an external
    driver — the ``ray.init(address=...)`` path.  The authkey comes from
    ``$RAY_TPU_AUTHKEY`` unless passed.

    With ``address="ray_tpu://host:port"`` connects through the
    multi-tenant client proxy (``ray_tpu.util.client``): the proxy spawns
    an isolated driver subprocess for this connection, and named actors
    default to this tenant's own ``namespace`` (its job id unless given).
    ``namespace`` scopes named-actor registration/lookup in every mode.
    """
    from ray_tpu._private.client import CoreClient
    from ray_tpu._private.node import Node

    import os as _os

    if address is None and _os.environ.get("RAY_TPU_ADDRESS", "").startswith("tcp://"):
        # submitted jobs join the cluster that launched them (the
        # reference's $RAY_ADDRESS behavior)
        address = _os.environ["RAY_TPU_ADDRESS"]
    with _init_lock:
        if global_worker.connected:
            if ignore_reinit_error:
                return
            raise RuntimeError("ray_tpu.init() called twice")
        thin = False
        proxied = False
        if address is not None:
            import json
            import os

            thin = address.startswith("client://")
            if thin:
                # Ray Client analog (reference ``ray.init("ray://...")``,
                # util/client/ARCHITECTURE.md): a remote process that shares
                # no shm with the cluster; object payloads ride the control
                # socket both ways, everything else is already socket-based
                address = "tcp://" + address[len("client://"):]
            elif address.startswith("ray_tpu://"):
                # multi-tenant proxy mode: thin-client object paths over a
                # per-connection isolated driver the proxy owns
                proxied = thin = True
                address = "tcp://" + address[len("ray_tpu://"):]
            if address == "auto":
                with open("/tmp/ray_tpu/last_session.json") as f:
                    sess = json.load(f)
                address = sess["address"]
                authkey = bytes.fromhex(sess["authkey"])
                if sess.get("session_id"):
                    # adopt the head's shm namespace so this driver's puts
                    # live (and are swept) with the session they belong to
                    from ray_tpu._private import shm as _shm

                    os.environ[_shm._SESSION_ENV] = sess["session_id"]
            else:
                authkey = _authkey or bytes.fromhex(os.environ["RAY_TPU_AUTHKEY"])
            from ray_tpu._private import object_transfer

            object_transfer.configure(authkey)
            node = None
            client = CoreClient(address, authkey,
                                proxy_namespace=namespace, proxy=proxied)
            from ray_tpu._private import shm as _shm

            if not thin and _shm._SESSION_ENV not in os.environ:
                # adopt the head's shm namespace so this driver's puts are
                # swept with the session they belong to
                try:
                    sess_id = client.request({"type": "whoami"}, timeout=30)["value"]
                    os.environ[_shm._SESSION_ENV] = sess_id["session_id"]
                except Exception:
                    pass
        else:
            node = Node(num_cpus=num_cpus, num_tpus=num_tpus, resources=resources,
                        gcs_persistence_path=_gcs_persistence_path)
            client = CoreClient(node.address, node.authkey)
        ident = client.register_client(namespace=namespace)
        global_worker.mode = "driver"
        global_worker.thin_client = thin
        global_worker.job_id = ident.get("job_id")
        global_worker.namespace = ident.get("namespace") or namespace or "default"
        global_worker.node = node
        global_worker.client = client
        global_worker.node_id = node._head_node_id if node else "node-head"
        # driver log streaming (reference: print_to_stdstream over GCS
        # pubsub): subscribe to this job's shipped log records and
        # re-emit them prefixed "(name pid=… node=…)".  RAY_TPU_LOG_TO_DRIVER=0
        # turns the re-emission off.
        if (global_worker.job_id
                and _os.environ.get("RAY_TPU_LOG_TO_DRIVER", "1") != "0"):
            if _log_plane.enabled():
                try:
                    client.subscribe(f"logs:{global_worker.job_id}",
                                     _log_plane.make_driver_log_callback())
                except Exception:
                    pass  # log streaming is best-effort, never boot-fatal
        if node is None:
            # external driver: its flight-recorder events (streaming pump,
            # serve router) ship to the head like a worker's do.  The
            # in-process head path needs no pusher — driver emits land in
            # the head's own ring.
            from ray_tpu._private import events as _events

            origin = (f"tenant-{global_worker.job_id}" if proxied
                      else f"driver-{_os.getpid()}")
            global_worker._events_pusher = _events.EventsPusher(
                client.send, origin=origin,
                closed_fn=lambda: client.closed).start()
        atexit.register(shutdown)


def is_initialized() -> bool:
    return global_worker.connected


def shutdown() -> None:
    with _init_lock:
        if not global_worker.connected:
            return
        if global_worker.node is not None:
            # the in-process driver's own disconnect must not run a tenant
            # reap against the head it is about to tear down
            global_worker.node._reap_on_disconnect = False
        pusher = getattr(global_worker, "_events_pusher", None)
        if pusher is not None:
            try:
                pusher.stop()  # final event ship while the socket is live
            except Exception:
                pass
            global_worker._events_pusher = None
        try:
            global_worker.client.close()
        except Exception:
            pass
        if global_worker.node is not None:
            global_worker.node.shutdown()
        global_worker.client = None
        global_worker.node = None
        global_worker.mode = None
        global_worker.thin_client = False
        global_worker.job_id = None
        global_worker.namespace = None
        global_worker.function_cache.clear()
        global_worker.registered_fn_ids.clear()


def remote(*args, **kwargs):
    """``@remote`` decorator for tasks and actors (``ray.remote`` analog).

    Supports ``@remote``, ``@remote(num_cpus=..., num_tpus=..., ...)`` on
    functions and classes.
    """
    if len(args) == 1 and not kwargs and (callable(args[0]) or isinstance(args[0], type)):
        return _make_remote(args[0], {})
    if args:
        raise TypeError("@remote takes keyword options only, e.g. @remote(num_tpus=1)")

    def decorator(fn_or_cls):
        return _make_remote(fn_or_cls, kwargs)

    return decorator


def _make_remote(fn_or_cls, options):
    if isinstance(fn_or_cls, type):
        return ActorClass(fn_or_cls, options)
    return RemoteFunction(fn_or_cls, options)


def put(value: Any) -> ObjectRef:
    _ensure_connected()
    return global_worker.put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None):
    _ensure_connected()
    if isinstance(refs, ObjectRef):
        return global_worker.get([refs], timeout=timeout)[0]
    # A CompiledDAGRef can only exist if dag.compiled is already imported,
    # so a sys.modules probe keeps get() import-free for every process
    # that never compiles a graph (the dag package's lazy-load contract).
    import sys as _sys

    compiled_mod = _sys.modules.get("ray_tpu.dag.compiled")
    CompiledDAGRef = (compiled_mod.CompiledDAGRef if compiled_mod is not None
                      else None)
    if CompiledDAGRef is not None and isinstance(refs, CompiledDAGRef):
        # compiled-graph results read their pre-allocated output channel
        # directly — no object plane involved (dag/compiled.py)
        return refs.get(timeout=timeout)
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"get() takes an ObjectRef or a list of them, got {type(refs)}")
    if CompiledDAGRef is not None and any(
            isinstance(r, CompiledDAGRef) for r in refs):
        import time as _time

        for r in refs:
            if not isinstance(r, (ObjectRef, CompiledDAGRef)):
                raise TypeError(
                    f"get() list elements must be ObjectRefs or "
                    f"CompiledDAGRefs, got {type(r)}")
        # one overall deadline across the list, matching the pure-
        # ObjectRef path's timeout semantics; the ObjectRef elements still
        # fetch as ONE batched call (a single CompiledDAGRef must not
        # degrade a 1000-ref get into 1000 head round trips)
        deadline = None if timeout is None else _time.monotonic() + timeout
        plain = [r for r in refs if isinstance(r, ObjectRef)]
        values: dict = {}
        if plain:
            fetched = global_worker.get(plain, timeout=timeout)
            values = {id(r): v for r, v in zip(plain, fetched)}
        out = []
        for r in refs:
            if isinstance(r, ObjectRef):
                out.append(values[id(r)])
            else:
                remaining = (None if deadline is None
                             else max(0.0, deadline - _time.monotonic()))
                out.append(r.get(timeout=remaining))
        return out
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"get() list elements must be ObjectRefs, got {type(r)}")
    return global_worker.get(list(refs), timeout=timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
) -> Tuple[List[ObjectRef], List[ObjectRef]]:
    _ensure_connected()
    if num_returns > len(refs):
        raise ValueError("num_returns cannot exceed the number of refs")
    return global_worker.wait(list(refs), num_returns, timeout)


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    _ensure_connected()
    global_worker.client.kill_actor(actor._actor_id, no_restart=no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True) -> None:
    """Cancel the task that produces ``ref`` (reference
    ``python/ray/_private/worker.py:2573``).

    Queued tasks are dequeued; running tasks get a KeyboardInterrupt
    (``force=True`` SIGKILLs the worker instead — not allowed for actor
    tasks); finished tasks are untouched.  ``recursive`` also cancels
    tasks the cancelled task submitted.  Cancelled returns raise
    :class:`ray_tpu.exceptions.TaskCancelledError` on ``get``."""
    _ensure_connected()
    if not isinstance(ref, ObjectRef):
        raise TypeError(f"cancel() expects an ObjectRef, got {type(ref)}")
    global_worker.client.cancel_task(ref.binary(), force=force,
                                     recursive=recursive)


def cluster_resources() -> Dict[str, float]:
    _ensure_connected()
    snap = global_worker.client.state_snapshot()
    totals: Dict[str, float] = {}
    for res in snap["cluster_resources"].values():
        for k, v in res.items():
            totals[k] = totals.get(k, 0.0) + v
    return totals


def available_resources() -> Dict[str, float]:
    _ensure_connected()
    snap = global_worker.client.state_snapshot()
    totals: Dict[str, float] = {}
    for res in snap["available_resources"].values():
        for k, v in res.items():
            totals[k] = totals.get(k, 0.0) + v
    return totals


def nodes() -> List[dict]:
    _ensure_connected()
    snap = global_worker.client.state_snapshot()
    return [
        {"NodeID": n.node_id, "Alive": n.alive, "Resources": n.resources}
        for n in snap["nodes"]
    ]


def _ensure_connected() -> None:
    if not global_worker.connected:
        import threading

        if threading.current_thread() is not threading.main_thread():
            # a BACKGROUND thread (e.g. a stale poller from a torn-down
            # session) must never silently boot a fresh default head: that
            # zombie session would absorb every later init() in the process
            raise RuntimeError(
                "ray_tpu is not initialized (auto-init only runs on the "
                "main thread)")
        init()


# Convenience re-exports matching the reference's layout.
from ray_tpu.util.placement_group import (  # noqa: E402
    placement_group,
    remove_placement_group,
)

__all__ = [
    "ObjectRef",
    "ActorClass",
    "ActorHandle",
    "RemoteFunction",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "put",
    "get",
    "wait",
    "kill",
    "get_actor",
    "get_runtime_context",
    "cluster_resources",
    "available_resources",
    "nodes",
    "placement_group",
    "remove_placement_group",
    "exceptions",
]


def __getattr__(name):
    # Lazy AIR-style subpackages (no jax import unless used).
    import importlib

    if name in ("train", "tune", "data", "serve", "rllib", "air", "util", "models", "ops", "parallel", "cluster_utils", "experimental"):
        return importlib.import_module(f"ray_tpu.{name}")
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")
