"""LLM serving: iteration-level continuous batching over the KV-cache
decode kernels, behind a Serve deployment.

The reference's serving data plane stops at routing a request to a replica
(``python/ray/serve/_private/router.py:221`` -> ``replica.py:250``); token
generation is user code.  On TPU the generation loop IS the workload, so it
is part of the framework here:

- :class:`GenerationEngine` — Orca-style continuous batching: a fixed set
  of cache slots, prompt prefills admitted into free slots, one fused
  ``decode_chunk`` advancing every active slot per iteration.  New requests
  join between chunks; finished slots free mid-stream.  All device
  computations have static shapes (prompt buckets, fixed chunk length), so
  everything compiles exactly once per bucket.
- :func:`llm_deployment` — wraps the engine in a Serve deployment on a
  ``num_tpus`` replica; requests block on a future the engine thread
  resolves, so Serve's threaded replica concurrency (not the engine)
  bounds in-flight requests.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from functools import partial
from typing import Any, Dict, List, Optional

import numpy as np


class _Request:
    __slots__ = ("tokens", "max_new", "future", "emitted", "submitted_at")

    def __init__(self, tokens: List[int], max_new: int):
        self.tokens = list(tokens)
        self.max_new = int(max_new)
        self.future: Future = Future()
        self.emitted: List[int] = []
        self.submitted_at = time.perf_counter()


class GenerationEngine:
    """Continuous-batching decode engine over :mod:`ray_tpu.models.generate`.

    One background thread owns the device state (cache, last tokens); the
    public :meth:`submit` is thread-safe and returns a Future of the
    generated token list.
    """

    def __init__(
        self,
        cfg,
        params=None,
        *,
        n_slots: int = 4,
        max_new_tokens: int = 128,
        decode_chunk_steps: int = 16,
        prefill_buckets: tuple = (32, 64, 128, 256),
        temperature: float = 0.0,
        top_k: int = 0,
        eos_id: Optional[int] = None,
        seed: int = 0,
    ):
        import jax
        from ray_tpu.models import generate as gen

        self._gen = gen
        self.cfg = cfg
        if params is None:
            params = _default_init(cfg, seed)
        # inference-only params: pre-cast master f32 weights to the compute
        # dtype ONCE — the per-step .astype inside the blocks otherwise
        # re-reads the f32 copy every decode step (2x the HBM traffic of
        # the weights, which is the whole cost of a decode step)
        import jax.numpy as jnp

        self.params = jax.tree.map(
            lambda x: x.astype(cfg.dtype)
            if hasattr(x, "dtype") and x.dtype == jnp.float32 else x,
            params)
        self.n_slots = n_slots
        self.max_new_tokens = max_new_tokens
        self.chunk = decode_chunk_steps
        self.buckets = tuple(sorted(prefill_buckets))
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id

        max_len = self.buckets[-1] + max_new_tokens + decode_chunk_steps
        # one extra SCRATCH slot (index n_slots): batched admission pads
        # the prefill batch to a bucketed size and parks the padding rows
        # there, so admitting 1..n_slots requests costs ONE device dispatch
        # (each dispatch pays full tunnel latency on a remote-attached chip)
        self.cache = gen.init_cache(cfg, n_slots + 1, max_len)
        self._key = jax.random.PRNGKey(seed)

        # jitted kernels: one prefill per (bucket, batch-size) pair
        # (compiled lazily), one chunked decode.  cfg is closed over
        # (hashable frozen dataclass).
        self._prefill_jit = jax.jit(
            lambda params, toks, lens, cache, slots: gen.prefill_at(
                params, cfg, toks, lens, cache, slots),
            donate_argnums=(3,),  # scatter into the cache in place
        )
        self._decode_jit = jax.jit(
            partial(
                _decode_chunk_wrapper, gen, cfg,
                steps=decode_chunk_steps, temperature=temperature,
                top_k=top_k, eos_id=eos_id,
            ),
            donate_argnums=(1,),  # cache buffers reused in place
        )
        self._sample_jit = jax.jit(
            lambda logits, key: gen.sample_logits(
                logits, key, temperature=temperature, top_k=top_k))

        self._slots: List[Optional[_Request]] = [None] * n_slots
        self._last_tok = np.zeros((n_slots + 1,), np.int32)
        self._queue: List[_Request] = []
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serving metrics (Serve data-plane observability)
        self.total_generated = 0
        self.total_requests = 0

    # -- public API ----------------------------------------------------
    def _submit_req(self, tokens: List[int], max_new: Optional[int]) -> _Request:
        """Validate + enqueue (shared by submit and stream)."""
        if not tokens:
            raise ValueError("empty prompt")
        if len(tokens) > self.buckets[-1]:
            raise ValueError(
                f"prompt length {len(tokens)} exceeds the largest prefill "
                f"bucket {self.buckets[-1]}")
        req = _Request(tokens, min(max_new or self.max_new_tokens,
                                   self.max_new_tokens))
        with self._lock:
            self._queue.append(req)
            self.total_requests += 1
        self._work.set()
        return req

    def submit(self, tokens: List[int], max_new: Optional[int] = None) -> Future:
        return self._submit_req(tokens, max_new).future

    def generate(self, tokens: List[int], max_new: Optional[int] = None,
                 timeout: float = 300.0) -> List[int]:
        return self.submit(tokens, max_new).result(timeout)

    def stream(self, tokens: List[int], max_new: Optional[int] = None,
               timeout: float = 300.0):
        """Yield token ids AS THE ENGINE EMITS THEM (token streaming for
        serve's chunked responses).  Raises the request's error, if any."""
        req = self._submit_req(tokens, max_new)
        n = 0
        deadline = time.perf_counter() + timeout
        while True:
            emitted = req.emitted  # list append is atomic; len-snapshot safe
            m = len(emitted)
            while n < m:
                yield emitted[n]
                n += 1
            if req.future.done():
                for t in req.emitted[n:]:
                    yield t
                req.future.result()  # surface engine errors
                return
            if time.perf_counter() > deadline:
                raise TimeoutError("token stream timed out")
            time.sleep(0.02)

    def start(self) -> "GenerationEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="generation-engine")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "active_slots": sum(s is not None for s in self._slots),
                "queued": len(self._queue),
                "total_requests": self.total_requests,
                "total_generated_tokens": self.total_generated,
            }

    # -- engine loop ---------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                worked = self.step()
            except Exception as e:  # noqa: BLE001 — a kernel error (OOM,
                # bad request shape) must fail the affected requests, not
                # silently kill the engine thread and wedge the replica
                with self._lock:
                    victims = [s for s in self._slots if s is not None]
                    victims += self._queue
                    self._slots = [None] * self.n_slots
                    self._queue.clear()
                for req in victims:
                    if not req.future.done():
                        req.future.set_exception(e)
                worked = False
            if not worked:
                self._work.wait(timeout=0.05)
                self._work.clear()

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _admit(self) -> None:
        """Prefill queued prompts into ALL free slots with one device call
        (batch padded to a fixed n_slots width; padding rows target the
        scratch slot)."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            free = [i for i, s in enumerate(self._slots) if s is None]
            take = min(len(free), len(self._queue))
            if take == 0:
                return
            batch = [(free[j], self._queue.pop(0)) for j in range(take)]
            for slot, req in batch:
                self._slots[slot] = req
        b = self._bucket(max(len(r.tokens) for _, r in batch))
        # fixed admission width = n_slots: ONE compiled prefill program per
        # prompt bucket (variable widths recompiled mid-serving, which cost
        # far more than the padded rows' wasted FLOPs)
        n = self.n_slots
        toks = np.zeros((n, b), np.int32)
        toks[:, 0] = 1  # padding rows: 1-token dummy prompt
        lens = np.ones((n,), np.int32)
        slots = np.full((n,), self.n_slots, np.int32)  # scratch slot
        for j, (slot, req) in enumerate(batch):
            toks[j, :len(req.tokens)] = req.tokens
            lens[j] = len(req.tokens)
            slots[j] = slot
        last_logits, self.cache = self._prefill_jit(
            self.params, jnp.asarray(toks), jnp.asarray(lens),
            self.cache, jnp.asarray(slots))
        self._key, sub = jax.random.split(self._key)
        firsts = np.asarray(self._sample_jit(last_logits, sub))
        for j, (slot, req) in enumerate(batch):
            req.emitted.append(int(firsts[j]))
            self._last_tok[slot] = req.emitted[-1]
            self._finish_if_done(slot)

    def _finish_if_done(self, i: int) -> None:
        req = self._slots[i]
        if req is None:
            return
        done = len(req.emitted) >= req.max_new or (
            self.eos_id is not None and req.emitted
            and req.emitted[-1] == self.eos_id)
        if done:
            self._slots[i] = None
            self.total_generated += len(req.emitted)
            req.future.set_result(req.emitted)

    def step(self) -> bool:
        """One engine iteration: admit + one decode chunk.  Returns True if
        any work happened."""
        import jax.numpy as jnp

        self._admit()
        with self._lock:
            active_idx = [i for i, s in enumerate(self._slots) if s is not None]
        if not active_idx:
            return False
        active = np.zeros((self.n_slots + 1,), bool)  # scratch stays inactive
        active[active_idx] = True
        chunk, self.cache, _, self._key = self._decode_jit(
            self.params, self.cache, jnp.asarray(self._last_tok),
            jnp.asarray(active), self._key)
        chunk = np.asarray(chunk)  # [B, steps] — the once-per-chunk sync
        for i in active_idx:
            req = self._slots[i]
            for t in chunk[i]:
                t = int(t)
                req.emitted.append(t)
                if len(req.emitted) >= req.max_new or t == self.eos_id:
                    break
            self._last_tok[i] = req.emitted[-1]
            self._finish_if_done(i)
        return True


def _decode_chunk_wrapper(gen, cfg, params, cache, tokens, active, key, *,
                          steps, temperature, top_k, eos_id):
    return gen.decode_chunk(
        params, cfg, cache, tokens, active, key, steps=steps,
        temperature=temperature, top_k=top_k, eos_id=eos_id)


def _default_init(cfg, seed: int):
    import jax

    from ray_tpu.models import generate as gen

    fam = gen.family_of(cfg)
    if fam == "gpt2":
        from ray_tpu.models import gpt2 as m
    else:
        from ray_tpu.models import llama as m
    return m.init(cfg, jax.random.PRNGKey(seed))


def make_config(family: str = "gpt2", size: str = "tiny", **kw):
    if family == "gpt2":
        from ray_tpu.models.gpt2 import GPT2Config as C

        return C.gpt2_small(**kw) if size in ("small", "125m") else C.tiny(**kw)
    if family == "llama":
        from ray_tpu.models.llama import LlamaConfig as C

        return C.llama_125m(**kw) if size in ("small", "125m") else C.tiny(**kw)
    raise ValueError(f"unknown model family {family!r}")


def llm_deployment(
    family: str = "gpt2",
    size: str = "tiny",
    *,
    name: str = "llm",
    num_replicas: int = 1,
    num_tpus: float = 0,
    engine_kwargs: Optional[Dict[str, Any]] = None,
    config_kwargs: Optional[Dict[str, Any]] = None,
    max_concurrent_queries: int = 64,
):
    """Build a Serve deployment serving token generation with continuous
    batching (the ``num_tpus=1`` replica shape of BASELINE config 5, with
    the engine replacing the plain forward)."""
    from ray_tpu import serve

    ekw = dict(engine_kwargs or {})
    ckw = dict(config_kwargs or {})
    actor_opts: Dict[str, Any] = {"max_concurrency": max_concurrent_queries}
    if num_tpus:
        actor_opts["num_tpus"] = num_tpus

    @serve.deployment(
        name=name,
        num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries,
        ray_actor_options=actor_opts,
    )
    class LLMServer:
        def __init__(self):
            cfg = make_config(family, size, **ckw)
            self.engine = GenerationEngine(cfg, **ekw).start()

        def __call__(self, request):
            """request: {"tokens": [int, ...], "max_new_tokens": int,
            "stream": bool} -> {"tokens": generated ids}, or a token-per-
            line StreamingResponse when ``stream`` is set.  Blocks this
            replica thread; the engine interleaves all in-flight requests
            between chunks."""
            from ray_tpu.serve._private.http_util import Request as _HttpReq

            if isinstance(request, _HttpReq):
                request = request.json()
            if isinstance(request, (list, tuple)):
                request = {"tokens": list(request)}
            if request.get("stream"):
                from ray_tpu import serve as _serve

                gen = self.engine.stream(
                    request["tokens"], request.get("max_new_tokens"))
                return _serve.StreamingResponse(
                    (f"{t}\n" for t in gen), content_type="text/plain")
            toks = self.engine.generate(
                request["tokens"], request.get("max_new_tokens"))
            return {"tokens": toks}

        def stats(self):
            return self.engine.stats()

    return LLMServer
