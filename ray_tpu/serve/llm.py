"""LLM serving: iteration-level continuous batching over the KV-cache
decode kernels, behind a Serve deployment.

The reference's serving data plane stops at routing a request to a replica
(``python/ray/serve/_private/router.py:221`` -> ``replica.py:250``); token
generation is user code.  On TPU the generation loop IS the workload, so it
is part of the framework here:

- :class:`GenerationEngine` — Orca-style continuous batching: a fixed set
  of cache slots, prompt prefills admitted into free slots, one fused
  ``decode_chunk`` advancing every active slot per iteration.  New requests
  join between chunks; finished slots free mid-stream.  All device
  computations have static shapes (prompt buckets, fixed chunk length), so
  everything compiles exactly once per bucket.
- :func:`llm_deployment` — wraps the engine in a Serve deployment on a
  ``num_tpus`` replica; requests block on a future the engine thread
  resolves, so Serve's threaded replica concurrency (not the engine)
  bounds in-flight requests.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from functools import partial
from typing import Any, Dict, List, Optional

import numpy as np


class _Request:
    __slots__ = ("tokens", "max_new", "future", "emitted", "scheduled",
                 "submitted_at")

    def __init__(self, tokens: List[int], max_new: int):
        self.tokens = list(tokens)
        self.max_new = int(max_new)
        self.future: Future = Future()
        self.emitted: List[int] = []
        # tokens DISPATCHED for this request (prefill + chunks), maintained
        # at dispatch time — emitted lags one chunk behind in the pipeline,
        # so completion prediction must count scheduled, not emitted
        self.scheduled = 0
        self.submitted_at = time.perf_counter()


class _PendingChunk:
    """One dispatched-but-not-drained engine iteration: the device arrays
    (tokens already streaming host-ward via ``copy_to_host_async``) plus
    the host bookkeeping needed to route them when they land."""

    __slots__ = ("chunk_dev", "rows", "admissions", "firsts_dev")

    def __init__(self, chunk_dev, rows, admissions, firsts_dev):
        self.chunk_dev = chunk_dev          # [n_slots+1, steps] device
        self.rows = rows                    # [(slot, _Request)] active in chunk
        self.admissions = admissions        # [(row_j, slot, _Request)] this iter
        self.firsts_dev = firsts_dev        # [n_slots] device or None


class GenerationEngine:
    """Continuous-batching decode engine over :mod:`ray_tpu.models.generate`.

    One background thread owns the device state (cache, last tokens); the
    public :meth:`submit` is thread-safe and returns a Future of the
    generated token list.
    """

    def __init__(
        self,
        cfg,
        params=None,
        *,
        n_slots: int = 4,
        max_new_tokens: int = 128,
        decode_chunk_steps: int = 16,
        prefill_buckets: tuple = (32, 64, 128, 256),
        temperature: float = 0.0,
        top_k: int = 0,
        eos_id: Optional[int] = None,
        seed: int = 0,
    ):
        import jax
        from ray_tpu.models import generate as gen

        self._gen = gen
        self.cfg = cfg
        if params is None:
            params = _default_init(cfg, seed)
        # inference-only params: pre-cast master f32 weights to the compute
        # dtype ONCE — the per-step .astype inside the blocks otherwise
        # re-reads the f32 copy every decode step (2x the HBM traffic of
        # the weights, which is the whole cost of a decode step)
        import jax.numpy as jnp

        self.params = jax.tree.map(
            lambda x: x.astype(cfg.dtype)
            if hasattr(x, "dtype") and x.dtype == jnp.float32 else x,
            params)
        self.n_slots = n_slots
        self.max_new_tokens = max_new_tokens
        self.chunk = decode_chunk_steps
        self.buckets = tuple(sorted(prefill_buckets))
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id

        self._max_len = self.buckets[-1] + max_new_tokens + decode_chunk_steps
        # one extra SCRATCH slot (index n_slots): batched admission pads
        # the prefill batch to a bucketed size and parks the padding rows
        # there, so admitting 1..n_slots requests costs ONE device dispatch
        # (each dispatch pays full tunnel latency on a remote-attached chip)
        self.cache = gen.init_cache(cfg, n_slots + 1, self._max_len)
        self._key = jax.random.PRNGKey(seed)

        # jitted kernels: one prefill per (bucket, batch-size) pair
        # (compiled lazily), one chunked decode.  cfg is closed over
        # (hashable frozen dataclass).
        self._prefill_jit = jax.jit(
            lambda params, toks, lens, cache, slots: gen.prefill_at(
                params, cfg, toks, lens, cache, slots),
            donate_argnums=(3,),  # scatter into the cache in place
        )
        self._decode_jit = jax.jit(
            partial(
                _decode_chunk_wrapper, gen, cfg,
                steps=decode_chunk_steps, temperature=temperature,
                top_k=top_k, eos_id=eos_id,
            ),
            donate_argnums=(1,),  # cache buffers reused in place
        )
        self._sample_jit = jax.jit(
            lambda logits, key: gen.sample_logits(
                logits, key, temperature=temperature, top_k=top_k))
        # prefill's sampled first tokens fold into the device-resident
        # last-token row without a host round trip
        self._merge_jit = jax.jit(
            lambda last, slots, firsts: last.at[slots].set(firsts))

        self._slots: List[Optional[_Request]] = [None] * n_slots
        # device-resident last token per slot: decode chunk N+1 chains off
        # chunk N's output ON DEVICE, so dispatching N+1 never waits for
        # N's tokens to reach the host
        self._last_tok_dev = jnp.zeros((n_slots + 1,), jnp.int32)
        self._pending: Optional[_PendingChunk] = None
        self._draining: Optional[_PendingChunk] = None  # mid-_drain record
        self._queue: List[_Request] = []
        self._lock = threading.Lock()
        self._work = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # serving metrics (Serve data-plane observability)
        self.total_generated = 0
        self.total_requests = 0

    # -- public API ----------------------------------------------------
    def _submit_req(self, tokens: List[int], max_new: Optional[int]) -> _Request:
        """Validate + enqueue (shared by submit and stream)."""
        if not tokens:
            raise ValueError("empty prompt")
        if len(tokens) > self.buckets[-1]:
            raise ValueError(
                f"prompt length {len(tokens)} exceeds the largest prefill "
                f"bucket {self.buckets[-1]}")
        req = _Request(tokens, min(max_new or self.max_new_tokens,
                                   self.max_new_tokens))
        with self._lock:
            self._queue.append(req)
            self.total_requests += 1
        self._work.set()
        return req

    def submit(self, tokens: List[int], max_new: Optional[int] = None) -> Future:
        return self._submit_req(tokens, max_new).future

    def generate(self, tokens: List[int], max_new: Optional[int] = None,
                 timeout: float = 300.0) -> List[int]:
        return self.submit(tokens, max_new).result(timeout)

    def stream(self, tokens: List[int], max_new: Optional[int] = None,
               timeout: float = 300.0):
        """Yield token ids AS THE ENGINE EMITS THEM (token streaming for
        serve's chunked responses).  Raises the request's error, if any."""
        req = self._submit_req(tokens, max_new)
        n = 0
        deadline = time.perf_counter() + timeout
        while True:
            emitted = req.emitted  # list append is atomic; len-snapshot safe
            m = len(emitted)
            while n < m:
                yield emitted[n]
                n += 1
            if req.future.done():
                for t in req.emitted[n:]:
                    yield t
                req.future.result()  # surface engine errors
                return
            if time.perf_counter() > deadline:
                raise TimeoutError("token stream timed out")
            time.sleep(0.02)

    def start(self) -> "GenerationEngine":
        if self._thread is None:
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="generation-engine")
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._work.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            # cap-prediction frees slots at dispatch, so in-flight work
            # also lives in the undrained pipeline records — count unique
            # unresolved requests across both views
            inflight = {id(s): s for s in self._slots if s is not None}
            for rec in (self._pending, self._draining):
                if rec is not None:
                    inflight.update(
                        (id(r), r) for _, r in rec.rows
                        if not r.future.done())
            return {
                "active_slots": sum(s is not None for s in self._slots),
                "inflight_requests": len(inflight),
                "queued": len(self._queue),
                "total_requests": self.total_requests,
                "total_generated_tokens": self.total_generated,
            }

    # -- engine loop ---------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                worked = self.step()
            except Exception as e:  # noqa: BLE001 — a kernel error (OOM,
                # bad request shape) must fail the affected requests, not
                # silently kill the engine thread and wedge the replica
                import jax.numpy as jnp

                with self._lock:
                    victims = [s for s in self._slots if s is not None]
                    victims += self._queue
                    # BOTH in-flight pipeline records: a drain failure must
                    # also fail cap-freed requests that live only in the
                    # record being drained (they are in neither _slots nor
                    # the newly dispatched _pending)
                    for rec in (self._pending, self._draining):
                        if rec is not None:
                            victims += [r for _, r in rec.rows]
                    self._slots = [None] * self.n_slots
                    self._queue.clear()
                    self._pending = None
                    self._draining = None
                for req in dict.fromkeys(victims):
                    if not req.future.done():
                        req.future.set_exception(e)
                # the donated cache lineage may be poisoned mid-pipeline;
                # restart from a fresh one so the engine survives
                self.cache = self._gen.init_cache(
                    self.cfg, self.n_slots + 1, self._max_len)
                self._last_tok_dev = jnp.zeros((self.n_slots + 1,), jnp.int32)
                worked = False
            if not worked:
                self._work.wait(timeout=0.05)
                self._work.clear()

    def _bucket(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def _admit(self):
        """Prefill queued prompts into ALL free slots with one device call
        (batch padded to a fixed n_slots width; padding rows target the
        scratch slot).  Returns ``(admissions, firsts_dev)`` — the sampled
        first tokens stay ON DEVICE (merged into the last-token row there);
        their values reach the host with the next chunk drain."""
        import jax
        import jax.numpy as jnp

        with self._lock:
            free = [i for i, s in enumerate(self._slots) if s is None]
            take = min(len(free), len(self._queue))
            if take == 0:
                return [], None
            batch = [(free[j], self._queue.pop(0)) for j in range(take)]
            for slot, req in batch:
                self._slots[slot] = req
        b = self._bucket(max(len(r.tokens) for _, r in batch))
        # fixed admission width = n_slots: ONE compiled prefill program per
        # prompt bucket (variable widths recompiled mid-serving, which cost
        # far more than the padded rows' wasted FLOPs)
        n = self.n_slots
        toks = np.zeros((n, b), np.int32)
        toks[:, 0] = 1  # padding rows: 1-token dummy prompt
        lens = np.ones((n,), np.int32)
        slots = np.full((n,), self.n_slots, np.int32)  # scratch slot
        for j, (slot, req) in enumerate(batch):
            toks[j, :len(req.tokens)] = req.tokens
            lens[j] = len(req.tokens)
            slots[j] = slot
        last_logits, self.cache = self._prefill_jit(
            self.params, jnp.asarray(toks), jnp.asarray(lens),
            self.cache, jnp.asarray(slots))
        self._key, sub = jax.random.split(self._key)
        firsts_dev = self._sample_jit(last_logits, sub)
        self._last_tok_dev = self._merge_jit(
            self._last_tok_dev, jnp.asarray(slots), firsts_dev)
        if hasattr(firsts_dev, "copy_to_host_async"):
            firsts_dev.copy_to_host_async()
        for _, req in batch:
            req.scheduled = 1  # the prefill's sampled first token
        admissions = [(j, slot, req) for j, (slot, req) in enumerate(batch)]
        return admissions, firsts_dev

    def step(self) -> bool:
        """One engine iteration, software-pipelined against the device:

        1. admit queued prompts into free slots (prefill, no readback)
        2. dispatch decode chunk N (chains off device-side last tokens)
        3. free slots whose request deterministically finishes in chunk N
           (cap-based — the HOST knows completion timing without seeing
           token values), so the next iteration's admission reuses them
           with zero idle chunks
        4. drain chunk N-1 (its ``copy_to_host_async`` transfer has been
           streaming since last iteration), resolve finished futures

        The drain of N-1 thus overlaps chunk N's device compute: steady
        state pays max(compute, transfer) per chunk instead of their sum —
        on a remote-attached chip (sync readback ~112ms) this is the
        difference between ~26%% and ~100%% of the kernel rate."""
        import jax.numpy as jnp

        admissions, firsts_dev = self._admit()
        with self._lock:
            rows = [(i, s) for i, s in enumerate(self._slots) if s is not None]
        dispatched = None
        if rows:
            active = np.zeros((self.n_slots + 1,), bool)  # scratch inactive
            active[[i for i, _ in rows]] = True
            chunk_dev, self.cache, self._last_tok_dev, self._key = (
                self._decode_jit(
                    self.params, self.cache, self._last_tok_dev,
                    jnp.asarray(active), self._key))
            if hasattr(chunk_dev, "copy_to_host_async"):
                chunk_dev.copy_to_host_async()
            dispatched = _PendingChunk(chunk_dev, rows, admissions, firsts_dev)
            # cap-based predicted completion: these slots are free for the
            # NEXT admission even though their token values haven't landed
            # (completion timing is deterministic; EOS only finishes a
            # request EARLIER, confirmed at drain)
            with self._lock:
                for i, req in rows:
                    req.scheduled = min(req.max_new, req.scheduled + self.chunk)
                    if req.scheduled >= req.max_new:
                        self._slots[i] = None
        prev, self._pending = self._pending, dispatched
        if prev is not None:
            self._draining = prev  # visible to _loop's error recovery
            self._drain(prev)
            self._draining = None
        return dispatched is not None or prev is not None

    def _drain(self, pending: _PendingChunk) -> None:
        """Materialize one landed chunk: route first tokens + chunk rows to
        their requests, resolve futures, confirm EOS slot frees."""
        if pending.firsts_dev is not None:
            firsts = np.asarray(pending.firsts_dev)
            for j, slot, req in pending.admissions:
                req.emitted.append(int(firsts[j]))
        chunk = np.asarray(pending.chunk_dev)  # transfer already in flight
        for i, req in pending.rows:
            if req.future.done():
                continue
            for t in chunk[i]:
                # check BEFORE append: the prefill's first token may already
                # have satisfied max_new (or been EOS) for this request
                if len(req.emitted) >= req.max_new or (
                        self.eos_id is not None and req.emitted
                        and req.emitted[-1] == self.eos_id):
                    break
                req.emitted.append(int(t))
            done = len(req.emitted) >= req.max_new or (
                self.eos_id is not None and req.emitted
                and req.emitted[-1] == self.eos_id)
            if done:
                with self._lock:
                    if self._slots[i] is req:  # EOS finish: slot not yet
                        self._slots[i] = None  # freed by cap prediction
                self.total_generated += len(req.emitted)
                req.future.set_result(req.emitted)


def _decode_chunk_wrapper(gen, cfg, params, cache, tokens, active, key, *,
                          steps, temperature, top_k, eos_id):
    emitted, cache, _active, key = gen.decode_chunk(
        params, cfg, cache, tokens, active, key, steps=steps,
        temperature=temperature, top_k=top_k, eos_id=eos_id)
    # chain the NEXT chunk off this one's final tokens without a host
    # round trip (inactive slots carry their input token through, so
    # emitted[:, -1] is correct for every slot)
    return emitted, cache, emitted[:, -1], key


def _default_init(cfg, seed: int):
    import jax

    from ray_tpu.models import generate as gen

    fam = gen.family_of(cfg)
    if fam == "gpt2":
        from ray_tpu.models import gpt2 as m
    else:
        from ray_tpu.models import llama as m
    return m.init(cfg, jax.random.PRNGKey(seed))


def make_config(family: str = "gpt2", size: str = "tiny", **kw):
    if family == "gpt2":
        from ray_tpu.models.gpt2 import GPT2Config as C

        return C.gpt2_small(**kw) if size in ("small", "125m") else C.tiny(**kw)
    if family == "llama":
        from ray_tpu.models.llama import LlamaConfig as C

        return C.llama_125m(**kw) if size in ("small", "125m") else C.tiny(**kw)
    raise ValueError(f"unknown model family {family!r}")


def llm_deployment(
    family: str = "gpt2",
    size: str = "tiny",
    *,
    name: str = "llm",
    num_replicas: int = 1,
    num_tpus: float = 0,
    engine_kwargs: Optional[Dict[str, Any]] = None,
    config_kwargs: Optional[Dict[str, Any]] = None,
    max_concurrent_queries: int = 64,
):
    """Build a Serve deployment serving token generation with continuous
    batching (the ``num_tpus=1`` replica shape of BASELINE config 5, with
    the engine replacing the plain forward)."""
    from ray_tpu import serve

    ekw = dict(engine_kwargs or {})
    ckw = dict(config_kwargs or {})
    actor_opts: Dict[str, Any] = {"max_concurrency": max_concurrent_queries}
    if num_tpus:
        actor_opts["num_tpus"] = num_tpus

    @serve.deployment(
        name=name,
        num_replicas=num_replicas,
        max_concurrent_queries=max_concurrent_queries,
        ray_actor_options=actor_opts,
    )
    class LLMServer:
        def __init__(self):
            cfg = make_config(family, size, **ckw)
            self.engine = GenerationEngine(cfg, **ekw).start()

        def __call__(self, request):
            """request: {"tokens": [int, ...], "max_new_tokens": int,
            "stream": bool} -> {"tokens": generated ids}, or a token-per-
            line StreamingResponse when ``stream`` is set.  Blocks this
            replica thread; the engine interleaves all in-flight requests
            between chunks."""
            from ray_tpu.serve._private.http_util import Request as _HttpReq

            if isinstance(request, _HttpReq):
                request = request.json()
            if isinstance(request, (list, tuple)):
                request = {"tokens": list(request)}
            if request.get("stream"):
                from ray_tpu import serve as _serve

                gen = self.engine.stream(
                    request["tokens"], request.get("max_new_tokens"))
                return _serve.StreamingResponse(
                    (f"{t}\n" for t in gen), content_type="text/plain")
            toks = self.engine.generate(
                request["tokens"], request.get("max_new_tokens"))
            return {"tokens": toks}

        def stats(self):
            return self.engine.stats()

    return LLMServer
