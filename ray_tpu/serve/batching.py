"""@serve.batch — transparent request batching inside a replica.

Analog of the reference's ``python/ray/serve/batching.py``: concurrent
calls to the decorated method are collected into one list and executed by
a single underlying call; each caller gets its own element back.  On TPU
this is the difference between N single-row model invocations and one
batched MXU-shaped forward — the central trick of TPU serving.

Replicas whose callable uses ``@serve.batch`` are created with
``max_concurrency = max_concurrent_queries`` (the controller detects the
decorator), so requests arrive on concurrent executor threads.  A
dedicated batcher thread per decorated callable collects them: callers
enqueue and park; the batcher waits up to ``batch_wait_timeout_s`` from
the first queued item (returning early at ``max_batch_size``), runs the
wrapped function once on the list, and distributes results.  With the
default ``max_concurrent_batches=1`` all user code runs on the single
batcher thread, so deployment state needs no locking; raising it runs up
to K batches on concurrent executor threads — the decorated function
must then be thread-safe (pure jit-apply functions are).
"""

from __future__ import annotations

import functools
import inspect
import threading
import time
from typing import Callable, List, Optional

BATCH_ATTR = "_ray_tpu_serve_batch"


class _Slot:
    __slots__ = ("item", "event", "result", "error", "enqueued_at")

    def __init__(self, item):
        self.item = item
        self.event = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.enqueued_at = time.monotonic()


class _Batcher:
    """One collector thread per decorated callable (replica-side only —
    never pickled; built lazily on first call).

    ``max_concurrent_batches > 1`` lets the collector hand batch N+1 to a
    worker thread while batch N is still executing.  On a TPU whose host
    round trip dominates (remote-attached chips: a sync readback costs
    ~100 ms regardless of size), overlapping batches is the difference
    between ``batch/rtt`` and ``batch*K/rtt`` throughput — the device
    serializes the actual compute either way."""

    def __init__(self, run_fn: Callable[[List], List], max_batch_size: int,
                 timeout_s: float, max_concurrent_batches: int = 1):
        self._run_fn = run_fn
        self._max = max_batch_size
        self._timeout = timeout_s
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._queue: List[_Slot] = []
        self._thread_started = False
        self._inflight_sem = threading.Semaphore(max(1, max_concurrent_batches))
        # K>1: daemon executor threads over a queue (not ThreadPoolExecutor,
        # whose non-daemon threads would leak per deploy and whose atexit
        # join wedges worker shutdown if a batch ever hangs)
        self._exec_queue = None
        self._n_exec_threads = max(1, max_concurrent_batches)

    def submit(self, item):
        slot = _Slot(item)
        with self._nonempty:
            if not self._thread_started:
                # lazily here, not in __init__: racing first callers may
                # each construct a _Batcher and only setdefault's winner
                # survives — an eagerly-started loser thread would park on
                # its empty queue forever
                self._thread_started = True
                threading.Thread(
                    target=self._loop, daemon=True, name="serve-batcher"
                ).start()
            self._queue.append(slot)
            self._nonempty.notify()
        slot.event.wait()
        if slot.error is not None:
            raise slot.error
        return slot.result

    def _loop(self) -> None:
        while True:
            with self._nonempty:
                while not self._queue:
                    self._nonempty.wait()
                # the batch window opens when the OLDEST item was enqueued
                # (items that aged while the previous batch executed don't
                # pay a fresh full wait); predicate loop guards against
                # spurious wakeups forming tiny batches
                deadline = self._queue[0].enqueued_at + self._timeout
                while len(self._queue) < self._max:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    self._nonempty.wait(remaining)
                batch = self._queue[: self._max]
                del self._queue[: len(batch)]
            # bounds in-flight batches; with K=1 this serializes execution
            # on this collector thread exactly as before
            self._inflight_sem.acquire()
            if self._n_exec_threads == 1:
                self._execute(batch)
            else:
                if self._exec_queue is None:
                    import queue as queue_mod

                    self._exec_queue = queue_mod.Queue()
                    for i in range(self._n_exec_threads):
                        threading.Thread(
                            target=self._exec_loop, daemon=True,
                            name=f"serve-batch-exec-{i}",
                        ).start()
                self._exec_queue.put(batch)

    def _exec_loop(self) -> None:
        while True:
            self._execute(self._exec_queue.get())

    def _execute(self, batch: List[_Slot]) -> None:
        try:
            results = self._run_fn([s.item for s in batch])
            if len(results) != len(batch):
                # caught by the BaseException arm on purpose: the error
                # rides s.error to every waiting caller and re-raises there
                # raylint: disable=R2
                raise ValueError(
                    f"@serve.batch function returned {len(results)} "
                    f"results for a batch of {len(batch)}"
                )
            for s, r in zip(batch, results):
                s.result = r
        except BaseException as e:  # noqa: BLE001 — every caller must wake
            for s in batch:
                s.error = e
        finally:
            self._inflight_sem.release()
            for s in batch:
                s.event.set()


def uses_batching(func_or_class) -> bool:
    """True if the deployment callable (class or function) carries any
    @serve.batch-decorated entry point — the controller keys replica
    concurrency on this."""
    if getattr(func_or_class, BATCH_ATTR, False):
        return True
    if isinstance(func_or_class, type):
        # dir() walks the MRO — inherited @serve.batch methods count too
        return any(
            getattr(getattr(func_or_class, name, None), BATCH_ATTR, False)
            for name in dir(func_or_class)
        )
    return False


def batch(_func: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01, max_concurrent_batches: int = 1):
    """Decorate a replica method (or function deployment) taking a LIST of
    requests::

        @serve.deployment(max_concurrent_queries=32)
        class Model:
            @serve.batch(max_batch_size=16, batch_wait_timeout_s=0.005)
            def __call__(self, requests):           # list in ...
                return self.model(np.stack(requests)).tolist()  # list out

    ``max_concurrent_batches=K`` (default 1) overlaps up to K batch
    executions on concurrent threads — use when per-batch latency is
    dominated by device round trips rather than compute (remote-attached
    TPUs), and only if the decorated function is thread-safe.
    """
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1")

    def deco(fn: Callable):
        # the batcher holds a lock + thread, so it must be created lazily
        # replica-side (cloudpickle ships the decorated def before any call)
        params = list(inspect.signature(fn).parameters)
        is_method = bool(params) and params[0] == "self"
        attr = f"__serve_batcher_{fn.__name__}"

        if is_method:

            @functools.wraps(fn)
            def wrapper(self, request):
                b = self.__dict__.get(attr)
                if b is None:
                    # dict.setdefault is atomic: racing first calls keep one
                    b = self.__dict__.setdefault(
                        attr,
                        _Batcher(lambda items: fn(self, items),
                                 max_batch_size, batch_wait_timeout_s,
                                 max_concurrent_batches),
                    )
                return b.submit(request)
        else:

            @functools.wraps(fn)
            def wrapper(request):
                b = wrapper.__dict__.get(attr)
                if b is None:
                    b = wrapper.__dict__.setdefault(
                        attr,
                        _Batcher(fn, max_batch_size, batch_wait_timeout_s,
                                 max_concurrent_batches),
                    )
                return b.submit(request)

        setattr(wrapper, BATCH_ATTR, True)
        return wrapper

    if _func is not None:
        return deco(_func)
    return deco
