"""Declarative Serve config schema.

Analog of ``python/ray/serve/schema.py:1`` (ServeDeploySchema /
ServeApplicationSchema / DeploymentSchema, pydantic there): a validated
JSON/YAML shape for deploying applications from config instead of code::

    applications:
      - name: default
        import_path: my_pkg.app:graph        # module:attr -> Application
        route_prefix: /api
        deployments:                          # per-deployment overrides
          - name: Model
            num_replicas: 2
            max_concurrent_queries: 32
            user_config: {threshold: 0.5}

Submitted over REST (``PUT /api/serve/applications`` — serve_head.py
analog) or ``python -m ray_tpu serve-deploy config.yaml``; the controller
reconciles live state to it and ``serve status`` reports goal vs actual.

Validation is plain dataclasses + explicit checks (no pydantic in the
image); errors carry the offending path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

_UNSET = "__unset__"


class SchemaError(ValueError):
    pass


@dataclasses.dataclass
class DeploymentSchema:
    name: str
    num_replicas: Optional[int] = None
    max_concurrent_queries: Optional[int] = None
    user_config: Any = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    route_prefix: Any = _UNSET


@dataclasses.dataclass
class ServeApplicationSchema:
    import_path: str
    name: str = "default"
    route_prefix: Any = _UNSET
    runtime_env: Dict[str, Any] = dataclasses.field(default_factory=dict)
    deployments: List[DeploymentSchema] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class ServeDeploySchema:
    applications: List[ServeApplicationSchema] = dataclasses.field(
        default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _expect(cond: bool, path: str, msg: str) -> None:
    if not cond:
        raise SchemaError(f"{path}: {msg}")


def parse_deploy_config(raw: Dict[str, Any]) -> ServeDeploySchema:
    """Validate a config dict into a ServeDeploySchema (raises
    :class:`SchemaError` naming the offending field)."""
    _expect(isinstance(raw, dict), "$", f"expected an object, got {type(raw).__name__}")
    apps_raw = raw.get("applications")
    _expect(isinstance(apps_raw, list),
            "applications", "required list (empty = delete all config apps)")
    apps: List[ServeApplicationSchema] = []
    seen_names: set = set()
    for i, app_raw in enumerate(apps_raw):
        path = f"applications[{i}]"
        _expect(isinstance(app_raw, dict), path, "expected an object")
        unknown = set(app_raw) - {
            "import_path", "name", "route_prefix", "runtime_env", "deployments"}
        _expect(not unknown, path, f"unknown fields {sorted(unknown)}")
        import_path = app_raw.get("import_path")
        _expect(isinstance(import_path, str) and ":" in import_path,
                f"{path}.import_path",
                "required 'module.sub:attr' string")
        name = app_raw.get("name", "default")
        _expect(isinstance(name, str) and name, f"{path}.name", "non-empty string")
        _expect(name not in seen_names, f"{path}.name", f"duplicate app name {name!r}")
        seen_names.add(name)
        route_prefix = app_raw.get("route_prefix", _UNSET)
        if route_prefix not in (_UNSET, None):
            _expect(isinstance(route_prefix, str) and route_prefix.startswith("/"),
                    f"{path}.route_prefix", "must start with '/' (or be null)")
        runtime_env = app_raw.get("runtime_env") or {}
        _expect(isinstance(runtime_env, dict), f"{path}.runtime_env", "expected object")
        deployments: List[DeploymentSchema] = []
        for j, d_raw in enumerate(app_raw.get("deployments") or []):
            dpath = f"{path}.deployments[{j}]"
            _expect(isinstance(d_raw, dict), dpath, "expected an object")
            unknown = set(d_raw) - {
                "name", "num_replicas", "max_concurrent_queries", "user_config",
                "ray_actor_options", "autoscaling_config", "route_prefix"}
            _expect(not unknown, dpath, f"unknown fields {sorted(unknown)}")
            dname = d_raw.get("name")
            _expect(isinstance(dname, str) and dname, f"{dpath}.name",
                    "required non-empty string")
            nr = d_raw.get("num_replicas")
            _expect(nr is None or (isinstance(nr, int) and nr >= 0),
                    f"{dpath}.num_replicas", "must be an int >= 0")
            mcq = d_raw.get("max_concurrent_queries")
            _expect(mcq is None or (isinstance(mcq, int) and mcq >= 1),
                    f"{dpath}.max_concurrent_queries", "must be an int >= 1")
            rao = d_raw.get("ray_actor_options")
            _expect(rao is None or isinstance(rao, dict),
                    f"{dpath}.ray_actor_options", "expected object")
            asc = d_raw.get("autoscaling_config")
            _expect(asc is None or isinstance(asc, dict),
                    f"{dpath}.autoscaling_config", "expected object")
            deployments.append(DeploymentSchema(
                name=dname, num_replicas=nr, max_concurrent_queries=mcq,
                user_config=d_raw.get("user_config"),
                ray_actor_options=rao, autoscaling_config=asc,
                route_prefix=d_raw.get("route_prefix", _UNSET)))
        apps.append(ServeApplicationSchema(
            import_path=import_path, name=name, route_prefix=route_prefix,
            runtime_env=runtime_env, deployments=deployments))
    return ServeDeploySchema(applications=apps)


def import_target(import_path: str):
    """Resolve 'module.sub:attr' to the bound Application (or Deployment,
    which is bound with no args)."""
    import importlib

    mod_name, _, attr = import_path.partition(":")
    target = getattr(importlib.import_module(mod_name), attr)
    from ray_tpu.serve.api import Application, Deployment

    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise SchemaError(
            f"{import_path} resolved to {type(target).__name__}; expected a "
            "bound Application (call .bind()) or a Deployment")
    return target
