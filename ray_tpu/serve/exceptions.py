"""Serve-specific exception types (``python/ray/serve/exceptions.py``
analog).

These cross process boundaries: a replica raises
:class:`ReplicaDrainingError`, the worker wraps it in ``RayTaskError``
(with ``cause`` preserved through pickling), and the ingress unwraps it to
decide retryability.  Keep them dependency-free and picklable.
"""

from __future__ import annotations


class RayServeException(Exception):
    """Base class for serve control/data-plane errors."""


class BackPressureError(RayServeException):
    """The router's queued-request backlog crossed ``max_queued_requests``.

    Raised *instead of* queueing: the caller gets an immediate, cheap
    signal that the deployment is saturated.  The HTTP ingress maps this
    to ``503`` with a ``Retry-After`` header; handle callers can catch it
    and apply their own backoff.
    """

    def __init__(self, deployment: str, queued: int, limit: int,
                 retry_after_s: float = 1.0):
        super().__init__(
            f"deployment {deployment!r} is shedding load: {queued} requests "
            f"already queued (max_queued_requests={limit})")
        self.deployment = deployment
        self.queued = queued
        self.limit = limit
        self.retry_after_s = retry_after_s

    def __reduce__(self):
        return (BackPressureError,
                (self.deployment, self.queued, self.limit,
                 self.retry_after_s))


class ReplicaDrainingError(RayServeException):
    """The chosen replica is draining and no longer accepts new requests.

    Only a membership race can hit this (the controller pulls a draining
    replica out of the routing set *before* telling it to drain), so the
    request was never executed — it is safe to re-assign regardless of
    idempotency.
    """

    def __init__(self, replica_tag: str = "?"):
        super().__init__(
            f"replica {replica_tag!r} is draining and accepts no new "
            "requests")
        self.replica_tag = replica_tag

    def __reduce__(self):
        return (ReplicaDrainingError, (self.replica_tag,))
