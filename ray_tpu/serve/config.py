"""Serve configuration dataclasses.

Analog of the reference's ``python/ray/serve/config.py`` (DeploymentConfig,
HTTPOptions) — the declarative half of a deployment: replica count, queue
caps, actor resources, and the HTTP front door.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Demand-driven replica scaling (``serve/config.py`` AutoscalingConfig +
    ``_private/autoscaling_policy.py`` analog).  The controller aggregates
    ongoing-request counts reported by routers and sizes the replica set to
    ``total_ongoing / target_num_ongoing_requests_per_replica``, smoothed by
    the up/downscale delays."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0
    # router metric reports older than this are dropped from the aggregate
    look_back_period_s: float = 10.0

    def validate(self) -> None:
        if self.min_replicas < 0 or self.max_replicas < max(1, self.min_replicas):
            raise ValueError(
                "need 0 <= min_replicas <= max_replicas (and max_replicas >= 1)"
            )
        if self.target_num_ongoing_requests_per_replica <= 0:
            raise ValueError("target_num_ongoing_requests_per_replica must be > 0")


@dataclass
class DeploymentConfig:
    """Goal-state knobs the controller reconciles toward
    (``serve/config.py`` DeploymentConfig analog)."""

    num_replicas: int = 1
    max_concurrent_queries: int = 100
    user_config: Optional[Any] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    health_check_period_s: float = 2.0
    graceful_shutdown_timeout_s: float = 10.0
    autoscaling_config: Optional[AutoscalingConfig] = None
    # Load-shedding watermark: a router whose queued (not-yet-assigned)
    # backlog reaches this sheds with BackPressureError instead of queueing
    # (HTTP: 503 + Retry-After).  -1 = unbounded (the pre-shedding
    # behavior handle callers rely on); the reference's handle-API knob of
    # the same name also defaults unbounded.
    max_queued_requests: int = -1
    # Default per-request deadline the HTTP ingress applies when the
    # client sends no X-Serve-Deadline-S header.  None = the ingress
    # default (INGRESS_DEFAULT_TIMEOUT_S).
    request_timeout_s: Optional[float] = None

    def validate(self) -> None:
        if self.num_replicas < 0:
            raise ValueError("num_replicas must be >= 0")
        if self.max_concurrent_queries <= 0:
            raise ValueError("max_concurrent_queries must be > 0")
        if self.max_queued_requests < -1 or self.max_queued_requests == 0:
            raise ValueError(
                "max_queued_requests must be -1 (unbounded) or > 0")
        if self.request_timeout_s is not None and self.request_timeout_s <= 0:
            raise ValueError("request_timeout_s must be > 0")
        if self.autoscaling_config is not None:
            self.autoscaling_config.validate()


# How long routers/proxies trust a cached routing snapshot before re-pulling
# from the controller (the poll-TTL stand-in for the reference's long-poll).
ROUTE_TABLE_TTL_S = 1.0

# Consecutive replica-start failures before the controller stops retrying a
# deployment and marks it UNHEALTHY (deployment_state's backoff analog).
MAX_CONSECUTIVE_START_FAILURES = 3

# One controller pull on the routing path.  Deliberately short: a stalled
# controller must cost a request at most this much before the router falls
# back to its stale table and retries in the background.
ROUTING_PULL_TIMEOUT_S = 5.0
# Routing-refresh failure backoff (MetricsPusher-style bounded retry): the
# stale table keeps serving while retries space out base * 2^n up to cap.
REFRESH_BACKOFF_BASE_S = 0.2
REFRESH_BACKOFF_CAP_S = 5.0

# Ingress request defaults.  Every HTTP request carries a deadline: the
# client's X-Serve-Deadline-S header, else the deployment's
# request_timeout_s, else this.
INGRESS_DEFAULT_TIMEOUT_S = 60.0
# Replica-death retries per request (idempotent requests only); each retry
# re-assigns to a live replica under the same deadline.
INGRESS_MAX_RETRIES = 3
# Retry-After value (seconds) sent with shedding 503s.
SHED_RETRY_AFTER_S = 1.0


def async_ingress_enabled() -> bool:
    """The asyncio front door is the default; ``RAY_TPU_SERVE_ASYNC=0`` is
    the escape hatch back to the stdlib ThreadingHTTPServer proxy."""
    import os

    return os.environ.get("RAY_TPU_SERVE_ASYNC", "1") not in (
        "0", "false", "no")


@dataclass
class HTTPOptions:
    """HTTP proxy options (``serve/config.py`` HTTPOptions analog)."""

    host: str = "127.0.0.1"
    port: int = 8000
    # port=0 binds an ephemeral port (test-friendly on shared machines)
    # None -> follow RAY_TPU_SERVE_ASYNC (default on); False forces the
    # legacy threaded proxy for this instance only
    async_ingress: Optional[bool] = None
    # request-executor threads for the asyncio ingress (blocking
    # router/get work runs here; connections themselves cost no thread)
    num_exec_threads: Optional[int] = None
    # proxy-wide in-flight watermark: requests past it shed 503 straight
    # from the event loop (None -> 2x exec threads)
    max_inflight_requests: Optional[int] = None


@dataclass
class ReplicaState:
    """One replica's lifecycle state as the controller tracks it
    (``_private/common.py`` ReplicaState analog)."""

    STARTING = "STARTING"
    RUNNING = "RUNNING"
    # out of the routing set, finishing accepted work before termination
    DRAINING = "DRAINING"
    STOPPING = "STOPPING"
    DEAD = "DEAD"
