"""Serve configuration dataclasses.

Analog of the reference's ``python/ray/serve/config.py`` (DeploymentConfig,
HTTPOptions) — the declarative half of a deployment: replica count, queue
caps, actor resources, and the HTTP front door.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Demand-driven replica scaling (``serve/config.py`` AutoscalingConfig +
    ``_private/autoscaling_policy.py`` analog).  The controller aggregates
    ongoing-request counts reported by routers and sizes the replica set to
    ``total_ongoing / target_num_ongoing_requests_per_replica``, smoothed by
    the up/downscale delays."""

    min_replicas: int = 1
    max_replicas: int = 4
    target_num_ongoing_requests_per_replica: float = 1.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0
    # router metric reports older than this are dropped from the aggregate
    look_back_period_s: float = 10.0

    def validate(self) -> None:
        if self.min_replicas < 0 or self.max_replicas < max(1, self.min_replicas):
            raise ValueError(
                "need 0 <= min_replicas <= max_replicas (and max_replicas >= 1)"
            )
        if self.target_num_ongoing_requests_per_replica <= 0:
            raise ValueError("target_num_ongoing_requests_per_replica must be > 0")


@dataclass
class DeploymentConfig:
    """Goal-state knobs the controller reconciles toward
    (``serve/config.py`` DeploymentConfig analog)."""

    num_replicas: int = 1
    max_concurrent_queries: int = 100
    user_config: Optional[Any] = None
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)
    health_check_period_s: float = 2.0
    graceful_shutdown_timeout_s: float = 10.0
    autoscaling_config: Optional[AutoscalingConfig] = None

    def validate(self) -> None:
        if self.num_replicas < 0:
            raise ValueError("num_replicas must be >= 0")
        if self.max_concurrent_queries <= 0:
            raise ValueError("max_concurrent_queries must be > 0")
        if self.autoscaling_config is not None:
            self.autoscaling_config.validate()


# How long routers/proxies trust a cached routing snapshot before re-pulling
# from the controller (the poll-TTL stand-in for the reference's long-poll).
ROUTE_TABLE_TTL_S = 1.0

# Consecutive replica-start failures before the controller stops retrying a
# deployment and marks it UNHEALTHY (deployment_state's backoff analog).
MAX_CONSECUTIVE_START_FAILURES = 3


@dataclass
class HTTPOptions:
    """HTTP proxy options (``serve/config.py`` HTTPOptions analog)."""

    host: str = "127.0.0.1"
    port: int = 8000
    # port=0 binds an ephemeral port (test-friendly on shared machines)


@dataclass
class ReplicaState:
    """One replica's lifecycle state as the controller tracks it
    (``_private/common.py`` ReplicaState analog)."""

    STARTING = "STARTING"
    RUNNING = "RUNNING"
    STOPPING = "STOPPING"
    DEAD = "DEAD"
