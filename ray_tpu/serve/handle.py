"""DeploymentHandle: call a deployment from Python.

Analog of the reference's ``serve/handle.py`` (RayServeHandle /
RayServeSyncHandle): ``handle.remote(*args)`` routes a ``__call__`` request
through a Router and returns an ObjectRef; ``handle.method.remote(...)``
targets a named method.  Handles pickle (deployment composition passes them
into other replicas' constructors) and rebuild their Router lazily in the
destination process.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, Optional, Tuple

# weak values: a Router lives only while some handle references it, so a
# deleted deployment's router (and its background threads, which hold only a
# weakref) unwinds once its handles are dropped
_router_cache: "weakref.WeakValueDictionary[Tuple[str, str], Any]" = (
    weakref.WeakValueDictionary()
)
_router_cache_lock = threading.Lock()


class _MethodCaller:
    __slots__ = ("_handle", "_method")

    def __init__(self, handle: "DeploymentHandle", method: str):
        self._handle = handle
        self._method = method

    def remote(self, *args, **kwargs):
        return self._handle._remote(self._method, args, kwargs)


class DeploymentHandle:
    def __init__(self, deployment_name: str, controller_handle=None,
                 method_name: Optional[str] = None):
        self.deployment_name = deployment_name
        self._controller = controller_handle
        self._router = None
        self._method_name = method_name  # options(method_name=...) override

    # -- plumbing ------------------------------------------------------
    def _get_router(self):
        if self._router is None:
            from ray_tpu.serve._private.router import Router

            if self._controller is None:
                import ray_tpu
                from ray_tpu.serve._private.controller import (
                    CONTROLLER_NAME, SERVE_NAMESPACE)

                self._controller = ray_tpu.get_actor(
                    CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
            # one Router per (controller, deployment) per process: handles
            # are cheap to churn, and each Router owns background
            # listener/metrics threads that must stay bounded
            key = (self._controller._id_hex, self.deployment_name)
            with _router_cache_lock:
                router = _router_cache.get(key)
                if router is None:
                    router = Router(self._controller, self.deployment_name)
                    _router_cache[key] = router
            self._router = router
        return self._router

    def _remote(self, method: str, args, kwargs):
        return self._get_router().assign_request(method, args, kwargs)

    # -- public --------------------------------------------------------
    def remote(self, *args, **kwargs):
        """Route one request to ``__call__`` (or the ``options``-selected
        method); returns an ObjectRef."""
        return self._remote(self._method_name or "__call__", args, kwargs)

    def __getattr__(self, item: str) -> _MethodCaller:
        if item.startswith("_") or item in ("deployment_name",):
            raise AttributeError(item)
        return _MethodCaller(self, item)

    def options(self, *, method_name: Optional[str] = None,
                **kwargs) -> "DeploymentHandle":
        """A copy of this handle with options applied.  ``method_name``
        retargets ``.remote()`` at a named replica method (equivalent to
        attribute access, but composable — the reference's
        ``handle.options(method_name=...)``).  Unknown options raise
        instead of being silently dropped."""
        if kwargs:
            raise ValueError(
                f"unknown DeploymentHandle options: {sorted(kwargs)} "
                f"(supported: method_name)")
        h = DeploymentHandle(self.deployment_name, self._controller,
                             method_name=method_name)
        h._router = self._router  # share the cached per-process router
        return h

    def __reduce__(self):
        # Router state is per-process; rebuild lazily on the other side.
        return (DeploymentHandle,
                (self.deployment_name, self._controller, self._method_name))

    # Handles to the same deployment (with the same options) are
    # interchangeable; the controller's code-change diff relies on this
    # (fresh handle instances are created on every deploy of a composed
    # app — those carry no method override, so its comparisons are
    # unchanged).  A method-retargeted handle is behaviorally different
    # and must not dedup against the plain one.
    def __eq__(self, other):
        return (
            isinstance(other, DeploymentHandle)
            and other.deployment_name == self.deployment_name
            and other._method_name == self._method_name
        )

    def __hash__(self):
        return hash(("DeploymentHandle", self.deployment_name,
                     self._method_name))

    def __repr__(self) -> str:
        return f"DeploymentHandle({self.deployment_name!r})"
