"""ServeReplica: the actor hosting one copy of a deployment's callable.

Analog of ``python/ray/serve/_private/replica.py:250`` (RayServeReplica):
constructs the user's class (or wraps a function), executes requests,
applies ``user_config`` through ``reconfigure``, and answers health checks.
TPU-backed deployments get here with ``ray_actor_options={"num_tpus": 1}``
so the scheduler pins a chip before the model loads.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import cloudpickle


class ServeReplica:
    def __init__(
        self,
        deployment_name: str,
        replica_tag: str,
        serialized_def: bytes,
        init_args: Tuple,
        init_kwargs: Dict,
        user_config: Optional[Any] = None,
    ):
        self.deployment_name = deployment_name
        self.replica_tag = replica_tag
        func_or_class = cloudpickle.loads(serialized_def)
        if isinstance(func_or_class, type):
            self.callable = func_or_class(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self.callable = func_or_class
            self._is_function = True
        if user_config is not None:
            self.reconfigure(user_config)
        # lock-guarded: batched replicas serve requests from concurrent
        # threads, and a bare += (or a max() read-modify-write) can lose or
        # regress counts under preemption
        import threading

        self._stats_lock = threading.Lock()
        self._num_requests = 0
        self._start_time = time.time()

    def handle_request(self, method_name: str, args: Tuple, kwargs: Dict) -> Any:
        """Run one request (``replica.py:250`` handle_request analog).
        ``method_name='__call__'`` hits the callable itself."""
        with self._stats_lock:
            self._num_requests += 1
        if self._is_function:
            if method_name not in ("__call__", None):
                raise AttributeError(
                    f"function deployment {self.deployment_name!r} has no "
                    f"method {method_name!r}"
                )
            return self.callable(*args, **kwargs)
        if method_name == "__call__":
            if not callable(self.callable):
                raise TypeError(
                    f"deployment {self.deployment_name!r} defines no __call__; "
                    "invoke a named method via handle.<method>.remote()"
                )
            return self.callable(*args, **kwargs)
        return getattr(self.callable, method_name)(*args, **kwargs)

    def reconfigure(self, user_config: Any) -> bool:
        """Apply a new ``user_config`` in place (deployment_state reconciler
        calls this instead of restarting the replica)."""
        if not self._is_function and hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True

    def ping(self) -> str:
        """Liveness probe: a dead worker fails the call with RayActorError,
        which is the controller's death signal."""
        return "pong"

    def stats(self) -> Dict[str, Any]:
        return {
            "deployment": self.deployment_name,
            "replica_tag": self.replica_tag,
            "num_requests": self._num_requests,
            "uptime_s": time.time() - self._start_time,
        }

    def prepare_for_shutdown(self) -> bool:
        """Graceful-shutdown hook: user callables may define ``__del__`` or
        ``shutdown``; call the latter if present."""
        if not self._is_function and hasattr(self.callable, "shutdown"):
            try:
                self.callable.shutdown()
            except Exception:
                pass
        return True
