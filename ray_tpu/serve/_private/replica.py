"""ServeReplica: the actor hosting one copy of a deployment's callable.

Analog of ``python/ray/serve/_private/replica.py:250`` (RayServeReplica):
constructs the user's class (or wraps a function), executes requests,
applies ``user_config`` through ``reconfigure``, and answers health checks.
TPU-backed deployments get here with ``ray_actor_options={"num_tpus": 1}``
so the scheduler pins a chip before the model loads.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import cloudpickle

from ray_tpu.serve.exceptions import ReplicaDrainingError


class ServeReplica:
    def __init__(
        self,
        deployment_name: str,
        replica_tag: str,
        serialized_def: bytes,
        init_args: Tuple,
        init_kwargs: Dict,
        user_config: Optional[Any] = None,
    ):
        self.deployment_name = deployment_name
        self.replica_tag = replica_tag
        func_or_class = cloudpickle.loads(serialized_def)
        if isinstance(func_or_class, type):
            self.callable = func_or_class(*init_args, **init_kwargs)
            self._is_function = False
        else:
            self.callable = func_or_class
            self._is_function = True
        if user_config is not None:
            self.reconfigure(user_config)
        # lock-guarded: batched replicas serve requests from concurrent
        # threads, and a bare += (or a max() read-modify-write) can lose or
        # regress counts under preemption
        import threading

        self._stats_lock = threading.Lock()
        self._num_requests = 0
        self._inflight = 0
        self._draining = False
        self._drain_deadline: Optional[float] = None
        self._start_time = time.time()
        # live streaming responses: stream id -> iterator (the proxy pulls
        # batches of chunks with next_chunks until exhausted)
        self._streams: Dict[str, Any] = {}
        self._streams_lock = threading.Lock()

    def handle_request(self, method_name: str, args: Tuple, kwargs: Dict) -> Any:
        """Run one request (``replica.py:250`` handle_request analog).
        ``method_name='__call__'`` hits the callable itself.  During a
        drain's graceful window, requests that raced past a stale routing
        table still EXECUTE (the drain loop waits for them too — a handle
        caller must not see an error on a request the pre-drain replica
        would have served); only once the window has lapsed — when the
        controller is about to kill the actor anyway — does the typed
        refusal fire, so the caller gets a cleanly retryable error
        instead of a mid-execution RayActorError."""
        with self._stats_lock:
            if self._draining and (
                    self._drain_deadline is None
                    or time.monotonic() >= self._drain_deadline):
                raise ReplicaDrainingError(self.replica_tag)
            self._num_requests += 1
            self._inflight += 1
        try:
            return self._run_request(method_name, args, kwargs)
        finally:
            with self._stats_lock:
                self._inflight -= 1

    def _run_request(self, method_name: str, args: Tuple, kwargs: Dict) -> Any:
        if self._is_function:
            if method_name not in ("__call__", None):
                raise AttributeError(
                    f"function deployment {self.deployment_name!r} has no "
                    f"method {method_name!r}"
                )
            result = self.callable(*args, **kwargs)
        elif method_name == "__call__":
            if not callable(self.callable):
                raise TypeError(
                    f"deployment {self.deployment_name!r} defines no __call__; "
                    "invoke a named method via handle.<method>.remote()"
                )
            result = self.callable(*args, **kwargs)
        else:
            result = getattr(self.callable, method_name)(*args, **kwargs)
        from ray_tpu.serve._private.http_util import (
            Request as _HttpRequest,
            StreamingResponse,
        )

        if isinstance(result, StreamingResponse):
            if not (args and isinstance(args[0], _HttpRequest)):
                raise TypeError(
                    "StreamingResponse is only supported for HTTP requests "
                    "(the proxy drains it incrementally); a DeploymentHandle "
                    "caller should return/iterate the data directly")
            return self._register_stream(result)
        return result

    def _register_stream(self, result) -> Dict[str, Any]:
        """Drain the generator on a dedicated thread into a bounded queue
        so follow-up ``next_chunks`` polls never BLOCK a replica executor
        thread between chunks (N slow streams would otherwise pin N
        threads and exhaust max_concurrency)."""
        import queue as queue_mod
        import threading
        import uuid

        from ray_tpu.serve._private.http_util import encode_chunk

        sid = uuid.uuid4().hex
        state = {"q": queue_mod.Queue(maxsize=64), "done": False,
                 "error": None, "stop": threading.Event()}

        def drain(it=iter(result.iterable)):
            try:
                for chunk in it:
                    data = encode_chunk(chunk)
                    while not state["stop"].is_set():
                        try:
                            state["q"].put(data, timeout=0.2)
                            break
                        except queue_mod.Full:
                            continue
                    if state["stop"].is_set():
                        if hasattr(it, "close"):
                            it.close()
                        return
            except Exception as e:  # noqa: BLE001 — surfaced to the proxy
                state["error"] = f"{type(e).__name__}: {e}"
            finally:
                state["done"] = True

        threading.Thread(target=drain, daemon=True,
                         name=f"serve-stream-{sid[:8]}").start()
        with self._streams_lock:
            self._streams[sid] = state
        return {"__serve_stream__": sid, "content_type": result.content_type}

    def next_chunks(self, sid: str, max_n: int = 16) -> Dict[str, Any]:
        """Non-blocking drain of up to ``max_n`` buffered chunks; ``done``
        unregisters the stream, ``error`` carries a producer failure."""
        import queue as queue_mod

        with self._streams_lock:
            state = self._streams.get(sid)
        if state is None:
            return {"chunks": [], "done": True}
        chunks = []
        for _ in range(max_n):
            try:
                chunks.append(state["q"].get_nowait())
            except queue_mod.Empty:
                break
        finished = state["done"] and state["q"].empty()
        if finished:
            self.cancel_stream(sid)
        return {"chunks": chunks, "done": finished,
                "error": state["error"] if finished else None}

    def cancel_stream(self, sid: str) -> bool:
        with self._streams_lock:
            state = self._streams.pop(sid, None)
        if state is not None:
            state["stop"].set()
        return state is not None

    def reconfigure(self, user_config: Any) -> bool:
        """Apply a new ``user_config`` in place (deployment_state reconciler
        calls this instead of restarting the replica)."""
        if not self._is_function and hasattr(self.callable, "reconfigure"):
            self.callable.reconfigure(user_config)
        return True

    def ping(self) -> str:
        """Liveness probe: a dead worker fails the call with RayActorError,
        which is the controller's death signal."""
        return "pong"

    def stats(self) -> Dict[str, Any]:
        import os

        with self._stats_lock:
            inflight = self._inflight
            draining = self._draining
        return {
            "deployment": self.deployment_name,
            "replica_tag": self.replica_tag,
            "num_requests": self._num_requests,
            "inflight": inflight,
            "draining": draining,
            "pid": os.getpid(),
            "uptime_s": time.time() - self._start_time,
        }

    # -- graceful draining ---------------------------------------------
    def prepare_for_drain(self, grace_s: Optional[float] = None) -> Dict[str, Any]:
        """Begin draining: the controller calls this AFTER pulling the
        replica from the routing set, then polls :meth:`drain_status`
        until in-flight work hits zero (or the graceful window lapses)
        before killing the actor.  ``grace_s`` bounds the window in
        which racing requests are still served (see handle_request);
        None refuses new work immediately."""
        with self._stats_lock:
            self._draining = True
            self._drain_deadline = (
                time.monotonic() + grace_s if grace_s is not None else None)
        return self.drain_status()

    def drain_status(self) -> Dict[str, Any]:
        """{"inflight": n, "streams": m, "draining": bool} — zero inflight
        AND zero live streams means the replica is safe to terminate
        without losing accepted work."""
        with self._stats_lock:
            inflight = self._inflight
            draining = self._draining
        with self._streams_lock:
            streams = len(self._streams)
        return {"inflight": inflight, "streams": streams,
                "draining": draining}

    def prepare_for_shutdown(self) -> bool:
        """Graceful-shutdown hook: user callables may define ``__del__`` or
        ``shutdown``; call the latter if present."""
        if not self._is_function and hasattr(self.callable, "shutdown"):
            try:
                self.callable.shutdown()
            except Exception:
                pass
        return True
