"""HTTP ingress: the asyncio front door mapping routes to deployments.

Analog of ``python/ray/serve/_private/http_proxy.py:218`` (HTTPProxy over
uvicorn/starlette) rebuilt on ``asyncio.start_server``: the event loop owns
every connection (accept, parse, keep-alive, response writes — a
connection costs a StreamReader, not a thread), while the blocking data
plane (router assignment + ``ray_tpu.get``) runs on a bounded executor
pool.  That split is the graceful-degradation design: concurrency the pool
can't absorb is *shed* with a fast 503 + Retry-After straight from the
loop instead of queueing unboundedly, so accepted requests keep a bounded
p99 no matter how many clients pile on.

Request-level fault tolerance, shared by both ingress implementations:

deadline
    Every request carries one — the client's ``X-Serve-Deadline-S``
    header, else the deployment's ``request_timeout_s``, else
    ``INGRESS_DEFAULT_TIMEOUT_S`` — threaded through router admission AND
    replica execution, so a 5s-budget request can never queue for 60s.
    Expiry while queued is capacity (503); expiry while executing is 504.
retry
    A replica death (``RayActorError``) re-assigns idempotent requests
    (GET/HEAD/PUT/DELETE/OPTIONS, or any method carrying
    ``X-Idempotency-Key``) to a live replica with bounded backoff under
    the same deadline — replica SIGKILL is never a client-visible 500 for
    them.  A draining-replica race retries for every method (the request
    was refused before execution).
shed
    The router's ``max_queued_requests`` watermark and the proxy-wide
    in-flight cap both answer 503 + Retry-After.

``RAY_TPU_SERVE_ASYNC=0`` (or ``HTTPOptions(async_ingress=False)``) falls
back to the stdlib ``ThreadingHTTPServer`` loop — same semantics, thread
per connection.
"""

from __future__ import annotations

import asyncio
import contextlib
import json
import os
import threading
import time
import traceback
from concurrent.futures import ThreadPoolExecutor
from http.client import responses as _HTTP_REASONS
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from ray_tpu._private import events as _events
from ray_tpu.serve._private.http_util import (
    Request,
    Response,
    encode_response,
    parse_http_head,
)
from ray_tpu.serve._private.router import Router
from ray_tpu.serve.config import (
    INGRESS_DEFAULT_TIMEOUT_S,
    INGRESS_MAX_RETRIES,
    REFRESH_BACKOFF_BASE_S,
    REFRESH_BACKOFF_CAP_S,
    ROUTE_TABLE_TTL_S,
    SHED_RETRY_AFTER_S,
    async_ingress_enabled,
)
from ray_tpu.serve.exceptions import BackPressureError, ReplicaDrainingError

DEADLINE_HEADER = "x-serve-deadline-s"
IDEMPOTENCY_HEADER = "x-idempotency-key"
# idempotent by HTTP semantics; POST/PATCH opt in via the header
IDEMPOTENT_METHODS = frozenset({"GET", "HEAD", "PUT", "DELETE", "OPTIONS"})
# request head / body ceilings for the asyncio parser
MAX_HEAD_BYTES = 64 * 1024
MAX_BODY_BYTES = 256 * 1024 * 1024

_SHED_BODY = json.dumps(
    {"error": "ingress overloaded, retry later"}).encode()


def _build_response(status: int, body: bytes, ctype: str,
                    extra_headers: Optional[Dict[str, str]] = None,
                    keep_alive: bool = True,
                    omit_body: bool = False) -> bytes:
    """One wire blob: status line + headers + body.  A single write means
    a single packet on loopback — no torn responses on reused keep-alive
    connections, no Nagle/delayed-ACK stall.  ``omit_body`` is the HEAD
    contract: headers (including the Content-Length GET would send) with
    no body — writing one would desync the client's keep-alive parser."""
    reason = _HTTP_REASONS.get(status, "Unknown")
    lines = [
        f"HTTP/1.1 {status} {reason}",
        f"Content-Type: {ctype}",
        f"Content-Length: {len(body)}",
    ]
    if not keep_alive:
        lines.append("Connection: close")
    for k, v in (extra_headers or {}).items():
        lines.append(f"{k}: {v}")
    head = ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")
    return head if omit_body else head + body


# SLO taps: trailing-window p99 gauge + status-class counter, fed by the
# single request funnel (_execute) both ingress impls share.  Lazy
# singletons like the router metrics.
_SLO_METRICS = None
P99_WINDOW_REQUESTS = 512
P99_RECOMPUTE_EVERY = 16


def _slo_metrics():
    global _SLO_METRICS
    if _SLO_METRICS is None:
        from ray_tpu.util.metrics import Counter, Gauge

        _SLO_METRICS = {
            "p99": Gauge(
                "ray_tpu_serve_http_p99_s",
                "HTTP p99 latency over the trailing request window (s)"),
            "requests": Counter(
                "ray_tpu_serve_http_requests_total",
                "HTTP requests by status class (2xx/4xx/5xx)",
                tag_keys=("code_class",)),
        }
    return _SLO_METRICS


class _Reply:
    """What ``_execute`` hands back to the transport layer."""

    __slots__ = ("status", "headers", "body", "ctype", "stream")

    def __init__(self, status: int, body: bytes, ctype: str,
                 headers: Optional[Dict[str, str]] = None,
                 stream: Optional[Tuple[Any, Dict]] = None):
        self.status = status
        self.body = body
        self.ctype = ctype
        self.headers = headers or {}
        self.stream = stream  # (replica_handle, meta) for chunked delivery


class HTTPProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 controller_name: Optional[str] = None,
                 async_ingress: Optional[bool] = None,
                 num_exec_threads: Optional[int] = None,
                 max_inflight_requests: Optional[int] = None):
        import ray_tpu
        from ray_tpu.serve._private.controller import (
            CONTROLLER_NAME, SERVE_NAMESPACE)

        self._controller = ray_tpu.get_actor(
            controller_name or CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
        self._routers: Dict[str, Router] = {}
        self._routers_lock = threading.Lock()
        self._route_table: Dict[str, str] = {}
        self._route_table_at = 0.0
        self._route_failures = 0
        self._route_next_attempt = 0.0
        # ingress counters (ingress_stats snapshot; tests and the chaos
        # bench read them to assert zero lost idempotent requests)
        self._stats_lock = threading.Lock()
        self._stats = {
            "requests": 0, "ok": 0, "retries": 0, "shed": 0,
            "replica_deaths": 0, "deadline_504": 0, "errors": 0,
        }
        # trailing latency window behind the p99 SLO gauge
        from collections import deque

        self._lat_window: deque = deque(maxlen=P99_WINDOW_REQUESTS)
        self._lat_n = 0
        if async_ingress is None:
            async_ingress = async_ingress_enabled()
        self.mode = "asyncio" if async_ingress else "threaded"
        if async_ingress:
            self._impl = _AsyncIngress(self, host, port, num_exec_threads,
                                       max_inflight_requests)
        else:
            self._impl = _ThreadedIngress(self, host, port)
        self.host, self.port = self._impl.host, self._impl.port

    # -- actor API -----------------------------------------------------
    def ready(self):
        """(host, port) once the socket is bound (it is, from __init__)."""
        return self.host, self.port

    def ping(self) -> str:
        return "pong"

    def ingress_stats(self) -> Dict[str, Any]:
        """Counter snapshot: requests/ok/retries/shed/replica_deaths/
        deadline_504/errors, plus the ingress mode."""
        with self._stats_lock:
            out = dict(self._stats)
        out["mode"] = self.mode
        return out

    def _count(self, key: str, n: int = 1) -> None:
        with self._stats_lock:
            self._stats[key] += n

    # -- routing table ---------------------------------------------------
    def _refresh_route_table(self, force: bool = False) -> Dict[str, str]:
        """TTL-cached {route_prefix: deployment} pull with the same
        bounded-backoff stale-table behavior as Router._refresh: a
        controller stall must not take routing down with it."""
        import ray_tpu

        now = time.monotonic()
        if not (force or now - self._route_table_at >= ROUTE_TABLE_TTL_S):
            return self._route_table
        if self._route_failures and now < self._route_next_attempt:
            return self._route_table
        try:
            table = ray_tpu.get(
                self._controller.get_route_table.remote(), timeout=5
            )
        except Exception as e:  # noqa: BLE001 — controller stall/restart
            self._route_failures += 1
            self._route_next_attempt = now + min(
                REFRESH_BACKOFF_CAP_S,
                REFRESH_BACKOFF_BASE_S * (2 ** (self._route_failures - 1)))
            if _events.ENABLED:
                _events.emit(
                    "serve", "route table refresh failed",
                    severity="WARNING", entity_id="__proxy__",
                    failures=self._route_failures,
                    error=f"{type(e).__name__}: {e}"[:200])
            return self._route_table
        self._route_failures = 0
        self._route_table = table
        self._route_table_at = now
        return self._route_table

    def _match_route(self, path: str) -> Optional[str]:
        """Longest-prefix route match (http_proxy.py's starlette routing
        analog): '/api' matches '/api' and '/api/x', not '/apix'."""
        for force in (False, True):
            table = self._refresh_route_table(force=force)
            best, best_len = None, -1
            for prefix, name in table.items():
                if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                    if len(prefix) > best_len:
                        best, best_len = name, len(prefix)
            if best is not None:
                return best
            # miss may just be a stale cache (deployment created <TTL ago):
            # force one refresh before 404ing
        return None

    def _router_for(self, name: str) -> Router:
        with self._routers_lock:
            router = self._routers.get(name)
            if router is None:
                router = self._routers[name] = Router(self._controller, name)
        return router

    # -- request path ----------------------------------------------------
    def _execute(self, method: str, raw_path: str,
                 headers: Dict[str, str], body: bytes) -> _Reply:
        """SLO tap around the request funnel — both ingress impls route
        through here, so the trailing-window p99 gauge and the
        status-class counter see every request exactly once (the series
        the serve_p99 / serve_5xx SLOs burn on)."""
        t0 = time.perf_counter()
        reply = self._execute_inner(method, raw_path, headers, body)
        self._observe_slo(time.perf_counter() - t0, reply.status)
        return reply

    def _observe_slo(self, latency_s: float, status: int) -> None:
        code_class = f"{status // 100}xx"
        m = _slo_metrics()
        m["requests"].inc(tags={"code_class": code_class})
        with self._stats_lock:
            self._lat_window.append(latency_s)
            self._lat_n += 1
            snap = (tuple(self._lat_window)
                    if self._lat_n % P99_RECOMPUTE_EVERY == 0 else None)
        if snap:
            # p99 over the trailing window, recomputed every few requests
            # and sorted outside the lock (sorting 512 floats per request
            # would be the expensive way)
            lats = sorted(snap)
            m["p99"].set(lats[min(len(lats) - 1,
                                  int(0.99 * (len(lats) - 1)))])

    def _execute_inner(self, method: str, raw_path: str,
                       headers: Dict[str, str], body: bytes) -> _Reply:
        """Route + execute one request; never raises (transport layers
        only write bytes).  Runs on an executor thread (asyncio ingress)
        or the connection thread (threaded fallback)."""
        from ray_tpu.exceptions import GetTimeoutError

        path = raw_path.split("?")[0]
        if path == "/-/routes":
            try:
                table = self._refresh_route_table()
            except Exception as e:  # noqa: BLE001
                return _Reply(500, json.dumps({"error": str(e)}).encode(),
                              "application/json")
            return _Reply(200, json.dumps(table).encode(), "application/json")
        name = self._match_route(path)
        if name is None:
            return _Reply(404, b'{"error": "no route"}', "application/json")
        self._count("requests")
        lc_headers = {k.lower(): v for k, v in headers.items()}
        request = Request.from_raw(method, raw_path, dict(headers), body)
        router = self._router_for(name)
        budget = None
        if DEADLINE_HEADER in lc_headers:
            try:
                budget = float(lc_headers[DEADLINE_HEADER])
            except ValueError:
                return _Reply(
                    400, b'{"error": "bad X-Serve-Deadline-S value"}',
                    "application/json")
        if budget is None:
            if router._last_refresh == 0.0:
                # brand-new router: pull config once BEFORE sizing the
                # deadline, or the first request to a deployment with a
                # tight request_timeout_s gets the 60s default
                router._refresh(force=True)
            budget = router.request_timeout_s or INGRESS_DEFAULT_TIMEOUT_S
        deadline = time.monotonic() + budget
        idempotent = (method.upper() in IDEMPOTENT_METHODS
                      or IDEMPOTENCY_HEADER in lc_headers)
        # each routed request is a trace ROOT: the span tree under it
        # (router admission -> replica task -> nested submissions /
        # compiled-graph nodes) is what `ray_tpu trace <id>` renders.
        # Off when the observability layer is off.
        if _events.ENABLED:
            from ray_tpu.util import tracing

            cm = tracing.trace(f"HTTP {method} {path}",
                               {"deployment": name}, phase="http")
        else:
            cm = contextlib.nullcontext()
        try:
            with cm:
                result, replica = self._route_with_policy(
                    router, request, deadline, idempotent, name)
        except BackPressureError as e:
            self._count("shed")
            return _Reply(
                503,
                json.dumps({"error": str(e)}).encode(), "application/json",
                headers={"Retry-After": f"{e.retry_after_s:g}"})
        except GetTimeoutError as e:
            if "no replica" in str(e):
                # never assigned: capacity, safe to retry elsewhere/later
                self._count("shed")
                return _Reply(
                    503, json.dumps({"error": str(e)}).encode(),
                    "application/json",
                    headers={"Retry-After": f"{SHED_RETRY_AFTER_S:g}"})
            # the request is (still) executing — slow, not capacity
            self._count("deadline_504")
            return _Reply(504,
                          b'{"error": "request deadline exceeded while '
                          b'executing"}', "application/json")
        except _ReplicaLost as e:
            # replica died; the retry budget (non-idempotent: zero) is
            # spent.  Idempotent: 503 so the client retries — by
            # construction never a 500.  Non-idempotent: execution state
            # unknown, an honest (structured) 500.
            self._count("errors")
            if e.idempotent:
                return _Reply(
                    503, json.dumps({"error": str(e)}).encode(),
                    "application/json",
                    headers={"Retry-After": f"{SHED_RETRY_AFTER_S:g}"})
            return _Reply(500, json.dumps({"error": str(e)}).encode(),
                          "application/json")
        except Exception as e:  # noqa: BLE001 — user-code errors et al.
            self._count("errors")
            err = json.dumps({"error": str(e),
                              "traceback": traceback.format_exc()})
            return _Reply(500, err.encode(), "application/json")
        if isinstance(result, dict) and "__serve_stream__" in result:
            return _Reply(200, b"", result.get("content_type", "text/plain"),
                          stream=(replica, result))
        self._count("ok")
        if isinstance(result, Response):
            return _Reply(result.status_code, result.body,
                          result.content_type, headers=result.headers)
        payload, ctype = encode_response(result)
        return _Reply(200, payload, ctype)

    def _route_with_policy(self, router: Router, request: Request,
                           deadline: float, idempotent: bool,
                           name: str):
        """Assign + get under the request deadline, re-assigning on
        replica death (idempotent requests, bounded backoff) and on the
        draining-membership race (all requests — a draining replica
        refused before executing)."""
        import ray_tpu
        from ray_tpu.exceptions import (
            GetTimeoutError,
            RayActorError,
            RayTaskError,
        )

        attempt = 0
        last_death: Optional[BaseException] = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                if last_death is not None:
                    raise _ReplicaLost(
                        f"replica died and the deadline lapsed during "
                        f"retry: {last_death}", idempotent)
                raise GetTimeoutError(
                    f"no replica of {name!r} available within the request "
                    "deadline")
            ref, replica = router.assign_request(
                "__call__", (request,), {}, return_replica=True,
                deadline=deadline)
            try:
                result = ray_tpu.get(
                    ref, timeout=max(deadline - time.monotonic(), 0.01))
            except RayActorError as e:
                router.on_replica_error(ref)
                self._count("replica_deaths")
                if not (idempotent and attempt < INGRESS_MAX_RETRIES):
                    raise _ReplicaLost(
                        f"replica of {name!r} died mid-request"
                        + ("" if idempotent else
                           " (non-idempotent, not retried)"),
                        idempotent) from e
                attempt += 1
                last_death = e
                self._count("retries")
                if _events.ENABLED:
                    _events.emit(
                        "serve", "request retried after replica death",
                        severity="INFO", entity_id=name, attempt=attempt)
                backoff = min(0.05 * (2 ** (attempt - 1)),
                              max(deadline - time.monotonic(), 0.0))
                if backoff > 0:
                    time.sleep(backoff)
                continue
            except RayTaskError as e:
                router.on_request_done(ref)
                if (isinstance(getattr(e, "cause", None),
                               ReplicaDrainingError)
                        or "ReplicaDrainingError" in str(e)):
                    # membership race: the replica refused BEFORE running
                    # anything, so re-assigning is safe for every method
                    if attempt < INGRESS_MAX_RETRIES * 2:
                        attempt += 1
                        self._count("retries")
                        router._refresh(force=True)
                        continue
                raise
            except GetTimeoutError:
                # request is STILL executing on the replica — the slot is
                # genuinely occupied; prune reclaims it when it finishes
                raise
            except Exception:
                router.on_request_done(ref)  # slot back on app errors
                raise
            router.on_request_done(ref)
            return result, replica

    # -- threaded-fallback transport glue ------------------------------
    def _handle_http_threaded(self, h: BaseHTTPRequestHandler) -> None:
        length = int(h.headers.get("Content-Length") or 0)
        body = h.rfile.read(length) if length else b""
        try:
            reply = self._execute(h.command, h.path, dict(h.headers), body)
        except Exception as e:  # noqa: BLE001 — pre-route parse errors
            reply = _Reply(500, json.dumps({"error": str(e)}).encode(),
                           "application/json")
        if reply.stream is not None:
            replica, meta = reply.stream
            _threaded_stream(h, replica, meta)
            return
        _threaded_respond(h, reply.status, reply.body, reply.ctype,
                          reply.headers)


class _ReplicaLost(Exception):
    """Internal: replica death exhausted the retry budget (the transport
    maps idempotent→503, non-idempotent→500)."""

    def __init__(self, msg: str, idempotent: bool):
        super().__init__(msg)
        self.idempotent = idempotent


# ---------------------------------------------------------------------------
# asyncio ingress (the default)
# ---------------------------------------------------------------------------


class _AsyncIngress:
    """``asyncio.start_server`` front door on a dedicated loop thread.

    The loop owns connections; a bounded ThreadPoolExecutor owns the
    blocking per-request work.  ``_inflight`` (loop-confined, no lock) is
    the proxy-wide watermark: past it, 503s are written straight from the
    loop — the overload answer costs no executor slot, which is exactly
    what keeps it fast enough to matter at 1k clients.
    """

    def __init__(self, proxy: HTTPProxyActor, host: str, port: int,
                 num_exec_threads: Optional[int],
                 max_inflight: Optional[int]):
        if num_exec_threads is None:
            num_exec_threads = int(
                os.environ.get("RAY_TPU_SERVE_EXEC_THREADS", "128"))
        if max_inflight is None:
            max_inflight = int(
                os.environ.get("RAY_TPU_SERVE_MAX_INFLIGHT",
                               str(2 * num_exec_threads)))
        self._proxy = proxy
        self._pool = ThreadPoolExecutor(
            max_workers=num_exec_threads, thread_name_prefix="serve-exec")
        self._max_inflight = max_inflight
        self._inflight = 0
        self._shedding = False
        self._loop = asyncio.new_event_loop()
        self._startup_error: Optional[BaseException] = None
        started = threading.Event()
        self._thread = threading.Thread(
            target=self._run, args=(host, port, started),
            daemon=True, name="serve-ingress")
        self._thread.start()
        started.wait(timeout=30)
        if self._startup_error is not None:
            raise self._startup_error

    def _run(self, host: str, port: int, started: threading.Event) -> None:
        asyncio.set_event_loop(self._loop)
        try:
            server = self._loop.run_until_complete(
                asyncio.start_server(self._handle_conn, host, port,
                                     backlog=512, limit=MAX_HEAD_BYTES))
            sock = server.sockets[0].getsockname()
            self.host, self.port = sock[0], sock[1]
        except BaseException as e:  # noqa: BLE001 — surfaced to __init__
            self._startup_error = e
            started.set()
            return
        started.set()
        self._loop.run_forever()

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        import socket as socket_mod

        proxy = self._proxy
        sock = writer.get_extra_info("socket")
        if sock is not None:
            with contextlib.suppress(OSError):
                sock.setsockopt(socket_mod.IPPROTO_TCP,
                                socket_mod.TCP_NODELAY, 1)
        try:
            while True:
                try:
                    head = await reader.readuntil(b"\r\n\r\n")
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    return  # client closed between requests
                except asyncio.LimitOverrunError:
                    writer.write(_build_response(
                        431, b'{"error": "request head too large"}',
                        "application/json", keep_alive=False))
                    await writer.drain()
                    return
                try:
                    method, raw_path, version, headers = \
                        parse_http_head(head[:-4])
                    # transport-level lookups are case-insensitive; the
                    # original-case dict goes to the deployment
                    lc = {k.lower(): v for k, v in headers.items()}
                    length = int(lc.get("content-length") or 0)
                except ValueError:
                    writer.write(_build_response(
                        400, b'{"error": "malformed request"}',
                        "application/json", keep_alive=False))
                    await writer.drain()
                    return
                if "chunked" in lc.get("transfer-encoding", "").lower():
                    # we don't parse chunked request bodies — answer
                    # honestly instead of desyncing on the unread body
                    writer.write(_build_response(
                        411, b'{"error": "chunked request bodies are not '
                        b'supported; send Content-Length"}',
                        "application/json", keep_alive=False))
                    await writer.drain()
                    return
                if length > MAX_BODY_BYTES:
                    writer.write(_build_response(
                        413, b'{"error": "body too large"}',
                        "application/json", keep_alive=False))
                    await writer.drain()
                    return
                body = await reader.readexactly(length) if length else b""
                keep_alive = (version != "HTTP/1.0"
                              and lc.get("connection", "").lower()
                              != "close")
                if self._inflight >= self._max_inflight:
                    self._shed_from_loop(keep_alive, writer)
                    await writer.drain()
                    if not keep_alive:
                        return
                    continue
                self._inflight += 1
                try:
                    reply = await self._loop.run_in_executor(
                        self._pool, proxy._execute, method, raw_path,
                        headers, body)
                except Exception as e:  # noqa: BLE001 — _execute guards
                    # its own body; this catches pre-route parse errors
                    reply = _Reply(
                        500, json.dumps({"error": str(e)}).encode(),
                        "application/json")
                finally:
                    self._inflight -= 1
                    if self._shedding and \
                            self._inflight <= self._max_inflight // 2:
                        self._shedding = False
                        if _events.ENABLED:
                            _events.emit(
                                "serve", "ingress shedding stopped",
                                severity="INFO", entity_id="__proxy__",
                                inflight=self._inflight)
                if reply.stream is not None:
                    ok = await self._stream_response(writer, reply)
                    if not ok or not keep_alive:
                        return
                    continue
                writer.write(_build_response(
                    reply.status, reply.body, reply.ctype, reply.headers,
                    keep_alive, omit_body=(method == "HEAD")))
                await writer.drain()
                if not keep_alive:
                    return
        except (ConnectionResetError, BrokenPipeError,
                asyncio.CancelledError):
            pass
        except Exception:  # noqa: BLE001 — connection already unusable;
            # nothing left to answer on
            pass
        finally:
            with contextlib.suppress(Exception):
                writer.close()

    def _shed_from_loop(self, keep_alive: bool,
                        writer: asyncio.StreamWriter) -> None:
        """Proxy-wide overload answer, written without an executor hop.
        Loop-confined state, so no locks; the started/stopped hysteresis
        pair is what doctor's ingress_shedding rule reads."""
        self._proxy._count("shed")
        if not self._shedding:
            self._shedding = True
            if _events.ENABLED:
                _events.emit(
                    "serve", "ingress shedding started",
                    severity="WARNING", entity_id="__proxy__",
                    inflight=self._inflight,
                    max_inflight=self._max_inflight)
        writer.write(_build_response(
            503, _SHED_BODY, "application/json",
            {"Retry-After": f"{SHED_RETRY_AFTER_S:g}"}, keep_alive))

    async def _stream_response(self, writer: asyncio.StreamWriter,
                               reply: _Reply) -> bool:
        """Chunked-transfer delivery of a StreamingResponse: blocking
        next_chunks pulls ride the executor, writes stay on the loop.
        Returns False when the connection is no longer reusable (producer
        error truncates the body so the client sees an aborted stream,
        not a clean end)."""
        import ray_tpu

        replica, meta = reply.stream
        sid = meta["__serve_stream__"]

        def pull():
            return ray_tpu.get(replica.next_chunks.remote(sid, 16),
                               timeout=120.0)

        try:
            writer.write(
                (f"HTTP/1.1 200 OK\r\nContent-Type: {reply.ctype}\r\n"
                 "Transfer-Encoding: chunked\r\n\r\n").encode("latin-1"))
            while True:
                out = await self._loop.run_in_executor(self._pool, pull)
                buf = b"".join(
                    f"{len(c):x}\r\n".encode() + c + b"\r\n"
                    for c in out["chunks"] if c)
                if buf:
                    writer.write(buf)
                    await writer.drain()
                if out["done"]:
                    if out.get("error"):
                        return False  # truncate: no terminating chunk
                    writer.write(b"0\r\n\r\n")
                    await writer.drain()
                    return True
                if not out["chunks"]:
                    await asyncio.sleep(0.02)
        except Exception:  # noqa: BLE001 — client disconnect or replica
            # death; either way the stream (and connection) is done
            with contextlib.suppress(Exception):
                replica.cancel_stream.remote(sid)
            return False


# ---------------------------------------------------------------------------
# threaded fallback (RAY_TPU_SERVE_ASYNC=0)
# ---------------------------------------------------------------------------


class _ThreadedIngress:
    """The PR-11-era stdlib ``ThreadingHTTPServer`` loop, kept as the
    escape hatch.  Thread per connection; same ``_execute`` semantics."""

    def __init__(self, proxy: HTTPProxyActor, host: str, port: int):
        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Nagle + the peer's delayed ACK turns a two-write response
            # into a ~40 ms stall per request; the data plane runs on
            # loopback/ICI where coalescing buys nothing
            disable_nagle_algorithm = True

            def log_message(self, *args):  # silence per-request stderr spam
                pass

            def _dispatch(self):
                proxy._handle_http_threaded(self)

            do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _dispatch
            do_HEAD = do_OPTIONS = _dispatch

        class Server(ThreadingHTTPServer):
            # stock backlog is 5: a burst of concurrent clients overflows
            # it and the kernel RSTs the rest
            request_queue_size = 128
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host = self._server.server_address[0]
        self.port = self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True, name="serve-http"
        ).start()


def _threaded_respond(h: BaseHTTPRequestHandler, code: int, body: bytes,
                      ctype: str,
                      extra_headers: Optional[Dict[str, str]] = None) -> None:
    try:
        # one write for headers+body: even with TCP_NODELAY, separate
        # writes mean separate packets and a chance for the client to
        # read a torn response on a reused keep-alive connection
        h.send_response(code)
        h.send_header("Content-Type", ctype)
        h.send_header("Content-Length", str(len(body)))
        for k, v in (extra_headers or {}).items():
            h.send_header(k, v)
        h._headers_buffer.append(b"\r\n")
        payload = b"".join(h._headers_buffer)
        if h.command != "HEAD":  # HEAD: headers only, or the client's
            # keep-alive parser desyncs on the unexpected body
            payload += body
        h._headers_buffer = []
        h.wfile.write(payload)
    except (BrokenPipeError, ConnectionResetError):
        pass
    finally:
        h._headers_buffer = []


def _threaded_stream(h: BaseHTTPRequestHandler, replica, meta: Dict) -> None:
    """Chunked delivery on the connection thread.  NEVER raises: once the
    200 + chunked headers are on the wire, a second response would corrupt
    the stream — any failure just ends the body and closes the (no longer
    reusable) connection."""
    import ray_tpu

    sid = meta["__serve_stream__"]
    try:
        h.send_response(200)
        h.send_header("Content-Type", meta.get("content_type", "text/plain"))
        h.send_header("Transfer-Encoding", "chunked")
        h.end_headers()
        while True:
            # non-blocking drain replica-side; an empty reply means the
            # producer hasn't caught up — pace the poll, don't spin
            out = ray_tpu.get(replica.next_chunks.remote(sid, 16),
                              timeout=120.0)
            for c in out["chunks"]:
                if c:  # a zero-length chunk would terminate the stream
                    h.wfile.write(f"{len(c):x}\r\n".encode() + c + b"\r\n")
            h.wfile.flush()
            if out["done"]:
                if out.get("error"):
                    # mid-stream producer failure: the body is already
                    # partial — truncate (no terminating chunk) so the
                    # client sees an aborted stream, not a clean end
                    h.close_connection = True
                    return
                h.wfile.write(b"0\r\n\r\n")
                return
            if not out["chunks"]:
                time.sleep(0.02)
    except Exception:  # noqa: BLE001 — includes client disconnects and
        # replica death; the connection is unusable either way
        h.close_connection = True
        try:
            replica.cancel_stream.remote(sid)
        except Exception:
            pass
