"""HTTP proxy: the front door mapping routes to deployments.

Analog of ``python/ray/serve/_private/http_proxy.py:218`` (HTTPProxy over
uvicorn/starlette) rebuilt on the stdlib: a ``ThreadingHTTPServer`` runs
inside the proxy actor, each connection thread resolves the route against a
TTL-cached route table from the controller, assembles a picklable
``Request``, routes it through a per-deployment Router (concurrency-capped),
and encodes the replica's return value as the HTTP response.
"""

from __future__ import annotations

import contextlib
import json
import threading
import time
import traceback
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from ray_tpu._private import events as _events
from ray_tpu.serve._private.http_util import Request, encode_response
from ray_tpu.serve._private.router import Router
from ray_tpu.serve.config import ROUTE_TABLE_TTL_S


class HTTPProxyActor:
    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 controller_name: Optional[str] = None):
        import ray_tpu
        from ray_tpu.serve._private.controller import CONTROLLER_NAME

        self._controller = ray_tpu.get_actor(controller_name or CONTROLLER_NAME)
        self._routers: Dict[str, Router] = {}
        self._routers_lock = threading.Lock()
        self._route_table: Dict[str, str] = {}
        self._route_table_at = 0.0

        proxy = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"
            # Nagle + the peer's delayed ACK turns our two-write response
            # (headers, then body) into a ~40 ms stall per request — the
            # whole data plane runs on loopback/ICI where coalescing buys
            # nothing, so turn it off unconditionally.
            disable_nagle_algorithm = True

            def log_message(self, *args):  # silence per-request stderr spam
                pass

            def _dispatch(self):
                proxy._handle_http(self)

            do_GET = do_POST = do_PUT = do_DELETE = do_PATCH = _dispatch

        class Server(ThreadingHTTPServer):
            # stock backlog is 5: a burst of concurrent clients (the bench
            # opens 16 at once) overflows it and the kernel RSTs the rest
            request_queue_size = 128
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.host, self.port = self._server.server_address[0], self._server.server_address[1]
        threading.Thread(
            target=self._server.serve_forever, daemon=True, name="serve-http"
        ).start()

    # -- actor API -----------------------------------------------------
    def ready(self):
        """(host, port) once the socket is bound (it is, from __init__)."""
        return self.host, self.port

    def ping(self) -> str:
        return "pong"

    # -- request path ----------------------------------------------------
    def _refresh_route_table(self, force: bool = False) -> Dict[str, str]:
        import ray_tpu

        now = time.monotonic()
        if force or now - self._route_table_at >= ROUTE_TABLE_TTL_S:
            self._route_table = ray_tpu.get(
                self._controller.get_route_table.remote(), timeout=30
            )
            self._route_table_at = now
        return self._route_table

    def _match_route(self, path: str) -> Optional[str]:
        """Longest-prefix route match (http_proxy.py's starlette routing
        analog): '/api' matches '/api' and '/api/x', not '/apix'."""
        for force in (False, True):
            table = self._refresh_route_table(force=force)
            best, best_len = None, -1
            for prefix, name in table.items():
                if path == prefix or path.startswith(prefix.rstrip("/") + "/"):
                    if len(prefix) > best_len:
                        best, best_len = name, len(prefix)
            if best is not None:
                return best
            # miss may just be a stale cache (deployment created <TTL ago):
            # force one refresh before 404ing
        return None

    def _handle_http(self, h: BaseHTTPRequestHandler) -> None:
        import ray_tpu
        from ray_tpu.exceptions import GetTimeoutError

        try:
            if h.path == "/-/routes":
                self._respond(h, 200, json.dumps(self._refresh_route_table()).encode(),
                              "application/json")
                return
            name = self._match_route(h.path.split("?")[0])
            if name is None:
                self._respond(h, 404, b'{"error": "no route"}', "application/json")
                return
            length = int(h.headers.get("Content-Length") or 0)
            body = h.rfile.read(length) if length else b""
            request = Request.from_raw(h.command, h.path, dict(h.headers), body)
            with self._routers_lock:
                router = self._routers.get(name)
                if router is None:
                    router = self._routers[name] = Router(self._controller, name)
            # each routed request is a trace ROOT: the span tree under it
            # (router admission -> replica task -> nested submissions /
            # compiled-graph nodes) is what `ray_tpu trace <id>` renders.
            # Off when the observability layer is off.
            if _events.ENABLED:
                from ray_tpu.util import tracing

                cm = tracing.trace(f"HTTP {h.command} {h.path}",
                                   {"deployment": name}, phase="http")
            else:
                cm = contextlib.nullcontext()
            with cm:
                result, replica = self._route_with_retry(router, request)
                if isinstance(result, dict) and "__serve_stream__" in result:
                    self._stream_response(h, replica, result)
                    return
                payload, ctype = encode_response(result)
                self._respond(h, 200, payload, ctype)
        except GetTimeoutError as e:
            if "no replica" in str(e):
                self._respond(h, 503, b'{"error": "no replica available"}',
                              "application/json")
            else:
                # the request is (still) executing — slow, not capacity
                self._respond(h, 504, b'{"error": "replica execution timed out"}',
                              "application/json")
        except Exception as e:  # noqa: BLE001
            err = json.dumps({"error": str(e), "traceback": traceback.format_exc()})
            self._respond(h, 500, err.encode(), "application/json")

    def _route_with_retry(self, router: Router, request: Request):
        """Assign + get, retrying once if the chosen replica died under us
        (stale membership during a scale-down/redeploy is routine, not a
        user-visible error)."""
        import ray_tpu
        from ray_tpu.exceptions import GetTimeoutError, RayActorError

        last_exc = None
        for _ in range(2):
            ref, replica = router.assign_request(
                "__call__", (request,), {}, timeout=30.0, return_replica=True)
            try:
                result = ray_tpu.get(ref, timeout=120.0)
            except RayActorError as e:
                router.on_replica_error(ref)
                last_exc = e
                continue
            except GetTimeoutError:
                # request is STILL executing on the replica — the slot is
                # genuinely occupied; prune reclaims it when it finishes
                raise
            except Exception:
                router.on_request_done(ref)  # slot back on app errors
                raise
            router.on_request_done(ref)
            return result, replica
        raise last_exc

    def _stream_response(self, h: BaseHTTPRequestHandler, replica,
                         meta: Dict) -> None:
        """Deliver a StreamingResponse with chunked transfer encoding,
        draining buffered chunks from the replica as the generator produces
        them (the streaming data plane the reference gets from starlette).

        NEVER raises: once the 200 + chunked headers are on the wire, a
        second response would corrupt the stream — any failure just ends
        the body and closes the (no longer reusable) connection."""
        import ray_tpu

        sid = meta["__serve_stream__"]
        try:
            h.send_response(200)
            h.send_header("Content-Type", meta.get("content_type", "text/plain"))
            h.send_header("Transfer-Encoding", "chunked")
            h.end_headers()
            while True:
                # non-blocking drain replica-side; an empty reply means the
                # producer hasn't caught up — pace the poll, don't spin
                out = ray_tpu.get(replica.next_chunks.remote(sid, 16),
                                  timeout=120.0)
                for c in out["chunks"]:
                    if c:  # a zero-length chunk would terminate the stream
                        h.wfile.write(f"{len(c):x}\r\n".encode() + c + b"\r\n")
                h.wfile.flush()
                if out["done"]:
                    if out.get("error"):
                        # mid-stream producer failure: the body is already
                        # partial — truncate (no terminating chunk) so the
                        # client sees an aborted stream, not a clean end
                        h.close_connection = True
                        return
                    h.wfile.write(b"0\r\n\r\n")
                    return
                if not out["chunks"]:
                    time.sleep(0.02)
        except Exception:  # noqa: BLE001 — includes client disconnects and
            # replica death; the connection is unusable either way
            h.close_connection = True
            try:
                replica.cancel_stream.remote(sid)
            except Exception:
                pass

    @staticmethod
    def _respond(h: BaseHTTPRequestHandler, code: int, body: bytes, ctype: str) -> None:
        try:
            # one write for headers+body: even with TCP_NODELAY, separate
            # writes mean separate packets and a chance for the client to
            # read a torn response on a reused keep-alive connection
            h.send_response(code)
            h.send_header("Content-Type", ctype)
            h.send_header("Content-Length", str(len(body)))
            h._headers_buffer.append(b"\r\n")
            payload = b"".join(h._headers_buffer) + body
            h._headers_buffer = []
            h.wfile.write(payload)
        except (BrokenPipeError, ConnectionResetError):
            pass
        finally:
            h._headers_buffer = []
