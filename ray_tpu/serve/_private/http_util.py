"""HTTP request/response plumbing shared by the proxy and replicas.

The reference hands replicas a starlette ``Request`` built by uvicorn
(``serve/_private/http_util.py``); this environment has no ASGI stack, so
``Request`` is a small picklable equivalent assembled by the stdlib proxy
and shipped to the replica over the actor call.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional
from urllib.parse import parse_qsl, urlsplit


class Request:
    """An HTTP request as seen by a deployment's ``__call__``.

    Mirrors the parts of starlette's Request that serve users touch:
    ``method``, ``path``, ``query_params``, ``headers``, ``body`` (bytes),
    and ``json()``.
    """

    def __init__(
        self,
        method: str = "GET",
        path: str = "/",
        query_params: Optional[Dict[str, str]] = None,
        headers: Optional[Dict[str, str]] = None,
        body: bytes = b"",
    ):
        self.method = method
        self.path = path
        self.query_params = query_params or {}
        self.headers = headers or {}
        self.body = body

    @classmethod
    def from_raw(cls, method: str, raw_path: str, headers: Dict[str, str], body: bytes) -> "Request":
        parts = urlsplit(raw_path)
        return cls(
            method=method,
            path=parts.path,
            query_params=dict(parse_qsl(parts.query)),
            headers=headers,
            body=body,
        )

    def json(self) -> Any:
        return json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode("utf-8", errors="replace")

    def __repr__(self) -> str:
        return f"Request({self.method} {self.path}, {len(self.body)}B)"


class Response:
    """An explicit HTTP response from a deployment: status + headers +
    body.  The starlette ``Response`` seat — what the ``@serve.ingress``
    ASGI adapter returns, and what any deployment can return directly to
    control the status code.  Picklable (crosses the replica->proxy actor
    call)."""

    def __init__(self, body: Any = b"", status_code: int = 200,
                 headers: Optional[Dict[str, str]] = None,
                 content_type: Optional[str] = None):
        if isinstance(body, (bytes, bytearray)):
            self.body = bytes(body)
            default_ct = "application/octet-stream"
        elif isinstance(body, str):
            self.body = body.encode()
            default_ct = "text/plain; charset=utf-8"
        else:
            self.body = json.dumps(body).encode()
            default_ct = "application/json"
        self.status_code = int(status_code)
        self.headers = dict(headers or {})
        if content_type is not None:
            self.content_type = content_type
        else:
            self.content_type = self.headers.pop(
                "content-type", self.headers.pop("Content-Type", default_ct))

    def __repr__(self) -> str:
        return f"Response({self.status_code}, {len(self.body)}B)"


class StreamingResponse:
    """Return this from a deployment to stream the response body
    incrementally (the starlette StreamingResponse seat).  ``iterable``
    yields str/bytes chunks (anything else is JSON-encoded per chunk); the
    proxy delivers them with chunked transfer encoding as produced, pulling
    batches from the replica's stream registry."""

    def __init__(self, iterable, content_type: str = "text/plain"):
        self.iterable = iterable
        self.content_type = content_type


def encode_chunk(chunk: Any) -> bytes:
    if isinstance(chunk, bytes):
        return chunk
    if isinstance(chunk, str):
        return chunk.encode()
    return (json.dumps(chunk) + "\n").encode()


def encode_response(result: Any) -> tuple:
    """(body_bytes, content_type) for an HTTP response, mirroring the
    reference proxy's str/bytes/json handling (``http_util.py`` Response)."""
    if isinstance(result, bytes):
        return result, "application/octet-stream"
    if isinstance(result, str):
        return result.encode(), "text/plain; charset=utf-8"
    return json.dumps(result).encode(), "application/json"


def run_asgi_app(app, request: Request) -> Response:
    """Run one request through an ASGI application and collect the reply.

    The environment has no uvicorn, so this is the ASGI *server* half in
    ~40 lines: build an ``http`` scope from our picklable Request, feed
    the body through ``receive``, fold ``http.response.start`` /
    ``http.response.body`` messages into a :class:`Response`.  Runs the
    app on a private event loop (the replica executes requests on plain
    threads) — what ``@serve.ingress`` calls per request.
    """
    import asyncio
    from urllib.parse import urlencode

    state: Dict[str, Any] = {"status": 500, "headers": [],
                             "body": bytearray()}
    fed = {"done": False}

    async def receive():
        if fed["done"]:
            # the app asked again after consuming the body: a one-shot
            # request has nothing more to say
            return {"type": "http.disconnect"}
        fed["done"] = True
        return {"type": "http.request", "body": request.body or b"",
                "more_body": False}

    async def send(message):
        t = message.get("type")
        if t == "http.response.start":
            state["status"] = int(message.get("status", 200))
            state["headers"] = list(message.get("headers") or [])
        elif t == "http.response.body":
            state["body"] += message.get("body", b"")

    scope = {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": request.method,
        "scheme": "http",
        "path": request.path,
        "raw_path": request.path.encode("latin-1"),
        "query_string": urlencode(request.query_params).encode("latin-1"),
        "root_path": "",
        "headers": [(k.lower().encode("latin-1"), str(v).encode("latin-1"))
                    for k, v in request.headers.items()],
        "client": None,
        "server": None,
    }
    asyncio.run(app(scope, receive, send))
    headers = {}
    for k, v in state["headers"]:
        if isinstance(k, bytes):
            k = k.decode("latin-1")
        if isinstance(v, bytes):
            v = v.decode("latin-1")
        headers[k] = v
    return Response(bytes(state["body"]), status_code=state["status"],
                    headers=headers)


def parse_http_head(head: bytes) -> tuple:
    """Parse a raw request head (request line + header block, without the
    terminating blank line) into ``(method, raw_path, version, headers)``
    — the asyncio ingress's stand-in for http.server's parsing.  Header
    names keep the sender's ORIGINAL case (deployment code reading
    ``request.headers`` must see the same keys under both transports);
    callers needing case-insensitive lookups lowercase their own view.
    Raises ValueError on malformed input (the caller answers 400)."""
    lines = head.split(b"\r\n")
    try:
        method, raw_path, version = lines[0].decode("latin-1").split(" ", 2)
    except (UnicodeDecodeError, ValueError):
        raise ValueError(f"malformed request line: {lines[0][:80]!r}")
    headers: Dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        k, sep, v = line.partition(b":")
        if not sep:
            raise ValueError(f"malformed header line: {line[:80]!r}")
        headers[k.decode("latin-1").strip()] = v.decode("latin-1").strip()
    return method, raw_path, version, headers
