"""HTTP request/response plumbing shared by the proxy and replicas.

The reference hands replicas a starlette ``Request`` built by uvicorn
(``serve/_private/http_util.py``); this environment has no ASGI stack, so
``Request`` is a small picklable equivalent assembled by the stdlib proxy
and shipped to the replica over the actor call.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Optional
from urllib.parse import parse_qsl, urlsplit


class Request:
    """An HTTP request as seen by a deployment's ``__call__``.

    Mirrors the parts of starlette's Request that serve users touch:
    ``method``, ``path``, ``query_params``, ``headers``, ``body`` (bytes),
    and ``json()``.
    """

    def __init__(
        self,
        method: str = "GET",
        path: str = "/",
        query_params: Optional[Dict[str, str]] = None,
        headers: Optional[Dict[str, str]] = None,
        body: bytes = b"",
    ):
        self.method = method
        self.path = path
        self.query_params = query_params or {}
        self.headers = headers or {}
        self.body = body

    @classmethod
    def from_raw(cls, method: str, raw_path: str, headers: Dict[str, str], body: bytes) -> "Request":
        parts = urlsplit(raw_path)
        return cls(
            method=method,
            path=parts.path,
            query_params=dict(parse_qsl(parts.query)),
            headers=headers,
            body=body,
        )

    def json(self) -> Any:
        return json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode("utf-8", errors="replace")

    def __repr__(self) -> str:
        return f"Request({self.method} {self.path}, {len(self.body)}B)"


class StreamingResponse:
    """Return this from a deployment to stream the response body
    incrementally (the starlette StreamingResponse seat).  ``iterable``
    yields str/bytes chunks (anything else is JSON-encoded per chunk); the
    proxy delivers them with chunked transfer encoding as produced, pulling
    batches from the replica's stream registry."""

    def __init__(self, iterable, content_type: str = "text/plain"):
        self.iterable = iterable
        self.content_type = content_type


def encode_chunk(chunk: Any) -> bytes:
    if isinstance(chunk, bytes):
        return chunk
    if isinstance(chunk, str):
        return chunk.encode()
    return (json.dumps(chunk) + "\n").encode()


def encode_response(result: Any) -> tuple:
    """(body_bytes, content_type) for an HTTP response, mirroring the
    reference proxy's str/bytes/json handling (``http_util.py`` Response)."""
    if isinstance(result, bytes):
        return result, "application/octet-stream"
    if isinstance(result, str):
        return result.encode(), "text/plain; charset=utf-8"
    return json.dumps(result).encode(), "application/json"
