"""Router: picks a replica for each request under a concurrency cap.

Analog of ``python/ray/serve/_private/router.py:221`` (ReplicaSet with
``max_concurrent_queries``) + ``:261`` (assign_replica): least-loaded
selection among RUNNING replicas, counting this router's in-flight calls
per replica, blocking when every replica is at its cap until an in-flight
call drains.  Replica membership arrives via a LongPollClient-style
listener thread parked in the controller's ``listen_for_change`` (TTL pull
as fallback); routers also report ongoing-request counts that feed the
controller's autoscaler.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import events as _events
from ray_tpu.util import tracing as _tracing
from ray_tpu.serve.config import ROUTE_TABLE_TTL_S

# Lazy router metric singletons (tags: deployment).
_ROUTER_METRICS = None
# long-stall flight-recorder events are throttled per router
_STALL_EVENT_MIN_INTERVAL_S = 1.0


def _router_metrics():
    global _ROUTER_METRICS
    if _ROUTER_METRICS is None:
        from ray_tpu.util.metrics import Gauge, Histogram

        _ROUTER_METRICS = {
            "admission": Histogram(
                "ray_tpu_serve_admission_latency_s",
                "request arrival -> replica assignment latency (s)",
                boundaries=[0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5],
                tag_keys=("deployment",)),
            "queue_len": Gauge(
                "ray_tpu_serve_router_queue_len",
                "requests waiting for a replica in this router",
                tag_keys=("deployment",)),
        }
    return _ROUTER_METRICS


class Router:
    def __init__(self, controller_handle, deployment_name: str):
        import uuid

        self._controller = controller_handle
        self._name = deployment_name
        self._lock = threading.Lock()
        self._version = -1
        self._replicas: List[Tuple[str, Any]] = []  # (tag, ActorHandle)
        self._max_concurrent = 100
        self._last_refresh = 0.0
        # tag -> {oid: ObjectRef}: dict-keyed so on_request_done is O(1)
        self._inflight: Dict[str, Dict[bytes, Any]] = {}
        self._ref_tags: Dict[bytes, str] = {}  # oid -> tag for done-reports
        self._rr = 0  # round-robin tiebreak among equally-loaded replicas
        self._router_id = uuid.uuid4().hex[:12]  # raylint: disable=R3 (per router)
        # the session (client) this router belongs to: its poll/metrics
        # threads exit when the session is shut down or replaced
        from ray_tpu._private.worker import global_worker

        self._born_client = global_worker.client
        self._last_metrics_push = 0.0
        self._listener_started = False
        # callers inside assign_request that have not been assigned a
        # replica yet — queued demand the autoscaler must see
        self._pending = 0

    def _ensure_listener(self) -> None:
        """LongPollClient analog (``long_poll.py:68``): a daemon thread
        parks in the controller's listen_for_change and applies membership
        updates the moment they happen (the TTL pull stays as a fallback
        for missed notifications).  The threads hold only a weakref — when
        the Router is garbage-collected they exit on their next cycle, so
        handle churn can't leak threads or parked controller slots."""
        import weakref

        with self._lock:
            if self._listener_started:
                return
            self._listener_started = True
        ref = weakref.ref(self)
        t = threading.Thread(
            target=_listen_loop, args=(ref,), daemon=True,
            name=f"router-poll-{self._name}",
        )
        t.start()
        # periodic prune+report even when no requests arrive — without it a
        # gone-idle router's last (high) in-flight report would pin the
        # autoscaler at peak size until look_back_period expires
        m = threading.Thread(
            target=_metrics_loop, args=(ref,), daemon=True,
            name=f"router-metrics-{self._name}",
        )
        m.start()

    def _apply_routing_info(self, info: dict) -> None:
        with self._lock:
            self._last_refresh = time.monotonic()
            self._version = info["version"]
            self._max_concurrent = info["max_concurrent_queries"]
            self._replicas = info["replicas"]
            live = {tag for tag, _ in self._replicas}
            self._inflight = {
                tag: refs for tag, refs in self._inflight.items() if tag in live
            }
            self._ref_tags = {
                oid: tag for oid, tag in self._ref_tags.items() if tag in live
            }

    def _set_queue_gauge(self) -> None:
        """Mirror ``_pending`` into the router queue-length gauge (lock
        held).  Set on every transition — a gauge updated only on arrival
        would freeze at the last burst's peak forever."""
        if _events.ENABLED:
            _router_metrics()["queue_len"].set(
                self._pending, tags={"deployment": self._name})

    def _push_metrics(self) -> None:
        """Throttled fire-and-forget ongoing-request report feeding the
        controller's autoscaler."""
        now = time.monotonic()
        if now - self._last_metrics_push < 0.5:
            return
        self._last_metrics_push = now
        # ongoing = assigned + queued (the reference's num_ongoing_requests
        # counts queued handle requests too — autoscaling_policy.py)
        total = self._pending + sum(len(refs) for refs in self._inflight.values())
        try:
            self._controller.record_handle_metrics.remote(
                self._name, self._router_id, total
            )
        except Exception:
            pass

    # ------------------------------------------------------------------
    def _refresh(self, force: bool = False) -> None:
        import ray_tpu

        now = time.monotonic()
        if not force and now - self._last_refresh < ROUTE_TABLE_TTL_S:
            return
        info = ray_tpu.get(
            self._controller.get_routing_info.remote(self._name), timeout=30
        )
        if info is None:
            with self._lock:
                self._last_refresh = now
                self._replicas = []
            return
        self._apply_routing_info(info)

    def _prune_inflight(self) -> None:
        """Drop completed refs from the in-flight ledgers (lock held).
        Costs one head round trip — callers that finished via the fast
        path already reported through on_request_done, so this only runs
        when saturated or from the periodic metrics loop."""
        import ray_tpu

        for tag, refs in self._inflight.items():
            if not refs:
                continue
            ready, not_ready = ray_tpu.wait(
                list(refs.values()), num_returns=len(refs), timeout=0
            )
            self._inflight[tag] = {r.binary(): r for r in not_ready}
            for r in ready:
                self._ref_tags.pop(r.binary(), None)

    def on_request_done(self, ref) -> None:
        """Caller finished ``ray_tpu.get(ref)``: release the concurrency
        slot without a head round trip (the reference router decrements
        its in-flight counter from the completion callback the same way —
        ``router.py:221`` ReplicaSet)."""
        oid = ref.binary()
        with self._lock:
            tag = self._ref_tags.pop(oid, None)
            if tag is not None:
                self._inflight.get(tag, {}).pop(oid, None)

    def _pick(self) -> Optional[Tuple[str, Any]]:
        """Least-loaded replica under the cap, round-robin on ties (lock
        held).  None if every replica is saturated or none are RUNNING."""
        if not self._replicas:
            return None
        best = None
        best_load = None
        n = len(self._replicas)
        for i in range(n):
            tag, handle = self._replicas[(self._rr + i) % n]
            load = len(self._inflight.get(tag, ()))
            if load >= self._max_concurrent:
                continue
            if best_load is None or load < best_load:
                best, best_load = (tag, handle), load
        if best is not None:
            self._rr = (self._rr + 1) % n
        return best

    def assign_request(
        self,
        method_name: str,
        args: Tuple,
        kwargs: Dict,
        timeout: Optional[float] = 60.0,
        return_replica: bool = False,
    ):
        """Submit one request to a replica; returns the ObjectRef (or
        ``(ref, replica_handle)`` with ``return_replica`` — streaming
        responses need follow-up next_chunks calls on the SAME replica).
        Blocks while no replica is available (deployment still starting, or
        all at max_concurrent_queries)."""
        import ray_tpu
        from ray_tpu.exceptions import GetTimeoutError

        deadline = time.monotonic() + timeout if timeout is not None else None
        t_arrival = time.perf_counter()
        stall_reported = False
        # traced callers (HTTP ingress root or a user trace() block): the
        # admission wait becomes a child span and the replica task is
        # submitted UNDER it, so replica execution chains off the admission
        # in the assembled tree (tracing_helper's context-injection analog)
        trace_ctx = None
        if _events.ENABLED:
            trace_ctx = _tracing.child_context(f"admission {self._name}")
        self._ensure_listener()
        force = False
        with self._lock:
            self._pending += 1  # queued demand, visible to the autoscaler
            self._set_queue_gauge()
        assigned = False
        try:
            pruned = False
            while True:
                self._refresh(force=force)
                force = False
                with self._lock:
                    picked = self._pick()
                    if picked is None and not pruned:
                        # saturated by our own ledger: reconcile against
                        # the head once (callers that crashed before
                        # on_request_done would otherwise leak slots)
                        self._prune_inflight()
                        pruned = True
                        picked = self._pick()
                    if picked is not None:
                        tag, handle = picked
                        self._pending -= 1
                        self._set_queue_gauge()
                        assigned = True
                        if trace_ctx is not None:
                            token = _tracing.adopt(trace_ctx)
                            try:
                                ref = handle.handle_request.remote(
                                    method_name, args, kwargs)
                            finally:
                                _tracing.restore(token)
                        else:
                            ref = handle.handle_request.remote(
                                method_name, args, kwargs)
                        self._inflight.setdefault(tag, {})[ref.binary()] = ref
                        self._ref_tags[ref.binary()] = tag
                        self._push_metrics()
                        if _events.ENABLED:
                            waited = time.perf_counter() - t_arrival
                            _router_metrics()["admission"].observe(
                                waited, tags={"deployment": self._name})
                            # serve-admission span: arrival -> assignment
                            _events.emit(
                                "serve", f"admission {self._name}",
                                severity="DEBUG", entity_id=tag,
                                span_dur=waited)
                            if trace_ctx is not None:
                                _tracing.emit_span(
                                    f"admission {self._name}", waited,
                                    trace_ctx, phase="router_admission",
                                    replica=tag, deployment=self._name)
                        return (ref, handle) if return_replica else ref
                    self._push_metrics()
                    waitable = [r for refs in self._inflight.values()
                                for r in refs.values()]
                if _events.ENABLED and not stall_reported \
                        and time.perf_counter() - t_arrival > _STALL_EVENT_MIN_INTERVAL_S:
                    stall_reported = True
                    _events.emit(
                        "serve", "router stalled: no replica available",
                        severity="WARNING", entity_id=self._name,
                        pending=self._pending,
                        replicas=len(self._replicas))
                if deadline is not None and time.monotonic() >= deadline:
                    raise GetTimeoutError(
                        f"no replica of {self._name!r} available within {timeout}s"
                    )
                if waitable:
                    # our own backpressure: wait for one in-flight call to drain
                    ray_tpu.wait(waitable, num_returns=1, timeout=0.5)
                else:
                    # deployment still starting (or scaled to 0): poll membership
                    time.sleep(0.1)
                    force = True
        finally:
            if not assigned:
                with self._lock:
                    self._pending -= 1
                    self._set_queue_gauge()

    def on_replica_error(self, ref) -> None:
        """Caller observed a RayActorError from ``ref``: evict that replica
        locally and force the next assignment to re-pull membership (the
        reference router's replica-removal-on-failure path)."""
        oid = ref.binary()
        with self._lock:
            dead_tag = self._ref_tags.pop(oid, None)
            if dead_tag is not None:
                self._inflight.pop(dead_tag, None)
                self._ref_tags = {
                    o: t for o, t in self._ref_tags.items() if t != dead_tag
                }
                self._replicas = [
                    (t, h) for t, h in self._replicas if t != dead_tag
                ]
            self._last_refresh = 0.0


# ---------------------------------------------------------------------------
# background loops — module functions over a weakref so a dropped Router is
# collectable and its threads unwind instead of leaking
# ---------------------------------------------------------------------------


def _session_gone(router) -> bool:
    """The session this router was born in was shut down (or replaced):
    its threads must unwind instead of poking a dead/new head forever."""
    from ray_tpu._private.worker import global_worker

    client = getattr(router, "_born_client", None)
    return client is None or client.closed or global_worker.client is not client


def _listen_loop(router_ref) -> None:
    import ray_tpu

    while True:
        router = router_ref()
        if router is None:
            return
        if _session_gone(router):
            return
        controller, name, version = router._controller, router._name, router._version
        del router  # don't pin the Router across the blocking poll
        try:
            info = ray_tpu.get(
                controller.listen_for_change.remote(name, version, 30.0),
                timeout=45,
            )
        except Exception:
            time.sleep(1.0)
            continue
        router = router_ref()
        if router is None:
            return
        if info is not None:
            router._apply_routing_info(info)
        else:
            # deployment gone (deleted or not yet deployed): don't hammer
            # the controller with back-to-back polls
            time.sleep(1.0)


def _metrics_loop(router_ref) -> None:
    while True:
        time.sleep(2.0)
        router = router_ref()
        if router is None:
            return
        if _session_gone(router):
            return
        try:
            with router._lock:
                router._prune_inflight()
                router._push_metrics()
        except Exception:
            pass
