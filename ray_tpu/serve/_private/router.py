"""Router: picks a replica for each request under a concurrency cap.

Analog of ``python/ray/serve/_private/router.py:221`` (ReplicaSet with
``max_concurrent_queries``) + ``:261`` (assign_replica): least-loaded
selection among RUNNING replicas, counting this router's in-flight calls
per replica, blocking when every replica is at its cap until an in-flight
call drains.  Replica membership arrives via a LongPollClient-style
listener thread parked in the controller's ``listen_for_change`` (TTL pull
as fallback); routers also report ongoing-request counts that feed the
controller's autoscaler.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import events as _events
from ray_tpu.util import tracing as _tracing
from ray_tpu.serve.config import (
    REFRESH_BACKOFF_BASE_S,
    REFRESH_BACKOFF_CAP_S,
    ROUTE_TABLE_TTL_S,
    ROUTING_PULL_TIMEOUT_S,
    SHED_RETRY_AFTER_S,
)
from ray_tpu.serve.exceptions import BackPressureError

# Lazy router metric singletons (tags: deployment).
_ROUTER_METRICS = None
# long-stall flight-recorder events are throttled per router
_STALL_EVENT_MIN_INTERVAL_S = 1.0


def _router_metrics():
    global _ROUTER_METRICS
    if _ROUTER_METRICS is None:
        from ray_tpu.util.metrics import Counter, Gauge, Histogram

        _ROUTER_METRICS = {
            "admission": Histogram(
                "ray_tpu_serve_admission_latency_s",
                "request arrival -> replica assignment latency (s)",
                boundaries=[0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5],
                tag_keys=("deployment",)),
            "queue_len": Gauge(
                "ray_tpu_serve_router_queue_len",
                "requests waiting for a replica in this router",
                tag_keys=("deployment",)),
            "shed": Counter(
                "ray_tpu_serve_shed_total",
                "requests shed at the backlog watermark (503 + Retry-After)",
                tag_keys=("deployment",)),
        }
    return _ROUTER_METRICS


class Router:
    def __init__(self, controller_handle, deployment_name: str):
        import uuid

        self._controller = controller_handle
        self._name = deployment_name
        self._lock = threading.Lock()
        self._version = -1
        self._replicas: List[Tuple[str, Any]] = []  # (tag, ActorHandle)
        self._max_concurrent = 100
        self._last_refresh = 0.0
        # tag -> {oid: ObjectRef}: dict-keyed so on_request_done is O(1)
        self._inflight: Dict[str, Dict[bytes, Any]] = {}
        self._ref_tags: Dict[bytes, str] = {}  # oid -> tag for done-reports
        self._rr = 0  # round-robin tiebreak among equally-loaded replicas
        self._router_id = uuid.uuid4().hex[:12]  # raylint: disable=R3 (per router)
        # the session (client) this router belongs to: its poll/metrics
        # threads exit when the session is shut down or replaced
        from ray_tpu._private.worker import global_worker

        self._born_client = global_worker.client
        self._last_metrics_push = 0.0
        self._listener_started = False
        # callers inside assign_request that have not been assigned a
        # replica yet — queued demand the autoscaler must see
        self._pending = 0
        # load shedding: the controller-owned backlog watermark (-1 =
        # unbounded) plus hysteresis state so doctor gets a clean
        # started/stopped incident instead of one event per shed request
        self._max_queued = -1
        self._request_timeout = None  # deployment default deadline (s)
        self._shedding = False
        self._shed_count = 0
        # routing-refresh failure backoff (the stale table keeps serving
        # while the controller is unreachable)
        self._refresh_failures = 0
        self._next_refresh_attempt = 0.0
        # replicas observed dead by a caller (RayActorError): filtered out
        # of every routing snapshot until the controller itself stops
        # listing them — a forced re-pull of a stale table must not
        # resurrect a corpse for the retry that just evicted it
        self._dead_tags: Dict[str, float] = {}

    def _ensure_listener(self) -> None:
        """LongPollClient analog (``long_poll.py:68``): a daemon thread
        parks in the controller's listen_for_change and applies membership
        updates the moment they happen (the TTL pull stays as a fallback
        for missed notifications).  The threads hold only a weakref — when
        the Router is garbage-collected they exit on their next cycle, so
        handle churn can't leak threads or parked controller slots."""
        import weakref

        with self._lock:
            if self._listener_started:
                return
            self._listener_started = True
        ref = weakref.ref(self)
        t = threading.Thread(
            target=_listen_loop, args=(ref,), daemon=True,
            name=f"router-poll-{self._name}",
        )
        t.start()
        # periodic prune+report even when no requests arrive — without it a
        # gone-idle router's last (high) in-flight report would pin the
        # autoscaler at peak size until look_back_period expires
        m = threading.Thread(
            target=_metrics_loop, args=(ref,), daemon=True,
            name=f"router-metrics-{self._name}",
        )
        m.start()

    def _apply_routing_info(self, info: dict) -> None:
        with self._lock:
            self._last_refresh = time.monotonic()
            self._refresh_failures = 0
            self._next_refresh_attempt = 0.0
            self._version = info["version"]
            self._max_concurrent = info["max_concurrent_queries"]
            self._max_queued = info.get("max_queued_requests", -1)
            self._request_timeout = info.get("request_timeout_s")
            listed = {tag for tag, _ in info["replicas"]}
            # drop dead-tag memory once the controller agrees (its health
            # loop removed the replica) — tags are uuid-unique, so there
            # is no reuse to worry about
            self._dead_tags = {t: ts for t, ts in self._dead_tags.items()
                               if t in listed}
            self._replicas = [(t, h) for t, h in info["replicas"]
                              if t not in self._dead_tags]
            live = {tag for tag, _ in self._replicas}
            self._inflight = {
                tag: refs for tag, refs in self._inflight.items() if tag in live
            }
            self._ref_tags = {
                oid: tag for oid, tag in self._ref_tags.items() if tag in live
            }

    def _set_queue_gauge(self) -> None:
        """Mirror ``_pending`` into the router queue-length gauge (lock
        held).  Set on every transition — a gauge updated only on arrival
        would freeze at the last burst's peak forever."""
        if _events.ENABLED:
            _router_metrics()["queue_len"].set(
                self._pending, tags={"deployment": self._name})

    def _push_metrics(self) -> None:
        """Throttled fire-and-forget ongoing-request report feeding the
        controller's autoscaler."""
        now = time.monotonic()
        if now - self._last_metrics_push < 0.5:
            return
        self._last_metrics_push = now
        # ongoing = assigned + queued (the reference's num_ongoing_requests
        # counts queued handle requests too — autoscaling_policy.py)
        total = self._pending + sum(len(refs) for refs in self._inflight.values())
        try:
            self._controller.record_handle_metrics.remote(
                self._name, self._router_id, total
            )
        except Exception:
            pass

    # ------------------------------------------------------------------
    def _pull_routing_info(self):
        """One controller round trip (split out so tests can inject
        failures and the backoff logic stays testable)."""
        import ray_tpu

        return ray_tpu.get(
            self._controller.get_routing_info.remote(self._name),
            timeout=ROUTING_PULL_TIMEOUT_S,
        )

    def _refresh(self, force: bool = False) -> None:
        """TTL pull with bounded-backoff failure handling: a transient
        controller stall must NOT poison routing.  On a failed pull the
        stale routing table keeps serving and the next attempt backs off
        ``base * 2^n`` up to the cap (MetricsPusher's retry shape) — a
        `force` pull honors the backoff too, or a dead controller would
        eat one ROUTING_PULL_TIMEOUT_S per request."""
        now = time.monotonic()
        if not force and now - self._last_refresh < ROUTE_TABLE_TTL_S:
            return
        if self._refresh_failures and now < self._next_refresh_attempt:
            return  # backing off; the stale table keeps routing
        try:
            info = self._pull_routing_info()
        except Exception as e:  # noqa: BLE001 — controller stall/restart:
            # every failure mode gets the same stale-table-and-retry answer
            with self._lock:
                self._refresh_failures += 1
                delay = min(
                    REFRESH_BACKOFF_CAP_S,
                    REFRESH_BACKOFF_BASE_S * (2 ** (self._refresh_failures - 1)),
                )
                self._next_refresh_attempt = time.monotonic() + delay
                n_stale = len(self._replicas)
            if _events.ENABLED:
                _events.emit(
                    "serve", "routing refresh failed",
                    severity="WARNING", entity_id=self._name,
                    failures=self._refresh_failures, retry_in_s=round(delay, 2),
                    stale_replicas=n_stale,
                    error=f"{type(e).__name__}: {e}"[:200])
            return
        if info is None:
            with self._lock:
                self._last_refresh = now
                self._refresh_failures = 0
                self._next_refresh_attempt = 0.0
                self._replicas = []
            return
        self._apply_routing_info(info)

    def _prune_inflight(self) -> None:
        """Drop completed refs from the in-flight ledgers (lock held).
        Costs one head round trip — callers that finished via the fast
        path already reported through on_request_done, so this only runs
        when saturated or from the periodic metrics loop."""
        import ray_tpu

        for tag, refs in self._inflight.items():
            if not refs:
                continue
            ready, not_ready = ray_tpu.wait(
                list(refs.values()), num_returns=len(refs), timeout=0
            )
            self._inflight[tag] = {r.binary(): r for r in not_ready}
            for r in ready:
                self._ref_tags.pop(r.binary(), None)

    def on_request_done(self, ref) -> None:
        """Caller finished ``ray_tpu.get(ref)``: release the concurrency
        slot without a head round trip (the reference router decrements
        its in-flight counter from the completion callback the same way —
        ``router.py:221`` ReplicaSet)."""
        oid = ref.binary()
        with self._lock:
            tag = self._ref_tags.pop(oid, None)
            if tag is not None:
                self._inflight.get(tag, {}).pop(oid, None)

    @property
    def request_timeout_s(self) -> Optional[float]:
        """The deployment's default per-request deadline (config-owned;
        None until the first routing refresh lands or when unset)."""
        return self._request_timeout

    def _shed_locked(self) -> None:
        """Backlog at the watermark: refuse instead of queueing (lock
        held).  Raises BackPressureError after recording the shed.  The
        started/stopped episode pair is what doctor's ingress_shedding
        rule reads — per-shed volume rides the counter metric, not one
        event per refused request."""
        self._shed_count += 1
        if _events.ENABLED:
            _router_metrics()["shed"].inc(tags={"deployment": self._name})
            if not self._shedding:
                _events.emit(
                    "serve", "ingress shedding started",
                    severity="WARNING", entity_id=self._name,
                    queued=self._pending, max_queued=self._max_queued,
                    replicas=len(self._replicas))
        self._shedding = True
        raise BackPressureError(self._name, self._pending, self._max_queued,
                                retry_after_s=SHED_RETRY_AFTER_S)

    def _maybe_stop_shedding_locked(self) -> None:
        """Close the shedding episode once the backlog has drained to half
        the watermark (hysteresis: flapping around the watermark must not
        spray started/stopped pairs).  Lock held."""
        if self._shedding and (
                self._max_queued <= 0
                or self._pending <= self._max_queued // 2):
            self._shedding = False
            if _events.ENABLED:
                _events.emit(
                    "serve", "ingress shedding stopped", severity="INFO",
                    entity_id=self._name, queued=self._pending,
                    shed_total=self._shed_count)

    def _pick(self) -> Optional[Tuple[str, Any]]:
        """Least-loaded replica under the cap, round-robin on ties (lock
        held).  None if every replica is saturated or none are RUNNING."""
        if not self._replicas:
            return None
        best = None
        best_load = None
        n = len(self._replicas)
        for i in range(n):
            tag, handle = self._replicas[(self._rr + i) % n]
            load = len(self._inflight.get(tag, ()))
            if load >= self._max_concurrent:
                continue
            if best_load is None or load < best_load:
                best, best_load = (tag, handle), load
        if best is not None:
            self._rr = (self._rr + 1) % n
        return best

    def assign_request(
        self,
        method_name: str,
        args: Tuple,
        kwargs: Dict,
        timeout: Optional[float] = 60.0,
        return_replica: bool = False,
        deadline: Optional[float] = None,
    ):
        """Submit one request to a replica; returns the ObjectRef (or
        ``(ref, replica_handle)`` with ``return_replica`` — streaming
        responses need follow-up next_chunks calls on the SAME replica).
        Blocks while no replica is available (deployment still starting, or
        all at max_concurrent_queries) — up to the request's REMAINING
        deadline when the caller passes one (``deadline`` is a
        ``time.monotonic()`` timestamp and wins over ``timeout``: a
        5s-budget request must not queue for the 60s default).  Raises
        :class:`BackPressureError` instead of queueing when the queued
        backlog has reached the deployment's ``max_queued_requests``."""
        import ray_tpu
        from ray_tpu.exceptions import GetTimeoutError

        if deadline is None:
            deadline = (time.monotonic() + timeout
                        if timeout is not None else None)
        t_arrival = time.perf_counter()
        stall_reported = False
        # traced callers (HTTP ingress root or a user trace() block): the
        # admission wait becomes a child span and the replica task is
        # submitted UNDER it, so replica execution chains off the admission
        # in the assembled tree (tracing_helper's context-injection analog)
        trace_ctx = None
        if _events.ENABLED:
            trace_ctx = _tracing.child_context(f"admission {self._name}")
        self._ensure_listener()
        # refresh BEFORE the shed check so a just-raised watermark (or the
        # very first call) sheds against current config, not defaults
        self._refresh()
        force = False
        with self._lock:
            if 0 < self._max_queued <= self._pending:
                self._shed_locked()  # raises BackPressureError
            self._pending += 1  # queued demand, visible to the autoscaler
            self._set_queue_gauge()
        assigned = False
        try:
            pruned = False
            while True:
                self._refresh(force=force)
                force = False
                with self._lock:
                    picked = self._pick()
                    if picked is None and not pruned:
                        # saturated by our own ledger: reconcile against
                        # the head once (callers that crashed before
                        # on_request_done would otherwise leak slots)
                        self._prune_inflight()
                        pruned = True
                        picked = self._pick()
                    if picked is not None:
                        tag, handle = picked
                        self._pending -= 1
                        self._set_queue_gauge()
                        self._maybe_stop_shedding_locked()
                        assigned = True
                        if trace_ctx is not None:
                            token = _tracing.adopt(trace_ctx)
                            try:
                                ref = handle.handle_request.remote(
                                    method_name, args, kwargs)
                            finally:
                                _tracing.restore(token)
                        else:
                            ref = handle.handle_request.remote(
                                method_name, args, kwargs)
                        self._inflight.setdefault(tag, {})[ref.binary()] = ref
                        self._ref_tags[ref.binary()] = tag
                        self._push_metrics()
                        if _events.ENABLED:
                            waited = time.perf_counter() - t_arrival
                            _router_metrics()["admission"].observe(
                                waited, tags={"deployment": self._name})
                            # serve-admission span: arrival -> assignment
                            _events.emit(
                                "serve", f"admission {self._name}",
                                severity="DEBUG", entity_id=tag,
                                span_dur=waited)
                            if trace_ctx is not None:
                                _tracing.emit_span(
                                    f"admission {self._name}", waited,
                                    trace_ctx, phase="router_admission",
                                    replica=tag, deployment=self._name)
                        return (ref, handle) if return_replica else ref
                    self._push_metrics()
                    waitable = [r for refs in self._inflight.values()
                                for r in refs.values()]
                if _events.ENABLED and not stall_reported \
                        and time.perf_counter() - t_arrival > _STALL_EVENT_MIN_INTERVAL_S:
                    stall_reported = True
                    _events.emit(
                        "serve", "router stalled: no replica available",
                        severity="WARNING", entity_id=self._name,
                        pending=self._pending,
                        replicas=len(self._replicas))
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        raise GetTimeoutError(
                            f"no replica of {self._name!r} available within "
                            f"the request deadline "
                            f"(waited {time.perf_counter() - t_arrival:.1f}s)"
                        )
                else:
                    remaining = 0.5
                if waitable:
                    # our own backpressure: wait for one in-flight call to
                    # drain — never past the caller's remaining deadline
                    ray_tpu.wait(waitable, num_returns=1,
                                 timeout=min(0.5, max(remaining, 0.01)))
                else:
                    # deployment still starting (or scaled to 0): poll membership
                    time.sleep(0.1)
                    force = True
        finally:
            if not assigned:
                with self._lock:
                    self._pending -= 1
                    self._set_queue_gauge()
                    # queued callers leaving via timeout also drain the
                    # backlog — without this, an episode whose queue
                    # expired (hung replicas, deleted deployment) would
                    # stay an open doctor incident forever
                    self._maybe_stop_shedding_locked()

    def on_replica_error(self, ref) -> None:
        """Caller observed a RayActorError from ``ref``: evict that replica
        locally and force the next assignment to re-pull membership (the
        reference router's replica-removal-on-failure path)."""
        oid = ref.binary()
        with self._lock:
            dead_tag = self._ref_tags.pop(oid, None)
            if dead_tag is not None:
                self._dead_tags[dead_tag] = time.monotonic()
                self._inflight.pop(dead_tag, None)
                self._ref_tags = {
                    o: t for o, t in self._ref_tags.items() if t != dead_tag
                }
                self._replicas = [
                    (t, h) for t, h in self._replicas if t != dead_tag
                ]
            self._last_refresh = 0.0


# ---------------------------------------------------------------------------
# background loops — module functions over a weakref so a dropped Router is
# collectable and its threads unwind instead of leaking
# ---------------------------------------------------------------------------


def _session_gone(router) -> bool:
    """The session this router was born in was shut down (or replaced):
    its threads must unwind instead of poking a dead/new head forever."""
    from ray_tpu._private.worker import global_worker

    client = getattr(router, "_born_client", None)
    return client is None or client.closed or global_worker.client is not client


def _listen_loop(router_ref) -> None:
    import ray_tpu

    while True:
        router = router_ref()
        if router is None:
            return
        if _session_gone(router):
            return
        controller, name, version = router._controller, router._name, router._version
        del router  # don't pin the Router across the blocking poll
        try:
            info = ray_tpu.get(
                controller.listen_for_change.remote(name, version, 30.0),
                timeout=45,
            )
        except Exception:
            time.sleep(1.0)
            continue
        router = router_ref()
        if router is None:
            return
        if info is not None:
            router._apply_routing_info(info)
        else:
            # deployment gone (deleted or not yet deployed): don't hammer
            # the controller with back-to-back polls
            time.sleep(1.0)


def _metrics_loop(router_ref) -> None:
    while True:
        time.sleep(2.0)
        router = router_ref()
        if router is None:
            return
        if _session_gone(router):
            return
        try:
            with router._lock:
                router._prune_inflight()
                router._push_metrics()
        except Exception:
            pass
