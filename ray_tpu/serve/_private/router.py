"""Router: picks a replica for each request under a concurrency cap.

Analog of ``python/ray/serve/_private/router.py:221`` (ReplicaSet with
``max_concurrent_queries``) + ``:261`` (assign_replica): least-loaded
selection among RUNNING replicas, counting this router's in-flight calls
per replica, blocking when every replica is at its cap until an in-flight
call drains.  Each handle/proxy owns a Router (per-caller accounting, as in
the reference); the replica membership is pulled from the controller with a
short TTL instead of the reference's long-poll push.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.serve.config import ROUTE_TABLE_TTL_S


class Router:
    def __init__(self, controller_handle, deployment_name: str):
        self._controller = controller_handle
        self._name = deployment_name
        self._lock = threading.Lock()
        self._version = -1
        self._replicas: List[Tuple[str, Any]] = []  # (tag, ActorHandle)
        self._max_concurrent = 100
        self._last_refresh = 0.0
        self._inflight: Dict[str, List[Any]] = {}  # tag -> [ObjectRef]
        self._rr = 0  # round-robin tiebreak among equally-loaded replicas

    # ------------------------------------------------------------------
    def _refresh(self, force: bool = False) -> None:
        import ray_tpu

        now = time.monotonic()
        if not force and now - self._last_refresh < ROUTE_TABLE_TTL_S:
            return
        info = ray_tpu.get(
            self._controller.get_routing_info.remote(self._name), timeout=30
        )
        with self._lock:
            self._last_refresh = now
            if info is None:
                self._replicas = []
                return
            self._version = info["version"]
            self._max_concurrent = info["max_concurrent_queries"]
            self._replicas = info["replicas"]
            live = {tag for tag, _ in self._replicas}
            self._inflight = {
                tag: refs for tag, refs in self._inflight.items() if tag in live
            }

    def _prune_inflight(self) -> None:
        """Drop completed refs from the in-flight ledgers (lock held)."""
        import ray_tpu

        for tag, refs in self._inflight.items():
            if not refs:
                continue
            ready, not_ready = ray_tpu.wait(
                refs, num_returns=len(refs), timeout=0
            )
            self._inflight[tag] = not_ready

    def _pick(self) -> Optional[Tuple[str, Any]]:
        """Least-loaded replica under the cap, round-robin on ties (lock
        held).  None if every replica is saturated or none are RUNNING."""
        if not self._replicas:
            return None
        best = None
        best_load = None
        n = len(self._replicas)
        for i in range(n):
            tag, handle = self._replicas[(self._rr + i) % n]
            load = len(self._inflight.get(tag, ()))
            if load >= self._max_concurrent:
                continue
            if best_load is None or load < best_load:
                best, best_load = (tag, handle), load
        if best is not None:
            self._rr = (self._rr + 1) % n
        return best

    def assign_request(
        self,
        method_name: str,
        args: Tuple,
        kwargs: Dict,
        timeout: Optional[float] = 60.0,
    ):
        """Submit one request to a replica; returns the ObjectRef.  Blocks
        while no replica is available (deployment still starting, or all at
        max_concurrent_queries)."""
        import ray_tpu
        from ray_tpu.exceptions import GetTimeoutError

        deadline = time.monotonic() + timeout if timeout is not None else None
        force = False
        while True:
            self._refresh(force=force)
            force = False
            with self._lock:
                self._prune_inflight()
                picked = self._pick()
                if picked is not None:
                    tag, handle = picked
                    ref = handle.handle_request.remote(method_name, args, kwargs)
                    self._inflight.setdefault(tag, []).append(ref)
                    return ref
                waitable = [r for refs in self._inflight.values() for r in refs]
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(
                    f"no replica of {self._name!r} available within {timeout}s"
                )
            if waitable:
                # our own backpressure: wait for one in-flight call to drain
                ray_tpu.wait(waitable, num_returns=1, timeout=0.5)
            else:
                # deployment still starting (or scaled to 0): poll membership
                time.sleep(0.1)
                force = True

    def on_replica_error(self, ref) -> None:
        """Caller observed a RayActorError from ``ref``: evict that replica
        locally and force the next assignment to re-pull membership (the
        reference router's replica-removal-on-failure path)."""
        oid = ref.binary()
        with self._lock:
            dead_tag = None
            for tag, refs in self._inflight.items():
                if any(r.binary() == oid for r in refs):
                    dead_tag = tag
                    break
            if dead_tag is not None:
                self._inflight.pop(dead_tag, None)
                self._replicas = [
                    (t, h) for t, h in self._replicas if t != dead_tag
                ]
            self._last_refresh = 0.0
