"""ServeController: the serve control plane, as a named actor.

Analog of ``python/ray/serve/controller.py:61`` (ServeController) plus the
``DeploymentState`` reconciler (``serve/_private/deployment_state.py:958``):
holds declarative deployment goal state, diffs it against live replica
actors, and converges — creating replicas, replacing dead ones (detected by
a background health loop pinging each replica), scaling up/down, and
propagating ``user_config`` via ``reconfigure``.  Routers and proxies get
routing tables via ``listen_for_change`` — a LongPollHost-style blocking
poll (``serve/_private/long_poll.py:185``) parked on the controller's
threaded executor — with a TTL pull as fallback.  Demand-driven replica
autoscaling (``_private/autoscaling_policy.py`` analog) sizes deployments
from router-reported ongoing-request counts.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

import math

from ray_tpu._private import events as _events
from ray_tpu.serve.config import (
    MAX_CONSECUTIVE_START_FAILURES,
    DeploymentConfig,
    ReplicaState,
)

logger = logging.getLogger(__name__)

CONTROLLER_NAME = "SERVE_CONTROLLER"
HTTP_PROXY_NAME = "SERVE_HTTP_PROXY"
# Cluster-singleton serve infrastructure lives in a FIXED system
# namespace: named-actor lookups are namespace-scoped per tenant, and a
# controller registered in the deploying driver's namespace would be
# invisible to the dashboard/CLI/chaos (and a second tenant's
# serve.start() would boot a second controller + proxy on the same port).
SERVE_NAMESPACE = "serve"


class _Replica:
    __slots__ = ("tag", "handle", "state")

    def __init__(self, tag: str, handle, state: str = ReplicaState.STARTING):
        self.tag = tag
        self.handle = handle
        self.state = state


class _DeploymentState:
    """Goal + actual state for one deployment (deployment_state.py:958)."""

    def __init__(self, name: str, goal: dict):
        self.name = name
        self.goal = goal  # serialized_def/init_args/init_kwargs/config/route_prefix
        self.replicas: List[_Replica] = []
        # replicas out of the routing set, finishing in-flight requests
        # before termination (visible as DRAINING in get_status)
        self.draining: List[_Replica] = []
        self.version = 1
        self.deleting = False
        self.consecutive_failures = 0  # replica deaths with no RUNNING between
        self.unhealthy_reason: Optional[str] = None
        self.last_probe = 0.0
        # autoscaling: per-router ongoing-request reports + decision smoothing
        self.handle_metrics: Dict[str, Tuple[float, float]] = {}  # router -> (count, ts)
        self.scale_direction = 0  # sign of the pending decision
        self.scale_pending_since = 0.0

    @property
    def config(self) -> DeploymentConfig:
        return self.goal["config"]


class ServeController:
    def __init__(self, http_config: Optional[dict] = None):
        self._deployments: Dict[str, _DeploymentState] = {}
        self._lock = threading.RLock()
        # LongPollHost analog: routers park in listen_for_change on this
        # condition; every version bump notifies it (requires the controller
        # actor to run with max_concurrency > #parked listeners)
        self._changed = threading.Condition(self._lock)
        self._stopped = threading.Event()
        self._http_config = http_config or {}
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True, name="serve-health"
        )
        self._health_thread.start()
        self._autoscale_thread = threading.Thread(
            target=self._autoscale_loop, daemon=True, name="serve-autoscale"
        )
        self._autoscale_thread.start()

    # ------------------------------------------------------------------
    # control-plane API (called by serve.api / proxies / handles)
    # ------------------------------------------------------------------
    def deploy(self, name: str, goal: dict) -> bool:
        """Set/replace a deployment's goal state and converge toward it
        (``controller.py`` deploy -> DeploymentState.deploy analog)."""
        goal["config"].validate()
        auto = goal["config"].autoscaling_config
        with self._lock:
            state = self._deployments.get(name)
            if auto is not None:
                # the autoscaler owns num_replicas: new deployments start at
                # the floor; a redeploy keeps the current autoscaled size
                # (clamped to the new bounds) so config tweaks don't collapse
                # live capacity
                prev = state.config if state is not None else None
                if prev is not None and prev.autoscaling_config is not None:
                    goal["config"].num_replicas = max(
                        auto.min_replicas,
                        min(auto.max_replicas, prev.num_replicas),
                    )
                else:
                    goal["config"].num_replicas = auto.min_replicas
            if state is None:
                self._deployments[name] = state = _DeploymentState(name, goal)
            else:
                old = state.goal
                code_changed = (
                    old["serialized_def"] != goal["serialized_def"]
                    or old["init_args"] != goal["init_args"]
                    or old["init_kwargs"] != goal["init_kwargs"]
                )
                user_config_changed = (
                    old["config"].user_config != goal["config"].user_config
                )
                state.goal = goal
                state.deleting = False
                state.consecutive_failures = 0
                state.unhealthy_reason = None
                if code_changed:
                    # new code/args: replace every replica (simplified rolling
                    # update — the reference also versions replicas)
                    for r in list(state.replicas):
                        self._stop_replica(state, r)
                elif user_config_changed:
                    for r in state.replicas:
                        try:
                            r.handle.reconfigure.remote(goal["config"].user_config)
                        except Exception:
                            pass
                self._bump(state)
            self._reconcile(state)
        return True

    def delete_deployment(self, name: str) -> bool:
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return False
            state.deleting = True
            for r in list(state.replicas):
                self._stop_replica(state, r)
            del self._deployments[name]
            self._changed.notify_all()  # wake listeners on the deleted name
        return True

    def get_routing_info(self, name: str) -> Optional[dict]:
        """Routing snapshot for one deployment: consumed by Routers
        (replaces the reference's long-poll channel)."""
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return None
            return {
                "version": state.version,
                "max_concurrent_queries": state.config.max_concurrent_queries,
                "max_queued_requests": state.config.max_queued_requests,
                "request_timeout_s": state.config.request_timeout_s,
                "replicas": [
                    (r.tag, r.handle)
                    for r in state.replicas
                    if r.state == ReplicaState.RUNNING
                ],
            }

    def get_route_table(self) -> Dict[str, str]:
        """{route_prefix: deployment_name} for the HTTP proxy."""
        with self._lock:
            table = {}
            for name, state in self._deployments.items():
                prefix = state.goal.get("route_prefix")
                if prefix:
                    table[prefix] = name
            return table

    def get_status(self) -> Dict[str, dict]:
        with self._lock:
            out = {}
            for name, state in self._deployments.items():
                counts: Dict[str, int] = {}
                for r in state.replicas:
                    counts[r.state] = counts.get(r.state, 0) + 1
                if state.draining:
                    counts[ReplicaState.DRAINING] = len(state.draining)
                running = counts.get(ReplicaState.RUNNING, 0)
                goal_n = state.config.num_replicas
                if state.unhealthy_reason is not None:
                    status = "UNHEALTHY"
                elif running >= goal_n:
                    status = "HEALTHY"
                else:
                    status = "UPDATING"
                out[name] = {
                    "status": status,
                    "version": state.version,
                    "replica_states": counts,
                    "num_replicas_goal": goal_n,
                    "message": state.unhealthy_reason or "",
                }
            return out

    def list_deployments(self) -> List[str]:
        with self._lock:
            return list(self._deployments)

    # ------------------------------------------------------------------
    # declarative config deploy (serve/schema.py + serve_head.py analog)
    # ------------------------------------------------------------------
    def apply_deploy_config(self, config: dict) -> dict:
        """Reconcile live state to a validated declarative config: import
        each application's target, apply per-deployment overrides, deploy,
        and delete config-owned deployments the new config dropped.
        Code-deployed apps (serve.run) are left alone."""
        import cloudpickle
        import ray_tpu
        from ray_tpu.serve.api import Application
        from ray_tpu.serve.batching import uses_batching
        from ray_tpu.serve.handle import DeploymentHandle
        from ray_tpu.serve.schema import _UNSET, import_target, parse_deploy_config

        schema = parse_deploy_config(config)
        self_handle = ray_tpu.get_actor(CONTROLLER_NAME,
                                        namespace=SERVE_NAMESPACE)
        deployed: List[str] = []
        warnings: List[str] = []

        def deploy_app(app_schema, a, is_root: bool):
            d = a.deployment
            ov = next((o for o in app_schema.deployments
                       if o.name == d.name), None)
            if ov is not None:
                d = d.options(
                    num_replicas=ov.num_replicas,
                    max_concurrent_queries=ov.max_concurrent_queries,
                    user_config=ov.user_config,
                    ray_actor_options=ov.ray_actor_options,
                    route_prefix=ov.route_prefix,  # shares options()'s
                    # "__unset__" sentinel value
                    autoscaling_config=(ov.autoscaling_config
                                        if ov.autoscaling_config is not None
                                        else "__unset__"),
                )
            if (is_root and app_schema.route_prefix != _UNSET
                    and (ov is None or ov.route_prefix == _UNSET)):
                d = d.options(route_prefix=app_schema.route_prefix)
            args = tuple(
                deploy_app(app_schema, v, False) if isinstance(v, Application)
                else v for v in a.args)
            kwargs = {
                k: deploy_app(app_schema, v, False) if isinstance(v, Application)
                else v for k, v in a.kwargs.items()}
            goal = {
                "serialized_def": cloudpickle.dumps(d._func_or_class),
                "init_args": args,
                "init_kwargs": kwargs,
                "config": d.config,
                "route_prefix": d.route_prefix,
                "uses_batching": uses_batching(d._func_or_class),
            }
            self.deploy(d.name, goal)
            deployed.append(d.name)
            return DeploymentHandle(d.name, self_handle)

        for app_schema in schema.applications:
            if app_schema.runtime_env:
                warnings.append(
                    f"app {app_schema.name!r}: runtime_env is recorded but "
                    "not applied to config imports (import_path must be "
                    "importable in the controller's environment)")
            deploy_app(app_schema, import_target(app_schema.import_path), True)

        prev_owned = set(getattr(self, "_config_owned", ()))
        for name in prev_owned - set(deployed):
            self.delete_deployment(name)
        self._config_owned = set(deployed)
        self._goal_config = schema.to_dict()
        out = {"deployed": deployed}
        if warnings:
            out["warnings"] = warnings
        return out

    def get_deploy_config(self) -> Optional[dict]:
        """The last applied declarative config (goal), or None."""
        return getattr(self, "_goal_config", None)

    def graceful_shutdown(self) -> bool:
        """Kill every replica; the controller actor itself is killed by
        serve.shutdown() afterwards."""
        self._stopped.set()
        with self._lock:
            for state in self._deployments.values():
                for r in list(state.replicas):
                    self._stop_replica(state, r)
            self._deployments.clear()
            self._changed.notify_all()  # release parked long-poll listeners
        return True

    def ping(self) -> str:
        return "pong"

    def _bump(self, state: _DeploymentState) -> None:
        """Version bump + wake every parked long-poll listener (lock held)."""
        state.version += 1
        self._changed.notify_all()

    def listen_for_change(
        self, name: str, known_version: int, timeout_s: float = 30.0
    ) -> Optional[dict]:
        """LongPollHost analog (``serve/_private/long_poll.py:185``): block
        until the deployment's routing info is newer than ``known_version``
        (or the timeout lapses), then return the fresh snapshot.  Runs on
        the controller's threaded executor, so parked listeners don't block
        other control-plane calls."""
        deadline = time.monotonic() + timeout_s
        with self._lock:
            while not self._stopped.is_set():
                state = self._deployments.get(name)
                if state is None or state.version != known_version:
                    break
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._changed.wait(remaining)
        return self.get_routing_info(name)

    # ------------------------------------------------------------------
    # autoscaling (serve/_private/autoscaling_policy.py analog)
    # ------------------------------------------------------------------
    def record_handle_metrics(
        self, name: str, router_id: str, num_ongoing: float
    ) -> None:
        """Routers report their in-flight request count here (the
        reference's handle autoscaling-metrics push)."""
        with self._lock:
            state = self._deployments.get(name)
            if state is not None:
                state.handle_metrics[router_id] = (float(num_ongoing), time.monotonic())

    def get_autoscaling_metrics(self, name: str) -> Optional[dict]:
        """Live router load reports for one deployment (observability)."""
        with self._lock:
            state = self._deployments.get(name)
            if state is None:
                return None
            now = time.monotonic()
            return {
                rid: {"ongoing": c, "age_s": now - ts}
                for rid, (c, ts) in state.handle_metrics.items()
            }

    def scale_deployment(self, name: str, delta: int = 0,
                         num_replicas: Optional[int] = None) -> Optional[int]:
        """Externally-driven replica scaling — the hook the trend
        autoscaler's ``replica_scaler`` calls when router-backlog slope
        says capacity must arrive before the queue becomes an incident.
        Clamped to the deployment's autoscaling bounds (when configured)
        so an external scaler and the demand autoscaler can coexist.
        Returns the new goal, or None for an unknown deployment."""
        with self._lock:
            state = self._deployments.get(name)
            if state is None or state.deleting:
                return None
            cur = state.config.num_replicas
            target = num_replicas if num_replicas is not None else cur + int(delta)
            auto = state.config.autoscaling_config
            if auto is not None:
                target = max(auto.min_replicas, min(auto.max_replicas, target))
            target = max(0, target)
            if target != cur:
                _events.emit(
                    "serve", "deployment scaled", severity="INFO",
                    entity_id=name, prev=cur, goal=target)
                logger.info("serve: external scale %s %d -> %d",
                            name, cur, target)
                state.config.num_replicas = target
                self._reconcile(state)
                self._bump(state)
            return target

    def _autoscale_once(self, state: _DeploymentState, now: float) -> None:
        """One scaling decision for one deployment (lock held)."""
        cfg = state.config.autoscaling_config
        if cfg is None or state.deleting or state.unhealthy_reason:
            return
        # drop reports from routers that stopped reporting (dead handles) —
        # freshness-filtering alone would leak one entry per router ever seen
        stale = [
            rid for rid, (_, ts) in state.handle_metrics.items()
            if now - ts > cfg.look_back_period_s
        ]
        for rid in stale:
            del state.handle_metrics[rid]
        total_ongoing = sum(c for c, _ in state.handle_metrics.values())
        desired = math.ceil(
            total_ongoing / cfg.target_num_ongoing_requests_per_replica
        )
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        current = state.config.num_replicas
        direction = (desired > current) - (desired < current)
        if direction == 0:
            state.scale_direction = 0
            return
        if direction != state.scale_direction:
            state.scale_direction = direction
            state.scale_pending_since = now
            return
        delay = cfg.upscale_delay_s if direction > 0 else cfg.downscale_delay_s
        if now - state.scale_pending_since < delay:
            return
        logger.info(
            "serve: autoscaling %s %d -> %d (ongoing=%.1f)",
            state.name, current, desired, total_ongoing,
        )
        state.config.num_replicas = desired
        state.scale_direction = 0
        self._reconcile(state)
        self._bump(state)

    def _autoscale_loop(self) -> None:
        while not self._stopped.is_set():
            now = time.monotonic()
            with self._lock:
                for state in list(self._deployments.values()):
                    self._autoscale_once(state, now)
            self._stopped.wait(0.5)

    # ------------------------------------------------------------------
    # reconciliation (deployment_state.py:958 update loop)
    # ------------------------------------------------------------------
    def _reconcile(self, state: _DeploymentState) -> None:
        """Converge one deployment's replica set toward its goal.  Caller
        holds the lock."""
        if state.unhealthy_reason is not None:
            return  # crash-looping: stop churning workers until redeployed
        goal_n = state.config.num_replicas
        live = [r for r in state.replicas if r.state in (ReplicaState.STARTING, ReplicaState.RUNNING)]
        for _ in range(goal_n - len(live)):
            self._start_replica(state)
        if len(live) > goal_n:
            # scale down: drop STARTING replicas first, newest first
            victims = sorted(
                live, key=lambda r: (r.state == ReplicaState.RUNNING,)
            )[: len(live) - goal_n]
            for r in victims:
                self._stop_replica(state, r)
            self._bump(state)

    def _start_replica(self, state: _DeploymentState) -> None:
        import ray_tpu
        from ray_tpu.serve._private.replica import ServeReplica

        goal = state.goal
        tag = f"{state.name}#{uuid.uuid4().hex[:8]}"
        options = dict(goal["config"].ray_actor_options or {})
        # control-plane concurrency group: health pings and drain polls
        # run in their OWN bounded pool on the replica worker, so a
        # saturated request lane can never starve them (the PR 12 ingress
        # exposure this group exists to close).  User code still runs on
        # the default lane: serialized unless batching raises it.
        # MERGED into any user-declared groups — setdefault would drop
        # "control" whenever ray_actor_options declares its own groups,
        # and with it every health probe.
        groups = dict(options.get("concurrency_groups") or {})
        groups.setdefault("control", 2)
        options["concurrency_groups"] = groups
        # replicas are serve infrastructure managed (and explicitly
        # killed) by the detached controller: the tenant-disconnect reap
        # must not SIGKILL them past the graceful drain path just because
        # the driver that deployed the app went away
        options.setdefault("lifetime", "detached")
        if goal.get("uses_batching"):
            # @serve.batch replicas execute up to their query cap
            # concurrently so batches can form; user code still runs on
            # the single batcher thread.  Plain deployments stay
            # serialized — unsynchronized state must not start racing.
            options.setdefault(
                "max_concurrency", goal["config"].max_concurrent_queries
            )
        handle = ray_tpu.remote(ServeReplica).options(**options).remote(
            state.name,
            tag,
            goal["serialized_def"],
            goal["init_args"],
            goal["init_kwargs"],
            goal["config"].user_config,
        )
        state.replicas.append(_Replica(tag, handle))
        logger.info("serve: starting replica %s", tag)

    def _stop_replica(self, state: _DeploymentState, replica: _Replica) -> None:
        """Graceful replica termination: stop assigning, finish in-flight,
        then terminate.  Three ordered moves (caller holds the lock):

        1. out of the routing set + version bump — routers stop assigning
           to it before it learns it is draining (so ReplicaDrainingError
           is a race, not a steady state);
        2. background drain: ``prepare_for_drain`` flips the replica's
           accept flag, then ``drain_status`` is polled until in-flight
           requests AND live streams hit zero or the graceful window
           lapses (a timeout means accepted work WOULD have been lost —
           doctor's drain_stuck food);
        3. the user's shutdown hook, then ``kill``.

        Scale-downs, code redeploys, autoscaler shrink and replica
        replacement all route through here, so every deliberate
        termination gets the same no-lost-requests story."""
        import ray_tpu

        replica.state = ReplicaState.DRAINING
        if replica in state.replicas:
            state.replicas.remove(replica)
        state.draining.append(replica)
        self._bump(state)
        grace = state.config.graceful_shutdown_timeout_s
        dep_name = state.name

        def drain():
            from ray_tpu.exceptions import GetTimeoutError

            t0 = time.monotonic()
            deadline = t0 + grace
            _events.emit(
                "serve", "replica draining", severity="INFO",
                entity_id=replica.tag, deployment=dep_name, grace_s=grace)
            pending = None
            died = None
            try:
                # control group: the drain flag flips and the polls answer
                # even while the request lane is saturated (previously
                # these queued behind every accepted request and a slow
                # lane starved the drain).  grace_s lets the replica keep
                # serving stale-router racers inside the window (refusing
                # only once a kill is imminent).
                st = ray_tpu.get(
                    replica.handle.prepare_for_drain.options(
                        concurrency_group="control").remote(
                        grace_s=max(deadline - time.monotonic(), 0.1)),
                    timeout=max(deadline - time.monotonic(), 0.1))
                while (st.get("inflight", 0) > 0 or st.get("streams", 0) > 0):
                    if time.monotonic() >= deadline:
                        pending = st
                        break
                    time.sleep(0.1)
                    st = ray_tpu.get(replica.handle.drain_status.options(
                        concurrency_group="control").remote(),
                        timeout=max(deadline - time.monotonic(), 0.1))
                if not pending:
                    # default-lane barrier: a request ACCEPTED before the
                    # drain but still queued at the worker is invisible to
                    # the inflight gauge — this call rides the same FIFO
                    # lane, so its reply proves the lane is empty (the
                    # airtight everything-accepted-finished guarantee the
                    # queued-behind-requests drain used to give)
                    ray_tpu.get(replica.handle.drain_status.remote(),
                                timeout=max(deadline - time.monotonic(), 0.1))
            except GetTimeoutError:
                # never reached the replica inside the window — a request
                # is still occupying its executor (the cut-off case)
                pending = {"inflight": 1, "streams": 0, "confirmed": False}
            except Exception as e:  # noqa: BLE001 — replica died mid-
                # drain: NOT a clean drain (anything it was running is
                # lost), but also not a cutoff we chose
                died = f"{type(e).__name__}: {e}"[:200]
            if died is not None:
                _events.emit(
                    "serve", "replica died while draining",
                    severity="WARNING", entity_id=replica.tag,
                    deployment=dep_name, error=died)
            elif pending is None:
                _events.emit(
                    "serve", "replica drained", severity="INFO",
                    entity_id=replica.tag, deployment=dep_name,
                    wait_s=round(time.monotonic() - t0, 3))
            else:
                _events.emit(
                    "serve", "replica drain timeout", severity="WARNING",
                    entity_id=replica.tag, deployment=dep_name,
                    inflight=pending.get("inflight", 0),
                    streams=pending.get("streams", 0), grace_s=grace)
            try:
                fut = replica.handle.prepare_for_shutdown.remote()
                ray_tpu.get(fut, timeout=max(deadline - time.monotonic(), 1.0))
            except Exception:
                pass
            try:
                ray_tpu.kill(replica.handle)
            except Exception:
                pass
            replica.state = ReplicaState.DEAD
            with self._lock:
                if replica in state.draining:
                    state.draining.remove(replica)

        threading.Thread(target=drain, daemon=True, name=f"drain-{replica.tag}").start()

    # ------------------------------------------------------------------
    # health loop (GcsHealthCheckManager-style active probing of replicas)
    # ------------------------------------------------------------------
    def _health_loop(self) -> None:
        import ray_tpu

        while not self._stopped.is_set():
            now = time.monotonic()
            with self._lock:
                probes: List[Tuple[_DeploymentState, _Replica, Any]] = []
                for state in self._deployments.values():
                    if now - state.last_probe < state.config.health_check_period_s:
                        continue
                    state.last_probe = now
                    for r in state.replicas:
                        if r.state in (ReplicaState.STARTING, ReplicaState.RUNNING):
                            try:
                                # control group: a replica saturated with
                                # slow requests still answers its health
                                # probe (liveness, not busyness)
                                probes.append((state, r, r.handle.ping.options(
                                    concurrency_group="control").remote()))
                            except Exception:
                                pass
            if probes:
                # one shared wait bounds the cycle regardless of replica
                # count; non-ready pings mean "busy/starting", not dead
                refs = [fut for _, _, fut in probes]
                ready, _ = ray_tpu.wait(refs, num_returns=len(refs), timeout=2.0)
                ready_set = {r.binary() for r in ready}
                for state, r, fut in probes:
                    if fut.binary() not in ready_set:
                        continue
                    try:
                        ray_tpu.get(fut, timeout=5.0)
                        alive = True
                    except Exception:
                        alive = False
                    with self._lock:
                        if r not in state.replicas:
                            continue
                        if alive:
                            if r.state == ReplicaState.STARTING:
                                r.state = ReplicaState.RUNNING
                                self._bump(state)
                                state.consecutive_failures = 0
                                logger.info("serve: replica %s RUNNING", r.tag)
                        else:
                            state.replicas.remove(r)
                            self._bump(state)
                            if r.state == ReplicaState.STARTING:
                                state.consecutive_failures += 1
                            if (
                                state.consecutive_failures
                                >= MAX_CONSECUTIVE_START_FAILURES
                            ):
                                state.unhealthy_reason = (
                                    f"replicas failed to start "
                                    f"{state.consecutive_failures} times in a "
                                    "row; giving up until next deploy"
                                )
                                logger.error(
                                    "serve: deployment %s UNHEALTHY: %s",
                                    state.name, state.unhealthy_reason,
                                )
                            elif not state.deleting:
                                logger.warning(
                                    "serve: replica %s died; replacing", r.tag
                                )
                                self._reconcile(state)
            self._stopped.wait(0.25)
