"""ray_tpu.serve — model serving on TPU-backed replicas.

Analog of ``python/ray/serve`` (SURVEY §3.6): a controller actor reconciles
declarative deployment state into replica actors (``num_tpus=1`` replicas
for BASELINE config 5), handles route through a least-loaded router under a
max-concurrent-queries cap, and an HTTP proxy actor exposes deployments
over REST.
"""

from ray_tpu.serve.api import (
    Application,
    Deployment,
    delete,
    deployment,
    get_deployment_handle,
    get_http_address,
    ingress,
    run,
    shutdown,
    start,
    status,
)
from ray_tpu.serve.batching import batch
from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from ray_tpu.serve.exceptions import (
    BackPressureError,
    RayServeException,
    ReplicaDrainingError,
)
from ray_tpu.serve.handle import DeploymentHandle
from ray_tpu.serve._private.http_util import Request, Response, StreamingResponse

__all__ = [
    "StreamingResponse",
    "Response",
    "deployment",
    "Deployment",
    "DeploymentConfig",
    "AutoscalingConfig",
    "batch",
    "Application",
    "ingress",
    "run",
    "start",
    "delete",
    "status",
    "shutdown",
    "get_deployment_handle",
    "get_http_address",
    "DeploymentHandle",
    "HTTPOptions",
    "Request",
    "RayServeException",
    "BackPressureError",
    "ReplicaDrainingError",
]
