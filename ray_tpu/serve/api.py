"""serve public API: @deployment, run, status, delete, shutdown.

Analog of ``python/ray/serve/api.py`` (``@serve.deployment`` ``:251-277``,
``serve.run`` ``:455``) + ``serve/deployment.py:35`` (Deployment): the
declarative surface users touch.  ``Deployment.bind`` builds an
``Application`` graph (nested bound deployments become DeploymentHandles in
the parent's constructor — the deployment-graph composition path); ``run``
ships it to the controller and blocks until every deployment is healthy.
"""

from __future__ import annotations

import copy
import time
from typing import Any, Callable, Dict, Optional, Tuple, Union

import cloudpickle

from ray_tpu.serve.config import AutoscalingConfig, DeploymentConfig, HTTPOptions
from ray_tpu.serve.handle import DeploymentHandle

_client: Optional["_ServeClient"] = None


def _coerce_autoscaling(v) -> Optional[AutoscalingConfig]:
    if v is None or isinstance(v, AutoscalingConfig):
        return v
    if isinstance(v, dict):
        return AutoscalingConfig(**v)
    raise TypeError(f"autoscaling_config must be a dict or AutoscalingConfig, got {type(v)}")


class Deployment:
    """A deployment definition (``serve/deployment.py:35`` analog).
    Immutable; ``options()`` returns a modified copy."""

    def __init__(
        self,
        func_or_class: Union[Callable, type],
        name: str,
        config: Optional[DeploymentConfig] = None,
        route_prefix: Optional[str] = "__auto__",
    ):
        self._func_or_class = func_or_class
        self.name = name
        self.config = config or DeploymentConfig()
        # "__auto__" -> "/<name>"; None -> not HTTP-exposed
        self.route_prefix = f"/{name}" if route_prefix == "__auto__" else route_prefix

    def options(
        self,
        name: Optional[str] = None,
        num_replicas: Optional[int] = None,
        max_concurrent_queries: Optional[int] = None,
        user_config: Optional[Any] = None,
        ray_actor_options: Optional[Dict] = None,
        route_prefix: Optional[str] = "__unset__",
        autoscaling_config: Optional[Any] = "__unset__",
        max_queued_requests: Optional[int] = None,
        request_timeout_s: Optional[Any] = "__unset__",
    ) -> "Deployment":
        cfg = copy.deepcopy(self.config)
        if num_replicas is not None:
            cfg.num_replicas = num_replicas
        if autoscaling_config != "__unset__":
            cfg.autoscaling_config = _coerce_autoscaling(autoscaling_config)
        if max_concurrent_queries is not None:
            cfg.max_concurrent_queries = max_concurrent_queries
        if user_config is not None:
            cfg.user_config = user_config
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if max_queued_requests is not None:
            cfg.max_queued_requests = max_queued_requests
        if request_timeout_s != "__unset__":
            cfg.request_timeout_s = request_timeout_s
        d = Deployment(
            self._func_or_class,
            name or self.name,
            cfg,
            route_prefix="__auto__",
        )
        d.route_prefix = (
            self.route_prefix if route_prefix == "__unset__" else route_prefix
        )
        if name and d.route_prefix == f"/{self.name}":
            d.route_prefix = f"/{name}"
        return d

    def bind(self, *args, **kwargs) -> "Application":
        """Bind constructor args, producing an Application DAG node
        (``deployment.py`` bind / DAG build analog).  Args may contain other
        Applications — they deploy first and arrive as handles."""
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment(name={self.name!r}, num_replicas={self.config.num_replicas})"


class Application:
    """A bound deployment graph node (``serve.built_application`` analog)."""

    def __init__(self, deployment: Deployment, args: Tuple, kwargs: Dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


def deployment(
    _func_or_class: Optional[Union[Callable, type]] = None,
    *,
    name: Optional[str] = None,
    num_replicas: int = 1,
    max_concurrent_queries: int = 100,
    user_config: Optional[Any] = None,
    ray_actor_options: Optional[Dict] = None,
    route_prefix: Optional[str] = "__auto__",
    autoscaling_config: Optional[Any] = None,
    max_queued_requests: int = -1,
    request_timeout_s: Optional[float] = None,
) -> Union[Deployment, Callable[[Callable], Deployment]]:
    """``@serve.deployment`` decorator (``api.py:251`` analog)."""

    def make(func_or_class):
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            user_config=user_config,
            ray_actor_options=dict(ray_actor_options or {}),
            autoscaling_config=_coerce_autoscaling(autoscaling_config),
            max_queued_requests=max_queued_requests,
            request_timeout_s=request_timeout_s,
        )
        return Deployment(
            func_or_class,
            name or func_or_class.__name__,
            cfg,
            route_prefix=route_prefix,
        )

    if _func_or_class is not None:
        return make(_func_or_class)
    return make


def ingress(app) -> Callable[[type], type]:
    """Mount an ASGI application as a deployment class's HTTP surface
    (``@serve.ingress(fastapi_app)`` analog, ``serve/api.py`` ingress).

    The wrapped class's ``__call__`` feeds every routed HTTP request
    through the ASGI protocol (scope/receive/send — see
    ``http_util.run_asgi_app``) and returns the app's reply as a
    :class:`Response`, so status codes and headers survive to the client.
    The app sees the FULL request path in ``scope["path"]`` (with
    ``root_path=""``) and can route on it; non-HTTP callers (plain
    ``handle.remote(...)``) still reach the class's other methods
    directly.

    Usage::

        @serve.deployment
        @serve.ingress(asgi_app)
        class MyApp:
            def health(self):   # handle.health.remote() still works
                return "ok"
    """

    def decorator(cls: type) -> type:
        if not isinstance(cls, type):
            raise TypeError(
                "@serve.ingress decorates a class (put it UNDER "
                "@serve.deployment); got " + repr(cls))

        class ASGIIngressWrapper(cls):
            __serve_asgi_app__ = staticmethod(app)

            def __call__(self, request):
                from ray_tpu.serve._private.http_util import (
                    Request as _HttpRequest,
                    run_asgi_app,
                )

                if not isinstance(request, _HttpRequest):
                    raise TypeError(
                        "@serve.ingress deployments serve HTTP requests; "
                        "call named methods via handle.<method>.remote() "
                        "for direct access")
                return run_asgi_app(app, request)

        ASGIIngressWrapper.__name__ = cls.__name__
        ASGIIngressWrapper.__qualname__ = cls.__qualname__
        ASGIIngressWrapper.__module__ = cls.__module__
        return ASGIIngressWrapper

    return decorator


# ---------------------------------------------------------------------------
# client / lifecycle
# ---------------------------------------------------------------------------


class _ServeClient:
    """Driver-side connection to the serve control plane
    (``_private/client.py`` ServeControllerClient analog)."""

    def __init__(self, controller, proxy=None, http=None):
        self.controller = controller
        self.proxy = proxy
        self.http = http  # (host, port) or None


def start(http_options: Optional[HTTPOptions] = None, _http: bool = True) -> _ServeClient:
    """Start (or connect to) the serve instance: controller + HTTP proxy
    (``serve.start`` analog)."""
    global _client
    import ray_tpu
    from ray_tpu.serve._private.controller import (
        CONTROLLER_NAME, HTTP_PROXY_NAME, SERVE_NAMESPACE, ServeController)
    from ray_tpu.serve._private.http_proxy import HTTPProxyActor

    ray_tpu.init()
    if _client is not None:
        try:
            ray_tpu.get(_client.controller.ping.remote(), timeout=10)
            return _client
        except Exception:
            _client = None  # stale (previous ray session); rebuild

    try:
        controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                       namespace=SERVE_NAMESPACE)
        ray_tpu.get(controller.ping.remote(), timeout=10)
    except Exception:
        controller = (
            ray_tpu.remote(ServeController)
            # threaded executor: every router parks one 30 s long-poll here,
            # so headroom must exceed any realistic router count or the
            # control plane wedges behind parked listeners.  Detached:
            # the serve instance is cluster infrastructure — it must
            # survive the deploying driver's disconnect (multi-tenancy
            # reaps a job's non-detached actors when its driver dies)
            .options(name=CONTROLLER_NAME, namespace=SERVE_NAMESPACE,
                     max_concurrency=512, lifetime="detached")
            .remote()
        )
        ray_tpu.get(controller.ping.remote(), timeout=60)

    proxy = None
    http = None
    if _http:
        opts = http_options or HTTPOptions()
        # get-or-create like the controller: a second driver's start()
        # must REUSE the live proxy, not bind a second one to the same
        # port (named + detached in the serve system namespace so it is
        # findable across tenants and survives its creator)
        try:
            proxy = ray_tpu.get_actor(HTTP_PROXY_NAME,
                                      namespace=SERVE_NAMESPACE)
            http = tuple(ray_tpu.get(proxy.ready.remote(), timeout=10))
        except Exception:
            proxy = ray_tpu.remote(HTTPProxyActor).options(
                name=HTTP_PROXY_NAME, namespace=SERVE_NAMESPACE,
                lifetime="detached").remote(
                opts.host, opts.port,
                async_ingress=opts.async_ingress,
                num_exec_threads=opts.num_exec_threads,
                max_inflight_requests=opts.max_inflight_requests,
            )
            http = tuple(ray_tpu.get(proxy.ready.remote(), timeout=60))
    _client = _ServeClient(controller, proxy, http)
    return _client


def _get_client() -> _ServeClient:
    if _client is None:
        raise RuntimeError("serve not started — call serve.run()/serve.start() first")
    return _client


def _deploy_application(
    client: _ServeClient, app: Application, deployed_names: Optional[list] = None
) -> DeploymentHandle:
    """Depth-first deploy: nested Applications become handles in the
    parent's init args (deployment-graph build analog)."""
    import ray_tpu

    def resolve(v):
        if isinstance(v, Application):
            return _deploy_application(client, v, deployed_names)
        return v

    args = tuple(resolve(a) for a in app.args)
    kwargs = {k: resolve(v) for k, v in app.kwargs.items()}
    from ray_tpu.serve.batching import uses_batching

    d = app.deployment
    goal = {
        "serialized_def": cloudpickle.dumps(d._func_or_class),
        "init_args": args,
        "init_kwargs": kwargs,
        "config": d.config,
        "route_prefix": d.route_prefix,
        # @serve.batch needs concurrent request threads to form batches;
        # plain deployments keep serialized execution (no surprise races)
        "uses_batching": uses_batching(d._func_or_class),
    }
    ray_tpu.get(client.controller.deploy.remote(d.name, goal), timeout=60)
    if deployed_names is not None:
        deployed_names.append(d.name)
    return DeploymentHandle(d.name, client.controller)


def run(
    target: Union[Application, Deployment],
    *,
    host: str = "127.0.0.1",
    port: int = 8000,
    _blocking: bool = True,
    timeout_s: float = 180.0,
) -> DeploymentHandle:
    """Deploy an application and wait until healthy (``api.py:455``).
    Returns a handle to the root deployment."""
    from ray_tpu._private.usage import record_feature
    record_feature("serve")
    import ray_tpu

    if isinstance(target, Deployment):
        target = target.bind()
    if not isinstance(target, Application):
        raise TypeError(f"serve.run expects an Application or Deployment, got {type(target)}")
    client = start(HTTPOptions(host=host, port=port))
    deployed_names: list = []
    handle = _deploy_application(client, target, deployed_names)
    if _blocking:
        deadline = time.monotonic() + timeout_s
        while True:
            status_map = ray_tpu.get(client.controller.get_status.remote(), timeout=30)
            # only THIS app's deployments gate the wait — an unrelated
            # unhealthy deployment must not fail this run
            mine = {n: status_map[n] for n in deployed_names if n in status_map}
            bad = [n for n, s in mine.items() if s["status"] == "UNHEALTHY"]
            if bad:
                raise RuntimeError(
                    f"deployment(s) {bad} unhealthy: "
                    + "; ".join(mine[n].get("message", "") for n in bad)
                )
            if all(s["status"] == "HEALTHY" for s in mine.values()):
                break
            if time.monotonic() >= deadline:
                raise TimeoutError(
                    f"deployments not healthy after {timeout_s}s: {mine}"
                )
            time.sleep(0.2)
    return handle


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name, _get_client().controller)


def status() -> Dict[str, dict]:
    import ray_tpu

    return ray_tpu.get(_get_client().controller.get_status.remote(), timeout=30)


def get_http_address() -> Optional[Tuple[str, int]]:
    """(host, port) of the running HTTP proxy."""
    return _get_client().http


def delete(name: str) -> None:
    import ray_tpu

    ray_tpu.get(_get_client().controller.delete_deployment.remote(name), timeout=30)


def shutdown() -> None:
    """Tear down all deployments, the proxy, and the controller."""
    global _client
    import ray_tpu

    if _client is None:
        return
    try:
        ray_tpu.get(_client.controller.graceful_shutdown.remote(), timeout=30)
    except Exception:
        pass
    for h in (_client.proxy, _client.controller):
        if h is not None:
            try:
                ray_tpu.kill(h)
            except Exception:
                pass
    _client = None
