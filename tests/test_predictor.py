"""Predictor / BatchPredictor (reference: python/ray/train/tests/test_predictor.py,
test_batch_predictor.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air import Checkpoint
from ray_tpu.data import read_api
from ray_tpu.train import BatchPredictor, JaxPredictor, Predictor


def _linear_apply(params, batch):
    return batch @ params["w"] + params["b"]


@pytest.fixture
def linear_checkpoint():
    w = np.array([[2.0], [3.0]], np.float32)
    b = np.array([1.0], np.float32)
    return Checkpoint.from_dict({"params": {"w": w, "b": b}, "step": 7})


def test_jax_predictor_single_batch(ray_start_regular, linear_checkpoint):
    pred = JaxPredictor.from_checkpoint(linear_checkpoint, _linear_apply)
    x = np.array([[1.0, 1.0], [0.0, 2.0]], np.float32)
    out = pred.predict(x)
    np.testing.assert_allclose(out, [[6.0], [7.0]], rtol=1e-6)


def test_batch_predictor_over_dataset(ray_start_regular, linear_checkpoint):
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    ds = read_api.from_numpy(x)
    bp = BatchPredictor.from_checkpoint(
        linear_checkpoint, JaxPredictor, apply_fn=_linear_apply
    )
    result = bp.predict(ds, batch_size=4, max_scoring_workers=2)
    rows = result.take_all()
    got = np.concatenate([np.atleast_1d(r["predictions"]) for r in rows]).reshape(-1)
    want = (x @ np.array([[2.0], [3.0]], np.float32) + 1.0).reshape(-1)
    np.testing.assert_allclose(np.sort(got), np.sort(want), rtol=1e-5)


def test_batch_predictor_keep_columns(ray_start_regular, linear_checkpoint):
    n = 8
    ds = read_api.from_items(
        [{"x": np.array([i, i], np.float32), "id": i} for i in range(n)]
    )
    bp = BatchPredictor.from_checkpoint(
        linear_checkpoint, JaxPredictor, apply_fn=_linear_apply
    )
    result = bp.predict(
        ds, batch_size=4, feature_columns=["x"], keep_columns=["id"]
    )
    rows = result.take_all()
    assert len(rows) == n
    for r in rows:
        i = r["id"]
        np.testing.assert_allclose(r["predictions"], [5.0 * i + 1.0], rtol=1e-5)


def test_predictor_base_raises(ray_start_regular, linear_checkpoint):
    with pytest.raises(NotImplementedError):
        Predictor.from_checkpoint(linear_checkpoint)
    with pytest.raises(TypeError):
        BatchPredictor.from_checkpoint(linear_checkpoint, dict)


def test_sklearn_trainer_and_predictor(ray_start_regular):
    """SklearnTrainer fits a gradient-boosted model under Tune and the
    checkpoint scores Datasets via BatchPredictor (the GBDT trainer-family
    analog — sklearn HistGradientBoosting in this image)."""
    from sklearn.ensemble import HistGradientBoostingClassifier

    from ray_tpu.train import SklearnPredictor, SklearnTrainer

    rng = np.random.default_rng(0)
    X = rng.standard_normal((300, 4))
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    rows = [{"f0": a, "f1": b, "f2": c, "f3": d, "label": int(t)}
            for (a, b, c, d), t in zip(X, y)]
    train_ds = read_api.from_items(rows[:240])
    valid_ds = read_api.from_items(rows[240:])

    trainer = SklearnTrainer(
        estimator=HistGradientBoostingClassifier(max_iter=30, random_state=0),
        datasets={"train": train_ds, "valid": valid_ds},
        label_column="label",
    )
    result = trainer.fit()
    assert result.metrics["fit_rows"] == 240
    assert result.metrics["valid_score"] > 0.85
    est = SklearnTrainer.get_model(result.checkpoint)
    assert est.predict(X[:5]).shape == (5,)

    bp = BatchPredictor.from_checkpoint(result.checkpoint, SklearnPredictor)
    score_ds = read_api.from_items(
        [{"f0": a, "f1": b, "f2": c, "f3": d}
         for a, b, c, d in X[:40]]
    )
    out = bp.predict(score_ds, batch_size=16, max_scoring_workers=1)
    preds = np.concatenate([np.atleast_1d(r["predictions"]) for r in out.take_all()])
    assert preds.shape == (40,)
    assert (preds == y[:40]).mean() > 0.8
