"""RLlib: SampleBatch/GAE units, PPO learning, workers, Tune integration.

Mirrors the reference's rllib test surface: algorithms run a few
iterations on CartPole and must actually learn (the reference's
``rllib/tests`` learning checks), plus unit tests for the data path.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    PPO,
    PPOConfig,
    RolloutWorker,
    SampleBatch,
    compute_gae,
)


def test_sample_batch_concat_and_minibatches():
    b1 = SampleBatch({"obs": np.ones((3, 2)), "actions": np.arange(3)})
    b2 = SampleBatch({"obs": np.zeros((2, 2)), "actions": np.arange(2)})
    cat = SampleBatch.concat_samples([b1, b2])
    assert cat.count == 5 and cat["obs"].shape == (5, 2)

    rng = np.random.default_rng(0)
    mbs = list(cat.minibatches(2, rng))
    assert len(mbs) == 2 and all(m.count == 2 for m in mbs)


def test_gae_matches_bruteforce():
    gamma, lam = 0.9, 0.8
    rewards = np.array([1.0, 2.0, 3.0], np.float32)
    values = np.array([0.5, 1.0, 1.5], np.float32)
    batch = SampleBatch({
        SampleBatch.REWARDS: rewards,
        SampleBatch.VF_PREDS: values,
        SampleBatch.TERMINATEDS: np.array([False, False, False]),
    })
    last_v = 2.0
    out = compute_gae(batch, last_v, gamma, lam)
    # brute force
    next_v = np.array([1.0, 1.5, last_v])
    deltas = rewards + gamma * next_v - values
    expected = np.array([
        deltas[0] + gamma * lam * (deltas[1] + gamma * lam * deltas[2]),
        deltas[1] + gamma * lam * deltas[2],
        deltas[2],
    ])
    np.testing.assert_allclose(out[SampleBatch.ADVANTAGES], expected, rtol=1e-5)
    np.testing.assert_allclose(
        out[SampleBatch.VALUE_TARGETS], expected + values, rtol=1e-5
    )


def test_gae_cuts_trace_at_terminal():
    batch = SampleBatch({
        SampleBatch.REWARDS: np.array([1.0, 1.0], np.float32),
        SampleBatch.VF_PREDS: np.array([0.0, 0.0], np.float32),
        SampleBatch.TERMINATEDS: np.array([True, False]),
    })
    out = compute_gae(batch, last_value=5.0, gamma=0.9, lambda_=1.0)
    # step 0 is terminal: no bootstrap from step 1's return
    np.testing.assert_allclose(out[SampleBatch.ADVANTAGES][0], 1.0, rtol=1e-5)


def test_rollout_worker_fragment_shape():
    w = RolloutWorker({"env": "CartPole-v1", "rollout_fragment_length": 64,
                       "seed": 0})
    batch = w.sample()
    assert batch.count == 64
    assert set(batch) >= {
        SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.ADVANTAGES,
        SampleBatch.VALUE_TARGETS, SampleBatch.ACTION_LOGP,
    }
    assert batch[SampleBatch.OBS].shape == (64, 4)
    # weights round-trip
    weights = w.get_weights()
    w.set_weights(weights)


def _fast_ppo_config(num_workers=0):
    return (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=num_workers, rollout_fragment_length=400)
        .training(train_batch_size=2000, sgd_minibatch_size=128,
                  num_sgd_iter=8, lr=3e-4, entropy_coeff=0.01)
        .debugging(seed=0)
    )


def test_ppo_cartpole_learns():
    """The RLlib 'done' bar: reward >= 195 on CartPole in minutes on CPU."""
    algo = _fast_ppo_config().build()
    best = 0.0
    for _ in range(30):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 195:
            break
    assert best >= 195, f"PPO failed to learn CartPole: best={best}"
    # greedy inference from the trained policy holds the pole
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    obs, _ = env.reset(seed=123)
    total = 0.0
    for _ in range(500):
        obs, reward, terminated, truncated, _ = env.step(
            algo.compute_single_action(obs)
        )
        total += reward
        if terminated or truncated:
            break
    assert total >= 100, f"greedy rollout too short: {total}"
    algo.cleanup()


def test_ppo_checkpoint_restore():
    algo = _fast_ppo_config().build()
    for _ in range(3):
        algo.train()
    state = algo.save_checkpoint()
    ts = state["timesteps_total"]
    w0 = state["policy_state"]["weights"]

    algo2 = _fast_ppo_config().build()
    algo2.load_checkpoint(state)
    assert algo2._timesteps_total == ts
    w1 = algo2.workers.local_worker.get_weights()
    np.testing.assert_allclose(w0["pi"][0]["w"], w1["pi"][0]["w"])
    # optimizer moments restored too (not zeroed): adam mu is non-zero
    mu_leaves = [
        x for x in __import__("jax").tree_util.tree_leaves(
            algo2.workers.local_worker.policy.opt_state
        ) if hasattr(x, "shape") and x.size > 1
    ]
    assert any(float(abs(np.asarray(x)).max()) > 0 for x in mu_leaves)
    algo.cleanup()
    algo2.cleanup()


def test_ppo_parallel_rollout_workers(ray_start_regular):
    """num_rollout_workers>0: sampling happens on actors, weights sync."""
    algo = _fast_ppo_config(num_workers=2).build()
    r1 = algo.train()
    assert r1["timesteps_total"] >= 2000
    r2 = algo.train()
    assert r2["timesteps_total"] > r1["timesteps_total"]
    assert r2["episodes_total"] > 0
    algo.cleanup()


def test_ppo_under_tuner(ray_start_regular):
    """BASELINE config 4 shape: PPO as a Tune trainable reaching the reward
    target under Tuner.fit."""
    from ray_tpu.air import RunConfig
    from ray_tpu.tune import TuneConfig, Tuner

    tuner = Tuner(
        PPO,
        param_space=_fast_ppo_config().to_dict(),
        tune_config=TuneConfig(
            metric="episode_reward_mean",
            mode="max",
            num_samples=1,
            stop={"episode_reward_mean": 195, "training_iteration": 30},
        ),
        run_config=RunConfig(name="ppo_cartpole_test"),
    )
    results = tuner.fit()
    best = results.get_best_result()
    assert best.metrics["episode_reward_mean"] >= 195


def test_replay_buffer_ring_and_sample():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=100, seed=0)
    b1 = SampleBatch({
        SampleBatch.OBS: np.arange(80, dtype=np.float32).reshape(40, 2),
        SampleBatch.ACTIONS: np.arange(40),
    })
    buf.add_batch(b1)
    assert len(buf) == 40
    # wrap the ring
    for _ in range(4):
        buf.add_batch(b1)
    assert len(buf) == 100
    mb = buf.sample(32)
    assert mb[SampleBatch.OBS].shape == (32, 2)
    assert mb[SampleBatch.ACTIONS].shape == (32,)


def test_dqn_cartpole_learns():
    """DQN (replay + target net + epsilon-greedy) reaches a learning
    signal on CartPole quickly (dqn.py training_step analog)."""
    from ray_tpu.rllib import DQNConfig

    config = (
        DQNConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=200)
        .debugging(seed=7)
        .training(
            lr=5e-4,
            timesteps_per_iteration=500,
            updates_per_iteration=200,
            learning_starts=500,
            epsilon_timesteps=3500,
            target_network_update_freq=200,
            fcnet_hiddens=(64, 64),
        )
    )
    algo = config.build()
    best = 0.0
    for _ in range(40):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 130:
            break
    assert best >= 130, f"DQN failed to learn CartPole: best={best}"
    info = r["info"]["learner"]
    assert info["replay_size"] > 0 and info["epsilon"] < 1.0
    algo.cleanup()


# ---------------------------------------------------------------------------
# round 3: A2C / IMPALA / SAC / vector env / offline IO / evaluation
# ---------------------------------------------------------------------------


def test_a2c_cartpole_learns():
    from ray_tpu.rllib import A2CConfig

    algo = (
        A2CConfig()
        .environment("CartPole-v1")
        .rollouts(rollout_fragment_length=200)
        .training(train_batch_size=800, lr=2e-3, entropy_coeff=0.01)
        .debugging(seed=3)
        .build()
    )
    best = 0.0
    for _ in range(40):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 120:
            break
    algo.cleanup()
    assert best >= 120, f"A2C failed to improve on CartPole: best={best}"


def test_impala_cartpole_learns():
    from ray_tpu.rllib import ImpalaConfig

    algo = (
        ImpalaConfig()
        .environment("CartPole-v1")
        .rollouts(rollout_fragment_length=200)
        .training(train_batch_size=800, lr=2e-3)
        .debugging(seed=0)
        .build()
    )
    best = 0.0
    for _ in range(40):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 120:
            break
    algo.cleanup()
    assert best >= 120, f"IMPALA failed to improve on CartPole: best={best}"


def test_vtrace_reduces_to_gae_targets_on_policy():
    """With identical behavior/current logp, rho = c = 1 and vs equals the
    discounted return recursion."""
    from ray_tpu.rllib import compute_vtrace

    rng = np.random.default_rng(0)
    T = 6
    logp = rng.normal(size=T).astype(np.float32)
    values = rng.normal(size=T).astype(np.float32)
    rewards = rng.normal(size=T).astype(np.float32)
    gamma = 0.9
    vs, pg_adv, rho = compute_vtrace(
        logp, logp, values, 0.5, rewards, gamma
    )
    assert np.allclose(rho, 1.0)
    # on-policy vs recursion == n-step TD(lambda=1) targets
    expect = np.zeros(T, np.float32)
    boot = 0.5
    acc = boot
    for t in range(T - 1, -1, -1):
        acc = rewards[t] + gamma * acc
        expect[t] = acc
    np.testing.assert_allclose(vs, expect, rtol=1e-5)


def test_vector_env_rollout():
    from ray_tpu.rllib import RolloutWorker

    w = RolloutWorker({
        "env": "CartPole-v1",
        "num_envs_per_worker": 4,
        "rollout_fragment_length": 25,
        "_loss_factory": None,
        "seed": 0,
    })
    batch = w.sample()
    assert batch.count == 100  # 4 envs x 25 steps
    assert len(set(batch["eps_id"].tolist())) >= 4  # one episode per env


def test_offline_write_read_roundtrip(tmp_path):
    from ray_tpu.rllib import JsonReader, JsonWriter, SampleBatch

    w = JsonWriter(str(tmp_path))
    b = SampleBatch({
        "obs": np.random.default_rng(0).standard_normal((5, 3)).astype(np.float32),
        "actions": np.arange(5),
        "terminateds": np.array([False, False, True, False, True]),
    })
    w.write(b)
    w.write(b)
    r = JsonReader(str(tmp_path))
    all_b = r.read_all()
    assert all_b.count == 10
    np.testing.assert_allclose(all_b["obs"][:5], b["obs"], rtol=1e-6)
    assert all_b["terminateds"].dtype == np.bool_
    nxt = r.next()
    assert nxt.count == 5


def test_dqn_offline_training(tmp_path):
    """Record CartPole transitions with one DQN, train a second purely
    offline from the files."""
    from ray_tpu.rllib import DQNConfig

    rec = (
        DQNConfig()
        .environment("CartPole-v1")
        .offline_data(output=str(tmp_path))
        .training(timesteps_per_iteration=500, updates_per_iteration=20,
                  learning_starts=100)
        .build()
    )
    for _ in range(3):
        rec.train()
    rec.cleanup()

    offline = (
        DQNConfig()
        .environment("CartPole-v1")
        .offline_data(input_=str(tmp_path))
        .training(timesteps_per_iteration=400, updates_per_iteration=50,
                  learning_starts=100)
        .build()
    )
    r = offline.train()
    assert r["info"]["learner"]["replay_size"] >= 400
    assert np.isfinite(r["info"]["learner"].get("mean_td_error", 0.0))
    offline.cleanup()


def test_evaluation_interval():
    from ray_tpu.rllib import PPOConfig

    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .evaluation(evaluation_interval=2, evaluation_num_episodes=2)
        .training(train_batch_size=400, sgd_minibatch_size=64, num_sgd_iter=2)
        .build()
    )
    r1 = algo.train()
    assert "evaluation" not in r1
    r2 = algo.train()
    assert "evaluation" in r2
    assert r2["evaluation"]["episodes_this_eval"] == 2
    assert np.isfinite(r2["evaluation"]["episode_reward_mean"])
    algo.cleanup()


def test_sac_pendulum_runs_and_improves():
    from ray_tpu.rllib import SACConfig

    algo = (
        SACConfig()
        .environment("Pendulum-v1")
        .training(timesteps_per_iteration=400, updates_per_iteration=100,
                  learning_starts=300)
        .debugging(seed=0)
        .build()
    )
    first = None
    last = None
    for i in range(8):
        r = algo.train()
        m = r["episode_reward_mean"]
        if first is None and np.isfinite(m):
            first = m
        if np.isfinite(m):
            last = m
    lm = r["info"]["learner"]
    assert np.isfinite(lm["critic_loss"]) and np.isfinite(lm["actor_loss"])
    assert lm["alpha"] > 0
    # policy acts in the canonical [-1,1] box; the worker rescales to the
    # env's Box(-2, 2) so full torque is reachable
    pol = algo.get_policy()
    a = pol.greedy_action(np.zeros((4, 3), np.float32))
    assert a.shape == (4, 1) and np.all(np.abs(a) <= 1.0 + 1e-6)
    w = algo.workers.local_worker
    assert np.allclose(w._env_action(np.array([1.0])), [2.0])
    assert np.allclose(w._env_action(np.array([-1.0])), [-2.0])
    # Pendulum mean reward should move up from the random-policy floor
    assert last is not None and first is not None
    assert last >= first - 100  # not collapsing; strict improvement is noisy in 8 iters
    algo.cleanup()


def test_appo_async_cartpole_learns(ray_start_regular):
    """APPO: async rollout/learner overlap (workers always have a
    sample in flight; the learner trains on whatever lands first) with
    the clipped surrogate over V-trace-corrected advantages
    (reference rllib/algorithms/appo/appo.py)."""
    from ray_tpu.rllib import APPOConfig

    algo = (
        APPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=200)
        .training(train_batch_size=400, lr=3e-3, num_sgd_iter=2,
                  minibatch_size=200, batches_per_step=2)
        .debugging(seed=0)
        .build()
    )
    best = 0.0
    for _ in range(120):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 120:
            break
    algo.cleanup()
    assert best >= 120, f"APPO failed to improve on CartPole: best={best}"


def test_appo_overlaps_sampling_with_learning(ray_start_regular):
    """The async contract itself: while the learner is inside
    training_step, every rollout worker has a sample() already in
    flight (no sampling barrier)."""
    from ray_tpu.rllib import APPOConfig

    algo = (
        APPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=50)
        .training(train_batch_size=100)
        .debugging(seed=0)
        .build()
    )
    algo.train()
    # after a step returns, the workers are re-armed: one in-flight
    # sample per worker is already running
    assert len(algo._inflight) == len(algo.workers.remote_workers)
    algo.cleanup()
    assert not algo._inflight
