"""RLlib: SampleBatch/GAE units, PPO learning, workers, Tune integration.

Mirrors the reference's rllib test surface: algorithms run a few
iterations on CartPole and must actually learn (the reference's
``rllib/tests`` learning checks), plus unit tests for the data path.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    PPO,
    PPOConfig,
    RolloutWorker,
    SampleBatch,
    compute_gae,
)


def test_sample_batch_concat_and_minibatches():
    b1 = SampleBatch({"obs": np.ones((3, 2)), "actions": np.arange(3)})
    b2 = SampleBatch({"obs": np.zeros((2, 2)), "actions": np.arange(2)})
    cat = SampleBatch.concat_samples([b1, b2])
    assert cat.count == 5 and cat["obs"].shape == (5, 2)

    rng = np.random.default_rng(0)
    mbs = list(cat.minibatches(2, rng))
    assert len(mbs) == 2 and all(m.count == 2 for m in mbs)


def test_gae_matches_bruteforce():
    gamma, lam = 0.9, 0.8
    rewards = np.array([1.0, 2.0, 3.0], np.float32)
    values = np.array([0.5, 1.0, 1.5], np.float32)
    batch = SampleBatch({
        SampleBatch.REWARDS: rewards,
        SampleBatch.VF_PREDS: values,
        SampleBatch.TERMINATEDS: np.array([False, False, False]),
    })
    last_v = 2.0
    out = compute_gae(batch, last_v, gamma, lam)
    # brute force
    next_v = np.array([1.0, 1.5, last_v])
    deltas = rewards + gamma * next_v - values
    expected = np.array([
        deltas[0] + gamma * lam * (deltas[1] + gamma * lam * deltas[2]),
        deltas[1] + gamma * lam * deltas[2],
        deltas[2],
    ])
    np.testing.assert_allclose(out[SampleBatch.ADVANTAGES], expected, rtol=1e-5)
    np.testing.assert_allclose(
        out[SampleBatch.VALUE_TARGETS], expected + values, rtol=1e-5
    )


def test_gae_cuts_trace_at_terminal():
    batch = SampleBatch({
        SampleBatch.REWARDS: np.array([1.0, 1.0], np.float32),
        SampleBatch.VF_PREDS: np.array([0.0, 0.0], np.float32),
        SampleBatch.TERMINATEDS: np.array([True, False]),
    })
    out = compute_gae(batch, last_value=5.0, gamma=0.9, lambda_=1.0)
    # step 0 is terminal: no bootstrap from step 1's return
    np.testing.assert_allclose(out[SampleBatch.ADVANTAGES][0], 1.0, rtol=1e-5)


def test_rollout_worker_fragment_shape():
    w = RolloutWorker({"env": "CartPole-v1", "rollout_fragment_length": 64,
                       "seed": 0})
    batch = w.sample()
    assert batch.count == 64
    assert set(batch) >= {
        SampleBatch.OBS, SampleBatch.ACTIONS, SampleBatch.ADVANTAGES,
        SampleBatch.VALUE_TARGETS, SampleBatch.ACTION_LOGP,
    }
    assert batch[SampleBatch.OBS].shape == (64, 4)
    # weights round-trip
    weights = w.get_weights()
    w.set_weights(weights)


def _fast_ppo_config(num_workers=0):
    return (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=num_workers, rollout_fragment_length=400)
        .training(train_batch_size=2000, sgd_minibatch_size=128,
                  num_sgd_iter=8, lr=3e-4, entropy_coeff=0.01)
        .debugging(seed=0)
    )


def test_ppo_cartpole_learns():
    """The RLlib 'done' bar: reward >= 195 on CartPole in minutes on CPU."""
    algo = _fast_ppo_config().build()
    best = 0.0
    for _ in range(30):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 195:
            break
    assert best >= 195, f"PPO failed to learn CartPole: best={best}"
    # greedy inference from the trained policy holds the pole
    import gymnasium as gym

    env = gym.make("CartPole-v1")
    obs, _ = env.reset(seed=123)
    total = 0.0
    for _ in range(500):
        obs, reward, terminated, truncated, _ = env.step(
            algo.compute_single_action(obs)
        )
        total += reward
        if terminated or truncated:
            break
    assert total >= 100, f"greedy rollout too short: {total}"
    algo.cleanup()


def test_ppo_checkpoint_restore():
    algo = _fast_ppo_config().build()
    for _ in range(3):
        algo.train()
    state = algo.save_checkpoint()
    ts = state["timesteps_total"]
    w0 = state["policy_state"]["weights"]

    algo2 = _fast_ppo_config().build()
    algo2.load_checkpoint(state)
    assert algo2._timesteps_total == ts
    w1 = algo2.workers.local_worker.get_weights()
    np.testing.assert_allclose(w0["pi"][0]["w"], w1["pi"][0]["w"])
    # optimizer moments restored too (not zeroed): adam mu is non-zero
    mu_leaves = [
        x for x in __import__("jax").tree_util.tree_leaves(
            algo2.workers.local_worker.policy.opt_state
        ) if hasattr(x, "shape") and x.size > 1
    ]
    assert any(float(abs(np.asarray(x)).max()) > 0 for x in mu_leaves)
    algo.cleanup()
    algo2.cleanup()


def test_ppo_parallel_rollout_workers(ray_start_regular):
    """num_rollout_workers>0: sampling happens on actors, weights sync."""
    algo = _fast_ppo_config(num_workers=2).build()
    r1 = algo.train()
    assert r1["timesteps_total"] >= 2000
    r2 = algo.train()
    assert r2["timesteps_total"] > r1["timesteps_total"]
    assert r2["episodes_total"] > 0
    algo.cleanup()


def test_ppo_under_tuner(ray_start_regular):
    """BASELINE config 4 shape: PPO as a Tune trainable reaching the reward
    target under Tuner.fit."""
    from ray_tpu.air import RunConfig
    from ray_tpu.tune import TuneConfig, Tuner

    tuner = Tuner(
        PPO,
        param_space=_fast_ppo_config().to_dict(),
        tune_config=TuneConfig(
            metric="episode_reward_mean",
            mode="max",
            num_samples=1,
            stop={"episode_reward_mean": 195, "training_iteration": 30},
        ),
        run_config=RunConfig(name="ppo_cartpole_test"),
    )
    results = tuner.fit()
    best = results.get_best_result()
    assert best.metrics["episode_reward_mean"] >= 195


def test_replay_buffer_ring_and_sample():
    from ray_tpu.rllib import ReplayBuffer

    buf = ReplayBuffer(capacity=100, seed=0)
    b1 = SampleBatch({
        SampleBatch.OBS: np.arange(80, dtype=np.float32).reshape(40, 2),
        SampleBatch.ACTIONS: np.arange(40),
    })
    buf.add_batch(b1)
    assert len(buf) == 40
    # wrap the ring
    for _ in range(4):
        buf.add_batch(b1)
    assert len(buf) == 100
    mb = buf.sample(32)
    assert mb[SampleBatch.OBS].shape == (32, 2)
    assert mb[SampleBatch.ACTIONS].shape == (32,)


def test_dqn_cartpole_learns():
    """DQN (replay + target net + epsilon-greedy) reaches a learning
    signal on CartPole quickly (dqn.py training_step analog)."""
    from ray_tpu.rllib import DQNConfig

    config = (
        DQNConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=0, rollout_fragment_length=200)
        .debugging(seed=7)
        .training(
            lr=5e-4,
            timesteps_per_iteration=500,
            updates_per_iteration=200,
            learning_starts=500,
            epsilon_timesteps=3500,
            target_network_update_freq=200,
            fcnet_hiddens=(64, 64),
        )
    )
    algo = config.build()
    best = 0.0
    for _ in range(40):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 130:
            break
    assert best >= 130, f"DQN failed to learn CartPole: best={best}"
    info = r["info"]["learner"]
    assert info["replay_size"] > 0 and info["epsilon"] < 1.0
    algo.cleanup()
