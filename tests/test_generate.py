"""KV-cache generation: decode must reproduce the full forward exactly.

The reference has no decode engine (serving calls a plain user forward,
``python/ray/serve/_private/replica.py:250``); these tests pin our cache
semantics instead: greedy cached decode == greedy full-recompute decode,
per-slot positions, EOS freezing.  f32 configs so argmax never flips on
accumulation-order noise.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import generate as gen
from ray_tpu.models import gpt2, llama


def _greedy_reference(apply_fn, params, cfg, prompt, n_new):
    """Teacher-forcing loop: full forward each step, argmax last logit."""
    toks = list(prompt)
    for _ in range(n_new):
        logits = apply_fn(params, jnp.asarray([toks]), cfg)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return toks[len(prompt):]


@pytest.mark.parametrize("family", ["gpt2", "llama"])
def test_cached_decode_matches_full_forward(family):
    if family == "gpt2":
        cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
        params = gpt2.init(cfg, jax.random.PRNGKey(0))
        apply_fn = lambda p, t, c: gpt2.apply(p, t, c)
    else:
        cfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
        params = llama.init(cfg, jax.random.PRNGKey(0))
        apply_fn = lambda p, t, c: llama.apply(p, t, c)

    prompt = [3, 17, 5, 9, 2, 11]
    want = _greedy_reference(apply_fn, params, cfg, prompt, 8)
    out = gen.generate(
        params, cfg, jnp.asarray([prompt]), jnp.asarray([len(prompt)]),
        max_new_tokens=8)
    assert [int(t) for t in out[0]] == want


def test_batched_slots_with_different_lengths():
    """Two prompts of different lengths decode in one batch exactly as they
    would alone (padding + per-slot positions change nothing)."""
    cfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
    params = llama.init(cfg, jax.random.PRNGKey(1))
    p_a, p_b = [5, 9, 2], [7, 1, 4, 8, 3, 6, 12]
    solo = {}
    for name, p in (("a", p_a), ("b", p_b)):
        out = gen.generate(params, cfg, jnp.asarray([p]),
                           jnp.asarray([len(p)]), max_new_tokens=6)
        solo[name] = [int(t) for t in out[0]]
    pad = max(len(p_a), len(p_b))
    batch = jnp.asarray([p_a + [0] * (pad - len(p_a)), p_b])
    out = gen.generate(params, cfg, batch,
                       jnp.asarray([len(p_a), len(p_b)]), max_new_tokens=6)
    assert [int(t) for t in out[0]] == solo["a"]
    assert [int(t) for t in out[1]] == solo["b"]


def test_eos_freezes_slot():
    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init(cfg, jax.random.PRNGKey(2))
    prompt = jnp.asarray([[3, 17, 5, 9]])
    out = gen.generate(params, cfg, prompt, jnp.asarray([4]),
                       max_new_tokens=10)
    toks = [int(t) for t in out[0]]
    # re-run declaring the 3rd emitted token as EOS: everything after must
    # repeat it (the slot went inactive)
    eos = toks[2]
    out2 = gen.generate(params, cfg, prompt, jnp.asarray([4]),
                        max_new_tokens=10, eos_id=eos)
    toks2 = [int(t) for t in out2[0]]
    assert toks2[:3] == toks[:3]
    assert all(t == eos for t in toks2[2:])


def test_prefill_then_chunked_decode_equals_one_shot():
    """The serving path (prefill + several decode_chunk calls) must equal
    one-shot generate."""
    cfg = llama.LlamaConfig.tiny(dtype=jnp.float32)
    params = llama.init(cfg, jax.random.PRNGKey(3))
    prompt = [9, 4, 7, 2, 5]
    one = gen.generate(params, cfg, jnp.asarray([prompt]),
                       jnp.asarray([len(prompt)]), max_new_tokens=9)

    cache = gen.init_cache(cfg, 1, len(prompt) + 9)
    last, cache = gen.prefill(
        params, cfg, jnp.asarray([prompt]), jnp.asarray([len(prompt)]),
        cache, jnp.int32(0))
    tok = gen.sample_logits(last, jax.random.PRNGKey(0))
    emitted = [int(tok[0])]
    active = jnp.ones((1,), bool)
    key = jax.random.PRNGKey(0)
    for _ in range(2):  # 2 chunks of 4 = the remaining 8 tokens
        chunk, cache, active, key = gen.decode_chunk(
            params, cfg, cache, tok, active, key, steps=4)
        emitted.extend(int(t) for t in np.asarray(chunk[0]))
        tok = chunk[:, -1]
    assert emitted == [int(t) for t in one[0]]
