"""ActorPool + distributed Queue (reference: python/ray/tests/test_actor_pool.py,
test_queue.py)."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.util import ActorPool
from ray_tpu.util.queue import Empty, Full, Queue


@ray_tpu.remote
class Doubler:
    def double(self, x):
        return 2 * x

    def slow_double(self, x):
        time.sleep(0.05 * (x % 3))
        return 2 * x


def test_actor_pool_map_ordered(ray_start_regular):
    pool = ActorPool([Doubler.remote() for _ in range(3)])
    out = list(pool.map(lambda a, x: a.slow_double.remote(x), range(10)))
    assert out == [2 * x for x in range(10)]


def test_actor_pool_map_unordered(ray_start_regular):
    pool = ActorPool([Doubler.remote() for _ in range(3)])
    out = list(pool.map_unordered(lambda a, x: a.slow_double.remote(x), range(10)))
    assert sorted(out) == [2 * x for x in range(10)]


def test_actor_pool_submit_get_next(ray_start_regular):
    pool = ActorPool([Doubler.remote() for _ in range(2)])
    pool.submit(lambda a, x: a.double.remote(x), 1)
    pool.submit(lambda a, x: a.double.remote(x), 2)
    assert pool.get_next() == 2
    assert pool.get_next() == 4
    assert not pool.has_next()


def test_actor_pool_push_pop(ray_start_regular):
    pool = ActorPool([Doubler.remote()])
    extra = Doubler.remote()
    pool.push(extra)
    a = pool.pop_idle()
    assert a is not None
    pool.submit(lambda a, x: a.double.remote(x), 5)
    assert pool.get_next() == 10


def test_queue_basic(ray_start_regular):
    q = Queue()
    q.put(1)
    q.put("two")
    assert q.qsize() == 2
    assert q.get() == 1
    assert q.get() == "two"
    assert q.empty()


def test_queue_nowait_and_batch(ray_start_regular):
    q = Queue(maxsize=2)
    q.put_nowait(1)
    q.put_nowait(2)
    with pytest.raises(Full):
        q.put_nowait(3)
    assert q.get_nowait() == 1
    with pytest.raises(Full):  # batch of 2 does not fit next to the 1 left
        q.put_nowait_batch([10, 11])
    assert q.get_nowait() == 2
    q.put_nowait_batch([10, 11])
    with pytest.raises(Empty):
        Queue().get_nowait()
    assert q.get_nowait_batch(10) == [10, 11]


def test_queue_blocking_get(ray_start_regular):
    q = Queue()

    def producer():
        time.sleep(0.3)
        q.put("late")

    t = threading.Thread(target=producer)
    t.start()
    assert q.get(timeout=5) == "late"
    t.join()


def test_queue_get_timeout(ray_start_regular):
    q = Queue()
    with pytest.raises(Empty):
        q.get(timeout=0.2)


def test_queue_across_tasks(ray_start_regular):
    q = Queue()

    @ray_tpu.remote
    def producer(q, n):
        for i in range(n):
            q.put(i)
        return n

    ray_tpu.get(producer.remote(q, 5))
    assert [q.get() for _ in range(5)] == list(range(5))
