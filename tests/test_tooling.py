"""Cluster tooling: state API, dashboard, metrics, jobs, CLI, timeline.

Reference surfaces: state API (``experimental/state/api.py:729-1333``),
dashboard head (``dashboard/head.py:69``), ``ray.util.metrics``, job
submission (``dashboard/modules/job/job_manager.py:431``), ``ray`` CLI
(``python/ray/scripts/scripts.py``), ``ray timeline``
(``_private/state.py:829``).
"""

import json
import os
import sys
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu.experimental.state import (
    list_actors,
    list_nodes,
    list_objects,
    list_tasks,
    list_workers,
    summarize_tasks,
)


def _http_json(url):
    with urllib.request.urlopen(url, timeout=30) as r:
        return json.loads(r.read())


def test_state_api(ray_start_regular):
    @ray_tpu.remote
    def f(x):
        return x + 1

    @ray_tpu.remote
    class A:
        def ping(self):
            return "ok"

    a = A.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "ok"
    ray_tpu.get([f.remote(i) for i in range(3)], timeout=60)

    nodes = list_nodes()
    assert any(n["node_id"] == "node-head" for n in nodes)
    actors = list_actors()
    assert any(x["class_name"] == "A" and x["state"] == "ALIVE" for x in actors)
    # seal (which completes get) slightly precedes task_done bookkeeping
    deadline = time.time() + 10
    while time.time() < deadline:
        tasks = [t for t in list_tasks() if t["name"] == "f"]
        if len(tasks) == 3 and all(t["state"] == "FINISHED" for t in tasks):
            break
        time.sleep(0.1)
    assert len(tasks) == 3
    assert all(t["state"] == "FINISHED" for t in tasks)
    workers = list_workers()
    assert any(w["is_actor_worker"] for w in workers)
    ref = ray_tpu.put(list(range(100)))
    objs = list_objects()
    assert any(o["object_id"] == ref.hex() for o in objs)
    summary = summarize_tasks()
    assert summary["f"]["FINISHED"] == 3


def test_dashboard_endpoints(ray_start_regular):
    from ray_tpu._private.worker import global_worker

    dash = global_worker.node.dashboard
    assert dash is not None
    host, port = dash.address

    @ray_tpu.remote
    def noop():
        return 1

    ray_tpu.get(noop.remote(), timeout=60)

    status = _http_json(f"http://{host}:{port}/api/cluster_status")
    assert status["num_nodes"] >= 1
    assert "CPU" in status["cluster_resources"]["node-head"]
    nodes = _http_json(f"http://{host}:{port}/api/nodes")
    assert nodes[0]["node_id"] == "node-head"
    with urllib.request.urlopen(f"http://{host}:{port}/metrics", timeout=30) as r:
        text = r.read().decode()
    assert "ray_tpu_num_workers" in text and "ray_tpu_tasks" in text


def test_app_metrics_flow_to_head(ray_start_regular):
    from ray_tpu._private.worker import global_worker

    @ray_tpu.remote
    def record():
        from ray_tpu.util.metrics import Counter

        Counter("my_app_events", "test counter").inc(5, tags={"kind": "x"})
        # pusher interval is 5s; push promptly via the worker's client
        from ray_tpu.util import metrics as mm
        global_worker_client = None
        import ray_tpu._private.worker as wmod

        wmod.global_worker.client.send({
            "type": "metrics_report",
            "origin": wmod.global_worker.worker_id.hex(),
            "metrics": mm.registry().snapshot(),
        })
        return 1

    assert ray_tpu.get(record.remote(), timeout=60) == 1
    deadline = time.time() + 10
    while time.time() < deadline:
        snap = global_worker.node.worker_metrics_registry.snapshot()
        if "my_app_events" in snap:
            break
        time.sleep(0.2)
    assert "my_app_events" in snap
    from ray_tpu.util.metrics import prometheus_text

    text = prometheus_text(snap)
    assert 'my_app_events{kind="x"' in text


def test_job_submission(ray_start_regular, tmp_path):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    script = tmp_path / "job.py"
    script.write_text(
        "import ray_tpu, os\n"
        "ray_tpu.init(address=os.environ['RAY_TPU_ADDRESS'])\n"
        "@ray_tpu.remote\n"
        "def sq(x):\n"
        "    return x * x\n"
        "print('result:', ray_tpu.get(sq.remote(7), timeout=120))\n"
    )
    job_id = client.submit_job(entrypoint=f"{sys.executable} {script}")
    status = client.wait_until_finish(job_id, timeout=180)
    logs = client.get_job_logs(job_id)
    assert status == JobStatus.SUCCEEDED, logs
    assert "result: 49" in logs
    assert any(j["job_id"] == job_id for j in client.list_jobs())


def test_job_stop(ray_start_regular):
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(entrypoint=f"{sys.executable} -c 'import time; time.sleep(600)'")
    time.sleep(0.5)
    assert client.stop_job(job_id)
    assert client.wait_until_finish(job_id, timeout=30) == "STOPPED"


def test_timeline_dump(ray_start_regular, tmp_path):
    @ray_tpu.remote
    def work():
        time.sleep(0.05)
        return 1

    ray_tpu.get([work.remote() for _ in range(3)], timeout=60)
    from ray_tpu.util.timeline import timeline_dump

    path = timeline_dump(str(tmp_path / "trace.json"))
    events = json.loads(open(path).read())
    mine = [e for e in events if e["name"] == "work"]
    assert len(mine) == 3
    assert all(e["ph"] == "X" and e["dur"] > 0 for e in mine)


def test_cli_status_and_list(ray_start_regular):
    """The CLI's list path against a live session (in-process)."""
    from ray_tpu.scripts import cli

    sess = cli._session()
    assert sess["address"].startswith("tcp://")

    @ray_tpu.remote
    def g():
        return 1

    ray_tpu.get(g.remote(), timeout=60)
    # list command goes through the already-initialized driver
    import io
    from contextlib import redirect_stdout

    buf = io.StringIO()
    with redirect_stdout(buf):
        cli.main(["list", "tasks", "--limit", "50"])
    rows = json.loads(buf.getvalue())
    assert any(r["name"] == "g" for r in rows)


def test_autoscaler_scales_up_and_down(ray_start_regular):
    """Unmet demand launches real node_agent workers; idle nodes reap."""
    from ray_tpu._private.worker import global_worker
    from ray_tpu.autoscaler import LocalNodeProvider, Monitor, StandardAutoscaler
    from ray_tpu.autoscaler.autoscaler import AutoscalingConfig

    head = global_worker.node
    provider = LocalNodeProvider(head)
    scaler = StandardAutoscaler(
        head, provider,
        AutoscalingConfig(min_workers=0, max_workers=2, idle_timeout_s=3.0,
                          worker_node={"num_cpus": 4}),
    )
    monitor = Monitor(scaler, interval_s=0.5).start()
    try:
        # head has 4 CPUs; each task wants 3, so only one fits at a time —
        # the queued remainder is unmet demand the autoscaler must absorb
        @ray_tpu.remote(num_cpus=3)
        def heavy(i):
            time.sleep(3.0)
            return i

        refs = [heavy.remote(i) for i in range(4)]  # 12 CPUs of demand
        deadline = time.time() + 60
        while not provider.non_terminated_nodes() and time.time() < deadline:
            time.sleep(0.2)
        assert provider.non_terminated_nodes(), "autoscaler never launched a node"
        assert sorted(ray_tpu.get(refs, timeout=240)) == [0, 1, 2, 3]

        # idle nodes terminate after the timeout
        deadline = time.time() + 60
        while provider.non_terminated_nodes() and time.time() < deadline:
            time.sleep(0.5)
        assert not provider.non_terminated_nodes(), "idle nodes never reaped"
    finally:
        monitor.stop()
        provider.shutdown()


def test_timeline_exec_slices(ray_start_regular, tmp_path):
    """Worker-reported exec windows show up as per-worker-pid slices with a
    separate queued slice (profile-event enrichment)."""
    @ray_tpu.remote
    def tick():
        time.sleep(0.05)
        return 1

    ray_tpu.get([tick.remote() for _ in range(2)], timeout=60)
    deadline = time.time() + 10
    while time.time() < deadline:
        from ray_tpu.util.timeline import timeline_events

        evs = [e for e in timeline_events() if e["name"] == "tick"]
        if len(evs) == 2 and all(isinstance(e["tid"], int) for e in evs):
            break
        time.sleep(0.1)
    assert len(evs) == 2
    assert all(e["dur"] >= 0.04e6 for e in evs)
    queued = [e for e in timeline_events() if e["name"] == "tick (queued)"]
    assert len(queued) == 2


def test_profiling_timed_scope(ray_start_regular):
    from ray_tpu.util import profiling
    from ray_tpu.util.metrics import registry

    with profiling.timed("unit_scope"):
        time.sleep(0.01)
    snap = registry().snapshot()
    assert "ray_tpu_timed_unit_scope_seconds" in snap
    vals = list(snap["ray_tpu_timed_unit_scope_seconds"]["values"].values())
    assert vals[0]["count"] >= 1 and vals[0]["sum"] >= 0.01

    # span() is a no-op without opentelemetry installed
    with profiling.span("noop-span"):
        pass


def test_usage_report_written(tmp_path):
    """Opt-out usage stats: a session report lands in the session dir
    (local-only; the reference posts the same schema to a collector)."""
    import ray_tpu
    from ray_tpu._private import usage

    ray_tpu.init(num_cpus=2)
    node = ray_tpu._private.worker.global_worker.node
    session_dir = node.session_dir

    @ray_tpu.remote
    def f():
        return 1

    assert ray_tpu.get(f.remote(), timeout=60) == 1
    usage.record_feature("unit-test-feature")
    ray_tpu.shutdown()

    report = json.load(open(os.path.join(session_dir, "usage_report.json")))
    assert "unit-test-feature" in report["features_used"]
    assert report["counters"]["tasks_total"] >= 1


def test_trace_context_propagates_across_tasks(ray_start_regular):
    """util.tracing: tasks submitted inside trace() carry the context;
    nested submissions in workers chain under the same trace (the
    reference's tracing_helper span-injection analog)."""
    import ray_tpu
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def child():
        from ray_tpu.util import tracing as t

        return t.current_context()

    @ray_tpu.remote
    def parent():
        from ray_tpu.util import tracing as t

        ctx = t.current_context()
        nested = ray_tpu.get(child.remote(), timeout=120)
        return ctx, nested

    with tracing.trace("experiment") as root:
        ref = parent.remote()
    p_ctx, c_ctx = ray_tpu.get(ref, timeout=120)
    assert p_ctx["trace_id"] == root["trace_id"]
    assert p_ctx["parent_span_id"] == root["span_id"]
    # nested task chains under the parent task's span, same trace
    assert c_ctx["trace_id"] == root["trace_id"]
    assert c_ctx["parent_span_id"] == p_ctx["span_id"]

    # untraced tasks carry nothing
    @ray_tpu.remote
    def plain():
        from ray_tpu.util import tracing as t

        return t.current_context()

    assert ray_tpu.get(plain.remote(), timeout=120) is None

    # head recorded the context; the timeline links parent -> child
    from ray_tpu.util.timeline import timeline_events

    events = timeline_events()
    traced = [e for e in events
              if e.get("args", {}).get("trace_id") == root["trace_id"]]
    assert len(traced) >= 2
    flows = [e for e in events if e.get("cat") == "trace" and e["ph"] in ("s", "f")]
    assert any(e["ph"] == "s" for e in flows) and any(e["ph"] == "f" for e in flows)
