"""Connector pipelines + RLModule plugin surface (rllib/connectors/,
rllib/rl_module.py).

Mirrors the reference's ``rllib/connectors/tests``: composition order,
running-stat determinism under state round-trips, frame-stack episode
boundaries, action clip/unsquash inverses, pipelines pickled through
configs to remote workers and the PolicyServer, multi-agent pass-through,
and custom RLModules plugging into PPO without subclassing Policy.
"""

import pickle

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    PPO,
    PPOConfig,
    RLModule,
    RolloutWorker,
    SampleBatch,
    compute_gae,
    serve_policy,
)
from ray_tpu.rllib.connectors import (
    ActionConnectorPipeline,
    AgentConnector,
    AgentConnectorPipeline,
    ClipObs,
    ConnectorContext,
    FlattenObs,
    FrameStackObs,
    NormalizeObs,
    UnsquashAction,
    register_connector,
)


@pytest.fixture
def ray_instance():
    ray_tpu.init(num_cpus=4)
    yield
    ray_tpu.shutdown()


class _AddOne(AgentConnector):
    NAME = "test_add_one"

    def __call__(self, x, env_id=0, training=True):
        return np.asarray(x, np.float32) + 1.0


class _Double(AgentConnector):
    NAME = "test_double"

    def __call__(self, x, env_id=0, training=True):
        return np.asarray(x, np.float32) * 2.0


register_connector(_AddOne.NAME, _AddOne)
register_connector(_Double.NAME, _Double)


def test_pipeline_composition_order():
    """Pipelines apply left to right — (x+1)*2 != x*2+1 — and a custom
    registered connector restores by name through from_state."""
    ctx = ConnectorContext(obs_shape=(3,), obs_dim=3)
    p1 = AgentConnectorPipeline(ctx, [_AddOne(), _Double()])
    p2 = AgentConnectorPipeline(ctx, [_Double(), _AddOne()])
    x = np.array([1.0, 2.0, 3.0], np.float32)
    np.testing.assert_allclose(p1(x), (x + 1) * 2)
    np.testing.assert_allclose(p2(x), x * 2 + 1)
    # state round-trip preserves the ORDER (the whole point of to_state)
    restored = AgentConnectorPipeline.from_state(ctx, p1.to_state())
    np.testing.assert_allclose(restored(x), p1(x))
    assert [c.NAME for c in restored.connectors] == [
        "test_add_one", "test_double"]


def test_normalize_obs_deterministic_state_roundtrip():
    """Running-stat normalization is bit-stable under a mid-stream
    to_state/from_state round trip: the restored filter produces the SAME
    outputs and the SAME subsequent statistics as the original."""
    rng = np.random.default_rng(0)
    stream = [rng.normal(3.0, 2.0, size=4) for _ in range(50)]
    a = NormalizeObs(clip=5.0)
    for o in stream[:25]:
        a(o)
    name, params = a.to_state()
    assert name == "normalize_obs" and params["n"] == 25
    b = NormalizeObs.from_state(ConnectorContext(), dict(params))
    for o in stream[25:]:
        out_a, out_b = a(o), b(o)
        np.testing.assert_array_equal(out_a, out_b)
    pa, pb = a.to_state()[1], b.to_state()[1]
    assert pa["n"] == pb["n"] == 50
    np.testing.assert_array_equal(pa["mean"], pb["mean"])
    np.testing.assert_array_equal(pa["m2"], pb["m2"])
    # statistics actually converge on the stream's moments
    assert abs(pa["mean"].mean() - 3.0) < 0.5
    # training=False freezes statistics (the evaluation path)
    before = a.to_state()[1]["n"]
    a(stream[0], training=False)
    assert a.to_state()[1]["n"] == before


def test_frame_stack_episode_boundary_reset():
    fs = FrameStackObs(num_frames=3)
    o1, o2 = np.array([1.0, 1.0]), np.array([2.0, 2.0])
    # first obs of an episode repeats (wrapper-deque reset semantic)
    np.testing.assert_allclose(fs(o1, env_id=0), [1, 1, 1, 1, 1, 1])
    np.testing.assert_allclose(fs(o2, env_id=0), [1, 1, 1, 1, 2, 2])
    # envs are independent streams
    np.testing.assert_allclose(fs(o2, env_id=1), [2, 2, 2, 2, 2, 2])
    # episode boundary: env 0 starts fresh, env 1 untouched
    fs.reset(0)
    np.testing.assert_allclose(fs(o2, env_id=0), [2, 2, 2, 2, 2, 2])
    np.testing.assert_allclose(fs(o1, env_id=1), [2, 2, 2, 2, 1, 1])


def test_action_clip_unsquash_inverses():
    u = UnsquashAction(low=[-2.0, 0.0], high=[2.0, 10.0])
    # canonical -> env -> canonical is the identity inside the box
    for a in ([-1.0, -1.0], [0.0, 0.0], [1.0, 1.0], [-0.3, 0.7]):
        a = np.asarray(a, np.float32)
        np.testing.assert_allclose(u.squash(u(a)), a, rtol=1e-5, atol=1e-6)
    # env -> canonical -> env likewise
    for x in ([-2.0, 0.0], [2.0, 10.0], [0.5, 4.0]):
        x = np.asarray(x, np.float32)
        np.testing.assert_allclose(u(u.squash(x)), x, rtol=1e-5, atol=1e-5)
    # bounds: out-of-box canonical actions clip to the box edges
    np.testing.assert_allclose(u(np.array([5.0, -5.0])), [2.0, 0.0])


def test_worker_uses_connectors_as_the_sample_path():
    """The worker's obs/action paths ARE the pipelines: a custom agent
    connector in the config visibly transforms every stored observation."""
    w = RolloutWorker({
        "env": "CartPole-v1", "rollout_fragment_length": 16, "seed": 0,
        "agent_connectors": [("flatten_obs", {}), ("test_add_one", {})],
    })
    assert [c.NAME for c in w.agent_connectors.connectors] == [
        "flatten_obs", "test_add_one"]
    batch = w.sample()
    # CartPole obs[0] is cart position in [-2.4, 2.4]; +1 shifts the mean
    # a full unit — impossible by chance
    assert batch["obs"].shape == (16, 4)
    assert 0.5 < np.mean(batch["obs"][:, 0]) < 1.5


def test_pipeline_pickles_and_rides_policy_server(ray_instance):
    """The pickled-pipeline path: connector pipelines (with learned
    state) pickle; a config carrying them reaches REMOTE rollout workers
    whose policy is the shared PolicyServer, and sampling flows through
    the pipeline on every worker."""
    ctx = ConnectorContext(obs_shape=(4,), obs_dim=4)
    pipe = AgentConnectorPipeline(ctx, [FlattenObs(), NormalizeObs()])
    pipe(np.arange(4.0))  # learned state rides the pickle
    blob = pickle.dumps(pipe)
    restored = pickle.loads(blob)
    orig_state, rest_state = pipe.to_state(), restored.to_state()
    assert [n for n, _ in rest_state] == [n for n, _ in orig_state]
    assert rest_state[1][1]["n"] == orig_state[1][1]["n"] == 1
    np.testing.assert_array_equal(rest_state[1][1]["mean"],
                                  orig_state[1][1]["mean"])

    cfg = (
        PPOConfig()
        .environment("CartPole-v1")
        .rollouts(num_rollout_workers=2, rollout_fragment_length=20)
        .connectors(
            agent_connectors=[("flatten_obs", {}), ("normalize_obs", {})])
        .training(train_batch_size=80, sgd_minibatch_size=32, num_sgd_iter=2,
                  fcnet_hiddens=(16,))
        .debugging(seed=0)
    ).to_dict()
    server, overrides = serve_policy(cfg, obs_dim=4, num_actions=2,
                                     max_concurrency=8)
    cfg.update(overrides)
    algo = cfg.pop("_algo_class")(config=cfg)
    try:
        r = algo.step()
        assert r["timesteps_total"] >= 80
        assert "total_loss" in r["info"]["learner"]
        # the local worker's filter saw real observations...
        state = algo.workers.local_worker.get_connector_state()
        name, params = state["agent"][-1]
        assert name == "normalize_obs" and params["n"] > 0
        # ...and the REMOTE workers' pipelines did too (pickled through
        # the actor constructor config, exercised by sampling)
        remote_states = ray_tpu.get(
            [w.get_connector_state.remote()
             for w in algo.workers.remote_workers], timeout=120)
        for rs in remote_states:
            rname, rparams = rs["agent"][-1]
            assert rname == "normalize_obs" and rparams["n"] > 0
    finally:
        algo.cleanup()


def test_multi_agent_connector_passthrough():
    """Multi-agent sampling routes per-policy pipelines: defaults behave
    like the old hardwired prep, and a custom spec applies per agent."""
    from ray_tpu.rllib import MultiAgentEnv, MultiAgentRolloutWorker

    class _Box:
        def __init__(self, shape):
            self.shape = shape

    class _Disc:
        def __init__(self, n):
            self.n = n

    class TwoAgentEnv(MultiAgentEnv):
        agents = ["a", "b"]

        def __init__(self, config=None):
            self._t = 0

        def reset(self, *, seed=None, options=None):
            self._t = 0
            return {a: np.zeros(3, np.float32) + 7.0 for a in self.agents}, {}

        def step(self, action_dict):
            assert all(isinstance(v, int) for v in action_dict.values())
            self._t += 1
            done = self._t >= 5
            obs = {a: np.zeros(3, np.float32) + 7.0 for a in self.agents}
            rew = {a: 1.0 for a in self.agents}
            return obs, rew, {"__all__": done}, {"__all__": False}, {}

        def observation_space(self, agent_id):
            return _Box((3,))

        def action_space(self, agent_id):
            return _Disc(2)

    base = {
        "env_creator": lambda cfg: TwoAgentEnv(cfg),
        "multiagent": {"policies": {"shared": None},
                       "policy_mapping_fn": lambda a: "shared"},
        "rollout_fragment_length": 10,
        "fcnet_hiddens": (8,),
        "seed": 0,
    }
    w = MultiAgentRolloutWorker(dict(base))
    b = w.sample()
    assert b.policy_batches["shared"]["obs"].shape[1] == 3
    np.testing.assert_allclose(b.policy_batches["shared"]["obs"][0], 7.0)
    # per-policy custom pipeline: normalization applies to every agent
    w2 = MultiAgentRolloutWorker(dict(
        base, agent_connectors=[("flatten_obs", {}), ("normalize_obs", {})]))
    b2 = w2.sample()
    assert abs(float(b2.policy_batches["shared"]["obs"].mean())) < 7.0
    state = w2.get_connector_state()
    name, params = state["agent"]["shared"][-1]
    assert name == "normalize_obs" and params["n"] > 0
    w2.set_connector_state(state)


def test_multi_agent_filter_knob_and_instance_isolation():
    """observation_filter='MeanStdFilter' works for multi-agent too, and
    a spec carrying connector INSTANCES gets a per-policy deep copy —
    stateful connectors must not be shared across policies."""
    from ray_tpu.rllib import MultiAgentEnv, MultiAgentRolloutWorker

    class _Box:
        def __init__(self, shape):
            self.shape = shape

    class _Disc:
        def __init__(self, n):
            self.n = n

    class TwoPolicyEnv(MultiAgentEnv):
        agents = ["a", "b"]

        def reset(self, *, seed=None, options=None):
            return {ag: np.zeros(3, np.float32) for ag in self.agents}, {}

        def step(self, action_dict):
            return ({ag: np.zeros(3, np.float32) for ag in self.agents},
                    {ag: 0.0 for ag in self.agents},
                    {"__all__": False}, {"__all__": False}, {})

        def observation_space(self, agent_id):
            return _Box((3,))

        def action_space(self, agent_id):
            return _Disc(2)

    base = {
        "env_creator": lambda cfg: TwoPolicyEnv(),
        "multiagent": {"policies": {"p0": None, "p1": None},
                       "policy_mapping_fn": lambda a: "p0" if a == "a" else "p1"},
        "fcnet_hiddens": (8,),
        "seed": 0,
    }
    w = MultiAgentRolloutWorker(dict(base, observation_filter="MeanStdFilter"))
    n0 = w.agent_connectors["p0"].connectors[-1]
    n1 = w.agent_connectors["p1"].connectors[-1]
    assert isinstance(n0, NormalizeObs) and isinstance(n1, NormalizeObs)
    assert n0 is not n1
    w2 = MultiAgentRolloutWorker(dict(base, agent_connectors=[NormalizeObs()]))
    assert (w2.agent_connectors["p0"].connectors[0]
            is not w2.agent_connectors["p1"].connectors[0])


def test_normalize_obs_parallel_welford_merge():
    """Distributed filter sync math: merging two workers' popped deltas
    reproduces the sequential statistics exactly, and pop clears the
    buffer."""
    rng = np.random.default_rng(3)
    xs = rng.normal(2.0, 3.0, size=(64, 4))
    seq = NormalizeObs()
    for x in xs:
        seq(x, env_id=0)
    a, b = NormalizeObs(), NormalizeObs()
    for x in xs[:41]:
        a(x, env_id=0)
    for x in xs[41:]:
        b(x, env_id=0)
    master = NormalizeObs()
    master.apply_sync_delta(a.pop_sync_delta())
    master.apply_sync_delta(b.pop_sync_delta())
    sa, sm = seq.get_sync_state(), master.get_sync_state()
    assert sm["n"] == sa["n"] == 64
    np.testing.assert_allclose(sm["mean"], sa["mean"], rtol=1e-12)
    np.testing.assert_allclose(sm["m2"], sa["m2"], rtol=1e-9)
    assert a.pop_sync_delta() is None
    # broadcast half: set_sync_state replaces stats, restarts the buffer
    c = NormalizeObs()
    c.set_sync_state(sm)
    assert c.get_sync_state()["n"] == 64 and c.pop_sync_delta() is None


def test_filter_stats_sync_from_remote_workers(ray_instance):
    """MeanStdFilter with remote rollout workers: the workers' running
    statistics must reach the local (learner) worker each sampling round
    — otherwise eval/compute_single_action/checkpoints ride n=0 stats."""
    from ray_tpu.rllib import PPOConfig

    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=1, rollout_fragment_length=32)
            .training(train_batch_size=64, num_sgd_iter=1)
            .connectors(observation_filter="MeanStdFilter")
            .build())
    algo.train()
    local_stats = [s for s in
                   algo.workers.local_worker.get_connector_stat_states()
                   if s is not None]
    assert local_stats and local_stats[0]["n"] >= 64, \
        "remote filter statistics never reached the local worker"
    state = algo.save_checkpoint()
    name, params = state["connector_state"]["agent"][-1]
    assert name == "normalize_obs" and params["n"] >= 64, \
        "checkpoint persisted empty filter statistics"
    algo.stop()


def test_multi_agent_frame_stack_no_boundary_double_push():
    """A fragment boundary's bootstrap peek must not advance frame-stack
    state twice: the boundary obs is transformed once and the next
    fragment's first tick reuses it, so every stacked row matches the
    true counter stream (a double push would duplicate the boundary
    frame for the rest of the episode)."""
    from ray_tpu.rllib import MultiAgentEnv, MultiAgentRolloutWorker

    class _Box:
        def __init__(self, shape):
            self.shape = shape

    class _Disc:
        def __init__(self, n):
            self.n = n

    class CounterEnv(MultiAgentEnv):
        agents = ["a"]

        def __init__(self, config=None):
            self._t = 0

        def reset(self, *, seed=None, options=None):
            self._t = 0
            return {"a": np.array([0.0], np.float32)}, {}

        def step(self, action_dict):
            self._t += 1
            return ({"a": np.array([float(self._t)], np.float32)},
                    {"a": 0.0}, {"__all__": False}, {"__all__": False}, {})

        def observation_space(self, agent_id):
            return _Box((1,))

        def action_space(self, agent_id):
            return _Disc(2)

    w = MultiAgentRolloutWorker({
        "env_creator": lambda cfg: CounterEnv(cfg),
        "multiagent": {"policies": {"shared": None},
                       "policy_mapping_fn": lambda a: "shared"},
        "agent_connectors": [("frame_stack_obs", {"num_frames": 2})],
        "rollout_fragment_length": 4,
        "fcnet_hiddens": (8,),
        "seed": 0,
    })
    rows = np.concatenate([
        w.sample().policy_batches["shared"]["obs"],
        w.sample().policy_batches["shared"]["obs"]])
    # counter stream 0,1,2,... stacked pairwise: [t-1, t], the episode's
    # first frame repeated
    expected = np.array([[0, 0], [0, 1], [1, 2], [2, 3],
                         [3, 4], [4, 5], [5, 6], [6, 7]], np.float32)
    np.testing.assert_allclose(rows, expected)


class _LinearModule(RLModule):
    """Minimal custom jax model: one shared linear layer, split heads."""

    def __init__(self, obs_dim, num_actions):
        self.obs_dim, self.num_actions = obs_dim, num_actions

    def init(self, rng):
        import jax

        k1, k2 = jax.random.split(rng)
        return {
            "w_pi": jax.random.normal(k1, (self.obs_dim, self.num_actions))
            * 0.01,
            "w_vf": jax.random.normal(k2, (self.obs_dim, 1)) * 0.01,
        }

    def forward_train(self, params, obs):
        from ray_tpu.rllib import Columns

        return {
            Columns.ACTION_DIST_INPUTS: obs @ params["w_pi"],
            Columns.VF_PREDS: (obs @ params["w_vf"])[..., 0],
        }


def test_custom_rl_module_plugs_into_ppo():
    """A custom RLModule drops into PPO via config.rl_module() — no
    Policy subclass: sampling, the loss, greedy inference, and the
    optimizer all route through its forwards."""
    algo = (
        PPOConfig()
        .environment("CartPole-v1")
        .rl_module(lambda ctx: _LinearModule(ctx.obs_dim, ctx.num_actions))
        .rollouts(rollout_fragment_length=100)
        .training(train_batch_size=200, sgd_minibatch_size=64, num_sgd_iter=2)
        .debugging(seed=0)
        .build()
    )
    try:
        policy = algo.get_policy()
        assert isinstance(policy.module, _LinearModule)
        assert set(policy.params) == {"w_pi", "w_vf"}
        w_before = np.asarray(policy.params["w_pi"]).copy()
        r = algo.train()
        assert np.isfinite(r["info"]["learner"]["total_loss"])
        # SGD updated the CUSTOM params
        assert not np.allclose(
            w_before, np.asarray(policy.params["w_pi"]))
        a = algo.compute_single_action(np.zeros(4, np.float32))
        assert a in (0, 1)
    finally:
        algo.cleanup()


def test_gae_truncation_cuts_trace_and_bootstraps():
    """A mid-fragment truncation must not leak the next episode's GAE
    trace across the reset, and must bootstrap with the value estimate
    instead of zero (the TERMINATEDS-only check was the bug)."""
    gamma, lam = 0.9, 0.8
    rewards = np.array([1.0, 2.0, 3.0], np.float32)
    values = np.array([0.5, 1.0, 1.5], np.float32)
    batch = SampleBatch({
        SampleBatch.REWARDS: rewards,
        SampleBatch.VF_PREDS: values,
        SampleBatch.TERMINATEDS: np.array([False, False, False]),
        SampleBatch.TRUNCATEDS: np.array([False, True, False]),
    })
    last_v = 2.0
    out = compute_gae(batch, last_v, gamma, lam)
    # step 2 (new episode's start): plain tail bootstrap
    d2 = rewards[2] + gamma * last_v - values[2]
    # step 1 truncated: bootstraps its OWN value estimate, trace cut
    d1 = rewards[1] + gamma * values[1] - values[1]
    # step 0: normal recursion INTO step 1 (same episode)
    d0 = rewards[0] + gamma * values[1] - values[0]
    expected = np.array([d0 + gamma * lam * d1, d1, d2])
    np.testing.assert_allclose(out[SampleBatch.ADVANTAGES], expected,
                               rtol=1e-5)
    # a trace-leak (the old behavior) would have coupled step 1 to d2
    leaked = d1 + gamma * lam * d2
    assert abs(out[SampleBatch.ADVANTAGES][1] - leaked) > 1e-3


def test_worker_truncation_bootstrap_matches_value():
    """End-to-end: an env that TRUNCATES mid-fragment produces segments
    whose tail advantage used v(s_T), not 0 (the time-limit contract)."""

    class TruncEnv:
        def __init__(self):
            self.observation_space = type(
                "S", (), {"shape": (2,), "dtype": np.float32})()
            self.action_space = type("A", (), {"n": 2})()
            self._t = 0

        def reset(self, *, seed=None, options=None):
            self._t = 0
            return np.zeros(2, np.float32), {}

        def step(self, action):
            self._t += 1
            return (np.zeros(2, np.float32), 1.0, False, self._t >= 5, {})

    w = RolloutWorker({
        "env_creator": lambda cfg: TruncEnv(),
        "rollout_fragment_length": 12, "seed": 0, "gamma": 0.9,
        "lambda_": 1.0, "fcnet_hiddens": (8,),
    })
    batch = w.sample()
    # truncation boundaries present mid-fragment, and every row got a
    # finite advantage (the bootstrap path ran)
    assert batch["truncateds"].sum() >= 2
    assert np.all(np.isfinite(batch["advantages"]))
    # tail row of the first truncated episode: adv = r + gamma*v(s_T) - v
    end = int(np.argmax(batch["truncateds"]))
    v_end = batch["vf_preds"][end]
    boot = w.policy.value(batch["obs"][end][None])[0]  # same obs stream
    expect = 1.0 + 0.9 * boot - v_end
    # v(s_T) is computed from the TRUE next obs (all-zeros env: identical
    # to the stored obs), so this is exact up to float32 noise
    np.testing.assert_allclose(batch["advantages"][end], expect, atol=1e-4)
