"""Chaos-proven serve ingress: replica SIGKILL mid-soak.

The ROADMAP item 2 headline scenario as a tier-1 test: concurrent
keep-alive clients soak the asyncio ingress while ``devtools/chaos``
SIGKILLs a replica out from under them.  Acceptance asserted here:

- zero lost idempotent requests — every client request ends 200 (in-flight
  requests on the dead replica are retried to a live one; shed 503s are
  re-tried by the client after Retry-After, never a 500/504);
- bounded p99 across the incident;
- the controller replaces the dead replica (recovery measured);
- ``ray_tpu doctor`` can explain the incident from the flight recorder
  and reports no OPEN ingress incident after recovery.

The tier-1 variant runs 64 clients; the 1k-client soak is ``slow``
(auto-deselected — run with ``-m slow`` or ``RAY_TPU_RUN_SLOW=1``).
"""

import json
import os
import threading
import time

import http.client

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_instance():
    os.environ["RAY_TPU_EVENTS_FLUSH_S"] = "0.2"
    ray_tpu.init(num_cpus=16)
    client = serve.start(serve.HTTPOptions(host="127.0.0.1", port=0))
    yield client
    serve.shutdown()
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_EVENTS_FLUSH_S", None)


class _SoakStats:
    def __init__(self):
        self.lock = threading.Lock()
        self.latencies = []          # (t_end, served-attempt latency) per 200
        self.lost = []               # 500/504: accepted-then-failed = LOST
        self.refused = 0             # logical requests that only ever got
        #                              503s — shed honestly, never accepted
        self.shed_retries = 0        # 503s absorbed by client retry
        self.errors = []             # transport-level failures


def _soak(port, path, n_clients, duration_s, deadline_s=30.0,
          stats=None) -> _SoakStats:
    """Closed-loop soak: each client hammers ``path`` over one keep-alive
    connection.  A 503 (shed) waits out Retry-After and retries; a
    request is LOST only if the system accepted it and then failed it
    (500/504/transport error).  A request that only ever saw 503s was
    REFUSED — the shedding design working, counted separately."""
    stats = stats or _SoakStats()
    t_end = time.monotonic() + duration_s

    def client_loop():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        try:
            while time.monotonic() < t_end:
                req_deadline = time.monotonic() + deadline_s
                while True:  # one logical (idempotent) request
                    t_a = time.monotonic()
                    try:
                        conn.request(
                            "GET", path,
                            headers={"X-Serve-Deadline-S": f"{deadline_s}"})
                        resp = conn.getresponse()
                        body = resp.read()
                        status = resp.status
                    except Exception as e:  # noqa: BLE001 — transport
                        # failure: reconnect and retry within the deadline
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", port, timeout=120)
                        if time.monotonic() >= req_deadline:
                            with stats.lock:
                                stats.errors.append(repr(e))
                            break
                        continue
                    if status == 200:
                        # latency of the SERVED attempt: what "bounded
                        # p99 for accepted requests" promises
                        with stats.lock:
                            stats.latencies.append(
                                (time.monotonic(),
                                 time.monotonic() - t_a))
                        break
                    if status == 503:
                        if time.monotonic() < req_deadline:
                            retry_after = float(
                                resp.headers.get("Retry-After") or 0.2)
                            with stats.lock:
                                stats.shed_retries += 1
                            time.sleep(min(retry_after, 0.5))
                            continue
                        with stats.lock:
                            stats.refused += 1
                        break
                    with stats.lock:
                        stats.lost.append((status, body[:200]))
                    break
        finally:
            conn.close()

    threads = [threading.Thread(target=client_loop, name=f"soak-{i}")
               for i in range(n_clients)]
    for t in threads:
        t.start()
    return stats, threads


def _p99(vals):
    vals = sorted(vals)
    return vals[min(len(vals) - 1, int(len(vals) * 0.99))] if vals else 0.0


def _run_chaos_scenario(serve_instance, n_clients, duration_s,
                        kill_at_s, deployment_name):
    """Deploy → soak → SIGKILL one replica mid-soak → assert the
    acceptance criteria.  Shared by the tier-1 and slow variants."""
    from ray_tpu.devtools.chaos import ChaosMonkey
    from ray_tpu.experimental.state import api as state
    from ray_tpu.util import doctor

    @serve.deployment(
        name=deployment_name, num_replicas=2, max_concurrent_queries=64,
        max_queued_requests=512,
        ray_actor_options={"max_concurrency": 64})
    class Soak:
        def __call__(self, request=None):
            time.sleep(0.03)
            return "ok"

    serve.run(Soak.bind(), port=0)
    _, port = serve.get_http_address()
    stats0 = ray_tpu.get(serve_instance.proxy.ingress_stats.remote(),
                         timeout=30)

    stats, threads = _soak(port, f"/{deployment_name}", n_clients,
                           duration_s)
    time.sleep(kill_at_s)
    monkey = ChaosMonkey()
    t_kill = time.monotonic()
    rec = monkey.kill_serve_replica(deployment_name,
                                    controller=serve_instance.controller)
    assert rec["op"] == "kill_replica" and rec["pid"] > 0
    dead_tag = rec["target"]

    # the controller's health loop replaces the dead replica: recovered
    # means the corpse is OUT of the routing set (stale status right
    # after the kill still lists it RUNNING) and 2 live replicas are back
    recovery_s = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        info = ray_tpu.get(
            serve_instance.controller.get_routing_info.remote(
                deployment_name), timeout=30)
        tags = {t for t, _ in info["replicas"]}
        if dead_tag not in tags and len(tags) >= 2:
            recovery_s = time.monotonic() - t_kill
            break
        time.sleep(0.25)
    for t in threads:
        t.join(timeout=max(duration_s, 60) + 120)
    assert not any(t.is_alive() for t in threads), "soak clients wedged"

    # ---- acceptance ----
    # zero LOST idempotent requests: nothing the system accepted failed
    # (500/504/transport).  Refusals (pure-503 give-ups under extreme
    # synthetic overload) are the shedding design being honest — allowed,
    # but they must be refusals, not failures.
    assert stats.lost == [], f"lost idempotent requests: {stats.lost[:5]}"
    assert stats.errors == [], f"transport failures: {stats.errors[:5]}"
    assert len(stats.latencies) > n_clients, "soak made no progress"
    assert recovery_s is not None, "dead replica never replaced"
    during = [l for ts, l in stats.latencies
              if 0 <= ts - t_kill <= max(recovery_s, 2.0)]
    after = [l for ts, l in stats.latencies
             if ts - t_kill > max(recovery_s, 2.0)]
    # bounded p99 ACROSS the incident: accepted requests never see the
    # 30s client deadline even while a replica is being replaced
    p99_during = _p99(during)
    p99_after = _p99(after)
    assert p99_during < 10.0, f"p99 unbounded during incident: {p99_during:.2f}s"
    if after:
        assert p99_after < 10.0, f"p99 after recovery: {p99_after:.2f}s"

    # the ingress absorbed the death by re-assigning in-flight requests
    stats1 = ray_tpu.get(serve_instance.proxy.ingress_stats.remote(),
                         timeout=30)
    assert stats1["replica_deaths"] > stats0["replica_deaths"], \
        "no in-flight request ever saw the death (soak not saturating?)"
    assert stats1["retries"] > stats0["retries"]

    # doctor: the incident is explained (chaos injection + retries on
    # record) and NO ingress incident stays open after recovery
    deadline = time.monotonic() + 20
    rows = []
    while time.monotonic() < deadline:
        rows = state.list_events(limit=100_000)
        if any(e.get("source") == "chaos"
               and e.get("message") == "inject kill_replica"
               for e in rows):
            break
        time.sleep(0.3)
    assert any(e.get("source") == "chaos"
               and e.get("message") == "inject kill_replica"
               for e in rows), "chaos injection not on record"
    open_rules = {f["rule"] for f in doctor.diagnose(rows)}
    assert "ingress_shedding" not in open_rules, \
        "shedding incident still open after recovery"
    assert "drain_stuck" not in open_rules
    serve.delete(deployment_name)
    return stats, stats1


def test_chaos_soak_64_clients_replica_kill(serve_instance):
    """Tier-1 variant: 64 concurrent clients, replica SIGKILL mid-soak —
    zero lost idempotent requests, bounded p99, replacement + clean
    doctor after recovery."""
    _run_chaos_scenario(serve_instance, n_clients=64, duration_s=6.0,
                        kill_at_s=2.0, deployment_name="Soak64")


@pytest.mark.slow
def test_chaos_soak_1k_clients_replica_kill(serve_instance):
    """The ROADMAP headline at full width: 1000 concurrent clients.
    Slow-marked (thread count + duration); the semantics are identical
    to the tier-1 variant."""
    _run_chaos_scenario(serve_instance, n_clients=1000, duration_s=15.0,
                        kill_at_s=5.0, deployment_name="Soak1k")


def test_trend_autoscaler_scales_replicas_off_router_backlog(
        serve_instance):
    """The PR 7 trend policy closes the loop on serve: a router-backlog
    series (the queue gauge the router already exports) produces a
    ``scale_up_replicas`` decision, and ``serve_replica_scaler`` applies
    it through the controller's scale_deployment RPC — capacity arrives
    off the TREND, before doctor's router_saturation incident forms."""
    from ray_tpu._private import events as events_mod
    from ray_tpu.autoscaler.policy import TrendPolicy, serve_replica_scaler

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 4,
        "target_num_ongoing_requests_per_replica": 1000.0,  # inert
        "upscale_delay_s": 600.0, "downscale_delay_s": 600.0,
    })
    class Backlogged:
        def __call__(self, request=None):
            return "ok"

    serve.run(Backlogged.bind(), port=0)
    assert serve.status()["Backlogged"]["num_replicas_goal"] == 1

    # a standing router backlog, in the exact shape query_metric returns
    now = time.time()
    series_map = {"ray_tpu_serve_router_queue_len": [{
        "tags": {"deployment": "Backlogged"},
        "points": [[now - 60 + i * 5, 3.0 + i * 0.2] for i in range(12)],
    }]}
    policy = TrendPolicy()
    decisions = policy.decide(series_map, now=now)
    ups = [d for d in decisions if d.action == "scale_up_replicas"]
    assert ups and ups[0].deployment == "Backlogged", decisions

    scaler = serve_replica_scaler(serve_instance.controller)
    scaler(ups[0].deployment, ups[0].amount)
    goal = serve.status()["Backlogged"]["num_replicas_goal"]
    assert goal >= 2, f"trend decision did not grow capacity (goal={goal})"
    # the decision trail is on the flight recorder (autoscaler source
    # emits in TrendAutoscaler.apply; here we assert the controller side)
    assert events_mod.ENABLED
    serve.delete("Backlogged")
