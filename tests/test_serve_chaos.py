"""Chaos-proven serve ingress: replica SIGKILL mid-soak.

The ROADMAP item 2 headline scenario as a tier-1 test: concurrent
keep-alive clients soak the asyncio ingress while ``devtools/chaos``
SIGKILLs a replica out from under them.  Acceptance asserted here:

- zero lost idempotent requests — every client request ends 200 (in-flight
  requests on the dead replica are retried to a live one; shed 503s are
  re-tried by the client after Retry-After, never a 500/504);
- bounded p99 across the incident;
- the controller replaces the dead replica (recovery measured);
- ``ray_tpu doctor`` can explain the incident from the flight recorder
  and reports no OPEN ingress incident after recovery;
- the WATCHDOG turns the death into an incident within a tick, pushes it
  out the webhook sink, freezes a post-mortem bundle holding the dead
  replica's stderr tail + a trace + the serve-p99 TSDB slice, and
  auto-resolves once the replacement replica absorbs the load.

The tier-1 variant runs 64 clients; the 1k-client soak is ``slow``
(auto-deselected — run with ``-m slow`` or ``RAY_TPU_RUN_SLOW=1``).
"""

import http.server
import json
import os
import sys
import threading
import time

import http.client

import pytest

import ray_tpu
from ray_tpu import serve


class _WebhookLog(http.server.BaseHTTPRequestHandler):
    payloads: list = []

    def do_POST(self):
        body = self.rfile.read(int(self.headers["Content-Length"]))
        type(self).payloads.append(json.loads(body))
        self.send_response(200)
        self.end_headers()

    def log_message(self, *a):
        pass


@pytest.fixture(scope="module")
def serve_instance():
    hook = http.server.ThreadingHTTPServer(("127.0.0.1", 0), _WebhookLog)
    threading.Thread(target=hook.serve_forever, daemon=True).start()
    _WebhookLog.payloads = []
    env = {
        "RAY_TPU_EVENTS_FLUSH_S": "0.2",
        # watchdog at test cadence: incident within a tick of the kill,
        # evidence window short enough that auto-resolve is observable
        "RAY_TPU_WATCHDOG_S": "0.3",
        "RAY_TPU_WATCHDOG_EVENT_WINDOW_S": "2.5",
        "RAY_TPU_LOG_SHIP_S": "0.1",
        # the proxy actor's p99/requests gauges must be IN the head TSDB
        # by the time the incident bundle freezes its metric slices
        "RAY_TPU_METRICS_PUSH_S": "0.5",
        "RAY_TPU_INCIDENT_WEBHOOK":
            f"http://127.0.0.1:{hook.server_port}/hook",
    }
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    ray_tpu.init(num_cpus=16)
    client = serve.start(serve.HTTPOptions(host="127.0.0.1", port=0))
    yield client
    serve.shutdown()
    ray_tpu.shutdown()
    for k, v in old.items():
        os.environ.pop(k, None) if v is None else os.environ.update({k: v})
    hook.shutdown()
    hook.server_close()


def _wait_for(fn, timeout=30.0, interval=0.1, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"{desc} not met within {timeout}s")


class _SoakStats:
    def __init__(self):
        self.lock = threading.Lock()
        self.latencies = []          # (t_end, served-attempt latency) per 200
        self.lost = []               # 500/504: accepted-then-failed = LOST
        self.refused = 0             # logical requests that only ever got
        #                              503s — shed honestly, never accepted
        self.shed_retries = 0        # 503s absorbed by client retry
        self.errors = []             # transport-level failures


def _soak(port, path, n_clients, duration_s, deadline_s=30.0,
          stats=None) -> _SoakStats:
    """Closed-loop soak: each client hammers ``path`` over one keep-alive
    connection.  A 503 (shed) waits out Retry-After and retries; a
    request is LOST only if the system accepted it and then failed it
    (500/504/transport error).  A request that only ever saw 503s was
    REFUSED — the shedding design working, counted separately."""
    stats = stats or _SoakStats()
    t_end = time.monotonic() + duration_s

    def client_loop():
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=120)
        try:
            while time.monotonic() < t_end:
                req_deadline = time.monotonic() + deadline_s
                while True:  # one logical (idempotent) request
                    t_a = time.monotonic()
                    try:
                        conn.request(
                            "GET", path,
                            headers={"X-Serve-Deadline-S": f"{deadline_s}"})
                        resp = conn.getresponse()
                        body = resp.read()
                        status = resp.status
                    except Exception as e:  # noqa: BLE001 — transport
                        # failure: reconnect and retry within the deadline
                        conn.close()
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", port, timeout=120)
                        if time.monotonic() >= req_deadline:
                            with stats.lock:
                                stats.errors.append(repr(e))
                            break
                        continue
                    if status == 200:
                        # latency of the SERVED attempt: what "bounded
                        # p99 for accepted requests" promises
                        with stats.lock:
                            stats.latencies.append(
                                (time.monotonic(),
                                 time.monotonic() - t_a))
                        break
                    if status == 503:
                        if time.monotonic() < req_deadline:
                            retry_after = float(
                                resp.headers.get("Retry-After") or 0.2)
                            with stats.lock:
                                stats.shed_retries += 1
                            time.sleep(min(retry_after, 0.5))
                            continue
                        with stats.lock:
                            stats.refused += 1
                        break
                    with stats.lock:
                        stats.lost.append((status, body[:200]))
                    break
        finally:
            conn.close()

    threads = [threading.Thread(target=client_loop, name=f"soak-{i}")
               for i in range(n_clients)]
    for t in threads:
        t.start()
    return stats, threads


def _p99(vals):
    vals = sorted(vals)
    return vals[min(len(vals) - 1, int(len(vals) * 0.99))] if vals else 0.0


def _run_chaos_scenario(serve_instance, n_clients, duration_s,
                        kill_at_s, deployment_name):
    """Deploy → soak → SIGKILL one replica mid-soak → assert the
    acceptance criteria.  Shared by the tier-1 and slow variants."""
    from ray_tpu.devtools.chaos import ChaosMonkey
    from ray_tpu.experimental.state import api as state
    from ray_tpu.util import doctor

    @serve.deployment(
        name=deployment_name, num_replicas=2, max_concurrent_queries=64,
        max_queued_requests=512,
        ray_actor_options={"max_concurrency": 64})
    class Soak:
        def __init__(self):
            # stderr canary: when this replica is SIGKILLed, the shipped
            # tail is what worker_stderr_at_death surfaces and what the
            # incident bundle must contain
            print("Traceback (most recent call last):", file=sys.stderr)
            print(f"RuntimeError: chaos-canary-{deployment_name}",
                  file=sys.stderr)
            sys.stderr.flush()

        def __call__(self, request=None):
            time.sleep(0.03)
            return "ok"

    serve.run(Soak.bind(), port=0)
    _, port = serve.get_http_address()
    stats0 = ray_tpu.get(serve_instance.proxy.ingress_stats.remote(),
                         timeout=30)

    stats, threads = _soak(port, f"/{deployment_name}", n_clients,
                           duration_s)
    time.sleep(kill_at_s)
    monkey = ChaosMonkey()
    t_kill = time.monotonic()
    t_kill_wall = time.time()
    rec = monkey.kill_serve_replica(deployment_name,
                                    controller=serve_instance.controller)
    assert rec["op"] == "kill_replica" and rec["pid"] > 0
    dead_tag = rec["target"]

    # the controller's health loop replaces the dead replica: recovered
    # means the corpse is OUT of the routing set (stale status right
    # after the kill still lists it RUNNING) and 2 live replicas are back
    recovery_s = None
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        info = ray_tpu.get(
            serve_instance.controller.get_routing_info.remote(
                deployment_name), timeout=30)
        tags = {t for t, _ in info["replicas"]}
        if dead_tag not in tags and len(tags) >= 2:
            recovery_s = time.monotonic() - t_kill
            break
        time.sleep(0.25)
    for t in threads:
        t.join(timeout=max(duration_s, 60) + 120)
    assert not any(t.is_alive() for t in threads), "soak clients wedged"

    # ---- acceptance ----
    # zero LOST idempotent requests: nothing the system accepted failed
    # (500/504/transport).  Refusals (pure-503 give-ups under extreme
    # synthetic overload) are the shedding design being honest — allowed,
    # but they must be refusals, not failures.
    assert stats.lost == [], f"lost idempotent requests: {stats.lost[:5]}"
    assert stats.errors == [], f"transport failures: {stats.errors[:5]}"
    assert len(stats.latencies) > n_clients, "soak made no progress"
    assert recovery_s is not None, "dead replica never replaced"
    during = [l for ts, l in stats.latencies
              if 0 <= ts - t_kill <= max(recovery_s, 2.0)]
    after = [l for ts, l in stats.latencies
             if ts - t_kill > max(recovery_s, 2.0)]
    # bounded p99 ACROSS the incident: accepted requests never see the
    # 30s client deadline even while a replica is being replaced
    p99_during = _p99(during)
    p99_after = _p99(after)
    assert p99_during < 10.0, f"p99 unbounded during incident: {p99_during:.2f}s"
    if after:
        assert p99_after < 10.0, f"p99 after recovery: {p99_after:.2f}s"

    # the ingress absorbed the death by re-assigning in-flight requests
    stats1 = ray_tpu.get(serve_instance.proxy.ingress_stats.remote(),
                         timeout=30)
    assert stats1["replica_deaths"] > stats0["replica_deaths"], \
        "no in-flight request ever saw the death (soak not saturating?)"
    assert stats1["retries"] > stats0["retries"]

    # doctor: the incident is explained (chaos injection + retries on
    # record) and NO ingress incident stays open after recovery
    deadline = time.monotonic() + 20
    rows = []
    while time.monotonic() < deadline:
        rows = state.list_events(limit=100_000)
        if any(e.get("source") == "chaos"
               and e.get("message") == "inject kill_replica"
               for e in rows):
            break
        time.sleep(0.3)
    assert any(e.get("source") == "chaos"
               and e.get("message") == "inject kill_replica"
               for e in rows), "chaos injection not on record"
    open_rules = {f["rule"] for f in doctor.diagnose(rows)}
    assert "ingress_shedding" not in open_rules, \
        "shedding incident still open after recovery"
    assert "drain_stuck" not in open_rules

    # ---- watchdog plane ----
    # the replica SIGKILL became an incident within a tick or two of the
    # death landing on the head (0.3s cadence here), with the transition
    # on the flight recorder AND out the webhook sink
    iid = "worker_stderr_at_death--cluster"

    def _incident():
        for i in state.list_incidents():
            if i["id"] == iid:
                return i
        return None

    inc = _wait_for(
        lambda: (lambda i: i if i and any(
            h["transition"] in ("open", "reopen")
            and h["ts"] >= t_kill_wall - 0.5
            for h in i["history"]) else None)(_incident()),
        timeout=20, desc="watchdog incident for replica death")
    fired = next(h for h in inc["history"]
                 if h["transition"] in ("open", "reopen")
                 and h["ts"] >= t_kill_wall - 0.5)
    assert fired["ts"] - t_kill_wall < 10.0, \
        f"incident lagged the kill by {fired['ts'] - t_kill_wall:.1f}s"
    _wait_for(lambda: any(
        p.get("incident", {}).get("id") == iid
        and p.get("transition") in ("open", "reopen")
        for p in _WebhookLog.payloads),
        timeout=15, desc="incident pushed to webhook sink")

    # the post-mortem bundle froze the evidence: the dead replica's
    # stderr tail, a trace, and the serve-p99 TSDB slice
    inc = _wait_for(lambda: (lambda i: i if i and i.get("bundle_dir")
                             else None)(_incident()),
                    timeout=15, desc="post-mortem bundle captured")
    bdir = inc["bundle_dir"]
    tails = ""
    for fn in os.listdir(os.path.join(bdir, "logs")):
        with open(os.path.join(bdir, "logs", fn), errors="replace") as f:
            tails += f.read()
    assert "chaos-canary-" in tails, \
        f"dead replica stderr missing from bundle: {os.listdir(bdir)}"
    assert any(fn.startswith("trace") for fn in os.listdir(bdir)), \
        f"no trace evidence in bundle: {os.listdir(bdir)}"
    tsdb_slices = os.listdir(os.path.join(bdir, "tsdb"))
    assert "ray_tpu_serve_http_p99_s.json" in tsdb_slices, \
        f"serve p99 slice missing from bundle: {tsdb_slices}"

    # auto-resolve: replacement absorbed the load, the evidence aged out
    # of the doctor window, hysteresis closed the incident
    _wait_for(lambda: _incident()["state"] == "resolved",
              timeout=30, desc="incident auto-resolved after recovery")
    serve.delete(deployment_name)
    return stats, stats1


def test_chaos_soak_64_clients_replica_kill(serve_instance):
    """Tier-1 variant: 64 concurrent clients, replica SIGKILL mid-soak —
    zero lost idempotent requests, bounded p99, replacement + clean
    doctor after recovery."""
    _run_chaos_scenario(serve_instance, n_clients=64, duration_s=6.0,
                        kill_at_s=2.0, deployment_name="Soak64")


def test_chaos_repeat_kill_reopens_incident(serve_instance):
    """A second replica kill after the first incident resolved RE-OPENS
    the same incident (stable id) instead of minting a new one — the
    reopen counter is the flap record escalation keys off."""
    from ray_tpu.devtools.chaos import ChaosMonkey
    from ray_tpu.experimental.state import api as state

    iid = "worker_stderr_at_death--cluster"

    def _incident():
        for i in state.list_incidents():
            if i["id"] == iid:
                return i
        return None

    # quiesce first: deleting the previous canary-printing deployment
    # retires replicas whose stderr holds a Traceback, which legitimately
    # re-fires the rule a beat later — let that land and resolve before
    # measuring, so the reopen below is attributable to OUR kill
    _wait_for(
        lambda: (lambda i: i if i and i["state"] == "resolved" else None)(
            _incident()),
        timeout=30, desc="prior incident resolved before repeat kill")
    time.sleep(4.0)
    prior = _wait_for(
        lambda: (lambda i: i if i and i["state"] == "resolved" else None)(
            _incident()),
        timeout=30, desc="incident quiesced before repeat kill")
    prior_reopens = prior["reopen_count"]

    @serve.deployment(name="Repeat", num_replicas=2)
    class Repeat:
        def __init__(self):
            print("Traceback (most recent call last):", file=sys.stderr)
            print("RuntimeError: chaos-canary-Repeat", file=sys.stderr)
            sys.stderr.flush()

        def __call__(self, request=None):
            return "ok"

    serve.run(Repeat.bind(), port=0)
    time.sleep(0.5)  # let the replicas' stderr canaries ship to the head
    ChaosMonkey().kill_serve_replica(
        "Repeat", controller=serve_instance.controller)

    inc = _wait_for(
        lambda: (lambda i: i if i
                 and i["reopen_count"] > prior_reopens else None)(
            _incident()),
        timeout=20, desc="repeat kill re-opened the incident")
    assert [h["transition"] for h in inc["history"]].count("open") == 1, \
        "repeat kill minted a second open instead of a reopen"
    _wait_for(lambda: any(
        p.get("incident", {}).get("id") == iid
        and p.get("transition") == "reopen"
        for p in _WebhookLog.payloads),
        timeout=15, desc="reopen pushed to webhook sink")
    serve.delete("Repeat")


@pytest.mark.slow
def test_chaos_healthy_soak_60s_incident_free(serve_instance):
    """The healthy gate at soak length: 60 s of steady traffic with no
    fault injected opens ZERO fault incidents and burns no SLO — the
    watchdog is quiet exactly when the cluster is healthy.

    Head-resource trend findings (GIL/lock/serialization pressure) are
    tolerated here: the simulated cluster runs replicas, ingress, and
    clients in ONE Python process, so a soak legitimately saturates the
    test process's GIL — that is the profiler plane describing the
    harness, not a serve fault."""
    from ray_tpu.experimental.state import api as state

    harness_rules = {"gil_saturation", "lock_contention",
                     "serialization_hot", "rss_growth"}

    @serve.deployment(name="Healthy", num_replicas=2,
                      max_concurrent_queries=64,
                      ray_actor_options={"max_concurrency": 64})
    class Healthy:
        def __call__(self, request=None):
            time.sleep(0.01)
            return "ok"

    serve.run(Healthy.bind(), port=0)
    _, port = serve.get_http_address()
    def _fault_rows():
        return [i for i in state.list_incidents()
                if i["rule"] not in harness_rules]

    _wait_for(lambda: all(i["state"] == "resolved"
                          for i in _fault_rows()),
              timeout=30, desc="carried-over incidents resolved")
    # quiesce: a just-deleted canary deployment's retirements can re-fire
    # the stderr rule a beat later — absorb that before baselining
    time.sleep(4.0)
    _wait_for(lambda: all(i["state"] == "resolved"
                          for i in _fault_rows()),
              timeout=30, desc="incidents quiesced before healthy soak")
    baseline = {i["id"]: len(i["history"]) for i in _fault_rows()}

    stats, threads = _soak(port, "/Healthy", 32, 60.0)
    for t in threads:
        t.join(timeout=180)
    assert stats.lost == [] and stats.errors == []

    time.sleep(1.0)  # a few watchdog ticks past the soak's end
    for inc in _fault_rows():
        assert inc["state"] == "resolved", \
            f"healthy soak opened incident {inc['id']}"
        assert len(inc["history"]) == baseline.get(inc["id"]), \
            f"healthy soak produced transitions on {inc['id']}"
    assert all(not s["burning"] for s in state.list_slos())
    serve.delete("Healthy")


@pytest.mark.slow
def test_chaos_soak_1k_clients_replica_kill(serve_instance):
    """The ROADMAP headline at full width: 1000 concurrent clients.
    Slow-marked (thread count + duration); the semantics are identical
    to the tier-1 variant."""
    _run_chaos_scenario(serve_instance, n_clients=1000, duration_s=15.0,
                        kill_at_s=5.0, deployment_name="Soak1k")


def test_trend_autoscaler_scales_replicas_off_router_backlog(
        serve_instance):
    """The PR 7 trend policy closes the loop on serve: a router-backlog
    series (the queue gauge the router already exports) produces a
    ``scale_up_replicas`` decision, and ``serve_replica_scaler`` applies
    it through the controller's scale_deployment RPC — capacity arrives
    off the TREND, before doctor's router_saturation incident forms."""
    from ray_tpu._private import events as events_mod
    from ray_tpu.autoscaler.policy import TrendPolicy, serve_replica_scaler

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 4,
        "target_num_ongoing_requests_per_replica": 1000.0,  # inert
        "upscale_delay_s": 600.0, "downscale_delay_s": 600.0,
    })
    class Backlogged:
        def __call__(self, request=None):
            return "ok"

    serve.run(Backlogged.bind(), port=0)
    assert serve.status()["Backlogged"]["num_replicas_goal"] == 1

    # a standing router backlog, in the exact shape query_metric returns
    now = time.time()
    series_map = {"ray_tpu_serve_router_queue_len": [{
        "tags": {"deployment": "Backlogged"},
        "points": [[now - 60 + i * 5, 3.0 + i * 0.2] for i in range(12)],
    }]}
    policy = TrendPolicy()
    decisions = policy.decide(series_map, now=now)
    ups = [d for d in decisions if d.action == "scale_up_replicas"]
    assert ups and ups[0].deployment == "Backlogged", decisions

    scaler = serve_replica_scaler(serve_instance.controller)
    scaler(ups[0].deployment, ups[0].amount)
    goal = serve.status()["Backlogged"]["num_replicas_goal"]
    assert goal >= 2, f"trend decision did not grow capacity (goal={goal})"
    # the decision trail is on the flight recorder (autoscaler source
    # emits in TrendAutoscaler.apply; here we assert the controller side)
    assert events_mod.ENABLED
    serve.delete("Backlogged")
