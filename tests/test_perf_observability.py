"""Performance observability: the shared FLOPs/roofline model
(``util/flops.py``), the step profiler's phase attribution + live MFU +
compile-cache accounting (``util/perf.py``), decode-loop attribution in
the serve engine (TTFT/ITL + prefill-interference meter), the four perf
doctor rules, the ``perf_summary`` surfaces (state API / CLI /
dashboard), and the ``profiling.py`` double-start guard.

NOTE on ordering: the cluster-backed healthy-run gate runs BEFORE the
induced-pathology tests in this module (tier-1 runs with
``-p no:randomly``) — the recompile-storm loop deliberately pollutes the
driver's local event ring, and the head folds that ring into
``list_events``.
"""

import io
import json
import os
import time
from contextlib import redirect_stdout

import pytest

import ray_tpu
from ray_tpu._private import events as events_mod
from ray_tpu.util import flops as flops_mod
from ray_tpu.util.perf import CompileTracker, StepProfiler, sample_device_memory


def _wait_for(pred, timeout=30.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# flops model (pure)
# ---------------------------------------------------------------------------

def test_flops_model_shared_with_bench():
    """util/flops.py carries the exact bench formulas: 6N + 12·L·D·T and
    the per-generation peak table with a v5e fallback."""
    from ray_tpu.models import gpt2

    cfg = gpt2.GPT2Config.tiny()
    n_params = 123_456
    assert flops_mod.transformer_flops_per_token(
        n_params, cfg.n_layers, cfg.d_model, cfg.max_seq_len) == \
        6 * n_params + 12 * cfg.n_layers * cfg.d_model * cfg.max_seq_len
    assert flops_mod.model_flops_per_token(cfg, n_params) == \
        flops_mod.transformer_flops_per_token(
            n_params, cfg.n_layers, cfg.d_model, cfg.max_seq_len)
    # bench.py re-exports: the two modules can never drift
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if root not in sys.path:
        sys.path.insert(0, root)
    import bench

    assert bench.peak_flops is flops_mod.peak_flops
    assert flops_mod.peak_flops("TPU v4") == 275e12
    assert flops_mod.peak_flops("TPU v5p") == 459e12
    assert flops_mod.peak_flops("weird accelerator") == \
        flops_mod.DEFAULT_PEAK_FLOPS  # fallback, never 0
    assert flops_mod.mfu(1000.0, 1e9, peak=4e12) == pytest.approx(0.25)
    assert flops_mod.mfu(1000.0, 1e9, "TPU v4") == \
        pytest.approx(1e12 / 275e12)
    assert flops_mod.decode_flops_per_token(n_params) == 2 * n_params


def test_xla_cost_analysis_crosscheck():
    """The analytical matmul count agrees with XLA's own cost analysis
    (the cross-check that keeps the 6N model honest)."""
    import jax
    import jax.numpy as jnp

    m, k, n = 32, 64, 16
    f = jax.jit(lambda a, b: a @ b)
    xla = flops_mod.xla_cost_analysis_flops(
        f, jnp.ones((m, k)), jnp.ones((k, n)))
    if xla is None:
        pytest.skip("backend exposes no cost_analysis")
    assert xla == pytest.approx(2 * m * k * n, rel=0.01)
    # diagnostic contract: bad input degrades to None, never raises
    assert flops_mod.xla_cost_analysis_flops(lambda x: x, 1) is None


# ---------------------------------------------------------------------------
# step profiler (pure-ish; local events + metrics only)
# ---------------------------------------------------------------------------

def test_step_profiler_phases_sum_exactly_to_wall():
    prof = StepProfiler(flops_per_token=1e6, tokens_per_step=100,
                        peak=1e9, hbm_every=1)
    for _ in range(3):
        with prof.step():
            with prof.phase("ingest"):
                time.sleep(0.001)
            with prof.phase("compute"):
                time.sleep(0.005)
    assert prof.summary()["steps"] == 3
    for rec in prof.steps:
        # the exact-sum invariant, per step: explicit phases + the
        # "other" residual == measured wall, to the float
        assert sum(rec["phases"].values()) == rec["wall_s"]
        assert rec["phases"]["ingest"] >= 0.001
        assert rec["phases"]["other"] >= 0.0
        assert rec["mfu"] is not None and rec["mfu"] > 0
    s = prof.summary()
    assert sum(p["s"] for p in s["phases"].values()) == \
        pytest.approx(s["wall_s"], abs=1e-7)
    assert s["mfu"]["mean"] > 0 and s["mfu"]["last"] > 0
    # CPU fallback HBM sample still lands (kind=host_rss, real bytes)
    assert s["hbm"] is not None and s["hbm"]["bytes_in_use"] > 0
    # a phase scope outside any step attributes nowhere (and must not
    # corrupt the next step)
    with prof.phase("ingest"):
        pass
    assert prof.summary()["steps"] == 3


def test_step_profiler_emits_perf_events_and_gauges():
    before = events_mod.buffer().last_seq()
    prof = StepProfiler(flops_per_token=1e6, tokens_per_step=10, peak=1e9)
    with prof.step():
        time.sleep(0.001)
    rows = [r for r in events_mod.local_events()
            if r["source"] == "perf" and r["seq"] > before]
    steps = [r for r in rows if r["message"] == "step phases"]
    assert len(steps) == 1
    d = steps[0]["data"]
    assert d["phases"]["other"] > 0 and d["mfu"] > 0
    assert steps[0]["span_dur"] == pytest.approx(d["wall_s"], abs=1e-6)
    # the MFU gauge is live in the registry (what the head TSDB ingests
    # and the mfu_regression trend rule reads)
    from ray_tpu.util.metrics import registry

    snap = registry().snapshot()
    assert any(v > 0 for v in
               snap["ray_tpu_train_step_mfu"]["values"].values())
    assert "ray_tpu_hbm_bytes_in_use" in snap


def test_wrap_jit_compile_cache_accounting():
    """Hit/miss counters across a forced reshape recompile: same shape =
    hit, new shape = miss with its own signature + compile wall."""
    import jax
    import jax.numpy as jnp

    prof = StepProfiler(hbm_every=0)
    f = prof.wrap_jit(jax.jit(lambda x: x * 2), name="reshape_probe")
    f(jnp.ones((4,)))      # miss (compile)
    f(jnp.ones((4,)))      # hit
    f(jnp.ones((4,)))      # hit
    f(jnp.ones((8,)))      # miss — the forced reshape recompile
    table = {e["fn"]: e for e in prof.summary()["compiles"]}
    e = table["reshape_probe"]
    assert e["misses"] == 2 and e["hits"] == 2
    assert e["n_sigs"] == 2 and len(set(e["signatures"])) == 2
    assert e["compile_s"] > 0
    # the compile events carry the cumulative signature count the
    # recompile-storm doctor rule thresholds on
    compiles = [r for r in events_mod.local_events()
                if r["source"] == "perf" and r["message"] == "jit compile"
                and (r.get("data") or {}).get("fn") == "reshape_probe"]
    assert [c["data"]["n_sigs"] for c in compiles] == [1, 2]
    # a plain callable (no _cache_size) degrades to all-compute
    g = prof.wrap_jit(lambda x: x, name="plain")
    g(1)
    assert {e["fn"]: e for e in prof.summary()["compiles"]}[
        "plain"]["misses"] == 0


def test_collective_phase_bills_into_open_step(monkeypatch):
    """jax_utils.allreduce_grads bills its wall to the active profiler's
    ``collective`` phase — gang sync shows up in the breakdown without
    the train fn instrumenting anything."""
    import numpy as np

    from ray_tpu.train import jax_utils
    from ray_tpu.util import collective

    def fake_allreduce(arr, group_name=None, op="mean"):
        time.sleep(0.003)
        return np.asarray(arr)

    monkeypatch.setattr(collective, "allreduce", fake_allreduce)
    prof = StepProfiler(hbm_every=0).install()
    try:
        with prof.step():
            out = jax_utils.allreduce_grads({"w": np.ones((4,))})
        assert list(out) == ["w"]
        rec = list(prof.steps)[-1]
        assert rec["phases"]["collective"] >= 0.003
        assert sum(rec["phases"].values()) == rec["wall_s"]
    finally:
        prof.uninstall()


def test_profiling_double_start_guard_and_profile_step(tmp_path):
    """profile_trace degrades to a no-op when a trace is already live
    (instead of raising out of XLA), and profile_step arms a one-step
    trace on the active profiler."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.util import profiling

    outer = tmp_path / "outer"
    with profiling.profile_trace(str(outer)):
        # nested start must not raise — the PR-11 guard
        with profiling.profile_trace(str(tmp_path / "inner")):
            jnp.ones(3).block_until_ready()
    assert outer.exists() and any(outer.rglob("*"))
    # no active profiler: arming reports False
    assert profiling.profile_step(str(tmp_path / "none")) is False
    prof = StepProfiler(hbm_every=0).install()
    try:
        stepdir = tmp_path / "one-step"
        assert profiling.profile_step(str(stepdir)) is True
        with prof.step():
            jax.jit(lambda x: x + 1)(jnp.ones(3)).block_until_ready()
        assert stepdir.exists() and any(stepdir.rglob("*"))
        # one-shot: the NEXT step runs untraced
        before = set(stepdir.rglob("*"))
        with prof.step():
            pass
        assert set(stepdir.rglob("*")) == before
    finally:
        prof.uninstall()


# ---------------------------------------------------------------------------
# decode attribution (engine, no cluster)
# ---------------------------------------------------------------------------

def _tiny_engine(**kw):
    from ray_tpu.serve.llm import GenerationEngine, make_config

    kw.setdefault("n_slots", 4)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("decode_chunk_steps", 2)
    kw.setdefault("max_new_tokens", 128)
    return GenerationEngine(make_config("gpt2", "tiny"), **kw).start()


def test_ttft_itl_histograms_populated_by_engine_loop():
    eng = _tiny_engine()
    try:
        futs = [eng.submit([1, 2, 3], 12) for _ in range(4)]
        for f in futs:
            assert len(f.result(timeout=120)) == 12
    finally:
        eng.stop()
    ps = eng.perf_stats()
    assert ps["ttft"]["count"] >= 4
    assert ps["ttft"]["p99_s"] >= ps["ttft"]["p50_s"] > 0
    assert ps["itl"]["count"] > 0 and ps["itl"]["p50_s"] > 0
    # the registry histograms feed the TSDB on the same numbers
    from ray_tpu.util.metrics import registry

    snap = registry().snapshot()
    ttft_hist = list(snap["ray_tpu_llm_ttft_s"]["values"].values())[0]
    assert ttft_hist["count"] >= 4


def test_prefill_interference_meter_fires_only_under_interleave():
    # sequential load: a lone request's admission never co-schedules
    # with another slot's decode — the meter must stay at zero
    eng = _tiny_engine()
    try:
        eng.generate([1, 2, 3], 8)
        eng.generate([4, 5], 8)
    finally:
        eng.stop()
    ps = eng.perf_stats()
    assert ps["ticks"]["interleaved"] == 0
    assert ps["interference_s"] == 0.0 and ps["interference_frac"] == 0.0

    # induced interleave: admissions landing while another request is
    # mid-decode bill admission dispatch (+ tick excess) to prefill
    eng = _tiny_engine()
    try:
        eng.generate([1, 2, 3], 4)  # compile outside the measurement
        f1 = eng.submit([1, 2, 3, 4], 128)
        time.sleep(0.03)
        f2 = eng.submit([5, 6, 7], 64)
        time.sleep(0.03)
        f3 = eng.submit([8, 9], 64)
        for f in (f1, f2, f3):
            f.result(timeout=300)
    finally:
        eng.stop()
    ps = eng.perf_stats()
    assert ps["ticks"]["interleaved"] >= 1
    assert ps["interference_s"] > 0
    assert 0 < ps["excess_billed_to_prefill"] <= 1.0
    # stop() flushed the meter as a perf event for the doctor/CLI
    rows = [r for r in events_mod.local_events()
            if r["source"] == "perf"
            and r["message"] == "prefill interference"]
    assert rows and rows[-1]["data"]["interleaved_ticks"] >= 1


# ---------------------------------------------------------------------------
# doctor rules (pure)
# ---------------------------------------------------------------------------

def _storm_events(n_sigs):
    return [{"source": "perf", "message": "jit compile",
             "severity": "DEBUG", "span_dur": 0.2,
             "data": {"fn": "train_step", "signature": f"s{i}",
                      "n_sigs": i + 1, "misses": i + 1, "hits": 3}}
            for i in range(n_sigs)]


def _step_events(n, ingest_frac):
    return [{"source": "perf", "message": "step phases",
             "severity": "DEBUG", "span_dur": 1.0, "entity_id": "rank0",
             "data": {"wall_s": 1.0, "mfu": 0.4,
                      "phases": {"ingest": ingest_frac,
                                 "compute": 1.0 - ingest_frac}}}
            ] * n


def _interference_event(frac, ticks):
    return {"source": "perf", "message": "prefill interference",
            "severity": "DEBUG", "entity_id": "engine-1", "ts": 10.0,
            "data": {"interference_s": frac * 100.0,
                     "interference_frac": frac,
                     "excess_billed_to_prefill": 0.9,
                     "interleaved_ticks": ticks,
                     "decode_only_ticks": 500}}


def test_perf_doctor_rules_fire_on_induced_pathologies():
    from ray_tpu.util import doctor

    # recompile storm: >= RECOMPILE_STORM_SIGS signatures for one fn
    f = doctor.diagnose(_storm_events(doctor.RECOMPILE_STORM_SIGS))
    assert [x["rule"] for x in f] == ["recompile_storm"]
    assert "train_step" in f[0]["summary"] and f[0]["remedy"]

    # ingest-bound: >= 30% of step wall waiting on data
    f = doctor.diagnose(_step_events(8, 0.5))
    assert [x["rule"] for x in f] == ["ingest_bound"]
    assert "50%" in f[0]["summary"]

    # prefill interference above threshold with enough interleaved ticks
    f = doctor.diagnose([_interference_event(0.45, 60)])
    assert [x["rule"] for x in f] == ["prefill_interference"]
    assert doctor.render(f)  # renders without KeyError

    # combined: all three at once, sorted by severity bucket
    f = doctor.diagnose(_storm_events(9) + _step_events(8, 0.6)
                        + [_interference_event(0.45, 60)])
    assert {x["rule"] for x in f} == {
        "recompile_storm", "ingest_bound", "prefill_interference"}


def test_perf_doctor_rules_stay_silent_on_healthy_runs():
    from ray_tpu.util import doctor

    healthy = (
        # multi-bucket prefill: 4 signatures is the DESIGN, not a storm
        _storm_events(doctor.RECOMPILE_STORM_SIGS - 1)
        # healthy step mix: 10% ingest wait
        + _step_events(20, 0.1)
        # mild interference, and high interference w/o enough ticks
        + [_interference_event(0.05, 500),
           _interference_event(0.9, doctor.PREFILL_MIN_TICKS - 1)])
    assert doctor.diagnose(healthy) == []
    # too few profiled steps: no verdict even at a high ingest share
    assert doctor.diagnose(
        _step_events(doctor.INGEST_MIN_STEPS - 1, 0.9)) == []


def test_mfu_regression_trend_rule():
    from ray_tpu.util import doctor

    def series(vals):
        return {"ray_tpu_train_step_mfu": [
            {"tags": {"rank": "0"}, "points": [[float(i), v]
                                               for i, v in enumerate(vals)]}]}

    # sustained 25% sag over the trailing quarter: fires
    sag = [0.40] * 12 + [0.30] * 4
    f = doctor.diagnose_trends(series(sag))
    assert [x["rule"] for x in f] == ["mfu_regression"]
    assert "regressed" in f[0]["summary"]
    # flat, noisy-flat, short, and CPU-noise-level series stay silent
    assert doctor.diagnose_trends(series([0.40] * 16)) == []
    assert doctor.diagnose_trends(
        series([0.40, 0.41, 0.39, 0.40] * 4)) == []
    assert doctor.diagnose_trends(series([0.4] * 6 + [0.2] * 2)) == []
    assert doctor.diagnose_trends(
        series([0.001] * 12 + [0.0001] * 4)) == []
    assert "ray_tpu_train_step_mfu" in doctor.TREND_METRICS


# ---------------------------------------------------------------------------
# cluster end-to-end.  Order matters (tier-1 runs -p no:randomly): the
# healthy-run doctor gate reads the head's whole perf event table, so it
# runs BEFORE the recompile-storm test pollutes the driver ring.
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def perf_cluster():
    env = {"RAY_TPU_METRICS_PUSH_S": "0.25", "RAY_TPU_EVENTS_FLUSH_S": "0.3"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _drive_profiler_steps(n=8):
    prof = StepProfiler(flops_per_token=1e6, tokens_per_step=1000,
                        peak=1e9, rank=0)
    for _ in range(n):
        with prof.step():
            with prof.phase("ingest"):
                time.sleep(0.0005)
            with prof.phase("compute"):
                time.sleep(0.005)
    return prof


def test_perf_summary_state_api_cli_and_dashboard(perf_cluster):
    import urllib.request

    from ray_tpu.experimental.state import api as state

    prof = _drive_profiler_steps()
    # the head samples its own registry into the TSDB on the push grid
    assert _wait_for(lambda: any(
        s.get("points")
        for s in state.query_metric("ray_tpu_train_step_mfu",
                                    window_s=600).get("series", [])))
    s = state.perf_summary(window_s=600.0)
    st = s["steps"]
    assert st["count"] >= 8
    assert st["phases"]["ingest"]["s"] > 0
    # the aggregate keeps the exact-sum property (head folds the same
    # per-step dicts the profiler emitted)
    assert sum(p["s"] for p in st["phases"].values()) == \
        pytest.approx(st["wall_s"], abs=1e-4)
    # origin-qualified keys: two gangs' rank0s must not collide
    assert any(k.endswith(":rank0") and v > 0
               for k, v in st["last_mfu"].items()), st["last_mfu"]
    assert s["mfu_trend"] and any(x.get("points") for x in s["mfu_trend"])
    assert any(row.get("bytes_in_use") for row in s["hbm"])

    # CLI renders the breakdown with the sum line
    from ray_tpu.scripts.cli import main as cli_main

    buf = io.StringIO()
    with redirect_stdout(buf):
        cli_main(["perf", "--window", "600"])
    text = buf.getvalue()
    assert "PHASE" in text and "ingest" in text
    assert "phases sum to measured step wall" in text
    assert "live MFU" in text

    # `ray_tpu top` shows the HBM watermark rows
    snap = state.top_snapshot()
    assert any(r.get("bytes_in_use") for r in snap.get("hbm", []))
    from ray_tpu.scripts.cli import _render_top

    assert "DEVICE MEMORY" in _render_top(snap, "cpu")

    # dashboard surface
    from ray_tpu._private.worker import global_worker

    dash = global_worker.node.dashboard
    if dash is None:
        pytest.skip("dashboard disabled in this environment")
    host, port = dash.address
    with urllib.request.urlopen(
            f"http://{host}:{port}/api/perf?window=600", timeout=30) as r:
        payload = json.loads(r.read().decode())
    assert payload["steps"]["count"] >= 8
    assert payload["steps"]["phases"]["ingest"]["s"] > 0
    del prof


def test_healthy_profiled_run_keeps_doctor_clean(perf_cluster):
    """The healthy-run-clean gate, extended to the perf rules: a normal
    profiled workload (one compile, low ingest share, no interference)
    produces ZERO findings from the four new rules."""
    import warnings

    import jax
    import jax.numpy as jnp

    from ray_tpu.experimental.state import api as state
    from ray_tpu.util import doctor

    prof = StepProfiler(flops_per_token=1e6, tokens_per_step=1000,
                        peak=1e9, rank=1)
    f = prof.wrap_jit(jax.jit(lambda x: x * 2), name="healthy_step")
    z = jnp.ones((8,))
    for _ in range(10):
        with prof.step():
            with prof.phase("ingest"):
                time.sleep(0.0002)
            f(z)
            with prof.phase("compute"):
                time.sleep(0.002)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        events = state.list_events(limit=100_000, source="perf")
    assert events, "profiled steps must reach the head's event table"
    findings = doctor.diagnose(events)
    perf_rules = {"recompile_storm", "ingest_bound", "prefill_interference"}
    assert not [x for x in findings if x["rule"] in perf_rules], findings


def test_recompile_storm_flags_through_real_event_pipeline(perf_cluster):
    """A forced-reshape loop drives the REAL compile-tracking pipeline
    past the storm threshold and doctor flags it off the head's event
    table.  Runs LAST in this module: the storm events stay in the
    driver ring afterwards (the healthy gate above already ran)."""
    import warnings

    import jax
    import jax.numpy as jnp

    from ray_tpu.experimental.state import api as state
    from ray_tpu.util import doctor

    prof = StepProfiler(hbm_every=0)
    f = prof.wrap_jit(jax.jit(lambda x: x + 1), name="storm_step")
    for i in range(doctor.RECOMPILE_STORM_SIGS + 1):
        with prof.step():
            f(jnp.ones((i + 1,)))  # every call a fresh shape signature
    table = {e["fn"]: e for e in prof.summary()["compiles"]}
    assert table["storm_step"]["n_sigs"] >= doctor.RECOMPILE_STORM_SIGS

    def storm_visible():
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            events = state.list_events(limit=100_000, source="perf")
        return any(x["rule"] == "recompile_storm"
                   for x in doctor.diagnose(events))

    assert _wait_for(storm_visible)


def test_backend_executor_collects_perf_summaries(perf_cluster):
    """A gang worker's installed profiler is harvestable through
    BackendExecutor.perf_summaries() after the run."""
    from ray_tpu.air import ScalingConfig
    from ray_tpu.train.backend import BackendConfig
    from ray_tpu.train.backend_executor import BackendExecutor

    def train_fn(config=None):
        import time as _t

        from ray_tpu.air import session
        from ray_tpu.train import jax_utils

        prof = jax_utils.step_profiler(
            flops_per_token=1e6, tokens_per_step=100, peak=1e9)
        for _ in range(4):
            with prof.step():
                _t.sleep(0.001)
        session.report({"done": True})

    be = BackendExecutor(
        BackendConfig(),
        ScalingConfig(num_workers=1, resources_per_worker={"CPU": 1}))
    be.start()
    try:
        be.start_training(train_fn)
        while be.get_next_results(timeout=60) is not None:
            pass
        summaries = be.perf_summaries()
        assert len(summaries) == 1 and summaries[0] is not None
        assert summaries[0]["steps"] == 4
        assert sum(p["s"] for p in summaries[0]["phases"].values()) == \
            pytest.approx(summaries[0]["wall_s"], abs=1e-6)
    finally:
        be.shutdown()
    # the gang aggregate landed as a perf event
    rows = [r for r in events_mod.local_events()
            if r["source"] == "perf"
            and r["message"] == "gang perf summary"]
    assert rows and rows[-1]["data"]["profiled_ranks"] == 1


def test_hbm_sample_shapes():
    """memory_stats-less devices fall back to host RSS; a fake device
    with stats reports HBM."""
    s = sample_device_memory()
    assert s is not None and s["bytes_in_use"] > 0
    assert s["kind"] in ("hbm", "host_rss")

    class FakeDev:
        id = 3

        @staticmethod
        def memory_stats():
            return {"bytes_in_use": 100, "bytes_limit": 1000,
                    "peak_bytes_in_use": 500}

    s = sample_device_memory(FakeDev())
    assert s == {"device": "3", "kind": "hbm", "bytes_in_use": 100,
                 "bytes_limit": 1000, "peak_bytes_in_use": 500}
