"""Object lifecycle: refcounting, eviction-by-GC, spilling, orphan sweep.

The VERDICT's acceptance bar: a loop putting throwaway arrays holds
steady-state shm, and a killed head leaves nothing behind after the next
init's sweep.  Mirrors the reference's reference_count.h / plasma eviction
/ local_object_manager spill test intents.
"""

import gc
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import ray_tpu


def _session_shm_segments():
    from ray_tpu._private.config import get_config
    from ray_tpu._private.shm import current_session_id

    prefix = f"{get_config().shm_prefix}-{current_session_id()}-"
    # the arena file is the session's (bounded, self-reclaiming) store
    # itself, not a leaked per-object segment
    return [n for n in os.listdir("/dev/shm")
            if n.startswith(prefix)
            and not n.endswith(("-alive", "-arena"))]


def _stats():
    snap = ray_tpu.global_worker.client.state_snapshot()
    return snap["object_store"]


def test_put_loop_holds_steady_state_shm(ray_start_regular):
    """Throwaway puts must be reclaimed — shm segment count stays bounded."""
    big = np.ones(512 * 1024, np.uint8)  # 512KiB -> shm path
    for i in range(40):
        ref = ray_tpu.put(big + (i % 3))
        assert int(ray_tpu.get(ref, timeout=30).sum()) >= big.size
        del ref
        if i % 10 == 9:
            gc.collect()
            ray_tpu.global_worker.flush_removals()
    gc.collect()
    ray_tpu.global_worker.flush_removals()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if len(_session_shm_segments()) <= 4:
            break
        time.sleep(0.2)
    assert len(_session_shm_segments()) <= 4, _session_shm_segments()


def test_task_return_reclaimed_after_ref_drop(ray_start_regular):
    @ray_tpu.remote
    def make():
        return np.zeros(1024 * 1024, np.uint8)  # 1MiB -> shm

    refs = [make.remote() for _ in range(6)]
    vals = ray_tpu.get(refs, timeout=120)
    assert all(v.size == 1024 * 1024 for v in vals)
    before = _stats()["num_objects"]
    del refs, vals
    gc.collect()
    ray_tpu.global_worker.flush_removals()
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        if _stats()["num_objects"] < before:
            break
        time.sleep(0.2)
    assert _stats()["num_objects"] < before


def test_contained_refs_cascade(ray_start_regular):
    """Deleting an outer object releases the inner objects it referenced."""
    inner = ray_tpu.put(np.ones(512 * 1024, np.uint8))
    outer = ray_tpu.put({"payload": inner})
    inner_oid = inner.binary()
    # dropping our inner handle leaves the contained pin from outer
    del inner
    gc.collect()
    ray_tpu.global_worker.flush_removals()
    time.sleep(0.3)
    # inner must still be gettable through outer
    got = ray_tpu.get(ray_tpu.get(outer, timeout=30)["payload"], timeout=30)
    assert got.size == 512 * 1024
    # now drop everything -> cascade deletes inner too
    del got, outer
    gc.collect()
    ray_tpu.global_worker.flush_removals()
    deadline = time.monotonic() + 10
    from ray_tpu._private.shm import session_shm_name

    name = session_shm_name(inner_oid.hex())
    while time.monotonic() < deadline:
        if not os.path.exists(os.path.join("/dev/shm", name)):
            break
        time.sleep(0.2)
    assert not os.path.exists(os.path.join("/dev/shm", name))


def test_fire_and_forget_reclaims(ray_start_regular):
    """Dropping a return ref before the task finishes reclaims at seal."""
    @ray_tpu.remote
    def slow():
        import time as t

        t.sleep(0.5)
        return np.zeros(512 * 1024, np.uint8)

    slow.remote()  # ref discarded immediately
    gc.collect()
    ray_tpu.global_worker.flush_removals()
    time.sleep(2.0)
    deadline = time.monotonic() + 8
    while time.monotonic() < deadline:
        if len(_session_shm_segments()) == 0:
            break
        gc.collect()
        ray_tpu.global_worker.flush_removals()
        time.sleep(0.3)
    assert len(_session_shm_segments()) == 0, _session_shm_segments()


def test_spilling_under_capacity_pressure():
    """Objects past object_store_memory spill to disk and stay gettable."""
    from ray_tpu._private.object_store import ObjectRegistry, store_value
    from ray_tpu._private.object_store import read_value
    from ray_tpu._private.object_ref import ObjectRef
    import ray_tpu._private.object_store as os_mod

    import tempfile

    spill_dir = tempfile.mkdtemp(prefix="rtpu_spill_test")
    reg = ObjectRegistry(capacity_bytes=3 * 1024 * 1024, spill_dir=spill_dir)
    old_idle = os_mod._SPILL_MIN_IDLE_S
    os_mod._SPILL_MIN_IDLE_S = 0.0
    try:
        locs = {}
        for i in range(6):
            ref = ObjectRef.random()
            loc, _ = store_value(ref, np.full(1024 * 1024, i, np.uint8))
            reg.seal(ref.binary(), loc)
            locs[ref.binary()] = (i, loc)
        stats = reg.stats()
        assert stats["num_spilled"] >= 3, stats
        assert stats["bytes_used"] <= 3 * 1024 * 1024 + 1024 * 1024
        # every object still readable through its (possibly updated) location
        for oid, (i, _) in locs.items():
            val = read_value(reg.get_location(oid))
            assert int(val[0]) == i
    finally:
        os_mod._SPILL_MIN_IDLE_S = old_idle
        reg.shutdown()


def test_orphan_sweep_after_killed_head():
    """kill -9 the head -> next init sweeps its shm segments."""
    code = r"""
import os, signal
import numpy as np
import ray_tpu
ray_tpu.init(num_cpus=2)
refs = [ray_tpu.put(np.ones(512 * 1024, np.uint8)) for _ in range(4)]
print("SESSION", os.environ["RAY_TPU_SESSION"], flush=True)
os.kill(os.getpid(), signal.SIGKILL)
"""
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=120)
    sid = None
    for line in proc.stdout.splitlines():
        if line.startswith("SESSION "):
            sid = line.split()[1]
    assert sid, proc.stderr[-1000:]
    from ray_tpu._private.config import get_config

    prefix = f"{get_config().shm_prefix}-{sid}-"
    orphans = [n for n in os.listdir("/dev/shm") if n.startswith(prefix)]
    assert orphans, "expected orphaned segments from the killed head"

    ray_tpu.init(num_cpus=2)
    try:
        left = [n for n in os.listdir("/dev/shm") if n.startswith(prefix)]
        assert left == [], left
    finally:
        ray_tpu.shutdown()
