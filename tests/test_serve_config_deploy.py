"""Declarative serve config deploy: schema validation, REST PUT through
the dashboard, controller reconciliation (deploy/update/delete), and
goal-vs-actual readback (serve/schema.py + dashboard serve REST analog)."""

import gc
import json
import os
import sys
import textwrap
import urllib.request

import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.schema import SchemaError, parse_deploy_config


def test_schema_validation_errors():
    with pytest.raises(SchemaError, match="applications"):
        parse_deploy_config({})
    with pytest.raises(SchemaError, match="import_path"):
        parse_deploy_config({"applications": [{"name": "a"}]})
    with pytest.raises(SchemaError, match="route_prefix"):
        parse_deploy_config({"applications": [
            {"import_path": "m:x", "route_prefix": "noslash"}]})
    with pytest.raises(SchemaError, match="num_replicas"):
        parse_deploy_config({"applications": [
            {"import_path": "m:x",
             "deployments": [{"name": "d", "num_replicas": -1}]}]})
    with pytest.raises(SchemaError, match="unknown fields"):
        parse_deploy_config({"applications": [
            {"import_path": "m:x", "bogus": 1}]})
    ok = parse_deploy_config({"applications": [
        {"import_path": "m:x", "name": "app",
         "deployments": [{"name": "d", "num_replicas": 2}]}]})
    assert ok.applications[0].deployments[0].num_replicas == 2


APP_MODULE = """
from ray_tpu import serve

@serve.deployment
class ConfigApp:
    def __init__(self, greeting="hello"):
        self.greeting = greeting
        self.threshold = 0.0

    def reconfigure(self, cfg):
        self.threshold = cfg.get("threshold", 0.0)

    def __call__(self, request=None):
        return {"greeting": self.greeting, "threshold": self.threshold}

app = ConfigApp.bind()
"""


@pytest.fixture
def config_app_module(tmp_path):
    (tmp_path / "serve_cfg_testmod.py").write_text(textwrap.dedent(APP_MODULE))
    old_pp = os.environ.get("PYTHONPATH", "")
    os.environ["PYTHONPATH"] = f"{tmp_path}{os.pathsep}{old_pp}"
    sys.path.insert(0, str(tmp_path))
    yield "serve_cfg_testmod"
    sys.path.remove(str(tmp_path))
    os.environ["PYTHONPATH"] = old_pp
    sys.modules.pop("serve_cfg_testmod", None)


@pytest.fixture
def serve_cluster():
    ray_tpu.init(num_cpus=4)
    serve.start(serve.HTTPOptions(host="127.0.0.1", port=0))
    yield
    try:
        serve.shutdown()
    finally:
        ray_tpu.shutdown()


def _dashboard_port():
    from ray_tpu._private import node as node_mod

    heads = [o for o in gc.get_objects()
             if isinstance(o, node_mod.Node) and not o._shutdown]
    return heads[-1].dashboard.address[1]


def _put_config(port, config):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/api/serve/applications",
        data=json.dumps(config).encode(),
        headers={"Content-Type": "application/json"}, method="PUT")
    try:
        with urllib.request.urlopen(req, timeout=180) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_rest_config_deploy_update_delete(config_app_module, serve_cluster):
    port = _dashboard_port()
    # bad config -> 400 with the offending path
    code, out = _put_config(port, {"applications": [{"name": "x"}]})
    assert code == 400 and "import_path" in out["error"]

    # deploy from config
    config = {"applications": [{
        "name": "cfgapp",
        "import_path": f"{config_app_module}:app",
        "route_prefix": "/cfg",
        "deployments": [{"name": "ConfigApp", "num_replicas": 1,
                         "user_config": {"threshold": 0.25}}],
    }]}
    code, out = _put_config(port, config)
    assert code == 200, out
    assert out["deployed"] == ["ConfigApp"]

    # the app serves HTTP on the configured route with the user_config
    host, hport = serve.get_http_address()
    deadline_ok = None
    for _ in range(60):
        try:
            with urllib.request.urlopen(
                    f"http://{host}:{hport}/cfg", timeout=30) as r:
                deadline_ok = json.loads(r.read())
            break
        except Exception:
            import time

            time.sleep(0.5)
    assert deadline_ok == {"greeting": "hello", "threshold": 0.25}

    # goal config is readable back (goal vs actual)
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/api/serve/config", timeout=30) as r:
        goal = json.loads(r.read())
    assert goal["applications"][0]["name"] == "cfgapp"
    assert serve.status()["ConfigApp"]["num_replicas_goal"] == 1

    # config update: num_replicas 2 reconciles live
    config["applications"][0]["deployments"][0]["num_replicas"] = 2
    code, out = _put_config(port, config)
    assert code == 200, out
    import time

    for _ in range(120):
        if serve.status()["ConfigApp"]["num_replicas_goal"] == 2:
            break
        time.sleep(0.5)
    assert serve.status()["ConfigApp"]["num_replicas_goal"] == 2

    # an empty config deletes every config-owned deployment
    code, out = _put_config(port, {"applications": []})
    assert code == 200, out
    for _ in range(60):
        if "ConfigApp" not in serve.status():
            break
        time.sleep(0.5)
    assert "ConfigApp" not in serve.status()
