"""``num_returns="dynamic"`` generator tasks + ObjectRefGenerator streaming
(reference ``python/ray/_private/worker.py:2924``)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu import ObjectRefGenerator


def test_dynamic_returns_basic(ray_start_regular):
    @ray_tpu.remote(num_returns="dynamic")
    def gen(n):
        for i in range(n):
            yield i * i

    g = gen.remote(5)
    assert isinstance(g, ObjectRefGenerator)
    vals = [ray_tpu.get(r, timeout=60) for r in g]
    assert vals == [0, 1, 4, 9, 16]
    # the terminal return materializes the same refs
    materialized = ray_tpu.get(g.completed(), timeout=60)
    assert isinstance(materialized, ObjectRefGenerator)
    assert len(materialized) == 5
    assert [ray_tpu.get(r, timeout=60) for r in materialized] == vals


def test_dynamic_returns_stream_before_completion(ray_start_regular):
    """Refs arrive WHILE the producer is still running — the consumer gets
    the first block long before the last one exists."""

    @ray_tpu.remote(num_returns="dynamic")
    def slow_gen():
        for i in range(4):
            yield np.full((1000,), i)
            time.sleep(1.0)

    g = slow_gen.remote()
    t0 = time.time()
    it = iter(g)
    first = ray_tpu.get(next(it), timeout=120)
    first_latency = time.time() - t0
    assert first[0] == 0
    # producer sleeps 1s per item (4s total); the first item must arrive
    # well before the stream ends
    assert first_latency < 3.0, f"first item took {first_latency:.1f}s"
    rest = [int(ray_tpu.get(r, timeout=120)[0]) for r in it]
    assert rest == [1, 2, 3]


def test_dynamic_returns_error_mid_stream(ray_start_regular):
    @ray_tpu.remote(num_returns="dynamic", max_retries=0)
    def bad_gen():
        yield "ok"
        raise RuntimeError("boom")

    g = bad_gen.remote()
    it = iter(g)
    assert ray_tpu.get(next(it), timeout=60) == "ok"
    with pytest.raises(Exception, match="boom"):
        for r in it:  # stream ends by surfacing the task's error
            ray_tpu.get(r, timeout=60)


def test_dynamic_returns_validation(ray_start_regular):
    with pytest.raises(ValueError):
        @ray_tpu.remote(num_returns="dynamic")
        class NotAllowed:  # actors can't be dynamic
            pass

    with pytest.raises(ValueError):
        ray_tpu.remote(num_returns="nope")(lambda: None)


def test_streamed_iter_batches_never_materializes(ray_start_regular):
    """Data wiring: iter_batches over a dynamic producer starts yielding
    batches while later blocks don't exist yet."""
    from ray_tpu import data as rd

    @ray_tpu.remote(num_returns="dynamic")
    def produce_blocks():
        for i in range(4):
            yield {"value": np.full((500,), i, dtype=np.int64)}
            time.sleep(1.0)

    ds = rd.from_block_generator(produce_blocks.remote())
    t0 = time.time()
    batches = []
    first_latency = None
    for batch in ds.iter_batches(batch_size=500, batch_format="numpy"):
        if first_latency is None:
            first_latency = time.time() - t0
        batches.append(int(np.asarray(batch)[0]))
    assert batches == [0, 1, 2, 3]
    assert first_latency < 3.0, f"first batch took {first_latency:.1f}s"
