"""Real-env RL validation: LunarLander-v3 (the hardest gymnasium env
installed — Box2D dynamics, shaped rewards, 8-dim obs, 4 actions).

Two tiers, per the suite's wall-clock budget:

- tier-1 smoke: a FIXED-SEED short PPO run must show a positive reward
  slope (learning signal), not convergence — minutes of Box2D stepping
  stay out of the 870s cap.
- ``slow``: the real bar — PPO reaches >= 200 mean reward (the env's
  "solved" threshold) and writes the learning-curve artifact
  (RL_LUNARLANDER_CURVE.json) that backs the published numbers; DQN
  shows substantial learning on the same env.  Run with ``-m slow`` or
  ``RAY_TPU_RUN_SLOW=1``.
"""

import json
import os

import numpy as np
import pytest

from ray_tpu.rllib import DQNConfig, PPOConfig

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ppo_lunarlander_config(seed: int = 0) -> PPOConfig:
    """The classic LunarLander PPO recipe (high gamma for the long
    shaped-reward horizon, lambda 0.98, entropy for early exploration)."""
    return (
        PPOConfig()
        .environment("LunarLander-v3")
        .rollouts(rollout_fragment_length=512, num_envs_per_worker=4)
        .training(train_batch_size=2048, sgd_minibatch_size=128,
                  num_sgd_iter=8, lr=3e-4, entropy_coeff=0.01,
                  gamma=0.999, lambda_=0.98)
        .debugging(seed=seed)
    )


def test_ppo_lunarlander_reward_slope_smoke():
    """Fixed-seed learning-SIGNAL check: mean reward over the last third
    of a short run beats the first third by a clear margin.  Asserting
    slope (not convergence) keeps this inside tier-1's budget while still
    catching a broken sample path, loss, or connector stack end-to-end on
    a real Box2D env."""
    algo = _ppo_lunarlander_config(seed=0).build()
    rewards = []
    try:
        for _ in range(12):
            r = algo.train()
            m = r["episode_reward_mean"]
            if np.isfinite(m):
                rewards.append(float(m))
    finally:
        algo.cleanup()
    assert len(rewards) >= 9, f"too few reward readings: {rewards}"
    first = float(np.mean(rewards[:3]))
    last = float(np.mean(rewards[-3:]))
    assert last > first + 10.0, (
        f"no learning signal on LunarLander: first3={first:.1f} "
        f"last3={last:.1f} (curve: {[round(x, 1) for x in rewards]})")


@pytest.mark.slow
def test_ppo_lunarlander_learns_to_200_with_curve_artifact():
    """The acceptance bar: PPO solves LunarLander-v3 (>= 200 mean reward
    over the trailing episode window) and the test writes the
    learning-curve artifact the published numbers point at."""
    algo = _ppo_lunarlander_config(seed=0).build()
    curve = []
    best = -float("inf")
    try:
        for i in range(400):
            r = algo.train()
            m = float(r["episode_reward_mean"])
            curve.append({"iter": i, "timesteps": int(r["timesteps_total"]),
                          "reward_mean": round(m, 2)})
            if np.isfinite(m):
                best = max(best, m)
            if m >= 200.0:
                break
    finally:
        algo.cleanup()
        path = os.environ.get(
            "RAY_TPU_RL_CURVE_PATH",
            os.path.join(_REPO_ROOT, "RL_LUNARLANDER_CURVE.json"))
        with open(path, "w") as f:
            json.dump({
                "env": "LunarLander-v3", "algo": "PPO", "seed": 0,
                "config": {"train_batch_size": 2048, "lr": 3e-4,
                           "gamma": 0.999, "lambda": 0.98,
                           "num_sgd_iter": 8, "entropy_coeff": 0.01},
                "best_reward_mean": round(best, 2),
                "curve": curve,
            }, f, indent=1)
    assert best >= 200.0, f"PPO failed to solve LunarLander: best={best:.1f}"


@pytest.mark.slow
def test_dqn_lunarlander_learns():
    """DQN (replay + target net + global epsilon anneal) shows
    substantial learning on LunarLander: from the random-policy floor
    (~ -200) past the 'controlled descent' band.  Full convergence to 200
    takes ~5x longer than PPO — the bar here is unambiguous learning,
    with the curve recorded alongside PPO's."""
    algo = (
        DQNConfig()
        .environment("LunarLander-v3")
        .rollouts(rollout_fragment_length=256, num_envs_per_worker=2)
        .training(lr=5e-4, train_batch_size=64,
                  timesteps_per_iteration=1024, updates_per_iteration=256,
                  learning_starts=2000, epsilon_timesteps=60_000,
                  target_network_update_freq=600,
                  replay_buffer_capacity=100_000,
                  fcnet_hiddens=(128, 128))
        .debugging(seed=0)
        .build()
    )
    curve = []
    best = -float("inf")
    try:
        for i in range(150):
            r = algo.train()
            m = float(r["episode_reward_mean"])
            curve.append({"iter": i, "timesteps": int(r["timesteps_total"]),
                          "reward_mean": round(m, 2)})
            if np.isfinite(m):
                best = max(best, m)
            if best >= 0.0 and i >= 40:
                break
    finally:
        algo.cleanup()
        path = os.environ.get(
            "RAY_TPU_RL_DQN_CURVE_PATH",
            os.path.join(_REPO_ROOT, "RL_LUNARLANDER_DQN_CURVE.json"))
        with open(path, "w") as f:
            json.dump({
                "env": "LunarLander-v3", "algo": "DQN", "seed": 0,
                "best_reward_mean": round(best, 2), "curve": curve,
            }, f, indent=1)
    assert best >= -40.0, (
        f"DQN failed to learn LunarLander: best={best:.1f} "
        f"(random-policy floor is ~ -200)")
