"""Dataset tests (transforms, shuffles, splits, io, pipeline)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


def test_range_and_aggregates(ray_start_regular):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.num_blocks() == 4
    assert ds.sum() == 4950
    assert ds.min() == 0 and ds.max() == 99
    assert ds.mean() == 49.5


def test_from_items_map_filter(ray_start_regular):
    ds = rd.from_items([{"x": i} for i in range(20)], parallelism=2)
    out = (
        ds.map(lambda r: {"x": r["x"] * 2})
          .filter(lambda r: r["x"] % 4 == 0)
          .take_all()
    )
    assert [r["x"] for r in out] == [0, 4, 8, 12, 16, 20, 24, 28, 32, 36]


def test_map_batches_numpy(ray_start_regular):
    ds = rd.range(16, parallelism=2)
    out = ds.map_batches(lambda batch: batch * 10, batch_size=4)
    np.testing.assert_array_equal(out.to_numpy(), np.arange(16) * 10)


def test_map_batches_actor_pool(ray_start_regular):
    class AddConst:
        def __init__(self, c):
            self.c = c

        def __call__(self, batch):
            return batch + self.c

    ds = rd.range(12, parallelism=3)
    out = ds.map_batches(
        AddConst, compute=rd.ActorPoolStrategy(size=2),
        fn_constructor_args=(100,),
    )
    np.testing.assert_array_equal(np.sort(out.to_numpy()), np.arange(12) + 100)


def test_flat_map_and_zip(ray_start_regular):
    ds = rd.from_items([1, 2, 3], parallelism=1)
    out = ds.flat_map(lambda x: [x, x]).take_all()
    assert out == [1, 1, 2, 2, 3, 3]


def test_split_and_union(ray_start_regular):
    ds = rd.range(12, parallelism=2)
    shards = ds.split(3)
    assert [s.count() for s in shards] == [4, 4, 4]
    joined = shards[0].union(shards[1], shards[2])
    assert joined.count() == 12


def test_shuffle_sort(ray_start_regular):
    ds = rd.from_items(list(range(50)), parallelism=2)
    shuffled = ds.random_shuffle(seed=0)
    assert shuffled.take_all() != list(range(50))
    assert sorted(shuffled.take_all()) == list(range(50))
    s = rd.from_items([{"k": v} for v in [3, 1, 2]], parallelism=1).sort(key="k")
    assert [r["k"] for r in s.take_all()] == [1, 2, 3]


def test_iter_batches(ray_start_regular):
    ds = rd.range(10, parallelism=3)
    batches = list(ds.iter_batches(batch_size=4))
    assert [len(b) for b in batches] == [4, 4, 2]
    np.testing.assert_array_equal(np.concatenate(batches), np.arange(10))
    dropped = list(ds.iter_batches(batch_size=4, drop_last=True))
    assert [len(b) for b in dropped] == [4, 4]


def test_csv_roundtrip(ray_start_regular, tmp_path):
    import pandas as pd

    df = pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    p = str(tmp_path / "t.csv")
    df.to_csv(p, index=False)
    ds = rd.read_csv(p)
    assert ds.count() == 3
    assert set(ds.schema()) == {"a", "b"}
    out = str(tmp_path / "out.csv")
    parts = ds.write_csv(out)  # directory of part files, one per block
    back = pd.concat([pd.read_csv(f) for f in sorted(parts)], ignore_index=True)
    pd.testing.assert_frame_equal(back, df)


def test_pipeline_window_repeat(ray_start_regular):
    ds = rd.range(8, parallelism=4)
    pipe = ds.window(blocks_per_window=2).map_batches(lambda b: b + 1)
    rows = [int(r) for r in pipe.iter_rows()]
    assert sorted(rows) == list(range(1, 9))
    reps = rd.range(4, parallelism=1).repeat(2)
    assert len(list(reps.iter_rows())) == 8


def test_dataset_feeds_trainer_shards(ray_start_regular):
    """Dataset.split -> session.get_dataset_shard wiring."""
    from ray_tpu.air import session
    from ray_tpu.train import JaxConfig, JaxTrainer
    from ray_tpu.air import ScalingConfig

    def loop(config):
        shard = session.get_dataset_shard("train")
        n = shard.count()
        session.report({"rows": n, "rank": session.get_world_rank()})

    ds = rd.range(8, parallelism=2)
    trainer = JaxTrainer(
        loop, jax_config=JaxConfig(),
        scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": ds},
        train_loop_config={},
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["rows"] == 4


def test_lazy_plan_and_fusion(ray_start_regular):
    """Transforms record stages; chains of per-block stages fuse into one
    task per block at execution (plan.py analog of _internal/plan.py:74)."""
    ds = rd.range(64, parallelism=4)
    out = ds.map(lambda x: {"x": x * 2}).filter(lambda r: r["x"] % 4 == 0).map(
        lambda r: {"x": r["x"] + 1}
    )
    # nothing executed yet
    assert out._plan._out is None
    assert len(out._plan.stages) == 3
    vals = sorted(r["x"] for r in out.take_all())
    assert vals == [x * 2 + 1 for x in range(64) if (x * 2) % 4 == 0]
    # the three one-to-one stages ran as ONE fused stage
    stats = out.stats()
    assert len(stats) == 1 and "map" in stats[0]["stage"] and "filter" in stats[0]["stage"]


def test_distributed_shuffle_no_driver_materialization(ray_start_regular):
    ds = rd.range(1000, parallelism=8)
    shuffled = ds.random_shuffle(seed=7)
    vals = sorted(shuffled.to_numpy().tolist())
    assert vals == list(range(1000))
    # actually shuffled
    first = rd.range(1000, parallelism=8).random_shuffle(seed=7).take(20)
    assert [r for r in first] != list(range(20))


def test_distributed_sort_by_key(ray_start_regular):
    import random as pyrandom

    rows = [{"k": pyrandom.Random(1).randint(0, 10_000), "i": i} for i in range(500)]
    pyrandom.Random(2).shuffle(rows)
    ds = rd.from_items(rows, parallelism=6).sort(key="k")
    out = [r["k"] for r in ds.take_all()]
    assert out == sorted(out)
    desc = rd.from_items(rows, parallelism=6).sort(key="k", descending=True)
    out_d = [r["k"] for r in desc.take_all()]
    assert out_d == sorted(out_d, reverse=True)


def test_repartition_counts(ray_start_regular):
    ds = rd.range(100, parallelism=3).repartition(5)
    assert ds.num_blocks() == 5
    assert ds.count() == 100
    assert sorted(ds.to_numpy().tolist()) == list(range(100))


def test_groupby(ray_start_regular):
    rows = [{"k": i % 3, "v": i} for i in range(30)]
    ds = rd.from_items(rows, parallelism=4)
    counts = {r["key"]: r["count"] for r in ds.groupby("k").count().take_all()}
    assert counts == {0: 10, 1: 10, 2: 10}
    sums = {r["key"]: r["sum"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums[0] == sum(i for i in range(30) if i % 3 == 0)
    means = {r["key"]: r["mean"] for r in ds.groupby("k").mean("v").take_all()}
    assert means[1] == sum(i for i in range(30) if i % 3 == 1) / 10


def test_custom_datasource(ray_start_regular):
    from ray_tpu.data import Datasource, ReadTask, read_datasource

    class SquaresSource(Datasource):
        def prepare_read(self, parallelism, **_):
            import numpy as np

            per = 10
            return [
                ReadTask(lambda lo=i * per: {"value": np.arange(lo, lo + per) ** 2},
                         num_rows=per)
                for i in range(parallelism)
            ]

    ds = read_datasource(SquaresSource(), parallelism=3)
    assert ds.count() == 30
    assert ds.max() == 29 ** 2


def test_iter_batches_prefetch(ray_start_regular):
    ds = rd.range(100, parallelism=5)
    batches = list(ds.iter_batches(batch_size=7, prefetch_blocks=3))
    flat = [v for b in batches for v in (b.tolist() if hasattr(b, "tolist") else b)]
    assert flat == list(range(100))


def test_stats_recorded(ray_start_regular):
    ds = rd.range(50, parallelism=2).map(lambda x: {"v": x}).random_shuffle(seed=0)
    ds.count()
    names = [s["stage"] for s in ds.stats()]
    assert any("map" in n for n in names) and any("shuffle" in n for n in names)


def test_zip_alignment_unequal_blocks(ray_start_regular):
    """zip pairs row i with row i even when block layouts differ."""
    a = rd.from_items([{"a": i} for i in range(10)], parallelism=2)
    b = rd.from_items([{"b": i * 10} for i in range(8)], parallelism=3)
    rows = a.zip(b).take_all()
    assert len(rows) == 8
    assert all(r["b"] == r["a"] * 10 for r in rows)


def test_empty_dataset_aggregates(ray_start_regular):
    ds = rd.from_items([])
    assert ds.sum() == 0
    with pytest.raises(ValueError, match="empty"):
        ds.min()
    with pytest.raises(ValueError, match="empty"):
        ds.mean()


def test_iter_batches_early_break(ray_start_regular):
    """Abandoning the iterator mid-epoch must not wedge the prefetcher."""
    import threading as _t

    def prefetchers():
        return [t for t in _t.enumerate() if t.name == "iter-batches-prefetch"]

    for _ in range(5):
        for batch in rd.range(100, parallelism=10).iter_batches(batch_size=5):
            break  # consumer stops after the first batch
    import gc
    import time as _time

    gc.collect()  # close abandoned generators -> stop flags set
    deadline = _time.time() + 5
    while prefetchers() and _time.time() < deadline:
        _time.sleep(0.1)
    assert not prefetchers(), f"leaked prefetch threads: {prefetchers()}"


def test_to_torch_and_iter_torch_batches(ray_start_regular):
    import torch

    from ray_tpu.data import read_api

    rows = [{"x": float(i), "y": 2.0 * i} for i in range(16)]
    ds = read_api.from_items(rows)
    batches = list(ds.iter_torch_batches(batch_size=8))
    assert all(isinstance(b["x"], torch.Tensor) for b in batches)
    total = torch.cat([b["x"] for b in batches])
    assert sorted(total.tolist()) == [float(i) for i in range(16)]

    it = ds.to_torch(label_column="y", feature_columns=["x"], batch_size=4)
    feats, labels = next(iter(it))
    assert isinstance(feats, torch.Tensor) and isinstance(labels, torch.Tensor)
    assert feats.shape == (4, 1) and feats.dtype == torch.float32
    assert labels.shape[-1] == 1
    torch.testing.assert_close(labels.double(), (feats * 2).double())


def test_arrow_blocks_end_to_end(ray_start_regular, tmp_path):
    """Arrow-native blocks: parquet reads produce pyarrow.Table blocks
    that ride the store zero-copy, slice zero-copy in iter_batches, and
    convert on demand (block.py arrow layout)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from ray_tpu.data import from_arrow, read_api

    t = pa.table({"x": list(range(100)), "y": [i * 2.0 for i in range(100)]})
    pq.write_table(t.slice(0, 50), str(tmp_path / "a.parquet"))
    pq.write_table(t.slice(50, 50), str(tmp_path / "b.parquet"))

    ds = read_api.read_parquet(str(tmp_path))
    # blocks are Arrow tables (not converted)
    block = ray_tpu.get(ds._blocks[0])
    assert isinstance(block, pa.Table)
    assert ds.count() == 100
    # numpy batches come out columnar
    batches = list(ds.iter_batches(batch_size=30))
    assert sum(len(b["x"]) for b in batches) == 100
    # arrow batches stay arrow
    ab = next(iter(ds.iter_batches(batch_size=32, batch_format="pyarrow")))
    assert isinstance(ab, pa.Table) and ab.num_rows == 32
    # transforms over arrow blocks via numpy path + sort round trip
    out = ds.map_batches(lambda b: {"x": b["x"] + 1, "y": b["y"]}) \
            .sort("x").take(3)
    assert [r["x"] for r in out] == [1, 2, 3]
    # from_arrow + zero-copy store round trip
    ds2 = from_arrow(t)
    assert ds2.count() == 100
    got = ray_tpu.get(ds2._blocks[0])
    assert got.column("x").to_pylist() == list(range(100))


def test_streaming_iter_overlaps_map(ray_start_regular):
    """One-to-one suffix stages stream through iter_batches with a
    bounded window: consumption begins before all map tasks finish, and
    the plan is NOT pre-materialized stage-by-stage."""
    import time as _t

    from ray_tpu.data import read_api

    # warm the worker pool first: under pytest the task closures pickle
    # BY REFERENCE to this test module, so each worker's first task pays
    # a one-time `import test_data` (numpy + ray_tpu chain) — ~1s/worker
    # on this 1-core box.  That cost is real but is not what this test
    # measures; the assertion targets streaming overlap, not cold boot.
    @ray_tpu.remote
    def warm():
        return 0

    ray_tpu.get([warm.remote() for _ in range(8)], timeout=120)

    def slow_inc(batch):
        _t.sleep(0.3)
        return np.asarray(batch) + 1

    ds = read_api.from_numpy(np.arange(64), parallelism=8).map_batches(slow_inc)
    t0 = _t.perf_counter()
    it = ds.iter_batches(batch_size=8)
    first = next(it)
    t_first = _t.perf_counter() - t0
    rest = list(it)
    t_all = _t.perf_counter() - t0
    got = np.concatenate([np.asarray(first)] + [np.asarray(b) for b in rest])
    assert sorted(got.tolist()) == list(range(1, 65))
    # 8 blocks x 0.3s serial floor is 2.4s; streaming yields the first
    # batch after ~1 block's latency — well before the tail completes
    assert t_first < t_all, (t_first, t_all)
    assert t_first < 1.5, f"first batch took {t_first:.2f}s (not streaming)"
