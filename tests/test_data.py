"""Dataset tests (transforms, shuffles, splits, io, pipeline)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


def test_range_and_aggregates(ray_start_regular):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.num_blocks() == 4
    assert ds.sum() == 4950
    assert ds.min() == 0 and ds.max() == 99
    assert ds.mean() == 49.5


def test_from_items_map_filter(ray_start_regular):
    ds = rd.from_items([{"x": i} for i in range(20)], parallelism=2)
    out = (
        ds.map(lambda r: {"x": r["x"] * 2})
          .filter(lambda r: r["x"] % 4 == 0)
          .take_all()
    )
    assert [r["x"] for r in out] == [0, 4, 8, 12, 16, 20, 24, 28, 32, 36]


def test_map_batches_numpy(ray_start_regular):
    ds = rd.range(16, parallelism=2)
    out = ds.map_batches(lambda batch: batch * 10, batch_size=4)
    np.testing.assert_array_equal(out.to_numpy(), np.arange(16) * 10)


def test_map_batches_actor_pool(ray_start_regular):
    class AddConst:
        def __init__(self, c):
            self.c = c

        def __call__(self, batch):
            return batch + self.c

    ds = rd.range(12, parallelism=3)
    out = ds.map_batches(
        AddConst, compute=rd.ActorPoolStrategy(size=2),
        fn_constructor_args=(100,),
    )
    np.testing.assert_array_equal(np.sort(out.to_numpy()), np.arange(12) + 100)


def test_flat_map_and_zip(ray_start_regular):
    ds = rd.from_items([1, 2, 3], parallelism=1)
    out = ds.flat_map(lambda x: [x, x]).take_all()
    assert out == [1, 1, 2, 2, 3, 3]


def test_split_and_union(ray_start_regular):
    ds = rd.range(12, parallelism=2)
    shards = ds.split(3)
    assert [s.count() for s in shards] == [4, 4, 4]
    joined = shards[0].union(shards[1], shards[2])
    assert joined.count() == 12


def test_shuffle_sort(ray_start_regular):
    ds = rd.from_items(list(range(50)), parallelism=2)
    shuffled = ds.random_shuffle(seed=0)
    assert shuffled.take_all() != list(range(50))
    assert sorted(shuffled.take_all()) == list(range(50))
    s = rd.from_items([{"k": v} for v in [3, 1, 2]], parallelism=1).sort(key="k")
    assert [r["k"] for r in s.take_all()] == [1, 2, 3]


def test_iter_batches(ray_start_regular):
    ds = rd.range(10, parallelism=3)
    batches = list(ds.iter_batches(batch_size=4))
    assert [len(b) for b in batches] == [4, 4, 2]
    np.testing.assert_array_equal(np.concatenate(batches), np.arange(10))
    dropped = list(ds.iter_batches(batch_size=4, drop_last=True))
    assert [len(b) for b in dropped] == [4, 4]


def test_csv_roundtrip(ray_start_regular, tmp_path):
    import pandas as pd

    df = pd.DataFrame({"a": [1, 2, 3], "b": ["x", "y", "z"]})
    p = str(tmp_path / "t.csv")
    df.to_csv(p, index=False)
    ds = rd.read_csv(p)
    assert ds.count() == 3
    assert set(ds.schema()) == {"a", "b"}
    out = str(tmp_path / "out.csv")
    ds.write_csv(out)
    pd.testing.assert_frame_equal(pd.read_csv(out), df)


def test_pipeline_window_repeat(ray_start_regular):
    ds = rd.range(8, parallelism=4)
    pipe = ds.window(blocks_per_window=2).map_batches(lambda b: b + 1)
    rows = [int(r) for r in pipe.iter_rows()]
    assert sorted(rows) == list(range(1, 9))
    reps = rd.range(4, parallelism=1).repeat(2)
    assert len(list(reps.iter_rows())) == 8


def test_dataset_feeds_trainer_shards(ray_start_regular):
    """Dataset.split -> session.get_dataset_shard wiring."""
    from ray_tpu.air import session
    from ray_tpu.train import JaxConfig, JaxTrainer
    from ray_tpu.air import ScalingConfig

    def loop(config):
        shard = session.get_dataset_shard("train")
        n = shard.count()
        session.report({"rows": n, "rank": session.get_world_rank()})

    ds = rd.range(8, parallelism=2)
    trainer = JaxTrainer(
        loop, jax_config=JaxConfig(),
        scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": ds},
        train_loop_config={},
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["rows"] == 4
