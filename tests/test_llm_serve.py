"""LLM serving: continuous-batching engine + Serve deployment.

Pins that iteration-level batching (requests admitted/freed mid-stream)
reproduces one-shot generation exactly under greedy decoding, and that the
engine works behind a Serve replica (the decode analog of the reference's
``serve/_private/replica.py:250`` request path).
"""

import jax
import jax.numpy as jnp
import pytest

import ray_tpu
from ray_tpu import serve
from ray_tpu.models import generate as gen
from ray_tpu.models.gpt2 import GPT2Config
from ray_tpu.models import gpt2
from ray_tpu.serve.llm import GenerationEngine, llm_deployment


@pytest.fixture
def serve_instance():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    client = serve.start(serve.HTTPOptions(host="127.0.0.1", port=0))
    yield client
    serve.shutdown()
    ray_tpu.shutdown()


def _one_shot(params, cfg, prompt, n):
    out = gen.generate(params, cfg, jnp.asarray([prompt]),
                       jnp.asarray([len(prompt)]), max_new_tokens=n)
    return [int(t) for t in out[0]]


def test_engine_matches_one_shot_under_continuous_batching():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init(cfg, jax.random.PRNGKey(0))
    eng = GenerationEngine(
        cfg, params, n_slots=2, max_new_tokens=8, decode_chunk_steps=3,
        prefill_buckets=(8, 16)).start()
    try:
        prompts = [[3, 17, 5], [9, 2], [11, 4, 7, 1], [6], [8, 8, 3, 2, 1]]
        futs = [eng.submit(p, 8) for p in prompts]  # 5 requests, 2 slots
        got = [f.result(timeout=120) for f in futs]
    finally:
        eng.stop()
    for p, g in zip(prompts, got):
        assert g == _one_shot(params, cfg, p, 8), f"prompt {p}"
    assert eng.stats()["total_requests"] == 5


def test_engine_eos_and_max_new():
    cfg = GPT2Config.tiny(dtype=jnp.float32)
    params = gpt2.init(cfg, jax.random.PRNGKey(1))
    ref = _one_shot(params, cfg, [5, 9, 2, 4], 12)
    # EOS semantics: the stream stops at the FIRST occurrence of the eos
    # value (tiny random models cycle quickly, so derive the expectation
    # from wherever the chosen value first appears)
    eos = ref[-1]
    idx = ref.index(eos)
    eng = GenerationEngine(
        cfg, params, n_slots=1, max_new_tokens=12, decode_chunk_steps=5,
        prefill_buckets=(8,), eos_id=eos).start()
    try:
        out = eng.generate([5, 9, 2, 4], timeout=120)
    finally:
        eng.stop()
    assert out == ref[:idx + 1]  # stops AT the eos token
    # max_new cutoff
    eng2 = GenerationEngine(
        cfg, params, n_slots=1, max_new_tokens=3, decode_chunk_steps=5,
        prefill_buckets=(8,)).start()
    try:
        out2 = eng2.generate([5, 9, 2, 4], timeout=120)
    finally:
        eng2.stop()
    assert out2 == ref[:3]


def test_llm_deployment_behind_serve(serve_instance):
    dep = llm_deployment(
        "gpt2", "tiny",
        engine_kwargs=dict(n_slots=2, max_new_tokens=6,
                           decode_chunk_steps=3, prefill_buckets=(8,)),
        config_kwargs=dict(dtype=jnp.float32),
    )
    handle = serve.run(dep.bind(), port=0)
    refs = [handle.remote({"tokens": [3, 5, 7], "max_new_tokens": 6})
            for _ in range(4)]
    outs = ray_tpu.get(refs, timeout=300)
    assert all(o == outs[0] for o in outs)  # greedy: identical prompts agree
    assert len(outs[0]["tokens"]) == 6
    stats = ray_tpu.get(handle.stats.remote(), timeout=60)
    assert stats["total_requests"] == 4
