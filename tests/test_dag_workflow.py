"""DAG nodes (``ray.dag``) + durable workflows (``ray.workflow``).

Reference: ``python/ray/dag/`` lazy nodes and ``python/ray/workflow/``
storage-backed recovery.
"""

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture(autouse=True)
def _wf_storage(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path / "wf"))


def test_function_dag(ray_start_regular):
    @ray_tpu.remote
    def a():
        return 2

    @ray_tpu.remote
    def b(x):
        return x * 3

    @ray_tpu.remote
    def c(x, y):
        return x + y

    # diamond: a feeds both b and c; a must run once
    an = a.bind()
    dag = c.bind(b.bind(an), an)
    assert ray_tpu.get(dag.execute(), timeout=60) == 8


def test_dag_with_input(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def add1(x):
        return x + 1

    with InputNode() as inp:
        dag = add1.bind(double.bind(inp))
    assert ray_tpu.get(dag.execute(5), timeout=60) == 11
    assert ray_tpu.get(dag.execute(10), timeout=60) == 21


def test_actor_dag(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    counter = Counter.bind(100)
    dag = counter.add.bind(5)
    assert ray_tpu.get(dag.execute(), timeout=60) == 105
    # same ClassNode -> same actor instance across executions
    assert ray_tpu.get(dag.execute(), timeout=60) == 110


def test_workflow_run_and_output(ray_start_regular):
    @ray_tpu.remote
    def fetch():
        return [1, 2, 3]

    @ray_tpu.remote
    def total(xs):
        return sum(xs)

    result = workflow.run(total.bind(fetch.bind()), workflow_id="sum-flow")
    assert result == 6
    assert workflow.get_status("sum-flow") == "SUCCEEDED"
    assert workflow.get_output("sum-flow") == 6
    assert any(m["workflow_id"] == "sum-flow" for m in workflow.list_all())


def test_workflow_resume_skips_completed_steps(ray_start_regular, tmp_path):
    """A step that fails mid-flow: resume() re-runs only the failed step —
    completed steps load from their checkpoints (the crash-recovery
    contract of workflow_storage.py)."""
    marker = tmp_path / "fail-once"
    marker.write_text("arm")
    counter_file = tmp_path / "a-runs"
    counter_file.write_text("0")

    @ray_tpu.remote
    def step_a():
        # count executions to prove resume doesn't re-run this step
        n = int(open(str(counter_file)).read()) + 1
        open(str(counter_file), "w").write(str(n))
        return 10

    @ray_tpu.remote
    def step_b(x, marker_path):
        if os.path.exists(marker_path):
            os.unlink(marker_path)
            raise RuntimeError("transient failure")
        return x + 1

    dag = step_b.bind(step_a.bind(), str(marker))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="flaky")
    assert workflow.get_status("flaky") == "FAILED"
    assert open(str(counter_file)).read() == "1"

    result = workflow.resume("flaky")
    assert result == 11
    assert workflow.get_status("flaky") == "SUCCEEDED"
    # step_a was NOT re-executed — its checkpoint was reused
    assert open(str(counter_file)).read() == "1"


def test_workflow_run_async(ray_start_regular):
    @ray_tpu.remote
    def slow():
        import time

        time.sleep(0.5)
        return "done"

    h = workflow.run_async(slow.bind(), workflow_id="async-flow")
    assert h.result(timeout=120) == "done"
    assert workflow.get_status("async-flow") == "SUCCEEDED"


def test_workflow_rejects_actor_nodes(ray_start_regular):
    @ray_tpu.remote
    class A:
        def go(self):
            return 1

    with pytest.raises(TypeError, match="task DAGs"):
        workflow.run(A.bind().go.bind(), workflow_id="bad")
