"""DAG nodes (``ray.dag``) + durable workflows (``ray.workflow``).

Reference: ``python/ray/dag/`` lazy nodes and ``python/ray/workflow/``
storage-backed recovery.
"""

import os

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture(autouse=True)
def _wf_storage(tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path / "wf"))


def test_function_dag(ray_start_regular, tmp_path):
    log = tmp_path / "a-runs"

    @ray_tpu.remote
    def a():
        with open(str(log), "a") as f:
            f.write("x")
        return 2

    @ray_tpu.remote
    def b(x):
        return x * 3

    @ray_tpu.remote
    def c(x, y):
        return x + y

    # diamond: a feeds both b and c; a must run once (diamond dedup —
    # both consumers receive the same ObjectRef, one submit per node)
    an = a.bind()
    dag = c.bind(b.bind(an), an)
    assert ray_tpu.get(dag.execute(), timeout=60) == 8
    assert log.read_text() == "x", "shared node ran more than once"


def test_dag_with_input(ray_start_regular):
    @ray_tpu.remote
    def double(x):
        return x * 2

    @ray_tpu.remote
    def add1(x):
        return x + 1

    with InputNode() as inp:
        dag = add1.bind(double.bind(inp))
    assert ray_tpu.get(dag.execute(5), timeout=60) == 11
    assert ray_tpu.get(dag.execute(10), timeout=60) == 21


def test_topological_deep_chain_is_iterative():
    """A ~5k-node chain must not hit Python's recursion limit (the
    recursive visit overflowed around 1k nodes)."""
    from ray_tpu.dag.dag_node import FunctionNode

    node = InputNode()
    for _ in range(5000):
        node = FunctionNode(None, (node,), {})
    order = node.topological()
    assert len(order) == 5001
    assert order[0] is not node and order[-1] is node


def test_class_node_options_parity(ray_start_regular):
    """ClassNode.options() (FunctionNode.options parity): actor options
    apply at creation; the original node is untouched."""

    @ray_tpu.remote
    class Named:
        def who(self):
            return ray_tpu.get_runtime_context().actor_id.hex()

    base = Named.bind()
    named = base.options(name="dag-named-actor")
    assert named._options.get("name") == "dag-named-actor"
    assert not base._options  # original node untouched
    aid = ray_tpu.get(named.who.bind().execute(), timeout=60)
    handle = ray_tpu.get_actor("dag-named-actor")
    assert handle._actor_id.hex() == aid
    # unknown options still fail fast at creation time
    with pytest.raises(ValueError):
        base.options(bogus_option=1).who.bind().execute()


def test_actor_dag(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self, start):
            self.v = start

        def add(self, x):
            self.v += x
            return self.v

    counter = Counter.bind(100)
    dag = counter.add.bind(5)
    assert ray_tpu.get(dag.execute(), timeout=60) == 105
    # same ClassNode -> same actor instance across executions
    assert ray_tpu.get(dag.execute(), timeout=60) == 110


def test_workflow_run_and_output(ray_start_regular):
    @ray_tpu.remote
    def fetch():
        return [1, 2, 3]

    @ray_tpu.remote
    def total(xs):
        return sum(xs)

    result = workflow.run(total.bind(fetch.bind()), workflow_id="sum-flow")
    assert result == 6
    assert workflow.get_status("sum-flow") == "SUCCEEDED"
    assert workflow.get_output("sum-flow") == 6
    assert any(m["workflow_id"] == "sum-flow" for m in workflow.list_all())


def test_workflow_resume_skips_completed_steps(ray_start_regular, tmp_path):
    """A step that fails mid-flow: resume() re-runs only the failed step —
    completed steps load from their checkpoints (the crash-recovery
    contract of workflow_storage.py)."""
    marker = tmp_path / "fail-once"
    marker.write_text("arm")
    counter_file = tmp_path / "a-runs"
    counter_file.write_text("0")

    @ray_tpu.remote
    def step_a():
        # count executions to prove resume doesn't re-run this step
        n = int(open(str(counter_file)).read()) + 1
        open(str(counter_file), "w").write(str(n))
        return 10

    @ray_tpu.remote
    def step_b(x, marker_path):
        if os.path.exists(marker_path):
            os.unlink(marker_path)
            raise RuntimeError("transient failure")
        return x + 1

    dag = step_b.bind(step_a.bind(), str(marker))
    with pytest.raises(Exception):
        workflow.run(dag, workflow_id="flaky")
    assert workflow.get_status("flaky") == "FAILED"
    assert open(str(counter_file)).read() == "1"

    result = workflow.resume("flaky")
    assert result == 11
    assert workflow.get_status("flaky") == "SUCCEEDED"
    # step_a was NOT re-executed — its checkpoint was reused
    assert open(str(counter_file)).read() == "1"


def test_workflow_run_async(ray_start_regular):
    @ray_tpu.remote
    def slow():
        import time

        time.sleep(0.5)
        return "done"

    h = workflow.run_async(slow.bind(), workflow_id="async-flow")
    assert h.result(timeout=120) == "done"
    assert workflow.get_status("async-flow") == "SUCCEEDED"


def test_workflow_rejects_actor_nodes(ray_start_regular):
    @ray_tpu.remote
    class A:
        def go(self):
            return 1

    with pytest.raises(TypeError, match="task DAGs"):
        workflow.run(A.bind().go.bind(), workflow_id="bad")


# ---------------------------------------------------------------------------
# round 5: continuations, per-step options, events, cancel, metadata
# (reference workflow/api.py continuation/options/wait_for_event/cancel)


def test_workflow_continuation(ray_start_regular, tmp_path, monkeypatch):
    """A step returning a DAG continues the workflow with it; sub-steps
    checkpoint under the parent step's id (recursive factorial, the
    reference's canonical continuation shape)."""
    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))

    @ray_tpu.remote
    def fact(n, acc=1):
        if n <= 1:
            return acc
        return workflow.continuation(fact.bind(n - 1, acc * n))

    assert workflow.run(fact.bind(5), workflow_id="fact5") == 120
    assert workflow.get_status("fact5") == "SUCCEEDED"


def test_workflow_step_retries(ray_start_regular, tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))
    marker = tmp_path / "attempts"

    @workflow.options(max_retries=3)
    @ray_tpu.remote
    def flaky():
        n = int(marker.read_text()) if marker.exists() else 0
        marker.write_text(str(n + 1))
        if n < 2:
            raise RuntimeError(f"boom {n}")
        return "ok"

    assert workflow.run(flaky.bind(), workflow_id="retry-flow") == "ok"
    assert int(marker.read_text()) == 3  # 2 failures + 1 success
    meta = workflow.get_metadata("retry-flow")
    step = next(iter(meta["steps"].values()))
    assert step["status"] == "SUCCEEDED" and step["attempt"] == 2


def test_workflow_catch_exceptions(ray_start_regular, tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))

    @workflow.options(catch_exceptions=True)
    @ray_tpu.remote
    def doomed():
        raise ValueError("expected-failure")

    @ray_tpu.remote
    def handle(pair):
        value, err = pair
        return "handled" if err is not None else value

    out = workflow.run(handle.bind(doomed.bind()), workflow_id="catch-flow")
    assert out == "handled"
    assert workflow.get_status("catch-flow") == "SUCCEEDED"


def test_workflow_sleep_checkpoints_wakeup(ray_start_regular, tmp_path,
                                           monkeypatch):
    """workflow.sleep resolves after the duration; the wake TIME is
    checkpointed so resume doesn't restart the clock."""
    import time as _time

    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))

    @ray_tpu.remote
    def after(end_time):
        return _time.time() >= end_time - 0.05

    t0 = _time.time()
    assert workflow.run(after.bind(workflow.sleep(1.0)),
                        workflow_id="sleepy") is True
    assert _time.time() - t0 >= 0.9


def test_workflow_custom_event_listener(ray_start_regular, tmp_path,
                                        monkeypatch):
    """A file-based EventListener: the workflow blocks until the event
    appears, then the commit step runs (checkpointed consumption)."""
    import threading as _threading
    import time as _time

    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))
    event_file = tmp_path / "evt.txt"
    ack_file = tmp_path / "ack.txt"

    class FileListener(workflow.EventListener):
        async def poll_for_event(self, path):
            import asyncio
            import os as _os

            while not _os.path.exists(path):
                await asyncio.sleep(0.05)
            with open(path) as f:
                return f.read()

        async def event_checkpointed(self, event):
            with open(str(ack_file), "w") as f:
                f.write(event)

    @ray_tpu.remote
    def consume(evt):
        return f"got:{evt}"

    def fire():
        _time.sleep(1.0)
        event_file.write_text("payload-7")

    _threading.Thread(target=fire, daemon=True).start()
    dag = consume.bind(
        workflow.wait_for_event(FileListener, str(event_file)))
    assert workflow.run(dag, workflow_id="evt-flow",
                        ) == "got:payload-7"
    assert ack_file.read_text() == "payload-7"


def test_workflow_cancel(ray_start_regular, tmp_path, monkeypatch):
    import time as _time

    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))

    @ray_tpu.remote
    def forever():
        _time.sleep(600)
        return 1

    h = workflow.run_async(forever.bind(), workflow_id="cancel-flow")
    deadline = _time.time() + 60
    while workflow.get_status("cancel-flow") != "RUNNING" \
            and _time.time() < deadline:
        _time.sleep(0.05)
    _time.sleep(0.5)  # let the step task actually submit
    workflow.cancel("cancel-flow")
    with pytest.raises(Exception):
        h.result(timeout=120)
    assert workflow.get_status("cancel-flow") == "CANCELED"


def test_workflow_resume_all(ray_start_regular, tmp_path, monkeypatch):
    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path / "wf"))
    gate = tmp_path / "gate"

    @ray_tpu.remote
    def needs_gate():
        if not gate.exists():
            raise RuntimeError("gate closed")
        return "opened"

    with pytest.raises(Exception):
        workflow.run(needs_gate.bind(), workflow_id="gated")
    assert workflow.get_status("gated") == "FAILED"

    gate.write_text("x")
    results = workflow.resume_all(include_failed=True)
    assert [wid for wid, _ in results] == ["gated"]
    assert results[0][1].result(timeout=120) == "opened"
    assert workflow.get_status("gated") == "SUCCEEDED"


def test_workflow_options_validation(ray_start_regular):
    with pytest.raises(ValueError, match="unknown workflow options"):
        workflow.options(bogus=1)


def test_workflow_cancel_unknown_and_terminal(ray_start_regular, tmp_path,
                                              monkeypatch):
    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))
    with pytest.raises(ValueError, match="no workflow"):
        workflow.cancel("never-existed")
    assert workflow.list_all() == []  # no phantom dir fabricated

    @ray_tpu.remote
    def one():
        return 1

    workflow.run(one.bind(), workflow_id="done-flow")
    workflow.cancel("done-flow")  # no-op, never downgrades terminal status
    assert workflow.get_status("done-flow") == "SUCCEEDED"


def test_workflow_liveness_cross_process(ray_start_regular, tmp_path,
                                         monkeypatch):
    """meta.json records pid+host at RUNNING time; another process's
    cancel()/resume_all() probe that liveness: a LIVE foreign run gets a
    cancel_requested flag (never a status overwrite) and is never
    double-run by resume_all; a DEAD one is safe to cancel/resume."""
    import socket
    import subprocess
    import sys
    import time as _time

    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path / "wf"))
    from ray_tpu.workflow.api import WorkflowStorage

    gate = tmp_path / "gate"

    @ray_tpu.remote
    def needs_gate():
        if not gate.exists():
            raise RuntimeError("gate closed")
        return "opened"

    with pytest.raises(Exception):
        workflow.run(needs_gate.bind(), workflow_id="live-flow")
    meta = WorkflowStorage("live-flow").read_meta()
    assert meta["status"] == "FAILED" and meta["pid"] is None

    # forge a LIVE foreign owner: a real subprocess whose pid we stamp in
    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(600)"])
    try:
        WorkflowStorage("live-flow").write_meta(
            status="RUNNING", pid=proc.pid, host=socket.gethostname(),
            cancel_requested=False)
        # resume_all must SKIP the live run, not double-run it
        assert workflow.resume_all(include_failed=True) == []
        with pytest.raises(ValueError, match="another live process"):
            workflow.resume("live-flow")
        # cancel must request, not overwrite, a live owner's status
        workflow.cancel("live-flow")
        meta = WorkflowStorage("live-flow").read_meta()
        assert meta["status"] == "RUNNING"
        assert meta["cancel_requested"] is True
    finally:
        proc.kill()
        proc.wait()
    # owner is DEAD now: cancel takes over and marks CANCELED
    workflow.cancel("live-flow")
    assert workflow.get_status("live-flow") == "CANCELED"
    # ...and a CANCELED workflow resumes cleanly (the stale
    # cancel_requested flag must not insta-cancel the new run)
    gate.write_text("x")
    results = workflow.resume_all()
    assert [wid for wid, _ in results] == ["live-flow"]
    assert results[0][1].result(timeout=120) == "opened"
    assert workflow.get_status("live-flow") == "SUCCEEDED"
    assert WorkflowStorage("live-flow").read_meta()["pid"] is None


def test_workflow_meta_records_pid_while_running(ray_start_regular, tmp_path,
                                                 monkeypatch):
    import time as _time

    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))

    @ray_tpu.remote
    def forever():
        _time.sleep(600)
        return 1

    h = workflow.run_async(forever.bind(), workflow_id="pid-flow")
    from ray_tpu.workflow.api import WorkflowStorage

    deadline = _time.time() + 60
    while workflow.get_status("pid-flow") != "RUNNING" \
            and _time.time() < deadline:
        _time.sleep(0.05)
    assert WorkflowStorage("pid-flow").read_meta()["pid"] == os.getpid()
    workflow.cancel("pid-flow")
    with pytest.raises(Exception):
        h.result(timeout=120)


def test_workflow_cancel_immediately_after_run_async(ray_start_regular,
                                                     tmp_path, monkeypatch):
    """cancel() in the window before the runner thread is scheduled must
    not be lost: the handle is registered for cancellation from the
    moment run_async returns."""
    import time as _time

    monkeypatch.setenv("RAY_TPU_WORKFLOW_STORAGE", str(tmp_path))

    @ray_tpu.remote
    def forever():
        _time.sleep(600)
        return 1

    h = workflow.run_async(forever.bind(), workflow_id="insta-cancel")
    workflow.cancel("insta-cancel")  # no wait: races the runner thread
    with pytest.raises(Exception):
        h.result(timeout=120)
    assert workflow.get_status("insta-cancel") == "CANCELED"
