"""Ray-Train-style JaxTrainer end-to-end on the fake cluster."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air import Checkpoint, CheckpointConfig, RunConfig, ScalingConfig
from ray_tpu.train import JaxConfig, JaxTrainer, WorkerGroup


def test_worker_group_execute(ray_start_regular):
    wg = WorkerGroup(2, {"CPU": 1.0})
    try:
        outs = wg.execute(lambda: 7)
        assert outs == [7, 7]
        assert wg.execute_single(1, lambda x: x * 2, 21) == 42
    finally:
        wg.shutdown()


def _train_loop(config):
    import jax
    import numpy as np
    import optax

    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint
    from ray_tpu.models import mlp
    from ray_tpu.train import jax_utils

    cfg = mlp.MLPConfig(in_dim=8, hidden=(16,), num_classes=2)
    params = mlp.init(cfg, jax.random.PRNGKey(0))  # same init on every rank
    opt = optax.sgd(0.5)
    opt_state = opt.init(params)

    rank = session.get_world_rank()
    assert session.get_world_size() == config["num_workers"]
    rng = np.random.default_rng(rank)  # each rank gets its own shard
    x = np.asarray(rng.normal(size=(64, 8)), np.float32)
    y = (x.sum(-1) > 0).astype(np.int32)
    batch = {"x": x, "y": y}

    grad_fn = jax.jit(lambda p, b: jax.value_and_grad(mlp.loss_fn)(p, b, cfg))
    first = last = None
    for step in range(config["steps"]):
        loss, grads = grad_fn(params, batch)
        grads = jax_utils.allreduce_grads(grads)  # psum-analog gradient sync
        updates, opt_state = opt.update(grads, opt_state)
        params = optax.apply_updates(params, updates)
        last = float(loss)
        if first is None:
            first = last
        session.report({"loss": last, "step": step, "first_loss": first})
    session.report(
        {"loss": last, "first_loss": first, "final": True},
        checkpoint=Checkpoint.from_dict(
            {"params": jax.tree.map(np.asarray, params), "rank": rank}
        ),
    )


def test_jax_trainer_dp(ray_start_regular, tmp_path):
    trainer = JaxTrainer(
        _train_loop,
        train_loop_config={"steps": 8, "num_workers": 2},
        jax_config=JaxConfig(),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="dp_test", storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(num_to_keep=2),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["final"] is True
    assert result.metrics["loss"] < result.metrics["first_loss"]
    ckpt = result.checkpoint
    assert ckpt is not None
    state = ckpt.to_dict()
    assert "params" in state and "w0" in state["params"]


def test_checkpoint_conversions(tmp_path, ray_start_regular):
    ckpt = Checkpoint.from_dict({"a": np.arange(3)})
    d = ckpt.to_directory(str(tmp_path / "c1"))
    back = Checkpoint.from_directory(d).to_dict()
    np.testing.assert_array_equal(back["a"], np.arange(3))
    ref = ckpt.to_object_ref()
    again = Checkpoint.from_object_ref(ref).to_dict()
    np.testing.assert_array_equal(again["a"], np.arange(3))
    uri = Checkpoint.from_dict({"b": 1}).to_uri(f"file://{tmp_path}/c2")
    assert Checkpoint.from_uri(uri).to_dict()["b"] == 1
