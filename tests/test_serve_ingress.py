"""Asyncio serve ingress: ASGI mounting, deadlines, shedding, retries,
graceful draining.

The request-level fault-tolerance surface of the asyncio front door
(``serve/_private/http_proxy.py``): per-request deadlines threaded
proxy→router→replica, retry-with-backoff on replica death for idempotent
requests, backlog-watermark load shedding (503 + Retry-After), and
controller-driven graceful replica draining.  Doctor's ingress rules are
unit-tested over synthetic rows here; the live chaos scenario lives in
``test_serve_chaos.py``.
"""

import http.client
import json
import os
import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture(scope="module")
def serve_instance():
    os.environ["RAY_TPU_EVENTS_FLUSH_S"] = "0.2"
    ray_tpu.init(num_cpus=16)
    client = serve.start(serve.HTTPOptions(host="127.0.0.1", port=0))
    yield client
    serve.shutdown()
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_EVENTS_FLUSH_S", None)


def _request(port, path, method="GET", body=None, headers=None, timeout=60):
    """One request on a fresh connection; returns (status, headers, body)."""
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request(method, path, body=body, headers=headers or {})
        resp = conn.getresponse()
        return resp.status, dict(resp.headers), resp.read()
    finally:
        conn.close()


def _events_rows(message=None, source="serve"):
    from ray_tpu.experimental.state import api as state

    rows = [e for e in state.list_events(limit=100_000)
            if e.get("source") == source]
    if message is not None:
        rows = [e for e in rows if e.get("message") == message]
    return rows


def _wait_for_event(message, pred=lambda rows: bool(rows), timeout=15.0):
    deadline = time.monotonic() + timeout
    rows = []
    while time.monotonic() < deadline:
        rows = _events_rows(message)
        if pred(rows):
            return rows
        time.sleep(0.3)
    return rows


# ---------------------------------------------------------------------------
# the asyncio front door itself
# ---------------------------------------------------------------------------

def test_asyncio_ingress_is_default_and_serves(serve_instance):
    @serve.deployment
    def hello(request):
        return {"hi": request.query_params.get("who", "world")}

    serve.run(hello.bind(), port=0)
    host, port = serve.get_http_address()
    status, headers, body = _request(port, "/hello?who=tpu")
    assert status == 200
    assert json.loads(body) == {"hi": "tpu"}
    stats = ray_tpu.get(serve_instance.proxy.ingress_stats.remote(),
                        timeout=30)
    assert stats["mode"] == "asyncio"
    assert stats["requests"] >= 1 and stats["ok"] >= 1
    # malformed request lines answer 400, and the listener survives
    import socket

    s = socket.create_connection(("127.0.0.1", port), timeout=30)
    try:
        s.sendall(b"NONSENSE\r\n\r\n")
        raw = s.recv(4096)
        assert b"400" in raw.split(b"\r\n", 1)[0], raw
    finally:
        s.close()
    status, _, _ = _request(port, "/hello")
    assert status == 200
    serve.delete("hello")


def test_response_status_and_headers_passthrough(serve_instance):
    @serve.deployment
    class Teapot:
        def __call__(self, request):
            return serve.Response(
                {"short": "stout"}, status_code=418,
                headers={"X-Teapot": "yes"})

    serve.run(Teapot.bind(), port=0)
    _, port = serve.get_http_address()
    status, headers, body = _request(port, "/Teapot")
    assert status == 418
    assert headers.get("X-Teapot") == "yes"
    assert json.loads(body) == {"short": "stout"}
    serve.delete("Teapot")


# ---------------------------------------------------------------------------
# @serve.ingress — ASGI adapter
# ---------------------------------------------------------------------------

async def _mini_asgi_app(scope, receive, send):
    """A minimal by-hand ASGI app: routes on path, echoes bodies, sets a
    header — no framework required (none is installed)."""
    assert scope["type"] == "http"
    path = scope["path"]
    if path.endswith("/hello"):
        await send({"type": "http.response.start", "status": 200,
                    "headers": [(b"x-asgi", b"mini")]})
        await send({"type": "http.response.body",
                    "body": b"hello from asgi"})
        return
    if path.endswith("/echo"):
        message = await receive()
        await send({"type": "http.response.start", "status": 200,
                    "headers": [(b"content-type", b"application/json")]})
        await send({"type": "http.response.body",
                    "body": json.dumps(
                        {"echo": message.get("body", b"").decode(),
                         "method": scope["method"]}).encode()})
        return
    await send({"type": "http.response.start", "status": 404,
                "headers": []})
    await send({"type": "http.response.body", "body": b"asgi: no route"})


def test_asgi_ingress_mount(serve_instance):
    @serve.deployment
    @serve.ingress(_mini_asgi_app)
    class Mounted:
        def side_channel(self):
            return "direct"

    serve.run(Mounted.bind(), port=0)
    _, port = serve.get_http_address()
    status, headers, body = _request(port, "/Mounted/hello")
    assert (status, body) == (200, b"hello from asgi")
    assert headers.get("x-asgi") == "mini"
    status, _, body = _request(port, "/Mounted/echo", method="POST",
                               body=b"ping")
    assert status == 200
    assert json.loads(body) == {"echo": "ping", "method": "POST"}
    # the app's own 404 (not the proxy's route miss) comes through
    status, _, body = _request(port, "/Mounted/nope")
    assert (status, body) == (404, b"asgi: no route")
    # non-HTTP callers still reach named methods directly
    handle = serve.get_deployment_handle("Mounted")
    assert ray_tpu.get(handle.side_channel.remote(), timeout=60) == "direct"
    serve.delete("Mounted")


def test_asgi_ingress_traced_root_span(serve_instance):
    """ROADMAP acceptance: root traces flow through the new proxy
    unchanged — an HTTP request into a mounted ASGI app yields one trace
    rooted at the proxy with the router admission chained under it."""
    from ray_tpu.experimental.state import api as state

    @serve.deployment
    @serve.ingress(_mini_asgi_app)
    class Traced:
        pass

    serve.run(Traced.bind(), port=0)
    _, port = serve.get_http_address()
    status, _, _ = _request(port, "/Traced/hello")
    assert status == 200

    def find_root():
        for s in state.list_traces(limit=200):
            if "GET /Traced/hello" in (s.get("name") or ""):
                return s
        return None

    deadline = time.monotonic() + 20
    root = None
    while time.monotonic() < deadline and root is None:
        root = find_root()
        time.sleep(0.3)
    assert root is not None, "no trace rooted at the HTTP request"
    tr = None
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        tr = state.get_trace(root["trace_id"])
        if tr is not None and any(
                s.get("phase") == "router_admission" for s in tr["spans"]):
            break
        time.sleep(0.3)
    phases = {s.get("phase") for s in tr["spans"]}
    assert "http" in phases, phases
    assert "router_admission" in phases, phases
    serve.delete("Traced")


def test_ingress_decorator_rejects_functions():
    with pytest.raises(TypeError, match="decorates a class"):
        serve.ingress(_mini_asgi_app)(lambda request: None)


# ---------------------------------------------------------------------------
# deadlines
# ---------------------------------------------------------------------------

def test_deadline_header_caps_queueing(serve_instance):
    """A 1s-budget request must not queue behind a busy replica for the
    60s default (the router threads the per-request deadline through)."""

    @serve.deployment(max_concurrent_queries=1)
    class Busy:
        def __call__(self, request=None):
            time.sleep(3.0)
            return "eventually"

    serve.run(Busy.bind(), port=0)
    _, port = serve.get_http_address()
    blocker = threading.Thread(
        target=lambda: _request(port, "/Busy", timeout=120))
    blocker.start()
    time.sleep(0.8)  # let the blocker occupy the only slot
    t0 = time.monotonic()
    status, headers, body = _request(
        port, "/Busy", headers={"X-Serve-Deadline-S": "1"}, timeout=60)
    waited = time.monotonic() - t0
    # never assigned -> capacity answer (503 + Retry-After), fast
    assert status == 503, body
    assert "Retry-After" in headers
    assert waited < 5.0, f"queued {waited:.1f}s past a 1s deadline"
    blocker.join()
    serve.delete("Busy")


def test_deadline_504_while_executing(serve_instance):
    @serve.deployment
    class Slow:
        def __call__(self, request=None):
            time.sleep(4.0)
            return "late"

    serve.run(Slow.bind(), port=0)
    _, port = serve.get_http_address()
    t0 = time.monotonic()
    status, _, body = _request(
        port, "/Slow", headers={"X-Serve-Deadline-S": "1"}, timeout=60)
    waited = time.monotonic() - t0
    assert status == 504, body  # executing, not capacity
    assert waited < 6.0
    status, _, _ = _request(port, "/Slow",
                            headers={"X-Serve-Deadline-S": "0.5"})
    assert status in (503, 504)  # saturated now: either never assigned
    # (503) or assigned and expired (504) — both bounded
    serve.delete("Slow")


def test_router_deadline_overrides_default_timeout(serve_instance):
    """Direct router check: deadline wins over the hardcoded 60s
    default."""
    from ray_tpu.exceptions import GetTimeoutError

    @serve.deployment(max_concurrent_queries=1)
    class OneSlot:
        def __call__(self, request=None):
            time.sleep(2.5)
            return "ok"

    handle = serve.run(OneSlot.bind(), port=0)
    blocked = handle.remote()  # occupy the single slot
    time.sleep(0.5)
    router = handle._get_router()
    t0 = time.monotonic()
    with pytest.raises(GetTimeoutError):
        router.assign_request("__call__", (), {},
                              deadline=time.monotonic() + 0.5)
    assert time.monotonic() - t0 < 4.0
    assert ray_tpu.get(blocked, timeout=60) == "ok"
    serve.delete("OneSlot")


# ---------------------------------------------------------------------------
# load shedding
# ---------------------------------------------------------------------------

def test_backlog_watermark_sheds_503(serve_instance):
    """Backlog past max_queued_requests answers 503 + Retry-After instead
    of queueing unboundedly; the episode opens and closes in the flight
    recorder so doctor can explain it, then go quiet."""
    from ray_tpu.util import doctor

    @serve.deployment(max_concurrent_queries=1, max_queued_requests=2)
    class Choke:
        def __call__(self, request=None):
            time.sleep(0.45)
            return "served"

    serve.run(Choke.bind(), port=0)
    _, port = serve.get_http_address()
    results = []
    lock = threading.Lock()

    def one():
        out = _request(port, "/Choke", timeout=120)
        with lock:
            results.append(out)

    threads = [threading.Thread(target=one) for _ in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    statuses = sorted(s for s, _, _ in results)
    assert 503 in statuses, f"nothing shed: {statuses}"
    assert all(s in (200, 503) for s in statuses), statuses
    shed = [(s, h) for s, h, _ in results if s == 503]
    assert all("Retry-After" in h for _, h in shed)
    # the shedding episode reached the flight recorder and CLOSED (the
    # backlog drained once the burst passed)
    started = _wait_for_event("ingress shedding started")
    assert started, "no shedding-started event shipped"
    # drain fully, then make one more request: admission closes the episode
    time.sleep(1.0)
    status, _, _ = _request(port, "/Choke", timeout=60)
    assert status == 200
    stopped = _wait_for_event("ingress shedding stopped")
    assert stopped, "shedding episode never closed"
    # doctor: the closed episode is NOT an open finding
    events = _events_rows()
    findings = [f for f in doctor.diagnose(events)
                if f["rule"] == "ingress_shedding"]
    assert findings == [], findings
    serve.delete("Choke")


def test_doctor_ingress_shedding_rule_open_and_clear():
    """Pure-rule check: started without stopped = open incident; a later
    stopped for the same entity clears it."""
    from ray_tpu.util import doctor

    started = {"source": "serve", "message": "ingress shedding started",
               "entity_id": "dep", "ts": 100.0, "severity": "WARNING",
               "data": {"queued": 9, "max_queued": 8}}
    out = doctor.diagnose([started])
    assert [f["rule"] for f in out] == ["ingress_shedding"]
    stopped = {"source": "serve", "message": "ingress shedding stopped",
               "entity_id": "dep", "ts": 101.0, "severity": "INFO",
               "data": {}}
    assert doctor.diagnose([started, stopped]) == []
    # a NEW episode after the stop re-opens
    again = dict(started, ts=102.0)
    out = doctor.diagnose([started, stopped, again])
    assert [f["rule"] for f in out] == ["ingress_shedding"]


def test_doctor_drain_stuck_rule():
    from ray_tpu.util import doctor

    start = {"source": "serve", "message": "replica draining",
             "entity_id": "dep#abc", "ts": 100.0, "severity": "INFO",
             "data": {}}
    tick = {"source": "serve", "message": "heartbeat-ish",
            "entity_id": "x", "ts": 100.0 + doctor.DRAIN_STUCK_S + 1,
            "severity": "INFO", "data": {}}
    out = doctor.diagnose([start, tick])
    assert [f["rule"] for f in out] == ["drain_stuck"]
    assert out[0]["severity"] == "ERROR"
    done = {"source": "serve", "message": "replica drained",
            "entity_id": "dep#abc", "ts": 101.0, "severity": "INFO",
            "data": {"wait_s": 1.0}}
    assert doctor.diagnose([start, done, tick]) == []
    # a drain that hit the graceful window is surfaced even though closed
    cut = {"source": "serve", "message": "replica drain timeout",
           "entity_id": "dep#abc", "ts": 101.0, "severity": "WARNING",
           "data": {"inflight": 2}}
    out = doctor.diagnose([start, cut, tick])
    assert [f["rule"] for f in out] == ["drain_stuck"]
    assert out[0]["severity"] == "WARNING"


# ---------------------------------------------------------------------------
# replica-death retries
# ---------------------------------------------------------------------------

def test_idempotent_requests_survive_replica_death(serve_instance):
    """Replica SIGKILL mid-request: idempotent requests are re-assigned to
    a live replica — never a client-visible 500."""

    import tempfile

    flag = os.path.join(tempfile.mkdtemp(prefix="serve_die_"), "died")

    @serve.deployment(num_replicas=2)
    class DiesOnce:
        def __init__(self, flag_path):
            self.flag = flag_path

        def __call__(self, request=None):
            try:
                # exactly ONE replica dies (first to claim the flag) —
                # no cleanup, no goodbye, like a SIGKILL
                fd = os.open(self.flag, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.close(fd)
                os._exit(1)
            except FileExistsError:
                return "survived"

    serve.run(DiesOnce.bind(flag), port=0)
    _, port = serve.get_http_address()
    statuses = []
    for _ in range(6):
        status, _, body = _request(
            port, "/DiesOnce",
            headers={"X-Serve-Deadline-S": "60"}, timeout=120)
        statuses.append((status, body))
    assert all(s == 200 for s, _ in statuses), statuses
    stats = ray_tpu.get(serve_instance.proxy.ingress_stats.remote(),
                        timeout=30)
    assert stats["replica_deaths"] >= 1
    assert stats["retries"] >= 1
    retried = _wait_for_event("request retried after replica death")
    assert retried
    serve.delete("DiesOnce")


def test_non_idempotent_death_is_structured_500_and_key_opts_in(
        serve_instance):
    import tempfile

    tmp = tempfile.mkdtemp(prefix="serve_die_post_")

    @serve.deployment(num_replicas=2)
    class DiesOnPost:
        def __init__(self, tmpdir):
            self.tmp = tmpdir

        def __call__(self, request, _flag="died-{}"):
            if request.method == "POST":
                n = 1 if "plain" in request.query_params else 2
                try:
                    fd = os.open(os.path.join(self.tmp, _flag.format(n)),
                                 os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                    os.close(fd)
                    os._exit(1)
                except FileExistsError:
                    pass
            return "ok"

    serve.run(DiesOnPost.bind(tmp), port=0)
    _, port = serve.get_http_address()
    status, _, body = _request(port, "/DiesOnPost?plain=1", method="POST",
                               body=b"{}", timeout=120)
    assert status == 500
    assert b"non-idempotent" in body
    # the SAME shape of failure with an idempotency key retries to the
    # surviving replica instead
    status, _, body = _request(
        port, "/DiesOnPost", method="POST", body=b"{}",
        headers={"X-Idempotency-Key": "req-1", "X-Serve-Deadline-S": "60"},
        timeout=120)
    assert status == 200, body
    serve.delete("DiesOnPost")


# ---------------------------------------------------------------------------
# routing-refresh resilience
# ---------------------------------------------------------------------------

def test_refresh_failure_keeps_stale_table_with_backoff(serve_instance):
    """A transient controller stall must not poison routing: failed pulls
    keep the stale routing table and back off, and requests keep landing
    on the stale replica set."""

    @serve.deployment
    class Steady:
        def __call__(self, request=None):
            return "steady"

    handle = serve.run(Steady.bind(), port=0)
    assert ray_tpu.get(handle.remote(), timeout=60) == "steady"
    router = handle._get_router()

    def explode():
        raise OSError("controller unreachable (injected)")

    orig = router._pull_routing_info
    router._pull_routing_info = explode
    try:
        router._refresh(force=True)
        assert router._refresh_failures == 1
        assert router._next_refresh_attempt > time.monotonic() - 1
        assert router._replicas, "stale replica set was dropped"
        # requests still route on the stale table
        assert ray_tpu.get(handle.remote(), timeout=60) == "steady"
        # inside the backoff window the failing pull is NOT retried
        router._refresh(force=True)
        assert router._refresh_failures == 1
        # past the window it is (and fails again, widening the backoff)
        router._next_refresh_attempt = time.monotonic() - 0.01
        router._refresh(force=True)
        assert router._refresh_failures == 2
    finally:
        router._pull_routing_info = orig
    router._next_refresh_attempt = 0.0
    router._refresh(force=True)
    assert router._refresh_failures == 0
    failures = _wait_for_event("routing refresh failed")
    assert failures
    serve.delete("Steady")


# ---------------------------------------------------------------------------
# graceful draining
# ---------------------------------------------------------------------------

def test_graceful_drain_completes_inflight_requests(serve_instance):
    """Deleting (or scaling down) a deployment lets accepted requests
    finish: stop assigning, finish in-flight, then terminate."""

    @serve.deployment
    class Lingering:
        def __call__(self, request=None):
            time.sleep(2.2)
            return "finished cleanly"

    serve.run(Lingering.bind(), port=0)
    _, port = serve.get_http_address()
    result = {}

    def slow_call():
        result["out"] = _request(port, "/Lingering", timeout=120)

    t = threading.Thread(target=slow_call)
    t.start()
    time.sleep(0.8)  # request is in flight on the replica
    serve.delete("Lingering")  # drains, not kills
    t.join(timeout=60)
    status, _, body = result["out"]
    assert (status, body) == (200, b"finished cleanly"), result["out"]

    def mine(rows):
        return [r for r in rows
                if (r.get("data") or {}).get("deployment") == "Lingering"]

    drained = mine(_wait_for_event(
        "replica drained", pred=lambda rows: bool(mine(rows))))
    assert drained, "no drain-completed event for Lingering"
    # the drain WAITED for the in-flight request (not an instant kill)
    assert any((r.get("data") or {}).get("wait_s", 0) > 1.0
               for r in drained), drained
    assert not mine(_events_rows("replica drain timeout"))


def test_drain_timeout_cuts_off_overlong_requests(serve_instance):
    """A handler that outlives the graceful window is cut off — and the
    cutoff is recorded (doctor's drain_stuck evidence)."""
    from ray_tpu.serve.config import ReplicaState

    @serve.deployment(num_replicas=1)
    class Immortal:
        def __call__(self, request=None):
            time.sleep(30.0)
            return "never"

    d = Immortal.bind()
    d.deployment.config.graceful_shutdown_timeout_s = 1.5
    handle = serve.run(d, port=0)
    ref = handle.remote()
    time.sleep(0.8)
    serve.delete("Immortal")
    cut = _wait_for_event("replica drain timeout", timeout=20)
    assert cut, "drain timeout not recorded"
    assert (cut[0].get("data") or {}).get("inflight", 0) >= 1
    with pytest.raises(Exception):
        ray_tpu.get(ref, timeout=30)
    assert ReplicaState.DRAINING  # state constant exists for status maps


# ---------------------------------------------------------------------------
# externally-driven scaling (trend-autoscaler hook)
# ---------------------------------------------------------------------------

def test_scale_deployment_rpc_and_replica_scaler(serve_instance):
    from ray_tpu.autoscaler.policy import serve_replica_scaler

    @serve.deployment(autoscaling_config={
        "min_replicas": 1, "max_replicas": 3,
        "target_num_ongoing_requests_per_replica": 100.0,  # stay put
        "upscale_delay_s": 60.0, "downscale_delay_s": 60.0,
    })
    class Scaled:
        def __call__(self, request=None):
            return "ok"

    serve.run(Scaled.bind(), port=0)
    assert serve.status()["Scaled"]["num_replicas_goal"] == 1
    scaler = serve_replica_scaler(serve_instance.controller)
    scaler("Scaled", 2)
    assert serve.status()["Scaled"]["num_replicas_goal"] == 3
    scaler("Scaled", 5)  # clamped to the autoscaling max
    assert serve.status()["Scaled"]["num_replicas_goal"] == 3
    scaled_events = _wait_for_event("deployment scaled")
    assert scaled_events
    assert ray_tpu.get(
        serve_instance.controller.scale_deployment.remote("missing"),
        timeout=30) is None
    serve.delete("Scaled")
