"""Trend-driven autoscaling (``autoscaler/policy.py``).

The policy reads TSDB series and scales BEFORE doctor's trend rules
would flag an incident — every "fires" test here also asserts doctor
stays silent on the SAME series, proving the ordering by construction.
The TrendAutoscaler integration test drives a decision from a real head
TSDB and asserts the decision is visible as a flight-recorder event
(the audit-trail claim).
"""

import time

import pytest

import ray_tpu
from ray_tpu.autoscaler import (
    AutoscalingConfig,
    Decision,
    TrendAutoscaler,
    TrendPolicy,
    TrendPolicyConfig,
)
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.util.doctor import diagnose_trends


def _series(name, points, tags=None):
    return {name: [{"tags": tags or {}, "points": points}]}


def _ramp(start, end, n=8, t0=0.0, dt=60.0):
    return [[t0 + i * dt, start + (end - start) * i / (n - 1)]
            for i in range(n)]


def test_queue_slope_scales_up_before_doctor_would_fire():
    pol = TrendPolicy()
    # 10 -> 17 over 7 minutes: slope 1/min, ratio 1.7 — past the policy's
    # 1.5x but BELOW doctor's queue_depth_climb 2.0x. Capacity arrives
    # while doctor still calls the cluster healthy.
    sm = _series("ray_tpu_sched_queue_depth", _ramp(10, 17))
    decisions = pol.decide(sm, now=1000.0)
    assert [d.action for d in decisions] == ["scale_up_nodes"]
    assert decisions[0].reason == "queue_depth_slope"
    assert decisions[0].evidence["slope_per_min"] >= 1.0
    assert diagnose_trends(sm) == [], "doctor fired first — policy too late"


def test_queue_decision_respects_cooldown():
    pol = TrendPolicy(TrendPolicyConfig(cooldown_s=60.0))
    sm = _series("ray_tpu_sched_queue_depth", _ramp(10, 20))
    assert pol.decide(sm, now=1000.0)
    assert pol.decide(sm, now=1030.0) == []   # inside cooldown
    assert pol.decide(sm, now=1061.0)          # cooled


def test_router_backlog_scales_replicas_per_deployment():
    pol = TrendPolicy()
    sm = _series("ray_tpu_serve_router_queue_len",
                 [[i * 10.0, 3.0] for i in range(8)],
                 tags={"deployment": "bert"})
    decisions = pol.decide(sm, now=1000.0)
    assert len(decisions) == 1
    d = decisions[0]
    assert d.action == "scale_up_replicas" and d.deployment == "bert"
    assert d.amount >= 1
    # a standing-but-DRAINING queue (negative slope) is recovery, not
    # saturation: no decision
    pol2 = TrendPolicy()
    sm2 = _series("ray_tpu_serve_router_queue_len", _ramp(6, 1),
                  tags={"deployment": "bert"})
    assert pol2.decide(sm2, now=1000.0) == []


def test_rss_trend_acts_below_doctor_leak_threshold():
    pol = TrendPolicy()
    # 40MB of monotone growth at 8MB/min: policy fires (32MB floor),
    # doctor's rss_growth needs 64MB — still silent.
    sm = _series("ray_tpu_proc_rss_mb", _ramp(100, 140, n=10, dt=30.0),
                 tags={"worker_id": "w1"})
    decisions = pol.decide(sm, now=1000.0)
    assert [d.action for d in decisions] == ["scale_up_nodes"]
    assert decisions[0].reason == "rss_trend"
    assert diagnose_trends(sm) == []


def test_short_or_flat_series_never_decide():
    pol = TrendPolicy()
    sm = {}
    sm.update(_series("ray_tpu_sched_queue_depth", _ramp(10, 20, n=3)))
    sm.update(_series("ray_tpu_proc_rss_mb",
                      [[i * 30.0, 100.0] for i in range(10)]))
    assert pol.decide(sm, now=1000.0) == []


class _RecordingProvider(NodeProvider):
    def __init__(self):
        super().__init__({}, "rec")
        self.created = []
        self.nodes = []

    def non_terminated_nodes(self):
        return list(self.nodes)

    def create_node(self, node_config, count=1):
        ids = [f"rec-{len(self.nodes) + i}" for i in range(count)]
        self.nodes += ids
        self.created.append((dict(node_config), count))
        return ids

    def terminate_node(self, node_id):
        self.nodes.remove(node_id)


def test_trend_autoscaler_scales_from_live_tsdb_and_emits_event(
        ray_start_regular):
    """A sustained queue-depth climb ingested into the head's REAL TSDB
    drives a scale-up through the reconcile loop, and the decision lands
    in the flight recorder (source ``autoscaler``) with its evidence."""
    node = ray_tpu._private.worker.global_worker.node
    prov = _RecordingProvider()
    scaler = TrendAutoscaler(
        node, prov,
        AutoscalingConfig(min_workers=0, max_workers=4,
                          idle_timeout_s=3600.0))

    now = time.time()
    for i in range(10):
        node.tsdb.ingest(
            "head",
            {"ray_tpu_sched_queue_depth": {
                "type": "gauge", "help": "",
                "values": {(): 10.0 + i}}},
            ts=now - (10 - i) * 30.0)
    scaler.update()
    assert prov.created, "no node launched from the TSDB trend"

    # the decision is on the audit trail with its trend evidence
    from ray_tpu.experimental.state import api as state

    deadline = time.time() + 20
    rows = []
    while time.time() < deadline:
        rows = [e for e in state.list_events(limit=5000)
                if e.get("source") == "autoscaler"
                and "scale decision" in e.get("message", "")]
        if rows:
            break
        time.sleep(0.5)
    assert rows, "scale decision never reached the flight recorder"
    d = rows[-1].get("data") or {}
    assert d.get("reason") == "queue_depth_slope"
    assert d.get("action") == "scale_up_nodes"


def test_idle_check_falls_back_to_head_slice_index(ray_start_regular):
    """A provider that can't map its node id to member hosts (GCP: the
    TPU API knows VMs, not our node ids) must not read a busy slice as
    idle: the autoscaler resolves members from the HEAD's slice_id tags
    (hosts join with RAY_TPU_SLICE_ID=<provider node name>)."""
    node = ray_tpu._private.worker.global_worker.node
    prov = _RecordingProvider()   # inherits base slice_members: [node_id]
    prov.nodes = ["prov-slice-1"]
    scaler = TrendAutoscaler(
        node, prov, AutoscalingConfig(min_workers=0, idle_timeout_s=0.0))

    node.add_node_state("h0", {"CPU": 1.0}, slice_id="prov-slice-1")
    node.add_node_state("h1", {"CPU": 1.0}, slice_id="prov-slice-1")
    try:
        assert scaler._slice_members("prov-slice-1") == ["h0", "h1"]
        assert scaler._node_is_idle("prov-slice-1")

        # one busy member host makes the WHOLE slice non-idle
        with node.lock:
            node.nodes["h0"].available["CPU"] = 0.0
        assert not scaler._node_is_idle("prov-slice-1")
        scaler.update()
        assert prov.nodes == ["prov-slice-1"], "idle scale-down killed a busy slice"
    finally:
        node.remove_node_state("h0")
        node.remove_node_state("h1")


def test_scale_up_counts_whole_slice_capacity(ray_start_regular):
    """Unmet demand bin-packs against a provider NODE's capacity = one
    slice = slice_hosts x host resources — not a single host's, which
    over-launched slices by up to slice_hosts x."""
    node = ray_tpu._private.worker.global_worker.node
    prov = _RecordingProvider()
    scaler = TrendAutoscaler(
        node, prov,
        AutoscalingConfig(min_workers=0, max_workers=8, upscaling_speed=8,
                          idle_timeout_s=3600.0,
                          worker_node={"num_cpus": 1, "num_tpus": 1,
                                       "slice_hosts": 4}))
    with node.lock:
        # TPU demand: the CPU-only head can't absorb it, so all four
        # are unmet — and must fit ONE 4-host slice, not four
        for _ in range(4):
            node.pending_tasks.append({"resources": {"TPU": 1.0}})
    try:
        scaler.update()
        assert len(prov.created) == 1 and prov.created[0][1] == 1, (
            f"4 one-CPU demands over-launched: {prov.created}")
    finally:
        with node.lock:
            node.pending_tasks.clear()
            node._starved.clear()


def test_replica_decisions_go_through_replica_scaler(ray_start_regular):
    node = ray_tpu._private.worker.global_worker.node
    prov = _RecordingProvider()
    calls = []
    scaler = TrendAutoscaler(
        node, prov, AutoscalingConfig(idle_timeout_s=3600.0),
        replica_scaler=lambda dep, n: calls.append((dep, n)))
    scaler.apply(Decision("scale_up_replicas", "router_backlog",
                          amount=2, deployment="bert"))
    assert calls == [("bert", 2)]
