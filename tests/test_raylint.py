"""raylint: rule fixtures, the full-repo tier-1 gate, and the dynamic
lock-order witness.

Every rule is proven twice — it fires exactly on the seeded violation
lines of its fixture (``# EXPECT:<rule>`` markers) and stays silent on
the clean twin.  R1 additionally survives the acceptance mutation: a
dispatch arm deliberately removed from a copy of the real ``node.py``
must be caught.  The full-repo run IS the CI gate: any new finding
beyond ``raylint_baseline.json`` fails this file, and therefore tier-1.
"""

import glob
import json
import os
import re
import shutil
import subprocess
import sys
import textwrap
import threading

import pytest

from ray_tpu.devtools.raylint import (
    LintConfig, analyze, run_gate, split_new,
)
from ray_tpu.devtools.raylint.core import Project, SourceFile

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "raylint_fixtures")


@pytest.fixture(scope="module")
def repo_project():
    """The repo parsed ONCE for every repo-wide test in this file (the
    parse is ~half the analysis cost; tier-1 rides a tight timeout)."""
    cfg = LintConfig(root=REPO_ROOT)
    return cfg, Project(cfg.root, cfg.iter_paths())


def _expected_lines(relpath):
    """{line: count} from ``# EXPECT:<rule>`` markers (``x2`` = two)."""
    out = {}
    with open(os.path.join(FIXTURES, relpath)) as f:
        for i, line in enumerate(f, start=1):
            m = re.search(r"# EXPECT:R\d(?: x(\d+))?", line)
            if m:
                out[i] = int(m.group(1) or 1)
    return out


def _fixture_config(**overrides):
    defaults = dict(
        root=FIXTURES,
        head_handler_modules=(), clientbound_handler_modules=(),
        clientbound_sender_modules=(), protocol_exclude=(),
        hot_path_modules=(), head_container_modules=(),
        events_module="", state_api_module="", state_surface_modules=(),
    )
    defaults.update(overrides)
    return LintConfig(**defaults)


def _assert_rule_matches(config, rule, violation_files, clean_files):
    findings = analyze(config, rules=[rule])
    by_file = {}
    for f in findings:
        by_file.setdefault(f.path, {}).setdefault(f.line, 0)
        by_file[f.path][f.line] += 1
    for rel in clean_files:
        assert rel not in by_file, (
            f"{rule} false positive(s) on clean fixture {rel}: "
            f"{by_file.get(rel)}")
    for rel in violation_files:
        expected = _expected_lines(rel)
        got = by_file.get(rel, {})
        assert got == expected, (
            f"{rule} on {rel}: expected findings at {expected}, "
            f"got {got}\n" + "\n".join(f.render() for f in findings))


def test_r1_protocol_fixture():
    cfg = _fixture_config(
        package="r1_bad", head_handler_modules=("r1_bad/node.py",))
    _assert_rule_matches(cfg, "R1",
                         ["r1_bad/client.py", "r1_bad/node.py"], [])
    cfg = _fixture_config(
        package="r1_good", head_handler_modules=("r1_good/node.py",))
    assert analyze(cfg, rules=["R1"]) == []


def test_r1_packed_codec_table_skew():
    """A frame type present in _FRAME_IDS/_PACK but missing from _UNPACK
    fails R1 (packed-codec parity — both wire directions, same contract
    as the Envelope arms)."""
    cfg = _fixture_config(
        package="r1_packed",
        head_handler_modules=("r1_packed/hub.py",),
        packed_codec_module="r1_packed/codec.py")
    findings = analyze(cfg, rules=["R1"])
    packed = [f for f in findings if f.detail.startswith("packed-")]
    assert [f.detail for f in packed] == ["packed-table-skew:_UNPACK:beta"], \
        "\n".join(f.render() for f in findings)
    _assert_rule_matches(cfg, "R1", ["r1_packed/codec.py"], [])


def test_r1_catches_removed_handler(repo_project):
    """The acceptance mutation: delete one real dispatch arm from a
    copy of node.py and R1 must flag every sender of that type."""
    cfg, project = repo_project
    assert analyze(cfg, rules=["R1"], project=project) == [], \
        "R1 must be clean before the mutation"
    rel = "ray_tpu/_private/node.py"
    original = project.files[rel]
    mutated = original.source.replace('elif mtype == "seal":',
                                      'elif mtype == "seal_disabled":', 1)
    assert mutated != original.source, \
        "node.py no longer dispatches on seal?"
    project.files[rel] = SourceFile(rel, mutated)
    try:
        findings = analyze(cfg, rules=["R1"], project=project)
    finally:
        project.files[rel] = original
    unhandled = [f for f in findings if "seal" in f.detail
                 and f.detail.startswith("unhandled-headbound")]
    assert unhandled, (
        "removing the seal arm must surface unhandled senders, got: "
        + "\n".join(f.render() for f in findings))


def test_r1_no_phantom_send_across_functions(tmp_path):
    """A frame dict assigned in one function must never satisfy a
    ``.send()`` in ANOTHER function: the phantom send would mark the
    type as live and hide a dead handler — the exact regression class
    R1 exists to catch."""
    pkg = tmp_path / "mini"
    pkg.mkdir()
    (pkg / "client.py").write_text(
        'class C:\n'
        '    def build_only(self):\n'
        '        msg = {"type": "ghost"}\n'
        '        return msg  # never sent\n'
        '\n'
        '    def send_other(self, conn, msg):\n'
        '        conn.send(msg)  # msg is a parameter, type unknown\n')
    (pkg / "node.py").write_text(
        'def dispatch(conn, msg):\n'
        '    mtype = msg.get("type")\n'
        '    if mtype == "ghost":\n'
        '        pass\n')
    cfg = _fixture_config(root=str(tmp_path), package="mini",
                          head_handler_modules=("mini/node.py",))
    findings = analyze(cfg, rules=["R1"])
    dead = [f for f in findings if f.detail == "dead-head-handler:ghost"]
    assert dead, (
        "the ghost arm has no live sender and must be reported dead; "
        "got: " + "\n".join(f.render() for f in findings))


def test_r2_exception_shadow_fixture():
    cfg = _fixture_config(package="r2")
    _assert_rule_matches(cfg, "R2", ["r2/violation.py"], ["r2/clean.py"])


def test_r3_hot_path_entropy_fixture():
    cfg = _fixture_config(
        package="r3",
        hot_path_modules=("r3/violation.py", "r3/clean.py"))
    _assert_rule_matches(cfg, "R3", ["r3/violation.py"], ["r3/clean.py"])


def test_r4_lock_scope_weight_fixture():
    cfg = _fixture_config(package="r4")
    _assert_rule_matches(cfg, "R4", ["r4/violation.py"], ["r4/clean.py"])


def test_r5_unbounded_container_fixture():
    cfg = _fixture_config(
        package="r5",
        head_container_modules=("r5/violation.py", "r5/clean.py"))
    _assert_rule_matches(cfg, "R5", ["r5/violation.py"], ["r5/clean.py"])


def test_r6_event_source_fixture():
    cfg = _fixture_config(
        package="r6_bad", events_module="r6_bad/events.py")
    _assert_rule_matches(cfg, "R6", ["r6_bad/emitter.py"], [])
    cfg = _fixture_config(
        package="r6_good", events_module="r6_good/events.py")
    assert analyze(cfg, rules=["R6"]) == []


def test_r7_state_parity_fixture():
    cfg = _fixture_config(
        package="r7_bad", state_api_module="r7_bad/api.py",
        head_handler_modules=("r7_bad/node.py",),
        state_surface_modules=("r7_bad/cli.py",))
    _assert_rule_matches(cfg, "R7", ["r7_bad/api.py"], ["r7_bad/node.py"])
    cfg = _fixture_config(
        package="r7_good", state_api_module="r7_good/api.py",
        head_handler_modules=("r7_good/node.py",),
        state_surface_modules=("r7_good/cli.py",))
    assert analyze(cfg, rules=["R7"]) == []


def test_r8_bare_thread_fixture():
    cfg = _fixture_config(package="r8")
    _assert_rule_matches(cfg, "R8", ["r8/violation.py"], ["r8/clean.py"])


# ---------------------------------------------------------------------------
# suppressions + baseline mechanics
# ---------------------------------------------------------------------------

def test_suppression_forms():
    sf = SourceFile("x.py", "\n".join([
        "import time",                                   # 1
        "a = 1  # raylint: disable=R3",                  # 2
        "b = 2  # raylint: disable=R3 (rationale here)",  # 3
        "c = 3  # raylint: disable=R3,R4",               # 4
        "# raylint: disable=R5",                         # 5 -> covers 6
        "d = 4",                                         # 6
        "e = 5  # raylint: disable",                     # 7 (all rules)
        "f = 6",                                         # 8
        "g = 7  # raylint: disable=R3 (see R4, R5 below)",  # 9
        "h = 8  # raylint: disable=R3 one-shot, cold R4 path",  # 10
    ]))
    assert sf.suppressed(2, "R3") and not sf.suppressed(2, "R4")
    assert sf.suppressed(3, "R3")
    assert sf.suppressed(4, "R3") and sf.suppressed(4, "R4")
    assert sf.suppressed(6, "R5") and not sf.suppressed(5, "R5")
    assert sf.suppressed(7, "R1") and sf.suppressed(7, "R8")
    assert not sf.suppressed(8, "R3")
    # a comma inside the rationale must not suppress rules the prose
    # merely mentions — only the ids before the rationale count
    assert sf.suppressed(9, "R3")
    assert not sf.suppressed(9, "R4") and not sf.suppressed(9, "R5")
    assert sf.suppressed(10, "R3") and not sf.suppressed(10, "R4")


def test_baseline_multiset_semantics():
    from ray_tpu.devtools.raylint.core import Finding

    def mk(detail):
        return Finding(rule="R4", path="m.py", line=1, message="m",
                       remedy="r", detail=detail, scope="f")

    baseline = {}
    for f in [mk("a"), mk("a"), mk("b")]:
        baseline[f.baseline_key()] = baseline.get(f.baseline_key(), 0) + 1
    # two 'a' + one 'b' baselined; a third 'a' occurrence is NEW
    new, old = split_new([mk("a"), mk("a"), mk("a"), mk("b")], baseline)
    assert len(old) == 3 and len(new) == 1


def test_update_baseline_rejects_rule_subset(tmp_path):
    # run against a throwaway root: if the guard ever regresses, the
    # rewrite must hit this copy, never the checked-in baseline
    src = os.path.join(REPO_ROOT, "raylint_baseline.json")
    dst = tmp_path / "raylint_baseline.json"
    shutil.copy(src, dst)
    before = dst.read_text()
    with pytest.raises(ValueError):
        run_gate(str(tmp_path), rules=["R3"], update_baseline=True)
    assert dst.read_text() == before


# ---------------------------------------------------------------------------
# the tier-1 gate itself
# ---------------------------------------------------------------------------

def test_full_repo_gate_is_green(repo_project):
    """THE gate: a new finding anywhere in ray_tpu/ beyond the baseline
    fails tier-1.  Fix the finding, suppress it inline with a rationale,
    or (for genuinely-intended cases) `ray_tpu lint --update-baseline`."""
    from ray_tpu.devtools.raylint import run_gate

    cfg, project = repo_project
    result = run_gate(REPO_ROOT, config=cfg, project=project)
    assert result.new == [], (
        "new raylint findings:\n" + "\n".join(f.render() for f in result.new))
    # the baseline only shrinks: stale entries mean someone fixed a
    # grandfathered finding but left its key behind
    assert result.stale_keys == [], (
        "stale baseline entries (rerun --update-baseline): "
        f"{result.stale_keys}")


def test_lint_cli_json(capsys):
    """`ray_tpu lint --json` through the real argparse entry (in-process:
    a subprocess would pay ~5 s of interpreter+import on a box where
    tier-1 rides the timeout)."""
    from ray_tpu.scripts import cli

    cli.main(["lint", "--json"])  # green tree: must NOT SystemExit
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is True
    assert payload["new"] == []
    assert isinstance(payload["baselined"], list)


def test_rule_subset_api(repo_project):
    cfg, project = repo_project
    r3 = analyze(cfg, rules=["R3"], project=project)
    assert all(f.rule == "R3" for f in r3)
    with pytest.raises(ValueError):
        analyze(cfg, rules=["R99"], project=project)


# ---------------------------------------------------------------------------
# lock-order witness (the dynamic sanitizer)
# ---------------------------------------------------------------------------

def test_lockwitness_abba_cycle(monkeypatch):
    from ray_tpu.devtools.raylint.lockwitness import WITNESS, wrap_lock

    monkeypatch.delenv("RAY_TPU_LOCKWITNESS_DIR", raising=False)
    WITNESS.reset()
    A = wrap_lock("fixA", threading.Lock())
    B = wrap_lock("fixB", threading.Lock())

    def ab():
        with A:
            with B:
                pass

    def ba():
        with B:
            with A:
                pass

    for fn in (ab, ba):
        t = threading.Thread(target=fn, daemon=True)
        t.start()
        t.join()
    snap = WITNESS.snapshot()
    assert "fixA->fixB" in snap["edges"] and "fixB->fixA" in snap["edges"]
    assert len(snap["cycles"]) == 1
    cyc = snap["cycles"][0]
    assert cyc["locks"][0] == cyc["locks"][-1]  # closed cycle
    assert cyc["closing_stack"]                 # stack captured
    assert all(stk for stk in cyc["edges"].values())  # both directions
    with pytest.raises(AssertionError):
        WITNESS.assert_cycle_free()
    WITNESS.reset()
    WITNESS.assert_cycle_free()


def test_lockwitness_rlock_reentry_no_false_cycle():
    from ray_tpu.devtools.raylint.lockwitness import WITNESS, wrap_lock

    WITNESS.reset()
    A = wrap_lock("reA", threading.RLock())
    B = wrap_lock("reB", threading.Lock())
    with A:
        with A:           # re-entry: no self edge
            with B:
                pass
    with A:               # same order again: same edge, no cycle
        with B:
            pass
    snap = WITNESS.snapshot()
    assert snap["edges"] == ["reA->reB"]
    WITNESS.assert_cycle_free()


def test_lockwitness_condition_over_wrapped_rlock():
    from ray_tpu.devtools.raylint.lockwitness import WITNESS, wrap_lock

    WITNESS.reset()
    L = wrap_lock("condL", threading.RLock())
    cond = threading.Condition(L)
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hits.append(1)

    t = threading.Thread(target=waiter, daemon=True)
    t.start()
    import time

    time.sleep(0.2)
    with cond:
        cond.notify_all()
    t.join(timeout=5)
    assert hits == [1]
    WITNESS.assert_cycle_free()


def test_lockwitness_live_cluster_cycle_free(tmp_path):
    """The tier-1 acceptance: a real cluster (head + workers + actor +
    puts + metrics) driven with every named lock witnessed stays
    lock-order-cycle-free — in the head AND every worker process
    (workers report cycles into RAY_TPU_LOCKWITNESS_DIR).

    The drive runs in a SUBPROCESS with RAY_TPU_LOCKWITNESS=1 set before
    the interpreter starts: module-level locks (the metrics registry,
    object_store's attached/arena maps) are created at import time, so
    flipping the env in-process — after conftest has already imported
    ray_tpu — would leave exactly the head-side locks unwitnessed and
    the 'cycle-free' verdict hollow for them."""
    report_dir = str(tmp_path / "lockwitness")
    drive = tmp_path / "drive.py"
    drive.write_text(textwrap.dedent("""\
        import json
        import ray_tpu
        from ray_tpu.devtools.raylint.lockwitness import WITNESS, WitnessLock

        # import-time module-level locks must be wrapped — the reason
        # this drive is a subprocess and not an in-process monkeypatch
        from ray_tpu._private import object_store
        from ray_tpu.util import metrics
        assert isinstance(metrics._global.lock, WitnessLock), \\
            "metrics registry lock unwitnessed"
        assert isinstance(object_store._ATTACHED_LOCK, WitnessLock), \\
            "object_store attached lock unwitnessed"
        assert isinstance(object_store._ARENA_MAPS_LOCK, WitnessLock), \\
            "object_store arena-maps lock unwitnessed"

        ray_tpu.init(num_cpus=2, num_tpus=0)
        try:
            @ray_tpu.remote
            def f(x):
                return x + 1

            @ray_tpu.remote
            class Counter:
                def __init__(self):
                    self.n = 0

                def inc(self):
                    self.n += 1
                    return self.n

            assert ray_tpu.get([f.remote(i) for i in range(12)]) == \\
                [i + 1 for i in range(12)]
            # actors on distinct dispatch shards — submit/complete take
            # shard locks alone, while the kill below nests head lock ->
            # shard lock; the witness must see both patterns stay acyclic
            actors = [Counter.remote() for _ in range(3)]
            for c in actors:
                assert ray_tpu.get([c.inc.remote() for _ in range(5)])[-1] == 5
            ray_tpu.kill(actors[0])
            ref = ray_tpu.put(b"x" * (1 << 18))
            assert len(ray_tpu.get(ref)) == 1 << 18
            metrics.Counter("raylint_witness_test_total", "coverage").inc()
        finally:
            ray_tpu.shutdown()
        snap = WITNESS.snapshot()
        WITNESS.assert_cycle_free()
        print("WITNESS_SNAPSHOT " + json.dumps({"edges": snap["edges"]}))
    """))
    env = dict(os.environ,
               RAY_TPU_LOCKWITNESS="1",
               RAY_TPU_LOCKWITNESS_DIR=report_dir)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run(
        [sys.executable, str(drive)], cwd=REPO_ROOT, env=env,
        capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, (
        f"witnessed drive failed (rc={proc.returncode}):\n"
        f"{proc.stdout}\n{proc.stderr}")
    marked = [ln for ln in proc.stdout.splitlines()
              if ln.startswith("WITNESS_SNAPSHOT ")]
    assert marked, f"no snapshot line in drive output:\n{proc.stdout}"
    edges = json.loads(marked[-1].split(" ", 1)[1])["edges"]
    assert edges, "witness saw no nested acquisitions — is it on?"
    # the sharded dispatch is live coverage, not theory: at least one
    # nested acquisition must involve a shard lock (head -> shard, or
    # shard -> a leaf like the outbox/registry locks)
    assert any("node.shard" in e for e in edges), edges
    reports = glob.glob(os.path.join(report_dir, "*.json"))
    assert reports == [], (
        f"lock-order cycles reported: "
        f"{[open(p).read() for p in reports]}")
