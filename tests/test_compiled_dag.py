"""Compiled execution graphs (``dag/compiled.py`` + ``dag/channel.py``).

Reference: Ray Compiled Graphs (aDAG) — compile a static actor DAG once,
run it over pre-allocated channels with zero scheduler involvement per
call.  Covers the channel substrate directly (ring semantics, overflow,
poison, stream transport), the compiled-graph lifecycle (execute/get,
error propagation, teardown idempotence), the chaos contract (a SIGKILLed
mid-graph actor surfaces as a typed error, never a hang), the workload
proofs (microbatch pipeline schedule, prefill→decode serving graph), and
the flight-recorder/timeline integration.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu.dag import InputNode
from ray_tpu.dag.channel import (
    ChannelClosedError,
    ChannelTimeoutError,
    ShmChannel,
    StreamReaderChannel,
    StreamWriterChannel,
)
from ray_tpu.exceptions import ActorDiedError, RayTaskError


# ---------------------------------------------------------------------------
# channel substrate (no cluster needed)
# ---------------------------------------------------------------------------


def _chan_name(tag):
    import os

    return f"cdag-test-{tag}-{os.urandom(4).hex()}"


def test_shm_channel_roundtrip_and_backpressure():
    name = _chan_name("ring")
    w = ShmChannel.create(name, n_slots=2, slot_bytes=64)
    r = ShmChannel.attach(name)
    try:
        w.put(b"a")
        w.put(b"b")
        # ring full: the third put must block until a get frees a slot
        with pytest.raises(ChannelTimeoutError):
            w.put(b"c", timeout=0.1)
        assert r.get(timeout=5) == (b"a", 0)
        w.put(b"c", timeout=5)
        assert r.get(timeout=5) == (b"b", 0)
        assert r.get(timeout=5) == (b"c", 0)
        with pytest.raises(ChannelTimeoutError):
            r.get(timeout=0.05)
    finally:
        r.close()
        w.close(unlink=True)


def test_shm_channel_overflow_payload():
    name = _chan_name("ovf")
    w = ShmChannel.create(name, n_slots=2, slot_bytes=64)
    r = ShmChannel.attach(name)
    try:
        big = bytes(range(256)) * 64  # 16 KiB >> 64-byte slots
        w.put(big, flags=0)
        payload, flags = r.get(timeout=5)
        assert payload == big and flags == 0
    finally:
        r.close()
        w.close(unlink=True)


def test_shm_channel_poison_wakes_blocked_reader():
    name = _chan_name("poison")
    w = ShmChannel.create(name, n_slots=2, slot_bytes=64)
    r = ShmChannel.attach(name)
    errs = []

    def blocked_get():
        try:
            r.get(timeout=30)
        except ChannelClosedError as e:
            errs.append(e)

    t = threading.Thread(target=blocked_get)
    t.start()
    time.sleep(0.1)
    w.poison()
    t.join(timeout=10)
    assert not t.is_alive() and len(errs) == 1
    r.close()
    w.close(unlink=True)


def test_stream_channel_roundtrip_credits_poison():
    authkey = b"stream-test-key"
    w = StreamWriterChannel(capacity=2, authkey=authkey)
    r = StreamReaderChannel(w.addr, authkey)
    try:
        w.put(b"x", timeout=10)
        w.put(b"y", flags=1, timeout=10)
        # credits exhausted until the reader acks
        with pytest.raises(ChannelTimeoutError):
            w.put(b"z", timeout=0.2)
        assert r.get(timeout=10) == (b"x", 0)
        assert r.get(timeout=10) == (b"y", 1)
        w.put(b"z", timeout=10)  # acks drained -> credit available
        assert r.get(timeout=10) == (b"z", 0)
        w.poison()
        with pytest.raises(ChannelClosedError):
            r.get(timeout=10)
    finally:
        r.close()
        w.close()


# ---------------------------------------------------------------------------
# compiled graph lifecycle
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def compiled_cluster():
    """One cluster for every compiled-graph test in this module: graphs
    are isolated by construction (own actors, own channels), and sharing
    the boot keeps the tier-1 wall-clock flat."""
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@ray_tpu.remote
class _Stage:
    def __init__(self, k=0):
        self.k = k
        self.calls = 0

    def fwd(self, x):
        self.calls += 1
        if x == "boom":
            raise ValueError("expected-failure")
        if x == "slow":
            time.sleep(15)
        return x + self.k

    def ncalls(self):
        return self.calls


@ray_tpu.remote
class _Join:
    def join(self, x, y, bias=0):
        return x + y + bias


def test_compiled_chain_basic(compiled_cluster):
    with InputNode() as inp:
        dag = _Stage.bind(10).fwd.bind(_Stage.bind(1).fwd.bind(inp))
    cg = dag.experimental_compile(max_inflight=4)
    try:
        assert cg.execute(5).get(timeout=60) == 16
        # repeated executions reuse the compiled loops + channels
        for i in range(20):
            assert ray_tpu.get(cg.execute(i), timeout=60) == i + 11
        # the graph ran on persistent actors, not fresh submits: the
        # second stage saw every call
        assert ray_tpu.get(cg.actors[1].ncalls.remote(), timeout=60) == 21
    finally:
        cg.teardown()


def test_compiled_diamond_constants_kwargs(compiled_cluster):
    with InputNode() as inp:
        s = _Stage.bind(1)
        j = _Join.bind()
        dag = j.join.bind(s.fwd.bind(inp), s.fwd.bind(inp), bias=100)
    cg = dag.experimental_compile(max_inflight=3)
    try:
        assert cg.execute(2).get(timeout=60) == 106
        assert cg.execute(0).get(timeout=60) == 102
    finally:
        cg.teardown()


def test_compiled_pipelined_inflight_and_order(compiled_cluster):
    with InputNode() as inp:
        dag = _Stage.bind(1).fwd.bind(inp)
    cg = dag.experimental_compile(max_inflight=2)
    try:
        # submit more than max_inflight; execute() drains completed
        # results into the buffer instead of deadlocking on the ring
        refs = [cg.execute(i) for i in range(10)]
        assert [r.get(timeout=60) for r in refs] == list(range(1, 11))
        # out-of-submission-order gets are served from the buffer
        r0 = cg.execute(100)
        r1 = cg.execute(200)
        assert r1.get(timeout=60) == 201
        assert r0.get(timeout=60) == 101
    finally:
        cg.teardown()


def test_compiled_node_error_propagates_and_graph_survives(compiled_cluster):
    with InputNode() as inp:
        dag = _Stage.bind(10).fwd.bind(_Stage.bind(0).fwd.bind(inp))
    cg = dag.experimental_compile(max_inflight=2)
    try:
        with pytest.raises(RayTaskError, match="expected-failure"):
            cg.execute("boom").get(timeout=60)
        # the error flowed through the downstream node as a value: the
        # loops are still alive and the next execution succeeds
        assert cg.execute(1).get(timeout=60) == 11
    finally:
        cg.teardown()


def test_compiled_teardown_idempotent_and_rejects_use(compiled_cluster):
    with InputNode() as inp:
        dag = _Stage.bind(1).fwd.bind(inp)
    cg = dag.experimental_compile(max_inflight=2)
    assert cg.execute(1).get(timeout=60) == 2
    cg.teardown()
    cg.teardown()  # second teardown is a no-op, not an error
    from ray_tpu.dag import CompiledGraphError

    with pytest.raises(CompiledGraphError, match="torn down"):
        cg.execute(1)


def test_compiled_graph_validation(compiled_cluster):
    from ray_tpu.dag import CompiledGraphError

    @ray_tpu.remote
    def plain_task(x):
        return x

    with pytest.raises(CompiledGraphError, match="actor method"):
        plain_task.bind(1).experimental_compile()

    with InputNode() as inp:
        nested = _Stage.bind(0).fwd.bind([inp])  # node inside a container
    with pytest.raises(CompiledGraphError, match="top-level"):
        nested.experimental_compile()


def test_compiled_chaos_actor_kill_types_error_no_hang(compiled_cluster):
    """test_chaos.py-style: SIGKILL a mid-graph actor while an execution
    is in flight — the caller gets a typed error within the channel
    timeout (never a hang) and teardown is clean afterwards."""
    with InputNode() as inp:
        a, b, c = _Stage.bind(0), _Stage.bind(0), _Stage.bind(0)
        dag = c.fwd.bind(b.fwd.bind(a.fwd.bind(inp)))
    cg = dag.experimental_compile(max_inflight=2)
    assert cg.execute(1).get(timeout=60) == 1
    ref = cg.execute("slow")  # wedges the middle stage for 15s
    time.sleep(0.5)
    ray_tpu.kill(cg.actors[1])
    t0 = time.monotonic()
    with pytest.raises(ActorDiedError, match="died or restarted"):
        ref.get(timeout=60)
    assert time.monotonic() - t0 < 30, "death detection took too long"
    cg.teardown()  # must not raise with a dead participant
    cg.teardown()


def test_compiled_mid_chain_poison_cascades_no_hang(compiled_cluster):
    """A mid-chain channel poisoned outside teardown (the loop-death
    shape): every downstream loop must cascade the poison, and the
    driver's get/execute must raise typed errors, never spin."""
    from ray_tpu.dag import CompiledGraphError

    with InputNode() as inp:
        dag = _Stage.bind(1).fwd.bind(_Stage.bind(1).fwd.bind(inp))
    cg = dag.experimental_compile(max_inflight=2)
    try:
        assert cg.execute(1).get(timeout=60) == 3
        mid = next(e for e in cg._edges
                   if e["writer"] == 0 and e["reader"] == 1)
        ch = ShmChannel.attach(mid["name"])
        ch.poison()
        ch.close()
        ref = cg.execute(5)
        with pytest.raises(CompiledGraphError, match="broken"):
            ref.get(timeout=30)
        with pytest.raises(CompiledGraphError, match="broken"):
            for _ in range(20):  # outlast any in-flight channel capacity
                cg.execute(6)
    finally:
        cg.teardown()


def test_compiled_events_merge_into_timeline(compiled_cluster):
    from ray_tpu.experimental.state import api as state
    from ray_tpu.util.timeline import merged_timeline

    with InputNode() as inp:
        dag = _Stage.bind(1).fwd.bind(inp)
    cg = dag.experimental_compile(max_inflight=2)
    try:
        for i in range(3):
            cg.execute(i).get(timeout=60)
        # driver-side spans (compile, result waits) land in the head ring
        # immediately; worker-side node spans arrive with the pusher
        deadline = time.monotonic() + 20
        rows = []
        while time.monotonic() < deadline:
            rows = state.list_events(source="compiled_dag", limit=10_000)
            if any(r.get("span_dur") for r in rows):
                break
            time.sleep(0.5)
        assert rows, "no compiled_dag events reached the head table"
        trace = merged_timeline([], rows)
        slices = [e for e in trace
                  if e.get("cat") == "compiled_dag" and e.get("ph") == "X"]
        assert slices, "compiled_dag spans missing from the chrome trace"
        assert any(e["pid"] == "recorder:compiled_dag" for e in slices)
    finally:
        cg.teardown()


# ---------------------------------------------------------------------------
# workload proofs
# ---------------------------------------------------------------------------


def test_microbatch_pipeline_schedule(compiled_cluster):
    from ray_tpu.parallel.pipeline import MicrobatchPipeline

    @ray_tpu.remote
    class Add:
        def __init__(self, k):
            self.k = k

        def run(self, x):
            time.sleep(0.05)
            return x + self.k

    pipe = MicrobatchPipeline([Add.bind(1), Add.bind(10), Add.bind(100)],
                              n_microbatches=6)
    try:
        t0 = time.perf_counter()
        out = pipe.run(list(range(6)), timeout=120)
        wall = time.perf_counter() - t0
        assert out == [i + 111 for i in range(6)]
        # serial = S*M*0.05 = 0.9s; the pipelined schedule is
        # (M+S-1)*0.05 = 0.4s.  Assert the stages actually overlapped.
        assert wall < 0.8, f"no pipeline overlap: wall={wall:.2f}s"
    finally:
        pipe.teardown()


def test_prefill_decode_compiled_graph(compiled_cluster):
    from ray_tpu.serve.llm import prefill_decode_graph

    g = prefill_decode_graph(max_new_tokens=3, prefill_bucket=8)
    try:
        out1 = g.execute([1, 2, 3]).get(timeout=300)
        assert len(out1) == 3 and all(isinstance(t, int) for t in out1)
        # greedy decoding: same prompt -> same tokens
        assert ray_tpu.get(g.execute([1, 2, 3]), timeout=300) == out1
    finally:
        g.teardown()


# ---------------------------------------------------------------------------
# cross-node: stream channels over a real agent process
# ---------------------------------------------------------------------------


def test_compiled_graph_cross_node_stream_edges():
    """Two stages pinned to different REAL nodes (private shm namespaces):
    the edge between them must come up as a stream channel and the graph
    must still round-trip."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "num_tpus": 0},
                      real_processes=True)
    try:
        node_b = cluster.add_node(num_cpus=2)
        head = cluster.node_ids[0]

        with InputNode() as inp:
            s1 = _Stage.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(head)
            ).bind(1)
            s2 = _Stage.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(node_b)
            ).bind(10)
            dag = s2.fwd.bind(s1.fwd.bind(inp))
        cg = dag.experimental_compile(max_inflight=2)
        try:
            assert any(e["kind"] == "stream" for e in cg._edges), \
                "cross-node edge did not use the stream transport"
            for i in range(5):
                assert cg.execute(i).get(timeout=120) == i + 11
        finally:
            cg.teardown()
    finally:
        cluster.shutdown()
