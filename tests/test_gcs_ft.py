"""GCS persistence, pubsub, and node health checking.

Reference surfaces: StoreClient persistence + GCS replay
(``store_client/redis_store_client.h:28``, ``gcs_init_data.h:29``),
pubsub channels (``src/ray/pubsub/``), active health checking
(``gcs_health_check_manager.h:39``).
"""

import os
import signal
import threading
import time

import pytest

import ray_tpu


def test_gcs_persistence_replay(tmp_path):
    """KV + control-plane history survive a head restart; prior live
    entities come back DEAD (their processes died with the old head)."""
    db = str(tmp_path / "gcs.db")

    ray_tpu.init(num_cpus=2, _gcs_persistence_path=db)

    @ray_tpu.remote
    class Keeper:
        def ping(self):
            return 1

    k = Keeper.remote()
    assert ray_tpu.get(k.ping.remote(), timeout=60) == 1

    @ray_tpu.remote
    def job(x):
        return x * 2

    assert ray_tpu.get(job.remote(21), timeout=60) == 42
    from ray_tpu._private.worker import global_worker

    node = global_worker.node
    node.gcs.kv_put("app", b"config", b"v2-settings")
    node.gcs.flush(node.gcs_store)
    ray_tpu.shutdown()

    # second head over the same store
    ray_tpu.init(num_cpus=2, _gcs_persistence_path=db)
    try:
        node2 = ray_tpu._private.worker.global_worker.node
        assert node2.gcs.kv_get("app", b"config") == b"v2-settings"
        actors = list(node2.gcs.actors.values())
        assert any(a.class_name == "Keeper" and a.state == "DEAD"
                   and a.death_cause == "head restarted" for a in actors)
        tasks = list(node2.gcs.tasks.values())
        assert any(t.name == "job" and t.state == "FINISHED" for t in tasks)
        # the new head still works
        @ray_tpu.remote
        def f():
            return "alive"

        assert ray_tpu.get(f.remote(), timeout=60) == "alive"
    finally:
        ray_tpu.shutdown()


def test_pubsub_app_channel(ray_start_regular):
    from ray_tpu.util import pubsub

    got = []
    ev = threading.Event()

    def cb(data):
        got.append(data)
        ev.set()

    pubsub.subscribe("my_channel", cb)
    time.sleep(0.2)  # subscription registration in flight

    @ray_tpu.remote
    def announce():
        from ray_tpu.util import pubsub as p

        p.publish("my_channel", {"from": "worker", "n": 7})
        return 1

    assert ray_tpu.get(announce.remote(), timeout=60) == 1
    assert ev.wait(20)
    assert got[0] == {"from": "worker", "n": 7}


def test_pubsub_error_channel(ray_start_regular):
    from ray_tpu.util import pubsub

    errors = []
    ev = threading.Event()
    pubsub.subscribe("error", lambda d: (errors.append(d), ev.set()))
    time.sleep(0.2)

    @ray_tpu.remote(max_retries=0)
    def boom():
        raise RuntimeError("kaboom")

    with pytest.raises(Exception):
        ray_tpu.get(boom.remote(), timeout=60)
    assert ev.wait(20)
    assert any("boom" in (e.get("task") or "") for e in errors)


def test_pubsub_node_change_channel(ray_start_regular):
    from ray_tpu.util import pubsub

    events = []
    pubsub.subscribe("node_change", events.append)
    time.sleep(0.2)

    from ray_tpu._private.worker import global_worker

    # spawn a real agent against the live head
    import subprocess
    import sys
    import tempfile

    host, port = global_worker.node.tcp_address
    shm_sub = tempfile.mkdtemp(prefix="rtpu-pubsubtest-", dir="/dev/shm")
    env = dict(os.environ)
    env["RAY_TPU_AUTHKEY"] = global_worker.node.authkey.hex()
    proc = subprocess.Popen(
        [sys.executable, "-m", "ray_tpu._private.node_agent",
         "--address", f"{host}:{port}", "--node-id", "pubsub-node",
         "--num-cpus", "1", "--shm-dir", shm_sub], env=env)
    try:
        deadline = time.time() + 30
        while time.time() < deadline:
            if any(e.get("node_id") == "pubsub-node" and e.get("alive") for e in events):
                break
            time.sleep(0.1)
        assert any(e.get("node_id") == "pubsub-node" and e.get("alive") for e in events)
        proc.kill()
        deadline = time.time() + 30
        while time.time() < deadline:
            if any(e.get("node_id") == "pubsub-node" and not e.get("alive") for e in events):
                break
            time.sleep(0.1)
        assert any(e.get("node_id") == "pubsub-node" and not e.get("alive") for e in events)
    finally:
        if proc.poll() is None:
            proc.kill()
        import shutil

        shutil.rmtree(shm_sub, ignore_errors=True)


def test_health_check_detects_hung_agent(monkeypatch):
    """SIGSTOP an agent: the TCP conn stays open but pongs stop — the
    health prober must declare the node dead within the timeout."""
    os.environ["RAY_TPU_HEALTH_CHECK_TIMEOUT_S"] = "4"
    os.environ["RAY_TPU_HEALTH_CHECK_PERIOD_S"] = "1"
    import ray_tpu._private.config as cfg_mod

    cfg_mod._config = None  # re-read env overrides
    try:
        from ray_tpu.cluster_utils import Cluster

        cluster = Cluster(initialize_head=True,
                          head_node_args={"num_cpus": 2}, real_processes=True)
        try:
            node_b = cluster.add_node(num_cpus=1)
            agent_proc = cluster.agents[node_b]
            os.kill(agent_proc.pid, signal.SIGSTOP)  # hung, not dead
            from ray_tpu._private.worker import global_worker

            head = global_worker.node
            deadline = time.time() + 60
            while time.time() < deadline:
                with head.lock:
                    if not head.nodes[node_b].alive:
                        break
                time.sleep(0.3)
            with head.lock:
                assert not head.nodes[node_b].alive, "hung node never failed health check"
            os.kill(agent_proc.pid, signal.SIGCONT)
        finally:
            cluster.shutdown()
    finally:
        os.environ.pop("RAY_TPU_HEALTH_CHECK_TIMEOUT_S", None)
        os.environ.pop("RAY_TPU_HEALTH_CHECK_PERIOD_S", None)
        cfg_mod._config = None
