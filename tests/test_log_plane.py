"""Cluster log plane: capture (context-stamped redirect), ship
(rotation-safe tailing, rate limiting), store (rings, retirement,
bursts), and the consume surfaces (state API, driver streaming, trace
join, doctor rules).

Reference behaviors: ``python/ray/_private/log_monitor.py`` (rotation-
safe tailing), ``worker.print_to_stdstream`` (driver re-emission with
``(name pid=… node=…)`` prefixes), ``ray logs`` (state API log surface).
"""

import os
import time

import pytest

import ray_tpu
from ray_tpu._private import log_plane
from ray_tpu._private.log_plane import (
    ContextStampingStream,
    LogMonitor,
    _RotatingFile,
    format_stamp,
    parse_line,
)
from ray_tpu.util.log_store import LogStore


@pytest.fixture
def fast_ship(monkeypatch):
    """Boot the runtime with a fast ship cadence so tests wait ~0.2s,
    not the production 1s, for records to reach the head."""
    monkeypatch.setenv("RAY_TPU_LOG_SHIP_S", "0.1")
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


def _wait_for(fn, timeout=20.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = fn()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError(f"condition not met within {timeout}s: {fn}")


# ---------------------------------------------------------------------------
# stamp protocol
# ---------------------------------------------------------------------------

def test_stamp_roundtrip():
    s = format_stamp("o") + "hello world"
    src, job, task, actor, trace, text = parse_line(s)
    assert src == "o" and text == "hello world"

    # unstamped lines (C-level writes) keep the stream's default src
    assert parse_line("plain", "e") == ("e", "", "", "", "", "plain")
    # a corrupt stamp degrades to an unstamped line, never an exception
    assert parse_line("\x1frt1|broken")[5] == "\x1frt1|broken"


def test_stamp_tracks_context_epoch():
    from ray_tpu._private.worker import global_worker as gw

    old_task = gw.current_task_id
    try:
        gw.current_task_id = b"\xab\xcd"
        assert parse_line(format_stamp("o") + "x")[2] == "abcd"
        # the cached stamp must be invalidated by the setter
        gw.current_task_id = b"\x12\x34"
        assert parse_line(format_stamp("o") + "x")[2] == "1234"
        gw.current_task_id = None
        assert parse_line(format_stamp("o") + "x")[2] == ""
    finally:
        gw.current_task_id = old_task


def _stamped_stream(tmp_path, name="out.log", rotate=1 << 30):
    path = str(tmp_path / name)
    fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_APPEND)
    rot = _RotatingFile(path, rotate, fds=(fd,))
    return path, fd, ContextStampingStream(fd, "o", rot)


def test_stamping_stream_print_shapes(tmp_path):
    path, fd, st = _stamped_stream(tmp_path)
    try:
        print("one line", file=st)              # write(text) + write("\n")
        st.write("single call line\n")          # one complete line
        st.write("partial ")                    # three-part line
        st.write("continued")
        st.write(" end\n")
        st.write("a\nb\nc\n")                   # several lines in one call
        st.write("multi with tail\npartial2")   # complete + trailing partial
        st.flush()
    finally:
        os.close(fd)

    lines = open(path).read().splitlines()
    parsed = [parse_line(ln) for ln in lines]
    texts = [p[5] for p in parsed]
    assert texts == ["one line", "single call line", "partial continued end",
                     "a", "b", "c", "multi with tail", "partial2"]
    # every line got exactly one stamp (split lines included)
    assert all(p[0] == "o" for p in parsed)
    assert not any("\x1f" in t for t in texts)


def test_stamping_stream_write_record(tmp_path):
    path, fd, st = _stamped_stream(tmp_path)
    try:
        st.write("partial print ")
        st.write_record("E", "logger error line")
        st.flush()
    finally:
        os.close(fd)
    lines = open(path).read().splitlines()
    # the pending partial was terminated, then the record written with
    # its own level src
    assert parse_line(lines[0])[5] == "partial print "
    assert parse_line(lines[1])[0] == "E"
    assert parse_line(lines[1])[5] == "logger error line"


def test_rotating_file_caps_and_keeps_backup(tmp_path):
    path, fd, st = _stamped_stream(tmp_path, rotate=2000)
    try:
        for i in range(200):
            st.write(f"line number {i:04d} with padding text\n")
    finally:
        os.close(fd)
    assert os.path.exists(path + ".1")
    assert os.path.getsize(path) < 4000  # bounded, not unbounded growth
    # the union of current + backup holds a contiguous recent suffix
    all_lines = open(path + ".1").read() + open(path).read()
    assert "line number 0199" in all_lines


# ---------------------------------------------------------------------------
# LogMonitor: rotation-safe tailing
# ---------------------------------------------------------------------------

def _mk_monitor(shipped):
    return LogMonitor("test-node",
                      ingest_fn=lambda origin, recs, metas: shipped.extend(recs))


def test_monitor_tails_and_parses(tmp_path):
    path = str(tmp_path / "w.log")
    shipped = []
    mon = _mk_monitor(shipped)
    mon.register("w", path, pid=123)
    open(path, "a").write(format_stamp("o") + "hello\nunstamped\n")
    assert mon.poll_once() == 2
    assert shipped[0][log_plane.REC_LINE] == "hello"
    assert shipped[1][log_plane.REC_SRC] == "o"
    # nothing new -> nothing re-shipped
    assert mon.poll_once() == 0


def test_monitor_survives_rotation_without_loss(tmp_path):
    path = str(tmp_path / "w.log")
    shipped = []
    mon = _mk_monitor(shipped)
    mon.register("w", path)

    with open(path, "a") as f:
        for i in range(10):
            f.write(f"pre {i}\n")
    mon.poll_once()
    # rotate under the tailer: old inode renamed, fresh file at path
    with open(path, "a") as f:
        f.write("old tail line\n")
    os.replace(path, path + ".1")
    with open(path, "a") as f:
        for i in range(5):
            f.write(f"post {i}\n")
    mon.poll_once()  # drains old fd fully, detects rotation, reopens
    mon.poll_once()  # reads the new inode from offset 0

    texts = [r[log_plane.REC_LINE] for r in shipped]
    expected = [f"pre {i}" for i in range(10)] + ["old tail line"] + \
        [f"post {i}" for i in range(5)]
    assert texts == expected  # no line lost, none shipped twice


def test_monitor_rotation_terminates_partial_line(tmp_path):
    path = str(tmp_path / "w.log")
    shipped = []
    mon = _mk_monitor(shipped)
    mon.register("w", path)
    with open(path, "a") as f:
        f.write("no newline yet")  # partial at rotation time
    mon.poll_once()
    os.replace(path, path + ".1")
    open(path, "a").write("new file line\n")
    mon.poll_once()
    mon.poll_once()
    texts = [r[log_plane.REC_LINE] for r in shipped]
    # the old file's dangling partial became its final line
    assert texts == ["no newline yet", "new file line"]


def test_monitor_survives_truncation(tmp_path):
    path = str(tmp_path / "w.log")
    shipped = []
    mon = _mk_monitor(shipped)
    mon.register("w", path)
    with open(path, "a") as f:
        f.write("a\nb\n")
    mon.poll_once()
    os.truncate(path, 0)  # copytruncate-style rotation
    mon.poll_once()       # shrink observed: offset resets to 0
    with open(path, "a") as f:
        f.write("after truncate\n")
    mon.poll_once()
    texts = [r[log_plane.REC_LINE] for r in shipped]
    assert texts == ["a", "b", "after truncate"]


def test_monitor_rate_limit_suppression_marker(tmp_path):
    path = str(tmp_path / "w.log")
    shipped = []
    mon = LogMonitor(
        "test-node", rate_lps=5,
        ingest_fn=lambda origin, recs, metas: shipped.extend(recs))
    mon.register("w", path)
    with open(path, "a") as f:
        for i in range(100):
            f.write(f"spam {i}\n")
    t0 = time.time()
    mon.poll_once(now=t0)
    # bucket starts with one second's budget: 5 lines passed, 95 counted
    assert len([r for r in shipped if r[log_plane.REC_SRC] != "m"]) == 5
    # tokens recover after a quiet second -> one marker with the count
    with open(path, "a") as f:
        f.write("after storm\n")
    mon.poll_once(now=t0 + 2.0)
    markers = [r for r in shipped if r[log_plane.REC_SRC] == "m"]
    assert len(markers) == 1
    assert "(suppressed 95 lines)" in markers[0][log_plane.REC_LINE]
    assert shipped[-1][log_plane.REC_LINE] == "after storm"


def test_monitor_unregister_final_drain(tmp_path):
    """The death-tail guarantee: unregister ships everything the file
    gained since the last poll, including a dangling partial line."""
    path = str(tmp_path / "w.log")
    shipped = []
    mon = _mk_monitor(shipped)
    mon.register("w", path)
    mon.poll_once()
    with open(path, "a") as f:
        f.write("last words\nFatal: dying now")  # no trailing newline
    mon.unregister("w")
    texts = [r[log_plane.REC_LINE] for r in shipped]
    assert texts == ["last words", "Fatal: dying now"]
    assert "w" not in mon.streams()


# ---------------------------------------------------------------------------
# LogStore
# ---------------------------------------------------------------------------

def _rec(stream, line, src="o", job="", task="", actor="", trace="", ts=None):
    return (ts if ts is not None else time.time(),
            stream, src, job, task, actor, trace, line)


def test_store_ingest_query_filters():
    store = LogStore(max_lines_per_stream=100, max_total_bytes=1 << 20,
                     max_streams=10)
    store.ingest("node-1", [
        _rec("w1", "alpha", job="j1", task="t1"),
        _rec("w1", "beta error", src="e", job="j1", task="t2"),
        _rec("w2", "gamma", job="j2", trace="tr9"),
    ], metas={"w1": {"pid": 11}, "w2": {"pid": 22}})

    rows, cursor = store.query(task="t1")
    assert [r["line"] for r in rows] == ["alpha"]
    assert cursor == 3
    rows, _ = store.query(errors=True)
    assert [r["line"] for r in rows] == ["beta error"]
    rows, _ = store.query(grep="GAMMA")
    assert rows and rows[0]["stream"] == "w2" and rows[0]["pid"] == 22
    rows, _ = store.query(trace="tr9")
    assert len(rows) == 1
    # cursor-follow: only records past since_seq come back
    store.ingest("node-1", [_rec("w1", "delta", job="j1")])
    rows, c2 = store.query(since_seq=cursor)
    assert [r["line"] for r in rows] == ["delta"] and c2 == 4


def test_store_caps_and_retirement():
    store = LogStore(max_lines_per_stream=5, max_total_bytes=1 << 20,
                     max_streams=10)
    store.ingest("n", [_rec("w", f"line {i}") for i in range(20)])
    rows, _ = store.query(stream="w", limit=100)
    assert len(rows) == 5 and rows[0]["line"] == "line 15"
    meta = store.stats()[0]
    assert meta["total_lines"] == 20  # history count survives the ring cap

    store.retire("w")
    # retired ring stays queryable (the death-tail property)...
    assert store.tail_text("w", n=2) == ["line 18", "line 19"]
    # ...until the horizon passes
    assert store.retire_stale(0.0, now=time.time() + 10) == ["w"]
    assert "w" not in store


def test_store_byte_pressure_sheds_oldest():
    store = LogStore(max_lines_per_stream=10_000, max_total_bytes=3000,
                     max_streams=10)
    store.ingest("n", [_rec("quiet", "x" * 100) for _ in range(20)],
                 now=100.0)
    store.ingest("n", [_rec("busy", "y" * 100) for _ in range(20)],
                 now=200.0)
    # the least-recently-active stream lost records first
    quiet = [r for r in store.stats() if r["stream"] == "quiet"][0]
    busy = [r for r in store.stats() if r["stream"] == "busy"][0]
    assert quiet["lines"] < busy["lines"]


def test_store_error_burst_emits_event():
    events = []
    store = LogStore(max_lines_per_stream=1000, max_total_bytes=1 << 20,
                     max_streams=10, burst_n=5, burst_window_s=30.0,
                     emit_fn=lambda *a, **k: events.append((a, k)))
    now = time.time()
    store.ingest("n", [_rec("w", f"err {i}", src="e", ts=now)
                       for i in range(6)], now=now)
    assert len(events) == 1
    (source, message), kw = events[0]
    assert source == "log" and "error burst" in message
    assert kw["entity_id"] == "w"
    # cooldown: an immediately following burst doesn't double-fire
    store.ingest("n", [_rec("w", f"err2 {i}", src="e", ts=now)
                       for i in range(6)], now=now + 1)
    assert len(events) == 1


# ---------------------------------------------------------------------------
# doctor rules
# ---------------------------------------------------------------------------

def test_doctor_log_rules_fire_and_stay_silent():
    from ray_tpu.util.doctor import diagnose

    assert diagnose([], []) == []  # healthy gate: no events, no findings

    burst = {"source": "log", "severity": "WARNING",
             "message": "error burst: 60 error/traceback lines in 30s "
                        "from worker-ab", "entity_id": "worker-ab"}
    death = {"source": "log", "severity": "ERROR",
             "message": "worker died with uncollected stderr: exited with "
                        "code -9",
             "entity_id": "ab", "data": {"tail": ["Fatal: boom"]}}
    findings = diagnose([burst, death], [])
    rules = {f["rule"]: f for f in findings}
    assert "log_error_burst" in rules
    assert "worker-ab" in rules["log_error_burst"]["summary"]
    assert "worker_stderr_at_death" in rules
    assert rules["worker_stderr_at_death"]["severity"] == "ERROR"
    assert "Fatal: boom" in rules["worker_stderr_at_death"]["summary"]

    # unrelated log-source events (stream retirement) fire neither rule
    quiet = {"source": "log", "severity": "DEBUG",
             "message": "log stream retired", "entity_id": "w"}
    assert diagnose([quiet], []) == []


# ---------------------------------------------------------------------------
# end-to-end: print() -> capture -> ship -> store -> consume surfaces
# ---------------------------------------------------------------------------

def test_worker_print_correlated_end_to_end(fast_ship):
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    def chatty():
        print("needle-from-task")
        return ray_tpu.get_runtime_context().task_id

    task_id = ray_tpu.get(chatty.remote(), timeout=120).hex()

    rows = _wait_for(lambda: state.get_log(grep="needle-from-task")["records"])
    r = rows[0]
    assert r["task"] == task_id      # a plain print() carries the task id
    assert r["stream"].startswith("worker-")
    assert r["src"] == "o"
    # the same record is reachable via the task filter and the stream list
    assert state.get_log(task=task_id)["records"]
    streams = {row["stream"] for row in state.list_logs()}
    assert r["stream"] in streams


def test_actor_stderr_and_logger_records(fast_ship):
    import sys

    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    class Talker:
        def speak(self):
            print("to-stderr-needle", file=sys.stderr)
            from ray_tpu._private.logging_utils import get_logger
            get_logger("ray_tpu.testmod").warning("logger-needle")
            return ray_tpu.get_runtime_context().actor_id

    a = Talker.remote()
    actor_id = ray_tpu.get(a.speak.remote(), timeout=120).hex()

    err = _wait_for(
        lambda: state.get_log(grep="to-stderr-needle", errors=True)["records"])
    assert err[0]["actor"] == actor_id
    logged = _wait_for(lambda: state.get_log(grep="logger-needle")["records"])
    assert logged[0]["src"] == "W"   # logger level rode the stamp
    assert state.get_log(actor=actor_id)["records"]


def test_trace_join(fast_ship):
    from ray_tpu.experimental.state import api as state
    from ray_tpu.util import tracing

    @ray_tpu.remote
    def traced_work():
        print("trace-needle-line")
        return 1

    with tracing.trace("log-join-test") as ctx:
        ray_tpu.get(traced_work.remote(), timeout=120)
    trace_id = ctx["trace_id"]

    rows = _wait_for(lambda: state.get_log(trace=trace_id)["records"])
    assert any("trace-needle-line" in r["line"] for r in rows)
    trace = _wait_for(lambda: state.get_trace(trace_id))
    assert any("trace-needle-line" in r["line"]
               for r in trace.get("logs", []))


def test_driver_stream_and_follow_cursor(fast_ship):
    """The driver-side consume path: a job subscriber sees shipped
    records (prefixed re-emission is make_driver_log_callback), and the
    get_log cursor follows incrementally (the --follow loop)."""
    from ray_tpu._private.log_plane import make_driver_log_callback
    from ray_tpu._private.worker import global_worker
    from ray_tpu.experimental.state import api as state

    got = []
    cb = make_driver_log_callback(out_fn=got.append)
    global_worker.client.subscribe(
        f"logs:{global_worker.job_id}", cb)

    @ray_tpu.remote
    def noisy():
        print("driver-stream-needle")

    ray_tpu.get(noisy.remote(), timeout=120)
    _wait_for(lambda: any("driver-stream-needle" in s for s in got))
    line = next(s for s in got if "driver-stream-needle" in s)
    # reference print_to_stdstream prefix shape: "(name pid=…, node=…)"
    assert line.startswith("(worker-") and "pid=" in line and "node=" in line

    cursor = state.get_log(grep="driver-stream-needle")["cursor"]
    ray_tpu.get(noisy.remote(), timeout=120)
    fresh = _wait_for(lambda: state.get_log(
        grep="driver-stream-needle", since_seq=cursor)["records"])
    assert all(r["seq"] > cursor for r in fresh)


def test_sigkill_worker_stderr_retrievable_after_death(fast_ship):
    """Acceptance: a SIGKILL'd worker's last stderr lines are retrievable
    from the head after the process is gone."""
    import sys

    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote(max_retries=0)
    def doomed():
        print("final-stderr-needle before the bullet", file=sys.stderr)
        sys.stderr.flush()
        os.kill(os.getpid(), 9)

    with pytest.raises(Exception):
        ray_tpu.get(doomed.remote(), timeout=120)

    rows = _wait_for(lambda: state.get_log(
        grep="final-stderr-needle", errors=True)["records"])
    stream = rows[0]["stream"]
    # the stream is retired (its worker is dead) but its tail still serves
    meta = _wait_for(lambda: [
        s for s in state.list_logs() if s["stream"] == stream])[0]
    assert meta["retired"]
    tail = state.tail_log(stream, n=50, errors=True)
    assert any("final-stderr-needle" in ln for ln in tail)


def test_job_logs_unified_surface(fast_ship):
    """The job driver's log and `ray_tpu logs job-<id>` read the same
    store-backed surface (with on-disk fallback for aged-out rings)."""
    from ray_tpu.experimental.state import api as state
    from ray_tpu.job_submission import JobSubmissionClient

    client = JobSubmissionClient()
    job_id = client.submit_job(
        entrypoint="python -c \"print('job-driver-needle')\"")
    status = client.wait_until_finish(job_id, timeout=120)
    assert status == "SUCCEEDED"
    rows = _wait_for(lambda: state.get_log(
        stream=f"job-{job_id}", limit=1000)["records"])
    assert any("job-driver-needle" in r["line"] for r in rows)
    # the legacy job-logs surface reads the same records
    assert "job-driver-needle" in client.get_job_logs(job_id)


def test_cross_node_print_reaches_head_and_driver(monkeypatch, capsys):
    """Acceptance: a plain print() on an emulated remote node (real agent
    process, own shm/session namespace) lands in the head store with that
    node's id and is re-emitted at the driver within a ship interval."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.experimental.state import api as state
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    monkeypatch.setenv("RAY_TPU_LOG_SHIP_S", "0.1")
    cluster = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 2, "num_tpus": 0},
        real_processes=True,
    )
    try:
        node_b = cluster.add_node(num_cpus=2)

        @ray_tpu.remote(
            scheduling_strategy=NodeAffinitySchedulingStrategy(node_b))
        class RemoteTalker:
            def speak(self):
                print("cross-node-needle")
                return ray_tpu.get_runtime_context().node_id

        a = RemoteTalker.remote()
        assert ray_tpu.get(a.speak.remote(), timeout=120) == node_b

        rows = _wait_for(
            lambda: state.get_log(grep="cross-node-needle")["records"])
        assert rows[0]["node"] == node_b  # shipped by node B's agent
        assert rows[0]["actor"]          # actor id rode the stamp
        # driver re-emission carries the remote node id in its prefix
        # (readouterr drains, so accumulate across polls)
        chunks = []

        def _saw_line():
            chunks.append(capsys.readouterr().out)
            return [ln for ln in "".join(chunks).splitlines()
                    if "cross-node-needle" in ln and ln.startswith("(")]

        line = _wait_for(_saw_line, timeout=15)[0]
        assert f"node={node_b}" in line
    finally:
        cluster.shutdown()


def test_disabled_plane_keeps_plain_capture(tmp_path, monkeypatch):
    """RAY_TPU_LOG_PLANE=0: the redirect still captures (crash trail) but
    lines are unstamped and no monitor ships them."""
    import subprocess
    import sys

    code = (
        "import os, sys\n"
        "from ray_tpu._private.log_plane import redirect_process_output\n"
        f"redirect_process_output({str(tmp_path / 'cap.log')!r})\n"
        "print('disabled-path line')\n"
        "sys.stdout.flush()\n"
    )
    env = dict(os.environ)
    env["RAY_TPU_LOG_PLANE"] = "0"
    subprocess.run([sys.executable, "-c", code], env=env, check=True,
                   timeout=120)
    content = open(tmp_path / "cap.log").read()
    assert "disabled-path line" in content
    assert "\x1f" not in content


def test_cli_logs_command(fast_ship, capsys):
    from ray_tpu.scripts import cli

    @ray_tpu.remote
    def printer():
        print("cli-logs-needle")

    ray_tpu.get(printer.remote(), timeout=120)
    from ray_tpu.experimental.state import api as state

    _wait_for(lambda: state.get_log(grep="cli-logs-needle")["records"])

    cli.main(["logs"])  # stream table
    table = capsys.readouterr().out
    assert "STREAM" in table and "worker-" in table

    cli.main(["logs", "--grep", "cli-logs-needle"])
    out = capsys.readouterr().out
    assert "cli-logs-needle" in out and out.strip().startswith("(worker-")
