"""Round-4 cluster tooling: YAML cluster launcher (``ray up/down``
analog), remote experiment storage sync, dashboard on-demand profiling,
and multi-node chaos (agent SIGKILL under load)."""

import json
import os
import subprocess
import tempfile
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu


# ---------------------------------------------------------------------------
# cluster launcher
# ---------------------------------------------------------------------------


def test_cluster_up_down_local_provider(tmp_path):
    """`ray_tpu up` from a YAML with the local provider: a real head
    process + a real worker agent, then `down` reaps both."""
    from ray_tpu.autoscaler.commands import down, load_cluster_config, up

    cfg_path = tmp_path / "cluster.yaml"
    cfg_path.write_text(
        "cluster_name: lt\n"
        "provider: {type: local}\n"
        "head_node: {address: 127.0.0.1, num_cpus: 2, num_tpus: 0}\n"
        "worker_nodes:\n"
        "  - {address: 127.0.0.1, num_cpus: 1, num_tpus: 0}\n"
    )
    config = load_cluster_config(str(cfg_path))
    out = up(config)
    try:
        assert out["address"].startswith("tcp://")
        assert len(out["workers"]) == 1
        # join the launched cluster as a driver and see BOTH nodes
        ray_tpu.init(address="auto")
        deadline = time.time() + 60
        while time.time() < deadline:
            if len(ray_tpu.nodes()) >= 2:
                break
            time.sleep(0.5)
        assert len(ray_tpu.nodes()) >= 2, ray_tpu.nodes()

        @ray_tpu.remote
        def ping():
            return "up"

        assert ray_tpu.get(ping.remote(), timeout=120) == "up"
        ray_tpu.shutdown()
    finally:
        down(config)
    # the head process is gone (or a zombie — this container's pid 1 does
    # not reap orphans, and a zombie still answers os.kill(pid, 0))
    time.sleep(1.5)
    sess = json.loads(open("/tmp/ray_tpu/last_session.json").read())
    pid = sess["pid"]
    try:
        with open(f"/proc/{pid}/stat") as f:
            state = f.read().rsplit(")", 1)[-1].split()[0]
        assert state == "Z", f"head pid {pid} still running (state {state})"
    except FileNotFoundError:
        pass  # fully reaped


def test_ssh_runner_command_shape():
    """SSHCommandRunner builds a correct ssh argv (no ssh daemon here —
    verified against /bin/echo as the transport)."""
    from ray_tpu.autoscaler.commands import SSHCommandRunner

    r = SSHCommandRunner(ssh_user="alice", ssh_private_key="/k.pem")
    captured = {}

    def fake_run(argv, **kw):
        captured["argv"] = argv

        class P:
            returncode = 0
            stdout = "ok"
            stderr = ""

        return P()

    import ray_tpu.autoscaler.commands as cmds

    orig = cmds.subprocess.run
    cmds.subprocess.run = fake_run
    try:
        r.run("10.0.0.5", "echo hi")
    finally:
        cmds.subprocess.run = orig
    argv = captured["argv"]
    assert argv[0] == "ssh" and "alice@10.0.0.5" in argv
    assert "-i" in argv and "/k.pem" in argv
    assert argv[-1] == "echo hi"


# ---------------------------------------------------------------------------
# remote experiment storage
# ---------------------------------------------------------------------------


def test_tune_syncs_experiment_to_storage_uri(ray_start_regular, tmp_path):
    from ray_tpu import tune
    from ray_tpu.air import RunConfig, remote_storage

    root = str(tmp_path / "cloud")
    remote_storage.register_filesystem(
        "mock", remote_storage.DirBackedFilesystem(root))

    def trainable(config):
        from ray_tpu.air import session

        session.report({"score": config["x"] * 2, "done": True})

    tuner = tune.Tuner(
        trainable,
        param_space={"x": tune.grid_search([1, 2])},
        run_config=RunConfig(storage_path="mock://bucket/exps", name="e1"),
    )
    results = tuner.fit()
    assert len(results) == 2
    synced = os.path.join(root, "bucket", "exps", "e1")
    assert os.path.isfile(os.path.join(synced, "experiment_state.pkl"))

    state_file = os.path.join(synced, "experiment_state.pkl")
    mtime = os.path.getmtime(state_file)
    time.sleep(0.05)
    restored = tune.Tuner.restore("mock://bucket/exps/e1", trainable)
    grid = restored.fit()  # all trials terminal: returns immediately
    assert sorted(r.metrics["score"] for r in grid) == [2, 4]
    # a resumed run keeps syncing to the ORIGINAL URI (not just locally)
    assert os.path.getmtime(state_file) > mtime


def test_unknown_storage_scheme_is_actionable():
    from ray_tpu.air import remote_storage

    with pytest.raises(ValueError, match="register_filesystem"):
        remote_storage.upload_dir("/tmp", "s3://bucket/x")


# ---------------------------------------------------------------------------
# dashboard on-demand profiling
# ---------------------------------------------------------------------------


def test_dashboard_profile_head_and_worker(ray_start_regular):
    node = ray_tpu._private.worker.global_worker.node
    host, port = node.dashboard.address

    def get(path):
        with urllib.request.urlopen(f"http://{host}:{port}{path}", timeout=120) as r:
            return json.loads(r.read())

    head = get("/api/profile?duration=1")
    assert head["target"] == "head"
    assert head["report"] and all("stack" in row for row in head["report"])

    # keep a worker busy so its profile shows the executing frame
    @ray_tpu.remote
    def spin():
        t0 = time.time()
        while time.time() - t0 < 6:
            sum(i * i for i in range(1000))
        return "done"

    ref = spin.remote()
    time.sleep(1.5)
    workers = [w for w in get("/api/workers?limit=100")
               if w["state"] == "busy" and not w["is_actor_worker"]]
    assert workers, "no busy worker to profile"
    prof = get(f"/api/profile?duration=2&worker_id={workers[0]['worker_id']}")
    assert prof.get("report"), prof
    joined = " ".join(row["stack"] for row in prof["report"])
    assert "spin" in joined or "_execute_task" in joined, joined[:500]
    assert ray_tpu.get(ref, timeout=120) == "done"


# ---------------------------------------------------------------------------
# multi-node chaos: a whole NODE dies under load (agent SIGKILL)
# ---------------------------------------------------------------------------


def test_tasks_survive_node_agent_kill(tmp_path):
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 2, "num_tpus": 0},
        real_processes=True,
    )
    try:
        node_b = cluster.add_node(num_cpus=2)

        @ray_tpu.remote(num_cpus=1, max_retries=6)
        def slow(i):
            time.sleep(0.4)
            return i * 3

        refs = [slow.remote(i) for i in range(16)]
        time.sleep(1.2)  # let tasks spread onto node B
        proc = cluster.agents[node_b]
        proc.kill()  # SIGKILL the whole remote node mid-load
        out = ray_tpu.get(refs, timeout=240)
        assert out == [i * 3 for i in range(16)]
        # the dead node was detected and removed from membership
        deadline = time.time() + 60
        while time.time() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["Alive"]]
            if len(alive) == 1:
                break
            time.sleep(0.5)
        assert len([n for n in ray_tpu.nodes() if n["Alive"]]) == 1
    finally:
        cluster.shutdown()


def test_dashboard_web_ui_serves(ray_start_regular):
    """The single-page UI (the TS-frontend seat) renders with live tables."""
    node = ray_tpu._private.worker.global_worker.node
    host, port = node.dashboard.address
    with urllib.request.urlopen(f"http://{host}:{port}/", timeout=60) as r:
        html = r.read().decode()
    assert "<table" in html and "auto-refresh" in html
    for tab in ("nodes", "actors", "tasks", "workers"):
        assert f'"{tab}"' in html  # tab registry present


def test_dashboard_ui_escapes_interpolations(ray_start_regular):
    """Server-fed strings (log stream names, row ids, cell payloads) must
    never reach innerHTML/onclick unescaped: a job_id containing a quote
    or angle bracket would otherwise inject markup into the UI."""
    node = ray_tpu._private.worker.global_worker.node
    host, port = node.dashboard.address
    with urllib.request.urlopen(f"http://{host}:{port}/", timeout=60) as r:
        html = r.read().decode()
    # the escaping helper exists and guards the row-id attribute and cells
    assert "function esc(" in html
    assert 'data-id="${esc(id)}"' in html
    assert "${esc(cell(r[c]))}" in html
    # log stream buttons are built via createElement/textContent, not an
    # onclick string a stream name could break out of
    assert "showLog('${s.stream}')" not in html
    assert "b.onclick=()=>showLog(s.stream)" in html
    # path segments are URI-encoded before interpolation into fetch URLs
    assert "encodeURIComponent(stream)" in html
    assert "encodeURIComponent(id)" in html


# -----------------------------------------------------------------------
# round 5 dashboard depth: log viewer, drill-down details, timeline
# (reference dashboard/modules/log + client detail pages + ray timeline)


def test_dashboard_log_viewer(ray_start_regular):
    """Per-worker log files surface as streams; tailing one returns the
    worker's captured stdout."""
    import json as _json
    import time as _time

    @ray_tpu.remote
    def shout(i):
        print(f"dash-log-probe-{i}")
        return i

    assert ray_tpu.get([shout.remote(i) for i in range(2)], timeout=120) \
        == [0, 1]
    _time.sleep(0.5)
    node = ray_tpu._private.worker.global_worker.node
    host, port = node.dashboard.address

    def get(p):
        with urllib.request.urlopen(f"http://{host}:{port}{p}", timeout=60) as r:
            return r.read()

    streams = _json.loads(get("/api/logs"))
    workers = [s for s in streams if s["kind"] == "worker"]
    assert workers, streams
    texts = [get(f"/api/logs/{s['stream']}?tail=200").decode()
             for s in workers]
    assert any("dash-log-probe" in t for t in texts)
    # path traversal is rejected (urllib.error is loaded by
    # urllib.request at module scope)
    with pytest.raises(urllib.error.HTTPError):
        get("/api/logs/..%2f..%2fetc%2fpasswd")


def test_dashboard_drilldown_and_timeline(ray_start_regular):
    import json as _json

    @ray_tpu.remote
    class Probe:
        def hit(self):
            return 1

    a = Probe.remote()
    assert ray_tpu.get(a.hit.remote(), timeout=120) == 1
    node = ray_tpu._private.worker.global_worker.node
    host, port = node.dashboard.address

    def get(p):
        with urllib.request.urlopen(f"http://{host}:{port}{p}", timeout=60) as r:
            return r.read()

    tasks = _json.loads(get("/api/tasks?limit=50"))
    tid = tasks[0]["task_id"]
    detail = _json.loads(get(f"/api/tasks/{tid}"))
    assert detail["task_id"] == tid

    actors = _json.loads(get("/api/actors?limit=10"))
    aid = actors[0]["actor_id"]
    adetail = _json.loads(get(f"/api/actors/{aid}"))
    assert adetail["actor_id"] == aid
    assert "recent_tasks" in adetail

    tl = _json.loads(get("/api/timeline"))
    assert any(e.get("cat") == "task" for e in tl)
    assert all("ts" in e and "name" in e for e in tl)


def test_dashboard_per_node_stats(ray_start_regular):
    """/api/nodes rows carry live host utilization — head-local nodes
    read /proc at query time; remote nodes report via agent pongs
    (reference dashboard-agent reporter metrics)."""
    import json as _json

    node = ray_tpu._private.worker.global_worker.node
    host, port = node.dashboard.address
    with urllib.request.urlopen(f"http://{host}:{port}/api/nodes",
                                timeout=60) as r:
        rows = _json.loads(r.read())
    assert rows
    head_row = next(r for r in rows if r.get("node_id") == "node-head")
    hs = head_row["host_stats"]
    assert hs["cpu_count"] >= 1
    if os.path.exists("/proc/meminfo"):  # host_stats degrades off-Linux
        assert hs["mem_total_mb"] > 0
    assert "resource_utilization" in head_row


def test_remote_node_stats_via_agent_pong(tmp_path):
    """A REAL remote agent's pong carries host stats; they surface on
    the head's /api/nodes row for that node."""
    import json as _json

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2, "num_tpus": 0},
                      real_processes=True)
    try:
        node_b = cluster.add_node(num_cpus=1)
        node = ray_tpu._private.worker.global_worker.node
        host, port = node.dashboard.address
        deadline = time.time() + 60  # ping period is 2s
        row = None
        while time.time() < deadline:
            with urllib.request.urlopen(
                    f"http://{host}:{port}/api/nodes", timeout=60) as r:
                rows = _json.loads(r.read())
            row = next((r_ for r_ in rows if r_.get("node_id") == node_b), None)
            if row and row.get("host_stats"):
                break
            time.sleep(0.5)
        assert row and row.get("host_stats"), row
        assert row["host_stats"]["mem_total_mb"] > 0
    finally:
        cluster.shutdown()
