"""Real multi-process cluster: node agents over TCP + object transfer.

The round-1 verdict's item 5: a second node must be a real process that
registers over TCP, spawns its own workers, and serves object pulls —
matching the reference's node-join path
(``python/ray/_private/services.py:1273``) and object transfer plane
(``src/ray/object_manager/object_manager.h:117``, ``pull_manager.h:48``).
Each agent gets a private shm directory, so any cross-node read in these
tests necessarily went through a chunked pull.
"""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture
def real_cluster():
    cluster = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 2, "num_tpus": 0},
        real_processes=True,
    )
    yield cluster
    cluster.shutdown()


def test_remote_node_runs_tasks(real_cluster):
    node_b = real_cluster.add_node(num_cpus=2)

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(node_b))
    def where():
        import os

        return ray_tpu.get_runtime_context().node_id, os.getpid()

    nid, pid = ray_tpu.get(where.remote(), timeout=120)
    assert nid == node_b
    # the worker is a real separate process on "node b"
    import os

    assert pid != os.getpid()


def test_cross_node_object_transfer(real_cluster):
    """An array produced on node B is pulled to the driver, and a
    driver-put array is pulled by node B — both through the object plane
    (disjoint shm namespaces make an accidental local attach impossible)."""
    node_b = real_cluster.add_node(num_cpus=2)
    to_b = NodeAffinitySchedulingStrategy(node_b)

    @ray_tpu.remote(scheduling_strategy=to_b)
    def produce(n):
        return np.arange(n, dtype=np.float32)

    # B -> driver
    n = (64 << 20) // 4  # 64 MiB
    ref = produce.remote(n)
    arr = ray_tpu.get(ref, timeout=180)
    assert arr.shape == (n,) and float(arr[-1]) == n - 1

    # driver -> B
    payload = np.random.default_rng(0).standard_normal(1 << 20)
    big = ray_tpu.put(payload)

    @ray_tpu.remote(scheduling_strategy=to_b)
    def checksum(x):
        return float(np.sum(x))

    assert ray_tpu.get(checksum.remote(big), timeout=180) == pytest.approx(
        float(np.sum(payload))
    )

    # B -> B (second task on same node reuses the local segment)
    assert ray_tpu.get(checksum.options(scheduling_strategy=to_b).remote(big),
                       timeout=180) == pytest.approx(float(np.sum(payload)))


def test_actor_on_remote_node(real_cluster):
    node_b = real_cluster.add_node(num_cpus=2)

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(node_b))
    class Counter:
        def __init__(self):
            self.x = 0

        def incr(self):
            self.x += 1
            return self.x

        def node(self):
            return ray_tpu.get_runtime_context().node_id

    c = Counter.remote()
    assert ray_tpu.get(c.node.remote(), timeout=120) == node_b
    assert [ray_tpu.get(c.incr.remote(), timeout=60) for _ in range(3)] == [1, 2, 3]


def test_node_death_retries_elsewhere(real_cluster):
    """SIGKILL the agent: tasks retried on surviving nodes; node marked
    dead (the chaos NodeKiller scenario over a real process boundary)."""
    node_b = real_cluster.add_node(num_cpus=2)

    @ray_tpu.remote(max_retries=4)
    def slow(i):
        time.sleep(0.4)
        return i

    refs = [slow.remote(i) for i in range(8)]
    time.sleep(0.6)  # let some tasks land on node b
    real_cluster.remove_node(node_b)  # SIGKILL agent + wait for head to notice
    assert ray_tpu.get(refs, timeout=240) == list(range(8))

    node = ray_tpu._private.worker.global_worker.node
    with node.lock:
        assert not node.nodes[node_b].alive


def test_spread_across_real_nodes(real_cluster):
    real_cluster.add_node(num_cpus=2)

    @ray_tpu.remote(num_cpus=1)
    def where():
        time.sleep(0.3)
        return ray_tpu.get_runtime_context().node_id

    nodes = set(ray_tpu.get([where.remote() for _ in range(8)], timeout=240))
    assert len(nodes) == 2, f"tasks never spread: {nodes}"
