"""Continuous cluster profiling: the always-on sampler, the head
ProfileStore (rings, decay, retirement, diffs), the duty-cycled lock
timing plane, the per-task cost ledger, and the three trend doctor
rules that read them.
"""

import collections
import subprocess
import sys
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private import locks as _locks
from ray_tpu._private import sampling_profiler as sp
from ray_tpu.util.profile_store import (BUSY_CLASSES, ProfileStore,
                                        classify_stack)


# ---------------------------------------------------------------------------
# frame folding (pure)
# ---------------------------------------------------------------------------

def _deep_frame(depth):
    if depth:
        return _deep_frame(depth - 1)
    return sys._getframe()


def test_fold_frame_truncates_middle_not_root():
    """Regression: leaf→root truncation dropped the ROOTS of deep
    stacks, merging unrelated call trees at whatever mid-call frame
    landed at the cut.  Deep stacks must keep root-most and leaf-most
    frames around a mid-stack marker."""
    frame = _deep_frame(60)
    shallow_root = sp.fold_frame(sys._getframe(), 128).split("|")[0]
    folded = sp.fold_frame(frame, 24).split("|")
    assert len(folded) == 24
    assert sp.TRUNCATION_MARKER in folded
    # the root end survives: same outermost frame a shallow fold sees
    assert folded[0] == shallow_root
    # the leaf end survives: the recursion's innermost call
    assert folded[-1].endswith(":_deep_frame")
    # marker sits mid-stack with real frames on both sides
    i = folded.index(sp.TRUNCATION_MARKER)
    assert 0 < i < len(folded) - 1
    assert folded[i + 1].endswith(":_deep_frame")


def test_fold_frame_shallow_stack_untouched():
    folded = sp.fold_frame(sys._getframe(), 64)
    assert sp.TRUNCATION_MARKER not in folded
    assert folded.split("|")[-1].endswith(
        ":test_fold_frame_shallow_stack_untouched")


def test_classify_stack():
    assert classify_stack("a.py:f|selectors.py:select") == "idle"
    assert classify_stack("a.py:f|threading.py:wait") == "idle"
    # serialization nested under dispatch is serialization — the nesting
    # is what the ledger exists to expose
    assert classify_stack("node.py:dispatch|pickle.py:dumps") == "serialize"
    assert classify_stack("client.py:request|node.py:_handle") == "dispatch"
    assert classify_stack("locks.py:_timed_acquire") == "lock_wait"
    assert classify_stack("mymodel.py:train_step") == "other"


# ---------------------------------------------------------------------------
# ProfileStore (pure, synthetic time)
# ---------------------------------------------------------------------------

T0 = 1_700_000_000.0  # aligned epoch: bucket math must be deterministic


def _bucket(ts, folded, ticks=100.0, busy=50.0):
    return {"ts": ts, "folded": dict(folded), "ticks": ticks,
            "busy_ticks": busy}


def test_store_query_window_overlap():
    st = ProfileStore(bucket_s=60.0)
    st.ingest("w1", [_bucket(T0, {"a.py:f|b.py:g": 10})], now=T0)
    # a 5s window INSIDE the 60s bucket must still see it
    q = st.query(5.0, now=T0 + 30.0)
    assert q["samples"] == 10 and q["origins"] == ["w1"]
    # a window that ended before the bucket began must not
    assert st.query(5.0, now=T0 - 90.0)["samples"] == 0


def test_store_byte_cap_decays_fine_to_coarse():
    st = ProfileStore(bucket_s=10.0, coarse_s=100.0,
                      max_bytes_per_origin=4096, coarse_top_k=5)
    for i in range(40):
        folded = {f"mod{i}.py:fn{j}|leaf{i}_{j}.py:hot": 3 for j in range(8)}
        st.ingest("w1", [_bucket(T0 + 10.0 * i, folded)], now=T0 + 10.0 * i)
    row = st.stats(now=T0 + 400.0)[0]
    assert row["bytes"] <= 4096
    assert row["coarse_buckets"] >= 1  # pressure folded fine into coarse
    # the coarse ring keeps top-K + a decay marker, not the full tail
    q = st.query(1e6, now=T0 + 400.0)
    assert "(decayed)" in q["folded"]
    # no samples were lost to the decay, only resolution
    assert q["samples"] == 40 * 8 * 3


def test_store_origin_lru_eviction():
    st = ProfileStore(max_origins=3)
    for i, name in enumerate(("a", "b", "c", "d")):
        st.ingest(name, [_bucket(T0, {"x.py:f": 1})], now=T0 + i)
    names = {r["origin"] for r in st.stats(now=T0 + 10)}
    assert names == {"b", "c", "d"}  # oldest push evicted


def test_store_prune_ages_fine_then_drops_coarse():
    st = ProfileStore(bucket_s=10.0, coarse_s=100.0,
                      fine_retention_s=50.0, coarse_retention_s=300.0)
    st.ingest("w1", [_bucket(T0, {"x.py:f": 5})], now=T0)
    st.prune(now=T0 + 100.0)  # past fine retention -> folds to coarse
    row = st.stats(now=T0 + 100.0)[0]
    assert row["buckets"] == 0 and row["coarse_buckets"] == 1
    assert st.query(1e6, now=T0 + 100.0)["samples"] == 5  # still queryable
    st.prune(now=T0 + 1000.0)  # past coarse retention -> gone
    assert st.query(1e6, now=T0 + 1000.0)["samples"] == 0


def test_store_retires_dead_origins():
    st = ProfileStore()
    st.ingest("alive", [_bucket(T0, {"x.py:f": 1})], now=T0)
    st.ingest("dead", [_bucket(T0, {"x.py:f": 1})], now=T0)
    st.ingest("alive", [_bucket(T0 + 100, {"x.py:f": 1})], now=T0 + 100)
    assert st.retire_stale(60.0, now=T0 + 100.0) == ["dead"]
    assert {r["origin"] for r in st.stats()} == {"alive"}


def test_store_diff_scales_baseline_to_recent_span():
    st = ProfileStore(bucket_s=10.0)
    # baseline: steady 10 samples/bucket of f; recent: f gone, g hot
    for i in range(6):
        st.ingest("w1", [_bucket(T0 + 10.0 * i, {"a.py:f": 10})],
                  now=T0 + 10.0 * i)
    st.ingest("w1", [_bucket(T0 + 60.0, {"b.py:g": 30})], now=T0 + 60.0)
    d = st.diff(60.0, 10.0, now=T0 + 70.0)
    assert d["samples_a"] == 60 and d["samples_b"] == 30
    # A scaled to B's span: 60 * (10/60) = 10 -> f delta -10, g delta +30
    assert d["delta"]["a.py:f"] == pytest.approx(-10.0)
    assert d["delta"]["b.py:g"] == pytest.approx(30.0)
    lines = dict()
    for ln in d["collapsed"].splitlines():
        stack, a, b = ln.rsplit(" ", 2)
        lines[stack] = (int(a), int(b))
    assert lines["a.py:f"] == (10, 0)    # difffolded: countA countB
    assert lines["b.py:g"] == (0, 30)


def test_store_cost_ledger_columns_sum_to_wall():
    st = ProfileStore(bucket_s=10.0)
    # head: fully busy (busy == ticks -> util 1.0), half dispatch half
    # serialize; worker: fully busy too (its CPU overlaps a busy head,
    # so it must NOT inflate the sum)
    st.ingest("head", [_bucket(T0, {"node.py:_handle": 50,
                                    "pickle.py:dumps": 50},
                               ticks=100.0, busy=100.0)],
              meta={"lateness_frac": 0.0}, now=T0)
    st.ingest("w1", [_bucket(T0, {"worker.py:_main_loop|user.py:fn": 80},
                             ticks=80.0, busy=80.0)], now=T0)
    led = st.cost_ledger(10.0, tasks=1000,
                         roles={"head": "head", "w1": "worker"},
                         now=T0 + 5.0)
    cols = led["columns"]
    assert led["per_task_wall_us"] == pytest.approx(10_000.0)
    assert led["sum_over_wall"] == pytest.approx(1.0, abs=0.01)
    assert cols["head_dispatch_us"] == pytest.approx(5000.0, rel=0.01)
    assert cols["serialize_us"] == pytest.approx(5000.0, rel=0.01)
    # busy head leaves no wall gap: worker CPU reports as overlapped
    assert cols["worker_exec_us"] == 0.0
    assert led["overlapped_worker_cpu_us"] == pytest.approx(10_000.0,
                                                            rel=0.01)
    # GIL share comes off the top when the head reports lateness
    st.ingest("head", [], meta={"lateness_frac": 0.5, "ticks": 0}, now=T0)
    led2 = st.cost_ledger(10.0, tasks=1000, roles={"head": "head"},
                          now=T0 + 5.0)
    assert led2["columns"]["gil_wait_us"] > 0
    assert led2["sum_over_wall"] == pytest.approx(1.0, abs=0.01)


def test_store_class_rates_util_uses_busy_ticks():
    st = ProfileStore(bucket_s=10.0)
    # 4 GIL-inflated thread stacks per tick but only 30/100 ticks busy:
    # raw_busy photographs ~4 threads; util must report 0.3
    st.ingest("w", [_bucket(T0, {"a.py:f": 400}, ticks=100.0, busy=30.0)],
              now=T0)
    r = st.class_rates(100.0, origin="w", now=T0 + 5.0)
    assert r["raw_busy"] == pytest.approx(4.0)
    assert r["util"] == pytest.approx(0.3)
    assert set(r["classes"]) == set(BUSY_CLASSES)


# ---------------------------------------------------------------------------
# continuous profiler (in-process, no cluster)
# ---------------------------------------------------------------------------

def test_continuous_profiler_ships_into_store():
    st = ProfileStore(bucket_s=1.0)
    p = sp.ContinuousProfiler("test-origin", ingest_fn=st.ingest,
                              burst_s=0.03, interval_s=0.05,
                              period_s=0.002, ship_every_s=0.1)
    stop = threading.Event()

    def spin():  # give the sampler a busy stack to catch
        while not stop.is_set():
            sum(i * i for i in range(500))

    t = threading.Thread(target=spin, daemon=True)
    t.start()
    p.start()
    try:
        deadline = time.time() + 10.0
        while time.time() < deadline:
            if st.query(60.0).get("samples", 0) > 0:
                break
            time.sleep(0.05)
    finally:
        p.stop()
        stop.set()
        t.join(timeout=2.0)
    q = st.query(60.0)
    assert q["samples"] > 0 and q["origins"] == ["test-origin"]
    assert q["ticks"] > 0  # duty denominators shipped alongside stacks
    row = st.stats()[0]
    assert row["period_s"] == pytest.approx(0.002)


def test_continuous_profiler_backoff_and_reset():
    p = sp.ContinuousProfiler("t", ingest_fn=lambda *a, **k: None,
                              interval_s=0.5, max_interval_s=4.0)
    static = collections.Counter({"a.py:f|b.py:wait": 5})
    for _ in range(4):
        p._adapt(static)
    assert p._cur_interval > 0.5  # idle process: interval backed off
    p._adapt(collections.Counter({"a.py:f|c.py:work": 5}))
    assert p._cur_interval == 0.5  # stacks changed: full cadence again


# ---------------------------------------------------------------------------
# lock timing plane
# ---------------------------------------------------------------------------

def test_timed_lock_hammer_measures_contention():
    """Pin the timing window open and hammer one lock from 4 threads:
    contended waits and the holds behind them must both be measured,
    and the epoch-scaled acquire estimate must match the true count."""
    _locks.reset_lock_stats()
    lk = _locks.make_lock("test.hammer")
    assert type(lk).__name__ == "_TimedLock"
    _locks.arm_timing(True)
    old = sys.getswitchinterval()
    sys.setswitchinterval(1e-4)
    n = 20_000

    def hammer():
        for _ in range(n):
            with lk:
                pass

    try:
        ths = [threading.Thread(target=hammer) for _ in range(4)]
        for t in ths:
            t.start()
        for t in ths:
            t.join()
    finally:
        sys.setswitchinterval(old)
        _locks.arm_timing(None)
    row = _locks.lock_stats()["test.hammer"]
    assert row["contended"] > 0
    assert row["wait_s"] > 0 and row["hold_s"] > 0
    assert row["max_wait_s"] > 0
    # scaled row / scale = raw armed-window counts; armed covered the
    # whole hammer, so raw must be ~exact
    raw = row["acquires"] / _locks.timing_scale()
    assert raw == pytest.approx(4 * n, rel=0.15)


def test_timed_lock_disarmed_is_passthrough():
    _locks.reset_lock_stats()
    _locks.arm_timing(False)
    try:
        lk = _locks.make_lock("test.quiet")
        for _ in range(500):
            with lk:
                pass
        assert _locks.lock_stats()["test.quiet"]["acquires"] == 0
        # lock semantics intact either way
        assert lk.acquire() is True
        assert lk.acquire(False) is False
        lk.release()
        assert lk.locked() is False
    finally:
        _locks.arm_timing(None)


def test_full_timed_lock_counts_every_acquire(monkeypatch):
    monkeypatch.setenv("RAY_TPU_LOCKPROF", "1")
    _locks.reset_lock_stats()
    lk = _locks.make_lock("test.full")
    assert type(lk).__name__ == "_FullTimedLock"
    for _ in range(100):
        with lk:
            pass
    lk.acquire()
    lk.release()
    row = _locks.lock_stats()["test.full"]
    assert row["acquires"] == 101  # exact, no duty scale under LOCKPROF


def test_condition_on_timed_rlock():
    """Condition(make_lock(rlock=True)) must delegate the C RLock's
    owner tracking — a nonblocking-probe fallback reads a held REENTRANT
    lock as "not owned" and wait() then refuses to wait."""
    rlk = _locks.make_lock("test.cond", rlock=True)
    cond = threading.Condition(rlk)
    box = []

    def waiter():
        with cond:
            while not box:
                cond.wait(timeout=5.0)
            box.append("seen")

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.05)
    with cond:
        box.append("x")
        cond.notify()
    t.join(timeout=5.0)
    assert not t.is_alive() and box == ["x", "seen"]
    with rlk:
        with rlk:  # reentrancy through the proxy
            pass


def test_reset_lock_stats_restarts_epoch():
    _locks.arm_timing(True)
    time.sleep(0.01)
    _locks.reset_lock_stats()
    _locks.arm_timing(None)
    # post-reset: a fresh epoch, not the process-lifetime one
    assert _locks.timing_scale() < 100.0


# ---------------------------------------------------------------------------
# doctor trend rules
# ---------------------------------------------------------------------------

def _series(vals, tags=None, step=30.0):
    return {"tags": tags or {}, "points": [[T0 + i * step, v]
                                           for i, v in enumerate(vals)]}


def test_profiling_doctor_rules_fire_on_induced_pathology():
    from ray_tpu.util import doctor

    findings = doctor.diagnose_trends({
        # sustained GIL pressure on the head origin
        "ray_tpu_gil_lateness_frac": [
            _series([0.6] * 8, tags={"origin": "head"})],
        # a convoy: 6s of measured wait behind 0.5s of holds
        "ray_tpu_lock_wait_s": [
            _series([1.0 + i for i in range(7)],
                    tags={"lock": "node.registry"})],
        "ray_tpu_lock_hold_s": [
            _series([0.1 + 0.07 * i for i in range(7)],
                    tags={"lock": "node.registry"})],
        # the cluster ships bytes instead of computing
        "ray_tpu_profile_serialization_frac": [_series([0.55] * 8)],
    })
    rules = {f["rule"] for f in findings}
    assert rules == {"gil_saturation", "lock_contention",
                     "serialization_hot"}
    gil = next(f for f in findings if f["rule"] == "gil_saturation")
    assert "head" in gil["summary"]
    assert "ROADMAP item 3" in gil["remedy"]  # names the structural fix
    lock = next(f for f in findings if f["rule"] == "lock_contention")
    assert "node.registry" in lock["summary"]
    assert "ROADMAP item 3" in lock["remedy"]  # head-plane lock remedy
    ser = next(f for f in findings if f["rule"] == "serialization_hot")
    assert "ROADMAP item 5" in ser["remedy"]
    # render() must format all three without KeyError
    out = doctor.render(findings)
    for r in rules:
        assert r in out


def test_profiling_doctor_rules_stay_silent_on_healthy_gates():
    from ray_tpu.util import doctor

    assert doctor.diagnose_trends({
        # below-threshold pressure, one hot burst (not sustained),
        # waits in proportion to holds, serialization share modest
        "ray_tpu_gil_lateness_frac": [
            _series([0.1] * 8, tags={"origin": "head"}),
            _series([0.1, 0.1, 0.9, 0.1, 0.1, 0.1, 0.1, 0.1],
                    tags={"origin": "w1"})],
        "ray_tpu_lock_wait_s": [
            _series([1.0 + 0.5 * i for i in range(7)],
                    tags={"lock": "node.registry"})],
        "ray_tpu_lock_hold_s": [
            _series([1.0 + 0.4 * i for i in range(7)],
                    tags={"lock": "node.registry"})],
        "ray_tpu_profile_serialization_frac": [_series([0.2] * 8)],
    }) == []


# ---------------------------------------------------------------------------
# live cluster: sampler -> ship -> store -> state API/ledger
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def prof_cluster():
    import os

    env = {"RAY_TPU_METRICS_PUSH_S": "0.5",
           "RAY_TPU_CONT_PROFILE_INTERVAL_S": "0.2"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def test_live_profiles_reach_store_and_state_api(prof_cluster):
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    def f(x):
        return x + 1

    deadline = time.time() + 30.0
    prof = None
    while time.time() < deadline:
        ray_tpu.get([f.remote(i) for i in range(50)])
        prof = state.get_profile(window_s=600.0)
        if prof["samples"] > 0:
            break
        time.sleep(0.2)
    assert prof and prof["samples"] > 0
    assert prof["ticks"] > 0
    assert any(o.startswith("head") for o in prof["origins"])
    rows = state.list_profiles()
    assert rows and {"origin", "buckets", "bytes", "samples",
                     "gil_frac"} <= set(rows[0])
    d = state.profile_diff(window_a=600.0, window_b=60.0)
    assert "collapsed" in d and d["samples_b"] >= 0
    led = state.profile_ledger(window_s=60.0)
    assert set(led["columns"]) == {
        "driver_submit_us", "head_dispatch_us", "worker_exec_us",
        "serialize_us", "lock_wait_us", "gil_wait_us", "other_us"}
    assert led["sum_us"] == pytest.approx(sum(led["columns"].values()),
                                          rel=0.01)


# ---------------------------------------------------------------------------
# bench regression gate (slow: re-runs the core rows)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_bench_check_against_committed_baseline():
    """``python bench.py --check`` re-runs the cheap core rows and
    compares them to the committed BENCH_core.json inside tolerance
    bands; a regression (or a failed fresh run) exits nonzero."""
    import os

    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.join(here, "bench.py"), "--check"],
        capture_output=True, text=True, timeout=2400, cwd=here)
    assert proc.returncode == 0, (
        f"bench --check regressed:\n{proc.stdout[-3000:]}\n"
        f"{proc.stderr[-1000:]}")
