"""RLlib multi-agent + CNN catalog (reference
``rllib/env/multi_agent_env.py:30``, ``rllib/models/catalog.py:195``)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    MultiAgentEnv,
    MultiAgentPPOConfig,
    PPOConfig,
)
from ray_tpu.rllib.models import (
    apply_conv_actor_critic,
    apply_model,
    init_conv_actor_critic,
)


class _Box:
    def __init__(self, shape):
        self.shape = shape


class _Discrete:
    def __init__(self, n):
        self.n = n


class DualCartPole(MultiAgentEnv):
    """Two independent CartPole instances inside one MultiAgentEnv — each
    agent balances its own pole; the episode ends when BOTH are done (the
    '2-agent CartPole variant' of the verdict)."""

    agents = ["cart_0", "cart_1"]

    def __init__(self, _config=None):
        import gymnasium as gym

        self._envs = {a: gym.make("CartPole-v1") for a in self.agents}
        self._done = {a: False for a in self.agents}

    def observation_space(self, agent_id):
        return _Box(self._envs[agent_id].observation_space.shape)

    def action_space(self, agent_id):
        return _Discrete(int(self._envs[agent_id].action_space.n))

    def reset(self, *, seed=None, options=None):
        obs = {}
        for i, (a, env) in enumerate(self._envs.items()):
            o, _ = env.reset(seed=None if seed is None else seed + i)
            obs[a] = o
            self._done[a] = False
        return obs, {}

    def step(self, action_dict):
        obs, rewards, terms, truncs = {}, {}, {}, {}
        for a, act in action_dict.items():
            if self._done[a]:
                continue
            o, r, term, trunc, _ = self._envs[a].step(int(act))
            rewards[a] = r
            terms[a] = term
            truncs[a] = trunc
            if term or trunc:
                self._done[a] = True
            else:
                obs[a] = o
        done_all = all(self._done.values())
        terms["__all__"] = done_all
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, {}


def test_multiagent_ppo_learns_dual_cartpole(ray_start_regular):
    config = (
        MultiAgentPPOConfig()
        .environment(env_creator=lambda cfg: DualCartPole(cfg))
        .rollouts(num_rollout_workers=0, rollout_fragment_length=200)
        .training(train_batch_size=800, sgd_minibatch_size=128,
                  num_sgd_iter=6, lr=3e-4, entropy_coeff=0.01)
        .multi_agent(
            policies=["p0", "p1"],
            policy_mapping_fn=lambda agent_id: f"p{agent_id[-1]}",
        )
        .debugging(seed=7)
    )
    algo = config.build()
    first = None
    best = -np.inf
    for _ in range(18):
        res = algo.step()
        r = res["episode_reward_mean"]
        if not np.isnan(r):
            if first is None:
                first = r
            best = max(best, r)
        assert set(res["info"]["learner"]) <= {"p0", "p1"}
    algo.cleanup()
    # combined reward of two fresh CartPoles starts ~40-60; learning must
    # push the (100-episode-window) mean well past the initial level
    assert first is not None
    assert best > first * 1.5 and best > 100, (first, best)


def test_multiagent_checkpoint_roundtrip(ray_start_regular):
    config = (
        MultiAgentPPOConfig()
        .environment(env_creator=lambda cfg: DualCartPole(cfg))
        .training(train_batch_size=300, sgd_minibatch_size=64, num_sgd_iter=2)
        .multi_agent(policies=["p0", "p1"],
                     policy_mapping_fn=lambda aid: f"p{aid[-1]}")
    )
    algo = config.build()
    algo.step()
    state = algo.save_checkpoint()
    assert set(state["policy_state"]) == {"p0", "p1"}
    algo2 = config.build()
    algo2.load_checkpoint(state)
    w1 = algo.workers.local_worker.policies["p0"].get_weights()
    w2 = algo2.workers.local_worker.policies["p0"].get_weights()
    np.testing.assert_allclose(w1["pi"][0]["w"], w2["pi"][0]["w"])
    algo.cleanup()
    algo2.cleanup()


def test_conv_model_fwd_bwd_on_synthetic_frames():
    """Nature-CNN fwd/bwd on 84x84 frames (Atari-shaped; BASELINE config 4
    readiness) — gradients flow to every conv layer."""
    import jax
    import jax.numpy as jnp

    params = init_conv_actor_critic(jax.random.PRNGKey(0), (84, 84, 4), 6)
    frames = jnp.asarray(
        np.random.default_rng(0).random((8, 84, 84, 4), np.float32))
    logits, value = jax.jit(apply_conv_actor_critic)(params, frames)
    assert logits.shape == (8, 6) and value.shape == (8,)
    # dispatch: the same params route through apply_model
    l2, v2 = apply_model(params, frames)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(l2), rtol=1e-5)

    def loss(p):
        lg, v = apply_conv_actor_critic(p, frames)
        return jnp.mean(lg ** 2) + jnp.mean(v ** 2)

    grads = jax.jit(jax.grad(loss))(params)
    for i, layer in enumerate(grads["conv"]):
        assert float(jnp.abs(layer["w"]).max()) > 0, f"dead conv layer {i}"


class PixelSeeker:
    """Tiny learnable pixel env: the bright column marks the target; move
    toward it.  Exercises the conv path through PPO end-to-end."""

    class _Space:
        def __init__(self, shape=None, n=None):
            if shape is not None:
                self.shape = shape
            if n is not None:
                self.n = n
                self.shape = ()

    N = 11

    def __init__(self, _cfg=None):
        self.observation_space = self._Space(shape=(self.N, self.N, 1))
        self.action_space = self._Space(n=2)
        self._rng = np.random.default_rng(0)

    def _obs(self):
        img = np.zeros((self.N, self.N, 1), np.float32)
        img[:, self.target, 0] = 1.0
        img[self.N // 2, self.pos, 0] = 0.5
        return img

    def reset(self, seed=None):
        self.pos = self.N // 2
        self.target = int(self._rng.integers(0, self.N))
        self.t = 0
        return self._obs(), {}

    def step(self, action):
        self.pos = int(np.clip(
            self.pos + (1 if action == 1 else -1), 0, self.N - 1))
        self.t += 1
        done = self.pos == self.target
        # dense shaping: closeness each step + a bonus on arrival, so the
        # conv policy gets gradient signal from the first iteration
        reward = 1.0 if done else -abs(self.pos - self.target) / self.N * 0.2
        return self._obs(), reward, done, self.t >= 24, {}


def test_ppo_conv_policy_learns_pixels(ray_start_regular):
    config = (
        PPOConfig()
        .environment(env_creator=lambda cfg: PixelSeeker(cfg))
        .rollouts(rollout_fragment_length=200)
        .training(train_batch_size=600, sgd_minibatch_size=128,
                  num_sgd_iter=4, lr=1e-3, entropy_coeff=0.01)
        .debugging(seed=3)
    )
    algo = config.build()
    assert "conv" in algo.get_policy().params  # catalog picked the CNN
    first, best = None, -np.inf
    for _ in range(14):
        r = algo.step()["episode_reward_mean"]
        if not np.isnan(r):
            first = r if first is None else first
            best = max(best, r)
    algo.cleanup()
    assert best > first + 0.15, (first, best)
