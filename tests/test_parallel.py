"""Mesh / sharding / in-jit collective tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.parallel import MeshSpec, create_mesh
from ray_tpu.parallel import collective as col
from ray_tpu.parallel.sharding import (
    FSDP_TP_RULES,
    ShardingRules,
    infer_sharding,
    rules_for_mesh,
)


def test_mesh_spec_resolve():
    assert MeshSpec(dp=-1, tp=4).resolve(8) == {
        "pp": 1, "dp": 2, "fsdp": 1, "ep": 1, "sp": 1, "tp": 4
    }
    with pytest.raises(ValueError):
        MeshSpec(dp=3, tp=4).resolve(8)
    with pytest.raises(ValueError):
        MeshSpec(dp=-1, tp=-1).resolve(8)


def test_create_mesh_axis_order():
    mesh = create_mesh(MeshSpec(dp=2, tp=4))
    assert mesh.axis_names == ("dp", "tp")
    assert mesh.devices.shape == (2, 4)
    # tp is innermost: adjacent devices share a dp row
    flat = mesh.devices.reshape(-1)
    assert flat[0] is mesh.devices[0, 0] and flat[1] is mesh.devices[0, 1]


def test_create_mesh_single_axis_fallback():
    mesh = create_mesh(MeshSpec(), devices=jax.devices()[:1])
    assert mesh.axis_names == ("dp",)


def test_sharding_rules_spec():
    rules = ShardingRules(batch=("dp", "fsdp"), embed="fsdp", mlp="tp")
    assert rules.spec(("batch", None)) == P(("dp", "fsdp"), None)
    assert rules.spec(("embed", "mlp")) == P("fsdp", "tp")
    updated = rules.update(mlp=None)
    assert updated.spec(("embed", "mlp")) == P("fsdp", None)


def test_rules_for_mesh():
    mesh = create_mesh(MeshSpec(fsdp=2, tp=4))
    rules = rules_for_mesh(mesh)
    assert rules.rules["batch"] == "fsdp"
    assert rules.rules["mlp"] == "tp"
    assert rules.rules["seq"] is None


def test_infer_sharding_shards_largest_divisible_dim():
    mesh = create_mesh(MeshSpec(fsdp=8))
    params = {"w": jnp.zeros((16, 128)), "b": jnp.zeros((4,))}
    shardings = infer_sharding(params, mesh, FSDP_TP_RULES)
    assert shardings["w"].spec == P(None, "fsdp")
    assert shardings["b"].spec == P()  # too small -> replicated


def test_collectives_in_shard_map():
    mesh = create_mesh(MeshSpec(dp=8))
    x = jnp.arange(8.0)

    def body(x):
        s = col.allreduce(x, "dp")
        g = col.allgather(x, "dp")
        b = col.broadcast(x, "dp", root=3)
        r = col.ppermute_next(x, "dp", shift=1)
        return s, g, b, r

    f = jax.jit(
        jax.shard_map(
            body, mesh=mesh, in_specs=P("dp"),
            out_specs=(P("dp"), P(None), P("dp"), P("dp")),
            check_vma=False,
        )
    )
    s, g, b, r = f(x)
    np.testing.assert_allclose(s, np.full(8, 28.0))
    np.testing.assert_allclose(g, np.arange(8.0))
    np.testing.assert_allclose(b, np.full(8, 3.0))
    # ring shift by 1: device i's value moves to device i+1
    np.testing.assert_allclose(r, np.roll(np.arange(8.0), 1))


def test_reducescatter_in_shard_map():
    mesh = create_mesh(MeshSpec(dp=8))
    x = jnp.ones((8, 8))

    # the DDP-gradient shape: every device holds the full tensor, each ends
    # up owning the reduced shard of its slice
    f = jax.jit(
        jax.shard_map(
            lambda x: col.reducescatter(x, "dp", scatter_axis=0),
            mesh=mesh, in_specs=P(None, None), out_specs=P("dp", None),
            check_vma=False,
        )
    )
    out = f(x)
    assert out.shape == (8, 8)
    np.testing.assert_allclose(out, np.full((8, 8), 8.0))


def test_grad_sync_pmean():
    mesh = create_mesh(MeshSpec(dp=8))
    grads = {"w": jnp.arange(8.0), "b": jnp.ones(8)}

    f = jax.jit(
        jax.shard_map(
            lambda g: col.grad_sync(g, "dp"),
            mesh=mesh, in_specs=P("dp"), out_specs=P("dp"),
        )
    )
    out = f(grads)
    np.testing.assert_allclose(out["w"], np.full(8, 3.5))
    np.testing.assert_allclose(out["b"], np.ones(8))
