"""Placement-group + multi-node scheduling tests
(reference: python/ray/tests/test_placement_group.py, test_scheduling.py)."""

import pytest

import ray_tpu
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


def test_pg_create_ready(ray_start_regular):
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
    # generous: under full-suite contention a 30s bound has flaked
    assert pg.wait(120)


def test_pg_infeasible_pending(ray_start_regular):
    pg = placement_group([{"CPU": 100}], strategy="STRICT_PACK")
    assert not pg.wait(1.0)


def test_pg_task_scheduling(ray_start_regular):
    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.wait(30)

    @ray_tpu.remote
    def f():
        return ray_tpu.get_runtime_context().node_id

    strat = PlacementGroupSchedulingStrategy(placement_group=pg, placement_group_bundle_index=0)
    nid = ray_tpu.get(f.options(scheduling_strategy=strat).remote())
    assert nid == "node-head"
    remove_placement_group(pg)


def test_strict_spread_needs_nodes(ray_start_cluster):
    cluster = ray_start_cluster
    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_SPREAD")
    assert not pg.wait(1.0)  # only one node alive
    cluster.add_node(num_cpus=2)
    assert pg.wait(30)
    table_nodes = pg.bundle_count
    assert table_nodes == 2


def test_node_affinity(ray_start_cluster):
    cluster = ray_start_cluster
    nid = cluster.add_node(num_cpus=2)

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().node_id

    strat = NodeAffinitySchedulingStrategy(node_id=nid)
    assert ray_tpu.get(where.options(scheduling_strategy=strat).remote(), timeout=90) == nid


def test_tpu_resource_scheduling(ray_start_2_tpus):
    @ray_tpu.remote(num_tpus=1)
    def which_chips():
        return ray_tpu.get_runtime_context().get_tpu_ids()

    chips = ray_tpu.get([which_chips.remote(), which_chips.remote()])
    # each invocation gets exactly one distinct chip id (isolation by env)
    assert all(len(c) == 1 for c in chips)
    res = ray_tpu.cluster_resources()
    assert res["TPU"] == 2.0


def test_tpu_actor_env_isolation(ray_start_2_tpus):
    @ray_tpu.remote(num_tpus=1)
    class TpuActor:
        def chips(self):
            import os

            return os.environ.get("TPU_VISIBLE_CHIPS")

    a, b = TpuActor.remote(), TpuActor.remote()
    ca, cb = ray_tpu.get([a.chips.remote(), b.chips.remote()])
    assert ca is not None and cb is not None and ca != cb


def test_tpu_oversubscription_queues(ray_start_2_tpus):
    @ray_tpu.remote(num_tpus=2)
    def both():
        return sorted(ray_tpu.get_runtime_context().get_tpu_ids())

    assert ray_tpu.get(both.remote(), timeout=120) == [0, 1]
