"""runtime_env MVP: env_vars + working_dir honored at worker spawn.

Mirrors the reference's runtime-env plugin intents
(``python/ray/_private/runtime_env/plugin.py``): a task/actor declaring an
environment actually gets it, and unsupported keys error instead of being
silently dropped (the round-1 verdict's correctness trap).
"""

import os
import time
import tempfile

import pytest

import ray_tpu


def test_task_sees_env_vars(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_TEST_FLAG": "hello42"}})
    def read_env():
        return os.environ.get("RTPU_TEST_FLAG")

    assert ray_tpu.get(read_env.remote(), timeout=60) == "hello42"


def test_task_sees_working_dir(ray_start_regular):
    """working_dir is a SNAPSHOT (reference semantics): the tree is
    packaged, shipped through the cluster KV, and the worker chdirs into
    its extracted copy — relative reads work, later local edits don't
    leak in."""
    wd = tempfile.mkdtemp(prefix="rtpu_wd_")
    with open(os.path.join(wd, "data.txt"), "w") as f:
        f.write("snapshot-payload")

    @ray_tpu.remote(runtime_env={"working_dir": wd})
    def read_rel():
        with open("data.txt") as f:
            return f.read(), os.path.realpath(os.getcwd())

    content, cwd = ray_tpu.get(read_rel.remote(), timeout=60)
    assert content == "snapshot-payload"
    assert cwd != os.path.realpath(wd)  # the extracted copy, not the live dir


def test_plain_task_not_polluted(ray_start_regular):
    """A worker spawned for a runtime_env never serves plain tasks."""
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_POLLUTION": "yes"}})
    def with_env():
        return os.environ.get("RTPU_POLLUTION")

    @ray_tpu.remote
    def plain():
        return os.environ.get("RTPU_POLLUTION")

    assert ray_tpu.get(with_env.remote(), timeout=60) == "yes"
    assert ray_tpu.get(plain.remote(), timeout=60) is None


def test_actor_runtime_env(ray_start_regular):
    wd = tempfile.mkdtemp(prefix="rtpu_awd_")
    with open(os.path.join(wd, "marker.txt"), "w") as f:
        f.write("actor-snapshot")

    @ray_tpu.remote
    class EnvActor:
        def probe(self):
            with open("marker.txt") as f:
                return os.environ.get("RTPU_ACTOR_FLAG"), f.read()

    a = EnvActor.options(
        runtime_env={"env_vars": {"RTPU_ACTOR_FLAG": "actorenv"},
                     "working_dir": wd}
    ).remote()
    flag, content = ray_tpu.get(a.probe.remote(), timeout=60)
    assert flag == "actorenv"
    assert content == "actor-snapshot"  # snapshot extracted on the worker


def test_unsupported_runtime_env_key_errors(ray_start_regular):
    with pytest.raises(ValueError, match="container"):
        @ray_tpu.remote(runtime_env={"container": {"image": "x"}})
        def f():
            pass

    with pytest.raises(TypeError):
        @ray_tpu.remote(runtime_env={"env_vars": {"A": 1}})
        def g():
            pass

    with pytest.raises(TypeError):
        @ray_tpu.remote(runtime_env={"pip": "requests"})  # not a list
        def h():
            pass


def test_missing_working_dir_errors(ray_start_regular):
    with pytest.raises(ValueError, match="working_dir"):
        @ray_tpu.remote(runtime_env={"working_dir": "/nonexistent/dir/xyz"})
        def f():
            pass


def test_user_pythonpath_merged_not_clobbered(ray_start_regular):
    """A user PYTHONPATH must not break worker boot (merged, not replaced)."""
    wd = tempfile.mkdtemp(prefix="rtpu_pp_")
    with open(os.path.join(wd, "rtpu_pp_probe.py"), "w") as f:
        f.write("VALUE = 'from-user-path'\n")

    @ray_tpu.remote(runtime_env={"env_vars": {"PYTHONPATH": wd}})
    def read():
        import rtpu_pp_probe
        return rtpu_pp_probe.VALUE

    assert ray_tpu.get(read.remote(), timeout=120) == "from-user-path"


def test_unspawnable_env_surfaces_error(ray_start_regular):
    """A working_dir deleted between validation and submission must raise
    a clear error at packaging time, not defer the task forever."""
    import shutil

    wd = tempfile.mkdtemp(prefix="rtpu_gone_")

    @ray_tpu.remote(runtime_env={"working_dir": wd})
    def f():
        return 1

    shutil.rmtree(wd)  # dies between validation and packaging
    with pytest.raises(Exception, match="runtime_env|does not exist"):
        ray_tpu.get(f.remote(), timeout=120)


def test_actor_unspawnable_env_surfaces_error(ray_start_regular):
    """Actor whose dedicated worker cannot spawn must raise RayActorError on
    its first method, with node resources returned (not re-acquired every
    scheduler pass)."""
    import shutil

    wd = tempfile.mkdtemp(prefix="rtpu_agone_")

    @ray_tpu.remote
    class A:
        def ping(self):
            return "up"

    handle = A.options(runtime_env={"working_dir": wd})
    shutil.rmtree(wd)
    with pytest.raises(Exception, match="spawn|died|Actor|does not exist"):
        a = handle.remote()
        ray_tpu.get(a.ping.remote(), timeout=120)

    # the node is not drained: plain tasks still run
    @ray_tpu.remote
    def ok():
        return 1

    assert ray_tpu.get(ok.remote(), timeout=60) == 1


# ---------------------------------------------------------------------------
# pip runtime_env (reference python/ray/_private/runtime_env/pip.py):
# hash-keyed cached venvs built at worker spawn, offline via a local wheel
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def local_wheel():
    """Build a tiny wheel offline so pip can install a package that is NOT
    in the base environment."""
    import subprocess
    import sys

    src = tempfile.mkdtemp(prefix="rtpu_pkg_")
    pkg = os.path.join(src, "rtpu_testpkg")
    os.makedirs(pkg)
    with open(os.path.join(pkg, "__init__.py"), "w") as f:
        f.write("MAGIC = 42\n")
    with open(os.path.join(src, "pyproject.toml"), "w") as f:
        f.write(
            '[build-system]\nrequires = ["setuptools"]\n'
            'build-backend = "setuptools.build_meta"\n'
            '[project]\nname = "rtpu-testpkg"\nversion = "1.0"\n'
        )
    wheels = tempfile.mkdtemp(prefix="rtpu_whl_")
    subprocess.run(
        [sys.executable, "-m", "pip", "wheel", "--no-deps", "--no-index",
         "--no-build-isolation", "-w", wheels, src],
        check=True, capture_output=True, timeout=300,
    )
    return wheels


def _pip_env(wheels):
    return {"pip": {"packages": ["rtpu-testpkg"],
                    "pip_install_options": ["--no-index", "--find-links", wheels]}}


def test_pip_runtime_env_installs_package(ray_start_regular, local_wheel):
    with pytest.raises(ImportError):
        import rtpu_testpkg  # noqa: F401 — must be absent from the base env

    @ray_tpu.remote(runtime_env=_pip_env(local_wheel))
    def probe():
        import rtpu_testpkg

        return rtpu_testpkg.MAGIC

    assert ray_tpu.get(probe.remote(), timeout=300) == 42


def test_pip_runtime_env_cache_hit(ray_start_regular, local_wheel):
    """Same pip spec under a different env key reuses the venv (the ready
    marker is not rebuilt)."""
    from ray_tpu._private.runtime_env_setup import DEFAULT_BASE_DIR, pip_env_key

    env = _pip_env(local_wheel)

    @ray_tpu.remote(runtime_env=env)
    def first():
        import rtpu_testpkg

        return rtpu_testpkg.MAGIC

    assert ray_tpu.get(first.remote(), timeout=300) == 42
    marker = os.path.join(
        DEFAULT_BASE_DIR, f"pip-{pip_env_key(env['pip'])}", ".ready")
    assert os.path.exists(marker)
    mtime = os.path.getmtime(marker)

    # different env_vars -> different worker pool key, SAME venv
    env2 = dict(env, env_vars={"RTPU_MARK": "two"})

    @ray_tpu.remote(runtime_env=env2)
    def second():
        import os as _os

        import rtpu_testpkg

        return rtpu_testpkg.MAGIC, _os.environ.get("RTPU_MARK")

    t0 = time.time()
    assert ray_tpu.get(second.remote(), timeout=300) == (42, "two")
    assert os.path.getmtime(marker) == mtime, "venv was rebuilt, not reused"
    assert time.time() - t0 < 60, "cache hit should skip the install"


def test_pip_runtime_env_bad_package_fails(ray_start_regular):
    @ray_tpu.remote(runtime_env={"pip": {
        "packages": ["definitely-not-a-real-pkg-xyz"],
        "pip_install_options": ["--no-index"],
    }}, max_retries=0)
    def doomed():
        return 1

    with pytest.raises(Exception, match="runtime_env|died|setup"):
        ray_tpu.get(doomed.remote(), timeout=300)


# ---------------------------------------------------------------------------
# round 5: URI packaging (py_modules / working_dir snapshots) + conda
# (reference python/ray/_private/runtime_env/{packaging,py_modules,conda}.py)


def _make_module_dir(tmp, name, magic):
    mod = os.path.join(tmp, name)
    os.makedirs(mod, exist_ok=True)
    with open(os.path.join(mod, "__init__.py"), "w") as f:
        f.write(f"MAGIC = {magic}\n")
    return mod


def test_py_modules_importable(ray_start_regular):
    tmp = tempfile.mkdtemp(prefix="rtpu_pym_")
    _make_module_dir(tmp, "rtpu_pymod_a", 7)
    _make_module_dir(tmp, "rtpu_pymod_b", 8)

    @ray_tpu.remote(runtime_env={"py_modules": [
        os.path.join(tmp, "rtpu_pymod_a"), os.path.join(tmp, "rtpu_pymod_b"),
    ]})
    def use_modules():
        import rtpu_pymod_a
        import rtpu_pymod_b

        return rtpu_pymod_a.MAGIC + rtpu_pymod_b.MAGIC

    assert ray_tpu.get(use_modules.remote(), timeout=120) == 15


def test_py_modules_snapshot_shipped_via_kv(ray_start_regular):
    """The module tree travels as a content-addressed package through the
    cluster KV — deleting the source dir after submission must not break
    later tasks (the worker extracts from the KV, not the driver disk)."""
    import shutil

    tmp = tempfile.mkdtemp(prefix="rtpu_pym_")
    _make_module_dir(tmp, "rtpu_pymod_gone", 21)
    env = {"py_modules": [os.path.join(tmp, "rtpu_pymod_gone")]}

    @ray_tpu.remote(runtime_env=env)
    def one():
        import rtpu_pymod_gone

        return rtpu_pymod_gone.MAGIC

    assert ray_tpu.get(one.remote(), timeout=120) == 21

    # the identical env resubmitted AFTER the source dir is gone hits the
    # driver's prepared-env cache (no re-zip of a deleted tree) and the
    # worker still serves it from the KV package
    @ray_tpu.remote(runtime_env=env)
    def two():
        import rtpu_pymod_gone

        return rtpu_pymod_gone.MAGIC * 2

    shutil.rmtree(tmp)
    assert ray_tpu.get(two.remote(), timeout=120) == 42


def test_working_dir_excludes(ray_start_regular):
    wd = tempfile.mkdtemp(prefix="rtpu_wdx_")
    with open(os.path.join(wd, "keep.txt"), "w") as f:
        f.write("k")
    os.makedirs(os.path.join(wd, "big_data"))
    with open(os.path.join(wd, "big_data", "blob.bin"), "w") as f:
        f.write("x" * 1000)

    @ray_tpu.remote(runtime_env={"working_dir": wd,
                                 "excludes": ["big_data"]})
    def listing():
        return sorted(os.listdir("."))

    names = ray_tpu.get(listing.remote(), timeout=120)
    assert "keep.txt" in names and "big_data" not in names


def test_packaging_determinism_and_cache(tmp_path):
    from ray_tpu._private.runtime_env_packaging import (
        ensure_package_local, package_uri, zip_directory,
    )

    src = tmp_path / "src"
    src.mkdir()
    (src / "a.py").write_text("A = 1\n")
    (src / "__pycache__").mkdir()
    (src / "__pycache__" / "junk.pyc").write_text("junk")

    z1 = zip_directory(str(src), top_level=False)
    z2 = zip_directory(str(src), top_level=False)
    assert z1 == z2, "zips must be deterministic for content addressing"
    assert package_uri(z1) == package_uri(z2)
    import zipfile as _zf
    import io as _io

    assert _zf.ZipFile(_io.BytesIO(z1)).namelist() == ["a.py"]

    calls = []

    def fetch(uri):
        calls.append(uri)
        return z1

    base = str(tmp_path / "cache")
    d1 = ensure_package_local(fetch, package_uri(z1), base)
    d2 = ensure_package_local(fetch, package_uri(z1), base)
    assert d1 == d2 and len(calls) == 1, "second ensure must hit the cache"
    assert (os.path.join(d1, "a.py"), open(os.path.join(d1, "a.py")).read()) \
        == (os.path.join(d1, "a.py"), "A = 1\n")


def test_package_size_limit(tmp_path, monkeypatch):
    from ray_tpu._private import runtime_env_packaging as pkg

    src = tmp_path / "big"
    src.mkdir()
    (src / "blob").write_bytes(b"x" * 4096)
    monkeypatch.setattr(pkg, "_SIZE_LIMIT", 1024)
    with pytest.raises(ValueError, match="exceeds"):
        pkg.zip_directory(str(src), top_level=False)


def test_conda_named_env_with_fake_binary(ray_start_regular, tmp_path,
                                          monkeypatch):
    """conda runtime_env resolves an env's python through the conda
    binary; a fake conda proves the full spawn path without the real
    tool (the image has none — the gcloud-provider test pattern)."""
    import stat
    import sys as _sys

    fake = tmp_path / "conda"
    # `conda run -n NAME python -c ...` -> print THIS interpreter, i.e.
    # the "env" is the current python (the resolution contract is what we
    # test; package isolation is pip's covered path)
    fake.write_text(
        "#!/bin/sh\n"
        f"echo {_sys.executable}\n"
    )
    fake.chmod(fake.stat().st_mode | stat.S_IEXEC)
    monkeypatch.setenv("RAY_TPU_CONDA_EXE", str(fake))

    @ray_tpu.remote(runtime_env={"conda": "base",
                                 "env_vars": {"RAY_TPU_CONDA_EXE": str(fake)}})
    def in_conda():
        return os.environ.get("RAY_TPU_CONDA_EXE") is not None

    assert ray_tpu.get(in_conda.remote(), timeout=120) is True


def test_conda_missing_binary_fails_loudly(ray_start_regular, monkeypatch):
    monkeypatch.delenv("RAY_TPU_CONDA_EXE", raising=False)
    monkeypatch.setenv("PATH", "/nonexistent")

    from ray_tpu._private.runtime_env_setup import ensure_conda_env

    with pytest.raises(RuntimeError, match="conda binary"):
        ensure_conda_env("whatever")


def test_conda_plus_pip_rejected(ray_start_regular):
    with pytest.raises(ValueError, match="both 'pip' and 'conda'"):
        @ray_tpu.remote(runtime_env={"conda": "base", "pip": ["x"]})
        def nope():
            return 1


def test_container_rejected_with_hint(ray_start_regular):
    with pytest.raises(ValueError, match="container"):
        @ray_tpu.remote(runtime_env={"container": {"image": "img"}})
        def nope():
            return 1


def test_package_setup_failure_trips_breaker(ray_start_regular):
    """A worker that cannot materialize its packages dies BEFORE
    registration, so the spawn circuit breaker errors the task instead
    of respawning forever (the pip-shim exit-77 invariant)."""
    wd = tempfile.mkdtemp(prefix="rtpu_brk_")
    with open(os.path.join(wd, "x.txt"), "w") as f:
        f.write("x")

    @ray_tpu.remote(runtime_env={
        "working_dir": wd,
        # unwritable package cache -> extraction fails in every respawn
        "env_vars": {"RAY_TPU_RUNTIME_ENV_DIR": "/proc/nope"},
    }, max_retries=0)
    def doomed():
        return 1

    with pytest.raises(Exception, match="runtime_env|died|setup|spawn"):
        ray_tpu.get(doomed.remote(), timeout=180)
