"""runtime_env MVP: env_vars + working_dir honored at worker spawn.

Mirrors the reference's runtime-env plugin intents
(``python/ray/_private/runtime_env/plugin.py``): a task/actor declaring an
environment actually gets it, and unsupported keys error instead of being
silently dropped (the round-1 verdict's correctness trap).
"""

import os
import time
import tempfile

import pytest

import ray_tpu


def test_task_sees_env_vars(ray_start_regular):
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_TEST_FLAG": "hello42"}})
    def read_env():
        return os.environ.get("RTPU_TEST_FLAG")

    assert ray_tpu.get(read_env.remote(), timeout=60) == "hello42"


def test_task_sees_working_dir(ray_start_regular):
    wd = tempfile.mkdtemp(prefix="rtpu_wd_")
    real_wd = os.path.realpath(wd)

    @ray_tpu.remote(runtime_env={"working_dir": wd})
    def read_cwd():
        return os.path.realpath(os.getcwd())

    assert ray_tpu.get(read_cwd.remote(), timeout=60) == real_wd


def test_plain_task_not_polluted(ray_start_regular):
    """A worker spawned for a runtime_env never serves plain tasks."""
    @ray_tpu.remote(runtime_env={"env_vars": {"RTPU_POLLUTION": "yes"}})
    def with_env():
        return os.environ.get("RTPU_POLLUTION")

    @ray_tpu.remote
    def plain():
        return os.environ.get("RTPU_POLLUTION")

    assert ray_tpu.get(with_env.remote(), timeout=60) == "yes"
    assert ray_tpu.get(plain.remote(), timeout=60) is None


def test_actor_runtime_env(ray_start_regular):
    wd = tempfile.mkdtemp(prefix="rtpu_awd_")

    @ray_tpu.remote
    class EnvActor:
        def probe(self):
            return os.environ.get("RTPU_ACTOR_FLAG"), os.path.realpath(os.getcwd())

    a = EnvActor.options(
        runtime_env={"env_vars": {"RTPU_ACTOR_FLAG": "actorenv"},
                     "working_dir": wd}
    ).remote()
    flag, cwd = ray_tpu.get(a.probe.remote(), timeout=60)
    assert flag == "actorenv"
    assert cwd == os.path.realpath(wd)


def test_unsupported_runtime_env_key_errors(ray_start_regular):
    with pytest.raises(ValueError, match="conda"):
        @ray_tpu.remote(runtime_env={"conda": "myenv"})
        def f():
            pass

    with pytest.raises(TypeError):
        @ray_tpu.remote(runtime_env={"env_vars": {"A": 1}})
        def g():
            pass

    with pytest.raises(TypeError):
        @ray_tpu.remote(runtime_env={"pip": "requests"})  # not a list
        def h():
            pass


def test_missing_working_dir_errors(ray_start_regular):
    with pytest.raises(ValueError, match="working_dir"):
        @ray_tpu.remote(runtime_env={"working_dir": "/nonexistent/dir/xyz"})
        def f():
            pass


def test_user_pythonpath_merged_not_clobbered(ray_start_regular):
    """A user PYTHONPATH must not break worker boot (merged, not replaced)."""
    wd = tempfile.mkdtemp(prefix="rtpu_pp_")
    with open(os.path.join(wd, "rtpu_pp_probe.py"), "w") as f:
        f.write("VALUE = 'from-user-path'\n")

    @ray_tpu.remote(runtime_env={"env_vars": {"PYTHONPATH": wd}})
    def read():
        import rtpu_pp_probe
        return rtpu_pp_probe.VALUE

    assert ray_tpu.get(read.remote(), timeout=120) == "from-user-path"


def test_unspawnable_env_surfaces_error(ray_start_regular):
    """A runtime_env whose worker cannot even spawn (working_dir deleted
    after validation) must raise, not defer the task forever (the
    spawn-failure circuit breaker)."""
    import shutil

    wd = tempfile.mkdtemp(prefix="rtpu_gone_")

    @ray_tpu.remote(runtime_env={"working_dir": wd})
    def f():
        return 1

    shutil.rmtree(wd)  # dies between validation and spawn
    with pytest.raises(Exception, match="runtime_env|died|Worker"):
        ray_tpu.get(f.remote(), timeout=120)


def test_actor_unspawnable_env_surfaces_error(ray_start_regular):
    """Actor whose dedicated worker cannot spawn must raise RayActorError on
    its first method, with node resources returned (not re-acquired every
    scheduler pass)."""
    import shutil

    wd = tempfile.mkdtemp(prefix="rtpu_agone_")

    @ray_tpu.remote
    class A:
        def ping(self):
            return "up"

    handle = A.options(runtime_env={"working_dir": wd})
    shutil.rmtree(wd)
    a = handle.remote()
    with pytest.raises(Exception, match="spawn|died|Actor"):
        ray_tpu.get(a.ping.remote(), timeout=120)

    # the node is not drained: plain tasks still run
    @ray_tpu.remote
    def ok():
        return 1

    assert ray_tpu.get(ok.remote(), timeout=60) == 1


# ---------------------------------------------------------------------------
# pip runtime_env (reference python/ray/_private/runtime_env/pip.py):
# hash-keyed cached venvs built at worker spawn, offline via a local wheel
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def local_wheel():
    """Build a tiny wheel offline so pip can install a package that is NOT
    in the base environment."""
    import subprocess
    import sys

    src = tempfile.mkdtemp(prefix="rtpu_pkg_")
    pkg = os.path.join(src, "rtpu_testpkg")
    os.makedirs(pkg)
    with open(os.path.join(pkg, "__init__.py"), "w") as f:
        f.write("MAGIC = 42\n")
    with open(os.path.join(src, "pyproject.toml"), "w") as f:
        f.write(
            '[build-system]\nrequires = ["setuptools"]\n'
            'build-backend = "setuptools.build_meta"\n'
            '[project]\nname = "rtpu-testpkg"\nversion = "1.0"\n'
        )
    wheels = tempfile.mkdtemp(prefix="rtpu_whl_")
    subprocess.run(
        [sys.executable, "-m", "pip", "wheel", "--no-deps", "--no-index",
         "--no-build-isolation", "-w", wheels, src],
        check=True, capture_output=True, timeout=300,
    )
    return wheels


def _pip_env(wheels):
    return {"pip": {"packages": ["rtpu-testpkg"],
                    "pip_install_options": ["--no-index", "--find-links", wheels]}}


def test_pip_runtime_env_installs_package(ray_start_regular, local_wheel):
    with pytest.raises(ImportError):
        import rtpu_testpkg  # noqa: F401 — must be absent from the base env

    @ray_tpu.remote(runtime_env=_pip_env(local_wheel))
    def probe():
        import rtpu_testpkg

        return rtpu_testpkg.MAGIC

    assert ray_tpu.get(probe.remote(), timeout=300) == 42


def test_pip_runtime_env_cache_hit(ray_start_regular, local_wheel):
    """Same pip spec under a different env key reuses the venv (the ready
    marker is not rebuilt)."""
    from ray_tpu._private.runtime_env_setup import DEFAULT_BASE_DIR, pip_env_key

    env = _pip_env(local_wheel)

    @ray_tpu.remote(runtime_env=env)
    def first():
        import rtpu_testpkg

        return rtpu_testpkg.MAGIC

    assert ray_tpu.get(first.remote(), timeout=300) == 42
    marker = os.path.join(
        DEFAULT_BASE_DIR, f"pip-{pip_env_key(env['pip'])}", ".ready")
    assert os.path.exists(marker)
    mtime = os.path.getmtime(marker)

    # different env_vars -> different worker pool key, SAME venv
    env2 = dict(env, env_vars={"RTPU_MARK": "two"})

    @ray_tpu.remote(runtime_env=env2)
    def second():
        import os as _os

        import rtpu_testpkg

        return rtpu_testpkg.MAGIC, _os.environ.get("RTPU_MARK")

    t0 = time.time()
    assert ray_tpu.get(second.remote(), timeout=300) == (42, "two")
    assert os.path.getmtime(marker) == mtime, "venv was rebuilt, not reused"
    assert time.time() - t0 < 60, "cache hit should skip the install"


def test_pip_runtime_env_bad_package_fails(ray_start_regular):
    @ray_tpu.remote(runtime_env={"pip": {
        "packages": ["definitely-not-a-real-pkg-xyz"],
        "pip_install_options": ["--no-index"],
    }}, max_retries=0)
    def doomed():
        return 1

    with pytest.raises(Exception, match="runtime_env|died|setup"):
        ray_tpu.get(doomed.remote(), timeout=300)
