class HeadTable:
    def __init__(self):
        self.rows = {}  # EXPECT:R5 (grown below, never shrunk)
        self.capped = {}

    def on_push(self, origin, row):
        self.rows[origin] = row

    def on_other(self, origin):
        self.capped[origin] = 1
        if len(self.capped) > 100:
            self.capped.pop(next(iter(self.capped)))
