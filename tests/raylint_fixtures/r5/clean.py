class HeadTable:
    def __init__(self):
        self.rows = {}
        self.log = []

    def on_push(self, origin, row):
        self.rows[origin] = row
        self.log.append(origin)

    def expire(self, origin):
        self.rows.pop(origin, None)
        self.log.clear()
