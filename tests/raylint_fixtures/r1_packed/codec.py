"""Packed-codec fixture: the _UNPACK table is missing "beta" — R1 must
flag the skew (a frame type in the encoder but not the decoder is a
silent wire break at the peer)."""

_FRAME_IDS = {"alpha": 1, "beta": 2}

_PACK = {"alpha": None, "beta": None}

_UNPACK = {"alpha": None}  # EXPECT:R1
