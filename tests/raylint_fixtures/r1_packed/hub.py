"""Send + dispatch sites for the packed fixture types: both frame types
have live senders and handlers, so ONLY the table-skew finding fires."""


def serve(conn, msg):
    mtype = msg["type"]
    if mtype == "alpha":
        conn.ack()
    elif mtype == "beta":
        conn.ack()


def emit(conn):
    conn.send({"type": "alpha"})
    conn.send({"type": "beta"})
