import os
import uuid

_MODULE_SEED = os.urandom(8)  # module level: one-shot, must NOT fire


def submit(spec):
    task_id = uuid.uuid4().hex  # EXPECT:R3
    return task_id, spec


def seal(blob):
    key = os.urandom(16)  # EXPECT:R3
    return key, blob
