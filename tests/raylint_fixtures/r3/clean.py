import itertools
import os

_prefix = os.urandom(8)
_counter = itertools.count(1)


def submit(spec):
    return _prefix + str(next(_counter)).encode(), spec
