import socket


def dead_arm():
    try:
        socket.create_connection(("h", 1))
    except OSError:
        return None
    except TimeoutError:  # EXPECT:R2 (OSError above already catches it)
        return "timeout"


def swallowed(sock):
    try:
        data = sock.recv(1)
        if not data:
            raise TimeoutError("peer idle")  # EXPECT:R2 (eaten below)
    except OSError:
        sock.close()
