import socket


def narrow_first():
    try:
        socket.create_connection(("h", 1))
    except TimeoutError:
        return "timeout"
    except OSError:
        return None


def raise_escapes(sock):
    try:
        data = sock.recv(1)
    except OSError:
        sock.close()
        return None
    if not data:
        raise TimeoutError("peer idle")  # outside the try: propagates
    return data


def rereraised(sock):
    try:
        if not sock.recv(1):
            raise TimeoutError("peer idle")
    except OSError:
        sock.close()
        raise  # re-raise keeps the narrow exception alive
