class Head:
    def handle_list(self, what):
        if what == "gadgets":
            return ["g"]
        raise ValueError(what)
