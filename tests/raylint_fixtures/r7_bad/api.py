def _list(what, limit=100):
    return []


def list_widgets(limit=100):  # EXPECT:R7 x2 (no handler, no surface)
    return _list("widgets", limit)


def list_gadgets(limit=100):
    return _list("gadgets", limit)
