def cmd_list(args):
    if args.what == "gadgets":
        print("gadgets")
