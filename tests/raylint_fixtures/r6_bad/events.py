KNOWN_SOURCES = (
    "scheduler",
    "object_store",
)


def emit(source, message, **kw):
    pass
