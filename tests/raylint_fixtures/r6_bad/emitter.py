from r6_bad import events

_SOURCE = "schedulerr"  # typo'd


def notify():
    events.emit("scheduler", "ok")
    events.emit("not_declared", "boom")  # EXPECT:R6
    events.emit(_SOURCE, "typo")  # EXPECT:R6 (resolved via constant)
