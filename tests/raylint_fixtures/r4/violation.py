import threading
import time


class Table:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {}

    def slow_update(self, k, v):
        with self._lock:
            time.sleep(0.1)  # EXPECT:R4
            self._rows[k] = v

    def scan(self):
        with self._lock:
            import json  # EXPECT:R4

            return json.dumps(  # EXPECT:R4
                sorted(self._rows.values()))  # EXPECT:R4
