import json
import threading
import time


class Table:
    def __init__(self):
        self._lock = threading.Lock()
        self._rows = {}

    def update(self, k, v):
        with self._lock:
            self._rows[k] = v
        time.sleep(0.1)  # after release: fine

    def scan(self):
        with self._lock:
            snapshot = list(self._rows.values())
        return json.dumps(sorted(snapshot))

    def deferred(self):
        with self._lock:
            def later():
                time.sleep(1.0)  # runs after release: fine
            return later
