class Head:
    def handle_list(self, what):
        if what == "widgets":
            return ["w"]
        raise ValueError(what)
