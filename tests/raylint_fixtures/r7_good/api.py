def _list(what, limit=100):
    return []


def list_widgets(limit=100):
    return _list("widgets", limit)
