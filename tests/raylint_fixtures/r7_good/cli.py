def cmd_list(args):
    if args.what == "widgets":
        print("widgets")
