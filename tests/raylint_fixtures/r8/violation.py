import threading


def fire_and_forget(fn):
    threading.Thread(target=fn).start()  # EXPECT:R8


class Pump:
    def start(self):
        self._t = threading.Thread(target=self._loop)  # EXPECT:R8
        self._t.start()

    def _loop(self):
        pass
