import threading


def daemonized(fn):
    threading.Thread(target=fn, daemon=True).start()


class Pump:
    def start(self):
        self._t = threading.Thread(target=self._loop)
        self._t.start()

    def stop(self):
        self._t.join()

    def _loop(self):
        pass


def suppressed(fn):
    threading.Thread(target=fn).start()  # raylint: disable=R8 (short-lived)
