"""R1 fixture: one frame type nobody handles."""


class Client:
    def ping(self, conn):
        conn.send({"type": "ping_head"})

    def orphan(self, conn):
        conn.send({"type": "orphan_send"})  # EXPECT:R1 (no handler)
