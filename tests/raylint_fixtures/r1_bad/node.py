"""R1 fixture: one dead dispatch arm."""


class Node:
    def handle(self, msg):
        mtype = msg["type"]
        if mtype == "ping_head":
            return "pong"
        elif mtype == "dead_arm":  # EXPECT:R1 (no sender)
            return "never"
