class Node:
    def handle(self, msg):
        mtype = msg["type"]
        if mtype == "ping_head":
            return "pong"
        elif mtype == "batched_put":
            return "ok"
