class Client:
    def ping(self, conn):
        conn.send({"type": "ping_head"})

    def batched(self, conn):
        msg = {"type": "batched_put"}
        conn.send(msg)
