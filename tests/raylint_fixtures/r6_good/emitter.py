from r6_good import events


def notify(dynamic):
    events.emit("scheduler", "ok")
    events.emit("object_store", source="object_store")
    events.emit(dynamic, "not statically checkable: skipped")
