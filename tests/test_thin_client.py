"""Thin-client mode (Ray Client analog — reference ``ray.init("ray://...")``,
``python/ray/util/client/ARCHITECTURE.md``): a process that shares no shm
with the cluster drives it entirely over the control socket."""

import os
import subprocess
import sys
import textwrap

import ray_tpu


CLIENT_SCRIPT = textwrap.dedent("""
    import os
    import numpy as np
    import ray_tpu

    # simulate a foreign host: force a bogus shm namespace so any
    # accidental shm sharing would fail loudly
    os.environ["RAY_TPU_SESSION"] = "thin-client-isolated"

    ray_tpu.init(address=os.environ["THIN_ADDR"],
                 _authkey=bytes.fromhex(os.environ["THIN_KEY"]))
    from ray_tpu._private.worker import global_worker
    assert global_worker.thin_client

    # small put/get (inline path)
    r = ray_tpu.put({"a": 1})
    assert ray_tpu.get(r, timeout=60) == {"a": 1}

    # big put/get (blob path: > max_direct_call_object_size)
    arr = np.arange(300_000, dtype=np.float32)
    big = ray_tpu.put(arr)
    out = ray_tpu.get(big, timeout=120)
    np.testing.assert_array_equal(out, arr)

    # task with big args and big return, executed on the cluster
    @ray_tpu.remote
    def double(x):
        return x * 2

    res = ray_tpu.get(double.remote(arr), timeout=180)
    np.testing.assert_array_equal(res, arr * 2)

    # actor round trip
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0
        def add(self, k):
            self.n += k
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.add.remote(5), timeout=120) == 5
    assert ray_tpu.get(c.add.remote(7), timeout=120) == 12
    print("THIN_CLIENT_OK")
""")


def test_thin_client_end_to_end(ray_start_regular):
    from ray_tpu._private.worker import global_worker

    node = global_worker.node
    host, port = node.tcp_address
    env = dict(os.environ)
    env["THIN_ADDR"] = f"client://{host}:{port}"
    env["THIN_KEY"] = node.authkey.hex()
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "-c", CLIENT_SCRIPT],
        capture_output=True, text=True, timeout=420, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    assert "THIN_CLIENT_OK" in proc.stdout, (
        f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-3000:]}"
    )


REMOTE_OBJ_SCRIPT = textwrap.dedent("""
    import os
    import numpy as np
    import ray_tpu

    os.environ["RAY_TPU_SESSION"] = "thin-client-isolated-2"
    ray_tpu.init(address=os.environ["THIN_ADDR"],
                 _authkey=bytes.fromhex(os.environ["THIN_KEY"]))
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(
        os.environ["TARGET_NODE"]))
    def produce():
        return np.arange(200_000, dtype=np.float32)

    # the object lives on node B's private shm; the head must pull it
    # before shipping the payload bytes to this thin client
    out = ray_tpu.get(produce.remote(), timeout=180)
    assert out.shape == (200_000,) and out[-1] == 199_999.0
    print("THIN_REMOTE_OK")
""")


def test_thin_client_remote_node_object():
    """Thin-client get of an object produced on a real second node: head
    pulls the payload cross-node, then ships bytes over the socket."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu._private.worker import global_worker

    cluster = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 2, "num_tpus": 0},
        real_processes=True,
    )
    try:
        node_b = cluster.add_node(num_cpus=2)
        node = global_worker.node
        host, port = node.tcp_address
        env = dict(os.environ)
        env["THIN_ADDR"] = f"client://{host}:{port}"
        env["THIN_KEY"] = node.authkey.hex()
        env["TARGET_NODE"] = node_b
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run(
            [sys.executable, "-c", REMOTE_OBJ_SCRIPT],
            capture_output=True, text=True, timeout=420, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        assert "THIN_REMOTE_OK" in proc.stdout, (
            f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-3000:]}"
        )
    finally:
        cluster.shutdown()
