"""Serve data plane: HTTP keep-alive + chunked streaming responses + LLM
token streaming (the streaming half of the reference's starlette proxy,
``serve/_private/http_proxy.py:218``)."""

import http.client
import json
import time

import jax.numpy as jnp
import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_instance():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    client = serve.start(serve.HTTPOptions(host="127.0.0.1", port=0))
    yield client
    serve.shutdown()
    ray_tpu.shutdown()


def test_keep_alive_connection_reuse(serve_instance):
    @serve.deployment
    def echo(request):
        return {"n": request.json()["n"]}

    serve.run(echo.bind(), port=0)
    host, port = serve.get_http_address()
    conn = http.client.HTTPConnection(host, port, timeout=60)
    try:
        for i in range(3):  # same socket, three request/response cycles
            body = json.dumps({"n": i})
            conn.request("POST", "/echo", body=body,
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200
            assert json.loads(resp.read())["n"] == i
    finally:
        conn.close()


def test_streaming_response_chunks_arrive_incrementally(serve_instance):
    @serve.deployment
    class Streamer:
        def __call__(self, request):
            def gen():
                for i in range(4):
                    yield f"chunk-{i}\n"
                    time.sleep(0.8)

            return serve.StreamingResponse(gen())

    serve.run(Streamer.bind(), port=0)
    host, port = serve.get_http_address()
    conn = http.client.HTTPConnection(host, port, timeout=120)
    try:
        t0 = time.time()
        conn.request("GET", "/Streamer")
        resp = conn.getresponse()
        assert resp.status == 200
        assert resp.headers.get("Transfer-Encoding") == "chunked"
        first_at = None
        data = b""
        while True:
            piece = resp.read(16)
            if not piece:
                break
            if first_at is None:
                first_at = time.time() - t0
            data += piece
        total = time.time() - t0
        assert data.decode().splitlines() == [f"chunk-{i}" for i in range(4)]
        # the producer sleeps 0.8s per chunk (~3.2s total); the first chunk
        # must arrive long before the stream finishes
        assert first_at is not None and first_at < total - 1.5, (first_at, total)
    finally:
        conn.close()


def test_llm_token_streaming_over_http(serve_instance):
    from ray_tpu.serve.llm import llm_deployment

    dep = llm_deployment(
        "gpt2", "tiny",
        engine_kwargs=dict(n_slots=2, max_new_tokens=6,
                           decode_chunk_steps=3, prefill_buckets=(8,)),
        config_kwargs=dict(dtype=jnp.float32),
    )
    serve.run(dep.bind(), port=0, timeout_s=300)
    host, port = serve.get_http_address()

    def post(payload):
        conn = http.client.HTTPConnection(host, port, timeout=300)
        try:
            conn.request("POST", "/llm", body=json.dumps(payload),
                         headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            assert resp.status == 200, resp.read()
            return resp.read()
        finally:
            conn.close()

    plain = json.loads(post({"tokens": [3, 5, 7], "max_new_tokens": 6}))
    streamed = post({"tokens": [3, 5, 7], "max_new_tokens": 6,
                     "stream": True})
    toks = [int(x) for x in streamed.decode().split()]
    assert toks == plain["tokens"]  # greedy: identical either way
