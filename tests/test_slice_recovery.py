"""The slice failure domain, end-to-end (ROADMAP item 3 / VERDICT Weak #8).

Headline scenario: 16 emulated hosts form one TPU slice and hold a
STRICT_PACK training gang mid-run; chaos SIGKILLs one host.  The runtime
must detect the death (mesh + control EOF), declare the slice degraded,
restart the WHOLE gang from the latest checkpoint, and heal the fleet by
replacing the slice atomically (create-before-terminate) — with
``ray_tpu doctor`` explaining the incident while it is open and going
quiet after recovery.

Plus the pure-function halves: doctor's ``slice_degraded`` rule fire /
stay-silent semantics over synthetic events.
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.worker import global_worker
from ray_tpu.autoscaler import AutoscalingConfig, TrendAutoscaler
from ray_tpu.autoscaler.autoscaler import Monitor
from ray_tpu.autoscaler.local_node_provider import LocalNodeProvider
from ray_tpu.devtools.chaos import ChaosMonkey, Injection
from ray_tpu.util.doctor import diagnose

SLICE_HOSTS = 16
STEPS = 40


def _make_train_loop():
    """The gang's train fn, built as a CLOSURE: the gang runs in agent
    worker processes that cannot import this test module, so the fn must
    cloudpickle by value (a module-level fn pickles by reference and dies
    with ModuleNotFoundError on the far side)."""

    def _chaos_train_loop(config):
        import time as _time

        from ray_tpu.air import session
        from ray_tpu.air.checkpoint import Checkpoint

        ckpt = session.get_checkpoint()
        start = (ckpt.to_dict()["step"] + 1) if ckpt is not None else 0
        rank = session.get_world_rank()
        for step in range(start, config["steps"]):
            _time.sleep(0.25)
            if rank == 0:
                # progress marker the driver watches to time the injection
                with open(config["progress"], "w") as f:
                    f.write(str(step))
            session.report(
                {"step": step, "resumed_from": start},
                checkpoint=(Checkpoint.from_dict({"step": step})
                            if rank == 0 else None),
            )

    return _chaos_train_loop


def _wait(pred, timeout, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(0.2)
    pytest.fail(f"timed out waiting for {what}")


@pytest.fixture
def slice_fleet():
    # the head holds NO capacity: the gang can only live on the slice
    ray_tpu.init(num_cpus=0, num_tpus=0)
    node = global_worker.node
    provider = LocalNodeProvider(node, {"slice_hosts": SLICE_HOSTS}, "chaos")
    monitor = None
    try:
        yield node, provider, lambda m: monitor
    finally:
        provider.shutdown()
        ray_tpu.shutdown()


def test_sixteen_host_slice_chaos_recovery(slice_fleet, tmp_path):
    node, provider, _ = slice_fleet
    from ray_tpu.air import FailureConfig, RunConfig, ScalingConfig
    from ray_tpu.train.trainer import DataParallelTrainer

    cfg = AutoscalingConfig(
        min_workers=1, max_workers=1, idle_timeout_s=3600.0,
        worker_node={"num_cpus": 1, "slice_hosts": SLICE_HOSTS})
    autoscaler = TrendAutoscaler(node, provider, cfg)

    sid = provider.create_node({"num_cpus": 1}, 1)[0]
    members = provider.slice_members(sid)
    assert len(members) == SLICE_HOSTS
    _wait(lambda: all(m in node.nodes and node.nodes[m].alive
                      for m in members),
          120, "all 16 slice hosts to register")

    progress = tmp_path / "progress"
    trainer = DataParallelTrainer(
        _make_train_loop(),
        train_loop_config={"steps": STEPS, "progress": str(progress)},
        scaling_config=ScalingConfig(
            num_workers=SLICE_HOSTS,
            resources_per_worker={"CPU": 1},
            placement_strategy="STRICT_PACK"),
        run_config=RunConfig(
            storage_path=str(tmp_path), name="slice-chaos",
            failure_config=FailureConfig(max_failures=2)),
    )
    box = {}

    def run():
        try:
            box["result"] = trainer.fit()
        except BaseException as e:  # noqa: BLE001 — surfaced by the test
            box["error"] = e

    th = threading.Thread(target=run, daemon=True)
    th.start()

    # mid-train: rank 0 has taken (and checkpointed) a few steps
    _wait(lambda: progress.exists() and int(progress.read_text() or 0) >= 3,
          240, "training to reach step 3")

    # the gang leased STRICT_PACK *within the slice*: one bundle per host
    with node.lock:
        pgs = [rt for rt in node.pgs.values() if rt.info.state == "CREATED"]
        assert pgs, "no placement group created for the gang"
        bundle_nodes = list(pgs[0].info.bundle_nodes)
    assert set(bundle_nodes) <= set(members)
    assert len(set(bundle_nodes)) == SLICE_HOSTS  # spread across all hosts

    # chaos: SIGKILL a seeded-random member of THE slice, mid-train
    cm = ChaosMonkey(node=node, procs=provider.procs, seed=7)
    rec = cm.inject(Injection(at_s=0.0, op="sigkill", slice_id=sid))
    victim = rec["target"]
    assert victim in members

    _wait(lambda: not node.nodes[victim].alive, 60,
          "head to observe the member death")

    # doctor DURING the incident: slice degraded, no replacement in flight
    from ray_tpu.experimental.state import api as state

    events = state.list_events(limit=10_000)
    open_findings = diagnose(events)
    assert "slice_degraded" in [f["rule"] for f in open_findings], \
        [f["rule"] for f in open_findings]
    assert any(e.get("source") == "chaos" and e.get("entity_id") == victim
               for e in events), "injection missing from the flight recorder"

    # now let the autoscaler heal: slice-atomic replacement
    monitor = Monitor(autoscaler, interval_s=0.5).start()
    try:
        th.join(timeout=420)
        assert not th.is_alive(), "training never completed after the kill"
    finally:
        monitor.stop()
        cm.stop()
    assert "error" not in box, box.get("error")
    result = box["result"]
    assert result.error is None, result.error

    # whole-gang restart + checkpoint resume: the final report comes from
    # a SECOND gang incarnation that started from a mid-run checkpoint
    assert result.metrics["step"] == STEPS - 1
    assert result.metrics["resumed_from"] >= 3, result.metrics

    events = state.list_events(limit=20_000)

    def _rows(source, message):
        return [e for e in events if e.get("source") == source
                and e.get("message") == message]

    assert _rows("train", "gang restarted"), "no whole-gang restart"
    replaced = _rows("autoscaler", "slice replaced")
    assert any(r.get("entity_id") == sid for r in replaced), replaced

    # slice-atomic replacement: the old slice is gone WHOLE, the new one
    # is whole and holds the gang's world size
    live = provider.non_terminated_nodes()
    assert sid not in live
    new_sid = next(r["data"]["replacement"] for r in replaced
                   if r.get("entity_id") == sid)
    assert new_sid in live
    new_members = provider.slice_members(new_sid)
    assert len(new_members) == SLICE_HOSTS
    _wait(lambda: all(m in node.nodes and node.nodes[m].alive
                      for m in new_members),
          60, "replacement slice fully registered")

    # the restarted gang lives ON the replacement slice
    with node.lock:
        pgs = [rt for rt in node.pgs.values() if rt.info.state == "CREATED"]
        placed = {n for rt in pgs for n in rt.info.bundle_nodes}
    assert placed <= set(new_members) | set()  # old hosts are dead

    # doctor AFTER recovery: the replacement closed the incident — the
    # slice_degraded finding clears (gang_restart remains as the
    # explanation of what happened, which is the point of the recorder)
    closed = diagnose(events)
    assert "slice_degraded" not in [f["rule"] for f in closed], \
        [f["rule"] for f in closed]

    # the failure-domain view agrees: only the healthy replacement remains
    rows = state.list_slices()
    by_id = {r["slice_id"]: r for r in rows}
    assert by_id[new_sid]["alive_members"] == SLICE_HOSTS
    assert not by_id[new_sid]["degraded"]


# ---------------------------------------------------------------------------
# doctor rule: pure-function fire / stay-silent
# ---------------------------------------------------------------------------

def _ev(source, message, entity_id, ts, **data):
    return {"source": source, "message": message, "entity_id": entity_id,
            "ts": ts, "severity": "ERROR", "data": data}


def test_slice_degraded_rule_fires_without_repair():
    f = diagnose([_ev("node", "slice degraded", "s1", 100.0)])
    rules = {x["rule"]: x for x in f}
    assert "slice_degraded" in rules
    assert rules["slice_degraded"]["severity"] == "ERROR"
    assert "s1" in rules["slice_degraded"]["summary"]


def test_slice_degraded_rule_clears_once_repair_in_flight():
    evs = [_ev("node", "slice degraded", "s1", 100.0)]
    evs.append(_ev("autoscaler", "slice replacement started", "s1", 101.0))
    assert "slice_degraded" not in [x["rule"] for x in diagnose(evs)]

    # a NEW degradation after the last repair re-opens the incident
    evs.append(_ev("node", "slice degraded", "s1", 200.0))
    assert "slice_degraded" in [x["rule"] for x in diagnose(evs)]

    # repairing a DIFFERENT slice does not close it
    evs.append(_ev("autoscaler", "slice replaced", "s2", 300.0))
    assert "slice_degraded" in [x["rule"] for x in diagnose(evs)]

    # repairing THE slice does
    evs.append(_ev("autoscaler", "slice replaced", "s1", 301.0))
    assert "slice_degraded" not in [x["rule"] for x in diagnose(evs)]


def test_slice_degraded_rule_reopens_when_replacement_fails():
    """'started' alone is only a suppression while IN FLIGHT: a later
    'failed' means the slice is still degraded — doctor must not stay
    silent under e.g. persistent quota exhaustion."""
    evs = [
        _ev("node", "slice degraded", "s1", 100.0),
        _ev("autoscaler", "slice replacement started", "s1", 101.0),
        _ev("autoscaler", "slice replacement failed", "s1", 102.0),
    ]
    assert "slice_degraded" in [x["rule"] for x in diagnose(evs)]

    # a retry puts it back in flight...
    evs.append(_ev("autoscaler", "slice replacement started", "s1", 103.0))
    assert "slice_degraded" not in [x["rule"] for x in diagnose(evs)]
    # ...and its success closes the incident for good
    evs.append(_ev("autoscaler", "slice replaced", "s1", 104.0))
    assert "slice_degraded" not in [x["rule"] for x in diagnose(evs)]


def test_slice_degraded_rule_silent_on_healthy_events():
    evs = [
        _ev("node", "node removed", "n1", 1.0),
        _ev("autoscaler", "scale up: launched nodes", None, 2.0),
        _ev("chaos", "inject sigkill", "n1", 3.0),
    ]
    assert "slice_degraded" not in [x["rule"] for x in diagnose(evs)]
