"""Native arena store (src/store_core — the plasma analog).

The head's objects live as slices of one C++-managed arena: allocation,
free-list recycling, index, eviction decommit.  Workers attach the arena
file zero-copy on the same host; remote nodes pull arena slices through
the object plane.
"""

import gc
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.native import available

pytestmark = pytest.mark.skipif(not available(), reason="no C++ toolchain")


def _arena():
    node = ray_tpu._private.worker.global_worker.node
    assert node.arena is not None, "native arena did not come up"
    return node.arena


def test_puts_go_through_arena(ray_start_regular):
    arena = _arena()
    before = arena.stats()["num_objects"]
    ref = ray_tpu.put(np.ones(1 << 20))
    stats = arena.stats()
    assert stats["num_objects"] == before + 1
    out = ray_tpu.get(ref)
    assert out.nbytes == 8 << 20


def test_arena_reclaims_on_ref_drop(ray_start_regular):
    """The VERDICT bar: a loop putting throwaway arrays holds steady-state
    memory — freed slices recycle through the C++ free list."""
    arena = _arena()
    for _ in range(12):
        ref = ray_tpu.put(np.random.default_rng(0).standard_normal(4 << 20))  # 32MB
        assert ray_tpu.get(ref).shape == (4 << 20,)
        del ref
        gc.collect()
        ray_tpu.global_worker.flush_removals()
    import time

    deadline = time.time() + 10
    while time.time() < deadline:
        gc.collect()
        ray_tpu.global_worker.flush_removals()
        if arena.stats()["bytes_used"] < 100 << 20:
            break
        time.sleep(0.3)
    stats = arena.stats()
    # 12 x 32MB churned; steady state must be far below the total
    assert stats["bytes_used"] < 100 << 20, stats


def test_worker_reads_arena_object(ray_start_regular):
    """Same-host workers attach the arena file and slice zero-copy."""
    payload = np.arange(1 << 20, dtype=np.float64)
    ref = ray_tpu.put(payload)

    @ray_tpu.remote
    def total(x):
        return float(np.sum(x))

    assert ray_tpu.get(total.remote(ref), timeout=120) == pytest.approx(
        float(np.sum(payload)))


def test_remote_node_pulls_arena_slice():
    """A driver-put arena object is pulled across the node boundary (the
    arena-slice request path of the object server)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2}, real_processes=True)
    try:
        node_b = cluster.add_node(num_cpus=2)
        arena = _arena()
        payload = np.random.default_rng(1).standard_normal(1 << 20)  # 8MB
        ref = ray_tpu.put(payload)
        assert arena.stats()["num_objects"] >= 1

        @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(node_b))
        def checksum(x):
            return float(np.sum(x))

        assert ray_tpu.get(checksum.remote(ref), timeout=180) == pytest.approx(
            float(np.sum(payload)))
    finally:
        cluster.shutdown()


def test_zero_copy_views_pin_arena_slots(ray_start_regular):
    """A live numpy view of an arena object must keep its slot pinned:
    dropping the ObjectRef and churning new puts must NOT corrupt the
    array (the plasma client-pin semantics)."""
    arena = _arena()
    payload = np.full(1 << 20, 7.0)
    ref = ray_tpu.put(payload)
    arr = ray_tpu.get(ref)  # zero-copy view into the arena
    del ref
    gc.collect()
    ray_tpu.global_worker.flush_removals()
    import time

    time.sleep(1.5)
    # churn allocations that would reuse a freed slot
    for i in range(6):
        r = ray_tpu.put(np.full(1 << 20, float(i)))
        ray_tpu.get(r)
        del r
        gc.collect()
        ray_tpu.global_worker.flush_removals()
    assert float(arr[0]) == 7.0 and float(arr[-1]) == 7.0, "view corrupted!"
    # once the view dies, the slot is reclaimable
    used_with_pin = arena.stats()["bytes_used"]
    del arr
    gc.collect()
    ray_tpu.global_worker.flush_removals()
    deadline = time.time() + 10
    while time.time() < deadline:
        gc.collect()
        ray_tpu.global_worker.flush_removals()
        if arena.stats()["bytes_used"] < used_with_pin:
            break
        time.sleep(0.3)
    assert arena.stats()["bytes_used"] < used_with_pin


def test_cpp_unit_tests_under_asan():
    """Build + run the C++ allocator unit tests under ASan/UBSan
    (src/store_core/store_core_test.cc): free-list reuse, coalescing,
    fragmentation, accounting, randomized churn invariants."""
    import os
    import shutil
    import subprocess
    import sys

    if shutil.which("make") is None:
        pytest.skip("make not available")
    src_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src", "store_core")
    out = subprocess.run(["make", "test"], cwd=src_dir,
                         capture_output=True, text=True, timeout=300)
    sys.stdout.write(out.stdout[-1000:])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "ALL OK" in out.stdout
    # the RefIndex (head refcount hot maps) suite ran too — including
    # the concurrent batch add/remove churn, the race profile of a
    # GIL-released submission wave
    assert "refs concurrent churn OK" in out.stdout


@pytest.mark.slow
def test_cpp_capacity_vs_close_under_tsan():
    """The PR 1 race proof under ThreadSanitizer: one thread close()s the
    arena while others spin on capacity/bytes_used/get/put
    (test_close_vs_capacity in store_core_test.cc).  Also proves the
    RAY_TPU_STORE_TSAN=1 build path produces the instrumented .so."""
    import os
    import shutil
    import subprocess
    import sys

    if shutil.which("make") is None:
        pytest.skip("make not available")
    src_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src", "store_core")
    out = subprocess.run(["make", "test-tsan"], cwd=src_dir,
                         capture_output=True, text=True, timeout=600)
    sys.stdout.write(out.stdout[-1000:])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "ALL OK" in out.stdout
    assert "WARNING: ThreadSanitizer" not in out.stderr

    # the env-gated runtime build: same flags, separate cache name
    from ray_tpu._private import native

    env_before = os.environ.get("RAY_TPU_STORE_TSAN")
    os.environ["RAY_TPU_STORE_TSAN"] = "1"
    try:
        path = native._build()
    finally:
        if env_before is None:
            os.environ.pop("RAY_TPU_STORE_TSAN", None)
        else:
            os.environ["RAY_TPU_STORE_TSAN"] = env_before
    assert path is not None and path.endswith("_tsan.so")
    assert os.path.exists(path)


# ---------------------------------------------------------------------------
# RefIndex: the head registry's hot maps in C++ (+ the Python twin)
# ---------------------------------------------------------------------------

@pytest.mark.skipif(not available(), reason="native store core unavailable")
def test_refindex_binding_lifecycle():
    """ctypes binding contract: ensure/add/remove batches over packed
    oids, erase-at-zero atomic with the decrement, pins clamped at 0."""
    from ray_tpu._private.native import RefIndex

    ix = RefIndex()
    oids = [bytes([i]) * 16 for i in (1, 2, 3)]
    packed = b"".join(oids)
    ix.ensure(packed, 3, 0)
    ix.ensure(packed, 3, 0)  # setdefault: second call is a no-op
    ix.add(packed, 3, 1, 2)  # +2 task_arg each
    count, sealed, pins = ix.get(oids[0])
    assert (count, sealed) == (3, False)
    assert pins[0] == 1 and pins[1] == 2
    assert ix.size() == 3

    # sealed + decrement-to-zero erases atomically and reports the oid
    assert ix.seal(oids[0]) == 0
    dead = ix.remove(oids[0], 1, 1, 2)
    assert dead == []
    dead = ix.remove(oids[0], 1, 0, 1)
    assert dead == [oids[0]]
    assert ix.get(oids[0]) is None

    # unsealed entries linger negative; seal() then reclaims (returns 1)
    assert ix.remove(oids[1], 1, 0, 5) == []
    count, sealed, pins = ix.get(oids[1])
    assert count == -2 and pins[0] == 0  # pins clamp at zero
    assert ix.seal(oids[1]) == 1
    assert not ix.contains(oids[1])

    counts, pin_rows = ix.get_batch(packed, 3)
    assert counts[0] is None and counts[1] is None and counts[2] == 3
    assert pin_rows[2][1] == 2
    ix.clear()
    assert ix.size() == 0


def test_registry_parity_native_vs_python_refs():
    """The pure-Python ref index is a drop-in twin of the C one: the
    same lifecycle script must produce identical audit rows and
    identical survivors through the full ObjectRegistry surface."""
    import importlib

    import ray_tpu._private.object_store as osm

    def run(flag):
        os.environ["RAY_TPU_NATIVE_REFS"] = flag
        try:
            reg = osm.ObjectRegistry()
            a, b, c = (bytes([9, i]) * 8 for i in (1, 2, 3))
            reg.create_pending_batch([a, b, c])
            reg.seal(a, osm.ObjectLocation(inline=b"A"), owner="w1",
                     owner_kind="worker")
            reg.seal(b, osm.ObjectLocation(inline=b"BB"), contained=[a],
                     owner="w1", owner_kind="worker")
            reg.add_refs([a, b], reason="task_arg")
            reg.remove_refs([a], reason="handle")  # containment keeps a
            rows = {r["object_id"]: (r["ref_count"], r["pins"],
                                     r["pin_reason"]) for r in
                    reg.memory_audit()}
            summary = reg.owner_summary()
            # drop everything: b's deletion cascades to a
            reg.remove_refs([a, b], reason="task_arg")
            reg.remove_refs([b], reason="handle")
            survivors = (reg.contains(a), reg.contains(b), reg.contains(c))
            reg.shutdown()
            return type(reg._refs).__name__, rows, summary, survivors
        finally:
            os.environ.pop("RAY_TPU_NATIVE_REFS", None)

    name_native, rows_n, sum_n, surv_n = run("1")
    name_py, rows_p, sum_p, surv_p = run("0")
    assert name_py == "_PyRefs"
    if name_native != "_NativeRefs":
        pytest.skip("native refs unavailable in this environment")
    assert rows_n == rows_p
    assert sum_n == sum_p
    assert surv_n == surv_p == (False, False, True)


def test_registry_full_lifecycle_on_python_refs():
    """A real cluster runs end-to-end with RAY_TPU_NATIVE_REFS=0 (the
    no-toolchain fallback): puts, tasks, refcount-driven reclamation."""
    import subprocess
    import sys

    code = r"""
import gc
import ray_tpu
from ray_tpu._private.worker import global_worker

ray_tpu.init(num_cpus=2, num_tpus=0)
assert type(global_worker.node.registry._refs).__name__ == "_PyRefs"

@ray_tpu.remote
def double(x):
    return x * 2

assert ray_tpu.get([double.remote(i) for i in range(16)], timeout=120) \
    == [i * 2 for i in range(16)]
ref = ray_tpu.put(b"z" * 4096)
assert ray_tpu.get(ref) == b"z" * 4096
oid = ref.binary()
reg = global_worker.node.registry
del ref
gc.collect()
global_worker.flush_removals()
import time
deadline = time.time() + 10
while reg.contains(oid) and time.time() < deadline:
    time.sleep(0.1)
assert not reg.contains(oid), "refcount reclamation broken on _PyRefs"
ray_tpu.shutdown()
print("PYREFS_OK")
"""
    env = dict(os.environ, RAY_TPU_NATIVE_REFS="0")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert "PYREFS_OK" in proc.stdout, proc.stderr[-3000:]
