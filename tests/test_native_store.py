"""Native arena store (src/store_core — the plasma analog).

The head's objects live as slices of one C++-managed arena: allocation,
free-list recycling, index, eviction decommit.  Workers attach the arena
file zero-copy on the same host; remote nodes pull arena slices through
the object plane.
"""

import gc

import numpy as np
import pytest

import ray_tpu
from ray_tpu._private.native import available

pytestmark = pytest.mark.skipif(not available(), reason="no C++ toolchain")


def _arena():
    node = ray_tpu._private.worker.global_worker.node
    assert node.arena is not None, "native arena did not come up"
    return node.arena


def test_puts_go_through_arena(ray_start_regular):
    arena = _arena()
    before = arena.stats()["num_objects"]
    ref = ray_tpu.put(np.ones(1 << 20))
    stats = arena.stats()
    assert stats["num_objects"] == before + 1
    out = ray_tpu.get(ref)
    assert out.nbytes == 8 << 20


def test_arena_reclaims_on_ref_drop(ray_start_regular):
    """The VERDICT bar: a loop putting throwaway arrays holds steady-state
    memory — freed slices recycle through the C++ free list."""
    arena = _arena()
    for _ in range(12):
        ref = ray_tpu.put(np.random.default_rng(0).standard_normal(4 << 20))  # 32MB
        assert ray_tpu.get(ref).shape == (4 << 20,)
        del ref
        gc.collect()
        ray_tpu.global_worker.flush_removals()
    import time

    deadline = time.time() + 10
    while time.time() < deadline:
        gc.collect()
        ray_tpu.global_worker.flush_removals()
        if arena.stats()["bytes_used"] < 100 << 20:
            break
        time.sleep(0.3)
    stats = arena.stats()
    # 12 x 32MB churned; steady state must be far below the total
    assert stats["bytes_used"] < 100 << 20, stats


def test_worker_reads_arena_object(ray_start_regular):
    """Same-host workers attach the arena file and slice zero-copy."""
    payload = np.arange(1 << 20, dtype=np.float64)
    ref = ray_tpu.put(payload)

    @ray_tpu.remote
    def total(x):
        return float(np.sum(x))

    assert ray_tpu.get(total.remote(ref), timeout=120) == pytest.approx(
        float(np.sum(payload)))


def test_remote_node_pulls_arena_slice():
    """A driver-put arena object is pulled across the node boundary (the
    arena-slice request path of the object server)."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy

    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 2}, real_processes=True)
    try:
        node_b = cluster.add_node(num_cpus=2)
        arena = _arena()
        payload = np.random.default_rng(1).standard_normal(1 << 20)  # 8MB
        ref = ray_tpu.put(payload)
        assert arena.stats()["num_objects"] >= 1

        @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(node_b))
        def checksum(x):
            return float(np.sum(x))

        assert ray_tpu.get(checksum.remote(ref), timeout=180) == pytest.approx(
            float(np.sum(payload)))
    finally:
        cluster.shutdown()


def test_zero_copy_views_pin_arena_slots(ray_start_regular):
    """A live numpy view of an arena object must keep its slot pinned:
    dropping the ObjectRef and churning new puts must NOT corrupt the
    array (the plasma client-pin semantics)."""
    arena = _arena()
    payload = np.full(1 << 20, 7.0)
    ref = ray_tpu.put(payload)
    arr = ray_tpu.get(ref)  # zero-copy view into the arena
    del ref
    gc.collect()
    ray_tpu.global_worker.flush_removals()
    import time

    time.sleep(1.5)
    # churn allocations that would reuse a freed slot
    for i in range(6):
        r = ray_tpu.put(np.full(1 << 20, float(i)))
        ray_tpu.get(r)
        del r
        gc.collect()
        ray_tpu.global_worker.flush_removals()
    assert float(arr[0]) == 7.0 and float(arr[-1]) == 7.0, "view corrupted!"
    # once the view dies, the slot is reclaimable
    used_with_pin = arena.stats()["bytes_used"]
    del arr
    gc.collect()
    ray_tpu.global_worker.flush_removals()
    deadline = time.time() + 10
    while time.time() < deadline:
        gc.collect()
        ray_tpu.global_worker.flush_removals()
        if arena.stats()["bytes_used"] < used_with_pin:
            break
        time.sleep(0.3)
    assert arena.stats()["bytes_used"] < used_with_pin


def test_cpp_unit_tests_under_asan():
    """Build + run the C++ allocator unit tests under ASan/UBSan
    (src/store_core/store_core_test.cc): free-list reuse, coalescing,
    fragmentation, accounting, randomized churn invariants."""
    import os
    import shutil
    import subprocess
    import sys

    if shutil.which("make") is None:
        pytest.skip("make not available")
    src_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src", "store_core")
    out = subprocess.run(["make", "test"], cwd=src_dir,
                         capture_output=True, text=True, timeout=300)
    sys.stdout.write(out.stdout[-1000:])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "ALL OK" in out.stdout


@pytest.mark.slow
def test_cpp_capacity_vs_close_under_tsan():
    """The PR 1 race proof under ThreadSanitizer: one thread close()s the
    arena while others spin on capacity/bytes_used/get/put
    (test_close_vs_capacity in store_core_test.cc).  Also proves the
    RAY_TPU_STORE_TSAN=1 build path produces the instrumented .so."""
    import os
    import shutil
    import subprocess
    import sys

    if shutil.which("make") is None:
        pytest.skip("make not available")
    src_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src", "store_core")
    out = subprocess.run(["make", "test-tsan"], cwd=src_dir,
                         capture_output=True, text=True, timeout=600)
    sys.stdout.write(out.stdout[-1000:])
    assert out.returncode == 0, out.stderr[-2000:]
    assert "ALL OK" in out.stdout
    assert "WARNING: ThreadSanitizer" not in out.stderr

    # the env-gated runtime build: same flags, separate cache name
    from ray_tpu._private import native

    env_before = os.environ.get("RAY_TPU_STORE_TSAN")
    os.environ["RAY_TPU_STORE_TSAN"] = "1"
    try:
        path = native._build()
    finally:
        if env_before is None:
            os.environ.pop("RAY_TPU_STORE_TSAN", None)
        else:
            os.environ["RAY_TPU_STORE_TSAN"] = env_before
    assert path is not None and path.endswith("_tsan.so")
    assert os.path.exists(path)
