"""Wire-protocol codec tests (ray_tpu/_private/wire.py + the packed hot
codec in packed_wire.py + the protobuf IDL in ray_tpu/protocol/
ray_tpu.proto — reference src/ray/protobuf/).

The end-to-end proof is the whole suite: RAY_TPU_WIRE defaults to
"proto", so every cluster test already runs over the typed wire (packed
hot frames + Envelope long tail).  These tests pin the codec contracts
themselves: dict->wire->dict identity for every typed arm in BOTH typed
encodings, the pickle fallback, oversize gating per hot frame type,
codec/IDL parity, version rejection, and legacy-frame sniffing.
"""

import pickle

import pytest

from ray_tpu._private import packed_wire, wire
from ray_tpu._private.object_store import ObjectLocation
from ray_tpu.protocol import ray_tpu_pb2 as pb


FULL_SPEC = {
    "task_id": b"t1", "name": "f", "return_ids": [b"r1", b"r2"],
    "num_returns": 2, "fn_id": b"fn", "args_blob": b"blob",
    "dep_ids": [b"d1"], "pinned_refs": [b"d1", b"n1"], "owned_oids": [b"o"],
    "resources": {"CPU": 1.0, "TPU": 2.0}, "retries_left": 3,
    "scheduling_strategy": {"type": "node_affinity", "node_id": "n3"},
    "runtime_env": {"env_vars": {"A": "1"}}, "max_concurrency": 4,
    "parent_task_id": b"p", "trace_ctx": {"trace_id": "ab", "span_id": "cd"},
}

SHM_LOC = ObjectLocation(shm_name="seg", size=128, node_id="n2",
                         fetch_addr=("10.0.0.2", 7001),
                         arena_path="/dev/shm/arena", arena_off=4096,
                         arena_key=b"k")

# Hot frames: the packed codec owns these in proto mode (magic 0xB1).
PACKED_MESSAGES = [
    {"type": "submit_batch",
     "batch": [("task", FULL_SPEC),
               ("actor_task", {"task_id": b"t2", "name": "A.m",
                               "return_ids": [b"r"], "num_returns": 1,
                               "actor_id": b"a", "method_name": "m",
                               "dynamic_returns": True})]},
    {"type": "execute", "spec": FULL_SPEC,
     "dep_locs": {b"d1": SHM_LOC}, "tpu_ids": [0, 2]},
    {"type": "task_done",
     "seals": [(b"r1", ObjectLocation(inline=b"xy"), [b"c1"]),
               (b"r2", SHM_LOC, [])],
     "spec_ref": {"task_id": b"t1", "return_ids": [b"r1", b"r2"],
                  "is_actor_creation": None, "actor_id": None, "name": "f"},
     "failed": True, "error_str": "boom", "exec_start": 1.5, "exec_end": 2.5,
     "worker_pid": 42},
    {"type": "seal", "oid": b"o", "loc": ObjectLocation(spilled_path="/s", size=9),
     "contained": [b"c"]},
    # the packed ref arms carry the pin reason (the Envelope RefUpdate
    # schema predates it)
    {"type": "add_ref", "oids": [b"a", b"b"], "reason": "handle"},
    {"type": "remove_ref", "oids": [b"a"], "reason": "task_arg"},
    {"type": "metrics_report", "origin": "worker-1",
     "metrics": {"gauges": {"rss_mb": 41.5}}},
    {"type": "get_locations", "oids": [b"o1", b"o2"], "timeout": None,
     "req_id": 3},
    {"type": "wait", "oids": [b"o"], "num_returns": 1, "timeout": 2.5,
     "req_id": 4},
    # the three typed reply shapes (ray.get / ray.wait RTT path)
    {"type": "reply", "req_id": 3,
     "locations": {b"o": ObjectLocation(inline=b"v", is_error=True)}},
    {"type": "reply", "req_id": 4, "ready": [],
     "locations": {}},  # wait that timed out with nothing ready
    {"type": "reply", "req_id": 5, "timeout": True},
]

# Typed-but-not-hot frames: these keep the protobuf Envelope arm.
ENVELOPE_MESSAGES = [
    {"type": "kv_put", "ns": "fn", "key": b"k", "value": b"v" * 100},
    {"type": "kv_get", "ns": "fn", "key": b"k", "req_id": 9},
    {"type": "ping"},
]

TYPED_MESSAGES = PACKED_MESSAGES + ENVELOPE_MESSAGES


@pytest.mark.parametrize("msg", TYPED_MESSAGES,
                         ids=lambda m: m["type"] + str(m.get("req_id", "")))
def test_typed_roundtrip_identity(msg):
    assert wire.decode(wire.encode(msg)) == msg


@pytest.mark.parametrize("msg", PACKED_MESSAGES, ids=lambda m: m["type"])
def test_hot_frames_take_the_packed_arm(msg):
    # a silent fallback to the Envelope (or pickle) still roundtrips and
    # would regress the hot-path cost unnoticed — pin the encoding
    frame = wire.encode(msg)
    assert frame[:1] == packed_wire.MAGIC_BYTE, msg["type"]
    assert frame[1] == packed_wire.PACKED_VERSION


@pytest.mark.parametrize("msg", PACKED_MESSAGES, ids=lambda m: m["type"])
def test_hot_frames_envelope_arm_still_works(msg):
    # RAY_TPU_WIRE=envelope (and any pre-packed peer): the same hot
    # frames must round-trip through the protobuf arm.  The ref arms
    # with a reason fall back to pickle there (RefUpdate predates pin
    # reasons and would silently drop them).
    frame = wire.encode(msg, packed=False)
    assert wire.decode(frame) == msg
    reason = msg.get("reason", "handle")
    if msg["type"] in ("add_ref", "remove_ref") and reason != "handle":
        assert frame[:1] == b"\x80"
    elif msg["type"] == "metrics_report":
        assert frame[:1] == b"\x80"  # no Envelope arm for metrics
    else:
        assert frame[:1] == b"\x08"


@pytest.mark.parametrize("msg", ENVELOPE_MESSAGES,
                         ids=lambda m: m["type"] + str(m.get("req_id", "")))
def test_envelope_messages_do_not_use_pickle(msg):
    # every typed long-tail message — including all three reply shapes
    # on the ray.get/ray.wait RTT path — must actually take the typed
    # Envelope arm
    frame = wire.encode(msg)
    assert frame[:1] == b"\x08", msg["type"]
    env = pb.Envelope.FromString(frame)
    assert env.WhichOneof("body") not in (None, "pickled"), msg["type"]
    assert env.version == wire.WIRE_VERSION


def test_untyped_fallback_is_raw_pickle():
    # the long-tail arm ships RAW pickle frames: no envelope wrap means
    # no double copy and no protobuf 2 GiB cap for multi-GiB blobs
    msg = {"type": "register_worker", "worker_id": b"w", "pid": 1,
           "weird": {("tuple", "key"): [1, 2, {3}]}}
    frame = wire.encode(msg)
    assert frame[:1] == b"\x80"
    assert pickle.loads(frame) == msg
    assert wire.decode(frame) == msg


def test_reply_with_arbitrary_value_falls_back():
    msg = {"type": "reply", "req_id": 6, "value": {"locations": "not-a-loc"}}
    frame = wire.encode(msg)
    assert wire.decode(frame) == msg
    assert frame[:1] == b"\x80"


def test_execute_with_none_dep_loc_falls_back():
    # a dep can unseal between scheduling and dispatch: get_location
    # returns None, which the typed ObjectLocation cannot represent
    msg = {"type": "execute",
           "spec": {"task_id": b"t", "name": "f", "return_ids": [],
                    "num_returns": 1},
           "dep_locs": {b"d": None}}
    frame = wire.encode(msg)
    assert frame[:1] == b"\x80"
    assert wire.decode(frame) == msg


def test_oversized_kv_put_takes_pickle_arm(monkeypatch):
    # a near-/over-2 GiB kv_put value must NEVER ride the typed arm: upb
    # would serialize it but no receiver can parse the frame (DecodeError
    # at the peer = silent wire break), and the C++ backend raises at
    # SerializeToString.  Exercise the real gate with the cap lowered so
    # the test doesn't allocate 2 GiB.
    monkeypatch.setattr(wire, "_PB_MAX_FRAME", 1 << 10)
    msg = {"type": "kv_put", "ns": "n", "key": b"k", "value": b"v" * (1 << 10)}
    frame = wire.encode(msg)
    assert frame[:1] == b"\x80"  # raw pickle, no cap
    assert wire.decode(frame) == msg
    # under the gate the typed arm still wins
    small = {"type": "kv_put", "ns": "n", "key": b"k", "value": b"v"}
    assert wire.encode(small)[:1] == b"\x08"
    assert wire.decode(wire.encode(small)) == small


def test_oversized_typed_frame_falls_back(monkeypatch):
    # any OTHER typed arm that grows past the parse cap (big inline task
    # args, batched seals) is caught after serialization by the frame-
    # length check — encode() must return a pickle frame, not leak an
    # unparseable envelope or an exception
    monkeypatch.setattr(wire, "_PB_MAX_FRAME", 16)
    msg = {"type": "kv_get", "ns": "n", "key": b"k" * 64, "req_id": 9}
    frame = wire.encode(msg)
    assert frame[:1] == b"\x80"
    assert wire.decode(frame) == msg


def test_serialize_raise_falls_back(monkeypatch):
    # a backend that refuses at SerializeToString time (C++ 2 GiB cap)
    # must also land on the pickle arm instead of raising out of encode()
    class Boom:
        def __getattr__(self, name):
            import types

            return types.SimpleNamespace()  # absorbs any typed-arm field

        def SerializeToString(self):
            raise ValueError("message too large")

    real_envelope = wire.pb.Envelope
    monkeypatch.setattr(wire.pb, "Envelope", lambda **kw: Boom())
    try:
        msg = {"type": "kv_get", "ns": "n", "key": b"k", "req_id": 1}
        frame = wire.encode(msg)
    finally:
        monkeypatch.setattr(wire.pb, "Envelope", real_envelope)
    assert frame[:1] == b"\x80"
    assert wire.decode(frame) == msg


_OVERSIZE_MESSAGES = [
    {"type": "submit_batch",
     "batch": [("task", dict(FULL_SPEC, args_blob=b"A" * 2048))]},
    {"type": "execute", "spec": dict(FULL_SPEC, args_blob=b"A" * 2048)},
    {"type": "task_done",
     "seals": [(b"r1", ObjectLocation(inline=b"A" * 2048), [])],
     "spec_ref": {"task_id": b"t", "return_ids": [b"r1"],
                  "is_actor_creation": None, "actor_id": None, "name": "f"},
     "failed": False, "error_str": None, "exec_start": 0.0, "exec_end": 0.0,
     "worker_pid": 1},
    {"type": "seal", "oid": b"o", "loc": ObjectLocation(inline=b"A" * 2048),
     "contained": []},
    {"type": "add_ref", "oids": [b"A" * 2048], "reason": "handle"},
    {"type": "remove_ref", "oids": [b"A" * 2048], "reason": "handle"},
    {"type": "metrics_report", "origin": "w",
     "metrics": {"blob": "A" * 2048}},
    {"type": "get_locations", "oids": [b"A" * 2048], "timeout": None,
     "req_id": 3},
    {"type": "wait", "oids": [b"A" * 2048], "num_returns": 1,
     "timeout": None, "req_id": 4},
    {"type": "reply", "req_id": 5,
     "locations": {b"o": ObjectLocation(inline=b"A" * 2048)}},
]


@pytest.mark.parametrize("msg", _OVERSIZE_MESSAGES, ids=lambda m: m["type"])
def test_oversize_packed_frame_falls_back_per_type(msg, monkeypatch):
    """The >2 GiB interop gate covers EVERY packed arm: an oversize
    payload in any hot frame type must land on the raw-pickle arm (no
    cap there) and round-trip — exercised with the cap lowered so the
    test doesn't allocate 2 GiB.  The Envelope fallback chain is gated
    too, so the frame can never reach a peer unparseable."""
    monkeypatch.setattr(packed_wire, "_MAX_FRAME", 1 << 10)
    monkeypatch.setattr(wire, "_PB_MAX_FRAME", 1 << 10)
    frame = wire.encode(msg)
    assert frame[:1] == b"\x80", msg["type"]
    assert wire.decode(frame) == msg
    # under the gate the packed arm still wins for the same type
    small = next(m for m in PACKED_MESSAGES if m["type"] == msg["type"])
    assert wire.encode(small)[:1] == packed_wire.MAGIC_BYTE


def test_packed_version_rejection():
    frame = bytearray(wire.encode(PACKED_MESSAGES[-1]))
    assert frame[:1] == packed_wire.MAGIC_BYTE
    frame[1] = packed_wire.PACKED_VERSION + 1
    with pytest.raises(wire.WireDecodeError):
        wire.decode(bytes(frame))
    frame[1] = packed_wire.PACKED_VERSION
    frame[2] = 0xEE  # unknown frame id
    with pytest.raises(wire.WireDecodeError):
        wire.decode(bytes(frame))


def test_packed_tables_in_lockstep():
    """A frame type added to the codec but not the decoder (or vice
    versa) is a silent wire break; raylint R1 gates this statically, the
    test pins it at runtime."""
    assert packed_wire._PACK.keys() == packed_wire._UNPACK.keys()
    assert packed_wire._PACK.keys() == packed_wire._FRAME_IDS.keys()
    ids = list(packed_wire._FRAME_IDS.values())
    assert len(ids) == len(set(ids))  # frame ids collide -> misdecode


def test_packed_spec_table_matches_proto_descriptor():
    """The packed TaskSpec layout is generated from the IDL: every field
    table entry must match the .proto field number and name, so codec
    and schema cannot drift apart (the 'generated from ray_tpu.proto'
    contract)."""
    by_number = {f.number: f for f in pb.TaskSpec.DESCRIPTOR.fields}
    for key, (number, kind) in packed_wire._SPEC_FIELDS.items():
        f = by_number[number]
        assert f.name == key, (key, number, f.name)
    assert by_number[packed_wire._EXTRA_FIELD].name == "extra"
    # presence bits are field-number-derived: no two fields may share one
    numbers = [n for n, _ in packed_wire._SPEC_FIELDS.values()]
    assert len(numbers) == len(set(numbers))


def test_wire_mode_selection(monkeypatch):
    import io

    class _FakeConn:
        def send_bytes(self, b):
            self.sent = b

    for mode, first_bytes in (
        (None, (packed_wire.MAGIC_BYTE,)),        # default IS proto
        ("proto", (packed_wire.MAGIC_BYTE,)),
        ("envelope", (b"\x08",)),
        ("pickle", (b"\x80",)),
    ):
        if mode is None:
            monkeypatch.delenv("RAY_TPU_WIRE", raising=False)
        else:
            monkeypatch.setenv("RAY_TPU_WIRE", mode)
        conn = wire.wrap(_FakeConn())
        conn.send({"type": "seal", "oid": b"o",
                   "loc": ObjectLocation(inline=b"x"), "contained": []})
        assert conn._conn.sent[:1] in first_bytes, mode


def test_legacy_pickle_frame_sniffing():
    # a RAY_TPU_WIRE=pickle peer's frame (raw pickle starts 0x80) decodes
    frame = pickle.dumps({"type": "pong"})
    assert wire.decode(frame) == {"type": "pong"}


def test_version_rejection():
    bad = pb.Envelope(version=wire.WIRE_VERSION + 1, pickled=b"x")
    with pytest.raises(wire.WireDecodeError):
        wire.decode(bad.SerializeToString())
    # and WireDecodeError is caught by reader loops as UnpicklingError
    assert issubclass(wire.WireDecodeError, pickle.UnpicklingError)


def test_garbage_frame_raises_decode_error():
    with pytest.raises(wire.WireDecodeError):
        wire.decode(b"\x0bnot a proto frame at all")


def test_spec_strip_invariant_preserved():
    """Decode reproduces the stripped-dict form: falsy defaults stay
    absent, the four always-present keys stay present."""
    spec = {"task_id": b"t", "name": "f", "return_ids": [b"r"],
            "num_returns": 1}
    out = wire.decode(wire.encode({"type": "submit_batch",
                                   "batch": [("task", spec)]}))
    dec = out["batch"][0][1]
    assert dec == spec
    assert "actor_id" not in dec and "dep_ids" not in dec


def test_pickled_envelope_arm_still_decodes():
    # the Envelope.pickled arm stays decodable (schema compat for peers
    # that wrap rather than send raw frames)
    env = pb.Envelope(version=wire.WIRE_VERSION,
                      pickled=pickle.dumps({"type": "x", "v": 1}))
    assert wire.decode(env.SerializeToString()) == {"type": "x", "v": 1}


def test_pickle_wire_cluster_end_to_end():
    """A cluster in the raw-pickle send encoding (RAY_TPU_WIRE=pickle —
    the pre-flip default, still fully supported) runs tasks/actors/puts.
    Covers the pickle send path and the always-sniffing receive
    invariant now that the DEFAULT is the typed wire."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, RAY_TPU_WIRE="pickle")
    proc = subprocess.run([sys.executable, "-c", """
import ray_tpu
ray_tpu.init(num_cpus=2)

@ray_tpu.remote
def f(x):
    return x * 2

@ray_tpu.remote
class A:
    def go(self):
        return "actor-ok"

assert ray_tpu.get([f.remote(i) for i in range(8)], timeout=120) \
    == [i * 2 for i in range(8)]
a = A.remote()
assert ray_tpu.get(a.go.remote(), timeout=120) == "actor-ok"
r = ray_tpu.put({"k": list(range(100))})
assert ray_tpu.get(r)["k"][-1] == 99
ray_tpu.shutdown()
print("DEFAULT_WIRE_OK")
"""], env=env, capture_output=True, text=True, timeout=300)
    assert "DEFAULT_WIRE_OK" in proc.stdout, proc.stderr[-2000:]


def test_mixed_mode_peers_interoperate():
    """A proto-sending driver joins a pickle-sending head: both
    directions work because every receiver sniffs."""
    import os
    import subprocess
    import sys

    env = dict(os.environ, RAY_TPU_WIRE="pickle")
    proc = subprocess.run([sys.executable, "-c", """
import os, subprocess, sys
import ray_tpu
# head + its workers: DEFAULT (pickle) senders
ray_tpu.init(num_cpus=2)
from ray_tpu._private.worker import global_worker
node = global_worker.node
host, port = node.tcp_address

# a thin client in PROTO mode connects to the default head
client = subprocess.run([sys.executable, "-c", '''
import ray_tpu
ray_tpu.init(address="client://%s:%d", _authkey=bytes.fromhex("%s"))

@ray_tpu.remote
def g(x):
    return x + 100

assert ray_tpu.get(g.remote(1), timeout=120) == 101
print("MIXED_OK")
''' % (host, port, node.authkey.hex())],
    env=dict(os.environ, RAY_TPU_WIRE="proto", RAY_TPU_SESSION="foreign"),
    capture_output=True, text=True, timeout=240)
print(client.stdout)
sys.stderr.write(client.stderr[-2000:])
assert "MIXED_OK" in client.stdout
ray_tpu.shutdown()
print("HEAD_OK")
"""], env=env, capture_output=True, text=True, timeout=420)
    assert "MIXED_OK" in proc.stdout and "HEAD_OK" in proc.stdout, \
        proc.stderr[-2000:]
