"""Lineage reconstruction + memory monitor / OOM killing policy
(reference: object_recovery_manager.h:41, task_manager.h:87 lineage;
memory_monitor.h:52 + worker_killing_policy.h:30)."""

import time

import numpy as np
import pytest

import ray_tpu
from ray_tpu.cluster_utils import Cluster
from ray_tpu.exceptions import ObjectLostError
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture
def real_cluster():
    # real node processes with private shm namespaces: removing the node
    # genuinely destroys its object copies (fake in-process nodes share the
    # head's namespace, so nothing would be lost)
    cluster = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 2, "num_tpus": 0},
        real_processes=True,
    )
    yield cluster
    cluster.shutdown()


def test_lost_object_reconstructed(real_cluster):
    """An object whose only copy lived on a dead node is recomputed from
    its creating task's spec."""
    cluster = real_cluster
    nid = cluster.add_node(num_cpus=1)

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(nid))
    def produce(seed):
        # big enough to live in shm on the producing node, deterministic
        return np.full((200_000,), seed, np.float32)

    ref = produce.remote(7)
    first = ray_tpu.get(ref, timeout=120)
    assert first[0] == 7 and first.shape == (200_000,)

    cluster.remove_node(nid)
    # the copy died with the node; lineage resubmits produce(7)
    again = ray_tpu.get(ref, timeout=180)
    np.testing.assert_array_equal(again, first)


def test_lost_chain_reconstructed(real_cluster):
    """Reconstruction recurses through dependencies lost in the same node
    failure."""
    cluster = real_cluster
    nid = cluster.add_node(num_cpus=2)
    strat = NodeAffinitySchedulingStrategy(nid)

    @ray_tpu.remote(scheduling_strategy=strat)
    def base():
        return np.arange(150_000, dtype=np.int64)

    @ray_tpu.remote(scheduling_strategy=strat)
    def double(x):
        return x * 2

    b = base.remote()
    d = double.remote(b)
    assert ray_tpu.get(d, timeout=120)[-1] == 2 * 149_999

    cluster.remove_node(nid)
    out = ray_tpu.get(d, timeout=180)
    assert out[-1] == 2 * 149_999 and out[0] == 0


def test_lost_put_object_raises(real_cluster):
    """ray.put data has no lineage: losing its node surfaces
    ObjectLostError (reference semantics)."""
    cluster = real_cluster
    nid = cluster.add_node(num_cpus=1)

    @ray_tpu.remote(scheduling_strategy=NodeAffinitySchedulingStrategy(nid))
    def produce_via_put():
        return ray_tpu.put(np.ones(150_000, np.float32))

    inner = ray_tpu.get(produce_via_put.remote(), timeout=120)
    assert ray_tpu.get(inner, timeout=120).shape == (150_000,)
    cluster.remove_node(nid)
    with pytest.raises(ObjectLostError):
        ray_tpu.get(inner, timeout=120)


def _head():
    import gc

    from ray_tpu._private import node as node_mod

    # pick the LIVE head — stale Nodes from earlier tests may linger in gc
    heads = [
        o for o in gc.get_objects()
        if isinstance(o, node_mod.Node) and not o._shutdown
    ]
    assert heads, "no live head node"
    return heads[-1]


def test_oom_killer_picks_newest_retriable(ray_start_regular):
    """Under (synthetic) memory pressure the policy kills the newest
    retriable task's worker; the task retries and completes."""
    head = _head()

    @ray_tpu.remote(max_retries=5)
    def retriable(path):
        import os
        import time as _t

        if os.path.exists(path):
            return "done"
        open(path, "w").close()
        _t.sleep(300)  # parked until the OOM killer takes this worker

    marker = f"/tmp/rtpu_oom_{time.time()}"
    ref = retriable.remote(marker)
    # wait until the task BODY has run past the marker write — killing at
    # dispatch time (head.running is set then) would burn the one synthetic
    # reading before the retry could ever observe the marker
    import os

    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and not os.path.exists(marker):
        time.sleep(0.1)
    assert os.path.exists(marker) and head.running

    # one synthetic over-threshold reading; the iterator-with-default means
    # the background monitor thread racing us can consume it at most once
    # (whichever path reads it kills the worker — the outcome assert below
    # covers both)
    readings = iter([0.99])
    orig = head._memory_fraction
    try:
        head._memory_fraction = lambda: next(readings, 0.0)
        head._check_memory_pressure()
    finally:
        head._memory_fraction = orig
    # the sleep(300) body can only finish if the OOM kill + retry happened
    assert ray_tpu.get(ref, timeout=120) == "done"


def test_memory_monitor_noop_below_threshold(ray_start_regular):
    head = _head()
    frac = head._memory_fraction()
    assert 0.0 <= frac < 1.0
    if frac < head.cfg.memory_usage_threshold:
        assert head._check_memory_pressure() is False
