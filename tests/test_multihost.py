"""The real multi-host seam: ``JaxConfig(use_jax_distributed=True)``.

Two separate worker PROCESSES rendezvous through
``jax.distributed.initialize`` (the reference's torch process-group
rendezvous seat, ``python/ray/train/torch/config.py:69``) and execute ONE
SPMD program whose collective spans both processes — on CPU, exactly the
way a TPU pod slice would over ICI.  Plus gang-failure semantics: a worker
death mid-run restarts the whole gang and the rendezvous succeeds again in
the fresh processes.
"""

import os

import pytest

import ray_tpu
from ray_tpu.air import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train import JaxConfig, JaxTrainer


def _spmd_loop(config=None):
    """Runs in each training worker process."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu.air import session

    assert jax.process_count() == 2, jax.process_count()
    n_local = jax.local_device_count()
    n_global = jax.device_count()
    assert n_global == 2 * n_local, (n_global, n_local)

    # one global array sharded across BOTH processes; the jitted sum
    # lowers to a cross-process psum — the single-SPMD-program proof
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    x = jax.make_array_from_callback(
        (n_global,), sharding,
        lambda idx: np.arange(n_global, dtype=np.float32)[idx])
    total = float(jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x))
    assert total == n_global * (n_global - 1) / 2, total

    session.report({
        "final": True,
        "process_count": jax.process_count(),
        "global_devices": n_global,
        "sum": total,
    })


def test_two_process_jax_distributed_spmd(ray_start_regular, tmp_path):
    trainer = JaxTrainer(
        _spmd_loop,
        jax_config=JaxConfig(use_jax_distributed=True),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="spmd", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["process_count"] == 2
    assert result.metrics["global_devices"] >= 2
    assert result.metrics["final"] is True


def _sharded_train_loop(config=None):
    """2 processes x 4 virtual devices each: a GPT-2 tiny train step jitted
    over an 8-device dp(cross-process) x sp x tp mesh, with loss parity
    against a plain single-device run of the same init/batch."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.air import session
    from ray_tpu.models import gpt2
    from ray_tpu.parallel import MeshSpec, create_mesh
    from ray_tpu.parallel.sharding import rules_for_mesh

    assert jax.process_count() == 2, jax.process_count()
    assert jax.local_device_count() == 4, jax.local_device_count()
    assert jax.device_count() == 8, jax.device_count()

    cfg = gpt2.GPT2Config.tiny(dtype=jnp.float32)
    # dp is the outermost mesh axis -> it spans the two PROCESSES; grad
    # allreduce crosses the process boundary (the DCN/ICI seam), sp/tp
    # stay process-local
    mesh = create_mesh(MeshSpec(dp=2, sp=2, tp=2), devices=jax.devices(),
                       keep_unit_axes=True)
    rules = rules_for_mesh(mesh)
    optimizer = gpt2.make_optimizer(lr=1e-3)
    shard = gpt2.param_shardings(mesh, rules, cfg)
    key = jax.random.PRNGKey(0)
    params = jax.jit(lambda k: gpt2.init(cfg, k), out_shardings=shard)(key)
    state = {"params": params, "opt_state": jax.jit(optimizer.init)(params),
             "step": jnp.zeros((), jnp.int32)}
    step = jax.jit(gpt2.make_train_step(cfg, optimizer, mesh),
                   donate_argnums=(0,))

    B, T = 8, cfg.max_seq_len
    rng = np.random.default_rng(0)
    host_batch = {
        "inputs": rng.integers(0, cfg.vocab_size, (B, T), dtype=np.int32),
        "targets": rng.integers(0, cfg.vocab_size, (B, T), dtype=np.int32),
    }
    bs = NamedSharding(mesh, P(("dp", "fsdp"), "sp"))
    batch = {
        k: jax.make_array_from_callback((B, T), bs, lambda idx, v=v: v[idx])
        for k, v in host_batch.items()
    }
    _, metrics = step(state, batch)
    loss = float(metrics["loss"])  # replicated output: readable everywhere

    # parity golden: same init/batch, plain single-device, no mesh
    ref_params = jax.jit(lambda k: gpt2.init(cfg, k))(key)
    ref_state = {"params": ref_params,
                 "opt_state": jax.jit(optimizer.init)(ref_params),
                 "step": jnp.zeros((), jnp.int32)}
    _, ref_metrics = jax.jit(gpt2.make_train_step(cfg, optimizer))(
        ref_state, host_batch)
    ref_loss = float(ref_metrics["loss"])
    assert abs(loss - ref_loss) <= 2e-3, (loss, ref_loss)

    session.report({
        "final": True, "loss": loss, "ref_loss": ref_loss,
        "process_count": jax.process_count(),
        "global_devices": jax.device_count(),
    })


def test_two_process_four_device_sharded_train(ray_start_regular, tmp_path):
    """The combined scale proof: jax.distributed across 2 worker processes
    x 4 virtual devices each, through JaxTrainer, running the REAL sharded
    train step with cross-process data parallelism — and matching
    single-device loss."""
    trainer = JaxTrainer(
        _sharded_train_loop,
        jax_config=JaxConfig(
            use_jax_distributed=True,
            env_vars={"XLA_FLAGS": "--xla_force_host_platform_device_count=4",
                      "JAX_PLATFORMS": "cpu"},
        ),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="spmd8", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["process_count"] == 2
    assert result.metrics["global_devices"] == 8
    assert abs(result.metrics["loss"] - result.metrics["ref_loss"]) <= 2e-3


def _dying_loop(config):
    import jax

    from ray_tpu.air import session

    assert jax.process_count() == 2
    rank = int(os.environ["RAY_TRAIN_WORLD_RANK"])
    marker = os.path.join(config["dir"], "died_once")
    if rank == 1 and not os.path.exists(marker):
        open(marker, "w").close()
        os._exit(1)  # SIGKILL-style death mid-run, after rendezvous
    session.report({"final": True, "rank": rank,
                    "procs": jax.process_count()})


def test_gang_restart_rebuilds_jax_distributed(ray_start_regular, tmp_path):
    """One worker dies after the rendezvous -> the WHOLE gang restarts in
    fresh processes and jax.distributed comes up again (the failure-domain
    semantics a TPU slice needs: hosts die together, restart together)."""
    trainer = JaxTrainer(
        _dying_loop,
        train_loop_config={"dir": str(tmp_path)},
        jax_config=JaxConfig(use_jax_distributed=True),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="gang", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2),
            checkpoint_config=CheckpointConfig(),
        ),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["final"] is True
    assert result.metrics["procs"] == 2
    assert os.path.exists(os.path.join(str(tmp_path), "died_once"))
