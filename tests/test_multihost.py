"""The real multi-host seam: ``JaxConfig(use_jax_distributed=True)``.

Two separate worker PROCESSES rendezvous through
``jax.distributed.initialize`` (the reference's torch process-group
rendezvous seat, ``python/ray/train/torch/config.py:69``) and execute ONE
SPMD program whose collective spans both processes — on CPU, exactly the
way a TPU pod slice would over ICI.  Plus gang-failure semantics: a worker
death mid-run restarts the whole gang and the rendezvous succeeds again in
the fresh processes.
"""

import os

import pytest

import ray_tpu
from ray_tpu.air import CheckpointConfig, FailureConfig, RunConfig, ScalingConfig
from ray_tpu.train import JaxConfig, JaxTrainer


def _spmd_loop(config=None):
    """Runs in each training worker process."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from ray_tpu.air import session

    assert jax.process_count() == 2, jax.process_count()
    n_local = jax.local_device_count()
    n_global = jax.device_count()
    assert n_global == 2 * n_local, (n_global, n_local)

    # one global array sharded across BOTH processes; the jitted sum
    # lowers to a cross-process psum — the single-SPMD-program proof
    mesh = Mesh(np.array(jax.devices()), ("dp",))
    sharding = NamedSharding(mesh, P("dp"))
    x = jax.make_array_from_callback(
        (n_global,), sharding,
        lambda idx: np.arange(n_global, dtype=np.float32)[idx])
    total = float(jax.jit(jnp.sum, out_shardings=NamedSharding(mesh, P()))(x))
    assert total == n_global * (n_global - 1) / 2, total

    session.report({
        "final": True,
        "process_count": jax.process_count(),
        "global_devices": n_global,
        "sum": total,
    })


def test_two_process_jax_distributed_spmd(ray_start_regular, tmp_path):
    trainer = JaxTrainer(
        _spmd_loop,
        jax_config=JaxConfig(use_jax_distributed=True),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="spmd", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["process_count"] == 2
    assert result.metrics["global_devices"] >= 2
    assert result.metrics["final"] is True


def _dying_loop(config):
    import jax

    from ray_tpu.air import session

    assert jax.process_count() == 2
    rank = int(os.environ["RAY_TRAIN_WORLD_RANK"])
    marker = os.path.join(config["dir"], "died_once")
    if rank == 1 and not os.path.exists(marker):
        open(marker, "w").close()
        os._exit(1)  # SIGKILL-style death mid-run, after rendezvous
    session.report({"final": True, "rank": rank,
                    "procs": jax.process_count()})


def test_gang_restart_rebuilds_jax_distributed(ray_start_regular, tmp_path):
    """One worker dies after the rendezvous -> the WHOLE gang restarts in
    fresh processes and jax.distributed comes up again (the failure-domain
    semantics a TPU slice needs: hosts die together, restart together)."""
    trainer = JaxTrainer(
        _dying_loop,
        train_loop_config={"dir": str(tmp_path)},
        jax_config=JaxConfig(use_jax_distributed=True),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="gang", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2),
            checkpoint_config=CheckpointConfig(),
        ),
    )
    result = trainer.fit()
    assert result.error is None, result.error
    assert result.metrics["final"] is True
    assert result.metrics["procs"] == 2
    assert os.path.exists(os.path.join(str(tmp_path), "died_once"))
