"""Tune experiment tests: variants, schedulers, Tuner, restore."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import RunConfig, session
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner
from ray_tpu.tune.search.basic_variant import BasicVariantGenerator


def test_variant_generation():
    gen = BasicVariantGenerator(seed=0)
    space = {
        "lr": tune.grid_search([0.1, 0.01]),
        "wd": tune.uniform(0.0, 1.0),
        "nested": {"units": tune.choice([32, 64])},
        "fixed": 7,
    }
    variants = list(gen.variants(space, num_samples=2))
    assert len(variants) == 4  # 2 grid x 2 samples
    assert {v["lr"] for v in variants} == {0.1, 0.01}
    for v in variants:
        assert 0.0 <= v["wd"] <= 1.0
        assert v["nested"]["units"] in (32, 64)
        assert v["fixed"] == 7


def _objective(config):
    # quadratic bowl: best at x = 3
    for step in range(8):
        loss = (config["x"] - 3.0) ** 2 + 0.1 * step
        session.report({"loss": loss, "training_iteration": step + 1})


def test_tuner_grid(ray_start_regular, tmp_path):
    tuner = Tuner(
        _objective,
        param_space={"x": tune.grid_search([0.0, 3.0, 5.0])},
        tune_config=TuneConfig(metric="loss", mode="min", max_concurrent_trials=2),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 3
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["config"]["x"] == 3.0


def test_asha_stops_bad_trials(ray_start_regular, tmp_path):
    sched = ASHAScheduler(metric="loss", mode="min", max_t=8,
                          grace_period=2, reduction_factor=2)
    tuner = Tuner(
        _objective,
        param_space={"x": tune.grid_search([0.0, 1.0, 3.0, 10.0])},
        tune_config=TuneConfig(metric="loss", mode="min", scheduler=sched,
                               max_concurrent_trials=2),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["config"]["x"] == 3.0
    # at least one bad trial stopped before max_t
    iters = [grid[i].metrics.get("training_iteration", 0) for i in range(len(grid))]
    assert min(iters) < 8


class _Counter(tune.Trainable):
    def setup(self, config):
        self.count = config.get("start", 0)

    def step(self):
        self.count += 1
        return {"count": self.count, "done": self.count >= 5}

    def save_checkpoint(self):
        return {"count": self.count}

    def load_checkpoint(self, state):
        self.count = state["count"]


def test_class_trainable_and_checkpoint(ray_start_regular, tmp_path):
    tuner = Tuner(
        _Counter,
        param_space={"start": tune.grid_search([0, 10])},
        tune_config=TuneConfig(metric="count", mode="max"),
        run_config=RunConfig(name="cls", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert not grid.errors
    best = grid.get_best_result()
    assert best.metrics["count"] >= 10
    assert best.checkpoint is not None
    assert best.checkpoint.to_dict()["count"] == best.metrics["count"]


def test_tuner_restore(ray_start_regular, tmp_path):
    tuner = Tuner(
        _Counter,
        param_space={"start": tune.grid_search([0])},
        tune_config=TuneConfig(metric="count", mode="max"),
        run_config=RunConfig(name="resume", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert grid[0].metrics["count"] == 5
    restored = Tuner.restore(
        str(tmp_path / "resume"), _Counter,
        tune_config=TuneConfig(metric="count", mode="max"),
    )
    grid2 = restored.fit()  # everything terminated: results survive
    assert grid2[0].metrics["count"] == 5


def test_trial_timeout_kills_hung_trial(ray_start_regular):
    """A wedged trial must not stall the experiment (trial_timeout_s)."""
    import time

    from ray_tpu import tune

    def loop(config):
        from ray_tpu.air import session

        if config["hang"]:
            time.sleep(3600)
        for i in range(2):
            session.report({"score": i})

    tuner = tune.Tuner(
        loop,
        param_space={"hang": tune.grid_search([False, True])},
        tune_config=tune.TuneConfig(metric="score", mode="max",
                                    trial_timeout_s=8.0, stop={"score": 1}),
    )
    t0 = time.time()
    grid = tuner.fit()
    assert time.time() - t0 < 240
    statuses = sorted(r.error is not None for r in grid)
    assert statuses == [False, True], "expected one ok trial and one timed-out"


def test_tpe_searcher_converges(ray_start_regular, tmp_path):
    """TPE should concentrate samples near the optimum of a quadratic."""

    def objective(config):
        session.report({"loss": (config["x"] - 3.0) ** 2, "training_iteration": 1})

    space = {"x": tune.uniform(-10.0, 10.0)}
    searcher = tune.TPESearcher(space, metric="loss", mode="min",
                                n_initial_points=6, seed=0)
    tuner = Tuner(
        objective,
        tune_config=TuneConfig(
            metric="loss", mode="min", num_samples=24,
            max_concurrent_trials=2, search_alg=searcher,
            stop={"training_iteration": 1},
        ),
        run_config=RunConfig(storage_path=str(tmp_path), name="tpe"),
    )
    grid = tuner.fit()
    assert len(grid) == 24
    best = grid.get_best_result()
    assert best.metrics["loss"] < 1.5, best.metrics
    # the last half of suggestions should be much closer to 3 on average
    xs = [t.config["x"] for t in grid._trials]
    early = np.mean([abs(x - 3.0) for x in xs[:8]])
    late = np.mean([abs(x - 3.0) for x in xs[-8:]])
    assert late < early


def test_tpe_categorical_and_integer():
    space = {"c": tune.choice(["a", "b"]), "n": tune.randint(0, 10)}
    s = tune.TPESearcher(space, metric="m", mode="max", n_initial_points=4, seed=1)
    # feed it results where c="b", n>=7 is best
    for i in range(20):
        cfg = s.suggest(f"t{i}")
        score = (1.0 if cfg["c"] == "b" else 0.0) + (cfg["n"] >= 7)
        s.on_trial_complete(f"t{i}", {"m": score})
    tail = [s.suggest(f"z{i}") for i in range(10)]
    assert sum(1 for c in tail if c["c"] == "b") >= 7
    assert np.mean([c["n"] for c in tail]) > 5


def test_logger_callbacks(ray_start_regular, tmp_path):
    import csv
    import json
    import os

    tuner = Tuner(
        _objective,
        param_space={"x": tune.grid_search([1.0, 2.0])},
        tune_config=TuneConfig(metric="loss", mode="min",
                               stop={"training_iteration": 3}),
        run_config=RunConfig(
            storage_path=str(tmp_path), name="logs",
            callbacks=[tune.CSVLoggerCallback, tune.JSONLoggerCallback],
        ),
    )
    grid = tuner.fit()
    exp = os.path.join(str(tmp_path), "logs")
    trial_dirs = [d for d in os.listdir(exp) if d.startswith("trial_")]
    assert len(trial_dirs) == 2
    for d in trial_dirs:
        with open(os.path.join(exp, d, "progress.csv")) as f:
            rows = list(csv.DictReader(f))
        assert len(rows) == 3
        assert "loss" in rows[0]
        with open(os.path.join(exp, d, "result.json")) as f:
            lines = [json.loads(line) for line in f]
        assert len(lines) == 3
