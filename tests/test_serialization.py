"""Serialization + shm unit tests (no processes; reference: plasma tests +
python/ray/tests/test_serialization.py)."""

import numpy as np
import pytest

from ray_tpu._private import serialization
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.shm import ShmSegment


def _roundtrip(value):
    meta, bufs, refs = serialization.serialize(value)
    blob = serialization.to_bytes(meta, bufs)
    return serialization.deserialize(memoryview(blob)), refs


def test_roundtrip_primitives():
    for v in [None, 1, 1.5, "s", b"bytes", [1, 2], {"a": (1, 2)}, {1, 2}]:
        out, _ = _roundtrip(v)
        assert out == v


def test_roundtrip_numpy_zero_copy():
    arr = np.random.rand(256, 256)
    out, _ = _roundtrip(arr)
    np.testing.assert_array_equal(arr, out)


def test_roundtrip_numpy_dtypes():
    for dt in [np.float32, np.int8, np.uint16, np.bool_]:
        arr = np.ones((33, 7), dtype=dt)
        out, _ = _roundtrip(arr)
        assert out.dtype == dt
        np.testing.assert_array_equal(arr, out)


def test_noncontiguous_array():
    arr = np.arange(100).reshape(10, 10)[:, ::2]
    out, _ = _roundtrip(arr)
    np.testing.assert_array_equal(arr, out)


def test_object_refs_collected():
    r1, r2 = ObjectRef.random(), ObjectRef.random()
    out, refs = _roundtrip({"refs": [r1, r2]})
    assert out["refs"] == [r1, r2]
    assert set(refs) == {r1, r2}


def test_shm_segment_roundtrip():
    name = f"rtpu-test-{ObjectRef.random().hex()}"
    seg = ShmSegment.create(name, 4096)
    try:
        seg.buf[:5] = b"hello"
        seg2 = ShmSegment.attach(name)
        assert bytes(seg2.buf[:5]) == b"hello"
        seg2.close()
    finally:
        seg.close()
        ShmSegment.unlink(name)
    assert not ShmSegment.exists(name)


def test_store_value_inline_vs_shm():
    from ray_tpu._private.object_store import read_value, store_value
    from ray_tpu._private.shm import ShmSegment

    small_ref = ObjectRef.random()
    loc, _ = store_value(small_ref, [1, 2, 3])
    assert loc.inline is not None
    assert read_value(loc) == [1, 2, 3]

    big_ref = ObjectRef.random()
    arr = np.random.rand(512, 512)  # 2 MB
    loc, _ = store_value(big_ref, arr)
    assert loc.shm_name is not None
    try:
        np.testing.assert_array_equal(read_value(loc), arr)
    finally:
        ShmSegment.unlink(loc.shm_name)


def test_error_objects_raise():
    from ray_tpu._private.object_store import read_value, store_value

    ref = ObjectRef.random()
    loc, _ = store_value(ref, ValueError("stored error"), is_error=True)
    with pytest.raises(ValueError, match="stored error"):
        read_value(loc)
