"""``ray_tpu.cancel`` — pending/running/finished/actor/recursive cases
(reference cancel semantics, ``python/ray/_private/worker.py:2573``)."""

import time

import pytest

import ray_tpu
from ray_tpu.exceptions import TaskCancelledError


def test_cancel_pending_task(ray_start_regular):
    @ray_tpu.remote(num_cpus=1)
    def hog():
        time.sleep(5)
        return "hog"

    @ray_tpu.remote(num_cpus=1)
    def victim():
        return "ran"

    hogs = [hog.remote() for _ in range(4)]  # saturate the 4 CPUs
    time.sleep(0.5)
    v = victim.remote()  # must be queued behind the hogs
    ray_tpu.cancel(v)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(v, timeout=30)
    for r in hogs:
        ray_tpu.cancel(r, force=True)


def test_cancel_running_task_interrupts(ray_start_regular):
    @ray_tpu.remote
    def sleeper():
        time.sleep(60)
        return "done"

    r = sleeper.remote()
    time.sleep(1.0)  # let it start
    t0 = time.time()
    ray_tpu.cancel(r)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(r, timeout=30)
    assert time.time() - t0 < 10, "cancel did not unblock the caller promptly"

    # the worker pool survives the interrupt: later tasks run fine
    @ray_tpu.remote
    def ok():
        return 42

    assert ray_tpu.get(ok.remote(), timeout=60) == 42


def test_cancel_force_kills_worker(ray_start_regular):
    @ray_tpu.remote
    def stubborn():
        while True:  # ignores KeyboardInterrupt-free pure spin? no — sleep
            time.sleep(0.5)

    r = stubborn.remote()
    time.sleep(1.0)
    ray_tpu.cancel(r, force=True)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(r, timeout=30)

    @ray_tpu.remote
    def ok():
        return "alive"

    assert ray_tpu.get(ok.remote(), timeout=60) == "alive"


def test_cancel_finished_task_is_noop(ray_start_regular):
    @ray_tpu.remote
    def f():
        return 7

    r = f.remote()
    assert ray_tpu.get(r, timeout=60) == 7
    ray_tpu.cancel(r)  # no-op
    assert ray_tpu.get(r, timeout=60) == 7


def test_cancel_queued_actor_method(ray_start_regular):
    @ray_tpu.remote
    class Slow:
        def busy(self):
            time.sleep(4)
            return "busy"

        def quick(self):
            return "quick"

    a = Slow.remote()
    b = a.busy.remote()
    time.sleep(0.5)
    q = a.quick.remote()  # queued behind busy (max_concurrency=1)
    ray_tpu.cancel(q)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(q, timeout=30)
    assert ray_tpu.get(b, timeout=60) == "busy"  # the running one completes


def test_cancel_async_actor_method(ray_start_regular):
    @ray_tpu.remote(max_concurrency=8)
    class Async:
        async def forever(self):
            import asyncio

            await asyncio.sleep(3600)

        async def ping(self):
            return "pong"

    a = Async.remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"
    r = a.forever.remote()
    time.sleep(1.0)
    ray_tpu.cancel(r)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(r, timeout=30)
    # the actor still serves
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"


def test_cancel_force_on_actor_task_rejected(ray_start_regular):
    @ray_tpu.remote
    class A:
        def ready(self):
            return True

        def slow(self):
            time.sleep(5)

    a = A.remote()
    assert ray_tpu.get(a.ready.remote(), timeout=60)  # actor is up
    r = a.slow.remote()
    time.sleep(0.5)  # now the method is inflight, not queued
    with pytest.raises(ValueError):
        ray_tpu.cancel(r, force=True)
    ray_tpu.get(r, timeout=60)  # unaffected


def test_cancel_recursive_cancels_children(ray_start_regular):
    @ray_tpu.remote
    def child():
        time.sleep(60)
        return "child"

    @ray_tpu.remote
    def parent():
        c = child.remote()
        return ray_tpu.get(c, timeout=120)

    r = parent.remote()
    time.sleep(1.5)  # parent is blocked on the child
    ray_tpu.cancel(r, recursive=True)
    with pytest.raises(TaskCancelledError):
        ray_tpu.get(r, timeout=30)
