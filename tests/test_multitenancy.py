"""Multi-tenancy: client proxy with per-connection drivers, actor
namespaces, and concurrency groups (ISSUE 13).

Covers the three coupled parts end to end:
- namespace-scoped named actors (two tenants, same name, no collision;
  cross-namespace lookups raise; duplicate in ONE namespace rejected);
- the client proxy (``ray_tpu://``): one isolated driver subprocess per
  connection, per-tenant job attribution in the ownership audit, and the
  headline tenant-kill chaos scenario — SIGKILL tenant A's driver
  mid-workload, tenant B unaffected, A's non-detached state reaped, A's
  detached actor surviving, doctor explaining then going quiet;
- concurrency groups: per-group FIFO, cross-group non-interference,
  health-under-saturation (including the serve replica control group).
"""

import json
import os
import subprocess
import sys
import textwrap
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.worker import global_worker

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# fixtures / helpers
# ---------------------------------------------------------------------------

@pytest.fixture
def proxy_cluster():
    """In-process head + a multi-tenant proxy in front of it."""
    from ray_tpu.util.client import ProxyServer

    ray_tpu.init(num_cpus=4, num_tpus=0)
    node = global_worker.node
    host, port = node.tcp_address
    proxy = ProxyServer(f"tcp://{host}:{port}", node.authkey).start()
    yield node, proxy
    proxy.stop()
    ray_tpu.shutdown()


def _tenant_env(node, proxy) -> dict:
    env = dict(os.environ)
    env["PROXY_ADDR"] = f"ray_tpu://{proxy.address[0]}:{proxy.address[1]}"
    env["RAY_TPU_AUTHKEY"] = node.authkey.hex()
    env["JAX_PLATFORMS"] = "cpu"
    return env


def _run_tenant(script: str, env: dict, timeout: float = 180):
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True,
        timeout=timeout, env=env, cwd=REPO_ROOT)
    assert "TENANT_OK" in proc.stdout, (
        f"stdout:\n{proc.stdout[-2000:]}\nstderr:\n{proc.stderr[-3000:]}")
    return proc


def _spawn_tenant(script: str, env: dict) -> subprocess.Popen:
    return subprocess.Popen(
        [sys.executable, "-c", script], env=env, cwd=REPO_ROOT,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, bufsize=1)


def _wait_for_line(proc: subprocess.Popen, token: str, timeout: float) -> str:
    """Block until the child prints a line containing ``token``."""
    box = {"line": None}

    def read():
        while True:
            line = proc.stdout.readline()
            if not line:
                return
            if token in line:
                box["line"] = line
                return

    t = threading.Thread(target=read, daemon=True)
    t.start()
    t.join(timeout)
    assert box["line"] is not None, (
        f"child never printed {token!r} within {timeout}s "
        f"(alive={proc.poll() is None})")
    return box["line"]


def _wait_until(fn, timeout: float = 20.0, interval: float = 0.1):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# actor namespaces (in-process driver)
# ---------------------------------------------------------------------------

@ray_tpu.remote
class Named:
    def __init__(self, label="x"):
        self.label = label

    def who(self):
        ctx = ray_tpu.get_runtime_context()
        return {"label": self.label, "namespace": ctx.namespace,
                "job_id": ctx.job_id}


def test_runtime_context_identity(ray_start_regular):
    ctx = ray_tpu.get_runtime_context()
    assert ctx.namespace == "default"
    assert ctx.job_id and ctx.job_id.startswith("job-")

    @ray_tpu.remote
    def ident():
        c = ray_tpu.get_runtime_context()
        return (c.namespace, c.job_id)

    ns, job = ray_tpu.get(ident.remote(), timeout=60)
    assert ns == "default"
    assert job == ctx.job_id  # tasks inherit the submitting job


def test_namespace_scoped_named_actors(ray_start_regular):
    a = Named.options(name="svc", namespace="ns-a").remote("a")
    b = Named.options(name="svc", namespace="ns-b").remote("b")
    got_a = ray_tpu.get(
        ray_tpu.get_actor("svc", namespace="ns-a").who.remote(), timeout=60)
    got_b = ray_tpu.get(
        ray_tpu.get_actor("svc", namespace="ns-b").who.remote(), timeout=60)
    assert got_a["label"] == "a" and got_b["label"] == "b"
    # cross-namespace lookup raises exactly like a missing name
    with pytest.raises(ValueError):
        ray_tpu.get_actor("svc", namespace="ns-c")
    # the driver's own namespace ("default") cannot see tenant names
    with pytest.raises(ValueError):
        ray_tpu.get_actor("svc")
    # duplicate name INSIDE one namespace fails the second creation
    dup = Named.options(name="svc", namespace="ns-a").remote("dup")
    with pytest.raises(Exception):
        ray_tpu.get(dup.who.remote(), timeout=60)
    # ...but the name becomes reusable after the holder dies
    ray_tpu.kill(a)
    assert _wait_until(lambda: _lookup_missing("svc", "ns-a")), \
        "name not released after kill"
    c = Named.options(name="svc", namespace="ns-a").remote("a2")
    assert ray_tpu.get(c.who.remote(), timeout=60)["label"] == "a2"
    del b


def _lookup_missing(name, namespace) -> bool:
    try:
        ray_tpu.get_actor(name, namespace=namespace)
        return False
    except ValueError:
        return True


def test_actor_rows_carry_namespace_and_job(ray_start_regular):
    from ray_tpu.experimental.state import api as state

    h = Named.options(name="rowcheck", namespace="ns-rows").remote()
    ray_tpu.get(h.who.remote(), timeout=60)
    rows = [r for r in state.list_actors() if r.get("name") == "rowcheck"]
    assert rows and rows[0]["namespace"] == "ns-rows"
    assert rows[0]["job_id"] == ray_tpu.get_runtime_context().job_id
    tenants = state.list_tenants()
    me = [t for t in tenants
          if t["job_id"] == ray_tpu.get_runtime_context().job_id]
    assert me and me[0]["alive"] and me[0]["namespace"] == "default"


def test_option_validation(ray_start_regular):
    with pytest.raises(ValueError):
        Named.options(lifetime="ephemeral")
    with pytest.raises(ValueError):
        Named.options(namespace="")
    with pytest.raises(ValueError):
        Named.options(concurrency_groups={"io": 0})
    with pytest.raises(ValueError):
        Named.options(concurrency_groups={"_default": 2})


# ---------------------------------------------------------------------------
# concurrency groups
# ---------------------------------------------------------------------------

@ray_tpu.remote(concurrency_groups={"io": 1, "health": 1}, max_concurrency=2)
class Grouped:
    def slow(self, s):
        time.sleep(s)
        return "done"

    def ping(self):
        return "pong"

    def tag(self, i):
        return i


def test_concurrency_group_starvation_and_fifo(ray_start_regular):
    g = Grouped.remote()
    ray_tpu.get(g.ping.remote(), timeout=60)
    # saturate the default group (2 threads + pipeline) with slow calls
    slows = [g.slow.remote(3) for _ in range(10)]
    time.sleep(0.2)
    # a health-group call completes while the default group is saturated
    t0 = time.monotonic()
    assert ray_tpu.get(g.ping.options(concurrency_group="health").remote(),
                       timeout=60) == "pong"
    health_latency = time.monotonic() - t0
    assert health_latency < 2.0, \
        f"health group starved by default group: {health_latency:.1f}s"
    # per-group FIFO: a single-slot group preserves submission order...
    refs = [g.tag.options(concurrency_group="io").remote(i)
            for i in range(25)]
    assert ray_tpu.get(refs, timeout=120) == list(range(25))
    # ...and the io traffic did not block health either (non-interference)
    t0 = time.monotonic()
    more_io = [g.tag.options(concurrency_group="io").remote(i)
               for i in range(5)]
    assert ray_tpu.get(g.ping.options(concurrency_group="health").remote(),
                       timeout=60) == "pong"
    assert time.monotonic() - t0 < 2.0
    ray_tpu.get(slows + more_io, timeout=180)


def test_concurrency_groups_async_actor(ray_start_regular):
    import asyncio

    @ray_tpu.remote(concurrency_groups={"side": 1})
    class AsyncGrouped:
        async def block(self, s):
            await asyncio.sleep(s)
            return "slept"

        async def quick(self):
            return "quick"

    a = AsyncGrouped.remote()
    ray_tpu.get(a.quick.remote(), timeout=60)
    blocks = [a.block.remote(2) for _ in range(4)]
    t0 = time.monotonic()
    assert ray_tpu.get(a.quick.options(concurrency_group="side").remote(),
                       timeout=60) == "quick"
    assert time.monotonic() - t0 < 1.5
    ray_tpu.get(blocks, timeout=120)


def test_unknown_group_rejected_on_declared_handle(ray_start_regular):
    g = Grouped.remote()
    with pytest.raises(ValueError):
        g.ping.options(concurrency_group="nope")


def test_serve_replica_control_group_under_saturation(ray_start_regular):
    """A replica saturated with slow requests still answers health pings
    and completes a graceful drain inside its window: both ride the
    replica's dedicated 'control' concurrency group (before this group
    existed, they queued behind every accepted request)."""
    from ray_tpu import serve

    @serve.deployment(name="slow-mt", max_concurrent_queries=2,
                      num_replicas=1)
    class Slow:
        def __call__(self, request=None):
            time.sleep(2.0)
            return {"ok": True}

    serve.run(Slow.bind())
    try:
        from ray_tpu.serve import api as serve_api

        controller = serve_api._get_client().controller
        handle = serve.get_deployment_handle("slow-mt")
        futs = [handle.remote() for _ in range(2)]  # saturate the lane
        time.sleep(0.3)
        # health: a control-group ping completes while the request lane
        # is busy (the plain replica is SERIALIZED — a default-lane call
        # would wait for the 2s request)
        info = ray_tpu.get(
            controller.get_routing_info.remote("slow-mt"), timeout=10)
        assert info["replicas"], "replica dropped from routing under load"
        _, rhandle = info["replicas"][0]
        t0 = time.monotonic()
        assert ray_tpu.get(
            rhandle.ping.options(concurrency_group="control").remote(),
            timeout=10) is not None
        assert time.monotonic() - t0 < 1.5, "health ping starved"
        # drain: delete while busy — the control-group drain polls run
        # alongside the in-flight requests, the requests complete, and
        # the drain records 'replica drained' (not a timeout) quickly
        serve.delete("slow-mt")
        assert _wait_until(lambda: any(
            e.get("source") == "serve"
            and e.get("message") == "replica drained"
            for e in global_worker.node._list_state_page(
                "events", 100_000, {"source": "serve"})[0]),
            timeout=15), "drain did not complete cleanly"
        done = ray_tpu.get(futs, timeout=60)
        assert all(r == {"ok": True} for r in done), done
    finally:
        serve.shutdown()


# ---------------------------------------------------------------------------
# client proxy: per-connection drivers
# ---------------------------------------------------------------------------

TENANT_BASIC = textwrap.dedent("""
    import os
    import ray_tpu

    ray_tpu.init(os.environ["PROXY_ADDR"],
                 namespace=os.environ.get("TENANT_NS") or None)
    ctx = ray_tpu.get_runtime_context()
    print("IDENT", ctx.job_id, ctx.namespace, flush=True)

    @ray_tpu.remote
    def double(x):
        return x * 2

    assert ray_tpu.get(double.remote(21), timeout=120) == 42

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0
        def add(self, k):
            self.n += k
            return self.n
        def who(self):
            c = ray_tpu.get_runtime_context()
            return (c.namespace, c.job_id)

    c = Counter.options(name="svc").remote()
    assert ray_tpu.get(c.add.remote(5), timeout=120) == 5
    h = ray_tpu.get_actor("svc")
    assert ray_tpu.get(h.add.remote(2), timeout=120) == 7
    ns, job = ray_tpu.get(h.who.remote(), timeout=120)
    assert ns == ctx.namespace and job == ctx.job_id, (ns, job)
    print("TENANT_OK", flush=True)
""")


def test_proxy_two_tenants_isolated(proxy_cluster):
    node, proxy = proxy_cluster
    env = _tenant_env(node, proxy)
    p1 = _run_tenant(TENANT_BASIC, env)
    p2 = _run_tenant(TENANT_BASIC, env)
    ident1 = [ln for ln in p1.stdout.splitlines() if ln.startswith("IDENT")][0]
    ident2 = [ln for ln in p2.stdout.splitlines() if ln.startswith("IDENT")][0]
    _, job1, ns1 = ident1.split()
    _, job2, ns2 = ident2.split()
    # distinct jobs, distinct default namespaces: both owned a named
    # actor "svc" and neither collided with the other
    assert job1 != job2 and ns1 != ns2
    # both tenants appear in the directory as proxied, with driver pids
    rows, _ = node._list_state_page("tenants", 100)
    by_job = {r["job_id"]: r for r in rows}
    assert by_job[job1]["proxied"] and by_job[job1]["pid"]
    assert by_job[job2]["namespace"] == ns2
    # the reap after each tenant's clean exit removed its named actor
    assert _wait_until(lambda: not any(
        ns in (ns1, ns2) for ns, _ in node.gcs.named_actors))


TENANT_VICTIM = textwrap.dedent("""
    import os, time
    import ray_tpu

    ray_tpu.init(os.environ["PROXY_ADDR"], namespace="tenant-a")

    @ray_tpu.remote
    class Holder:
        def ping(self):
            return "up"

    victim = Holder.options(name="a-live").remote()
    keeper = Holder.options(name="a-keeper", lifetime="detached").remote()
    ray_tpu.get([victim.ping.remote(), keeper.ping.remote()], timeout=120)
    pins = [ray_tpu.put(bytes(256 * 1024)) for _ in range(4)]
    print("VICTIM_READY", flush=True)
    # keep the driver (and its pins/handles) alive until SIGKILLed
    while True:
        time.sleep(0.5)
        ray_tpu.get(victim.ping.remote(), timeout=120)
""")

TENANT_SOAKER = textwrap.dedent("""
    import json, os, time
    import ray_tpu

    ray_tpu.init(os.environ["PROXY_ADDR"], namespace="tenant-b")

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    class BActor:
        def bump(self):
            return "b-alive"

    b = BActor.options(name="b-svc").remote()
    ray_tpu.get([noop.remote(), b.bump.remote()], timeout=120)
    print("SOAKER_READY", flush=True)
    rows = []
    end = time.time() + float(os.environ["SOAK_S"])
    while time.time() < end:
        t0 = time.perf_counter()
        ray_tpu.get(noop.remote(), timeout=120)
        rows.append((time.time(), time.perf_counter() - t0))
    assert ray_tpu.get(
        ray_tpu.get_actor("b-svc").bump.remote(), timeout=120) == "b-alive"
    print("RESULT " + json.dumps(rows), flush=True)
    print("TENANT_OK", flush=True)
""")


def test_tenant_kill_chaos(proxy_cluster):
    """The headline scenario: two tenants drive workloads through the
    proxy; chaos SIGKILLs tenant A's driver subprocess mid-workload.
    Tenant B's throughput, named actors, and attribution rows are
    unaffected; A's non-detached actor and pinned objects are reaped;
    A's detached actor survives; doctor explains the incident and (on
    aged events) goes quiet."""
    from ray_tpu.devtools.chaos.harness import ChaosMonkey
    from ray_tpu.util import doctor as doctor_mod

    node, proxy = proxy_cluster
    env = _tenant_env(node, proxy)

    victim = _spawn_tenant(TENANT_VICTIM, env)
    try:
        _wait_for_line(victim, "VICTIM_READY", 90)

        env_b = dict(env)
        env_b["SOAK_S"] = "6"
        soaker = _spawn_tenant(TENANT_SOAKER, env_b)
        _wait_for_line(soaker, "SOAKER_READY", 90)

        # tenant A's footprint before the kill: job row, live actors,
        # driver-attributed pinned bytes
        rows, _ = node._list_state_page("tenants", 100)
        arow = [r for r in rows if r["namespace"] == "tenant-a"][0]
        assert arow["alive"] and arow["proxied"]
        audit = node._memory_audit(limit=0)
        assert audit["attributed_frac"] >= 0.95, audit["attributed_frac"]
        a_ns_rows = [r for r in audit["by_namespace"]
                     if r["namespace"] == "tenant-a"]
        assert a_ns_rows and a_ns_rows[0]["bytes"] >= 4 * 256 * 1024
        assert a_ns_rows[0]["actors"] == 2

        # chaos: SIGKILL tenant A's driver subprocess mid-workload
        monkey = ChaosMonkey(node=node)
        rec = monkey.kill_tenant_driver(namespace="tenant-a")
        assert rec["pid"] == arow["pid"]
        # tenant B's directory row is untouched by A's death
        rows, _ = node._list_state_page("tenants", 100)
        brow = [r for r in rows if r["namespace"] == "tenant-b"][0]
        assert brow["alive"] and brow["job_id"] != arow["job_id"]

        # A's non-detached actor is reaped, the detached one survives
        def a_reaped():
            with node.gcs.lock:
                states = {a.name: a.state for a in node.gcs.actors.values()
                          if a.job_id == arow["job_id"]}
            return states.get("a-live") == "DEAD" \
                and states.get("a-keeper") == "ALIVE"
        assert _wait_until(a_reaped, timeout=30), "tenant A not reaped"
        # A's pinned bytes released from the audit
        assert _wait_until(lambda: not any(
            r["namespace"] == "tenant-a" and r["bytes"] > 0
            for r in node._memory_audit(limit=0)["by_namespace"]),
            timeout=30), "tenant A pins not released"

        # tenant B sails through: every task of the soak completed
        out, err = soaker.communicate(timeout=120)
        assert "TENANT_OK" in out, f"stdout:\n{out[-2000:]}\nstderr:\n{err[-3000:]}"
        result = [ln for ln in out.splitlines() if ln.startswith("RESULT ")][0]
        b_rows = json.loads(result[len("RESULT "):])
        kill_ts = rec["ts"]
        after = [r for r in b_rows if r[0] >= kill_ts]
        assert after, "tenant B made no progress after the kill"

        # doctor explains the incident...
        events, _ = node._list_state_page("events", 100_000)
        findings = doctor_mod.diagnose(events)
        tenant_findings = [f for f in findings if f["rule"] == "tenant_killed"]
        assert tenant_findings, findings
        assert arow["job_id"] in tenant_findings[0]["summary"]
        assert tenant_findings[0]["severity"] == "WARNING"  # reap completed
        # ...and goes quiet once the incident has aged out (the rule is a
        # pure function of event rows: age them and re-diagnose)
        aged = [dict(e, ts=e.get("ts", 0) - 300)
                if e.get("source") == "client_proxy" else e for e in events]
        assert not [f for f in doctor_mod.diagnose(aged)
                    if f["rule"] == "tenant_killed"]
    finally:
        try:
            victim.kill()
        except OSError:
            pass


def test_doctor_tenant_rule_shapes():
    """Unit shapes of the tenant_killed rule: stuck reap = open ERROR;
    death + reap = recent WARNING; aged = quiet."""
    from ray_tpu.util import doctor as doctor_mod

    t = 1_000_000.0
    died = {"source": "client_proxy", "message": "tenant driver died",
            "entity_id": "job-0007", "ts": t}
    reaped = {"source": "client_proxy", "message": "tenant reaped",
              "entity_id": "job-0007", "ts": t + 1}
    clock = {"source": "node", "message": "tick", "ts": t + 60}

    f = doctor_mod._rule_tenant_killed([died, clock], ())
    assert f and f["severity"] == "ERROR"  # no reap ever landed
    f = doctor_mod._rule_tenant_killed([died, reaped, clock], ())
    assert f and f["severity"] == "WARNING" and "job-0007" in f["summary"]
    old_clock = {"source": "node", "message": "tick", "ts": t + 500}
    assert doctor_mod._rule_tenant_killed([died, reaped, old_clock], ()) is None
