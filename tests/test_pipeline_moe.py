"""Pipeline (pp) and expert (ep) parallelism.

SURVEY §2.5 rows PP/EP: both absent in the reference; here they are
first-class.  Correctness bar: the pipelined / expert-sharded train step
computes the same loss as the unsharded single-device run (same params,
same batch, same math).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models import gpt2
from ray_tpu.models.transformer import apply_stack
from ray_tpu.ops.moe import moe_ffn
from ray_tpu.parallel import MeshSpec, create_mesh, gpipe
from ray_tpu.parallel.sharding import rules_for_mesh


def _tiny(**kw):
    return gpt2.GPT2Config.tiny(**kw)


def _batch(cfg, B=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "inputs": rng.integers(0, cfg.vocab_size, (B, cfg.max_seq_len), dtype=np.int32),
        "targets": rng.integers(0, cfg.vocab_size, (B, cfg.max_seq_len), dtype=np.int32),
    }


def _sharded_loss(cfg, mesh, batch, seed=0):
    """Init on-mesh, compute loss and param-grad-norm under jit."""
    rules = rules_for_mesh(mesh)
    shard = gpt2.param_shardings(mesh, rules, cfg)
    params = jax.jit(lambda k: gpt2.init(cfg, k), out_shardings=shard)(
        jax.random.PRNGKey(seed)
    )
    bs = NamedSharding(mesh, P(tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names) or None))
    batch = {k: jax.device_put(v, bs) for k, v in batch.items()}

    @jax.jit
    def lg(params, batch):
        loss, grads = jax.value_and_grad(gpt2.loss_fn)(params, batch, cfg, mesh)
        gnorm = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2)
                             for g in jax.tree.leaves(grads)))
        return loss, gnorm

    loss, gnorm = lg(params, batch)
    return float(loss), float(gnorm)


def _single_device_loss(cfg, batch, seed=0):
    params = gpt2.init(cfg, jax.random.PRNGKey(seed))

    @jax.jit
    def lg(params, batch):
        loss, grads = jax.value_and_grad(gpt2.loss_fn)(params, batch, cfg, None)
        gnorm = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2)
                             for g in jax.tree.leaves(grads)))
        return loss, gnorm

    loss, gnorm = lg(params, batch)
    return float(loss), float(gnorm)


class TestGpipe:
    def test_matches_unpipelined_scan(self):
        """gpipe(stage) == plain scan over the full layer stack."""
        mesh = create_mesh(MeshSpec(pp=2, dp=4), keep_unit_axes=True)
        L, D, B = 4, 16, 8
        blocks = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(1), (B, D))

        def stage(local_blocks, h):
            def layer(h, w):
                return jnp.tanh(h @ w), jnp.zeros((), jnp.float32)
            h, auxs = jax.lax.scan(layer, h, local_blocks)
            return h, auxs.sum()

        y, aux = gpipe(stage, blocks, x, mesh=mesh, n_microbatches=4)
        ref, _ = stage(blocks, x)
        np.testing.assert_allclose(np.asarray(y), np.asarray(ref), atol=1e-5)
        assert float(aux) == 0.0

    def test_grad_matches(self):
        mesh = create_mesh(MeshSpec(pp=2, dp=4), keep_unit_axes=True)
        L, D, B = 4, 16, 8
        blocks = jax.random.normal(jax.random.PRNGKey(0), (L, D, D)) * 0.3
        x = jax.random.normal(jax.random.PRNGKey(1), (B, D))

        def stage(local_blocks, h):
            def layer(h, w):
                return jnp.tanh(h @ w), jnp.zeros((), jnp.float32)
            h, auxs = jax.lax.scan(layer, h, local_blocks)
            return h, auxs.sum()

        def loss_pp(blocks):
            y, _ = gpipe(stage, blocks, x, mesh=mesh, n_microbatches=4)
            return (y ** 2).sum()

        def loss_ref(blocks):
            y, _ = stage(blocks, x)
            return (y ** 2).sum()

        g1 = jax.jit(jax.grad(loss_pp))(blocks)
        g2 = jax.jit(jax.grad(loss_ref))(blocks)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)


class TestPipelineParallelGPT2:
    def test_pp_loss_matches_single_device(self):
        cfg = _tiny(pp_microbatches=4)
        batch = _batch(cfg)
        mesh = create_mesh(MeshSpec(pp=2, dp=2, tp=2), keep_unit_axes=True)
        loss_pp, gnorm_pp = _sharded_loss(cfg, mesh, batch)
        loss_1, gnorm_1 = _single_device_loss(cfg, batch)
        assert loss_pp == pytest.approx(loss_1, rel=2e-2)
        assert gnorm_pp == pytest.approx(gnorm_1, rel=5e-2)

    def test_pp_train_step_runs(self):
        cfg = _tiny(pp_microbatches=2)
        mesh = create_mesh(MeshSpec(pp=2, fsdp=2, tp=2), keep_unit_axes=True)
        rules = rules_for_mesh(mesh)
        shard = gpt2.param_shardings(mesh, rules, cfg)
        opt = gpt2.make_optimizer()
        params = jax.jit(lambda k: gpt2.init(cfg, k), out_shardings=shard)(
            jax.random.PRNGKey(0))
        state = {"params": params, "opt_state": opt.init(params),
                 "step": jnp.zeros((), jnp.int32)}
        step = jax.jit(gpt2.make_train_step(cfg, opt, mesh), donate_argnums=(0,))
        batch = {k: jnp.asarray(v) for k, v in _batch(cfg).items()}
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))


class TestMoE:
    def test_moe_ffn_shapes_and_aux(self):
        E, D, F = 4, 16, 32
        k = jax.random.PRNGKey(0)
        ks = jax.random.split(k, 3)
        x = jax.random.normal(ks[0], (2, 8, D))
        rw = jax.random.normal(ks[1], (D, E)) * 0.1
        w1 = jax.random.normal(ks[2], (E, D, F)) * 0.1
        y, aux = moe_ffn(x, rw, w1, jnp.zeros((E, F)),
                         jnp.swapaxes(w1, 1, 2) * 0.5, jnp.zeros((E, D)))
        assert y.shape == x.shape
        # load-balance loss is >= 1 (perfect balance) and bounded by E
        assert 0.9 <= float(aux) <= E + 1e-3

    def test_moe_grads_flow_to_router(self):
        E, D, F = 4, 16, 32
        ks = jax.random.split(jax.random.PRNGKey(0), 4)
        x = jax.random.normal(ks[0], (2, 8, D))
        p = {
            "rw": jax.random.normal(ks[1], (D, E)) * 0.1,
            "w1": jax.random.normal(ks[2], (E, D, F)) * 0.1,
            "w2": jax.random.normal(ks[3], (E, F, D)) * 0.1,
        }

        def loss(p):
            y, aux = moe_ffn(x, p["rw"], p["w1"], jnp.zeros((E, F)),
                             p["w2"], jnp.zeros((E, D)))
            return (y ** 2).mean() + 0.01 * aux

        g = jax.grad(loss)(p)
        assert all(np.isfinite(np.asarray(v)).all() for v in jax.tree.leaves(g))
        assert float(jnp.abs(g["rw"]).sum()) > 0.0

    def test_ep_loss_matches_single_device(self):
        cfg = _tiny(n_experts=4)
        batch = _batch(cfg)
        mesh = create_mesh(MeshSpec(ep=2, dp=2, tp=2), keep_unit_axes=True)
        loss_ep, gnorm_ep = _sharded_loss(cfg, mesh, batch)
        loss_1, gnorm_1 = _single_device_loss(cfg, batch)
        assert loss_ep == pytest.approx(loss_1, rel=2e-2)
        assert gnorm_ep == pytest.approx(gnorm_1, rel=5e-2)


class TestPipelinePlusExperts:
    def test_pp_ep_dp_train_step(self):
        """The dryrun config-B shape: pp=2, ep=2, dp=2 on 8 devices."""
        cfg = _tiny(n_experts=2, pp_microbatches=2)
        mesh = create_mesh(MeshSpec(pp=2, dp=2, ep=2), keep_unit_axes=True)
        rules = rules_for_mesh(mesh)
        shard = gpt2.param_shardings(mesh, rules, cfg)
        opt = gpt2.make_optimizer()
        params = jax.jit(lambda k: gpt2.init(cfg, k), out_shardings=shard)(
            jax.random.PRNGKey(0))
        state = {"params": params, "opt_state": opt.init(params),
                 "step": jnp.zeros((), jnp.int32)}
        step = jax.jit(gpt2.make_train_step(cfg, opt, mesh), donate_argnums=(0,))
        batch = {k: jnp.asarray(v) for k, v in _batch(cfg).items()}
        state, metrics = step(state, batch)
        loss0 = float(metrics["loss"])
        state, metrics = step(state, batch)
        assert np.isfinite(loss0) and np.isfinite(float(metrics["loss"]))
        assert float(metrics["loss"]) < loss0 + 1.0
