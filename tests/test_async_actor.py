"""Threaded + async actors and pipelined actor calls (max_concurrency).

Matches the intent of the reference's concurrency-group machinery
(``src/ray/core_worker/transport/out_of_order_actor_scheduling_queue.h``,
``fiber.h`` asyncio support): N methods genuinely in flight at once on one
actor, while the default sync actor keeps strict call ordering.
"""

import time

import pytest

import ray_tpu


def test_threaded_actor_concurrency(ray_start_regular):
    @ray_tpu.remote(max_concurrency=4)
    class Sleeper:
        def nap(self, d):
            time.sleep(d)
            return time.monotonic()

    a = Sleeper.remote()
    start = time.monotonic()
    refs = [a.nap.remote(1.0) for _ in range(4)]
    ray_tpu.get(refs, timeout=60)
    elapsed = time.monotonic() - start
    # serial would be >= 4s; concurrent should be ~1s (+ actor boot)
    assert elapsed < 3.0, f"methods did not overlap: {elapsed:.1f}s"


def test_async_actor_concurrency(ray_start_regular):
    @ray_tpu.remote
    class AsyncActor:
        async def wait_and_echo(self, i):
            import asyncio

            await asyncio.sleep(1.0)
            return i

    a = AsyncActor.remote()
    start = time.monotonic()
    refs = [a.wait_and_echo.remote(i) for i in range(8)]
    out = ray_tpu.get(refs, timeout=60)
    elapsed = time.monotonic() - start
    assert out == list(range(8))
    # 8 awaited sleeps must interleave on the event loop
    assert elapsed < 5.0, f"async methods did not interleave: {elapsed:.1f}s"


def test_sync_actor_preserves_order(ray_start_regular):
    @ray_tpu.remote
    class Ordered:
        def __init__(self):
            self.log = []

        def add(self, i):
            self.log.append(i)
            return i

        def get_log(self):
            return list(self.log)

    a = Ordered.remote()
    for i in range(20):
        a.add.remote(i)
    assert ray_tpu.get(a.get_log.remote(), timeout=60) == list(range(20))


def test_threaded_actor_state_updates_all_land(ray_start_regular):
    @ray_tpu.remote(max_concurrency=8)
    class Counter:
        def __init__(self):
            import threading

            self.lock = threading.Lock()
            self.n = 0

        def incr(self):
            with self.lock:
                self.n += 1
            return self.n

        def total(self):
            return self.n

    c = Counter.remote()
    ray_tpu.get([c.incr.remote() for _ in range(32)], timeout=60)
    assert ray_tpu.get(c.total.remote(), timeout=60) == 32


def test_concurrent_gets_inside_threaded_actor(ray_start_regular):
    """Blocked-CPU release is depth-counted: several methods of one actor
    blocked in ray.get at once must not wedge the node's CPU accounting."""
    @ray_tpu.remote
    def produce(i):
        time.sleep(0.2)
        return i * 10

    @ray_tpu.remote(max_concurrency=4)
    class Aggregator:
        def fetch(self, wrapped):
            # nested refs are not resolved by the head -> the actor blocks
            return ray_tpu.get(wrapped[0])

    a = Aggregator.remote()
    refs = [a.fetch.remote([produce.remote(i)]) for i in range(4)]
    assert sorted(ray_tpu.get(refs, timeout=60)) == [0, 10, 20, 30]

    # the node still schedules plain tasks afterwards (no CPU leak)
    @ray_tpu.remote
    def ping():
        return "ok"

    assert ray_tpu.get(ping.remote(), timeout=60) == "ok"


def test_async_waiters_beyond_old_thread_cap(ray_start_regular):
    """99 awaiting methods + 1 releaser under max_concurrency=100: awaiting
    methods must not park executor threads (the event loop multiplexes all
    in-flight coroutines), or any thread-pool cap below max_concurrency
    (the old hardcoded 64) deadlocks the releasing call forever."""
    import asyncio

    @ray_tpu.remote(max_concurrency=100)
    class Gate:
        def __init__(self):
            self.event = asyncio.Event()

        async def wait(self, i):
            await self.event.wait()
            return i

        async def open(self):
            self.event.set()
            return "opened"

    g = Gate.remote()
    waiters = [g.wait.remote(i) for i in range(99)]
    time.sleep(1.0)  # let the waiters dispatch & park on the event
    assert ray_tpu.get(g.open.remote(), timeout=60) == "opened"
    assert sorted(ray_tpu.get(waiters, timeout=120)) == list(range(99))
