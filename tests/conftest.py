"""Test fixtures.

Mirrors the reference's ``python/ray/tests/conftest.py``:
``ray_start_regular`` (reference ``conftest.py:245``) boots a real
single-node runtime in-process; ``ray_start_cluster`` (``conftest.py:326``)
gives the fake multi-node Cluster.  JAX tests run on a virtual 8-device CPU
mesh (``xla_force_host_platform_device_count``) per SURVEY §4's TPU note.
"""

import os

# Must be set before the first jax backend is initialized.  XLA_FLAGS is read
# at backend-init time; the platform itself must be forced through
# jax.config because this image's sitecustomize registers a TPU PJRT plugin
# whose JAX_PLATFORMS=axon would otherwise win over our env var.
os.environ["JAX_PLATFORMS"] = "cpu"
# Worker subprocesses inherit os.environ; without this the TPU plugin's
# sitecustomize registration would override JAX_PLATFORMS in them too.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("RAY_TPU_LOG_LEVEL", "WARNING")
# The suite runs over the TYPED wire protocol (also the production
# default since the packed hot-frame codec landed) so every packed and
# protobuf arm is exercised by every cluster test — see _private/wire.py.
os.environ.setdefault("RAY_TPU_WIRE", "proto")
# ... and with a SHARDED head dispatch (also the production default):
# the whole actor/gang/concurrency-group surface runs at shard count 4,
# pinned explicitly so a default change can't silently shrink coverage.
os.environ.setdefault("RAY_TPU_HEAD_SHARDS", "4")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

import ray_tpu  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: heavy sanitizer/chaos runs excluded from tier-1")


def pytest_collection_modifyitems(config, items):
    # tier-1 is a plain `pytest tests/` — slow tests must opt in via an
    # -m expression that names "slow" or RAY_TPU_RUN_SLOW=1, or the TSan
    # build+run pushes the suite past its wall-clock cap (an unrelated
    # -m filter must not pull them in as a side effect)
    if ("slow" in (config.option.markexpr or "")
            or os.environ.get("RAY_TPU_RUN_SLOW")):
        return
    skip = pytest.mark.skip(
        reason="slow: run with -m slow or RAY_TPU_RUN_SLOW=1")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def ray_start_regular():
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_2_tpus():
    """Single node with 2 fake TPU chips (chips are only env-assigned)."""
    ray_tpu.init(num_cpus=4, num_tpus=2)
    yield
    ray_tpu.shutdown()


@pytest.fixture
def ray_start_cluster():
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster(initialize_head=True, head_node_args={"num_cpus": 2, "num_tpus": 0})
    yield cluster
    cluster.shutdown()
