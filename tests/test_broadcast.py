"""Multi-location objects + push/broadcast over a real-process cluster.

Reference counterparts: location SETS per object
(``src/ray/object_manager/ownership_based_object_directory.h:37``) and the
1->N push path (``push_manager.h:29``).  Disjoint per-node shm namespaces
mean every cross-node copy necessarily moved through the object plane.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import experimental
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.scheduling_strategies import NodeAffinitySchedulingStrategy


@pytest.fixture
def three_node_cluster():
    cluster = Cluster(
        initialize_head=True,
        head_node_args={"num_cpus": 2, "num_tpus": 0},
        real_processes=True,
    )
    nodes = [cluster.add_node(num_cpus=1) for _ in range(2)]
    yield cluster, nodes
    cluster.shutdown()


def _head_node():
    return ray_tpu._private.worker.global_worker.node


def test_broadcast_replicates_to_all_nodes(three_node_cluster):
    cluster, nodes = three_node_cluster
    payload = np.arange(1 << 20, dtype=np.float32)  # 4 MiB, head-origin
    ref = ray_tpu.put(payload)

    out = experimental.broadcast_object(ref, timeout=120)
    assert out["error"] is None, out
    assert out["replicas"] == 2
    node = _head_node()
    assert set(node.registry.replica_nodes(ref.binary())) == set(nodes)

    # every node reads it; remote readers attach their local replica
    @ray_tpu.remote(num_cpus=1)
    def checksum(arr):
        return float(arr.sum())

    want = float(payload.sum())
    refs = [
        checksum.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(nid)
        ).remote(ref)
        for nid in nodes
    ]
    assert ray_tpu.get(refs, timeout=240) == [want, want]


def test_pull_reports_replica_and_origin_death_promotes(three_node_cluster):
    """A consumer's pull lands in the location set; when the ORIGIN node
    dies, the object survives by promoting a replica — no lineage
    reconstruction, no re-execution."""
    cluster, (node_a, node_b) = three_node_cluster

    @ray_tpu.remote(num_cpus=1,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(node_a))
    def produce():
        return np.full((1 << 18,), 7, dtype=np.int64)  # 2 MiB on node A

    ref = produce.remote()

    # consume on node B -> B pulls a copy and reports it
    @ray_tpu.remote(num_cpus=1,
                    scheduling_strategy=NodeAffinitySchedulingStrategy(node_b))
    def consume(arr):
        return int(arr[0])

    assert ray_tpu.get(consume.remote(ref), timeout=240) == 7
    node = _head_node()
    import time

    deadline = time.time() + 30
    while time.time() < deadline:
        if node_b in node.registry.replica_nodes(ref.binary()):
            break
        time.sleep(0.2)
    assert node_b in node.registry.replica_nodes(ref.binary())

    # kill the origin node: the replica on B must keep the object alive
    # (mark_node_lost would otherwise unseal + resubmit produce())
    cluster.remove_node(node_a)
    loc = node.registry.get_location(ref.binary())
    assert loc is not None and loc.node_id == node_b
    out = ray_tpu.get(ref, timeout=240)
    assert int(out[0]) == 7 and out.shape == (1 << 18,)


def test_broadcast_inline_object_is_noop(three_node_cluster):
    ref = ray_tpu.put(b"tiny")  # inline: rides messages, nothing to fan out
    out = experimental.broadcast_object(ref)
    assert out == {"replicas": 0, "error": None}
