"""The ray_perf microbenchmark harness stays runnable (reference analog:
release/microbenchmark/run_microbenchmark.py driving ray_perf.py)."""

from ray_tpu._private.ray_perf import main


def test_ray_perf_quick():
    results = main(quick=True)
    by_name = {r["metric"]: r["value"] for r in results}
    assert len(results) >= 9
    assert all(v > 0 for v in by_name.values())
    # sanity floors: these run ~1000+ ops/s standalone; the generous
    # floors only catch order-of-magnitude regressions without flaking
    # when this runs late in the suite on a loaded 1-core CI box
    assert by_name["task_round_trip"] > 20
    assert by_name["actor_call_round_trip"] > 40
