"""Host-side collective group tests (ray.util.collective surface)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util import collective as col


@ray_tpu.remote
class Rank:
    def __init__(self, world_size, rank, group="g"):
        col.init_collective_group(world_size, rank, group_name=group)
        self.rank = rank
        self.group = group

    def do_allreduce(self):
        return col.allreduce(np.full((4,), float(self.rank + 1)), self.group)

    def do_allgather(self):
        return col.allgather(np.array([self.rank]), self.group)

    def do_broadcast(self):
        return col.broadcast(np.array([self.rank * 10.0]), src_rank=1, group_name=self.group)

    def do_reducescatter(self):
        return col.reducescatter(np.arange(4.0), self.group)

    def do_barrier(self):
        col.barrier(self.group)
        return self.rank

    def do_send(self, dst):
        col.send(np.full((3,), float(self.rank)), dst, self.group)
        return True

    def do_recv(self, src):
        return col.recv((3,), np.float64, src, self.group)


@pytest.fixture
def four_ranks(ray_start_regular):
    # rank 0 first so it creates the coordinator before the rest poll
    r0 = Rank.remote(4, 0)
    rest = [Rank.remote(4, i) for i in range(1, 4)]
    return [r0] + rest


def test_allreduce(four_ranks):
    outs = ray_tpu.get([a.do_allreduce.remote() for a in four_ranks])
    for o in outs:
        np.testing.assert_allclose(o, np.full((4,), 10.0))


def test_allgather(four_ranks):
    outs = ray_tpu.get([a.do_allgather.remote() for a in four_ranks])
    for o in outs:
        assert [int(x[0]) for x in o] == [0, 1, 2, 3]


def test_broadcast(four_ranks):
    outs = ray_tpu.get([a.do_broadcast.remote() for a in four_ranks])
    for o in outs:
        np.testing.assert_allclose(o, np.array([10.0]))


def test_reducescatter(four_ranks):
    outs = ray_tpu.get([a.do_reducescatter.remote() for a in four_ranks])
    # sum over 4 ranks of arange(4) = [0,4,8,12], scattered 1 element each
    got = sorted(float(o[0]) for o in outs)
    assert got == [0.0, 4.0, 8.0, 12.0]


def test_barrier(four_ranks):
    outs = ray_tpu.get([a.do_barrier.remote() for a in four_ranks])
    assert sorted(outs) == [0, 1, 2, 3]


def test_send_recv_point_to_point(four_ranks):
    """p2p must involve only the (src, dst) pair — ranks 0,1 transfer while
    2,3 do nothing."""
    recv_ref = four_ranks[1].do_recv.remote(0)
    send_ref = four_ranks[0].do_send.remote(1)
    assert ray_tpu.get(send_ref) is True
    np.testing.assert_allclose(ray_tpu.get(recv_ref), np.zeros(3))


def test_xla_backend_single_process(ray_start_regular):
    """backend="xla" rides the jax runtime (single-process world here;
    multi-process gangs are wired by the JaxConfig Train backend)."""
    from ray_tpu.util.collective import collective as col

    col.init_collective_group(world_size=1, rank=0, backend="xla",
                              group_name="xg")
    try:
        x = np.arange(8.0)
        np.testing.assert_allclose(col.allreduce(x, group_name="xg"), x)
        gathered = col.allgather(x, group_name="xg")
        assert len(gathered) == 1
        np.testing.assert_allclose(gathered[0], x)
        np.testing.assert_allclose(
            col.broadcast(x, src_rank=0, group_name="xg"), x)
        np.testing.assert_allclose(
            col.reducescatter(x, group_name="xg"), x)
        col.barrier(group_name="xg")
        assert col.get_rank("xg") == 0
        with pytest.raises(NotImplementedError):
            col.send(x, 0, group_name="xg")
    finally:
        col.destroy_collective_group("xg")


def test_xla_backend_world_size_mismatch(ray_start_regular):
    from ray_tpu.util.collective import collective as col

    with pytest.raises(ValueError, match="process_count"):
        col.init_collective_group(world_size=4, rank=0, backend="xla",
                                  group_name="bad-xg")
