"""End-to-end distributed request tracing + critical path + doctor.

Covers the trace-context plane (``util/tracing.py``): nested task chains
sharing a trace_id, actor and serve-handle calls continuing the caller's
trace, compiled-graph executions joining the submitting trace through
channel payloads, disabled-by-default spec hygiene; the head-side
assembly (``TraceTable``, ``get_trace``/``list_traces``/
``summarize_traces``); critical-path analysis
(``util/trace_analysis.py``); the rule-based ``ray_tpu doctor``
(``util/doctor.py`` — induced pathologies flag, healthy runs stay
clean); the head-side ``summarize_state`` RPC; and the collapsed
sampling-profile format.
"""

import json
import os
import time
import urllib.request

import pytest

import ray_tpu
from ray_tpu._private import events as events_mod
from ray_tpu.util import tracing


@pytest.fixture(scope="module")
def trace_cluster():
    """One cluster for the tracing tests: traces are isolated by
    construction (fresh trace_id per block), and sharing the boot keeps
    the tier-1 wall-clock flat.  Fast event flush so worker-shipped spans
    land quickly."""
    os.environ["RAY_TPU_EVENTS_FLUSH_S"] = "0.2"
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()
    os.environ.pop("RAY_TPU_EVENTS_FLUSH_S", None)


def _get_trace_until(tid, pred, timeout=20.0):
    """Poll get_trace until ``pred(trace)`` holds (worker spans ship on
    the pusher cadence)."""
    from ray_tpu.experimental.state import api as state

    deadline = time.time() + timeout
    tr = None
    while time.time() < deadline:
        tr = state.get_trace(tid)
        if tr is not None and pred(tr):
            return tr
        time.sleep(0.2)
    return tr


# ---------------------------------------------------------------------------
# context plumbing (no cluster)
# ---------------------------------------------------------------------------

def test_no_context_means_no_propagation():
    """Disabled-by-default: outside any trace() block nothing is created
    — child contexts are None, span() is a no-op, emit_span drops."""
    assert tracing.current_context() is None
    assert tracing.child_context("x") is None
    assert tracing.child_context_for_task("x") is None
    before = events_mod.buffer().last_seq()
    with tracing.span("noop"):
        pass
    tracing.emit_span("nothing", 1.0, None)
    assert events_mod.buffer().last_seq() == before


def test_trace_context_nesting_and_span_events():
    with tracing.trace("outer") as outer:
        assert tracing.current_context() == outer
        child = tracing.child_context("hop")
        assert child["trace_id"] == outer["trace_id"]
        assert child["parent_span_id"] == outer["span_id"]
        with tracing.trace("inner") as inner:
            assert inner["trace_id"] == outer["trace_id"]
            assert inner["parent_span_id"] == outer["span_id"]
    assert tracing.current_context() is None
    rows = [r for r in events_mod.local_events()
            if r["source"] == "trace"
            and (r.get("data") or {}).get("trace_id") == outer["trace_id"]]
    names = {r["message"] for r in rows}
    assert {"outer", "inner"} <= names
    inner_row = next(r for r in rows if r["message"] == "inner")
    assert inner_row["data"]["parent_span_id"] == outer["span_id"]
    assert inner_row["span_dur"] >= 0


def test_trace_table_assembles_and_caps():
    t = events_mod.TraceTable(max_traces=2, max_spans=3)
    def row(tid, sid, parent="", ts=1.0, dur=0.5, msg="m"):
        return {"ts": ts, "source": "trace", "severity": "DEBUG",
                "message": msg, "span_dur": dur,
                "data": {"trace_id": tid, "span_id": sid,
                         "parent_span_id": parent, "phase": "span"}}
    t.add("w1", [row("a", "s1"), row("a", "s2", parent="s1", ts=1.4),
                 {"ts": 2.0, "source": "scheduler", "message": "no trace"}])
    got = t.get("a")
    assert [s["span_id"] for s in got["spans"]] == ["s1", "s2"]
    assert got["spans"][0]["start"] == pytest.approx(0.5)
    # per-trace span cap: LAST-N kept (spans arrive child-first, so the
    # root closes last — keep-last preserves the upper tree), the
    # overflow counted as dropped
    t.add("w1", [row("a", f"x{i}", ts=3.0 + i) for i in range(4)])
    got = t.get("a")
    assert len(got["spans"]) == 3 and got["dropped_spans"] == 3
    assert [s["span_id"] for s in got["spans"]] == ["x1", "x2", "x3"]
    # trace cap: LRU eviction of the least recently updated
    t.add("w1", [row("b", "s1")])
    t.add("w1", [row("c", "s1")])
    assert t.get("a") is None and t.get("c") is not None
    assert len(t) == 2
    summary = t.summarize()
    assert summary["num_traces"] == 2


# ---------------------------------------------------------------------------
# critical-path analysis (pure)
# ---------------------------------------------------------------------------

def test_critical_path_phase_attribution():
    from ray_tpu.util.trace_analysis import analyze, render_trace

    trace = {"trace_id": "t", "spans": [
        {"name": "root", "span_id": "r", "parent_span_id": "",
         "phase": "http", "source": "trace", "start": 0.0, "end": 10.0},
        {"name": "queue", "span_id": "q", "parent_span_id": "r",
         "phase": "scheduler_queue", "source": "task",
         "start": 1.0, "end": 4.0},
        {"name": "exec", "span_id": "x", "parent_span_id": "r",
         "phase": "execution", "source": "task", "start": 4.0, "end": 9.0},
        {"name": "wait", "span_id": "w", "parent_span_id": "x",
         "phase": "channel_wait", "source": "compiled_dag",
         "start": 5.0, "end": 7.0},
    ]}
    a = analyze(trace)
    assert a["wall_s"] == pytest.approx(10.0)
    # phases sum exactly to wall time; the deepest span wins its window
    assert a["phases"]["http"] == pytest.approx(2.0)  # 0-1 + 9-10
    assert a["phases"]["scheduler_queue"] == pytest.approx(3.0)
    assert a["phases"]["execution"] == pytest.approx(3.0)  # 4-5 + 7-9
    assert a["phases"]["channel_wait"] == pytest.approx(2.0)
    assert sum(a["phases"].values()) == pytest.approx(a["wall_s"])
    path = [(s["name"], s["phase"]) for s in a["critical_path"]]
    assert path == [("root", "http"), ("queue", "scheduler_queue"),
                    ("exec", "execution"), ("wait", "channel_wait"),
                    ("exec", "execution"), ("root", "http")]
    text = render_trace(trace, a)
    assert "critical path" in text and "scheduler_queue" in text
    # uninstrumented gaps attribute to "idle", not to a random span
    gap = analyze({"spans": [
        {"name": "a", "span_id": "a", "phase": "p", "start": 0.0, "end": 1.0},
        {"name": "b", "span_id": "b", "phase": "p", "start": 3.0, "end": 4.0},
    ]})
    assert gap["phases"]["idle"] == pytest.approx(2.0)
    assert analyze(None) == {"wall_s": 0.0, "num_spans": 0, "phases": {},
                             "critical_path": []}


# ---------------------------------------------------------------------------
# doctor rules (pure)
# ---------------------------------------------------------------------------

def test_doctor_healthy_run_is_clean():
    from ray_tpu.util.doctor import diagnose

    events = [
        {"source": "scheduler", "message": "dispatch tick",
         "severity": "DEBUG"},
        {"source": "streaming", "message": "backpressure stall",
         "severity": "DEBUG", "data": {"op": "map", "total_stalled_s": 0.1}},
        {"source": "serve", "message": "router stalled: no replica available",
         "severity": "WARNING", "data": {"replicas": 0}},  # startup, not saturation
        {"source": "train", "message": "gang started", "severity": "INFO"},
        {"source": "compiled_dag", "message": "channel wait",
         "severity": "DEBUG", "span_dur": 60.0, "data": {"op": "recv"}},
        # healthy perf plane (PR 11 rules must stay silent on these):
        # bucketed compiles below the storm threshold, low ingest share,
        # mild prefill interference
        {"source": "perf", "message": "jit compile", "severity": "DEBUG",
         "span_dur": 0.4, "data": {"fn": "prefill", "n_sigs": 4,
                                   "misses": 4, "hits": 900}},
        {"source": "perf", "message": "step phases", "severity": "DEBUG",
         "entity_id": "rank0", "span_dur": 0.1,
         "data": {"wall_s": 0.1, "mfu": 0.4,
                  "phases": {"ingest": 0.01, "compute": 0.09}}},
        {"source": "perf", "message": "prefill interference",
         "severity": "DEBUG", "entity_id": "engine-1",
         "data": {"interference_s": 0.5, "interference_frac": 0.05,
                  "interleaved_ticks": 400, "decode_only_ticks": 5000}},
    ]
    tasks = [{"name": "t", "node_id": "n1", "exec_start": 0.0,
              "exec_end": 0.01}] * 20
    assert diagnose(events, tasks) == []


def test_doctor_flags_each_pathology():
    from ray_tpu.util import doctor

    cases = {
        "backpressure_stall": [
            {"source": "streaming", "message": "backpressure stall",
             "severity": "DEBUG",
             "data": {"op": "map", "total_stalled_s": 4.2}}],
        "split_starvation": [
            {"source": "streaming", "message": "split starved",
             "severity": "DEBUG", "data": {"wait_s": 1.5}}] * 3,
        "spill_thrash": [
            {"source": "object_store", "message": "spilled object to disk",
             "severity": "WARNING", "data": {"size_mb": 100}}] * 4,
        "oom_kills": [
            {"source": "scheduler", "message": "OOM kill",
             "severity": "WARNING"}],
        "gang_restart": [
            {"source": "train", "message": "gang restarted",
             "severity": "WARNING"}],
        "stuck_channel": [
            {"source": "compiled_dag", "message": "actor loop died",
             "severity": "ERROR"}],
        "router_saturation": [
            {"source": "serve",
             "message": "router stalled: no replica available",
             "severity": "WARNING", "data": {"replicas": 3}}],
        "worker_churn": [
            {"source": "worker_pool", "message": "worker died: signal 9",
             "severity": "WARNING"}] * 3,
    }
    for rule, events in cases.items():
        findings = doctor.diagnose(events)
        assert [f["rule"] for f in findings] == [rule], (rule, findings)
        assert findings[0]["evidence"] and findings[0]["remedy"]
    # blocked SEND-side channel wait = stuck consumer (recv idle is fine)
    send_stuck = doctor.diagnose([
        {"source": "compiled_dag", "message": "channel wait",
         "severity": "DEBUG", "span_dur": 9.0, "data": {"op": "send"}}])
    assert [f["rule"] for f in send_stuck] == ["stuck_channel"]
    # slow-node skew needs same-name tasks on >= 2 nodes with real deltas
    slow = [{"name": "step", "node_id": "n-slow", "exec_start": 0.0,
             "exec_end": 0.9}] * 6
    fast = [{"name": "step", "node_id": "n-fast", "exec_start": 0.0,
             "exec_end": 0.1}] * 6
    findings = doctor.diagnose([], slow + fast)
    assert [f["rule"] for f in findings] == ["slow_node_skew"]
    assert "n-slow" in findings[0]["summary"]
    assert doctor.render(findings).startswith("ray_tpu doctor: 1 finding")
    assert "no findings" in doctor.render([])


# ---------------------------------------------------------------------------
# cluster end-to-end
# ---------------------------------------------------------------------------

def test_nested_tasks_share_trace_and_specs_stay_clean(trace_cluster):
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    def child(x):
        return x + 1

    @ray_tpu.remote
    def parent(x):
        return ray_tpu.get(child.remote(x)) + 1

    # untraced: no trace_ctx key anywhere
    assert ray_tpu.get(parent.remote(0), timeout=60) == 2
    with tracing.trace("req") as ctx:
        assert ray_tpu.get(parent.remote(1), timeout=60) == 3
    tid = ctx["trace_id"]
    deadline = time.time() + 15
    while time.time() < deadline:
        rows = [t for t in state.list_tasks(limit=10_000)
                if (t.get("trace_ctx") or {}).get("trace_id") == tid]
        if len(rows) >= 2 and all(t.get("exec_end") for t in rows):
            break
        time.sleep(0.2)
    by_name = {t["name"]: t for t in rows}
    assert set(by_name) == {"parent", "child"}
    # the nested submission chains: child's parent span IS parent's span
    assert (by_name["child"]["trace_ctx"]["parent_span_id"]
            == by_name["parent"]["trace_ctx"]["span_id"])
    assert by_name["parent"]["trace_ctx"]["parent_span_id"] == ctx["span_id"]
    # untraced rows stay clean (presence of a context IS the switch)
    untraced = [t for t in state.list_tasks(limit=10_000)
                if t["name"] == "parent" and not t.get("trace_ctx")]
    assert untraced


def test_actor_calls_continue_trace(trace_cluster):
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get(c.bump.remote(), timeout=60) == 1
    with tracing.trace("actor-req") as ctx:
        assert ray_tpu.get(c.bump.remote(), timeout=60) == 2
    rows = [t for t in state.list_tasks(limit=10_000)
            if (t.get("trace_ctx") or {}).get("trace_id") == ctx["trace_id"]]
    assert any(t["name"] == "Counter.bump" for t in rows)


def test_get_trace_assembles_task_and_span_tree(trace_cluster):
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    def work(x):
        time.sleep(0.05)
        return x

    with tracing.trace("assembled") as ctx:
        ray_tpu.get([work.remote(i) for i in range(3)], timeout=60)
    tid = ctx["trace_id"]
    tr = _get_trace_until(
        tid, lambda t: sum(s["phase"] == "execution"
                           for s in t["spans"]) >= 3)
    phases = {s["phase"] for s in tr["spans"]}
    assert {"span", "task", "scheduler_queue", "execution",
            "get_wait"} <= phases
    # root span + queue/exec sub-spans parented under their task spans
    by_id = {s["span_id"]: s for s in tr["spans"]}
    execs = [s for s in tr["spans"] if s["phase"] == "execution"]
    for s in execs:
        parent = by_id[s["parent_span_id"]]
        assert parent["phase"] == "task"
    # list/summarize surfaces
    summaries = state.list_traces(limit=100)
    assert any(r["trace_id"] == tid for r in summaries)
    assert state.summarize_traces()["num_traces"] >= 1
    assert state.get_trace("no-such-trace") is None
    # the analysis is consistent: phases sum to wall
    from ray_tpu.util.trace_analysis import analyze

    a = analyze(tr)
    assert a["wall_s"] > 0
    # each phase rounds to 1us in the payload; the identity holds to that
    assert sum(a["phases"].values()) == pytest.approx(a["wall_s"], abs=1e-4)


def test_compiled_graph_joins_submitting_trace(trace_cluster):
    from ray_tpu.dag import InputNode
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    class Stage:
        def fwd(self, x):
            return x + 1

    with InputNode() as inp:
        dag = Stage.bind().fwd.bind(Stage.bind().fwd.bind(inp))
    cg = dag.experimental_compile(max_inflight=4)
    try:
        assert cg.execute(0).get(timeout=60) == 2  # untraced warm
        with tracing.trace("cdag-req") as ctx:
            assert cg.execute(10).get(timeout=60) == 12
        # untraced executions still work after a traced one (payloads
        # revert to bare values)
        assert cg.execute(5).get(timeout=60) == 7
        tid = ctx["trace_id"]
        tr = _get_trace_until(
            tid, lambda t: sum(s["phase"] == "node_exec"
                               for s in t["spans"]) >= 2)
        nodes = [s for s in tr["spans"] if s["phase"] == "node_exec"]
        assert {s["name"] for s in nodes} == {"fwd:0", "fwd:1"}
        assert all(s["source"] == "compiled_dag" for s in nodes)
        # the stages chain: fwd:1's span parents to fwd:0's
        by_id = {s["span_id"]: s for s in tr["spans"]}
        fwd1 = next(s for s in nodes if s["name"] == "fwd:1")
        assert by_id[fwd1["parent_span_id"]]["name"] == "fwd:0"
        # driver-side submit span present
        assert any(s["phase"] == "submit" for s in tr["spans"])
    finally:
        cg.teardown()


def test_serve_request_trace_spans_router_replica_and_graph(trace_cluster):
    """Acceptance: a serve request through prefill_decode_graph yields ONE
    trace spanning router admission -> replica execution -> compiled-graph
    node executions with channel-wait attribution."""
    from ray_tpu import serve
    from ray_tpu.experimental.state import api as state

    serve.start(_http=False)

    @serve.deployment
    class Gen:
        def __init__(self):
            from ray_tpu.serve.llm import prefill_decode_graph

            self.graph = prefill_decode_graph(
                "gpt2", "tiny", max_new_tokens=4, prefill_bucket=16)
            self.graph.execute([1, 2]).get(timeout=120)  # warm/compile

        def __call__(self, tokens):
            return self.graph.execute(list(tokens)).get(timeout=120)

        def shutdown(self):
            self.graph.teardown()

    handle = serve.run(Gen.bind(), _blocking=True, timeout_s=300)
    try:
        with tracing.trace("serve-req") as ctx:
            out = ray_tpu.get(handle.remote([3, 5, 7]), timeout=120)
        assert isinstance(out, list) and len(out) == 4
        tid = ctx["trace_id"]
        tr = _get_trace_until(
            tid,
            lambda t: {"router_admission", "execution"}
            <= {s["phase"] for s in t["spans"]}
            and sum(s["phase"] == "node_exec" for s in t["spans"]) >= 2)
        phases = {s["phase"] for s in tr["spans"]}
        assert "router_admission" in phases      # router
        assert "execution" in phases             # replica task exec
        names = {s["name"] for s in tr["spans"]}
        assert "ServeReplica.handle_request" in names
        nodes = {s["name"] for s in tr["spans"] if s["phase"] == "node_exec"}
        assert {"prefill:0", "decode:1"} <= nodes
        # channel-wait attribution: decode waited on prefill's output
        # inside THIS request's window (clamped to it)
        waits = [s for s in tr["spans"] if s["phase"] == "channel_wait"]
        t0 = min(s["start"] for s in tr["spans"])
        assert all(s["start"] >= t0 - 0.5 for s in waits)
        from ray_tpu.util.trace_analysis import analyze

        a = analyze(tr)
        assert a["critical_path"], a
    finally:
        serve.delete("Gen")
        serve.shutdown()


def test_doctor_flags_induced_stall_and_gang_restart(trace_cluster):
    """Induced pathologies reach the doctor through the real event
    pipeline: a budget-1 streaming pump stalled by a slow consumer, and a
    gang-restart event emitted from a worker."""
    import numpy as np

    from ray_tpu import data as rd
    from ray_tpu.experimental.state import api as state
    from ray_tpu.util.doctor import diagnose, run_doctor

    # NO healthy-run precondition here: the driver's event ring is
    # process-global, so under the full suite earlier modules' deliberate
    # OOM/chaos events are still visible to list_events.  The
    # healthy-run-is-clean gate lives in test_doctor_healthy_run_is_clean
    # (pure rules) and in the bench harness (own subprocess).

    # budget 1 + a consumer sleeping per block: the pump stalls for well
    # over the rule threshold, and the 1/s-throttled stall events have
    # time to report a cumulative total past it
    os.environ["RAY_TPU_STREAMING_BLOCK_BUDGET"] = "1"
    try:
        blocks = 24
        ds = rd.from_numpy(np.arange(blocks << 11, dtype=np.int64),
                           parallelism=blocks)
        ds = ds.map_batches(lambda b: np.asarray(b) * 2)
        n = 0
        for batch in ds.iter_batches(batch_size=1 << 11):
            time.sleep(0.08)  # slow consumer: the pump stalls on budget 1
            n += len(batch)
        assert n == blocks << 11
    finally:
        os.environ.pop("RAY_TPU_STREAMING_BLOCK_BUDGET", None)

    @ray_tpu.remote
    def restart_gang():
        from ray_tpu._private import events

        events.emit("train", "gang restarted", severity="WARNING",
                    restarts=2, world_size=4)
        return 1

    assert ray_tpu.get(restart_gang.remote(), timeout=60) == 1

    def _mine_shipped():
        # MY induced event (marked world_size=4) made it worker ring ->
        # ship -> head table; earlier suites' train events could satisfy
        # the rule alone, so wait for the marked row specifically
        return any(
            r.get("message") == "gang restarted"
            and (r.get("data") or {}).get("world_size") == 4
            for r in state.list_events(limit=10_000, source="train"))

    deadline = time.time() + 20
    rules = set()
    while time.time() < deadline:
        findings = run_doctor()
        rules = {f["rule"] for f in findings}
        if {"backpressure_stall", "gang_restart"} <= rules \
                and _mine_shipped():
            break
        time.sleep(0.3)
    assert {"backpressure_stall", "gang_restart"} <= rules, rules
    assert _mine_shipped()
    # evidence rows ride along for the operator
    by_rule = {f["rule"]: f for f in findings}
    assert by_rule["gang_restart"]["evidence"]
    assert by_rule["backpressure_stall"]["count"] >= 1


def test_summarize_state_head_side(trace_cluster):
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    def tick():
        return 1

    ray_tpu.get([tick.remote() for _ in range(4)], timeout=60)
    tasks = state.summarize_state("tasks")
    assert tasks["tick"]["FINISHED"] >= 4
    assert state.summarize_tasks() == tasks
    ev = state.summarize_events()
    assert "scheduler" in ev
    assert isinstance(state.summarize_actors(), dict)
    with pytest.raises(ValueError):
        state.summarize_state("nonsense")


def test_profile_collapsed_format(trace_cluster):
    from ray_tpu._private.sampling_profiler import (
        SamplingProfiler,
        collapsed_from_report,
    )

    p = SamplingProfiler(period_s=0.001)
    p.samples["a.py:f|b.py:g"] = 7
    p.samples["a.py:f"] = 3
    folded = p.report_collapsed()
    assert "a.py:f;b.py:g 7" in folded.splitlines()
    assert collapsed_from_report(p.report()) == folded
    # dashboard endpoint serves it as plain text
    from ray_tpu._private.worker import global_worker

    host, port = global_worker.node.dashboard.address
    url = (f"http://{host}:{port}/api/profile"
           f"?duration=0.3&format=collapsed")
    with urllib.request.urlopen(url, timeout=60) as r:
        body = r.read().decode()
        assert "json" not in r.headers.get("Content-Type", "")
    for line in body.splitlines():
        stack, _, count = line.rpartition(" ")
        assert stack and count.isdigit()
        assert "|" not in stack


def test_timeline_merges_trace_flow_arrows():
    from ray_tpu.util.timeline import merged_timeline

    rows = [
        {"ts": 10.0, "source": "trace", "severity": "DEBUG",
         "message": "root", "span_dur": 2.0, "entity_id": "t1",
         "origin": "head",
         "data": {"trace_id": "t1", "span_id": "a",
                  "parent_span_id": "", "phase": "http"}},
        {"ts": 9.9, "source": "trace", "severity": "DEBUG",
         "message": "admission", "span_dur": 0.5, "entity_id": "t1",
         "origin": "head",
         "data": {"trace_id": "t1", "span_id": "b",
                  "parent_span_id": "a", "phase": "router_admission"}},
    ]
    events = merged_timeline([], rows)
    json.loads(json.dumps(events))
    # per-trace row: trace spans keyed by trace_id, not origin
    slices = [e for e in events if e.get("cat") == "trace" and e["ph"] == "X"]
    assert slices and all(e["tid"] == "t1" for e in slices)
    flows = [e for e in events if e.get("cat") == "trace_flow"]
    assert {e["ph"] for e in flows} == {"s", "f"}
    s = next(e for e in flows if e["ph"] == "s")
    f = next(e for e in flows if e["ph"] == "f")
    assert s["id"] == f["id"] == "b"
    assert f["ts"] >= s["ts"]  # arrow never points backwards
