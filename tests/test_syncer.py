"""P2P resource/health sync mesh (``_private/syncer.py``).

Unit half: the versioned-snapshot store's merge invariants (only newer
versions apply, death rumors keep the first observation and are erased by
resurrection, suspicions union per observer) and the signed framed
transport.  Mesh half: real in-process syncers converging over sockets.
Cluster half: the mesh is ON by default for agent-joined clusters, a
SIGSTOPPED agent is removed by peer suspect quorum well before the
missed-pong timeout, and a node whose head link goes lossy SURVIVES the
heartbeat timeout because its peers' reports keep vouching for it — the
head is no longer the sole fan-in.
"""

import os
import signal
import socket
import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.syncer import (
    ResourceSyncer,
    SyncerStore,
    recv_frame,
    send_frame,
)
from ray_tpu.cluster_utils import Cluster
from ray_tpu._private.worker import global_worker

AUTHKEY = b"test-authkey"


# ---------------------------------------------------------------------------
# store invariants
# ---------------------------------------------------------------------------

def test_store_version_gating():
    a = SyncerStore("a")
    a.local_update({"x": 1})
    a.local_update({"x": 2})
    assert a.get("a")["version"] == 2

    b = SyncerStore("b")
    # b folds a's v2; a stale v1 replay must not regress it
    applied = b.merge({"a": dict(a.get("a"))})
    assert applied == 1
    stale = dict(a.get("a"))
    stale["version"] = 1
    stale["x"] = 99
    assert b.merge({"a": stale}) == 0
    assert b.get("a")["x"] == 2

    # nobody but the node itself authors its own snapshot
    forged = {"node_id": "b", "version": 100, "ts": time.time()}
    b.merge({"b": forged})
    assert b.get("b") is None  # b never local_update'd


def test_death_rumor_first_observer_wins_and_resurrection_erases():
    s = SyncerStore("w")
    t0 = time.time()
    assert s.mark_dead("x", by="a", ts=t0 + 5)
    # an EARLIER observation replaces (it is the detection-latency truth)
    assert s.mark_dead("x", by="b", ts=t0 + 1)
    # a later observation is not news
    assert not s.mark_dead("x", by="c", ts=t0 + 9)
    _, deaths, _ = s.snapshot()
    assert deaths["x"]["by"] == "b"

    # a snapshot AUTHORED after the rumor proves resurrection
    s.merge({"x": {"node_id": "x", "version": 7, "ts": t0 + 30}})
    _, deaths, _ = s.snapshot()
    assert "x" not in deaths
    # ...but a snapshot older than the rumor does not
    s.mark_dead("x", by="a", ts=t0 + 60)
    s.merge(None, deaths={"x": {"ts": t0 + 60, "by": "a"}})
    _, deaths, _ = s.snapshot()
    assert "x" in deaths


def test_suspect_union_and_clear_on_progress():
    s = SyncerStore("w")
    s.mark_suspect("x", by="a", ts=1.0)
    s.merge(None, suspects={"x": {"b": 2.0, "a": 0.5}})
    _, _, suspects = s.snapshot()
    assert set(suspects["x"]) == {"a", "b"}
    assert suspects["x"]["a"] == 1.0  # freshest per observer kept

    # the suspect answered someone: a NEWER snapshot clears the suspicion
    s.merge({"x": {"node_id": "x", "version": 3, "ts": time.time()}})
    _, _, suspects = s.snapshot()
    assert "x" not in suspects


def test_store_prune_to_membership():
    s = SyncerStore("w")
    s.merge({"x": {"node_id": "x", "version": 1, "ts": 1.0}})
    s.mark_dead("y", by="w")
    s.mark_suspect("z", by="w")
    s.local_update()
    s.prune({"x"})
    snaps, deaths, suspects = s.snapshot()
    assert set(snaps) == {"w", "x"}  # own entry always kept
    assert not deaths and not suspects


# ---------------------------------------------------------------------------
# transport
# ---------------------------------------------------------------------------

def test_frame_signature_rejects_tamper_and_wrong_key():
    a, b = socket.socketpair()
    try:
        send_frame(a, AUTHKEY, {"type": "syncer_sync", "n": 1})
        assert recv_frame(b, AUTHKEY)["n"] == 1

        send_frame(a, b"wrong-key", {"type": "syncer_sync"})
        with pytest.raises(OSError, match="authentication"):
            recv_frame(b, AUTHKEY)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# in-process mesh (no cluster)
# ---------------------------------------------------------------------------

def _mesh(n, tick_s=0.05, **kw):
    syncers = [
        ResourceSyncer(f"m{i}", AUTHKEY, state_fn=lambda i=i: {"i": i},
                       tick_s=tick_s, seed=i, **kw).start()
        for i in range(n)
    ]
    directory = {s.node_id: s.addr for s in syncers}
    for s in syncers:
        s.set_peers(directory)
    return syncers


def _stop_all(syncers):
    for s in syncers:
        s.stop()


def test_mesh_converges_to_full_view():
    syncers = _mesh(8)
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            views = [set(s.store.snapshot()[0]) for s in syncers]
            if all(len(v) == 8 for v in views):
                break
            time.sleep(0.05)
        else:
            pytest.fail(f"mesh never converged: {[len(v) for v in views]}")
    finally:
        _stop_all(syncers)


def test_dead_peer_detected_by_refused_dials_and_rumor_gossips():
    syncers = _mesh(4)
    try:
        victim = syncers[0]
        deadline = time.time() + 20
        while time.time() < deadline:  # converge first
            if all(len(s.store.snapshot()[0]) == 4 for s in syncers):
                break
            time.sleep(0.05)
        victim.stop()  # closes the listener: dials now get ECONNREFUSED
        # first-observer-wins: the rumor spreads AND converges — every
        # store ends with the single EARLIEST observation time (two
        # observers may record a death within the same tick; gossip
        # settles them onto the earlier one)
        deadline = time.time() + 20
        while time.time() < deadline:
            deaths = [s.store.snapshot()[1] for s in syncers[1:]]
            ts = {round(d["m0"]["ts"], 6) for d in deaths if "m0" in d}
            if all("m0" in d for d in deaths) and len(ts) == 1:
                break
            time.sleep(0.05)
        else:
            pytest.fail(f"death rumor never converged: {deaths}")
    finally:
        _stop_all(syncers)


# ---------------------------------------------------------------------------
# real agent clusters (the mesh as deployed)
# ---------------------------------------------------------------------------

@pytest.fixture
def mesh_cluster():
    cluster = Cluster(initialize_head=True,
                      head_node_args={"num_cpus": 1, "num_tpus": 0},
                      real_processes=True)
    yield cluster
    cluster.shutdown()


def _wait(pred, timeout, what):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.1)
    pytest.fail(f"timed out waiting for {what}")


def test_mesh_on_by_default_and_head_folds_reports(mesh_cluster):
    """Agents register syncer listeners without any opt-in, and the head
    folds their converged views (version-gated) — the mesh is the default
    resource/health plane for emulated multi-node clusters."""
    n1 = mesh_cluster.add_node(num_cpus=1, slice_id="sl-a")
    n2 = mesh_cluster.add_node(num_cpus=1)
    node = global_worker.node
    with node.lock:
        assert node.nodes[n1].syncer_addr is not None
        assert node.nodes[n2].syncer_addr is not None
        assert node.nodes[n1].slice_id == "sl-a"

    _wait(lambda: set(node._syncer_versions) >= {n1, n2},
          30, "mesh reports folding at the head")
    v1 = node._syncer_versions[n1]
    _wait(lambda: node._syncer_versions[n1] > v1,
          30, "version advance (liveness through the mesh)")


def test_sigstop_removed_by_suspect_quorum_before_pong_timeout(mesh_cluster):
    """A paused host keeps its TCP sockets open, so only timeout paths can
    see it.  Peer suspect quorum must beat the head's own 15s missed-pong
    window — peer-observed death reaches the head faster."""
    nodes = [mesh_cluster.add_node(num_cpus=1) for _ in range(3)]
    node = global_worker.node
    _wait(lambda: set(node._syncer_versions) >= set(nodes),
          30, "mesh convergence before the pause")

    victim = nodes[0]
    pid = mesh_cluster.agents[victim].pid
    os.kill(pid, signal.SIGSTOP)
    t0 = time.time()
    try:
        _wait(lambda: not node.nodes[victim].alive, 13,
              "suspect-quorum removal")
        elapsed = time.time() - t0
    finally:
        os.kill(pid, signal.SIGCONT)
    timeout_s = node.cfg.health_check_timeout_s
    assert elapsed < timeout_s, (
        f"removal took {elapsed:.1f}s — not faster than the "
        f"{timeout_s:.0f}s heartbeat timeout path")
    from ray_tpu.experimental.state import api as state

    evs = state.list_events(limit=5000)
    assert any(e.get("source") == "syncer"
               and e.get("entity_id") == victim
               and "unresponsive" in e.get("message", "")
               for e in evs), "no syncer suspect/removal event at the head"


def test_lossy_head_link_survives_via_peer_reports(mesh_cluster):
    """Drop 100% of one agent's outbound control messages for longer than
    the heartbeat timeout: its pongs and reports vanish, but its gossip
    keeps flowing P2P, and its PEERS' reports carry its advancing
    snapshots to the head — so the head keeps it alive.  Exactly the
    'head is not the sole fan-in' claim."""
    n1 = mesh_cluster.add_node(num_cpus=1)
    n2 = mesh_cluster.add_node(num_cpus=1)
    node = global_worker.node
    _wait(lambda: set(node._syncer_versions) >= {n1, n2},
          30, "mesh convergence before the drop")

    old_timeout = node.cfg.health_check_timeout_s
    node.cfg.health_check_timeout_s = 4.0
    try:
        from ray_tpu.devtools.chaos import ChaosMonkey

        cm = ChaosMonkey(procs=mesh_cluster.agents)
        cm.drop_messages(n1, frac=1.0, duration_s=10.0)
        # ride out > 2x the (shrunk) timeout inside the drop window
        time.sleep(9.0)
        with node.lock:
            assert node.nodes[n1].alive, (
                "node died during the drop window — the mesh failed to "
                "vouch for it")
    finally:
        node.cfg.health_check_timeout_s = old_timeout
    # chaos injections are on the audit trail
    from ray_tpu.experimental.state import api as state

    evs = state.list_events(limit=5000)
    assert any(e.get("source") == "chaos" and e.get("entity_id") == n1
               for e in evs)
