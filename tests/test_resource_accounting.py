"""Resource accounting over time: head TSDB, `ray_tpu top`/`memory`,
object-ownership auditing, trend doctor rules, and the metrics-layer
satellites (origin expiry, Metric.remove, deadline-ticked pusher,
list truncation markers).
"""

import io
import json
import os
import time
import urllib.request
import warnings
from contextlib import redirect_stdout

import numpy as np
import pytest

import ray_tpu
from ray_tpu.util.tsdb import TimeSeriesStore


# ---------------------------------------------------------------------------
# TSDB (pure, no cluster)
# ---------------------------------------------------------------------------

T0 = 1_700_000_000.0  # fixed epoch: downsample buckets must be deterministic


def test_tsdb_downsampling_sum_max_last():
    """Each 1-min/10-min bucket keeps (last, max, min, sum, count) so any
    aggregation stays answerable after the raw ring rolled over."""
    ts = TimeSeriesStore(raw_points=10, m1_points=100, m10_points=100)
    # 30 min of 5s samples: value = sample index
    n = 360
    for i in range(n):
        ts.add_sample("m", float(i), tags={"k": "v"}, origin="w", ts=T0 + i * 5)
    now = T0 + n * 5
    # raw ring kept only the last 10 samples — minute-stage history must
    # still answer for the whole window
    for agg, check in [
        ("last", lambda pts: pts[-1][1] == n - 1 or pts[-1][1] >= n - 12),
        ("max", lambda pts: max(p[1] for p in pts) >= n - 12),
        ("sum", lambda pts: sum(p[1] for p in pts)
         == pytest.approx(sum(range(n)), rel=0.08)),
        ("count", lambda pts: sum(p[1] for p in pts)
         == pytest.approx(n, rel=0.08)),
    ]:
        q = ts.query("m", window_s=n * 5 + 60, step_s=60, agg=agg, now=now)
        pts = q["series"][0]["points"]
        assert len(pts) >= 28, (agg, len(pts))
        assert check(pts), (agg, pts[-3:])
    # closed 1-min buckets aggregate exactly 12 consecutive 5s samples:
    # sum = 12a + 66 for some integer a, and adjacent buckets differ by
    # 12*12 (the ramp advances 12 per minute)
    q = ts.query("m", window_s=n * 5, step_s=60, agg="sum", now=now)
    interior = [p[1] for p in q["series"][0]["points"][2:-2]]
    assert interior
    assert all((v - 66) % 12 == 0 for v in interior), interior[:5]
    assert all(b - a == 144 for a, b in zip(interior, interior[1:]))


def test_tsdb_histograms_become_count_and_sum_series():
    ts = TimeSeriesStore()
    snap = {"lat_s": {"type": "histogram", "help": "h", "values": {
        (("k", "v"),): {"buckets": [1, 2], "bounds": (0.1,),
                        "sum": 3.5, "count": 3},
    }}}
    ts.ingest("w1", snap, ts=T0)
    names = {m["name"] for m in ts.list_metrics()}
    assert names == {"lat_s_count", "lat_s_sum"}
    q = ts.query("lat_s_sum", window_s=60, step_s=5, now=T0 + 1)
    assert q["series"][0]["points"][-1][1] == 3.5
    assert q["series"][0]["tags"]["origin"] == "w1"


def test_tsdb_retention_cap_under_10k_series_load():
    """The byte cap holds under synthetic 10k-series load; eviction is
    least-recently-updated first."""
    cap = 300_000
    ts = TimeSeriesStore(max_bytes=cap, raw_points=64, m1_points=16,
                         m10_points=8)
    for i in range(10_000):
        ts.add_sample("m", float(i), tags={"s": str(i)}, origin="o",
                      ts=T0 + i * 0.1)
    stats = ts.stats()
    assert stats["est_bytes"] <= cap
    assert stats["evicted_series"] > 0
    assert stats["num_series"] < 10_000
    # survivors are the newest series (LRU eviction)
    q = ts.query("m", window_s=10_000, tags={"s": "9999"}, now=T0 + 1000)
    assert q["series"], "most recent series must survive the cap"
    q = ts.query("m", window_s=10_000, tags={"s": "0"}, now=T0 + 1000)
    assert not q["series"], "oldest series must be evicted first"


def test_tsdb_24h_of_5s_history_stays_under_cap():
    """Acceptance: 24 h of synthetic 5 s samples (several processes wide)
    stays under the default-shaped cap via staged downsampling."""
    cap = 8 << 20
    ts = TimeSeriesStore(max_bytes=cap)  # default ring shape
    n = (24 * 3600) // 5  # 17280 samples per series
    for origin in ("w1", "w2", "w3", "w4"):
        for i in range(n):
            ts.add_sample("rss", 100.0 + i * 0.01, tags={"w": origin},
                          origin=origin, ts=T0 + i * 5)
    assert ts.memory_bytes() <= cap
    assert ts.stats()["evicted_series"] == 0, "history decayed, not dropped"
    now = T0 + n * 5
    # the full day is queryable at 10-min resolution...
    q = ts.query("rss", window_s=24 * 3600, step_s=600, tags={"w": "w1"},
                 now=now)
    pts = q["series"][0]["points"]
    assert len(pts) >= 130  # 28h ring ≥ 144 buckets; ≥130 in-window
    assert pts[0][1] < pts[-1][1]  # the day-long ramp survived downsampling
    # ...and the last hour at raw resolution
    q = ts.query("rss", window_s=3600, step_s=5, tags={"w": "w1"}, now=now)
    assert len(q["series"][0]["points"]) >= 700
    # a day-wide window at a raw-resolution step must ESCALATE to the
    # rings that cover it, not silently return the raw ring's last hour
    # labeled as the full window
    q = ts.query("rss", window_s=24 * 3600, step_s=5, tags={"w": "w1"},
                 now=now)
    pts = q["series"][0]["points"]
    assert pts[0][0] <= now - 20 * 3600, "window not covered"


def test_tsdb_origin_expiry():
    """A dead origin's series (and its freshness bookkeeping) leave the
    store once it stops pushing."""
    ts = TimeSeriesStore()
    ts.add_sample("m", 1.0, origin="dead", ts=T0)
    ts.add_sample("m", 2.0, origin="live", ts=T0 + 100)
    assert ts.expire_stale(30.0, now=T0 + 110) == 1
    q = ts.query("m", window_s=1000, now=T0 + 110)
    origins = {s["tags"]["origin"] for s in q["series"]}
    assert origins == {"live"}
    assert set(ts.origins()) == {"live"}


def test_tsdb_query_edge_cases():
    ts = TimeSeriesStore()
    for i in range(10):
        ts.add_sample("m", float(i), origin="o", ts=T0 + i * 5)
    now = T0 + 50
    # empty / negative window -> no points, no error
    assert ts.query("m", window_s=0, now=now)["series"][0]["points"] == []
    assert ts.query("m", window_s=-5, now=now)["series"][0]["points"] == []
    # step > window -> exactly one bin
    pts = ts.query("m", window_s=30, step_s=600, now=now)["series"][0]["points"]
    assert len(pts) == 1 and pts[0][1] == 9.0
    # step <= 0 -> defaults to the sample interval
    q = ts.query("m", window_s=60, step_s=0, now=now)
    assert len(q["series"][0]["points"]) == 10
    # unknown metric -> empty result, not an error
    assert ts.query("nope", window_s=60, now=now)["series"] == []
    # unknown agg -> loud
    with pytest.raises(ValueError):
        ts.query("m", agg="p99")


# ---------------------------------------------------------------------------
# metrics satellites (pure)
# ---------------------------------------------------------------------------

def test_registry_merge_expires_dead_origins():
    from ray_tpu.util.metrics import _Registry

    reg = _Registry()
    snap = {"m": {"type": "gauge", "help": "", "values": {(): 1.0}}}
    reg.merge("w-dead", snap)
    time.sleep(0.15)
    reg.merge("w-live", snap)
    expired = reg.expire_origins(0.1)
    assert expired == ["w-dead"]
    keys = set(reg.snapshot()["m"]["values"])
    assert (("origin", "w-live"),) in keys
    assert (("origin", "w-dead"),) not in keys
    # idempotent; a refreshed origin survives the next sweep
    reg.merge("w-live", snap)
    assert reg.expire_origins(10.0) == []


def test_registry_merge_replaces_origins_previous_series():
    """Label series absent from an origin's next push (a dead worker pid
    in an agent's per-process gauges) must leave the merged view — under
    a live origin, origin expiry alone never fires."""
    from ray_tpu.util.metrics import _Registry

    reg = _Registry()
    reg.merge("agent", {"rss": {"type": "gauge", "help": "", "values": {
        (("pid", "1"),): 10.0, (("pid", "2"),): 20.0}}})
    reg.merge("agent", {"rss": {"type": "gauge", "help": "", "values": {
        (("pid", "2"),): 21.0}}})  # pid 1 died
    keys = set(reg.snapshot()["rss"]["values"])
    assert (("pid", "2"), ("origin", "agent")) in keys
    assert (("pid", "1"), ("origin", "agent")) not in keys
    # other origins' series are untouched by this origin's replacement
    reg.merge("other", {"rss": {"type": "gauge", "help": "", "values": {
        (("pid", "9"),): 5.0}}})
    reg.merge("agent", {"rss": {"type": "gauge", "help": "", "values": {
        (("pid", "2"),): 22.0}}})
    keys = set(reg.snapshot()["rss"]["values"])
    assert (("pid", "9"), ("origin", "other")) in keys


def test_tsdb_expire_stale_drops_idle_series_under_live_origin():
    """A series whose labels vanished from a live origin's pushes (dead
    pid on an agent node) goes stale and expires series-level."""
    ts = TimeSeriesStore()
    ts.add_sample("rss", 1.0, tags={"pid": "1"}, origin="agent", ts=T0)
    for i in range(5):
        ts.add_sample("rss", 2.0, tags={"pid": "2"}, origin="agent",
                      ts=T0 + 100 + i)
    assert ts.expire_stale(60.0, now=T0 + 105) == 1
    q = ts.query("rss", window_s=1000, now=T0 + 105)
    assert {s["tags"]["pid"] for s in q["series"]} == {"2"}
    assert "agent" in ts.origins()  # the origin itself is still live


def test_metric_remove_retires_label_series():
    from ray_tpu.util.metrics import Gauge, registry

    g = Gauge("ra_test_remove", "t")
    g.set(1.0, tags={"worker": "a"})
    g.set(2.0, tags={"worker": "b"})
    assert sorted(d["worker"] for d in g.label_sets()) == ["a", "b"]
    assert g.remove({"worker": "a"}) is True
    assert g.remove({"worker": "a"}) is False  # already gone
    vals = registry().snapshot()["ra_test_remove"]["values"]
    assert list(vals) == [(("worker", "b"),)]


def test_metrics_pusher_deadline_spacing_under_slow_send():
    """A send that takes ~60% of the interval must not stretch the
    spacing: deadline ticks keep the grid, sleep-after-work would drift
    to interval+send every cycle."""
    from ray_tpu.util.metrics import Counter, MetricsPusher

    Counter("ra_test_spacing", "t").inc()
    stamps = []

    def slow_send(msg):
        stamps.append(time.monotonic())
        time.sleep(0.06)

    interval = 0.1
    pusher = MetricsPusher(slow_send, origin="t", interval_s=interval).start()
    deadline = time.time() + 10
    while len(stamps) < 8 and time.time() < deadline:
        time.sleep(0.02)
    pusher.stop()
    assert len(stamps) >= 8
    gaps = [b - a for a, b in zip(stamps, stamps[1:])]
    gaps.sort()
    median = gaps[len(gaps) // 2]
    # sleep-after-work would put the median at ~0.16s; the grid holds ~0.1
    assert median == pytest.approx(interval, abs=0.03), gaps


def test_owner_aggregate_survives_zero_size_objects():
    """The incremental by-owner aggregate must count objects explicitly,
    not infer add/remove from a size sign a zero-byte payload breaks."""
    from ray_tpu._private.object_store import ObjectLocation, ObjectRegistry

    reg = ObjectRegistry()
    for i in range(5):
        oid = bytes([i]) * 16
        reg.seal(oid, ObjectLocation(inline=b""), owner="driver",
                 owner_kind="driver")
    agg = reg.owner_summary()
    assert agg[("driver", "driver")]["objects"] == 5
    for i in range(5):
        reg.remove_ref(bytes([i]) * 16)
    assert reg.owner_summary() == {}


# ---------------------------------------------------------------------------
# trend doctor rules (pure)
# ---------------------------------------------------------------------------

def _series(name_vals, tags=None, step=30.0):
    return {"tags": tags or {}, "points": [[T0 + i * step, v]
                                           for i, v in enumerate(name_vals)]}


def test_trend_rules_fire_on_induced_pathologies():
    from ray_tpu.util import doctor

    leak = _series([100 + 20 * i for i in range(20)],  # +20MB / 30s
                   tags={"worker_id": "wleak"})
    store = _series([(64 + 48 * i) * (1 << 20) for i in range(20)])
    queue = _series([4 + 3 * i for i in range(20)])
    findings = doctor.diagnose_trends({
        "ray_tpu_proc_rss_mb": [leak],
        "ray_tpu_object_store_bytes": [store],
        "ray_tpu_sched_queue_depth": [queue],
    })
    rules = {f["rule"] for f in findings}
    assert rules == {"rss_growth", "object_store_leak", "queue_depth_climb"}
    rss = next(f for f in findings if f["rule"] == "rss_growth")
    assert "wleak" in rss["summary"]
    assert rss["evidence"][0]["slope_mb_per_min"] == pytest.approx(40.0)
    # render() must format trend findings, not KeyError on their shape
    assert "rss_growth" in doctor.render(findings)


def test_trend_rules_stay_silent_on_healthy_series():
    from ray_tpu.util import doctor

    flat = _series([100.0 + (i % 3) for i in range(20)],
                   tags={"worker_id": "w"})
    sawtooth_queue = _series([0, 5, 2, 0, 7, 1, 0, 4, 0, 6] * 2)
    shrinking_store = _series([(512 - 10 * i) * (1 << 20) for i in range(20)])
    warmup = _series([100.0, 400.0, 405.0, 406.0, 406.0, 406.0, 406.0],
                     tags={"worker_id": "w2"})  # one-time jump, no slope after
    assert doctor.diagnose_trends({
        "ray_tpu_proc_rss_mb": [flat, warmup],
        "ray_tpu_object_store_bytes": [shrinking_store],
        "ray_tpu_sched_queue_depth": [sawtooth_queue],
    }) == []
    # too few points -> no verdict either way
    short = _series([100 + 50 * i for i in range(3)], tags={"worker_id": "w"})
    assert doctor.diagnose_trends({"ray_tpu_proc_rss_mb": [short]}) == []


# ---------------------------------------------------------------------------
# live cluster: sampler -> TSDB -> query/top/memory surfaces
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def ra_cluster():
    """One cluster for the live tests, with fast push/sample cadence so
    series accumulate in test time (workers inherit the env)."""
    env = {"RAY_TPU_METRICS_PUSH_S": "0.25", "RAY_TPU_EVENTS_FLUSH_S": "0.3"}
    old = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    ray_tpu.init(num_cpus=4, num_tpus=0)
    yield
    ray_tpu.shutdown()
    for k, v in old.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def _wait_for(pred, timeout=30.0, interval=0.1):
    deadline = time.time() + timeout
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        time.sleep(interval)
    raise AssertionError("condition not met within timeout")


def test_query_metric_returns_live_series(ra_cluster):
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    def tick(i):
        return i

    ray_tpu.get([tick.remote(i) for i in range(20)], timeout=60)

    def has_series():
        q = state.query_metric("ray_tpu_proc_rss_mb", window_s=120,
                               step_s=0.25)
        return q["series"] and any(len(s["points"]) >= 3
                                   for s in q["series"])
    _wait_for(has_series)
    names = {m["name"] for m in state.list_metrics()}
    assert "ray_tpu_proc_rss_mb" in names
    assert "ray_tpu_sched_queue_depth" in names
    # per-worker series carry worker_id tags and an origin
    q = state.query_metric("ray_tpu_proc_rss_mb", window_s=120)
    tags = [s["tags"] for s in q["series"]]
    assert any(t.get("worker_id") not in (None, "head") for t in tags)
    assert all("origin" in t for t in tags)
    # values are plausible RSS (MBs, not bytes or zero)
    vals = [p[1] for s in q["series"] for p in s["points"]]
    assert vals and all(5.0 < v < 100_000 for v in vals)


def test_memory_audit_attributes_bytes_to_owners(ra_cluster):
    """Acceptance: >= 95% of sealed object-store bytes attribute to an
    owner; driver puts, task returns, and actor returns all label."""
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    def produce():
        return np.zeros(600_000, dtype=np.uint8)

    @ray_tpu.remote
    class Holder:
        def make(self):
            return np.zeros(800_000, dtype=np.uint8)

    driver_ref = ray_tpu.put(np.zeros(1_000_000, dtype=np.uint8))
    task_refs = [produce.remote() for _ in range(3)]
    ray_tpu.wait(task_refs, num_returns=len(task_refs), timeout=60)
    holder = Holder.remote()
    actor_ref = holder.make.remote()
    ray_tpu.wait([actor_ref], num_returns=1, timeout=60)

    audit = state.memory_summary(limit=50)
    assert audit["total_bytes"] >= 1_000_000 + 3 * 600_000 + 800_000
    assert audit["attributed_frac"] >= 0.95
    kinds = {o["owner_kind"] for o in audit["by_owner"]}
    assert {"driver", "worker", "actor"} <= kinds
    actor_row = next(o for o in audit["by_owner"]
                     if o["owner_kind"] == "actor")
    assert actor_row["owner_label"].startswith("Holder:")
    assert actor_row["bytes"] >= 800_000
    # per-object rows carry pin reason + age
    assert all(r["pin_reason"] in ("handle", "task_arg", "contained",
                                   "lineage") for r in audit["rows"])
    assert all(r["age_s"] >= 0 for r in audit["rows"])
    del driver_ref, task_refs, actor_ref, holder


def test_memory_audit_flags_orphans_after_actor_death(ra_cluster):
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    class Leaker:
        def make(self):
            return np.zeros(700_000, dtype=np.uint8)

    leaker = Leaker.remote()
    ref = leaker.make.remote()
    ray_tpu.wait([ref], num_returns=1, timeout=60)
    ray_tpu.kill(leaker)

    def orphaned():
        audit = state.memory_summary(limit=50)
        return [r for r in audit["rows"]
                if r.get("orphan") and r["size"] >= 700_000]
    rows = _wait_for(orphaned)
    assert rows[0]["owner_kind"] == "actor"
    audit = state.memory_summary(limit=0)
    assert audit["orphan_bytes"] >= 700_000
    del ref


def test_top_and_memory_cli_render_live(ra_cluster):
    """Acceptance: `ray_tpu top` and `ray_tpu memory` render against the
    real running cluster."""
    from ray_tpu.scripts import cli
    from ray_tpu.experimental.state import api as state

    @ray_tpu.remote
    def spin():
        t0 = time.time()
        while time.time() - t0 < 0.3:
            pass
        return 1

    refs = [spin.remote() for _ in range(4)]
    _wait_for(lambda: any(
        w.get("rss_mb") for w in state.top_snapshot()["workers"]))
    ray_tpu.get(refs, timeout=60)

    buf = io.StringIO()
    with redirect_stdout(buf):
        cli.main(["top", "--iterations", "1", "--sort", "rss"])
    frame = buf.getvalue()
    assert "ray_tpu top" in frame and "WORKER" in frame and "NODE" in frame
    assert "MB" in frame  # a sampled RSS actually rendered

    held = ray_tpu.put(np.zeros(500_000, dtype=np.uint8))
    buf = io.StringIO()
    with redirect_stdout(buf):
        cli.main(["memory", "--limit", "5"])
    out = buf.getvalue()
    assert "attributed to an owner" in out
    assert "driver" in out and "OWNER" in out
    # metrics directory CLI lists TSDB contents
    buf = io.StringIO()
    with redirect_stdout(buf):
        cli.main(["metrics"])
    assert "ray_tpu_proc_rss_mb" in buf.getvalue()
    del held


def test_list_objects_truncation_marker(ra_cluster):
    """Satellite: list_* cannot masquerade a capped view as complete."""
    from ray_tpu.experimental.state import api as state

    refs = [ray_tpu.put(np.zeros(10, dtype=np.uint8)) for _ in range(5)]
    page = state.list_state_page("objects", limit=2)
    assert len(page["rows"]) == 2
    assert page["total"] >= 5
    assert page["truncated"] is True
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        rows = state.list_objects(limit=2)
    assert len(rows) == 2
    assert any("truncated" in str(x.message) for x in w)
    # an unbounded listing is complete and quiet
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        state.list_objects(limit=100_000)
    assert not [x for x in w if "truncated" in str(x.message)]
    del refs


def test_dashboard_metrics_memory_top_endpoints(ra_cluster):
    from ray_tpu._private.worker import global_worker

    dash = global_worker.node.dashboard
    if dash is None:
        pytest.skip("dashboard disabled in this environment")
    host, port = dash.address

    def get(path):
        with urllib.request.urlopen(f"http://{host}:{port}{path}",
                                    timeout=30) as r:
            return json.loads(r.read().decode())

    _wait_for(lambda: any(m["name"] == "ray_tpu_num_workers"
                          for m in get("/api/metrics/list")))
    q = get("/api/metrics/query?name=ray_tpu_num_workers&window=120&step=1")
    assert q["series"] and q["series"][0]["points"]
    mem = get("/api/memory")
    assert "by_owner" in mem and mem["attributed_frac"] >= 0.95
    top = get("/api/top")
    assert top["workers"] and top["nodes"]
    # grafana dashboard includes TSDB-retained metrics (per-proc gauges)
    dash_json = get("/api/grafana_dashboard")
    descs = [p["description"] for p in dash_json["panels"]]
    assert any("ray_tpu_proc_rss_mb" in d for d in descs)


def test_doctor_healthy_run_has_no_trend_findings(ra_cluster):
    """The trend rules' false-positive gate: a working cluster that just
    ran tasks shows no leak/climb findings."""
    from ray_tpu.util.doctor import run_doctor

    @ray_tpu.remote
    def work(i):
        return i * 2

    ray_tpu.get([work.remote(i) for i in range(30)], timeout=60)
    time.sleep(0.8)  # a few TSDB ticks over the settled state
    findings = run_doctor()
    trend_rules = {"rss_growth", "object_store_leak", "queue_depth_climb"}
    assert not [f for f in findings if f["rule"] in trend_rules], findings
